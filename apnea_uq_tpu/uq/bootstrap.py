"""Vectorized bootstrap confidence intervals, on device.

The reference's hot spot: a Python loop of B=100 resamples, each re-running
the full UQ metric suite on host NumPy — O(B*K*M) with a per-pass entropy
loop inside (uq_techniques.py:137-165; SURVEY §3.3 hot loop #2).

Key observation: every bootstrapped aggregate (overall mean variance,
per-class mean variance, mean total/aleatoric entropy, mean MI) is a
*window-wise mean* of a per-window quantity.  So the per-window vectors are
computed **once**, and the bootstrap reduces to: draw a (B, M) index
matrix, gather, and take masked means — one fused gather+reduce under
``jit``, mathematically identical to the reference loop.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apnea_uq_tpu.uq.metrics import uq_evaluation_dist

# The six scalar aggregates the reference tracks per resample
# (uq_techniques.py:150-157).
AGGREGATE_KEYS = (
    "overall_mean_variance",
    "mean_variance_class_0",
    "mean_variance_class_1",
    "mean_total_pred_entropy",
    "mean_expected_aleatoric_entropy",
    "mean_mutual_info",
)


@jax.jit
def gather_aggregates(
    pred_variance: jax.Array,
    total_entropy: jax.Array,
    aleatoric: jax.Array,
    mutual_info: jax.Array,
    y_true: jax.Array,
    idx: jax.Array,
) -> Dict[str, jax.Array]:
    """The six scalar aggregates for an explicit (B, M) resample-index
    matrix.  Exposed separately from :func:`_bootstrap_core` so parity
    tests can drive the gather engine with the reference's own
    ``np.random.choice`` index stream (uq_techniques.py:142) and compare
    per-resample values exactly."""
    var_b = pred_variance[idx]          # (B, M)
    tot_b = total_entropy[idx]
    ale_b = aleatoric[idx]
    mi_b = mutual_info[idx]
    y_b = y_true.astype(jnp.int32)[idx]

    mask0 = (y_b == 0).astype(jnp.float32)
    mask1 = (y_b == 1).astype(jnp.float32)
    n0 = jnp.sum(mask0, axis=1)
    n1 = jnp.sum(mask1, axis=1)
    mv0 = jnp.where(n0 > 0, jnp.sum(var_b * mask0, axis=1) / jnp.maximum(n0, 1.0), 0.0)
    mv1 = jnp.where(n1 > 0, jnp.sum(var_b * mask1, axis=1) / jnp.maximum(n1, 1.0), 0.0)

    return {
        "overall_mean_variance": jnp.mean(var_b, axis=1),
        "mean_variance_class_0": mv0,
        "mean_variance_class_1": mv1,
        "mean_total_pred_entropy": jnp.mean(tot_b, axis=1),
        "mean_expected_aleatoric_entropy": jnp.mean(ale_b, axis=1),
        "mean_mutual_info": jnp.mean(mi_b, axis=1),
    }


@partial(jax.jit, static_argnames=("n_bootstrap",))
def _bootstrap_core(
    pred_variance: jax.Array,
    total_entropy: jax.Array,
    aleatoric: jax.Array,
    mutual_info: jax.Array,
    y_true: jax.Array,
    key: jax.Array,
    n_bootstrap: int,
) -> Dict[str, jax.Array]:
    m = pred_variance.shape[0]
    idx = jax.random.randint(key, (n_bootstrap, m), 0, m)  # resample with replacement
    return gather_aggregates(
        pred_variance, total_entropy, aleatoric, mutual_info, y_true, idx
    )


@partial(jax.jit, static_argnames=())
def _pack_rows(pred_variance, total_entropy, aleatoric, mutual_info, y_true):
    """(16, M) metric rows for the count-weighted-sum formulation: every
    bootstrapped aggregate is a ratio of two of these rows' resample sums."""
    y = y_true.astype(jnp.int32)
    mask0 = (y == 0).astype(jnp.float32)
    mask1 = (y == 1).astype(jnp.float32)
    rows = jnp.stack([
        pred_variance,                 # 0: sum -> overall variance numerator
        total_entropy,                 # 1
        aleatoric,                     # 2
        mutual_info,                   # 3
        pred_variance * mask0,         # 4: class-0 variance numerator
        pred_variance * mask1,         # 5: class-1 variance numerator
        mask0,                         # 6: class-0 size
        mask1,                         # 7: class-1 size
        jnp.ones_like(pred_variance),  # 8: realized resample size
    ])
    from apnea_uq_tpu.ops.pallas_bootstrap import N_ROWS

    return jnp.pad(rows, ((0, N_ROWS - rows.shape[0]), (0, 0)))


def _poisson_aggregates(metrics, y_true, key, n_bootstrap) -> Dict[str, jax.Array]:
    """Aggregates via the fused Poisson-bootstrap engine
    (ops/pallas_bootstrap.py): one kernel pass instead of a (B, M) gather;
    ~95x faster on TPU at reference scale.  Each resample normalizes by
    its realized size (row 8) — the standard Poisson-bootstrap estimator."""
    from apnea_uq_tpu.ops.pallas_bootstrap import poisson_bootstrap_sums

    v = _pack_rows(
        metrics["pred_variance"],
        metrics["total_pred_entropy"],
        metrics["expected_aleatoric_entropy"],
        metrics["mutual_info"],
        jnp.asarray(y_true),
    )
    s = poisson_bootstrap_sums(v, key, n_bootstrap)    # (B, 16)
    n = jnp.maximum(s[:, 8], 1.0)
    n0, n1 = s[:, 6], s[:, 7]
    return {
        "overall_mean_variance": s[:, 0] / n,
        "mean_variance_class_0": jnp.where(n0 > 0, s[:, 4] / jnp.maximum(n0, 1.0), 0.0),
        "mean_variance_class_1": jnp.where(n1 > 0, s[:, 5] / jnp.maximum(n1, 1.0), 0.0),
        "mean_total_pred_entropy": s[:, 1] / n,
        "mean_expected_aleatoric_entropy": s[:, 2] / n,
        "mean_mutual_info": s[:, 3] / n,
    }


def bootstrap_aggregates(
    predictions,
    y_true,
    *,
    n_bootstrap: int = 100,
    key: Optional[jax.Array] = None,
    seed: Optional[int] = None,
    base: str = "nats",
    eps: float = 1e-10,
    metrics: Optional[Dict[str, jax.Array]] = None,
    engine: str = "exact",
) -> Dict[str, jax.Array]:
    """(B,)-vector of each scalar aggregate across B bootstrap resamples.

    ``engine='exact'`` (default) draws multinomial resamples and gathers —
    mathematically identical to the reference loop (uq_techniques.py:
    150-157; per-window metrics are resample-invariant, so recomputing
    them per resample is equivalent to gathering them), with a
    backend-stable CI stream.  ``engine='poisson'`` is the TPU fast path:
    the fused count-matmul kernel (ops/pallas_bootstrap.py), a
    statistically equivalent resampler that is ~95x faster at reference
    scale but whose stream is backend-specific.  Pass the ``metrics`` dict
    of a prior :func:`uq_evaluation_dist` call on the same stack to skip
    recomputing it.
    """
    if engine not in ("exact", "poisson"):
        raise ValueError(f"engine must be 'exact' or 'poisson', got {engine!r}")
    if key is None:
        key = jax.random.key(0 if seed is None else seed)
    if metrics is None:
        metrics = uq_evaluation_dist(predictions, y_true, base=base, eps=eps)
    if engine == "poisson":
        return _poisson_aggregates(metrics, y_true, key, n_bootstrap)
    return _bootstrap_core(
        metrics["pred_variance"],
        metrics["total_pred_entropy"],
        metrics["expected_aleatoric_entropy"],
        metrics["mutual_info"],
        jnp.asarray(y_true),
        key,
        n_bootstrap,
    )


def bootstrap_metrics(
    predictions,
    y_true,
    n_bootstrap: int = 100,
    random_state: Optional[int] = None,
    **kw,
) -> List[Dict[str, float]]:
    """Reference-shaped API: list of per-resample aggregate dicts
    (uq_techniques.py:116-172)."""
    agg = bootstrap_aggregates(
        predictions, y_true, n_bootstrap=n_bootstrap, seed=random_state, **kw
    )
    host = {k: np.asarray(v) for k, v in agg.items()}
    return [{k: float(host[k][b]) for k in AGGREGATE_KEYS} for b in range(n_bootstrap)]


def compute_confidence_intervals(
    bootstrap_results,
    alpha: float = 0.05,
) -> Dict[str, float]:
    """Percentile CIs + mean per metric (uq_techniques.py:175-206).

    Accepts either the dict-of-(B,)-arrays from :func:`bootstrap_aggregates`
    or the reference-shaped list of dicts from :func:`bootstrap_metrics`.
    """
    if not bootstrap_results:
        return {}
    # float64 throughout: np.percentile interpolates in float64 regardless of
    # input dtype, so a float32 mean of a near-constant bootstrap vector can
    # land ~1 ulp outside its own CI.  mean ∈ [lo, hi] must hold exactly.
    if isinstance(bootstrap_results, dict):
        columns = {
            k: np.asarray(v, dtype=np.float64) for k, v in bootstrap_results.items()
        }
    else:
        keys = bootstrap_results[0].keys()
        columns = {
            k: np.asarray([r[k] for r in bootstrap_results], dtype=np.float64)
            for k in keys
        }

    out: Dict[str, float] = {}
    for name, values in columns.items():
        out[f"{name}_mean"] = float(np.mean(values))
        out[f"{name}_ci_lower"] = float(np.percentile(values, 100 * alpha / 2))
        out[f"{name}_ci_upper"] = float(np.percentile(values, 100 * (1 - alpha / 2)))
    return out
