"""MC-Dropout and Deep-Ensemble prediction, TPU-first.

Reference behavior being replaced (SURVEY §3.3/3.4 hot loops):

- ``mc_dropout_predict``: a Python loop of T=50 full-test-set Keras calls
  with ``training=True`` (uq_techniques.py:22) — the whole test set as one
  batch per pass.
- ``deep_ensembles_predict``: N sequential full-set ``model.predict`` calls
  (uq_techniques.py:29-30).

Here both are a single jitted program: ``vmap`` over dropout RNG keys (or
over a stacked member-parameter axis) inside, ``lax.map`` over fixed-size
window chunks outside so HBM holds one chunk of activations at a time.
The T (or N) axis rides the batch dimension of every conv, keeping the MXU
fed with one large fused computation instead of T small ones.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from apnea_uq_tpu.models.cnn1d import AlarconCNN1D, apply_model, predict_proba
from apnea_uq_tpu.utils import prng

_MCD_MODES = {"clean": "mcd_clean", "parity": "mcd_parity"}


def _chunk(x: jax.Array, batch_size: int):
    """Pad to a multiple of batch_size and reshape to (chunks, bs, ...).

    Padding wraps around the real windows (modular gather) rather than
    zero-filling: in 'parity' mode BatchNorm uses batch statistics, and
    zero rows in the final chunk would drag the statistics toward zero
    and corrupt the real windows sharing that chunk.
    """
    m = x.shape[0]
    n_chunks = -(-m // batch_size)
    pad = n_chunks * batch_size - m
    if pad:
        x = jnp.take(x, jnp.arange(n_chunks * batch_size) % m, axis=0)
    return x.reshape((n_chunks, batch_size) + x.shape[1:]), m


@partial(jax.jit, static_argnames=("model", "n_passes", "mode", "batch_size"))
def _mcd_jit(model, variables, x, key, n_passes, mode, batch_size):
    keys = jax.random.split(key, n_passes)
    chunks, m = _chunk(x, batch_size)

    def one_chunk(args):
        chunk, chunk_idx = args

        def one_pass(k):
            # Fresh noise per (pass, chunk): reusing the per-pass key across
            # chunks would give windows in different chunks identical dropout
            # masks (correlated noise the reference does not have).
            k = jax.random.fold_in(k, chunk_idx)
            logits, _ = apply_model(model, variables, chunk, mode=mode, dropout_rng=k)
            return predict_proba(logits)

        return jax.vmap(one_pass)(keys)  # (T, bs)

    probs = jax.lax.map(
        one_chunk, (chunks, jnp.arange(chunks.shape[0]))
    )                                                 # (chunks, T, bs)
    probs = jnp.transpose(probs, (1, 0, 2)).reshape(n_passes, -1)
    return probs[:, :m]


def mc_dropout_predict(
    model: AlarconCNN1D,
    variables: dict,
    x,
    *,
    n_passes: int = 50,
    mode: str = "clean",
    batch_size: int = 512,
    key: Optional[jax.Array] = None,
    seed: int = 0,
) -> jax.Array:
    """(T, M) positive-class probabilities from T stochastic passes.

    ``mode='parity'`` reproduces the reference's ``training=True`` regime
    (dropout + batch-statistics BatchNorm, uq_techniques.py:22).  Note that
    in parity mode batch statistics are computed per ``batch_size`` chunk;
    the reference used the entire test set as one batch, so pass
    ``batch_size >= len(x)`` for exact parity of that detail.
    ``mode='clean'`` freezes BatchNorm at running statistics (standard MC
    Dropout; SURVEY §6).

    HBM note: all T passes of one chunk are live at once (the T axis rides
    the batch dimension), so the activation footprint scales with
    ``n_passes * batch_size`` rows.  The default (50 x 512 = 25.6K rows)
    fits a 16-GB v5e chip with headroom and measured fastest there;
    50 x 2048 already exceeds its HBM.
    """
    if mode not in _MCD_MODES:
        raise ValueError(f"mode must be 'clean' or 'parity', got {mode!r}")
    if key is None:
        key = prng.stochastic_key(seed)
    x = jnp.asarray(x, jnp.float32)
    return _mcd_jit(model, variables, x, key, n_passes, _MCD_MODES[mode], batch_size)


def stack_member_variables(member_variables: list) -> dict:
    """Stack per-member variable pytrees along a leading member axis."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves, axis=0), *member_variables)


@partial(jax.jit, static_argnames=("model", "batch_size"))
def _ensemble_jit(model, stacked_variables, x, batch_size):
    chunks, m = _chunk(x, batch_size)

    def one_chunk(chunk):
        def one_member(member_vars):
            logits, _ = apply_model(model, member_vars, chunk, mode="eval")
            return predict_proba(logits)

        return jax.vmap(one_member)(stacked_variables)  # (N, bs)

    probs = jax.lax.map(one_chunk, chunks)              # (chunks, N, bs)
    n_members = probs.shape[1]
    probs = jnp.transpose(probs, (1, 0, 2)).reshape(n_members, -1)
    return probs[:, :m]


def ensemble_predict(
    model: AlarconCNN1D,
    member_variables,
    x,
    *,
    batch_size: int = 2048,
) -> jax.Array:
    """(N, M) deterministic probabilities from N ensemble members.
    All N members' activations for one chunk are live at once, so the
    footprint scales with ``n_members * batch_size`` rows (see the HBM
    note on :func:`mc_dropout_predict`).

    ``member_variables`` is either a list of per-member variable pytrees or
    an already-stacked pytree with a leading member axis.  Members are
    vmapped — one batched program instead of the reference's N sequential
    ``model.predict`` calls (uq_techniques.py:29-30).
    """
    if isinstance(member_variables, (list, tuple)):
        member_variables = stack_member_variables(list(member_variables))
    x = jnp.asarray(x, jnp.float32)
    return _ensemble_jit(model, member_variables, x, batch_size)
