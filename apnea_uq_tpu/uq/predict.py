"""MC-Dropout and Deep-Ensemble prediction, TPU-first.

Reference behavior being replaced (SURVEY §3.3/3.4 hot loops):

- ``mc_dropout_predict``: a Python loop of T=50 full-test-set Keras calls
  with ``training=True`` (uq_techniques.py:22) — the whole test set as one
  batch per pass.
- ``deep_ensembles_predict``: N sequential full-set ``model.predict`` calls
  (uq_techniques.py:29-30).

Here both are a single jitted program: ``vmap`` over dropout RNG keys (or
over a stacked member-parameter axis) inside, ``lax.map`` over fixed-size
window chunks outside so HBM holds one chunk of activations at a time.
The T (or N) axis rides the batch dimension of every conv, keeping the MXU
fed with one large fused computation instead of T small ones.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apnea_uq_tpu.compilecache import store as program_store
from apnea_uq_tpu.config import VALID_DE_ENGINES, VALID_MCD_ENGINES
from apnea_uq_tpu.models.cnn1d import AlarconCNN1D, apply_model, predict_proba
from apnea_uq_tpu.ops import autotune as autotune_mod
from apnea_uq_tpu.ops import pallas_de, pallas_mcd
from apnea_uq_tpu.parallel import mesh as mesh_lib
from apnea_uq_tpu.telemetry import memory as telemetry_memory
from apnea_uq_tpu.uq.metrics import N_STAT_ROWS, sufficient_stats
from apnea_uq_tpu.utils import prng

# jax exports shard_map at top level from 0.5; on 0.4.x it lives under
# jax.experimental with the same (f, mesh, in_specs, out_specs) signature.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

_MCD_MODES = {"clean": "mcd_clean", "parity": "mcd_parity"}

# Every program label the predictors can emit, spelled as LITERALS: the
# warm-cache zoo (compilecache/zoo.py GROUP_LABELS), the audit manifest,
# and the drift pin (tests/test_compilecache.py scrapes these sources
# for label string constants) all key off these exact strings.  The
# grammar is base + optional suffixes in fixed order:
#   mcd[_chunk]_predict[_pallas][_fused][_bf16]
#   de[_chunk]_predict[_pallas][_fused][_bf16]
# `_chunk` = the streamed per-chunk program, `_pallas` = the fused
# kernel engine was REQUESTED (ops/pallas_mcd.py for MCD,
# ops/pallas_de.py for DE; the label tracks the request — off-TPU the
# same label runs the XLA fallback body, exactly like the bootstrap
# kernel), `_fused` = on-device sufficient-statistics reduction,
# `_bf16` = ModelConfig.compute_dtype='bfloat16' (the audit's blessed
# low-precision tier — audit/rules.py program-dtype-drift).
MCD_PROGRAM_LABELS = (
    "mcd_predict", "mcd_predict_bf16",
    "mcd_predict_fused", "mcd_predict_fused_bf16",
    "mcd_predict_pallas", "mcd_predict_pallas_bf16",
    "mcd_predict_pallas_fused", "mcd_predict_pallas_fused_bf16",
    "mcd_chunk_predict", "mcd_chunk_predict_bf16",
    "mcd_chunk_predict_fused", "mcd_chunk_predict_fused_bf16",
    "mcd_chunk_predict_pallas", "mcd_chunk_predict_pallas_bf16",
    "mcd_chunk_predict_pallas_fused", "mcd_chunk_predict_pallas_fused_bf16",
)
DE_PROGRAM_LABELS = (
    "de_predict", "de_predict_bf16",
    "de_predict_fused", "de_predict_fused_bf16",
    "de_predict_pallas", "de_predict_pallas_bf16",
    "de_predict_pallas_fused", "de_predict_pallas_fused_bf16",
    "de_chunk_predict", "de_chunk_predict_bf16",
    "de_chunk_predict_fused", "de_chunk_predict_fused_bf16",
    "de_chunk_predict_pallas", "de_chunk_predict_pallas_bf16",
    "de_chunk_predict_pallas_fused", "de_chunk_predict_pallas_fused_bf16",
)

# The online serving tier's bucket ladder (apnea_uq_tpu/serving/): every
# coalesced request batch pads up to one of these fixed window counts, so
# each dispatch hits an already-compiled fused-stats program and a warm
# serve process never compiles on the request path.  The ladder constant
# lives on the jax-free side (serving/coalescer.py — the CLI parser
# reads it at build time) and the ladder is part of the label grammar —
# `{mcd|de}_serve_b<bucket>[_pallas]_fused[_bf16]` — so the warm-cache
# zoo, the audit manifest, and the drift pin all name the bucket
# programs individually (a bucket that fell out of the store would
# otherwise pay a silent request-path compile).  `_pallas` tracks the
# REQUESTED serving engine exactly like the eval grammar above.
from apnea_uq_tpu.serving.coalescer import SERVE_BUCKET_SIZES  # noqa: E402

SERVE_PROGRAM_LABELS = (
    "mcd_serve_b16_fused", "mcd_serve_b16_fused_bf16",
    "mcd_serve_b64_fused", "mcd_serve_b64_fused_bf16",
    "mcd_serve_b256_fused", "mcd_serve_b256_fused_bf16",
    "mcd_serve_b16_pallas_fused", "mcd_serve_b16_pallas_fused_bf16",
    "mcd_serve_b64_pallas_fused", "mcd_serve_b64_pallas_fused_bf16",
    "mcd_serve_b256_pallas_fused", "mcd_serve_b256_pallas_fused_bf16",
    "de_serve_b16_fused", "de_serve_b16_fused_bf16",
    "de_serve_b64_fused", "de_serve_b64_fused_bf16",
    "de_serve_b256_fused", "de_serve_b256_fused_bf16",
    "de_serve_b16_pallas_fused", "de_serve_b16_pallas_fused_bf16",
    "de_serve_b64_pallas_fused", "de_serve_b64_pallas_fused_bf16",
    "de_serve_b256_pallas_fused", "de_serve_b256_pallas_fused_bf16",
)


def _dtype_tag(model: AlarconCNN1D) -> str:
    return ("_bf16" if jnp.dtype(model.config.compute_dtype) == jnp.bfloat16
            else "")


def mcd_program_label(model: AlarconCNN1D, *, streamed: bool, engine: str,
                      fused: bool) -> str:
    """The MCD program label a (model config, engine, path) combination
    prices/stores/dispatches under.  Derived from the REQUESTED engine —
    deterministic across backends — so a CPU audit, a warm-cache, and a
    TPU eval of the same config all name the same program."""
    label = "mcd_chunk_predict" if streamed else "mcd_predict"
    if engine == "pallas":
        label += "_pallas"
    if fused:
        label += "_fused"
    label += _dtype_tag(model)
    assert label in MCD_PROGRAM_LABELS, label
    return label


def de_program_label(model: AlarconCNN1D, *, streamed: bool, engine: str,
                     fused: bool) -> str:
    """The DE program label a (model config, engine, path) combination
    prices/stores/dispatches under — same REQUESTED-engine discipline as
    :func:`mcd_program_label` (off-TPU the `_pallas` label runs the XLA
    fallback body under the same name)."""
    label = "de_chunk_predict" if streamed else "de_predict"
    if engine == "pallas":
        label += "_pallas"
    if fused:
        label += "_fused"
    label += _dtype_tag(model)
    assert label in DE_PROGRAM_LABELS, label
    return label


def serve_program_label(model: AlarconCNN1D, *, method: str, bucket: int,
                        engine: str = "xla") -> str:
    """The serving-tier program label one (method, bucket, engine,
    dtype) cell prices/stores/dispatches under —
    `{mcd|de}_serve_b<bucket>[_pallas]_fused` plus the shared ``_bf16``
    dtype tag.  Always the fused-stats body (an online request wants the
    (4, bucket) sufficient-stats D2H payload, never the (K, bucket)
    stack).  ``engine`` follows the REQUESTED-engine discipline of the
    eval grammar: the `_pallas` label names the fused-kernel request and
    runs the XLA fallback body under the same name off-TPU, so a CPU
    audit, a warm-cache, and a TPU serve process name — and get — the
    same program."""
    if method not in ("mcd", "de"):
        raise ValueError(f"method must be 'mcd' or 'de', got {method!r}")
    label = f"{method}_serve_b{int(bucket)}"
    if engine == "pallas":
        label += "_pallas"
    label += "_fused" + _dtype_tag(model)
    assert label in SERVE_PROGRAM_LABELS, label
    return label


def serve_bucket_predict(
    model: AlarconCNN1D,
    variables,
    x,
    *,
    method: str = "mcd",
    bucket: int,
    n_passes: int = 50,
    key: Optional[jax.Array] = None,
    base: str = "nats",
    eps: float = 1e-10,
    engine: str = "xla",
    run_log=None,
    record_memory_only: bool = False,
    cache: Optional[dict] = None,
) -> jax.Array:
    """One coalesced serving bucket through its fused-stats program:
    ``x`` is EXACTLY ``(bucket, T, C)`` — the request coalescer
    (serving/coalescer.py) zero-pads up to the bucket, and the caller
    slices the pad columns back off the returned ``(N_STAT_ROWS,
    bucket)`` stack.  Pad rows are sound because serving always runs
    clean-mode MCD (frozen-BN) or eval-mode DE: every window's compute
    is independent of its batch neighbors, so the real columns are
    bit-identical (f32) to a direct dispatch of the same program family
    at the exact row count (pinned by tests/test_serving.py).

    ``method='mcd'`` runs ``n_passes`` stochastic passes under ``key``
    (clean mode only — parity-mode batch-statistics BN would let pad
    rows corrupt real windows); ``method='de'`` runs the deterministic
    ensemble, with ``variables`` any accepted DE-member carrier
    (:func:`as_stacked_members`).  The acquisition/pricing/dispatch
    discipline matches the eval predictors: ONE (label, fn, args) tuple
    drives all three, labels follow :func:`serve_program_label`, and
    ``record_memory_only=True`` is the warm-cache/audit no-dispatch
    mode.

    ``engine='pallas'`` (``UQConfig.mcd_engine`` / ``UQConfig.de_engine``
    by method) requests the fused serving kernel — ops/pallas_mcd.py for
    MCD buckets, ops/pallas_de.py for DE buckets — under the bucket's
    `_pallas` label, resolving through the shared fallback rules
    (:func:`resolve_engine`) and baking any autotuned tile geometry
    (ops/autotune.py) into the dispatched program.

    ``cache`` (a caller-owned dict — the ServingEngine passes its own)
    memoizes the acquisition per label: the first call pays weight
    placement, store-signature hashing, the compile_event, and the
    memory record; every later dispatch through the same cache reuses
    the acquired program and the already-placed carrier, keeping the
    request-path hot loop free of per-batch host overhead."""
    bucket = int(bucket)
    if bucket not in SERVE_BUCKET_SIZES:
        raise ValueError(
            f"bucket must be one of {SERVE_BUCKET_SIZES}, got {bucket} — "
            f"the serving ladder's labels are registered per bucket "
            f"(compilecache/zoo.py GROUP_LABELS['serve'])"
        )
    label = serve_program_label(model, method=method, bucket=bucket,
                                engine=engine)
    geometry = autotune_mod.tuned_kernel_kwargs(label)
    cached = cache.get(label) if cache is not None else None
    if cached is None:
        # Canonical weight placement: checkpoint-restored weights come
        # back COMMITTED (orbax restores onto device 0 with an explicit
        # SingleDeviceSharding) while warm-cache/audit sign with
        # fresh-init UNCOMMITTED arrays — and the store signature
        # includes pinned shardings, so without one shared placement
        # the warm process and the serve process would key the same
        # program differently and the request path would silently
        # re-jit (the warm-serve acceptance test pins this).  The mesh
        # predictors normalize the same way with their replicated
        # device_put.
        place = jax.local_devices()[0]
        variables = jax.tree.map(lambda a: jax.device_put(a, place),
                                 variables)
        if method == "de":
            variables = as_stacked_members(variables)
    else:
        program, variables = cached
    if record_memory_only:
        x = jax.ShapeDtypeStruct(
            (bucket,) + tuple(np.shape(x))[1:], jnp.float32)
    else:
        x = jnp.asarray(x, jnp.float32)
        if x.shape[0] != bucket:
            raise ValueError(
                f"bucket program {label} takes exactly {bucket} rows, "
                f"got {x.shape[0]} — the coalescer must pad to the bucket"
            )
    if method == "mcd":
        if key is None:
            key = prng.stochastic_key(0)
        fn = _mcd_stats_jit
        args = (model, variables, x, key, n_passes, _MCD_MODES["clean"],
                bucket, base, float(eps), None,
                resolve_mcd_engine(engine, "clean", None), geometry)
    else:
        fn = _ensemble_stats_jit
        args = (model, variables, x, bucket, base, float(eps),
                resolve_de_engine(engine, None), geometry)
    if cached is None:
        program = program_store.get_program(label, fn, *args,
                                            run_log=run_log)
        if run_log is not None:
            # Compiled-HBM accounting per bucket program (one
            # memory_profile event per signature) — free when a program
            # was acquired.
            telemetry_memory.record_jit_memory(run_log, label, fn, *args,
                                               program=program)
        if cache is not None:
            cache[label] = (program, variables)
    if record_memory_only:
        return None  # warm-cache / audit no-dispatch mode
    return program(*args) if program is not None else fn(*args)


def resolve_engine(engine: str, mode: str,
                   mesh: Optional[jax.sharding.Mesh], available) -> str:
    """The ONE fallback-rule table every fused-kernel family resolves
    through.  'pallas' resolves to the fused kernel only where a kernel
    is valid — ``mode='clean'`` (parity mode's BatchNorm batch
    statistics are whole-chunk reductions, incompatible with independent
    window tiles; DE always passes 'clean' since members run eval mode),
    single device (``mesh is None`` — the kernels are per-chip
    programs), and ``available()`` true (TPU backend with the pallas TPU
    package importable) — and silently falls back to the XLA body
    everywhere else, exactly like the bootstrap kernel's off-TPU
    fallback (ops/pallas_bootstrap.py).  Program LABELS track the
    requested engine (:func:`mcd_program_label` /
    :func:`de_program_label` / :func:`serve_program_label`); only the
    dispatched body changes."""
    if engine not in VALID_MCD_ENGINES:
        raise ValueError(
            f"engine must be one of {VALID_MCD_ENGINES}, got {engine!r}")
    if (engine == "pallas" and mode == "clean" and mesh is None
            and available()):
        return "pallas"
    return "xla"


def resolve_mcd_engine(engine: str, mode: str,
                       mesh: Optional[jax.sharding.Mesh]) -> str:
    """The engine an MCD predict call actually dispatches — the shared
    :func:`resolve_engine` rules gated on the MCD kernel's availability
    (ops/pallas_mcd.py)."""
    return resolve_engine(engine, mode, mesh, pallas_mcd.pallas_mcd_available)


def resolve_de_engine(engine: str,
                      mesh: Optional[jax.sharding.Mesh]) -> str:
    """The engine a DE predict call actually dispatches — the shared
    :func:`resolve_engine` rules gated on the DE kernel's availability
    (ops/pallas_de.py).  DE members always run eval mode (frozen
    running-statistics BN), so the mode rule is satisfied by
    construction and only the mesh and backend rules can fall back."""
    if engine not in VALID_DE_ENGINES:
        raise ValueError(
            f"engine must be one of {VALID_DE_ENGINES}, got {engine!r}")
    return resolve_engine(engine, "clean", mesh, pallas_de.pallas_de_available)


def _uq_stats(probs: jax.Array, base: str, eps: float) -> jax.Array:
    """(K, n) chunk probabilities -> (4, n) fused per-window sufficient
    statistics (uq/metrics.py), reduced on device in float32.  Because the
    statistics are per-window functions of the K resident passes/members,
    computing them per chunk equals computing them on the assembled
    (K, M) matrix — wrap-padded window columns produce padded stat
    columns that the callers slice off exactly as they slice padded
    probability columns."""
    with jax.named_scope("uq_stats"):
        return sufficient_stats(probs, base=base, eps=eps)


def _constrain(a: jax.Array, mesh, *axes: Optional[str]) -> jax.Array:
    """Sharding constraint helper: P(*axes) over ``mesh`` (no-op off-mesh)."""
    if mesh is None:
        return a
    return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, P(*axes)))


def _wrap_pad(a: jax.Array, multiple: int, axis: int = 0) -> jax.Array:
    """Pad ``axis`` up to a multiple of ``multiple`` by wrapping around the
    real rows (modular gather).  Wrapping rather than zero-filling matters
    in 'parity' mode, where BatchNorm uses batch statistics and zero rows
    would drag them toward zero, corrupting real windows in the same chunk;
    padded rows are always sliced or masked off by the caller."""
    n = a.shape[axis]
    padded = -(-n // multiple) * multiple
    if padded == n:
        return a
    return jnp.take(a, jnp.arange(padded) % n, axis=axis)


def _chunk(x: jax.Array, batch_size: int):
    """Wrap-pad to a multiple of batch_size, reshape to (chunks, bs, ...)."""
    m = x.shape[0]
    x = _wrap_pad(x, batch_size)
    return x.reshape((-1, batch_size) + x.shape[1:]), m


def _mcd_passes(model, variables, chunk, keys, chunk_idx, mode, mesh):
    """The T stochastic passes of ONE window chunk — the single body both
    the in-HBM ``lax.map`` path and the streamed per-chunk jit run, so
    streamed == in-HBM parity holds by construction rather than by keeping
    two copies in sync.  With ``mesh``, the passes shard over ``ensemble``
    and the chunk's windows over ``data``."""
    chunk = _constrain(chunk, mesh, mesh_lib.AXIS_DATA)

    def one_pass(k):
        # Named scope: profiler captures label the stochastic passes as
        # "mcd_pass/..." ops instead of anonymous fused convolutions.
        with jax.named_scope("mcd_pass"):
            # Fresh noise per (pass, chunk): reusing the per-pass key across
            # chunks would give windows in different chunks identical dropout
            # masks (correlated noise the reference does not have).
            k = jax.random.fold_in(k, chunk_idx)
            logits, _ = apply_model(model, variables, chunk, mode=mode,
                                    dropout_rng=k)
            # Constrain per pass, at the model output: with spmd_axis_name
            # threading the pass axis, this pins the conv batch itself to
            # the (pass-shard x window-shard) block — without it the
            # partitioner is free to replicate windows within ensemble
            # groups and merely reshard at the end (observed on CPU SPMD),
            # wasting the data axis.
            return _constrain(predict_proba(logits), mesh, mesh_lib.AXIS_DATA)

    if mesh is None:
        return jax.vmap(one_pass)(keys)  # (T, bs)
    return jax.vmap(one_pass, spmd_axis_name=mesh_lib.AXIS_ENSEMBLE)(keys)


def _chunk_passes(model, variables, chunk, key, keys, chunk_idx, mode,
                  mesh, engine, geometry=()):
    """ONE chunk's T stochastic passes under the RESOLVED engine: the
    XLA vmap body (:func:`_mcd_passes`) or the fused Pallas kernel
    (ops/pallas_mcd.py, clean-mode single-device TPU only — the
    resolver guarantees it).  The pallas body re-derives its hardware
    seed from (key, chunk_idx), the kernel-side spelling of the XLA
    path's per-(pass, chunk) fold_in discipline.  ``geometry`` is the
    label's autotuned tile-geometry kwargs (ops/autotune.py), a static
    tuple of (name, value) pairs — empty means kernel defaults."""
    if engine == "pallas":
        with jax.named_scope("mcd_pallas"):
            return pallas_mcd.mcd_pallas_passes(
                model, variables, chunk, key, chunk_idx, keys.shape[0],
                **dict(geometry))
    return _mcd_passes(model, variables, chunk, keys, chunk_idx, mode, mesh)


@partial(
    jax.jit,
    static_argnames=("model", "n_passes", "mode", "batch_size", "mesh",
                     "engine", "geometry"),
)
def _mcd_jit(model, variables, x, key, n_passes, mode, batch_size, mesh=None,
             engine="xla", geometry=()):
    """With ``mesh``, the T stochastic passes shard over the ``ensemble``
    axis and each chunk's windows over the ``data`` axis, so all devices
    work on every chunk; the computation per (pass, window) is unchanged —
    same keys, same masks — so results equal the single-device path."""
    keys = jax.random.split(key, n_passes)
    chunks, m = _chunk(x, batch_size)
    chunks = _constrain(chunks, mesh, None, mesh_lib.AXIS_DATA)

    def one_chunk(args):
        with jax.named_scope("mcd_chunk"):
            chunk, chunk_idx = args
            return _chunk_passes(model, variables, chunk, key, keys,
                                 chunk_idx, mode, mesh, engine, geometry)

    probs = jax.lax.map(
        one_chunk, (chunks, jnp.arange(chunks.shape[0]))
    )                                                 # (chunks, T, bs)
    probs = jnp.transpose(probs, (1, 0, 2)).reshape(n_passes, -1)
    return probs[:, :m]


@partial(jax.jit,
         static_argnames=("model", "n_passes", "mode", "mesh", "engine",
                          "geometry"))
def _mcd_chunk_jit(model, variables, chunk, key, chunk_idx, n_passes, mode,
                   mesh=None, engine="xla", geometry=()):
    """All T passes of ONE window chunk — the streamed unit of work.
    Same body as the in-HBM path (:func:`_chunk_passes`): split to T keys,
    fold in the chunk index, identical sharding, so streamed and in-HBM
    predictions are identical and a pod's chips all work on every chunk."""
    keys = jax.random.split(key, n_passes)
    return _chunk_passes(model, variables, chunk, key, keys, chunk_idx,
                         mode, mesh, engine, geometry)


@partial(
    jax.jit,
    static_argnames=("model", "n_passes", "mode", "batch_size", "base",
                     "mesh", "engine", "geometry"),
)
def _mcd_stats_jit(model, variables, x, key, n_passes, mode, batch_size,
                   base, eps, mesh=None, engine="xla", geometry=()):
    """Fused in-HBM MCD program: same chunked T-pass body as
    :func:`_mcd_jit` (same keys, same masks, same sharding), but each
    chunk's (T, bs) probabilities collapse on device to the (4, bs)
    sufficient statistics before ``lax.map`` stacks them — so the
    program's output (and its D2H cost) is (4, M) instead of (T, M),
    and the (chunks, T, bs) probability stack never materializes in
    HBM at all."""
    keys = jax.random.split(key, n_passes)
    chunks, m = _chunk(x, batch_size)
    chunks = _constrain(chunks, mesh, None, mesh_lib.AXIS_DATA)

    def one_chunk(args):
        with jax.named_scope("mcd_chunk"):
            chunk, chunk_idx = args
            probs = _chunk_passes(model, variables, chunk, key, keys,
                                  chunk_idx, mode, mesh, engine, geometry)
            return _constrain(_uq_stats(probs, base, eps), mesh, None,
                              mesh_lib.AXIS_DATA)

    stats = jax.lax.map(
        one_chunk, (chunks, jnp.arange(chunks.shape[0]))
    )                                                 # (chunks, 4, bs)
    stats = jnp.transpose(stats, (1, 0, 2)).reshape(N_STAT_ROWS, -1)
    return stats[:, :m]


@partial(
    jax.jit,
    static_argnames=("model", "n_passes", "mode", "base", "mesh", "engine",
                     "geometry"),
)
def _mcd_chunk_stats_jit(model, variables, chunk, key, chunk_idx, n_passes,
                         mode, base, eps, mesh=None, engine="xla",
                         geometry=()):
    """Fused streamed unit of work: all T passes of ONE chunk
    (:func:`_mcd_chunk_jit`'s exact body — same key discipline, same
    sharding) reduced on device to the chunk's (4, bs) sufficient
    statistics, so the per-chunk D2H fetch shrinks from T rows to 4."""
    keys = jax.random.split(key, n_passes)
    probs = _chunk_passes(model, variables, chunk, key, keys, chunk_idx,
                          mode, mesh, engine, geometry)
    return _constrain(_uq_stats(probs, base, eps), mesh, None,
                      mesh_lib.AXIS_DATA)


def _stream_chunked(x, batch_size: int, n_rows: int, prefetch: int, compute,
                    sharding=None):
    """Shared host-streamed chunk loop: wrap-padded chunks flow through
    the prefetch feed, ``compute(chunk, ci) -> (n_rows, bs)`` runs on
    device (``n_rows`` = the stacked output rows: T passes / N members
    for full probabilities, ``N_STAT_ROWS`` for fused sufficient
    statistics), and a bounded result queue — up to ``prefetch`` pending
    chunks, matching the feed depth — overlaps each chunk's D2H fetch
    with the following chunks' compute.  Returns the (n_rows, M)
    assembly.  ``sharding`` places each chunk directly onto a mesh
    (window axis over ``data``), so the H2D transfer lands shard-wise
    instead of bouncing through one device."""
    import collections

    import numpy as np

    from apnea_uq_tpu.data.feed import prefetch_to_device
    from apnea_uq_tpu.data.store import as_host_source
    from apnea_uq_tpu.utils.multihost import host_values

    # A memmap-backed store array (data/store.py) passes through lazily:
    # each chunk's modular gather materializes only its rows, so an
    # HBM-exceeding test set streams at O(prefetch x batch) host RSS too.
    x = as_host_source(x)
    m = x.shape[0]
    n_chunks = -(-m // batch_size)

    def chunks():
        for ci in range(n_chunks):
            rows = np.arange(ci * batch_size, (ci + 1) * batch_size) % m
            yield x[rows]

    out = np.empty((n_rows, n_chunks * batch_size), np.float32)

    def fetch(pending) -> None:
        pci, p = pending
        out[:, pci * batch_size:(pci + 1) * batch_size] = host_values(p)

    # The result queue depth follows the feed depth: with prefetch chunks
    # in flight on the H2D side, up to the same number of dispatched
    # results stay un-fetched on the D2H side, so fetch overlap scales
    # with the pipeline instead of being pinned at one pending chunk.
    # Chunk results come back through the multi-process-safe fetch: on a
    # process-spanning mesh each per-chunk output is not fully addressable
    # and a bare np.asarray would raise.  All processes run this loop in
    # lockstep (same chunks, same order), which host_values requires.
    depth = max(1, int(prefetch))
    pending: collections.deque = collections.deque()
    for ci, chunk in enumerate(
        prefetch_to_device(chunks(), size=prefetch, sharding=sharding)
    ):
        pending.append((ci, compute(chunk, ci)))
        if len(pending) > depth:
            fetch(pending.popleft())
    while pending:
        fetch(pending.popleft())
    return out[:, :m]


def effective_batch_size(batch_size: int, mesh=None) -> int:
    """The chunk size the predictors actually run at: with a mesh,
    ``batch_size`` rounds up to the data-axis multiple so chunks place
    shard-wise (required on process-spanning meshes).  Every mesh path
    applies the same rounding — both MCD paths (where chunk boundaries
    feed the per-chunk RNG fold and, in parity mode, the BN batch
    statistics, so in-HBM and streamed must agree to stay
    bit-comparable) and the streamed DE path.  Exposed so callers (e.g.
    the parity-mode chunk warning in uq/drivers.py) can reason about
    the real chunk."""
    if mesh is None:
        return batch_size
    d_axis = mesh.shape[mesh_lib.AXIS_DATA]
    return -(-batch_size // d_axis) * d_axis


def _chunk_sharding(mesh, batch_size: int):
    """Window-axis sharding for streamed chunks.

    The None branch is a defensive guard only: every streamed call site
    rounds ``batch_size`` via :func:`effective_batch_size` first, so the
    chunk always divides the data axis.  Were a non-multiple ever passed,
    returning None keeps the transfer correct (unsharded device_put; the
    in-jit constraint then reshards) on single-process meshes."""
    if mesh is None:
        return None
    if batch_size % mesh.shape[mesh_lib.AXIS_DATA] != 0:
        return None
    return NamedSharding(mesh, P(mesh_lib.AXIS_DATA))


def mc_dropout_predict_streaming(
    model: AlarconCNN1D,
    variables: dict,
    x,
    *,
    n_passes: int = 50,
    mode: str = "clean",
    batch_size: int = 512,
    key: Optional[jax.Array] = None,
    seed: int = 0,
    prefetch: int = 2,
    mesh: Optional[jax.sharding.Mesh] = None,
    run_log=None,
    record_memory_only: bool = False,
    stats=None,
    engine: str = "xla",
) -> "np.ndarray":
    """(T, M) MCD probabilities with the window set streamed from HOST
    memory: chunks flow through the double-buffered prefetch feed
    (data/feed.py) while the device computes the previous chunk's T
    passes, so HBM holds O(prefetch x batch_size) windows instead of the
    whole set — the scaling story for test sets that exceed HBM
    (SURVEY §5.7; replaces the whole-set-as-one-batch pattern of
    uq_techniques.py:22).  Produces bit-identical results to
    :func:`mc_dropout_predict` for the same key, ``mesh`` and resolved
    ``engine`` — both paths chunk at :func:`effective_batch_size`, so
    toggling streaming never changes predictions.

    ``engine='pallas'`` runs each chunk's T passes through the fused
    ops/pallas_mcd.py kernel where valid (clean mode, no mesh, TPU),
    falling back to the XLA body elsewhere (:func:`resolve_mcd_engine`).

    ``stats=(entropy_base, eps)`` switches to the fused reduction: each
    chunk's T resident passes collapse on device to the per-window
    sufficient statistics (uq/metrics.py) and the return value is the
    ``(N_STAT_ROWS, M)`` stack — the per-chunk D2H fetch shrinks from T
    rows to 4 while the stochastic passes themselves are unchanged.

    ``mesh`` composes both scaling axes: each streamed chunk's T passes
    shard over ``ensemble`` and its windows over ``data`` (the same
    layout and key discipline as the in-HBM mesh path), so a test set
    that exceeds HBM on a pod streams through ALL chips.
    """
    if mode not in _MCD_MODES:
        raise ValueError(f"mode must be 'clean' or 'parity', got {mode!r}")
    if key is None:
        key = prng.stochastic_key(seed)
    resolved_engine = resolve_mcd_engine(engine, mode, mesh)
    if mesh is not None:
        # Chunks must place shard-wise (an unsharded device_put fails on
        # a process-spanning mesh); the rounding is shared with the
        # in-HBM mesh path so both run at the same effective chunk.
        batch_size = effective_batch_size(batch_size, mesh)
        repl = mesh_lib.replicated(mesh)
        variables = jax.tree.map(lambda a: jax.device_put(a, repl), variables)
    # ONE (label, fn, per-chunk args) definition drives the program-store
    # acquisition, the memory pricing AND the streamed dispatch, so the
    # priced/stored program cannot drift from the executed one.  The
    # chunk index travels as a strong int32 scalar (fold_in numerics are
    # identical) so every chunk shares one program signature.
    if stats is not None:
        base, eps = stats
        eps = float(eps)
        label, fn, n_rows = (
            mcd_program_label(model, streamed=True, engine=engine,
                              fused=True),
            _mcd_chunk_stats_jit, N_STAT_ROWS)
        geometry = autotune_mod.tuned_kernel_kwargs(label)

        def chunk_args(chunk, ci):
            return (model, variables, chunk, key, jnp.asarray(ci, jnp.int32),
                    n_passes, _MCD_MODES[mode], base, eps, mesh,
                    resolved_engine, geometry)
    else:
        label, fn, n_rows = (
            mcd_program_label(model, streamed=True, engine=engine,
                              fused=False),
            _mcd_chunk_jit, n_passes)
        geometry = autotune_mod.tuned_kernel_kwargs(label)

        def chunk_args(chunk, ci):
            return (model, variables, chunk, key, jnp.asarray(ci, jnp.int32),
                    n_passes, _MCD_MODES[mode], mesh, resolved_engine,
                    geometry)

    # Abstract chunk at the placement the real streamed chunks land with
    # (sharded over the data axis on a mesh), so the acquired/priced
    # program IS the executed one.
    chunk_aval = jax.ShapeDtypeStruct(
        (batch_size,) + tuple(np.shape(x)[1:]), jnp.float32,
        sharding=_chunk_sharding(mesh, batch_size))
    program = program_store.get_program(
        label, fn, *chunk_args(chunk_aval, 0), run_log=run_log)
    if run_log is not None:
        # Compiled-HBM accounting of the per-chunk program (one event per
        # signature; telemetry/memory.py): abstract chunk shapes, so the
        # record never touches the window set — and with an acquired
        # program it costs nothing at all.
        telemetry_memory.record_jit_memory(
            run_log, label, fn, *chunk_args(chunk_aval, 0), program=program
        )
    if record_memory_only:
        # The drivers' pre-timing pass: the arg transforms and the
        # memory_profile record ran exactly as a real call's would, but
        # the AOT compile stays OUT of the measured predict window.
        return None
    dispatch = (
        (lambda chunk, ci: program(*chunk_args(chunk, ci)))
        if program is not None
        else (lambda chunk, ci: fn(*chunk_args(chunk, ci)))
    )
    return _stream_chunked(
        x, batch_size, n_rows, prefetch, dispatch,
        sharding=_chunk_sharding(mesh, batch_size),
    )


def mc_dropout_predict(
    model: AlarconCNN1D,
    variables: dict,
    x,
    *,
    n_passes: int = 50,
    mode: str = "clean",
    batch_size: int = 512,
    key: Optional[jax.Array] = None,
    seed: int = 0,
    mesh: Optional[jax.sharding.Mesh] = None,
    run_log=None,
    record_memory_only: bool = False,
    stats=None,
    engine: str = "xla",
) -> jax.Array:
    """(T, M) positive-class probabilities from T stochastic passes.

    ``engine='pallas'`` (``UQConfig.mcd_engine``) runs each chunk's T
    passes through the fused conv->BN->ReLU->dropout TPU kernel
    (ops/pallas_mcd.py): weights and the window tile load into VMEM once
    per tile instead of once per pass, and the dropout masks are drawn
    in-kernel from the hardware PRNG, never materializing in HBM.  Where
    the kernel is invalid (off-TPU, 'parity' mode, a mesh) the call
    silently falls back to the XLA body — :func:`resolve_mcd_engine`,
    the same fallback contract as the bootstrap kernel.  The hardware
    mask stream differs from threefry, so the two engines are
    distributionally equivalent, not bit-equal (PARITY.md "Tolerance
    tiers").

    ``stats=(entropy_base, eps)`` switches to the fused reduction:
    the same chunked T-pass program reduces each chunk on device to the
    per-window sufficient statistics (uq/metrics.py ``sufficient_stats``)
    and returns the ``(N_STAT_ROWS, M)`` stack instead of (T, M) — the
    K-axis never leaves the device and the (chunks, T, bs) probability
    stack never materializes in HBM.

    ``mesh`` spreads the work over a device mesh — passes over its
    ``ensemble`` axis, windows over ``data`` — replacing the reference's
    single-device T-pass loop (uq_techniques.py:22) at pod scale.  The
    chunk runs at :func:`effective_batch_size` (``batch_size``
    rounded up to the data-axis multiple, shared with the streamed
    path); results are identical to the single-device path at that
    effective batch size — same keys -> same dropout masks; the mesh
    only partitions the compute.

    ``mode='parity'`` reproduces the reference's ``training=True`` regime
    (dropout + batch-statistics BatchNorm, uq_techniques.py:22).  Note that
    in parity mode batch statistics are computed per (wrap-padded)
    ``batch_size`` chunk; the reference used the entire test set as one
    batch, so exact parity of that detail needs the EFFECTIVE chunk
    (:func:`effective_batch_size` — on a mesh, ``batch_size`` rounds up
    to the data-axis multiple) to be an exact multiple of ``len(x)``:
    off-mesh, pass ``batch_size = len(x)``; on a mesh, a multiple of the
    window count that the data axis divides.
    ``mode='clean'`` freezes BatchNorm at running statistics (standard MC
    Dropout; SURVEY §6).

    HBM note: all T passes of one chunk are live at once (the T axis rides
    the batch dimension), so the activation footprint scales with
    ``n_passes * batch_size`` rows.  The default (50 x 512 = 25.6K rows)
    fits a 16-GB v5e chip with headroom and measured fastest there;
    50 x 2048 already exceeds its HBM.
    """
    if mode not in _MCD_MODES:
        raise ValueError(f"mode must be 'clean' or 'parity', got {mode!r}")
    if key is None:
        key = prng.stochastic_key(seed)
    resolved_engine = resolve_mcd_engine(engine, mode, mesh)
    if record_memory_only:
        # The drivers' pre-timing pass lowers from an abstract window
        # set: same shape/dtype/sharding (so the compiled program — and
        # its memory analysis — match the real call), but the whole-set
        # H2D transfer is not paid twice.
        x = jax.ShapeDtypeStruct(
            tuple(np.shape(x)), jnp.float32,
            sharding=(mesh_lib.replicated(mesh) if mesh is not None
                      else None))
    else:
        x = jnp.asarray(x, jnp.float32)
    if mesh is not None:
        # Same rounding as the streamed path (effective_batch_size),
        # so streamed and in-HBM runs on the same mesh chunk identically
        # and their results stay bit-comparable.
        batch_size = effective_batch_size(batch_size, mesh)
        repl = mesh_lib.replicated(mesh)
        if not record_memory_only:
            x = jax.device_put(x, repl)
        variables = jax.tree.map(lambda a: jax.device_put(a, repl), variables)
    # ONE (label, fn, args) tuple drives the program-store acquisition,
    # the memory pricing and the dispatch, so the priced/stored program
    # cannot drift from the executed one.
    if stats is not None:
        base, eps = stats
        label, fn = (mcd_program_label(model, streamed=False, engine=engine,
                                       fused=True), _mcd_stats_jit)
        args = (model, variables, x, key, n_passes, _MCD_MODES[mode],
                batch_size, base, float(eps), mesh, resolved_engine,
                autotune_mod.tuned_kernel_kwargs(label))
    else:
        label, fn = (mcd_program_label(model, streamed=False, engine=engine,
                                       fused=False), _mcd_jit)
        args = (model, variables, x, key, n_passes, _MCD_MODES[mode],
                batch_size, mesh, resolved_engine,
                autotune_mod.tuned_kernel_kwargs(label))
    program = program_store.get_program(label, fn, *args, run_log=run_log)
    if run_log is not None:
        # Compiled-HBM accounting (one memory_profile event per program
        # signature): the whole T-passes-by-chunks program, priced before
        # it dispatches — for free when a program was acquired.
        telemetry_memory.record_jit_memory(run_log, label, fn, *args,
                                           program=program)
    if record_memory_only:
        # The drivers' pre-timing pass: record the program's HBM price
        # with the exact post-transform args, dispatch nothing — the
        # AOT compile stays OUT of the measured predict window.
        return None
    return program(*args) if program is not None else fn(*args)


def stack_member_variables(member_variables: list) -> dict:
    """Stack per-member variable pytrees along a leading member axis."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves, axis=0), *member_variables)


def as_stacked_members(member_variables) -> dict:
    """Normalize every accepted DE-member carrier to one stacked pytree:
    a list/tuple of per-member variable dicts, an already-stacked pytree,
    or an ``EnsembleFitResult`` (duck-typed via ``stacked_variables`` to
    avoid importing the trainer here).  Accepting the fit result directly
    means the EFFECTIVE member count — including padded lockstep slots
    promoted by ``EnsembleConfig.keep_padded_members`` — flows into
    inference whole; callers can't accidentally re-slice it away."""
    if hasattr(member_variables, "stacked_variables"):
        member_variables = member_variables.stacked_variables()
    if isinstance(member_variables, (list, tuple)):
        member_variables = stack_member_variables(list(member_variables))
    return member_variables


def _de_chunk_probs(model, stacked_variables, chunk, engine, geometry):
    """ONE chunk's (N, bs) member probabilities under the RESOLVED
    engine: the XLA member vmap or the fused Pallas kernel
    (ops/pallas_de.py, single-device TPU only — the resolver guarantees
    it).  ``geometry`` is the label's autotuned tile-geometry kwargs
    (ops/autotune.py), a static tuple of (name, value) pairs — empty
    means kernel defaults."""
    if engine == "pallas":
        with jax.named_scope("de_pallas"):
            return pallas_de.de_pallas_members(
                model, stacked_variables, chunk, **dict(geometry))
    return _member_vmap(model, stacked_variables, chunk)


def _de_chunk_stats(model, stacked_variables, chunk, base, eps, engine,
                    geometry):
    """ONE chunk reduced to its (4, bs) sufficient statistics under the
    RESOLVED engine.  The pallas body fuses the member reduction
    IN-KERNEL (ops/pallas_de.py ``de_pallas_stats`` — the (N, tile)
    probability block never leaves VMEM); the XLA body reduces the vmap
    output with the same ``sufficient_stats`` formula."""
    if engine == "pallas":
        with jax.named_scope("de_pallas"):
            return pallas_de.de_pallas_stats(
                model, stacked_variables, chunk, base=base, eps=float(eps),
                **dict(geometry))
    return _uq_stats(_member_vmap(model, stacked_variables, chunk), base, eps)


@partial(jax.jit,
         static_argnames=("model", "batch_size", "engine", "geometry"))
def _ensemble_jit(model, stacked_variables, x, batch_size, engine="xla",
                  geometry=()):
    chunks, m = _chunk(x, batch_size)

    def one_chunk(chunk):
        return _de_chunk_probs(model, stacked_variables, chunk, engine,
                               geometry)

    probs = jax.lax.map(one_chunk, chunks)              # (chunks, N, bs)
    n_members = probs.shape[1]
    probs = jnp.transpose(probs, (1, 0, 2)).reshape(n_members, -1)
    return probs[:, :m]


@partial(jax.jit, static_argnames=("model", "batch_size", "mesh"))
def _ensemble_shard_map_jit(model, stacked_variables, x, batch_size, mesh):
    """Deterministic ensemble inference as an explicit ``shard_map``: each
    device computes its (member-group x window-slice) block with purely
    local compute — no partitioner discretion, no collectives until the
    output is assembled.  (MCD cannot use this layout: per-pass dropout
    masks drawn per local block would differ from the single-device
    stream, so it keeps the GSPMD-partitioned global program instead.)

    Requires the member axis divisible by the mesh's ensemble axis (the
    caller wrap-pads) and wrap-pads windows to the data axis here."""
    m = x.shape[0]
    x = _wrap_pad(x, mesh.shape[mesh_lib.AXIS_DATA])

    def block(member_vars, x_local):
        def one_member(mv):
            chunks, m_local = _chunk(
                x_local, min(batch_size, x_local.shape[0])
            )

            def one_chunk(chunk):
                with jax.named_scope("de_member_chunk"):
                    logits, _ = apply_model(model, mv, chunk, mode="eval")
                    return predict_proba(logits)

            probs = jax.lax.map(one_chunk, chunks)      # (chunks, bs_local)
            return probs.reshape(-1)[:m_local]

        with jax.named_scope("de_shard_block"):
            return jax.vmap(one_member)(member_vars)    # (N_local, m_local)

    f = _shard_map(
        block,
        mesh=mesh,
        in_specs=(P(mesh_lib.AXIS_ENSEMBLE), P(mesh_lib.AXIS_DATA)),
        out_specs=P(mesh_lib.AXIS_ENSEMBLE, mesh_lib.AXIS_DATA),
    )
    return f(stacked_variables, x)[:, :m]


def _member_vmap(model, stacked_variables, chunk):
    """The XLA DE chunk body: eval-mode member forwards vmapped over the
    stacked member axis — shared by the single-device paths (where the
    pallas engine is its drop-in twin) and the shard_map mesh blocks."""
    def one_member(member_vars):
        with jax.named_scope("de_member"):
            logits, _ = apply_model(model, member_vars, chunk, mode="eval")
            return predict_proba(logits)

    return jax.vmap(one_member)(stacked_variables)  # (N, bs)


@partial(jax.jit, static_argnames=("model", "engine", "geometry"))
def _ensemble_chunk_jit(model, stacked_variables, chunk, engine="xla",
                        geometry=()):
    return _de_chunk_probs(model, stacked_variables, chunk, engine, geometry)


@partial(jax.jit, static_argnames=("model", "mesh"))
def _ensemble_chunk_mesh_jit(model, stacked_variables, chunk, mesh):
    """One streamed chunk through the whole ensemble on the mesh: the
    same explicit shard_map layout as :func:`_ensemble_shard_map_jit` —
    each device computes its (member-group x window-slice) block of the
    chunk with purely local math."""
    f = _shard_map(
        lambda mv, xl: _member_vmap(model, mv, xl),
        mesh=mesh,
        in_specs=(P(mesh_lib.AXIS_ENSEMBLE), P(mesh_lib.AXIS_DATA)),
        out_specs=P(mesh_lib.AXIS_ENSEMBLE, mesh_lib.AXIS_DATA),
    )
    return f(stacked_variables, chunk)


@partial(jax.jit, static_argnames=("model", "batch_size", "base", "engine",
                                   "geometry"))
def _ensemble_stats_jit(model, stacked_variables, x, batch_size, base, eps,
                        engine="xla", geometry=()):
    """Fused in-HBM DE program: :func:`_ensemble_jit`'s chunked member
    body with each chunk's (N, bs) probabilities collapsed on device to
    the (4, bs) sufficient statistics — output (and D2H) is (4, M).
    Under the pallas engine the reduction fuses in-kernel
    (:func:`_de_chunk_stats`)."""
    chunks, m = _chunk(x, batch_size)

    def one_chunk(chunk):
        return _de_chunk_stats(model, stacked_variables, chunk, base, eps,
                               engine, geometry)

    stats = jax.lax.map(one_chunk, chunks)              # (chunks, 4, bs)
    stats = jnp.transpose(stats, (1, 0, 2)).reshape(N_STAT_ROWS, -1)
    return stats[:, :m]


@partial(
    jax.jit,
    static_argnames=("model", "batch_size", "n_members", "base", "mesh"),
)
def _ensemble_shard_map_stats_jit(model, stacked_variables, x, batch_size,
                                  n_members, base, eps, mesh):
    """Fused mesh DE program: the explicit shard_map block of
    :func:`_ensemble_shard_map_jit` computes the (N_padded, M)
    probabilities exactly as the full path does, then — still inside the
    jit — the wrap-padded duplicate members are sliced OFF before the
    member-axis reduction (a duplicate member in the mean/variance would
    skew every statistic) and the (4, M) sufficient statistics come out.
    The cross-device member reduction is GSPMD's to schedule; the math
    per (member, window) is unchanged."""
    probs = _ensemble_shard_map_jit.__wrapped__(
        model, stacked_variables, x, batch_size, mesh
    )
    return _constrain(_uq_stats(probs[:n_members], base, eps), mesh, None,
                      mesh_lib.AXIS_DATA)


@partial(jax.jit, static_argnames=("model", "base", "engine", "geometry"))
def _ensemble_chunk_stats_jit(model, stacked_variables, chunk, base, eps,
                              engine="xla", geometry=()):
    """Fused streamed DE unit: one chunk through all members, reduced on
    device to (4, bs) — in-kernel under the pallas engine
    (:func:`_de_chunk_stats`)."""
    return _de_chunk_stats(model, stacked_variables, chunk, base, eps,
                           engine, geometry)


@partial(jax.jit, static_argnames=("model", "n_members", "base", "mesh"))
def _ensemble_chunk_mesh_stats_jit(model, stacked_variables, chunk,
                                   n_members, base, eps, mesh):
    """Fused streamed+mesh DE unit: the shard_map chunk block of
    :func:`_ensemble_chunk_mesh_jit`, wrap-padded duplicate members
    sliced off inside the jit, then the (4, bs) reduction."""
    probs = _ensemble_chunk_mesh_jit.__wrapped__(
        model, stacked_variables, chunk, mesh
    )
    return _constrain(_uq_stats(probs[:n_members], base, eps), mesh, None,
                      mesh_lib.AXIS_DATA)


def ensemble_predict_streaming(
    model: AlarconCNN1D,
    member_variables,
    x,
    *,
    batch_size: int = 2048,
    prefetch: int = 2,
    mesh: Optional[jax.sharding.Mesh] = None,
    run_log=None,
    record_memory_only: bool = False,
    stats=None,
    engine: str = "xla",
) -> "np.ndarray":
    """(N, M) deterministic ensemble probabilities with the window set
    streamed from HOST memory (see :func:`mc_dropout_predict_streaming`):
    chunks flow through the prefetch feed, a bounded result queue
    (depth = ``prefetch``) overlaps D2H with the following chunks'
    compute, and HBM holds O(prefetch x batch_size) windows plus the
    stacked members.  Identical results to :func:`ensemble_predict`
    (deterministic eval mode).

    ``engine='pallas'`` runs each chunk through the fused member-batched
    ops/pallas_de.py kernel where valid (no mesh, TPU), falling back to
    the XLA body elsewhere (:func:`resolve_de_engine`); DE is
    deterministic, so the engines agree elementwise at the f32 tier.

    ``stats=(entropy_base, eps)`` switches to the fused reduction: each
    chunk's member probabilities collapse on device to the per-window
    sufficient statistics and the return value is ``(N_STAT_ROWS, M)``
    (wrap-padded duplicate members are excluded inside the jit).

    ``mesh`` shards each streamed chunk's members over ``ensemble`` and
    windows over ``data`` (the shard_map layout of the in-HBM mesh path),
    composing the small-memory and many-chips axes.  The chunk size is
    rounded up to the data-axis multiple shard_map requires.
    """
    member_variables = as_stacked_members(member_variables)
    n_members = jax.tree.leaves(member_variables)[0].shape[0]
    resolved_engine = resolve_de_engine(engine, mesh)
    if stats is not None:
        base, eps = stats
        eps = float(eps)
    if mesh is not None:
        e_axis = mesh.shape[mesh_lib.AXIS_ENSEMBLE]
        batch_size = effective_batch_size(batch_size, mesh)
        member_variables = jax.tree.map(
            lambda a: _wrap_pad(a, e_axis), member_variables
        )
        member_variables = mesh_lib.shard_member_tree(member_variables, mesh)
    n_padded = jax.tree.leaves(member_variables)[0].shape[0]

    # ONE (label, fn, per-chunk args, output rows) definition drives both
    # the memory pricing and the streamed dispatch, so the priced program
    # cannot drift from the executed one.  Full-probs mesh chunks come
    # back with the wrap-padded member rows (sliced off after assembly);
    # fused chunks exclude the duplicates inside the jit.
    label = de_program_label(model, streamed=True, engine=engine,
                             fused=stats is not None)
    geometry = autotune_mod.tuned_kernel_kwargs(label)
    if mesh is None and stats is None:
        fn, n_rows = _ensemble_chunk_jit, n_members
        chunk_args = lambda chunk, ci: (model, member_variables, chunk,
                                        resolved_engine, geometry)
    elif mesh is None:
        fn, n_rows = _ensemble_chunk_stats_jit, N_STAT_ROWS
        chunk_args = lambda chunk, ci: (model, member_variables, chunk,
                                        base, eps, resolved_engine, geometry)
    elif stats is None:
        fn, n_rows = _ensemble_chunk_mesh_jit, n_padded
        chunk_args = lambda chunk, ci: (model, member_variables, chunk, mesh)
    else:
        fn, n_rows = _ensemble_chunk_mesh_stats_jit, N_STAT_ROWS
        chunk_args = lambda chunk, ci: (model, member_variables, chunk,
                                        n_members, base, eps, mesh)

    chunk_aval = jax.ShapeDtypeStruct(
        (batch_size,) + tuple(np.shape(x)[1:]), jnp.float32,
        sharding=_chunk_sharding(mesh, batch_size))
    program = program_store.get_program(
        label, fn, *chunk_args(chunk_aval, 0), run_log=run_log)
    if run_log is not None:
        telemetry_memory.record_jit_memory(
            run_log, label, fn, *chunk_args(chunk_aval, 0), program=program
        )
    if record_memory_only:
        return None  # drivers' pre-timing pass (see mc_dropout_predict)
    dispatch = (
        (lambda chunk, ci: program(*chunk_args(chunk, ci)))
        if program is not None
        else (lambda chunk, ci: fn(*chunk_args(chunk, ci)))
    )
    out = _stream_chunked(
        x, batch_size, n_rows, prefetch, dispatch,
        sharding=_chunk_sharding(mesh, batch_size),
    )
    return out if stats is not None else out[:n_members]


def ensemble_predict(
    model: AlarconCNN1D,
    member_variables,
    x,
    *,
    batch_size: int = 2048,
    mesh: Optional[jax.sharding.Mesh] = None,
    run_log=None,
    record_memory_only: bool = False,
    stats=None,
    engine: str = "xla",
) -> jax.Array:
    """(N, M) deterministic probabilities from N ensemble members.
    All N members' activations for one chunk are live at once, so the
    footprint scales with ``n_members * batch_size`` rows (see the HBM
    note on :func:`mc_dropout_predict`).

    ``engine='pallas'`` (``UQConfig.de_engine``) runs each chunk through
    the fused member-batched TPU kernel (ops/pallas_de.py): every
    member's folded weights load into VMEM once per window tile and the
    member axis is processed in ``member_group`` batches — with
    ``stats`` set, the sufficient-stats reduction fuses in-kernel too.
    Where the kernel is invalid (off-TPU, a mesh) the call silently
    falls back to the XLA body under the same label
    (:func:`resolve_de_engine`, the shared :func:`resolve_engine`
    fallback rules).  DE is deterministic, so the two engines agree
    elementwise at the f32 tier (PARITY.md "Tolerance tiers").

    ``stats=(entropy_base, eps)`` switches to the fused reduction: the
    member probabilities collapse on device to the per-window sufficient
    statistics and the return value is ``(N_STAT_ROWS, M)`` — on the mesh
    path the wrap-padded duplicate members are sliced off INSIDE the jit,
    before the member-axis reduction.

    ``member_variables`` is a list of per-member variable pytrees, an
    already-stacked pytree with a leading member axis, or a
    ``fit_ensemble`` result (whose effective member count — promoted
    padded slots included — then flows into inference).  Members are
    vmapped — one batched program instead of the reference's N sequential
    ``model.predict`` calls (uq_techniques.py:29-30).  With ``mesh``,
    members spread over the ``ensemble`` axis and windows over ``data``,
    so eval-de scales across a pod instead of leaving chips idle.
    """
    member_variables = as_stacked_members(member_variables)
    resolved_engine = resolve_de_engine(engine, mesh)
    if record_memory_only:
        # Abstract window set for the drivers' pre-timing pass: same
        # program (shape/dtype/sharding), no second whole-set transfer.
        x = jax.ShapeDtypeStruct(
            tuple(np.shape(x)), jnp.float32,
            sharding=(mesh_lib.replicated(mesh) if mesh is not None
                      else None))
    else:
        x = jnp.asarray(x, jnp.float32)
    n_members = jax.tree.leaves(member_variables)[0].shape[0]
    if stats is not None:
        base, eps = stats
        eps = float(eps)
    if mesh is not None:
        # device_put needs the member axis divisible by the ensemble axis;
        # wrap-pad it and slice the duplicate rows back off below (the
        # fused program slices them off inside the jit instead).
        e_axis = mesh.shape[mesh_lib.AXIS_ENSEMBLE]
        member_variables = jax.tree.map(
            lambda a: _wrap_pad(a, e_axis), member_variables
        )
        if not record_memory_only:
            x = jax.device_put(x, mesh_lib.replicated(mesh))
        member_variables = mesh_lib.shard_member_tree(member_variables, mesh)

    # ONE (label, fn, args) tuple drives the program-store acquisition,
    # the memory pricing and the dispatch, so the priced/stored program
    # cannot drift from the executed one.
    label = de_program_label(model, streamed=False, engine=engine,
                             fused=stats is not None)
    geometry = autotune_mod.tuned_kernel_kwargs(label)
    if mesh is not None and stats is not None:
        fn = _ensemble_shard_map_stats_jit
        args = (model, member_variables, x, batch_size, n_members, base,
                eps, mesh)
    elif mesh is not None:
        fn = _ensemble_shard_map_jit
        args = (model, member_variables, x, batch_size, mesh)
    elif stats is not None:
        fn = _ensemble_stats_jit
        args = (model, member_variables, x, batch_size, base, eps,
                resolved_engine, geometry)
    else:
        fn = _ensemble_jit
        args = (model, member_variables, x, batch_size, resolved_engine,
                geometry)
    program = program_store.get_program(label, fn, *args, run_log=run_log)
    if run_log is not None:
        # Compiled-HBM accounting (one memory_profile event per program
        # signature; telemetry/memory.py) — free when a program was
        # acquired.
        telemetry_memory.record_jit_memory(run_log, label, fn, *args,
                                           program=program)
    if record_memory_only:
        return None  # drivers' pre-timing pass (see mc_dropout_predict)
    out = program(*args) if program is not None else fn(*args)
    if mesh is not None and stats is None:
        out = out[:n_members]  # drop the wrap-padded duplicate members
    return out
