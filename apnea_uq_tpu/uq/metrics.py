"""On-device uncertainty metric engine.

Computes, from a (K, M) matrix of positive-class probabilities (K = MC
passes or ensemble members, M = windows), the full decomposition the
reference produces in host NumPy (uq_techniques.py:40-112):

- per-window mean probability and predictive variance,
- **total** uncertainty  H[E[p]]  (entropy of the mean),
- **aleatoric** proxy    E[H[p]]  (mean of per-pass entropies),
- **epistemic** proxy    MI = max(H[E[p]] - E[H[p]], 0),
- overall and per-true-class mean variance.

The reference computes E[H[p]] with a Python loop over passes
(uq_techniques.py:83-87); here it is one fused reduction under ``jit``.
Entropy base is explicit ('nats' matches uq_techniques.py:38; 'bits'
matches analyze_mcd_patient_level.py:114-115 — the reference silently uses
both).  Note the reference's inline comments at uq_techniques.py:75-81
mislabel total/aleatoric; the code (and this module) implement the
standard decomposition, matching the reference's returned key names.
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from apnea_uq_tpu.ops.entropy import binary_entropy

# Per-window sufficient-statistic rows of the decomposition: everything
# downstream (mutual information, the aggregate dict, the bootstrap) is a
# pure function of these four vectors — the K axis never needs to leave
# the device.  The fused predictors (uq/predict.py) emit exactly this
# stack per chunk, so an eval ships (4, M) floats device->host instead of
# the full (K, M) probability matrix.
STAT_MEAN, STAT_VARIANCE, STAT_TOTAL, STAT_ALEATORIC = range(4)
N_STAT_ROWS = 4


def sufficient_stats(predictions: jax.Array, *, base: str = "nats",
                     eps: float = 1e-10) -> jax.Array:
    """(K, n) probabilities -> the (4, n) per-window sufficient statistics
    [mean, population variance, H[E[p]], E[H[p]]].  Traceable; accumulates
    in float32 regardless of the input dtype (bf16 probabilities under
    ``compute_dtype='bfloat16'`` must not lose the K-axis reduction
    precision).  This IS the first half of :func:`_uq_core` — the full
    and fused paths share it, so their per-window values agree by
    construction, not by keeping two formula copies in sync."""
    p = predictions.astype(jnp.float32)
    mean_pred = jnp.mean(p, axis=0)
    pred_variance = jnp.var(p, axis=0)   # population variance, np.var parity
    total = binary_entropy(mean_pred, base=base, eps=eps,
                           dtype=jnp.float32)                  # H[E[p]]
    aleatoric = jnp.mean(binary_entropy(p, base=base, eps=eps,
                                        dtype=jnp.float32), axis=0)  # E[H[p]]
    return jnp.stack([mean_pred, pred_variance, total, aleatoric])


def _decompose(stats: jax.Array, y_true: jax.Array) -> Dict[str, jax.Array]:
    """(4, M) sufficient statistics -> the full metric dict (traceable)."""
    stats = stats.astype(jnp.float32)
    mean_pred = stats[STAT_MEAN]
    pred_variance = stats[STAT_VARIANCE]
    total = stats[STAT_TOTAL]
    aleatoric = stats[STAT_ALEATORIC]
    mutual_info = jnp.maximum(total - aleatoric, 0.0)  # uq_techniques.py:91
    return _aggregate(
        mean_pred, pred_variance, total, aleatoric, mutual_info, y_true
    )


@jax.jit
def decompose_from_stats(stats, y_true) -> Dict[str, jax.Array]:
    """Metric dict from a (4, M) sufficient-statistics stack (the fused
    predictors' output).  Produces the exact dict :func:`uq_evaluation_dist`
    returns for the full (K, M) stack — same ``_aggregate``, same keys —
    because both routes run :func:`_decompose` on :func:`sufficient_stats`
    output; only where the stats are computed differs (per device chunk
    vs. one whole-set reduction)."""
    stats = jnp.asarray(stats)
    if stats.ndim != 2 or stats.shape[0] != N_STAT_ROWS:
        raise ValueError(
            f"expected ({N_STAT_ROWS}, M) sufficient statistics, got "
            f"shape {stats.shape}"
        )
    y_true = jnp.asarray(y_true)
    if y_true.shape[0] != stats.shape[1]:
        raise ValueError(
            f"labels ({y_true.shape[0]}) do not match stat windows "
            f"({stats.shape[1]})"
        )
    return _decompose(stats, y_true)


@partial(jax.jit, static_argnames=("base",))
def _uq_core(predictions: jax.Array, y_true: jax.Array, base: str, eps: float) -> Dict[str, jax.Array]:
    return _decompose(sufficient_stats(predictions, base=base, eps=eps), y_true)


@jax.jit
def _aggregate(mean_pred, pred_variance, total, aleatoric, mutual_info, y_true):
    y = y_true.astype(jnp.int32)
    mask0 = (y == 0).astype(jnp.float32)
    mask1 = (y == 1).astype(jnp.float32)
    n0 = jnp.sum(mask0)
    n1 = jnp.sum(mask1)
    # Empty-class guard -> 0.0, matching uq_techniques.py:100-101.
    mv0 = jnp.where(n0 > 0, jnp.sum(pred_variance * mask0) / jnp.maximum(n0, 1.0), 0.0)
    mv1 = jnp.where(n1 > 0, jnp.sum(pred_variance * mask1) / jnp.maximum(n1, 1.0), 0.0)

    return {
        "mean_pred": mean_pred,
        "pred_variance": pred_variance,
        "total_pred_entropy": total,
        "expected_aleatoric_entropy": aleatoric,
        "mutual_info": mutual_info,
        "overall_mean_variance": jnp.mean(pred_variance),
        "mean_variance_class_0": mv0,
        "mean_variance_class_1": mv1,
    }


def uq_evaluation_dist(
    predictions,
    y_true,
    *,
    base: str = "nats",
    eps: float = 1e-10,
) -> Dict[str, jax.Array]:
    """UQ metric suite from a (K, M) (or (K, M, 1) / (M,)) prediction stack.

    Degenerate-input handling mirrors uq_techniques.py:61-66: trailing
    singleton dims are squeezed and a 1-D input is treated as a single
    pass (variance and MI collapse to zero).

    One jitted XLA fusion.  (A hand-written Pallas kernel for this
    reduction was measured SLOWER than the XLA fusion on a v5e —
    11.25 ms vs 15.9 ms chained at K=50, M=4.2M; the op is VPU
    transcendental-bound, where XLA's codegen wins — and was removed in
    r2.  The Pallas effort goes where it pays: the bootstrap resampler,
    ops/pallas_bootstrap.py.)
    """
    predictions = jnp.asarray(predictions)
    # Squeeze ONLY a trailing singleton output axis of a (K, M, 1) stack —
    # a blanket squeeze would misread a (K, 1) single-window stack as
    # (1, K).  Mirrors evaluate_uq_methods' dimension handling
    # (uq_techniques.py:316-319).
    if predictions.ndim == 3 and predictions.shape[-1] == 1:
        predictions = predictions[..., 0]
    if predictions.ndim == 1:
        predictions = predictions[None, :]
    if predictions.ndim != 2:
        raise ValueError(f"expected (K, M) predictions, got shape {predictions.shape}")
    y_true = jnp.asarray(y_true)
    if y_true.shape[0] != predictions.shape[1]:
        raise ValueError(
            f"labels ({y_true.shape[0]}) do not match prediction windows "
            f"({predictions.shape[1]})"
        )
    return _uq_core(predictions, y_true, base, eps)


def per_window_frame(metrics: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """The per-window vectors of the metric dict (for CSV emission)."""
    return {
        k: metrics[k]
        for k in (
            "mean_pred",
            "pred_variance",
            "total_pred_entropy",
            "expected_aleatoric_entropy",
            "mutual_info",
        )
    }
