"""End-to-end UQ evaluation drivers (reference C12-C16).

The reference splits this across five scripts — ``evaluate_uq_methods``
(uq_techniques.py:278-391) plus four near-duplicate driver scripts
(analyze_{mcd,de}_patient_level.py, evaluate_{mcd,de}_global.py) that
differ only in predictor, ensemble size, and whether a per-window CSV is
written.  Here one parameterized pipeline covers all four:

    predictions -> on-device UQ metrics -> vectorized bootstrap CIs
                -> detailed per-window frame -> artifacts

``run_mcd_analysis`` / ``run_de_analysis`` correspond to the patient-level
drivers (C13/C14); calling them with ``patient_ids=None`` and
``detailed=False`` reproduces the global variants (C15/C16).  The
reference's double T=50 prediction in evaluate_mcd_global.py:104,118 is
intentionally not replicated — prediction runs once per test set.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, Optional

import jax
import numpy as np
import pandas as pd

from apnea_uq_tpu.analysis.columns import (
    COL_ENTROPY,
    COL_PATIENT,
    COL_PRED_LABEL,
    COL_PROB,
    COL_TRUE_LABEL,
    COL_VARIANCE,
    COL_WINDOW,
)
from apnea_uq_tpu.config import UQConfig
from apnea_uq_tpu.evaluation.classification import evaluate_classification
from apnea_uq_tpu.ops.entropy import binary_entropy
from apnea_uq_tpu.training.trainer import predict_proba_batched
from apnea_uq_tpu.uq.bootstrap import bootstrap_aggregates, compute_confidence_intervals
from apnea_uq_tpu.uq.metrics import (
    N_STAT_ROWS,
    STAT_MEAN,
    STAT_VARIANCE,
    decompose_from_stats,
    uq_evaluation_dist,
)
from apnea_uq_tpu.uq.predict import (
    as_stacked_members,
    ensemble_predict,
    ensemble_predict_streaming,
    mc_dropout_predict,
    mc_dropout_predict_streaming,
    effective_batch_size,
)
from apnea_uq_tpu.telemetry import trace as telemetry_trace
from apnea_uq_tpu.telemetry.steps import StepMetrics
from apnea_uq_tpu.utils import prng
from apnea_uq_tpu.utils.timing import block

# The reference's detailed CSV writes binary entropy of the mean prob in
# BITS with eps 1e-9 (analyze_mcd_patient_level.py:113-115) while the
# aggregate engine uses nats/1e-10 (uq_techniques.py:35-38); both are
# explicit parameters here, defaulting to the per-surface reference values.
DETAILED_ENTROPY_BASE = "bits"
DETAILED_ENTROPY_EPS = 1e-9


# Prediction stacks from mesh-sharded inference span processes on a
# multi-host mesh; host fetches go through the shared helper.
from apnea_uq_tpu.utils.multihost import host_values as _host_predictions


@dataclasses.dataclass
class UQEvaluation:
    """Aggregates + bootstrap CIs over one prediction stack (C12 parity)."""

    aggregates: Dict[str, float]          # point estimates (full sample)
    confidence_intervals: Dict[str, float]
    per_window: Dict[str, np.ndarray]     # mean/variance/entropies/MI vectors
    n_passes: int
    n_windows: int


@dataclasses.dataclass
class UQRunResult:
    """One driver run on one test set.

    A fused run (``UQConfig.fused_reduction``, the default) never
    materializes the (K, M) probability matrix on host: ``predictions``
    is None and ``stats`` carries the (4, M) sufficient-statistics stack
    the decomposition (and the detailed frame) derive from.  A
    full-probs run (``--full-probs``) is the converse."""

    label: str
    predictions: Optional[np.ndarray]     # (K, M) probability stack (full-probs runs)
    evaluation: UQEvaluation
    detailed: Optional[pd.DataFrame]      # reference detailed-CSV schema
    classification: Dict                  # stochastic-mean-prob metric suite
    deterministic_classification: Optional[Dict]  # eval-mode sanity check
    predict_seconds: float
    y_true: Optional[np.ndarray] = None   # (M,) labels (for per-class plots)
    stats: Optional[np.ndarray] = None    # (4, M) sufficient stats (fused runs)
    fused: bool = False


def _finish_evaluation(metrics, y_true, config: UQConfig,
                       n_passes: int, n_windows: int,
                       key: Optional[jax.Array]) -> UQEvaluation:
    """Metric dict -> bootstrap CIs + host aggregates: the shared back
    half of :func:`evaluate_uq` and :func:`evaluate_uq_from_stats` (the
    bootstrap consumes only the per-window metric vectors, never the
    (K, M) stack, so both routes feed it identically)."""
    boot = bootstrap_aggregates(
        None,
        y_true,
        n_bootstrap=config.n_bootstrap,
        key=key,
        metrics=metrics,
        engine=config.bootstrap_engine,
    )
    metrics, boot = block((metrics, boot))

    aggregates = {
        "overall_mean_variance": float(metrics["overall_mean_variance"]),
        "mean_variance_class_0": float(metrics["mean_variance_class_0"]),
        "mean_variance_class_1": float(metrics["mean_variance_class_1"]),
        "mean_total_pred_entropy": float(np.mean(metrics["total_pred_entropy"])),
        "mean_expected_aleatoric_entropy": float(
            np.mean(metrics["expected_aleatoric_entropy"])
        ),
        "mean_mutual_info": float(np.mean(metrics["mutual_info"])),
    }
    per_window = {
        k: np.asarray(metrics[k])
        for k in (
            "mean_pred",
            "pred_variance",
            "total_pred_entropy",
            "expected_aleatoric_entropy",
            "mutual_info",
        )
    }
    return UQEvaluation(
        aggregates=aggregates,
        confidence_intervals=compute_confidence_intervals(
            boot, alpha=config.bootstrap_alpha
        ),
        per_window=per_window,
        n_passes=int(n_passes),
        n_windows=int(n_windows),
    )


def evaluate_uq(
    predictions,
    y_true,
    config: UQConfig = UQConfig(),
    *,
    key: Optional[jax.Array] = None,
    base: str = "nats",
) -> UQEvaluation:
    """Metric aggregates + bootstrap CIs from a (K, M) prediction stack.

    One fused on-device computation replacing evaluate_uq_methods'
    host-NumPy metric pass + B×(metric pass) bootstrap loop
    (uq_techniques.py:323,341-346).
    """
    predictions = np.asarray(predictions)
    if predictions.ndim == 3 and predictions.shape[-1] == 1:
        predictions = predictions[..., 0]
    metrics = uq_evaluation_dist(predictions, y_true, base=base, eps=config.entropy_eps)
    k_passes, m = (
        predictions.shape if predictions.ndim >= 2 else (1, predictions.shape[0])
    )
    return _finish_evaluation(metrics, y_true, config, k_passes, m, key)


def evaluate_uq_from_stats(
    stats,
    y_true,
    n_passes: int,
    config: UQConfig = UQConfig(),
    *,
    key: Optional[jax.Array] = None,
) -> UQEvaluation:
    """Metric aggregates + bootstrap CIs from a (4, M) sufficient-
    statistics stack (the fused predictors' output).  ``n_passes`` is
    recorded for provenance only — the statistics already integrated the
    K axis on device.  Same metric dict, same bootstrap stream, same CI
    formulas as :func:`evaluate_uq` on the corresponding full stack."""
    stats = np.asarray(stats)
    metrics = decompose_from_stats(stats, y_true)
    return _finish_evaluation(
        metrics, y_true, config, n_passes, stats.shape[1], key
    )


def detailed_frame(
    predictions,
    y_true,
    patient_ids=None,
    *,
    threshold: float = 0.5,
) -> pd.DataFrame:
    """Per-window detailed results in the reference CSV schema.

    Columns and semantics match analyze_mcd_patient_level.py:109-152 /
    analyze_de_patient_level.py:121-164: mean probability over passes,
    population variance, binary entropy of the mean in bits (eps 1e-9),
    and the 0.5-threshold label.
    """
    predictions = np.asarray(predictions)
    if predictions.ndim == 3 and predictions.shape[-1] == 1:
        predictions = predictions[..., 0]
    return _assemble_detailed(
        predictions.mean(axis=0), predictions.var(axis=0), y_true,
        patient_ids, threshold,
    )


def detailed_frame_from_stats(
    stats,
    y_true,
    patient_ids=None,
    *,
    threshold: float = 0.5,
) -> pd.DataFrame:
    """The reference detailed-CSV frame from a (4, M) sufficient-
    statistics stack: mean and variance are the first two stat rows,
    and the bits-base entropy column is (in both routes) derived from
    the mean probability — so a fused run's CSV matches a full-probs
    run's to float32 rounding."""
    stats = np.asarray(stats)
    if stats.ndim != 2 or stats.shape[0] != N_STAT_ROWS:
        raise ValueError(
            f"expected ({N_STAT_ROWS}, M) sufficient statistics, got "
            f"shape {stats.shape}"
        )
    return _assemble_detailed(
        stats[STAT_MEAN], stats[STAT_VARIANCE], y_true, patient_ids,
        threshold,
    )


def _assemble_detailed(mean_prob, variance, y_true, patient_ids,
                       threshold: float) -> pd.DataFrame:
    entropy = np.asarray(
        binary_entropy(
            mean_prob, base=DETAILED_ENTROPY_BASE, eps=DETAILED_ENTROPY_EPS
        )
    )
    y_true = np.asarray(y_true).reshape(-1)
    m = mean_prob.shape[0]
    if y_true.shape[0] != m:
        raise ValueError(f"labels ({y_true.shape[0]}) != windows ({m})")
    if patient_ids is None:
        patient_ids = np.full(m, "UNKNOWN")
    patient_ids = np.asarray(patient_ids).reshape(-1)
    if patient_ids.shape[0] != m:
        raise ValueError(f"patient_ids ({patient_ids.shape[0]}) != windows ({m})")
    return pd.DataFrame({
        COL_PATIENT: patient_ids,
        COL_WINDOW: np.arange(m),
        COL_TRUE_LABEL: y_true.astype(np.int64),
        COL_PRED_LABEL: (np.asarray(mean_prob) > threshold).astype(np.int64),
        COL_PROB: np.asarray(mean_prob, np.float64),
        COL_VARIANCE: np.asarray(variance, np.float64),
        COL_ENTROPY: entropy.astype(np.float64),
    })


def _member_count(member_variables) -> int:
    """Member count of any carrier ``as_stacked_members`` accepts, without
    forcing the stack copy a plain list would pay."""
    if isinstance(member_variables, (list, tuple)):
        return len(member_variables)
    stacked = as_stacked_members(member_variables)
    return int(jax.tree.leaves(stacked)[0].shape[0])


def _measured_predict(label: str, method: str, predict, n_windows: int,
                      n_passes: int, run_log, *, fused: bool = False):
    """Run one predictor thunk under StepMetrics: device-bounded predict
    seconds (``block_until_ready``, not dispatch return), windows/sec,
    and retrace/compile deltas; emits an ``eval_predict`` event when a
    run log is attached.  The event carries ``fused`` and a ``d2h_bytes``
    estimate — result rows x windows x 4 bytes (f32): the LOGICAL
    prediction-result payload, 4 stat rows fused vs K probability rows
    full, so the ~K/4x reduction is a gateable telemetry number, not
    prose.  It is a lower bound on the wire transfer: streamed paths
    also fetch the wrap-padded window columns (and, on full-probs mesh
    DE, the padded member rows) that are sliced off on host — padding
    overhead is a constant factor of the same rows, so the fused/full
    ratio it gates is unaffected.  Returns (predictions,
    predict_seconds)."""
    metrics = StepMetrics(run_log)
    with telemetry_trace.annotate(f"{label}.predict"):
        predictions = metrics.measure(
            f"{method}_predict", predict, n_items=n_windows
        )
    record = metrics.last
    if run_log is not None:
        result_rows = N_STAT_ROWS if fused else int(n_passes)
        run_log.event(
            "eval_predict",
            label=label,
            method=method,
            n_passes=int(n_passes),
            n_windows=int(n_windows),
            predict_s=round(record.device_s, 6),
            dispatch_s=round(record.dispatch_s, 6),
            windows_per_s=(round(record.items_per_s, 3)
                           if record.items_per_s is not None else None),
            retraces=record.retraces,
            backend_compiles=record.backend_compiles,
            fused=bool(fused),
            d2h_bytes=result_rows * int(n_windows) * 4,
        )
    return predictions, record.device_s


def _run_common(
    label: str,
    predictions: Optional[np.ndarray],
    y_true,
    patient_ids,
    config: UQConfig,
    deterministic_probs: Optional[np.ndarray],
    predict_seconds: float,
    detailed: bool,
    bootstrap_key: Optional[jax.Array],
    *,
    stats: Optional[np.ndarray] = None,
    n_passes: Optional[int] = None,
    run_log=None,
) -> UQRunResult:
    """Shared metric/CSV/classification pipeline.  Exactly one of
    ``predictions`` ((K, M) full probabilities) and ``stats`` ((4, M)
    fused sufficient statistics, with ``n_passes`` for provenance) is
    given; everything downstream of the decomposition is identical.
    With a ``run_log`` the finished run also emits its
    ``quality_metrics`` event (telemetry/quality.py): ECE/MCE/Brier,
    uncertainty-distribution summaries, and the per-patient rollup —
    all derived from the per-window vectors the decomposition already
    produced (a fused run never revives the (K, M) stack for this)."""
    if (predictions is None) == (stats is None):
        raise ValueError("pass exactly one of predictions / stats")
    if stats is not None:
        evaluation = evaluate_uq_from_stats(
            stats, y_true, n_passes, config, key=bootstrap_key
        )
    else:
        evaluation = evaluate_uq(predictions, y_true, config,
                                 key=bootstrap_key)
    mean_prob = evaluation.per_window["mean_pred"]
    classification = evaluate_classification(
        mean_prob, y_true,
        threshold=config.decision_threshold,
        description=f"{label} (mean of {evaluation.n_passes} passes)",
    )
    det = None
    if deterministic_probs is not None:
        # The reference's pre-MCD sanity probe: eval-mode accuracy should
        # sit near the deterministic ~88% (analyze_mcd_patient_level.py:203-211).
        det = evaluate_classification(
            deterministic_probs, y_true,
            threshold=config.decision_threshold,
            description=f"{label} (deterministic)",
        )
    frame = None
    if detailed:
        if stats is not None:
            frame = detailed_frame_from_stats(
                stats, y_true, patient_ids,
                threshold=config.decision_threshold,
            )
        else:
            frame = detailed_frame(
                predictions, y_true, patient_ids,
                threshold=config.decision_threshold,
            )
    result = UQRunResult(
        label=label,
        predictions=predictions,
        evaluation=evaluation,
        detailed=frame,
        classification=classification,
        deterministic_classification=det,
        predict_seconds=predict_seconds,
        y_true=np.asarray(y_true).reshape(-1),
        stats=stats,
        fused=stats is not None,
    )
    if run_log is not None:
        from apnea_uq_tpu.telemetry import log
        from apnea_uq_tpu.telemetry.quality import emit_quality_metrics

        try:
            emit_quality_metrics(run_log, result)
        except Exception as e:  # noqa: BLE001 - telemetry never kills an eval
            # E.g. a NaN that survived imputation lands in mean_pred:
            # it passes the [0, 1] range check (NaN comparisons are
            # False) and then detonates inside the binning.  The eval's
            # RESULTS are already computed — losing them to a quality
            # telemetry bug would invert the feature's purpose.
            log(f"quality_metrics emission skipped for {label}: "
                f"{type(e).__name__}: {e}")
    return result


def run_mcd_analysis(
    model,
    variables: dict,
    x,
    y_true,
    *,
    patient_ids=None,
    config: UQConfig = UQConfig(),
    label: str = "CNN_MCD",
    predict_key: Optional[jax.Array] = None,
    bootstrap_key: Optional[jax.Array] = None,
    seed: int = 0,
    mesh: Optional[jax.sharding.Mesh] = None,
    detailed: bool = True,
    sanity_check: bool = True,
    run_log=None,
    profiler=None,
) -> UQRunResult:
    """MC-Dropout UQ analysis of one test set (C13/C15).

    T=``config.mc_passes`` stochastic passes under ``config.mcd_mode``
    ('clean' frozen-BN MCD or 'parity' = the reference's training=True
    regime), then the full metric/bootstrap/CSV pipeline.

    ``predict_key`` (default ``prng.stochastic_key(seed)``, hardware-rbg on
    TPU) drives only the throughput-critical dropout masks; ``bootstrap_key``
    (default ``prng.bootstrap_key(seed)``) is always threefry so reported
    CIs stay stable across JAX versions/backends.
    """
    if len(x) == 0:
        raise ValueError("run_mcd_analysis needs at least one window; "
                         "got an empty window set")
    if predict_key is None:
        predict_key = prng.stochastic_key(seed)
    if bootstrap_key is None:
        bootstrap_key = prng.bootstrap_key(seed)
    # The reference ran the WHOLE test set as one batch, so its BN batch
    # statistics are whole-set.  Chunk statistics match that only when
    # every window appears equally often in one chunk — i.e. the chunk
    # the predictor ACTUALLY runs at (mcd_batch_size rounded up to the
    # mesh data-axis multiple; effective_batch_size) is an exact
    # multiple of the window count.  Smaller chunks see subsets; a larger
    # non-multiple chunk wrap-pads some windows more than others, skewing
    # the batch mean/variance.  Surface this so parity numbers are never
    # silently chunk-stat numbers.
    effective_bs = effective_batch_size(config.mcd_batch_size, mesh)
    if config.mcd_mode == "parity" and effective_bs % len(x) != 0:
        import warnings
        warnings.warn(
            f"mcd_mode='parity' with effective chunk {effective_bs}"
            f" (mcd_batch_size={config.mcd_batch_size}, rounded to the"
            f" mesh data-axis multiple) and {len(x)} windows: BatchNorm"
            " statistics are computed per (wrap-padded) chunk, not over"
            " the whole set as in the reference's model(x, training=True)."
            "  Set mcd_batch_size to a multiple of the window count that"
            " the mesh's data axis divides for exact parity.",
            stacklevel=2,
        )
    # Fused reduction (the default): the chunked prediction programs emit
    # the (4, M) per-window sufficient statistics instead of the (K, M)
    # probability matrix — the K axis never leaves the device, and the
    # decomposition below consumes the stats directly (no probability
    # re-upload).  The entropy base/eps baked into the on-device stats
    # are exactly what evaluate_uq would apply host-side.
    stat_spec = ("nats", config.entropy_eps) if config.fused_reduction else None

    def predict(record_memory_only=False):
        if config.mcd_streaming:
            # Host-streamed chunks for sets that exceed HBM; identical
            # results to the in-HBM path.  Streaming (small-memory) and
            # the mesh (many-chips) compose: each chunk shards over
            # (ensemble, data).
            return mc_dropout_predict_streaming(
                model, variables, x,
                n_passes=config.mc_passes,
                mode=config.mcd_mode,
                batch_size=config.mcd_batch_size,
                key=predict_key,
                mesh=mesh,
                run_log=run_log,
                record_memory_only=record_memory_only,
                stats=stat_spec,
                engine=config.mcd_engine,
            )
        return mc_dropout_predict(
            model, variables, x,
            n_passes=config.mc_passes,
            mode=config.mcd_mode,
            batch_size=config.mcd_batch_size,
            key=predict_key,
            mesh=mesh,
            run_log=run_log,
            record_memory_only=record_memory_only,
            stats=stat_spec,
            engine=config.mcd_engine,
        )

    if run_log is not None:
        # Price the compiled program (memory_profile event) BEFORE the
        # timed window: the one-time AOT compile must not inflate
        # predict_s/windows_per_s, which `telemetry compare` gates on.
        # The run-log memo then dedupes the in-window record attempt.
        predict(record_memory_only=True)
    # ``profiler`` (an unentered bracket-mode TraceSession from the
    # --profile CLI flag) captures ONLY the timed predict — entering it
    # here keeps the pre-pass AOT compile out of the trace artifact.
    with profiler if profiler is not None else contextlib.nullcontext():
        predictions, predict_seconds = _measured_predict(
            label, "mcd", predict, len(x), config.mc_passes, run_log,
            fused=stat_spec is not None,
        )
    det_probs = (
        _host_predictions(predict_proba_batched(
            model, variables, x, batch_size=config.inference_batch_size,
            mesh=mesh,
        ))
        if sanity_check
        else None
    )
    fetched = _host_predictions(predictions)
    return _run_common(
        label,
        None if stat_spec is not None else fetched,
        y_true, patient_ids, config,
        det_probs, predict_seconds, detailed, bootstrap_key,
        stats=fetched if stat_spec is not None else None,
        n_passes=config.mc_passes,
        run_log=run_log,
    )


def run_de_analysis(
    model,
    member_variables,
    x,
    y_true,
    *,
    patient_ids=None,
    config: UQConfig = UQConfig(),
    label: str = "CNN_DE",
    bootstrap_key: Optional[jax.Array] = None,
    seed: int = 0,
    mesh: Optional[jax.sharding.Mesh] = None,
    detailed: bool = True,
    run_log=None,
    profiler=None,
) -> UQRunResult:
    """Deep-Ensemble UQ analysis of one test set (C14/C16).

    Members are vmapped in one program (uq/predict.py) instead of the
    reference's N sequential full-set predicts (uq_techniques.py:29-30).
    ``member_variables`` takes any carrier ``as_stacked_members`` accepts —
    including a ``fit_ensemble`` result, whose EFFECTIVE member count
    (promoted padded slots included, ``EnsembleConfig.keep_padded_members``)
    then feeds the uncertainty decomposition: the formulas are unchanged,
    they simply see N_eff passes.  ``bootstrap_key`` defaults to
    ``prng.bootstrap_key(seed)`` — prediction itself is deterministic, so
    ``seed`` only moves the CI resamples.
    """
    if len(x) == 0:
        raise ValueError("run_de_analysis needs at least one window; "
                         "got an empty window set")
    if bootstrap_key is None:
        bootstrap_key = prng.bootstrap_key(seed)
    # Fused reduction (see run_mcd_analysis): members integrate on device
    # into (4, M) sufficient statistics — duplicate wrap-padded members
    # are excluded inside the jit on mesh paths.
    stat_spec = ("nats", config.entropy_eps) if config.fused_reduction else None
    n_members = _member_count(member_variables)

    def predict(record_memory_only=False):
        if config.de_streaming:
            return ensemble_predict_streaming(
                model, member_variables, x,
                batch_size=config.inference_batch_size,
                mesh=mesh,
                run_log=run_log,
                record_memory_only=record_memory_only,
                stats=stat_spec,
            )
        return ensemble_predict(
            model, member_variables, x,
            batch_size=config.inference_batch_size,
            mesh=mesh,
            run_log=run_log,
            record_memory_only=record_memory_only,
            stats=stat_spec,
        )

    if run_log is not None:
        # Price the compiled program outside the timed predict window
        # (see run_mcd_analysis).
        predict(record_memory_only=True)
    with profiler if profiler is not None else contextlib.nullcontext():
        predictions, predict_seconds = _measured_predict(
            label, "de", predict, len(x), n_members, run_log,
            fused=stat_spec is not None,
        )
    fetched = _host_predictions(predictions)
    return _run_common(
        label,
        None if stat_spec is not None else fetched,
        y_true, patient_ids, config,
        None, predict_seconds, detailed, bootstrap_key,
        stats=fetched if stat_spec is not None else None,
        n_passes=n_members,
        run_log=run_log,
    )


def run_synthetic_demo(
    *,
    n_models: int = 5,
    n_windows: int = 1000,
    positive_rate: float = 0.3,
    seed: int = 2025,
    config: UQConfig = UQConfig(n_bootstrap=50),
    label: str = "SYNTHETIC_DEMO",
) -> UQRunResult:
    """Self-contained smoke demo of the full UQ pipeline — no data, no
    trained model (reference C12 ``__main__``: uq_techniques.py:395-446
    fabricates a 5x1000 prediction matrix and runs evaluate_uq_methods
    on it).

    Windows get a class-dependent latent logit plus per-window difficulty
    noise; each "model" observes it through its own disagreement noise, so
    the stack has genuine aleatoric (overlapping classes) and epistemic
    (inter-model) components and every downstream quantity — decomposition,
    bootstrap CIs, classification suite, detailed frame, plots — is
    exercised with plausible values.  Synthetic patient ids let the
    patient-level analyses consume the result too.
    """
    if not 0.0 < positive_rate < 1.0:
        raise ValueError(f"positive_rate must be in (0, 1), got {positive_rate}")
    rng = np.random.default_rng(seed)
    y = (rng.uniform(size=n_windows) < positive_rate).astype(np.float32)
    # Latent per-window logit: separated class means, overlapping tails.
    latent = np.where(y == 1, 1.4, -1.4) + rng.normal(0.0, 0.9, n_windows)
    # Per-model observation: a small systematic offset per model plus
    # per-(model, window) noise -> non-degenerate mutual information.
    model_bias = rng.normal(0.0, 0.25, (n_models, 1))
    noise = rng.normal(0.0, 0.45, (n_models, n_windows))
    predictions = 1.0 / (1.0 + np.exp(-(latent[None, :] + model_bias + noise)))
    patient_ids = np.asarray(
        [f"DEMO{int(i):04d}" for i in rng.integers(0, 20, n_windows)]
    )
    return _run_common(
        label, predictions.astype(np.float32), y, patient_ids, config,
        None, 0.0, True, prng.bootstrap_key(seed),
    )


def save_run_plots(result: UQRunResult, out_dir: str) -> list:
    """The reference's per-evaluation plot set (uq_techniques.py:369-387):
    per-true-class distribution histograms of the three uncertainty
    metrics plus the class-mean-variance bar chart, one PNG each, named
    by run label."""
    import os

    from apnea_uq_tpu.analysis import plots

    ev = result.evaluation
    pw = ev.per_window
    y = result.y_true
    if y is None:
        raise ValueError("run result carries no labels; cannot plot per-class")
    pre = os.path.join(out_dir, result.label)
    return [
        plots.plot_metric_distribution(
            pw["pred_variance"], y, "predictive variance",
            f"{pre}_variance_distribution.png"),
        plots.plot_metric_distribution(
            pw["total_pred_entropy"], y, "total predictive entropy",
            f"{pre}_total_entropy_distribution.png"),
        plots.plot_metric_distribution(
            pw["mutual_info"], y, "mutual information",
            f"{pre}_mutual_info_distribution.png"),
        plots.plot_class_uncertainties(
            {"class 0": ev.aggregates["mean_variance_class_0"],
             "class 1": ev.aggregates["mean_variance_class_1"]},
            f"{pre}_class_variance.png"),
    ]


def run_metrics_document(result: UQRunResult) -> Dict:
    """The run's scalar results as one JSON-able document: aggregates,
    bootstrap CIs, the classification suite(s), and run provenance.  The
    reference merely *returned* its merged dict (uq_techniques.py:343-365)
    and lost it once the terminal scrolled; persisting it is the
    observability bar the registry sets for every other stage."""
    ev = result.evaluation
    doc = {
        "label": result.label,
        "n_passes": ev.n_passes,
        "n_windows": ev.n_windows,
        "predict_seconds": result.predict_seconds,
        "fused": bool(result.fused),
        "aggregates": dict(ev.aggregates),
        "confidence_intervals": dict(ev.confidence_intervals),
        "classification": dict(result.classification),
    }
    if result.deterministic_classification is not None:
        doc["deterministic_classification"] = dict(
            result.deterministic_classification
        )
    return doc


def save_run(registry, result: UQRunResult, *, config=None) -> Dict[str, str]:
    """Persist a run's artifacts under canonical registry keys.

    raw predictions -> ``raw_predictions:<label>`` (the reference's
    mc_raw_pred*.npy dump, analyze_mcd_patient_level.py:100; full-probs
    runs only — a fused run never materializes the (K, M) stack, so it
    saves its (4, M) sufficient statistics as ``uq_stats:<label>``
    instead), the detailed frame -> ``detailed_windows:<label>`` (the
    L5->L6 CSV), and the scalar results -> ``metrics:<label>`` (JSON:
    aggregates, CIs, classification suite).
    """
    from apnea_uq_tpu.data import registry as reg

    paths = {}
    if result.predictions is not None:
        # apnea-lint: disable=artifact-never-consumed -- end product: the raw (K, M) stack is read by analysts/offline tooling (the reference's mc_raw_pred*.npy), not by a pipeline stage
        paths["raw_predictions"] = registry.save_arrays(
            f"{reg.RAW_PREDICTIONS}:{result.label}",
            {"predictions": result.predictions},
            config=config,
        )
    if result.stats is not None:
        # apnea-lint: disable=artifact-never-consumed -- end product: the (4, M) sufficient statistics are the fused run's audit artifact, consumed by tests/analysts rather than a stage
        paths["uq_stats"] = registry.save_arrays(
            f"{reg.UQ_STATS}:{result.label}",
            {"stats": result.stats},
            config=config,
        )
    if result.detailed is not None:
        paths["detailed_windows"] = registry.save_table(
            f"{reg.DETAILED_WINDOWS}:{result.label}", result.detailed, config=config
        )
    paths["metrics"] = registry.save_json(
        f"{reg.METRICS}:{result.label}", run_metrics_document(result), config=config
    )
    return paths
