from apnea_uq_tpu.parallel.mesh import make_mesh, member_sharding, data_sharding
from apnea_uq_tpu.parallel.ensemble import EnsembleFitResult, fit_ensemble

__all__ = [
    "make_mesh",
    "member_sharding",
    "data_sharding",
    "fit_ensemble",
    "EnsembleFitResult",
]
