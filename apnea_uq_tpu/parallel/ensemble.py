"""Concurrent Deep-Ensemble training over a device mesh.

The reference trains N members **sequentially** — a Python loop building a
fresh Keras model per seed, fitting, saving, and freeing it
(train_deep_ensemble_cnns.py:125-177; SURVEY §3.2).  Here all members
train **simultaneously**: member-stacked parameters are ``vmap``-ed through
the train step and sharded over the mesh's ``ensemble`` axis, so N members
cost one member's wall-clock per device group.  Members differ only in
their RNG streams (init + shuffle + dropout), exactly the reference's
per-member-seed scheme (``2025+i``, train_deep_ensemble_cnns.py:126).

When the mesh has a ``data`` axis > 1, each member's batches additionally
shard over it (``spmd_axis_name`` threads the member axis through the
``with_sharding_constraint`` inside the epoch), and XLA inserts the
per-member gradient all-reduce over the data-axis device groups — real
data parallelism riding ICI, with semantics identical to the
single-device run (same global batches, sliced compute).

Per-member early stopping under lockstep execution (SURVEY §7 "hard
parts"): devices can't exit a vmapped computation at different epochs, so
every member keeps computing until the *last* active member stops, but a
member whose patience is exhausted has its state frozen via masked
updates, and its best-epoch weights are tracked per member on device —
semantically identical to the reference's independent EarlyStopping(
restore_best_weights=True) per member.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apnea_uq_tpu.compilecache import store as program_store
from apnea_uq_tpu.config import EnsembleConfig
from apnea_uq_tpu.models.cnn1d import (
    AlarconCNN1D, apply_model, init_variables, predict_proba,
)
from apnea_uq_tpu.ops import streaming_auc
from apnea_uq_tpu.ops.losses import masked_bce_with_logits
from apnea_uq_tpu.parallel import mesh as mesh_lib
from apnea_uq_tpu.telemetry import memory as telemetry_memory
from apnea_uq_tpu.telemetry import trace as telemetry_trace
from apnea_uq_tpu.telemetry.steps import StepMetrics
from apnea_uq_tpu.training.state import TrainState, make_optimizer
from apnea_uq_tpu.training.trainer import _epoch_jit, _eval_loss_jit, make_train_step
from apnea_uq_tpu.utils import prng
# Member-axis arrays are sharded over the global 'ensemble' axis, whose
# shards span other processes' devices in a multi-host run; host fetches
# go through the shared multi-process-safe helper.
from apnea_uq_tpu.utils.multihost import host_values as _host_values


@dataclasses.dataclass
class EnsembleFitResult:
    """Stacked member states + per-member training history.

    ``num_members`` is the RETURNED member count: the requested
    ``EnsembleConfig.num_members``, or the padded lockstep-slot count when
    ``keep_padded_members`` promoted the padding (``num_requested`` keeps
    the configured N; ``member_ids`` carries each returned member's global
    ensemble index — the RNG fold source, and the seed offset checkpoint
    stores key members by).  ``lockstep_epochs`` counts the jitted epoch
    dispatches the run executed — every member slot, padded or not, rode
    the same ``lockstep_epochs`` programs, which is what makes promoted
    members free per epoch: each dispatched epoch costs the same with
    promotion on or off.  The counter itself is identical whenever the
    epoch count is fixed (early stopping disabled or never firing); with
    early stopping active, a promoted slot that keeps improving extends
    the lockstep exactly as a requested member would — the run is an
    honest N=``num_members`` run, so those extra epochs train a real
    member rather than being discarded padding.
    """

    state: TrainState                      # leaves have leading member axis
    history: Dict[str, np.ndarray]         # (epochs_run, N) loss / val_loss
    best_epoch: np.ndarray                 # (N,)
    epochs_run: np.ndarray                 # (N,) epochs each member trained
    num_members: int
    num_requested: int = -1                # config.num_members (-1: legacy)
    member_ids: Optional[np.ndarray] = None  # (N,) global member indices
    lockstep_epochs: int = 0               # jitted epoch dispatches executed

    @property
    def promoted_members(self) -> int:
        """Padded slots returned as real members (0 unless promotion on)."""
        if self.num_requested < 0:
            return 0
        return self.num_members - self.num_requested

    def wasted_member_epochs(self) -> int:
        """Lockstep early-stop waste: epoch slots computed for members that
        had already stopped while others kept the lockstep program running
        (the cost VERDICT.md asks the bench to quantify, not fix)."""
        return int(self.num_members * self.lockstep_epochs
                   - int(np.sum(self.epochs_run)))

    def member_variables(self, i: int) -> dict:
        return {
            "params": jax.tree.map(lambda a: a[i], self.state.params),
            "batch_stats": jax.tree.map(lambda a: a[i], self.state.batch_stats),
        }

    def stacked_variables(self) -> dict:
        return {"params": self.state.params, "batch_stats": self.state.batch_stats}


def init_ensemble_state(
    model: AlarconCNN1D,
    num_members: int,
    root_key: jax.Array,
    *,
    learning_rate: float = 1e-3,
    member_indices=None,
) -> TrainState:
    """Member-stacked TrainState; member i's init stream derives from
    fold_in(root, member_indices[i]) — the vmapped analogue of per-member
    seeds.  ``member_indices`` defaults to 0..num_members-1; a resumed run
    passes the *global* indices of the members it is re-training so their
    streams match what a fresh full run would have produced."""
    tx = make_optimizer(learning_rate)
    if member_indices is None:
        member_indices = jnp.arange(num_members)
    else:
        member_indices = jnp.asarray(member_indices, jnp.int32)

    def one(member_idx):
        k = prng.stream(prng.member_key(root_key, member_idx), prng.STREAM_INIT)
        variables = init_variables(model, k)
        return TrainState(
            params=variables["params"],
            batch_stats=variables["batch_stats"],
            opt_state=tx.init(variables["params"]),
            step=jnp.zeros((), jnp.int32),
        )

    return jax.vmap(one)(member_indices)




def _tree_where(cond_vec, new_tree, old_tree):
    """Per-member select: cond_vec (N,) broadcast over member-axis leaves."""

    def sel(new, old):
        c = cond_vec.reshape((-1,) + (1,) * (new.ndim - 1))
        return jnp.where(c, new, old)

    return jax.tree.map(sel, new_tree, old_tree)


@partial(
    jax.jit,
    static_argnames=(
        "model", "tx", "batch_size", "patience", "data_sharding",
        "track_metrics",
    ),
    donate_argnames=("state", "book"),
)
def _ensemble_epoch(
    model, tx, state, book, x, y, x_val, y_val, epoch_key, member_ids,
    batch_size, patience, data_sharding=None, track_metrics=False
):
    """One lockstep epoch for all members + early-stop bookkeeping.

    ``book`` = (best_val, patience_left, active, best_params, best_stats,
    best_epoch, epochs_run); all leading-axis-N device arrays.
    ``member_ids`` are the members' global indices — the fold source for
    their shuffle/dropout streams, so a partial (resumed) run trains
    bit-identical members to a full run.

    ``data_sharding`` (spec P('data')) activates the DP sub-axis: inside
    the member vmap each batch is constrained to shard over ``data``
    (``spmd_axis_name`` prepends the member axis, so the stacked batch is
    laid out P('ensemble', 'data')) and XLA inserts the per-member
    gradient all-reduce over the ``data`` axis groups.

    ``track_metrics`` appends per-member (train_acc, train_auc, val_acc,
    val_auc) vectors to the returns — the reference ensemble trainer's
    Keras compile metrics, per member.  Like the existing val_loss
    history, a stopped member's entries describe the lockstep-epoch state
    that bookkeeping computes and then discards (the member itself stays
    frozen); read its history only up to ``epochs_run``.
    """
    member_keys = jax.vmap(lambda i: jax.random.fold_in(epoch_key, i))(member_ids)

    def member_epoch(member_state, key):
        # Labels the vmapped member program in profiler captures: every
        # op inside carries the "ensemble_member_epoch/" name prefix.
        with jax.named_scope("ensemble_member_epoch"):
            return _epoch_jit.__wrapped__(
                model, tx, member_state, x, y, key, batch_size, True,
                data_sharding, track_metrics
            )

    epoch_out = jax.vmap(
        member_epoch, spmd_axis_name=mesh_lib.AXIS_ENSEMBLE
    )(state, member_keys)
    if track_metrics:
        trained, train_loss, train_acc, train_auc = epoch_out
    else:
        trained, train_loss = epoch_out

    def member_val(member_state):
        with jax.named_scope("ensemble_member_val"):
            variables = {"params": member_state.params,
                         "batch_stats": member_state.batch_stats}
            return _eval_loss_jit.__wrapped__(
                model, variables, x_val, y_val, batch_size, data_sharding,
                track_metrics
            )

    val_out = jax.vmap(member_val, spmd_axis_name=mesh_lib.AXIS_ENSEMBLE)(trained)
    val_loss = val_out[0] if track_metrics else val_out
    booked = _epoch_bookkeeping.__wrapped__(
        state, trained, book, train_loss, val_loss, patience
    )
    if track_metrics:
        return booked + ((train_acc, train_auc, val_out[1], val_out[2]),)
    return booked


@partial(jax.jit, static_argnames=("patience",),
         donate_argnames=("state", "trained", "book"))
def _epoch_bookkeeping(state, trained, book, train_loss, val_loss, patience):
    """Epoch-end early-stop bookkeeping, shared by the in-HBM scan epoch
    and the streamed epoch: freeze stopped members, track per-member best
    weights/epoch, decrement patience."""
    with jax.named_scope("ensemble_bookkeeping"):
        return _epoch_bookkeeping_impl(state, trained, book, train_loss,
                                       val_loss, patience)


def _epoch_bookkeeping_impl(state, trained, book, train_loss, val_loss,
                            patience):
    best_val, patience_left, active, best_params, best_stats, best_epoch, epochs_run = book

    # Freeze members that already stopped.
    state = TrainState(
        params=_tree_where(active, trained.params, state.params),
        batch_stats=_tree_where(active, trained.batch_stats, state.batch_stats),
        opt_state=_tree_where(active, trained.opt_state, state.opt_state),
        step=jnp.where(active, trained.step, state.step),
    )
    epochs_run = epochs_run + active.astype(jnp.int32)

    improved = (val_loss < best_val) & active
    best_params = _tree_where(improved, state.params, best_params)
    best_stats = _tree_where(improved, state.batch_stats, best_stats)
    best_val = jnp.where(improved, val_loss, best_val)
    best_epoch = jnp.where(improved, epochs_run - 1, best_epoch)
    patience_left = jnp.where(
        improved, patience, jnp.where(active, patience_left - 1, patience_left)
    )
    active = active & (patience_left > 0)

    book = (best_val, patience_left, active, best_params, best_stats, best_epoch, epochs_run)
    return state, book, train_loss, val_loss, active


def _member_metric_state(n_members: int):
    """Per-member streaming-metric carry: leading member axis on both
    leaves of ops/streaming_auc.empty_metric_state()."""
    return jax.tree.map(
        lambda a: jnp.zeros((n_members,) + a.shape, a.dtype),
        streaming_auc.empty_metric_state(),
    )


@partial(
    jax.jit,
    static_argnames=("model", "tx", "data_sharding", "track_metrics"),
    donate_argnames=("state",),
)
def _stream_ensemble_step_jit(model, tx, state, xb, yb, mask, dropout_keys,
                              step_idx, data_sharding=None,
                              metric_state=None, track_metrics=False):
    """One streamed optimizer step for ALL members: per-member batches
    (N, bs, ...) vmapped through the train step.  Same math as one scan
    iteration of the in-HBM ensemble epoch.  The per-step dropout keys
    fold inside the jit (``step_idx`` is a device scalar), so the host
    loop issues exactly one dispatch per step.  ``state`` is donated —
    the epoch works on a copy, keeping HBM at one stacked state."""
    train_step = make_train_step(model, tx, with_probs=track_metrics)

    def constrained(xbi, ybi):
        mb = mask
        if data_sharding is not None:
            xbi = jax.lax.with_sharding_constraint(xbi, data_sharding)
            ybi = jax.lax.with_sharding_constraint(ybi, data_sharding)
            mb = jax.lax.with_sharding_constraint(mb, data_sharding)
        return xbi, ybi, mb

    if track_metrics:
        def member_step(member_state, xbi, ybi, dropout_key, mstate_i):
            xbi, ybi, mb = constrained(xbi, ybi)
            rng = jax.random.fold_in(dropout_key, step_idx)
            ms, loss, probs = train_step(member_state, xbi, ybi, mb, rng)
            return ms, loss, streaming_auc.metric_update(mstate_i, probs, ybi, mb)

        state, loss, metric_state = jax.vmap(
            member_step, spmd_axis_name=mesh_lib.AXIS_ENSEMBLE
        )(state, xb, yb, dropout_keys, metric_state)
        return state, loss * jnp.sum(mask), metric_state

    def member_step(member_state, xbi, ybi, dropout_key):
        xbi, ybi, mb = constrained(xbi, ybi)
        rng = jax.random.fold_in(dropout_key, step_idx)
        return train_step(member_state, xbi, ybi, mb, rng)

    state, loss = jax.vmap(
        member_step, spmd_axis_name=mesh_lib.AXIS_ENSEMBLE
    )(state, xb, yb, dropout_keys)
    return state, loss * jnp.sum(mask)


@partial(jax.jit, static_argnames=("model", "data_sharding", "track_metrics"))
def _stream_ensemble_eval_jit(model, state, xb, yb, mask, data_sharding=None,
                              metric_state=None, track_metrics=False):
    def eval_one(member_state):
        xbi, ybi, mb = xb, yb, mask
        if data_sharding is not None:
            xbi = jax.lax.with_sharding_constraint(xbi, data_sharding)
            ybi = jax.lax.with_sharding_constraint(ybi, data_sharding)
            mb = jax.lax.with_sharding_constraint(mb, data_sharding)
        variables = {"params": member_state.params,
                     "batch_stats": member_state.batch_stats}
        logits, _ = apply_model(model, variables, xbi, mode="eval")
        return masked_bce_with_logits(logits, ybi, mb) * jnp.sum(mb), logits, ybi, mb

    if track_metrics:
        def member_eval(member_state, mstate_i):
            weighted, logits, ybi, mb = eval_one(member_state)
            mstate_i = streaming_auc.metric_update(
                mstate_i, predict_proba(logits), ybi, mb
            )
            return weighted, mstate_i

        return jax.vmap(
            member_eval, spmd_axis_name=mesh_lib.AXIS_ENSEMBLE
        )(state, metric_state)

    return jax.vmap(
        lambda ms: eval_one(ms)[0], spmd_axis_name=mesh_lib.AXIS_ENSEMBLE
    )(state)


def _stream_ensemble_epoch(
    model, tx, state, book, x, y, x_val, y_val, epoch_key, member_ids,
    batch_size, patience, mesh, data_sharding, prefetch,
    track_metrics=False,
):
    """One lockstep ensemble epoch fed batch-by-batch from HOST arrays
    (x/y/x_val/y_val stay NumPy; data/feed.py pumps per-member batch
    stacks, pre-sharded onto the mesh when the shapes divide).  Same
    permutations, masks, and RNG streams as the in-HBM _ensemble_epoch,
    so both paths train the same members."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apnea_uq_tpu.data.feed import prefetch_to_device
    from apnea_uq_tpu.training.trainer import _pad_perm

    member_keys = jax.vmap(
        lambda i: jax.random.fold_in(epoch_key, i)
    )(member_ids)
    n_members = int(member_keys.shape[0])
    n = x.shape[0]
    # Per-member epoch key split + all permutations, identical to
    # _epoch_jit's, in two vectorized dispatches.
    split_keys = jax.vmap(jax.random.split)(member_keys)   # (N, 2)
    dropout_keys = split_keys[:, 1]
    # apnea-lint: disable=host-sync-in-timed-region -- per-member permutations must land on host to slice the host-resident dataset; computed once before the first step dispatches, so nothing in flight is serialized
    idx = np.asarray(jax.vmap(
        lambda k: _pad_perm(k, n, batch_size, True)[0]
    )(split_keys[:, 0]))                                   # (N, steps, bs)
    steps, bs = idx.shape[1], idx.shape[2]
    # The pad mask is key-independent: real positions < n per flat slot.
    mask = (np.arange(steps * bs) < n).astype(np.float32).reshape(steps, bs)

    # Place streamed stacks directly onto the mesh when the member/batch
    # axes divide it (member axis is padded to the ensemble axis already);
    # otherwise land them replicated and let the step constraint shard.
    stack_sharding = None
    mask_sharding = None
    if data_sharding is not None and bs % mesh.shape[mesh_lib.AXIS_DATA] == 0:
        stack_sharding = NamedSharding(
            mesh, P(mesh_lib.AXIS_ENSEMBLE, mesh_lib.AXIS_DATA)
        )
        mask_sharding = data_sharding

    def batches():
        for s in range(steps):
            yield x[idx[:, s]], y[idx[:, s]]               # (N, bs, ...) stacks

    masks_dev = [
        jax.device_put(mask[s], mask_sharding) if mask_sharding is not None
        else jnp.asarray(mask[s])
        for s in range(steps)
    ]
    # The epoch trains a COPY so per-step donation never invalidates the
    # pre-epoch state the bookkeeping needs (one copy per epoch instead of
    # one per step).
    trained = jax.tree.map(jnp.copy, state)
    total = jnp.zeros((n_members,))
    mstate = _member_metric_state(n_members) if track_metrics else None
    for s, (xb, yb) in enumerate(prefetch_to_device(
        batches(), size=prefetch, sharding=stack_sharding
    )):
        if track_metrics:
            trained, weighted, mstate = _stream_ensemble_step_jit(
                model, tx, trained, xb, yb, masks_dev[s], dropout_keys,
                jnp.asarray(s, jnp.int32), data_sharding,
                mstate, track_metrics=True,
            )
        else:
            trained, weighted = _stream_ensemble_step_jit(
                model, tx, trained, xb, yb, masks_dev[s], dropout_keys,
                jnp.asarray(s, jnp.int32), data_sharding,
            )
        total = total + weighted
    train_loss = total / n

    n_val = x_val.shape[0]
    val_steps = -(-n_val // batch_size)
    val_total = jnp.zeros((n_members,))
    val_mstate = _member_metric_state(n_members) if track_metrics else None
    for s in range(val_steps):
        lo, hi = s * batch_size, min((s + 1) * batch_size, n_val)
        # Materialize ONE validation batch off a (possibly store-backed
        # lazy) slice; free view for plain ndarrays.
        # apnea-lint: disable=host-sync-in-timed-region -- x_val/y_val are HOST-resident (ndarray or memmap-backed store slice), not device arrays; the O(batch) gather serializes nothing in flight
        xb, yb = np.asarray(x_val[lo:hi]), np.asarray(y_val[lo:hi])
        pad = batch_size - (hi - lo)
        if pad:
            xb = np.concatenate([xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
            yb = np.concatenate([yb, np.zeros((pad,), yb.dtype)])
        mb = (np.arange(batch_size) < hi - lo).astype(np.float32)
        if track_metrics:
            weighted, val_mstate = _stream_ensemble_eval_jit(
                model, trained, jnp.asarray(xb), jnp.asarray(yb),
                jnp.asarray(mb), data_sharding,
                val_mstate, track_metrics=True,
            )
        else:
            weighted = _stream_ensemble_eval_jit(
                model, trained, jnp.asarray(xb), jnp.asarray(yb),
                jnp.asarray(mb), data_sharding,
            )
        val_total = val_total + weighted
    val_loss = val_total / n_val

    booked = _epoch_bookkeeping(state, trained, book, train_loss, val_loss,
                                patience)
    if track_metrics:
        t_acc, t_auc = jax.vmap(streaming_auc.metric_results)(mstate)
        v_acc, v_auc = jax.vmap(streaming_auc.metric_results)(val_mstate)
        return booked + ((t_acc, t_auc, v_acc, v_auc),)
    return booked


@dataclasses.dataclass
class _EnsembleRun:
    """Device-resident inputs of one ensemble-epoch program."""

    mesh: jax.sharding.Mesh
    tx: optax.GradientTransformation
    state: TrainState
    book: tuple
    x: jax.Array
    y: jax.Array
    x_val: jax.Array
    y_val: jax.Array
    member_ids: jax.Array
    data_sharding: Optional[jax.sharding.NamedSharding]
    shuffle_root: jax.Array
    n_members: int
    n_padded: int
    n_effective: int  # members actually returned: n_padded when promoted


def _setup_ensemble_run(
    model, x_train, y_train, config, mesh, root_key, member_indices,
    streaming: bool = False,
) -> _EnsembleRun:
    n_members = config.num_members
    if member_indices is None:
        member_indices = list(range(n_members))
    if len(member_indices) != n_members:
        raise ValueError(
            f"member_indices has {len(member_indices)} entries for "
            f"{n_members} members"
        )
    if mesh is None:
        mesh = mesh_lib.make_mesh(n_members)
    if root_key is None:
        root_key = prng.seed_key(config.seed_base)
    tx = make_optimizer(config.learning_rate)

    if streaming:
        # The dataset stays in HOST memory; the streamed epoch pumps
        # per-member batch stacks through the prefetch feed.
        # as_host_source keeps a memmap-backed store array lazy
        # (data/store.py): each step gathers only its (members x batch)
        # row stack, so host RSS stays bounded over an out-of-core set.
        from apnea_uq_tpu.data.store import as_host_source

        x = as_host_source(x_train)
        y = np.asarray(y_train, np.float32)
    else:
        x = jnp.asarray(x_train, jnp.float32)
        y = jnp.asarray(y_train, jnp.float32)
    n = x.shape[0]
    # Keras split arithmetic (see trainer.fit): val gets the tail remainder.
    n_val = n - int(n * (1.0 - config.validation_split))
    if n_val <= 0:
        raise ValueError("ensemble training requires validation_split > 0 "
                         "(early stopping is per-member val-loss based)")
    x, x_val = x[: n - n_val], x[n - n_val:]
    y, y_val = y[: n - n_val], y[n - n_val:]

    # Pad member count to a multiple of the mesh ensemble axis so the
    # member axis shards evenly; padded members train but are discarded.
    e_axis = mesh.shape[mesh_lib.AXIS_ENSEMBLE]
    n_padded = -(-n_members // e_axis) * e_axis
    pad_base = max(member_indices) + 1
    padded_indices = list(member_indices) + [
        pad_base + j for j in range(n_padded - n_members)
    ]
    member_ids = jnp.asarray(padded_indices, jnp.int32)

    state = init_ensemble_state(model, n_padded, root_key,
                                learning_rate=config.learning_rate,
                                member_indices=member_ids)
    state = mesh_lib.shard_member_tree(state, mesh)
    # The dataset is replicated (every device can gather any batch row
    # locally); per-STEP batches are sharded over the 'data' axis inside
    # _ensemble_epoch, which is where the DP gradient all-reduce comes from.
    # In streaming mode the dataset never leaves the host.
    if not streaming:
        data_repl = mesh_lib.replicated(mesh)
        x, y, x_val, y_val = (
            jax.device_put(a, data_repl) for a in (x, y, x_val, y_val)
        )
    data_sharding = (
        mesh_lib.data_sharding(mesh)
        if mesh.shape[mesh_lib.AXIS_DATA] > 1 else None
    )

    book = (
        jnp.full((n_padded,), jnp.inf),                      # best_val
        jnp.full((n_padded,), config.early_stopping_patience, jnp.int32),
        jnp.ones((n_padded,), bool),                         # active
        # copies: state and book are both donated to the epoch step, so
        # they must not alias the same buffers
        jax.tree.map(jnp.copy, state.params),                # best_params
        jax.tree.map(jnp.copy, state.batch_stats),           # best_stats
        jnp.full((n_padded,), -1, jnp.int32),                # best_epoch
        jnp.zeros((n_padded,), jnp.int32),                   # epochs_run
    )
    book = tuple(mesh_lib.shard_member_tree(b, mesh) for b in book)
    return _EnsembleRun(
        mesh=mesh, tx=tx, state=state, book=book, x=x, y=y,
        x_val=x_val, y_val=y_val, member_ids=member_ids,
        data_sharding=data_sharding,
        shuffle_root=prng.stream(root_key, prng.STREAM_SHUFFLE),
        n_members=n_members, n_padded=n_padded,
        n_effective=(n_padded if config.keep_padded_members else n_members),
    )


def compile_ensemble_epoch(
    model: AlarconCNN1D,
    x_train,
    y_train,
    config: EnsembleConfig = EnsembleConfig(),
    *,
    mesh: Optional[jax.sharding.Mesh] = None,
):
    """AOT-compile one ensemble epoch, exactly as ``fit_ensemble`` would
    execute it over ``mesh``.  Returns ``(compiled, args)``:
    ``compiled.as_text()`` is the partitioned HLO (for asserting the DP
    collectives exist) and ``compiled(*args)`` executes the step — one
    compile serves both the diagnostic and a real training step."""
    run = _setup_ensemble_run(model, x_train, y_train, config, mesh, None, None)
    epoch_key = jax.random.fold_in(run.shuffle_root, 0)
    args = (run.state, run.book, run.x, run.y, run.x_val, run.y_val,
            epoch_key, run.member_ids)
    with run.mesh:
        lowered = _ensemble_epoch.lower(
            model, run.tx, *args,
            config.batch_size, config.early_stopping_patience,
            run.data_sharding, config.track_metrics,
        )
        return lowered.compile(), args


def ensemble_epoch_compiled_text(
    model: AlarconCNN1D,
    x_train,
    y_train,
    config: EnsembleConfig = EnsembleConfig(),
    *,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> str:
    """Compiled-HLO text of one ensemble epoch (see compile_ensemble_epoch)."""
    compiled, _ = compile_ensemble_epoch(model, x_train, y_train, config, mesh=mesh)
    return compiled.as_text()


def count_data_allreduces(hlo_text: str, mesh: jax.sharding.Mesh) -> int:
    """Number of all-reduce ops over ``mesh``'s replica groups in compiled
    HLO text — the one predicate tests and the multichip dryrun share for
    'did the SPMD partitioner insert the DP gradient reduction'."""
    e = mesh.shape[mesh_lib.AXIS_ENSEMBLE]
    d = mesh.shape[mesh_lib.AXIS_DATA]
    groups = f"replica_groups=[{e},{d}]"
    return sum(
        1 for line in hlo_text.splitlines()
        if (" all-reduce(" in line or " all-reduce-start(" in line)
        and groups in line
    )


def fit_ensemble(
    model: AlarconCNN1D,
    x_train,
    y_train,
    config: EnsembleConfig = EnsembleConfig(),
    *,
    mesh: Optional[jax.sharding.Mesh] = None,
    root_key: Optional[jax.Array] = None,
    member_indices=None,
    streaming: Optional[bool] = None,
    prefetch: int = 2,
    log_fn=None,
    run_log=None,
    profiler=None,
    compile_only: bool = False,
) -> EnsembleFitResult:
    """Train all N members concurrently over the mesh's ensemble axis,
    each member's batches data-parallel over the mesh's ``data`` axis.

    ``member_indices`` (default 0..N-1) are the members' global indices in
    the full ensemble; pass the missing subset when resuming so RNG
    streams match the never-interrupted run (the reference's skip-if-
    checkpoint-exists resume, train_deep_ensemble_cnns.py:130-132, gets
    the same property from its seed-per-member scheme).

    ``streaming`` (default: ``config.streaming``) keeps the dataset in
    host memory and feeds per-member batch stacks through the
    double-buffered prefetch pipeline (data/feed.py) — for training sets
    that exceed the HBM budget.  Same permutations, masks, and RNG streams
    as the in-HBM path, so both train the same members.

    ``config.track_metrics`` adds per-member on-device streaming metrics
    (ops/streaming_auc.py) to the history: (epochs, N) arrays
    ``accuracy``/``auc``/``val_accuracy``/``val_auc`` — the reference
    ensemble trainer's Keras compile metrics.

    Cost note (vmap packing): members train in lockstep over the mesh's
    ensemble axis, so the member count is padded up to a multiple of that
    axis and the padded slots train real epochs — e.g. N=10 on an 8-wide
    axis runs 16 member-slots, a 60% compute overhead over the requested
    members.  By default the padded slots' weights are discarded and the
    overhead is logged at startup via ``log_fn``; to avoid paying it for
    nothing, either pick N a multiple of (or dividing) the ensemble axis /
    shrink the axis via ``MeshConfig.ensemble_axis``, or — since ensemble
    quality improves monotonically with member count — set
    ``config.keep_padded_members`` to promote the slots to real returned
    members: their RNG streams already derive from their global member
    indices, so the promoted run is bit-identical to an explicit
    N=``n_padded`` run with the same root key, and cost-per-member drops
    by the padding fraction at zero extra device compute per epoch.  One
    consequence of that bit-identity: early stopping waits on ALL
    returned members, so a promoted slot that keeps improving can extend
    the lockstep beyond where the discarding run would have stopped —
    epochs that train a real member, not discarded padding.

    ``run_log`` (a :class:`apnea_uq_tpu.telemetry.RunLog`) records one
    ``step`` + one ``ensemble_epoch`` event per lockstep epoch (dispatch
    vs device time, member-windows/sec, retrace/compile deltas, active
    members, per-member val losses) and one final ``ensemble_fit``
    summary event — the canonical source of the effective-member /
    promoted-slot / wasted-member-epoch accounting bench.py reports.
    On the in-HBM path it also records the lockstep epoch program's
    compiled memory analysis once (``memory_profile`` event,
    telemetry/memory.py) — the HBM price of the whole vmapped ensemble,
    known before the first epoch dispatches.

    ``profiler`` (a :class:`apnea_uq_tpu.telemetry.profiler.TraceSession`)
    is stepped once per lockstep epoch, bounding a ``--profile`` capture
    to the session's warmup/step budget.

    ``compile_only=True`` (the ``apnea-uq warm-cache`` stage) runs the
    full setup, acquires/prices the exact lockstep epoch program via the
    compile-cost subsystem — seeding the persistent XLA cache for the
    next process — and returns None without training an epoch.
    """
    if streaming is None:
        streaming = config.streaming
    run = _setup_ensemble_run(
        model, x_train, y_train, config, mesh, root_key, member_indices,
        streaming=streaming,
    )
    if log_fn and run.n_padded > run.n_members:
        extra = run.n_padded - run.n_members
        if config.keep_padded_members:
            log_fn(
                f"ensemble axis {run.mesh.shape[mesh_lib.AXIS_ENSEMBLE]} pads "
                f"{run.n_members} members to {run.n_padded} lockstep slots: "
                f"{extra} promoted slot(s) returned as real members "
                f"(cost per member down "
                f"{100.0 * extra / run.n_padded:.0f}% at the same device "
                f"compute per epoch; early stopping now waits on all "
                f"{run.n_padded} members)"
            )
        else:
            log_fn(
                f"ensemble axis {run.mesh.shape[mesh_lib.AXIS_ENSEMBLE]} pads "
                f"{run.n_members} members to {run.n_padded} lockstep slots: "
                f"{extra} discarded slot(s) = "
                f"{100.0 * extra / run.n_members:.0f}% extra compute over the "
                f"requested members (EnsembleConfig.keep_padded_members "
                f"reclaims them)"
            )
    mesh = run.mesh
    tx, state, book = run.tx, run.state, run.book
    x, y, x_val, y_val = run.x, run.y, run.x_val, run.y_val
    member_ids, data_sharding = run.member_ids, run.data_sharding
    # Everything below — history slices, the all-stopped break, best-weight
    # restoration — runs over the EFFECTIVE member count, so promoted
    # padded slots get the same early-stop bookkeeping as requested ones
    # and a promoted N=10 run is bit-identical to an explicit N=16 run.
    shuffle_root, n_members = run.shuffle_root, run.n_effective
    track = config.track_metrics
    losses: List[np.ndarray] = []
    val_losses: List[np.ndarray] = []
    metric_history: Dict[str, List[np.ndarray]] = {
        k: [] for k in ("accuracy", "auc", "val_accuracy", "val_auc")
    } if track else {}
    lockstep_epochs = 0
    step_metrics = StepMetrics(run_log) if run_log is not None else None
    epoch_program = None
    with mesh:
        for epoch in range(config.num_epochs):
            epoch_key = jax.random.fold_in(shuffle_root, epoch)
            lockstep_epochs += 1

            if not streaming and epoch == 0:
                # Acquire the exact lockstep program through the
                # compile-cost subsystem (one lowering shared between the
                # HBM pricing and every epoch's dispatch) and price it.
                # exportable=False: jax.export drops buffer donation, and
                # a store-loaded twin of this donating program would
                # silently double the stacked-state HBM footprint — so
                # the epoch is AOT-shared in-process (its backend compile
                # still lands in the persistent XLA cache for the next
                # process) but never serialized.
                epoch_args = (model, tx, state, book, x, y, x_val, y_val,
                              epoch_key, member_ids, config.batch_size,
                              config.early_stopping_patience, data_sharding,
                              track)
                epoch_program = program_store.get_program(
                    "ensemble_epoch", _ensemble_epoch, *epoch_args,
                    exportable=False, donate_args=(2, 3), run_log=run_log)
                if run_log is not None:
                    # One-time compiled-HBM accounting of the exact
                    # lockstep program (deduped per signature in
                    # telemetry.memory): the member-stacked params/
                    # opt-state plus every slot's activations, priced
                    # before epoch 1 dispatches.
                    telemetry_memory.record_jit_memory(
                        run_log, "ensemble_epoch", _ensemble_epoch,
                        *epoch_args, program=epoch_program,
                    )
            if compile_only:
                # warm-cache: the lockstep program is built and priced;
                # no epoch dispatches, nothing trains.
                return None

            def run_lockstep_epoch():
                if streaming:
                    return _stream_ensemble_epoch(
                        model, tx, state, book, x, y, x_val, y_val,
                        epoch_key, member_ids, config.batch_size,
                        config.early_stopping_patience, mesh, data_sharding,
                        prefetch, track_metrics=track,
                    )
                if epoch_program is not None:
                    return epoch_program(
                        model, tx, state, book, x, y, x_val, y_val,
                        epoch_key, member_ids, config.batch_size,
                        config.early_stopping_patience, data_sharding,
                        track,
                    )
                return _ensemble_epoch(
                    model, tx, state, book, x, y, x_val, y_val, epoch_key,
                    member_ids, config.batch_size,
                    config.early_stopping_patience, data_sharding,
                    track_metrics=track,
                )

            with telemetry_trace.annotate(f"ensemble/epoch{epoch + 1}"):
                if step_metrics is not None:
                    # n_items: member-windows trained this lockstep epoch
                    # (every slot, promoted or padded, rides the program).
                    out = step_metrics.measure(
                        "ensemble_epoch", run_lockstep_epoch,
                        n_items=int(x.shape[0]) * run.n_padded,
                        extra={"epoch": epoch + 1},
                    )
                else:
                    out = run_lockstep_epoch()
            state, book, train_loss, val_loss, active = out[:5]
            if track:
                h_metrics = _host_values(out[5])
                for k, v in zip(
                    ("accuracy", "auc", "val_accuracy", "val_auc"), h_metrics
                ):
                    metric_history[k].append(v[:n_members])
            h_train, h_val, h_active = _host_values(
                (train_loss, val_loss, active)
            )
            losses.append(h_train[:n_members])
            val_losses.append(h_val[:n_members])
            n_active = int(np.sum(h_active[:n_members]))
            if run_log is not None:
                record = step_metrics.last
                run_log.event(
                    "ensemble_epoch",
                    epoch=epoch + 1,
                    active_members=n_active,
                    n_members=n_members,
                    loss=[round(float(v), 6) for v in h_train[:n_members]],
                    val_loss=[round(float(v), 6)
                              for v in h_val[:n_members]],
                    device_s=round(record.device_s, 6),
                    dispatch_s=round(record.dispatch_s, 6),
                    member_windows_per_s=(
                        round(record.items_per_s, 3)
                        if record.items_per_s is not None else None
                    ),
                    retraces=record.retraces,
                    backend_compiles=record.backend_compiles,
                )
            if log_fn:
                log_fn(
                    f"epoch {epoch + 1}/{config.num_epochs} "
                    f"active={n_active}/{n_members} "
                    f"val_loss={h_val[:n_members].round(4).tolist()}"
                )
            if profiler is not None:
                profiler.step()
            if n_active == 0:
                break

    best_val, patience_left, active, best_params, best_stats, best_epoch, epochs_run = book
    h_best_epoch, h_epochs_run = _host_values((best_epoch, epochs_run))
    final = TrainState(
        params=best_params, batch_stats=best_stats,
        opt_state=state.opt_state, step=state.step,
    )
    take = lambda a: jax.tree.map(lambda leaf: leaf[:n_members], a)
    history = {"loss": np.stack(losses), "val_loss": np.stack(val_losses)}
    for k, v in metric_history.items():
        history[k] = np.stack(v)
    result = EnsembleFitResult(
        state=take(final),
        history=history,
        best_epoch=h_best_epoch[:n_members],
        epochs_run=h_epochs_run[:n_members],
        num_members=n_members,
        num_requested=run.n_members,
        member_ids=np.asarray(run.member_ids)[:n_members],
        lockstep_epochs=lockstep_epochs,
    )
    if run_log is not None:
        # The canonical DE cost-accounting record: bench.py and the CLI
        # source effective_members / promoted / wasted-epoch numbers from
        # this event instead of recomputing them inline.
        run_log.event(
            "ensemble_fit",
            num_members=result.num_members,
            num_requested=result.num_requested,
            promoted_members=result.promoted_members,
            member_ids=[int(i) for i in result.member_ids],
            lockstep_epochs=result.lockstep_epochs,
            epochs_run=[int(e) for e in result.epochs_run],
            best_epoch=[int(e) for e in result.best_epoch],
            wasted_member_epochs=result.wasted_member_epochs(),
            early_stopping_patience=config.early_stopping_patience,
        )
    return result
