"""Device-mesh construction for ensemble + data parallelism.

The reference has no parallelism of any kind (SURVEY §2.3): ensemble
members train in a sequential Python loop and there are no collectives.
Here the two parallel axes are explicit mesh axes:

- ``ensemble``: independent Deep-Ensemble members (or MC-pass groups) —
  embarrassingly parallel, no cross-member communication;
- ``data``: batch sharding within a member — XLA inserts the gradient
  ``psum`` over this axis automatically from sharding propagation, riding
  ICI on real TPU topologies.

Construction is topology-driven (:mod:`apnea_uq_tpu.parallel.topology`):
the device list is ordered host-major and the layout solver places the
``data`` axis within hosts whenever the member bound allows, so the
per-step gradient all-reduce rides ICI and only the collective-free
``ensemble`` axis spans hosts.  On a single host (every current rig)
this degenerates bit-for-bit to the historical flat
``jax.devices()``-order reshape — pinned by ``tests/test_topo.py`` — and
on a single chip the mesh is 1x1 and everything degenerates to plain
jit; tests exercise 8 virtual CPU devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apnea_uq_tpu.parallel import topology as topo_mod

AXIS_ENSEMBLE = "ensemble"
AXIS_DATA = "data"


def _spec_and_devices(devices, topology):
    """Resolve the (spec, host-major devices) pair one of three ways:
    an explicit simulated ``topology`` over the given/live devices, or
    detection from the device list / live platform."""
    if topology is not None:
        devs = list(devices) if devices is not None else jax.devices()  # apnea-lint: disable=single-host-device-enumeration -- explicit-topology construction spans every process's devices by definition (the spec says which host owns which)
        return topology, topo_mod.host_major_devices(topology, devs)
    return topo_mod.detect_topology(devices)


def make_mesh(
    num_members: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    ensemble_axis: int = 0,
    topology: Optional[topo_mod.TopologySpec] = None,
) -> Mesh:
    """Build an ``(ensemble, data)`` mesh over the available devices.

    ``ensemble_axis=0`` (auto) picks the largest divisor of the device
    count that is <= num_members — preferring layouts whose data axis
    stays within a host (:func:`topology.solve_layout`) — maximizing
    concurrent members; remaining devices form the data axis.  Pass an
    explicit ``ensemble_axis`` to pin the layout (it must divide the
    device count).  ``topology`` pins a
    :class:`~apnea_uq_tpu.parallel.topology.TopologySpec` (simulated
    host boundaries included) instead of detecting one.
    """
    spec, devs = _spec_and_devices(devices, topology)
    e, d = topo_mod.solve_layout(spec, num_members,
                                 ensemble_axis=ensemble_axis)
    return topo_mod.build_mesh(spec, devs, e, d)


def make_mesh_from_config(
    config,
    num_members: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    topology: Optional[topo_mod.TopologySpec] = None,
) -> Mesh:
    """Build the mesh a :class:`apnea_uq_tpu.config.MeshConfig` describes.

    Explicit ``ensemble_axis`` wins; else an explicit ``data_axis`` fixes
    the DP factor (ensemble = devices / data); else fully auto (see
    :func:`make_mesh`).
    """
    spec, devs = _spec_and_devices(devices, topology)
    e, d = topo_mod.solve_layout(
        spec, num_members,
        ensemble_axis=config.ensemble_axis, data_axis=config.data_axis)
    return topo_mod.build_mesh(spec, devs, e, d)


def member_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays with a leading member axis: split members over
    the ensemble axis, replicate everything else."""
    return NamedSharding(mesh, P(AXIS_ENSEMBLE))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for per-window arrays: split the batch over the data axis,
    replicate across the ensemble axis."""
    return NamedSharding(mesh, P(AXIS_DATA))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_member_tree(tree, mesh: Mesh):
    """Place a stacked member-axis pytree with members split over the
    ensemble axis."""
    s = member_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, s), tree)
