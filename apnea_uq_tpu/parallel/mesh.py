"""Device-mesh construction for ensemble + data parallelism.

The reference has no parallelism of any kind (SURVEY §2.3): ensemble
members train in a sequential Python loop and there are no collectives.
Here the two parallel axes are explicit mesh axes:

- ``ensemble``: independent Deep-Ensemble members (or MC-pass groups) —
  embarrassingly parallel, no cross-member communication;
- ``data``: batch sharding within a member — XLA inserts the gradient
  ``psum`` over this axis automatically from sharding propagation, riding
  ICI on real TPU topologies.

On a single chip the mesh is 1x1 and everything degenerates to plain jit;
tests exercise 8 virtual CPU devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ENSEMBLE = "ensemble"
AXIS_DATA = "data"


def make_mesh(
    num_members: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    ensemble_axis: int = 0,
) -> Mesh:
    """Build an ``(ensemble, data)`` mesh over the available devices.

    ``ensemble_axis=0`` (auto) picks the largest divisor of the device
    count that is <= num_members, maximizing concurrent members; remaining
    devices form the data axis.  Pass an explicit ``ensemble_axis`` to pin
    the layout (it must divide the device count).
    """
    devs = list(devices) if devices is not None else jax.devices()
    d = len(devs)
    if ensemble_axis == 0:
        e = 1
        for cand in range(1, d + 1):
            if d % cand == 0 and cand <= max(num_members, 1):
                e = cand
    else:
        e = ensemble_axis
        if d % e != 0:
            raise ValueError(f"ensemble_axis {e} does not divide device count {d}")
    mesh_devices = np.asarray(devs).reshape(e, d // e)
    return Mesh(mesh_devices, (AXIS_ENSEMBLE, AXIS_DATA))


def make_mesh_from_config(
    config,
    num_members: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the mesh a :class:`apnea_uq_tpu.config.MeshConfig` describes.

    Explicit ``ensemble_axis`` wins; else an explicit ``data_axis`` fixes
    the DP factor (ensemble = devices / data); else fully auto (see
    :func:`make_mesh`).
    """
    devs = list(devices) if devices is not None else jax.devices()
    e = config.ensemble_axis
    if e == 0 and config.data_axis > 0:
        if len(devs) % config.data_axis:
            raise ValueError(
                f"data_axis {config.data_axis} does not divide device "
                f"count {len(devs)}"
            )
        e = len(devs) // config.data_axis
    if config.ensemble_axis > 0 and config.data_axis > 0:
        if config.ensemble_axis * config.data_axis != len(devs):
            raise ValueError(
                f"mesh {config.ensemble_axis}x{config.data_axis} does not "
                f"match device count {len(devs)}"
            )
    return make_mesh(num_members, devs, ensemble_axis=e)


def member_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays with a leading member axis: split members over
    the ensemble axis, replicate everything else."""
    return NamedSharding(mesh, P(AXIS_ENSEMBLE))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for per-window arrays: split the batch over the data axis,
    replicate across the ensemble axis."""
    return NamedSharding(mesh, P(AXIS_DATA))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_member_tree(tree, mesh: Mesh):
    """Place a stacked member-axis pytree with members split over the
    ensemble axis."""
    s = member_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, s), tree)
