"""Declarative device topology: the single source of truth for meshes.

``parallel/mesh.py`` historically reshaped a flat ``jax.devices()`` list
with no notion of *hosts* — fine on one chip or one host, silently wrong
at pod scale, where the fabric is two-tier: ICI within a host's slice,
DCN between hosts.  A mesh axis that spans hosts pays DCN latency on
every collective over it, so the layout rule for this codebase is:

- the ``data`` axis (gradient ``psum`` every step) lives WITHIN a host
  whenever the layout allows, so its all-reduce rides ICI;
- the ``ensemble`` axis (zero collectives by design — members are
  independent) is the axis that SPANS hosts, where the wire would hurt.

:class:`TopologySpec` makes that reasoning explicit and testable: hosts
× local devices per host, plus the per-device HBM budget and the
cross-host traffic allowance the static topology analysis
(``apnea-uq topo``, :mod:`apnea_uq_tpu.topo`) gates against.  Mesh
construction (:func:`build_mesh`) orders devices host-major and reshapes
``(ensemble, data)`` so data groups are contiguous within-host runs —
on a single host this degenerates to exactly the historical
``np.asarray(devices).reshape(e, d)`` (bit-parity pinned by
``tests/test_topo.py``), so nothing changes until a second host exists.

The spec is also how the analysis *simulates* multi-host layouts on the
8-virtual-device CPU test rig: ``TopologySpec(hosts=2,
devices_per_host=4)`` over 8 real CPU devices treats the host-major
device order as two simulated hosts of four, which is all the static
cross-host classification needs (jax 0.4.x cannot yet lower through an
``AbstractMesh`` with a device assignment, so fake-device 2×8 / 4×8
meshes stay out of reach; the simulated-host partition of the real rig
is the CPU-checkable projection of the same hazards).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

# Default per-device HBM budget for simulated topologies: one v5e chip
# (telemetry.memory.CHIP_HBM_BYTES["TPU v5e"]).  The topo analysis
# checks compiled per-device peaks against this — canonical audit shapes
# sit far under it, so a violation means a program's footprint no longer
# scales with the mesh (e.g. a replicated buffer that should shard).
DEFAULT_HBM_BYTES = int(16e9)

# Default per-program cross-host traffic allowance: collectives whose
# device groups span hosts ride DCN; 64 MiB per lowered program is far
# above anything the current zoo emits (zero) and far below a
# mistakenly-global all-gather of a window set.
DEFAULT_CROSS_HOST_BUDGET_BYTES = 64 << 20


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """hosts × local devices, with the budgets the topo rules enforce."""

    hosts: int
    devices_per_host: int
    hbm_bytes_per_device: int = DEFAULT_HBM_BYTES
    cross_host_budget_bytes: int = DEFAULT_CROSS_HOST_BUDGET_BYTES

    def __post_init__(self):
        if self.hosts < 1 or self.devices_per_host < 1:
            raise ValueError(
                f"topology needs >=1 host and >=1 device/host, got "
                f"{self.hosts}x{self.devices_per_host}")

    @property
    def total_devices(self) -> int:
        return self.hosts * self.devices_per_host

    @property
    def name(self) -> str:
        """``2x4`` = 2 hosts × 4 local devices (the manifest row key)."""
        return f"{self.hosts}x{self.devices_per_host}"


def detect_topology(devices: Optional[Sequence] = None,
                    ) -> Tuple[TopologySpec, List]:
    """The live platform's topology: devices grouped by
    ``process_index``, host-major order preserved.  Returns
    ``(spec, devices_in_host_major_order)``.

    Single-process platforms (every CPU/TPU test rig, one-host slices)
    come back as ``1 x len(devices)`` with the device order untouched —
    the bit-parity anchor for :func:`build_mesh`.  Ragged per-host
    device counts (no JAX platform produces them today) collapse to one
    logical host rather than guessing a layout.
    """
    if devices is None:
        import jax

        # The global mesh deliberately wants EVERY process's devices;
        # process-local enumeration is jax.local_devices(), not here.
        # apnea-lint: disable=single-host-device-enumeration -- detect_topology is the one sanctioned global-enumeration site: it groups the global list by process_index to build the host-aware spec
        devices = jax.devices()
    devs = list(devices)
    by_host: Dict[int, List] = {}
    for d in devs:
        by_host.setdefault(int(getattr(d, "process_index", 0)), []).append(d)
    counts = {len(v) for v in by_host.values()}
    if len(counts) != 1:
        return TopologySpec(1, len(devs)), devs
    local = counts.pop()
    # Host-major, stable: within a host the platform's own order holds.
    ordered = [d for host in sorted(by_host) for d in by_host[host]]
    return TopologySpec(len(by_host), local), ordered


def simulated_topologies(total_devices: int,
                         ) -> Tuple[TopologySpec, ...]:
    """The canonical simulated sweep over ``total_devices`` real
    devices: single-host (the parity anchor) plus every power-of-two
    host split up to 4 hosts.  On the canonical 8-device rig this is
    1x8, 2x4, 4x2 — the committed ``topo/manifest.json`` rows."""
    specs = [TopologySpec(1, total_devices)]
    for hosts in (2, 4):
        if total_devices % hosts == 0 and total_devices // hosts >= 1 \
                and hosts <= total_devices:
            specs.append(TopologySpec(hosts, total_devices // hosts))
    return tuple(specs)


def solve_layout(spec: TopologySpec, num_members: int = 1, *,
                 ensemble_axis: int = 0, data_axis: int = 0,
                 ) -> Tuple[int, int]:
    """The ``(ensemble, data)`` factor sizes for this topology.

    Explicit ``ensemble_axis`` wins; else an explicit ``data_axis``
    fixes the DP factor; else auto.  Auto maximizes concurrent members
    (largest divisor of the device count <= ``num_members``) — among
    layouts whose data axis fits WITHIN a host when any such layout
    satisfies the member bound, so the gradient ``psum`` rides ICI.
    When none does (the pure data-parallel ``num_members=1`` mesh on a
    multi-host topology — the global-batch axis genuinely spans hosts),
    auto falls back to the historical choice and the topo analysis
    charges the cross-host traffic instead of refusing the layout.
    On a single host every divisor is within-host, so auto reduces
    exactly to the historical behavior.
    """
    total = spec.total_devices
    if ensemble_axis:
        e = ensemble_axis
        if total % e != 0:
            raise ValueError(
                f"ensemble_axis {e} does not divide device count {total}")
        if data_axis and e * data_axis != total:
            raise ValueError(
                f"mesh {e}x{data_axis} does not match device count {total}")
        return e, total // e
    if data_axis:
        if total % data_axis != 0:
            raise ValueError(
                f"data_axis {data_axis} does not divide device count "
                f"{total}")
        return total // data_axis, data_axis
    bound = max(num_members, 1)
    divisors = [c for c in range(1, total + 1) if total % c == 0]
    candidates = [c for c in divisors if c <= bound]
    intra = [c for c in candidates
             if spec.devices_per_host % (total // c) == 0]
    e = max(intra) if intra else max(candidates)
    return e, total // e


def host_major_devices(spec: TopologySpec, devices: Sequence) -> List:
    """``devices`` in host-major order under ``spec``.  A simulated spec
    partitions the given order into ``hosts`` runs of
    ``devices_per_host``; live devices re-sort by their real
    ``process_index`` (stable, so single-host order is untouched)."""
    devs = list(devices)
    if len(devs) != spec.total_devices:
        raise ValueError(
            f"topology {spec.name} needs {spec.total_devices} devices, "
            f"got {len(devs)}")
    if spec.hosts == 1:
        return devs
    indices = {int(getattr(d, "process_index", 0)) for d in devs}
    if len(indices) > 1:
        devs.sort(key=lambda d: int(getattr(d, "process_index", 0)))
    return devs


def build_mesh(spec: TopologySpec, devices: Sequence, e: int, d: int):
    """The ``(ensemble, data)`` mesh for this topology: host-major
    device order reshaped ``(e, d)``, so each data group is a contiguous
    within-host run whenever ``d`` divides the host's device count —
    and on one host, exactly the historical flat reshape."""
    import numpy as np
    from jax.sharding import Mesh

    devs = host_major_devices(spec, devices)
    if e * d != len(devs):
        raise ValueError(
            f"layout {e}x{d} does not cover {len(devs)} devices")
    from apnea_uq_tpu.parallel import mesh as mesh_mod

    return Mesh(np.asarray(devs).reshape(e, d),
                (mesh_mod.AXIS_ENSEMBLE, mesh_mod.AXIS_DATA))


def axis_spans_hosts(spec: TopologySpec, e: int, d: int,
                     axis: str) -> bool:
    """Whether ``axis`` of the ``(e, d)`` layout communicates across
    hosts under ``spec``.  Data groups are contiguous host-major runs:
    within one host iff the run fits and aligns (``d`` divides the
    host's device count).  Ensemble groups stride across the data
    groups, so they span hosts whenever more than one host exists and
    the data axis doesn't already cover whole hosts' worth of rows per
    host... which for this construction reduces to: any second host
    puts some ensemble group across a host boundary."""
    if spec.hosts == 1:
        return False
    from apnea_uq_tpu.parallel import mesh as mesh_mod

    if axis == mesh_mod.AXIS_DATA:
        return spec.devices_per_host % d != 0
    if axis == mesh_mod.AXIS_ENSEMBLE:
        # Rows (data groups) tile the hosts; the ensemble axis crosses
        # a host boundary unless every column stays inside one host —
        # i.e. unless a single host holds the whole mesh.
        return True
    return True


def axis_sizes(e: int, d: int) -> Dict[str, int]:
    from apnea_uq_tpu.parallel import mesh as mesh_mod

    return {mesh_mod.AXIS_ENSEMBLE: e, mesh_mod.AXIS_DATA: d}
