"""The ``apnea-uq conc`` subcommand.

``apnea-uq conc [paths ...] [--json | --format gha] [--rule NAME ...]``
— exits 0 when every finding is suppressed-with-justification, 1 on
unsuppressed findings, 2 on usage errors.  With no paths it audits the
installed package plus the repo's ``bench.py`` — the exact scope the
tier-1 gate (``tests/test_conc.py``) runs.

Kept jax-free end to end, like ``apnea-uq lint``: the handler imports
only the conc package, the lint engine, and the shared reporters.
"""

from __future__ import annotations

from apnea_uq_tpu.telemetry import log


def cmd_conc(args) -> int:
    from apnea_uq_tpu.conc import run_conc
    from apnea_uq_tpu.lint.cli import default_paths
    from apnea_uq_tpu.lint.report import emit_result, resolve_format

    fmt = resolve_format(args)
    paths = args.paths or default_paths()
    try:
        result = run_conc(paths, rules=args.rule or None)
    except (FileNotFoundError, ValueError, SyntaxError) as e:
        # Usage errors exit 2, distinct from exit 1 = real findings.
        log(f"apnea-uq conc: {e}")
        raise SystemExit(2)
    emit_result(result, fmt)
    return 1 if result.unsuppressed else 0


def register(sub) -> None:
    """Attach the ``conc`` subcommand to the CLI's subparser registry."""
    from apnea_uq_tpu.lint.report import add_format_args

    p = sub.add_parser(
        "conc",
        help="Concurrency & crash-consistency audit: statically check "
             "the thread/process/crash seams — shared-state races, "
             "blocking calls under locks, unbounded producer queues, "
             "fork-after-jax pools, stray os.environ writes, and "
             "torn-read/commit-order resume discipline.")
    p.add_argument("paths", nargs="*", default=None,
                   help="Files/directories to audit; default: the "
                        "apnea_uq_tpu package plus bench.py beside it.")
    add_format_args(p)
    p.add_argument("--rule", action="append", default=[], metavar="NAME",
                   help="Run only this conc rule (repeatable); default: "
                        "all — see docs/LINT.md \"Concurrency rules\".")
    p.set_defaults(fn=cmd_conc)
