"""``apnea-uq conc`` — concurrency & crash-consistency audit (ISSUE 19).

Fifth static-analysis family on the lint engine: audit the
thread/process/crash seams the serving tier grew — shared-state races
around ``Thread(target=...)`` bodies, blocking work under locks,
unbounded producer queues, fork-after-jax process pools, stray
``os.environ`` writes, and the crash-consistency *read* side
(torn-tolerant state loads, effects-before-commit ordering)
(:mod:`apnea_uq_tpu.conc.rules`).  The runtime half is the seeded
schedule-perturbation harness (:mod:`apnea_uq_tpu.conc.perturb`) that
lets tier-1 drive the same invariants under adversarial interleavings.
Jax-free end to end.
"""

from apnea_uq_tpu.conc.rules import CONC_RULES, run_conc_rules

__all__ = ["CONC_RULES", "run_conc_rules", "run_conc"]


def run_conc(paths, *, rules=None, repo_root=None):
    """Programmatic twin of the CLI: lint-engine file loading + conc
    rules + suppression resolution, returning the same
    :class:`~apnea_uq_tpu.lint.engine.LintResult` shape the reporters
    render."""
    from apnea_uq_tpu.conc.rules import ConcContext
    from apnea_uq_tpu.lint.engine import (
        LintContext, LintResult, apply_suppressions, default_repo_root,
        load_files,
    )

    paths = list(paths)
    if not paths:
        raise ValueError("run_conc needs at least one path")
    if repo_root is None:
        repo_root = default_repo_root(paths)
    files = load_files(paths, repo_root)
    cc = ConcContext(context=LintContext(files=files, repo_root=repo_root))
    selected = tuple(dict.fromkeys(rules)) if rules is not None \
        else tuple(sorted(CONC_RULES))
    findings = run_conc_rules(cc, rules=selected)
    by_path = {f.path: f for f in files}
    findings = [
        apply_suppressions(f, by_path[f.path]) if f.path in by_path else f
        for f in findings
    ]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return LintResult(
        findings=findings, files_scanned=len(files), rules_run=selected,
        scanned_paths=tuple(f.path for f in files),
    )
