"""The conc-rule family: static audit of the thread/process/crash seams.

Fifth rule family on the lint engine — same :class:`Finding` type, same
severities, same ``# apnea-lint: disable=<rule> -- <why>`` suppressions,
same reporters — but the subject is the *concurrency topology* the
serving tier grew (PRs 15-18): the daemon pump thread, subprocess
replicas, spawn-context ingest pools, and the three kill -9-resumable
state protocols.  These hazards only surface under load, on hardware,
at 3am; this family makes them a static, pre-run exit code.

Thread/process rules:

- ``thread-shared-mutable-state`` — an attribute or declared
  global/nonlocal is mutated both inside a ``Thread(target=...)`` body
  and outside it with no lock held on both sides: a data race the GIL
  only *sometimes* hides.  ``__init__`` scopes are initialization (the
  thread does not exist yet) and do not count as racing sites.
- ``blocking-call-under-lock`` — a subprocess call, a bare
  ``queue.get()``/``.join()`` with no timeout, or a device sync
  (``block_until_ready``) inside a ``with <lock>:`` region: every other
  thread needing that lock now waits on I/O or the device.
- ``unbounded-producer-queue`` — a ``queue.Queue()`` with no positive
  ``maxsize`` (or a ``SimpleQueue``, which has none) in a module that
  starts a thread: the producer can outrun the consumer without bound —
  the backpressure hole the serve pump's ``maxsize=1024`` closes.
- ``fork-after-jax-import`` — a process pool / ``multiprocessing``
  primitive without an explicit spawn (or forkserver) context in a
  module that imports jax/flax (directly, or transitively through
  ``apnea_uq_tpu``): fork()ing a multithreaded runtime can deadlock a
  worker on an inherited lock.  ``data/ingest.py``'s
  ``mp_context=get_context("spawn")`` pin is the blessed shape.
- ``env-mutation-in-library`` — an ``os.environ`` write outside the one
  blessed startup seam (:mod:`apnea_uq_tpu.utils.env`): env mutation is
  process-global shared state, and duplicated ``XLA_FLAGS`` pins drift
  apart (the pre-fix ``topo/cli.py`` / ``cli/stages.py`` twins).

Crash-consistency read-side rules (the complement of flow's
write-discipline rules):

- ``torn-read-protocol`` — state/progress JSON parsed with a raw
  ``json.load`` instead of the shared torn-tail-tolerant reader
  (:func:`apnea_uq_tpu.utils.io.read_json_tolerant`): a torn or corrupt
  snapshot then crash-loops the resume path instead of degrading to a
  fresh start.
- ``resume-commit-order`` — a result row written *after* the last
  atomic state commit of its scope: a crash in that gap loses the row
  while the committed state claims it was emitted — the at-least-once
  ordering runs effects first, commit last.

Jax-free by construction.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from apnea_uq_tpu.lint.astwalk import (
    ScopeWalk,
    call_name,
    canonical_call,
    compatible,
    dotted_name,
    import_aliases,
    scopes,
)
from apnea_uq_tpu.lint.engine import (
    SEVERITIES,
    Finding,
    LintContext,
    Rule,
    SourceFile,
)

CONC_RULES: Dict[str, Rule] = {}

#: The ONE module allowed to write ``os.environ`` — the guarded startup
#: seam every caller (topo sweep, `apnea-uq check`) routes through.  The
#: env-mutation rule pins this: adding a second mutation site anywhere
#: in the package is a finding, not a style choice.
BLESSED_ENV_MODULES = ("apnea_uq_tpu/utils/env.py",)

#: Modules exempt from the torn-read rule: the shared tolerant reader
#: itself lives here (its internal ``json.load`` IS the protocol).
BLESSED_READ_MODULES = ("apnea_uq_tpu/utils/io.py",)

#: The reader the torn-read rule points violators at.
TOLERANT_READER = "apnea_uq_tpu.utils.io.read_json_tolerant"


def register_conc_rule(name: str, severity: str, summary: str):
    """Decorator twin of :func:`apnea_uq_tpu.lint.engine.register_rule`
    for rules that check the thread/process/crash seams."""
    if severity not in SEVERITIES:
        raise ValueError(
            f"severity must be one of {SEVERITIES}, got {severity!r}")

    def wrap(fn):
        CONC_RULES[name] = Rule(name=name, severity=severity,
                                summary=summary, check=fn)
        return fn

    return wrap


@dataclasses.dataclass
class ConcContext:
    """Everything a conc rule sees: the parsed in-scope files."""

    context: LintContext


def _finding(rule: str, path: str, line: int, message: str) -> Finding:
    return Finding(rule=rule, severity=CONC_RULES[rule].severity,
                   path=path, line=int(line), message=message)


def _blessed(sf: SourceFile, blessed: Tuple[str, ...]) -> bool:
    norm = sf.path.replace(os.sep, "/")
    return any(norm.endswith(b) for b in blessed)


# ---------------------------------------------------------- shared walks --

_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _segments(text: str) -> List[str]:
    """Lower-cased alphabetic segments: 'stream_state.json' ->
    ['stream', 'state', 'json'].  Segment equality (not substring) keeps
    'pstate'/'estimate' out of the state-marker net."""
    return [s for s in re.split(r"[^a-zA-Z]+", text.lower()) if s]


_STATE_MARKERS = frozenset({"state", "progress"})


def _marker_in(text: str) -> bool:
    return any(s in _STATE_MARKERS for s in _segments(text))


def _lockish(expr: ast.AST) -> bool:
    """True for ``with`` context expressions that read as a lock:
    ``lock``, ``self._lock``, ``threading.Lock()``, ``some_mutex``."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1].lower()
    return "lock" in last or "mutex" in last


def _stmt_bodies(stmt: ast.stmt) -> Iterator[List[ast.stmt]]:
    for attr in ("body", "orelse", "finalbody"):
        b = getattr(stmt, attr, None)
        if b:
            yield b
    for h in getattr(stmt, "handlers", []):
        yield h.body
    for c in getattr(stmt, "cases", []):
        yield c.body


def _iter_stmts(body: List[ast.stmt],
                locked: bool) -> Iterator[Tuple[ast.stmt, bool]]:
    """Every statement of one scope exactly once, tagged with whether a
    lexically-enclosing ``with <lock>:`` holds.  Nested function/class
    bodies are their own scopes and are not descended into."""
    for stmt in body:
        if isinstance(stmt, _FN_NODES + (ast.ClassDef,)):
            continue
        yield stmt, locked
        inner = locked
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = locked or any(_lockish(i.context_expr)
                                  for i in stmt.items)
        for child in _stmt_bodies(stmt):
            yield from _iter_stmts(child, inner)


def _stmt_exprs(stmt: ast.stmt) -> Iterator[ast.expr]:
    """Direct expression children of one statement (nested statement
    bodies excluded — they come back as their own statements)."""
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            yield item.context_expr
        return
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.expr):
            yield child


def _scope_calls(body: List[ast.stmt]) -> Iterator[Tuple[ast.Call, bool]]:
    """(call, under_lock) for every call of one scope, exactly once."""
    for stmt, locked in _iter_stmts(body, False):
        for expr in _stmt_exprs(stmt):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    yield node, locked


# --------------------------------------------- thread-shared-mutable-state --

@dataclasses.dataclass(frozen=True)
class _Mutation:
    kind: str           # "attr" | "name"
    key: str
    line: int
    locked: bool


def _declared_names(body: List[ast.stmt]) -> Set[str]:
    out: Set[str] = set()
    for stmt, _locked in _iter_stmts(body, False):
        if isinstance(stmt, (ast.Global, ast.Nonlocal)):
            out.update(stmt.names)
    return out


def _mutation_targets(stmt: ast.stmt) -> List[ast.expr]:
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    else:
        return []
    flat: List[ast.expr] = []
    for t in targets:
        flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t])
    return flat


def _scope_mutations(body: List[ast.stmt],
                     declared: Set[str]) -> List[_Mutation]:
    """Attribute stores (``self.x = ...``, ``obj.cache[k] = ...``) plus
    stores to names the scope declared global/nonlocal."""
    out: List[_Mutation] = []
    for stmt, locked in _iter_stmts(body, False):
        for target in _mutation_targets(stmt):
            if isinstance(target, ast.Subscript):
                target = target.value
            if isinstance(target, ast.Attribute):
                key = dotted_name(target)
                if key:
                    out.append(_Mutation("attr", key, target.lineno, locked))
            elif isinstance(target, ast.Name) and target.id in declared:
                out.append(_Mutation("name", target.id, target.lineno,
                                     locked))
    return out


@register_conc_rule(
    "thread-shared-mutable-state", "error",
    "an attribute/global mutated both inside a Thread(target=...) body "
    "and outside it with no lock held on both sides — a data race the "
    "GIL only sometimes hides",
)
def check_thread_shared_state(cc: ConcContext) -> Iterable[Finding]:
    for sf in cc.context.files:
        aliases = import_aliases(sf.tree)
        target_names: Set[str] = set()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if canonical_call(node, aliases) != "threading.Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    name = dotted_name(kw.value)
                    if name:
                        target_names.add(name.rsplit(".", 1)[-1])
        if not target_names:
            continue
        fns = [n for n in ast.walk(sf.tree) if isinstance(n, _FN_NODES)]
        muts = {id(fn): _scope_mutations(fn.body, _declared_names(fn.body))
                for fn in fns}
        for fn in fns:
            if fn.name not in target_names:
                continue
            inside = {id(n) for n in ast.walk(fn) if isinstance(n, _FN_NODES)}
            peers: Dict[Tuple[str, str], List[_Mutation]] = {}
            for other in fns:
                # __init__ runs before the thread exists — that is
                # initialization, not a racing site.
                if id(other) in inside or other.name == "__init__":
                    continue
                for m in muts[id(other)]:
                    peers.setdefault((m.kind, m.key), []).append(m)
            for m in muts[id(fn)]:
                racing = peers.get((m.kind, m.key))
                if not racing:
                    continue
                if m.locked and all(p.locked for p in racing):
                    continue
                lines = sorted({p.line for p in racing})
                yield _finding(
                    "thread-shared-mutable-state", sf.path, m.line,
                    f"'{m.key}' is mutated inside thread target "
                    f"'{fn.name}' and also at line(s) {lines} outside it "
                    f"with no lock held on both sides — guard every "
                    f"mutation with one Lock, or confine the state to "
                    f"the owning thread and hand results over a queue",
                )


# ------------------------------------------------- blocking-call-under-lock --

def _blocking_reason(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return None
    cn = canonical_call(call, aliases) or ""
    if cn.startswith("subprocess."):
        return f"a subprocess call ({cn})"
    last = (call_name(call) or "").rsplit(".", 1)[-1]
    if last == "block_until_ready":
        return "a device sync (block_until_ready)"
    if isinstance(call.func, ast.Attribute) and not call.args:
        if last == "get":
            for kw in call.keywords:
                if (kw.arg == "block" and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False):
                    return None
            return "a queue .get() with no timeout"
        if last == "join" and not call.keywords:
            return "a .join() with no timeout"
    return None


@register_conc_rule(
    "blocking-call-under-lock", "error",
    "a subprocess call, bare queue .get()/.join(), or device sync "
    "inside a `with <lock>:` region — every thread needing the lock "
    "now waits on I/O or the device",
)
def check_blocking_under_lock(cc: ConcContext) -> Iterable[Finding]:
    for sf in cc.context.files:
        aliases = import_aliases(sf.tree)
        for _scope, body in scopes(sf.tree):
            for call, locked in _scope_calls(body):
                if not locked:
                    continue
                reason = _blocking_reason(call, aliases)
                if reason:
                    yield _finding(
                        "blocking-call-under-lock", sf.path, call.lineno,
                        f"{reason} runs while a lock is held — move the "
                        f"blocking work outside the critical section, or "
                        f"bound it with a timeout",
                    )


# ------------------------------------------------ unbounded-producer-queue --

_BOUNDED_QUEUES = frozenset({
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "multiprocessing.Queue",
})
_SIMPLE_QUEUES = frozenset({"queue.SimpleQueue", "multiprocessing.SimpleQueue"})


@register_conc_rule(
    "unbounded-producer-queue", "error",
    "a queue constructed without a positive maxsize in a module that "
    "starts a thread — the producer can outrun the consumer without "
    "bound (no backpressure)",
)
def check_unbounded_queue(cc: ConcContext) -> Iterable[Finding]:
    for sf in cc.context.files:
        aliases = import_aliases(sf.tree)
        calls = [n for n in ast.walk(sf.tree) if isinstance(n, ast.Call)]
        if not any(canonical_call(c, aliases) == "threading.Thread"
                   for c in calls):
            continue
        for c in calls:
            cn = canonical_call(c, aliases)
            if cn in _SIMPLE_QUEUES:
                yield _finding(
                    "unbounded-producer-queue", sf.path, c.lineno,
                    f"{cn} has no maxsize at all — a threaded producer "
                    f"can grow it without bound; use queue.Queue with a "
                    f"positive maxsize so a fast source back-pressures",
                )
                continue
            if cn not in _BOUNDED_QUEUES:
                continue
            size: object = None
            if c.args:
                size = (c.args[0].value
                        if isinstance(c.args[0], ast.Constant) else "dynamic")
            for kw in c.keywords:
                if kw.arg == "maxsize":
                    size = (kw.value.value
                            if isinstance(kw.value, ast.Constant)
                            else "dynamic")
            if size == "dynamic":
                continue  # computed bound: benefit of the doubt
            if size is None or (isinstance(size, int) and size <= 0):
                yield _finding(
                    "unbounded-producer-queue", sf.path, c.lineno,
                    f"{cn} without a positive maxsize is unbounded "
                    f"(maxsize<=0 means infinite) — in a module that "
                    f"starts a thread this is a backpressure hole; pass "
                    f"a positive maxsize so the producer blocks instead "
                    f"of the process growing without bound",
                )


# -------------------------------------------------- fork-after-jax-import --

def _jax_taint(tree: ast.Module) -> Optional[str]:
    """The import that makes fork() unsafe in this module: jax/flax
    directly, or any apnea_uq_tpu import (the package loads jax
    transitively on most paths — the pragmatic approximation)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                top = a.name.split(".")[0]
                if top in ("jax", "flax"):
                    return top
                if top == "apnea_uq_tpu":
                    return a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level > 0:
                return "the package (relative import)"
            if node.module:
                top = node.module.split(".")[0]
                if top in ("jax", "flax"):
                    return top
                if top == "apnea_uq_tpu":
                    return node.module
    return None


def _spawn_context_ok(value: ast.expr) -> bool:
    """True when an mp_context= value is an explicit safe start method:
    ``multiprocessing.get_context("spawn"|"forkserver")`` (or a name we
    cannot see through — benefit of the doubt)."""
    if isinstance(value, ast.Call):
        last = (call_name(value) or "").rsplit(".", 1)[-1]
        if last == "get_context" and value.args \
                and isinstance(value.args[0], ast.Constant):
            return value.args[0].value in ("spawn", "forkserver")
        return False
    return not isinstance(value, ast.Constant)


@register_conc_rule(
    "fork-after-jax-import", "error",
    "a process pool / multiprocessing primitive without an explicit "
    "spawn context in a module importing jax (directly or via "
    "apnea_uq_tpu) — fork()ing a multithreaded runtime can deadlock a "
    "worker on an inherited lock",
)
def check_fork_after_jax(cc: ConcContext) -> Iterable[Finding]:
    for sf in cc.context.files:
        taint = _jax_taint(sf.tree)
        if taint is None:
            continue
        aliases = import_aliases(sf.tree)
        hint = (f"this module imports {taint}; pin "
                f"mp_context=multiprocessing.get_context('spawn') — the "
                f"data/ingest.py shape")
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = canonical_call(node, aliases) or ""
            last = cn.rsplit(".", 1)[-1]
            if last == "ProcessPoolExecutor":
                ctx = next((kw.value for kw in node.keywords
                            if kw.arg == "mp_context"), None)
                if ctx is None or not _spawn_context_ok(ctx):
                    yield _finding(
                        "fork-after-jax-import", sf.path, node.lineno,
                        f"ProcessPoolExecutor without an explicit spawn "
                        f"context inherits the platform default (fork on "
                        f"Linux) — {hint}",
                    )
            elif cn in ("multiprocessing.Pool", "multiprocessing.Process"):
                yield _finding(
                    "fork-after-jax-import", sf.path, node.lineno,
                    f"{cn} uses the platform default start method (fork "
                    f"on Linux) — {hint}",
                )
            elif cn == "os.fork":
                yield _finding(
                    "fork-after-jax-import", sf.path, node.lineno,
                    f"os.fork() of a multithreaded runtime can deadlock "
                    f"the child on an inherited lock — {hint}",
                )
            elif last in ("get_context", "set_start_method") and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value == "fork":
                yield _finding(
                    "fork-after-jax-import", sf.path, node.lineno,
                    f"an explicit 'fork' start method is exactly the "
                    f"unsafe case — {hint}",
                )


# ------------------------------------------------- env-mutation-in-library --

_ENV_MUTATOR_METHODS = frozenset({
    "setdefault", "update", "pop", "popitem", "clear", "__setitem__",
})


def _is_environ(node: ast.AST, aliases: Dict[str, str]) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    resolved = aliases.get(head, head)
    full = f"{resolved}.{rest}" if rest else resolved
    return full == "os.environ"


@register_conc_rule(
    "env-mutation-in-library", "error",
    "an os.environ write outside the blessed startup seam "
    "(apnea_uq_tpu/utils/env.py) — process-global mutable state, and "
    "duplicated XLA_FLAGS pins drift apart",
)
def check_env_mutation(cc: ConcContext) -> Iterable[Finding]:
    for sf in cc.context.files:
        if _blessed(sf, BLESSED_ENV_MODULES):
            continue
        aliases = import_aliases(sf.tree)
        hint = ("route through the guarded helper in "
                "apnea_uq_tpu/utils/env.py (pin_host_analysis_rig) — the "
                "one blessed mutation site")
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and _is_environ(t.value, aliases):
                        yield _finding(
                            "env-mutation-in-library", sf.path, t.lineno,
                            f"os.environ[...] assignment in library code "
                            f"— {hint}",
                        )
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and _is_environ(t.value, aliases):
                        yield _finding(
                            "env-mutation-in-library", sf.path, t.lineno,
                            f"del os.environ[...] in library code — {hint}",
                        )
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _ENV_MUTATOR_METHODS \
                        and _is_environ(f.value, aliases):
                    yield _finding(
                        "env-mutation-in-library", sf.path, node.lineno,
                        f"os.environ.{f.attr}(...) in library code — "
                        f"{hint}",
                    )
                elif (canonical_call(node, aliases)
                        in ("os.putenv", "os.unsetenv")):
                    yield _finding(
                        "env-mutation-in-library", sf.path, node.lineno,
                        f"{canonical_call(node, aliases)}(...) in library "
                        f"code — {hint}",
                    )


# ----------------------------------------------------- torn-read-protocol --

def _has_marker(expr: ast.AST, tainted: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            if node.id in tainted or _marker_in(node.id):
                return True
        elif isinstance(node, ast.Attribute):
            if _marker_in(node.attr):
                return True
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _marker_in(node.value):
                return True
    return False


def _is_open_call(call: ast.Call) -> bool:
    return (call_name(call) or "").rsplit(".", 1)[-1] == "open"


@register_conc_rule(
    "torn-read-protocol", "error",
    "state/progress JSON parsed with a raw json.load instead of the "
    "shared torn-tail-tolerant reader — a corrupt snapshot crash-loops "
    "the resume path instead of degrading to a fresh start",
)
def check_torn_read(cc: ConcContext) -> Iterable[Finding]:
    for sf in cc.context.files:
        if _blessed(sf, BLESSED_READ_MODULES):
            continue
        aliases = import_aliases(sf.tree)
        for scope, body in scopes(sf.tree):
            stmts = [s for s, _l in _iter_stmts(body, False)]
            tainted: Set[str] = set()
            if isinstance(scope, _FN_NODES):
                args = scope.args
                for a in (args.posonlyargs + args.args + args.kwonlyargs):
                    if _marker_in(a.arg):
                        tainted.add(a.arg)
            # Two passes: path taint may chain (path = _progress_path();
            # then open(path)).
            for _ in range(2):
                for stmt in stmts:
                    if isinstance(stmt, ast.Assign) \
                            and _has_marker(stmt.value, tainted):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                tainted.add(t.id)
            handles: Set[str] = set()
            for stmt in stmts:
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.value, ast.Call) \
                        and _is_open_call(stmt.value) \
                        and any(_has_marker(a, tainted)
                                for a in stmt.value.args):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            handles.add(t.id)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        ce = item.context_expr
                        if isinstance(ce, ast.Call) and _is_open_call(ce) \
                                and any(_has_marker(a, tainted)
                                        for a in ce.args) \
                                and isinstance(item.optional_vars, ast.Name):
                            handles.add(item.optional_vars.id)
            for stmt in stmts:
                for expr in _stmt_exprs(stmt):
                    for node in ast.walk(expr):
                        if not isinstance(node, ast.Call) or not node.args:
                            continue
                        if canonical_call(node, aliases) not in (
                                "json.load", "json.loads"):
                            continue
                        arg = node.args[0]
                        if _has_marker(arg, tainted | handles):
                            yield _finding(
                                "torn-read-protocol", sf.path, node.lineno,
                                f"state/progress snapshot parsed with a "
                                f"raw json parse — a torn or corrupt "
                                f"file crash-loops the resume path; "
                                f"route through {TOLERANT_READER} "
                                f"(missing/torn/corrupt degrades to the "
                                f"caller's default)",
                            )


# ---------------------------------------------------- resume-commit-order --

def _is_commit_call(call: ast.Call) -> bool:
    last = (call_name(call) or "").rsplit(".", 1)[-1]
    segs = set(_segments(last))
    if {"atomic", "write"} <= segs:
        return True
    if {"save", "state"} <= segs:
        return True
    return "progress" in segs and ("write" in segs or "record" in segs)


def _is_result_write(call: ast.Call) -> bool:
    return isinstance(call.func, ast.Attribute) \
        and call.func.attr in ("write", "writelines")


@register_conc_rule(
    "resume-commit-order", "error",
    "a result row written after the last atomic state commit of its "
    "scope — a crash in that gap loses the row while the committed "
    "state claims it was emitted",
)
def check_resume_commit_order(cc: ConcContext) -> Iterable[Finding]:
    for sf in cc.context.files:
        if _blessed(sf, BLESSED_READ_MODULES):
            continue
        for _scope, body in scopes(sf.tree):
            walk = ScopeWalk(body)
            commits = [c for c in walk.calls if _is_commit_call(c.node)]
            if not commits:
                continue
            for w in walk.calls:
                if not _is_result_write(w.node):
                    continue
                covered = any(c.order > w.order
                              and compatible(c.branch, w.branch)
                              for c in commits)
                if not covered:
                    yield _finding(
                        "resume-commit-order", sf.path, w.node.lineno,
                        "result written after the scope's last atomic "
                        "state commit — the at-least-once ordering is "
                        "effects first, commit last (a crash in the gap "
                        "then re-emits instead of silently losing the "
                        "row); move the write before the commit",
                    )


# ----------------------------------------------------------------- runner --

def run_conc_rules(cc: ConcContext,
                   rules: Optional[Iterable[str]] = None) -> List[Finding]:
    if rules is None:
        selected: Tuple[str, ...] = tuple(sorted(CONC_RULES))
    else:
        selected = tuple(dict.fromkeys(rules))
    unknown = [r for r in selected if r not in CONC_RULES]
    if unknown:
        raise ValueError(
            f"unknown conc rule(s) {unknown}; "
            f"available: {sorted(CONC_RULES)}")
    findings: List[Finding] = []
    for name in selected:
        findings.extend(CONC_RULES[name].check(cc))
    return findings
