"""Seeded schedule-perturbation hooks — the runtime half of the conc audit.

The static rules claim the serve pump and the stream scorer hold their
invariants (FIFO order, ``--max-wait-ms`` deadline, exactly-once drift
folds) under *any* interleaving.  CPython's scheduler on an idle CI box
explores almost none of them: the producer enqueues everything before
the consumer wakes, commits never land mid-drain, and the tests pass by
accident of timing.  This module plants named perturbation points at
the seams (pump enqueue/dequeue, flush result/commit) that are free
no-ops in production and, when armed with a seed, inject small
*deterministic* sleeps — same seed, same delay sequence — so tier-1 can
drive adversarial schedules reproducibly on CPU.

Arming, either way:

- env: ``APNEA_UQ_PERTURB=<seed>`` (+ optional
  ``APNEA_UQ_PERTURB_MAX_MS``, default 5) — lets bench/watch runs flip
  it on without code changes;
- code: :func:`configure` from a test, :func:`disable` to tear down.

Delays are derived per (seed, point, hit-count) via blake2b, so they do
not depend on wall-clock, thread identity, or import order.  Jax-free
by construction.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, Optional

ENV_SEED = "APNEA_UQ_PERTURB"
ENV_MAX_MS = "APNEA_UQ_PERTURB_MAX_MS"
DEFAULT_MAX_MS = 5.0


class _Perturber:
    """One process-wide perturbation state: seed, delay ceiling, and a
    per-point hit counter (the counter is what makes the delay sequence
    a pure function of the schedule, not of wall-clock)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seed: Optional[str] = None
        self._max_ms: float = DEFAULT_MAX_MS
        self._hits: Dict[str, int] = {}
        self._env_checked = False

    def configure(self, seed: str, max_delay_ms: float = DEFAULT_MAX_MS) -> None:
        with self._lock:
            self._seed = str(seed)
            self._max_ms = float(max_delay_ms)
            self._hits = {}
            self._env_checked = True

    def disable(self) -> None:
        with self._lock:
            self._seed = None
            self._hits = {}
            self._env_checked = True

    def _maybe_load_env(self) -> None:
        # Read-only env probe, once; arming from the environment keeps
        # library code free of os.environ writes (the conc rule's whole
        # point).
        if self._env_checked:
            return
        self._env_checked = True
        seed = os.environ.get(ENV_SEED)
        if seed:
            self._seed = seed
            try:
                self._max_ms = float(os.environ.get(ENV_MAX_MS, DEFAULT_MAX_MS))
            except ValueError:
                self._max_ms = DEFAULT_MAX_MS

    def delay_for(self, point: str) -> float:
        """The sleep (seconds) this hit of `point` gets; 0.0 when disarmed."""
        with self._lock:
            self._maybe_load_env()
            if self._seed is None or self._max_ms <= 0:
                return 0.0
            n = self._hits.get(point, 0)
            self._hits[point] = n + 1
            digest = hashlib.blake2b(
                f"{self._seed}:{point}:{n}".encode("utf-8"),
                digest_size=8).digest()
            frac = int.from_bytes(digest, "big") / 2.0 ** 64
            return frac * self._max_ms / 1000.0

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)


_PERTURBER = _Perturber()


def perturb_point(point: str) -> None:
    """Named scheduling seam.  Free no-op unless armed; armed, sleeps a
    deterministic seed-derived duration (< max_delay_ms) so concurrent
    code explores a different — but reproducible — interleaving."""
    delay = _PERTURBER.delay_for(point)
    if delay > 0.0:
        time.sleep(delay)


def configure(seed: str, max_delay_ms: float = DEFAULT_MAX_MS) -> None:
    """Arm perturbation for this process (tests call this directly)."""
    _PERTURBER.configure(seed, max_delay_ms)


def disable() -> None:
    """Disarm and reset hit counters (test teardown)."""
    _PERTURBER.disable()


def point_hits(point: str) -> int:
    """How many times a point fired since arming (test introspection)."""
    return _PERTURBER.hits(point)
