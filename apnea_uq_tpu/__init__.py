"""apnea_uq_tpu — TPU-native sleep-apnea uncertainty-quantification framework.

A ground-up JAX/XLA/Flax re-design of the capabilities of
``TrondVQ/UncertaintyQuantification-SleepApnea-1DCNN`` (a Keras/TF research
pipeline): SHHS2 ingestion, the Alarcón 1D-CNN apnea classifier, MC-Dropout
and Deep-Ensemble uncertainty quantification with total/aleatoric/epistemic
decomposition, bootstrap confidence intervals, and patient/window-level
analysis — all built TPU-first:

- the model and every UQ metric run on device under ``jit``;
- MC Dropout's T stochastic passes are a ``vmap`` over dropout RNG keys
  (reference: a Python loop of full-set passes, uq_techniques.py:22);
- Deep-Ensemble members train concurrently on an ``(ensemble, data)``
  ``jax.sharding.Mesh`` axis (reference: a sequential Python loop,
  train_deep_ensemble_cnns.py:125-177);
- the bootstrap CI engine is one vectorized gather+reduce (reference:
  a B×Python-loop recompute, uq_techniques.py:137-165).

Subpackages
-----------
- ``models``     — Flax model definitions (Alarcón 1D-CNN and variants)
- ``ops``        — low-level device ops (entropy, losses)
- ``training``   — train states, single-model trainer, early stopping
- ``uq``         — MC-Dropout / Deep-Ensemble prediction, UQ metric engine,
                   vectorized bootstrap, orchestration
- ``evaluation`` — classification metric suite
- ``cli``        — command-line entry points, one per pipeline stage
- ``utils``      — PRNG, timing, small helpers
"""

__version__ = "0.1.0"
