"""`apnea-uq lint` core: files, suppressions, the rule registry, the runner.

The hazards that actually corrupt a JAX/TPU run — PRNG key reuse that
silently correlates stochastic passes, reads of donated buffers, host
syncs inside the telemetry layer's timed windows, retrace storms — only
surface as wrong numbers or telemetry anomalies *after* an expensive
device run.  This engine makes them a static, pre-run exit code instead:
an AST walk over the package (plus ``bench.py``), a registry of rules
(:mod:`apnea_uq_tpu.lint.rules`), inline suppressions that *require* a
written justification, and text/JSON reporters behind
``apnea-uq lint [paths] [--json] [--rule ...]``.

Deliberately **jax-free**: the linter parses source, it never imports the
code under analysis, so it runs anywhere tier-1 runs — including
machines where the TPU tunnel (or jax itself) is unusable.  A test pins
this by poisoning ``jax``/``flax`` in ``sys.modules`` around a lint run.

Suppression syntax (both placements)::

    risky_call()  # apnea-lint: disable=prng-key-reuse -- chunk fold below
    # apnea-lint: disable=host-sync-in-timed-region -- indices must be host
    idx = np.asarray(device_perm)

A trailing comment suppresses its own line; a standalone comment
suppresses the next code line.  The justification after ``--`` is
mandatory: a bare ``disable=`` does not suppress (the finding stands,
annotated), so every exemption in the tree explains itself.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Callable, Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warning")

# `# apnea-lint: disable=rule-a,rule-b -- why this is fine here`
_SUPPRESS_RE = re.compile(
    r"#\s*apnea-lint:\s*disable=([a-z0-9\-,\s]+?)\s*(?:--\s*(\S.*))?$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit, suppressed or not (suppressed hits stay reportable
    so ``--json`` output shows the full audit trail, but only
    unsuppressed ones fail the run)."""

    rule: str
    severity: str
    path: str           # repo-root-relative display path
    line: int
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def render(self) -> str:
        tag = f"{self.path}:{self.line}: [{self.rule}] {self.severity}"
        text = f"{tag}: {self.message}"
        if self.suppressed:
            text += f"  (suppressed: {self.justification})"
        return text


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: Tuple[str, ...]
    justification: Optional[str]
    comment_line: int


@dataclasses.dataclass
class SourceFile:
    """One parsed file: AST plus the line->suppression map."""

    path: str                   # display path (repo-root relative)
    abspath: str
    text: str
    tree: ast.Module
    suppressions: Dict[int, List[Suppression]]


@dataclasses.dataclass
class LintContext:
    """Everything a rule sees: the parsed in-scope files and the repo
    root (rules that cross-check docs — the telemetry schema rule —
    resolve ``docs/*.md`` against it)."""

    files: List[SourceFile]
    repo_root: str

    def file_named(self, suffix: str) -> Optional[SourceFile]:
        """The scanned file whose path ends with ``suffix`` (posix-style),
        or None when it is out of scope."""
        norm = suffix.replace(os.sep, "/")
        for f in self.files:
            if f.path.replace(os.sep, "/").endswith(norm):
                return f
        return None


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    severity: str
    summary: str
    check: Callable[[LintContext], Iterable[Finding]]


# Populated by @register_rule at apnea_uq_tpu.lint.rules import time.
RULES: Dict[str, Rule] = {}


def register_rule(name: str, severity: str, summary: str):
    """Decorator: register ``check(context) -> iterable[Finding]`` under
    ``name``.  Rules construct findings via :func:`make_finding` so the
    severity never drifts from the registration."""
    if severity not in SEVERITIES:
        raise ValueError(f"severity must be one of {SEVERITIES}, got {severity!r}")

    def wrap(fn: Callable[[LintContext], Iterable[Finding]]) -> Rule:
        rule = Rule(name=name, severity=severity, summary=summary, check=fn)
        RULES[name] = rule
        return fn

    return wrap


def make_finding(rule: str, path: str, line: int, message: str) -> Finding:
    return Finding(rule=rule, severity=RULES[rule].severity, path=path,
                   line=int(line), message=message)


# ------------------------------------------------------------ suppressions --

def _code_lines(tokens) -> List[int]:
    """Line numbers that carry actual code tokens (suppression comments on
    their own line attach to the next one of these)."""
    skip = {tokenize.COMMENT, tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
            tokenize.DEDENT, tokenize.ENCODING, tokenize.ENDMARKER}
    return sorted({t.start[0] for t in tokens if t.type not in skip})


def parse_suppressions(text: str) -> Dict[int, List[Suppression]]:
    """``{code_line: [Suppression, ...]}`` for one file.

    Trailing comments bind to their own line; standalone comments bind to
    the next code line (so a suppression can sit above a long call).
    """
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {}
    code_lines = _code_lines(tokens)
    out: Dict[int, List[Suppression]] = {}
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = tuple(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        justification = m.group(2).strip() if m.group(2) else None
        standalone = not tok.line[: tok.start[1]].strip()
        if standalone:
            target = next(
                (ln for ln in code_lines if ln > tok.start[0]), None
            )
        else:
            target = tok.start[0]
        if target is None:
            continue
        out.setdefault(target, []).append(
            Suppression(rules=rules, justification=justification,
                        comment_line=tok.start[0])
        )
    return out


def apply_suppressions(finding: Finding, sf: SourceFile) -> Finding:
    """Resolve one finding against its file's suppression map: a justified
    match suppresses; an unjustified match leaves the finding standing,
    annotated — the 'missing justification = finding' contract."""
    for sup in sf.suppressions.get(finding.line, []):
        if finding.rule not in sup.rules and "all" not in sup.rules:
            continue
        if sup.justification:
            return dataclasses.replace(
                finding, suppressed=True, justification=sup.justification
            )
        return dataclasses.replace(
            finding,
            message=(finding.message
                     + "  [suppression comment lacks a justification: use "
                       "`# apnea-lint: disable=" + finding.rule
                     + " -- <why>`]"),
        )
    return finding


# ------------------------------------------------------------------ runner --

def _iter_py_files(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                files.extend(
                    os.path.join(root, n) for n in sorted(names)
                    if n.endswith(".py")
                )
        elif p.endswith(".py"):
            files.append(p)
        else:
            raise FileNotFoundError(f"lint path is neither a directory nor "
                                    f"a .py file: {p}")
    # De-duplicate while keeping order (a dir plus a file inside it).
    seen, unique = set(), []
    for f in files:
        if f not in seen:
            seen.add(f)
            unique.append(f)
    return unique


def default_repo_root(paths: Iterable[str]) -> str:
    """Best-effort repo root: the parent of the first scanned
    ``apnea_uq_tpu`` package directory, else the common parent."""
    abspaths = [os.path.abspath(p) for p in paths]
    for p in abspaths:
        parts = p.replace(os.sep, "/").split("/")
        if "apnea_uq_tpu" in parts:
            idx = parts.index("apnea_uq_tpu")
            return os.sep.join(parts[:idx]) or os.sep
    first = abspaths[0]
    return first if os.path.isdir(first) else os.path.dirname(first)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files_scanned: int
    rules_run: Tuple[str, ...]
    # Repo-root-relative paths actually scanned: lets callers (e.g. the
    # tier-1 gate) pin that a module has not silently MOVED out of the
    # lint's scope — the rglob covers new files implicitly, which also
    # means a relocated one leaves coverage without any test failing.
    scanned_paths: Tuple[str, ...] = ()

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]


def load_files(paths: Iterable[str], repo_root: str) -> List[SourceFile]:
    out: List[SourceFile] = []
    for abspath in _iter_py_files(paths):
        # Explicit UTF-8: the linter must behave identically under a
        # C-locale CI container, where the default codec would choke on
        # the package's own docstrings.
        with open(abspath, encoding="utf-8") as fh:
            text = fh.read()
        rel = os.path.relpath(abspath, repo_root)
        tree = ast.parse(text, filename=abspath)  # SyntaxError propagates
        out.append(SourceFile(
            path=rel, abspath=abspath, text=text, tree=tree,
            suppressions=parse_suppressions(text),
        ))
    return out


def run_lint(paths: Iterable[str], *, rules: Optional[Iterable[str]] = None,
             repo_root: Optional[str] = None) -> LintResult:
    """Run the (selected) rules over ``paths``; findings come back sorted
    by (path, line, rule) with suppressions already resolved."""
    from apnea_uq_tpu.lint import rules as _rules_pkg  # registers RULES

    del _rules_pkg
    paths = list(paths)
    if not paths:
        raise ValueError("run_lint needs at least one path")
    if repo_root is None:
        repo_root = default_repo_root(paths)
    if rules is None:
        selected = tuple(sorted(RULES))
    else:
        # Order-preserving dedupe: `--rule x --rule x` (easy via CI
        # templates that append flags) must not double every finding.
        selected = tuple(dict.fromkeys(rules))
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; available: {sorted(RULES)}"
        )
    files = load_files(paths, repo_root)
    context = LintContext(files=files, repo_root=repo_root)
    by_path = {f.path: f for f in files}
    findings: List[Finding] = []
    for name in selected:
        for finding in RULES[name].check(context):
            sf = by_path.get(finding.path)
            if sf is not None:
                finding = apply_suppressions(finding, sf)
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return LintResult(findings=findings, files_scanned=len(files),
                      rules_run=selected,
                      scanned_paths=tuple(f.path for f in files))
