"""Shared AST plumbing for the lint rules: scope-linear walks with
branch signatures and loop ancestry.

The correctness rules all reason the same way: *within one function
scope, in source order, did X happen between/inside Y?*  This module
gives them that spine once:

- :func:`scopes` — every function body (plus the module body) as its own
  scope; nested functions are excluded from their parent's walk so a
  closure's key use never aliases its enclosing function's.
- :class:`ScopeWalk` — calls and name-bindings of one scope in execution
  order, each tagged with a **branch signature** (which arm of which
  ``if``/``try``/``match`` it sits in — two calls in *exclusive* arms
  never conflict) and the stack of enclosing loops (a consumer inside a
  loop repeats per iteration).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.random.split' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def names_in(node: ast.AST) -> Tuple[str, ...]:
    """Every Name identifier referenced anywhere in an expression."""
    return tuple(sorted({
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    }))


def scopes(tree: ast.Module) -> Iterator[Tuple[Optional[ast.AST], List[ast.stmt]]]:
    """(scope_node, body) for the module and every (nested) function.
    scope_node is None for the module body."""
    yield None, list(tree.body)
    for node in ast.walk(tree):
        if isinstance(node, _FUNCTION_NODES):
            yield node, list(node.body)


# Branch signature: ((branch_node_id, arm_index), ...) innermost-last.
BranchSig = Tuple[Tuple[int, int], ...]


def compatible(a: BranchSig, b: BranchSig) -> bool:
    """True unless a and b sit in *different* arms of the same branch
    node — only then can the two events never occur in one execution."""
    arms_a = dict(a)
    for node_id, arm in b:
        if node_id in arms_a and arms_a[node_id] != arm:
            return False
    return True


@dataclasses.dataclass(frozen=True)
class CallSite:
    node: ast.Call
    order: int
    branch: BranchSig
    loops: Tuple[int, ...]        # ids of enclosing For/While, outermost first
    stmt: ast.stmt                # the statement the call executes in


@dataclasses.dataclass(frozen=True)
class Binding:
    name: str
    order: int
    branch: BranchSig
    loops: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class LoadSite:
    name: str
    order: int
    branch: BranchSig
    loops: Tuple[int, ...]
    node: ast.Name
    stmt: ast.stmt


class ScopeWalk:
    """Execution-ordered calls and name bindings of ONE scope body.

    Nested function/class bodies are not descended into (they are their
    own scopes); lambda bodies and comprehensions stay in this scope —
    they execute inline.  Binding records for a statement are emitted
    *after* the calls in its value, matching evaluation order (so
    ``k = fold_in(k, i)`` reads the old ``k`` before rebinding it).
    """

    def __init__(self, body: List[ast.stmt]):
        self.calls: List[CallSite] = []
        self.bindings: List[Binding] = []
        self.loads: List[LoadSite] = []
        self.loop_bodies: Dict[int, List[Binding]] = {}
        self._order = 0
        self._walk_body(body, (), ())

    # -- recording ---------------------------------------------------------

    def _next(self) -> int:
        self._order += 1
        return self._order

    def _record_expr(self, node: Optional[ast.AST], branch: BranchSig,
                     loops: Tuple[int, ...], stmt: ast.stmt) -> None:
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, _FUNCTION_NODES + (ast.ClassDef,)):
                # own scope; but its *name* is a binding here, handled by
                # the statement walk (defs are statements, not exprs)
                continue
            if isinstance(sub, ast.Call):
                self._add_call(sub, branch, loops, stmt)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                self.loads.append(LoadSite(
                    name=sub.id, order=self._next(), branch=branch,
                    loops=loops, node=sub, stmt=stmt,
                ))
            elif isinstance(sub, ast.NamedExpr):
                self._bind_target(sub.target, branch, loops)
            elif isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                                  ast.GeneratorExp)):
                for gen in sub.generators:
                    self._bind_target(gen.target, branch, loops)

    def _add_call(self, call: ast.Call, branch: BranchSig,
                  loops: Tuple[int, ...], stmt: ast.stmt) -> None:
        site = CallSite(node=call, order=self._next(), branch=branch,
                        loops=loops, stmt=stmt)
        self.calls.append(site)

    def _bind_target(self, target: ast.AST, branch: BranchSig,
                     loops: Tuple[int, ...]) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                b = Binding(name=sub.id, order=self._next(), branch=branch,
                            loops=loops)
                self.bindings.append(b)
                for loop_id in loops:
                    self.loop_bodies.setdefault(loop_id, []).append(b)

    # -- statement walk ----------------------------------------------------

    def _walk_body(self, body: List[ast.stmt], branch: BranchSig,
                   loops: Tuple[int, ...]) -> None:
        for stmt in body:
            self._walk_stmt(stmt, branch, loops)

    def _walk_stmt(self, stmt: ast.stmt, branch: BranchSig,
                   loops: Tuple[int, ...]) -> None:
        if isinstance(stmt, _FUNCTION_NODES + (ast.ClassDef,)):
            # Decorators/defaults evaluate in THIS scope; the body doesn't.
            for dec in getattr(stmt, "decorator_list", []):
                self._record_expr(dec, branch, loops, stmt)
            args = getattr(stmt, "args", None)
            if args is not None:
                for default in list(args.defaults) + [
                        d for d in args.kw_defaults if d is not None]:
                    self._record_expr(default, branch, loops, stmt)
            self._bind_target(ast.Name(id=stmt.name, ctx=ast.Store()),
                              branch, loops)
            return
        if isinstance(stmt, ast.Assign):
            self._record_expr(stmt.value, branch, loops, stmt)
            for t in stmt.targets:
                self._bind_target(t, branch, loops)
            return
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            self._record_expr(stmt.value, branch, loops, stmt)
            self._bind_target(stmt.target, branch, loops)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._record_expr(stmt.iter, branch, loops, stmt)
            inner = loops + (id(stmt),)
            self.loop_bodies.setdefault(id(stmt), [])
            self._bind_target(stmt.target, branch, inner)
            self._walk_body(stmt.body, branch, inner)
            self._walk_body(stmt.orelse, branch, loops)
            return
        if isinstance(stmt, ast.While):
            inner = loops + (id(stmt),)
            self.loop_bodies.setdefault(id(stmt), [])
            self._record_expr(stmt.test, branch, inner, stmt)
            self._walk_body(stmt.body, branch, inner)
            self._walk_body(stmt.orelse, branch, loops)
            return
        if isinstance(stmt, ast.If):
            self._record_expr(stmt.test, branch, loops, stmt)
            self._walk_body(stmt.body, branch + ((id(stmt), 0),), loops)
            self._walk_body(stmt.orelse, branch + ((id(stmt), 1),), loops)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._record_expr(item.context_expr, branch, loops, stmt)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars, branch, loops)
            self._walk_body(stmt.body, branch, loops)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, branch + ((id(stmt), 0),), loops)
            for i, handler in enumerate(stmt.handlers):
                if handler.name:
                    self._bind_target(
                        ast.Name(id=handler.name, ctx=ast.Store()),
                        branch + ((id(stmt), i + 1),), loops)
                self._walk_body(handler.body,
                                branch + ((id(stmt), i + 1),), loops)
            self._walk_body(stmt.orelse, branch + ((id(stmt), 0),), loops)
            self._walk_body(stmt.finalbody, branch, loops)
            return
        if isinstance(stmt, ast.Match):
            self._record_expr(stmt.subject, branch, loops, stmt)
            for i, case in enumerate(stmt.cases):
                self._walk_body(case.body, branch + ((id(stmt), i),), loops)
            return
        # Expr / Return / Raise / Assert / Delete / Global / Import / ...
        for field in ast.iter_child_nodes(stmt):
            if isinstance(field, ast.expr):
                self._record_expr(field, branch, loops, stmt)

    # -- queries -----------------------------------------------------------

    def bindings_between(self, names: Tuple[str, ...], start: int,
                         end: int) -> List[Binding]:
        wanted = set(names)
        return [b for b in self.bindings
                if b.name in wanted and start < b.order <= end]

    def loop_binds(self, loop_id: int, names: Tuple[str, ...]) -> bool:
        wanted = set(names)
        return any(b.name in wanted for b in self.loop_bodies.get(loop_id, []))

    def stmt_targets(self, stmt: ast.stmt) -> Tuple[str, ...]:
        """Plain names the statement (re)binds — used to clear taint for
        ``x, y = f(x, ...)`` in the same statement as the call."""
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = [stmt.target]
        names = []
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    names.append(sub.id)
        return tuple(names)


def module_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """Top-level function defs by name (for cross-function follows)."""
    return {
        node.name: node for node in tree.body
        if isinstance(node, _FUNCTION_NODES)
    }


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local-name -> canonical dotted module/function path for every
    import in the module (``import numpy as np`` -> {'np': 'numpy'};
    ``from jax import random`` -> {'random': 'jax.random'})."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for alias in node.names:
                out[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return out


def canonical_call(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """The call's dotted name with its leading segment resolved through
    the module's imports: ``jr.split`` -> ``jax.random.split``."""
    name = call_name(call)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    resolved = aliases.get(head, head)
    return f"{resolved}.{rest}" if rest else resolved
