"""``apnea-uq lint`` — AST rule engine for JAX/TPU correctness hazards.

Static guards for the failure modes that otherwise only surface as wrong
numbers or telemetry anomalies after an expensive device run: PRNG key
reuse (correlated MCD/DE streams), reads of donated buffers, host syncs
inside the telemetry layer's timed windows, jit retrace hazards, drift
between emitted telemetry events and ``docs/OBSERVABILITY.md``, and bare
``print`` calls.

Jax-free by design (pure ``ast``/``tokenize``), so it runs anywhere
tier-1 runs.  Public surface:

- :func:`apnea_uq_tpu.lint.engine.run_lint` — programmatic entry;
- :mod:`apnea_uq_tpu.lint.cli` — the ``apnea-uq lint`` subcommand;
- ``docs/LINT.md`` — the rule catalog and suppression syntax.
"""

from apnea_uq_tpu.lint.engine import (  # noqa: F401
    Finding,
    LintResult,
    RULES,
    run_lint,
)
from apnea_uq_tpu.lint.report import render_json, render_text, result_data  # noqa: F401
