"""Text and JSON reporters for lint results.

Both render the same resolved findings; ``--json`` is the machine side
(stable field set, sorted — the golden test pins it) and the text side
is the human one, grouped per file with a one-line summary.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from apnea_uq_tpu.lint.engine import LintResult


def result_data(result: LintResult) -> Dict[str, Any]:
    """The ``--json`` document: every finding (suppressed included, so
    the suppression audit trail is machine-readable) plus the summary."""
    findings: List[Dict[str, Any]] = [
        {
            "rule": f.rule,
            "severity": f.severity,
            "path": f.path.replace("\\", "/"),
            "line": f.line,
            "message": f.message,
            "suppressed": f.suppressed,
            "justification": f.justification,
        }
        for f in result.findings
    ]
    return {
        "findings": findings,
        "summary": {
            "files_scanned": result.files_scanned,
            "rules_run": list(result.rules_run),
            "findings": len(result.findings),
            "suppressed": sum(1 for f in result.findings if f.suppressed),
            "unsuppressed": len(result.unsuppressed),
        },
    }


def render_json(result: LintResult) -> str:
    return json.dumps(result_data(result), indent=2, sort_keys=False)


def render_text(result: LintResult) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f.render())
    n_sup = sum(1 for f in result.findings if f.suppressed)
    lines.append(
        f"{result.files_scanned} file(s), {len(result.rules_run)} rule(s): "
        f"{len(result.unsuppressed)} finding(s), {n_sup} suppressed"
    )
    return "\n".join(lines)
