"""Text, JSON, and GitHub-annotation reporters for lint/audit results.

All three render the same resolved findings: ``--json`` is the machine
side (stable field set, (path, line, rule) sort order, per-rule count
summary — the golden test pins it, so CI diffs are deterministic),
``--format gha`` emits GitHub Actions ``::error``/``::warning``
workflow-command lines (one per unsuppressed finding, so violations
annotate the PR diff inline), and the text side is the human one with a
one-line summary.  The audit subcommand shares every reporter — its
findings are the same :class:`Finding` type anchored at the
zoo-registration site.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from apnea_uq_tpu.lint.engine import LintResult


def result_data(result: LintResult) -> Dict[str, Any]:
    """The ``--json`` document: every finding (suppressed included, so
    the suppression audit trail is machine-readable) plus the summary —
    findings in (path, line, rule) order and a ``by_rule`` count block
    covering every rule that ran (zero counts included), so two runs
    over the same tree always diff clean."""
    findings: List[Dict[str, Any]] = [
        {
            "rule": f.rule,
            "severity": f.severity,
            "path": f.path.replace("\\", "/"),
            "line": f.line,
            "message": f.message,
            "suppressed": f.suppressed,
            "justification": f.justification,
        }
        for f in sorted(result.findings,
                        key=lambda f: (f.path, f.line, f.rule, f.message))
    ]
    by_rule = {
        rule: {"findings": 0, "suppressed": 0, "unsuppressed": 0}
        for rule in sorted(result.rules_run)
    }
    for f in result.findings:
        row = by_rule.setdefault(
            f.rule, {"findings": 0, "suppressed": 0, "unsuppressed": 0})
        row["findings"] += 1
        row["suppressed" if f.suppressed else "unsuppressed"] += 1
    return {
        "findings": findings,
        "summary": {
            "files_scanned": result.files_scanned,
            "rules_run": list(result.rules_run),
            "findings": len(result.findings),
            "suppressed": sum(1 for f in result.findings if f.suppressed),
            "unsuppressed": len(result.unsuppressed),
            "by_rule": dict(sorted(by_rule.items())),
        },
    }


def render_json(result: LintResult) -> str:
    return json.dumps(result_data(result), indent=2, sort_keys=False)


def render_text(result: LintResult, *, subject: str = "file(s)") -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f.render())
    n_sup = sum(1 for f in result.findings if f.suppressed)
    lines.append(
        f"{result.files_scanned} {subject}, {len(result.rules_run)} "
        f"rule(s): {len(result.unsuppressed)} finding(s), "
        f"{n_sup} suppressed"
    )
    return "\n".join(lines)


def _gha_escape(value: str, *, prop: bool = False) -> str:
    """GitHub workflow-command escaping: data %-escapes newlines;
    property values additionally escape ``:`` and ``,``."""
    value = (value.replace("%", "%25")
             .replace("\r", "%0D").replace("\n", "%0A"))
    if prop:
        value = value.replace(":", "%3A").replace(",", "%2C")
    return value


def render_gha(result: LintResult) -> str:
    """One ``::error``/``::warning`` annotation line per *unsuppressed*
    finding (suppressed findings are resolved exemptions — annotating
    them would bury real violations in a PR's checks tab).  Empty string
    when the run is clean."""
    lines: List[str] = []
    for f in result.findings:
        if f.suppressed:
            continue
        command = "error" if f.severity == "error" else "warning"
        path = _gha_escape(f.path.replace("\\", "/"), prop=True)
        title = _gha_escape(f.rule, prop=True)
        lines.append(
            f"::{command} file={path},line={f.line},title={title}"
            f"::{_gha_escape(f.message)}"
        )
    return "\n".join(lines)


# -------------------------------------------- shared CLI output contract --

def add_format_args(parser) -> None:
    """The output-format options both gates (``lint`` and ``audit``)
    share — one definition, so the two CLIs cannot drift."""
    parser.add_argument("--json", action="store_true",
                        help="Emit findings machine-readable (full audit "
                             "trail, suppressed findings included).")
    parser.add_argument("--format", choices=("text", "json", "gha"),
                        default="text",
                        help="Output format; `gha` emits GitHub Actions "
                             "::error/::warning annotation lines for "
                             "inline PR review (shared by `apnea-uq "
                             "lint` and `apnea-uq audit`).")


def resolve_format(args) -> str:
    """The effective format of a parsed gate invocation: an explicit
    ``--format gha`` wins, then ``--json``/``--format json``, else text."""
    if args.format == "gha":
        return "gha"
    if args.json or args.format == "json":
        return "json"
    return "text"


def emit_result(result: LintResult, fmt: str, *, subject: str = "file(s)",
                json_extra=None) -> None:
    """Render ``result`` in ``fmt`` through ``telemetry.log`` — the one
    dispatch both gates use.  ``json_extra`` merges extra top-level keys
    into the ``--json`` document (the audit's per-program cost facts);
    gha emits nothing at all on a clean tree (GitHub renders every
    stdout line that parses as a command — silence is green)."""
    from apnea_uq_tpu.telemetry import log

    if fmt == "json":
        doc = result_data(result)
        if json_extra:
            doc.update(json_extra)
        log(json.dumps(doc, indent=2, sort_keys=False))
    elif fmt == "gha":
        rendered = render_gha(result)
        if rendered:
            log(rendered)
    else:
        log(render_text(result, subject=subject))
