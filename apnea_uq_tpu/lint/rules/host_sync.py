"""host-sync-in-timed-region: device/host round-trips inside the
telemetry layer's honest-timing windows.

``StepMetrics.measure`` times a thunk twice — dispatch (call return) and
device (``block_until_ready`` on the result) — and that decomposition is
the whole point of the telemetry layer: the gap is what async dispatch
hides.  A host sync *inside* the thunk (``.item()``/``.tolist()``,
``float()``/``int()`` on a device array, ``np.asarray``/``np.array`` —
however the import is spelled, ``from numpy import asarray`` included —
``jax.device_get`` and its from-import aliases, an inner
``block_until_ready``, the repo's ``host_values`` helper) serializes the
device work mid-window, double-counts it into dispatch time, and makes
``dispatch_s`` vs ``device_s`` lie.  The same applies to
``Timer(block=True)`` bodies, whose contract is one block at ``__exit__``.

Scope: thunks passed to ``<StepMetrics instance>.measure(label, thunk)``
where the receiver is assigned from ``StepMetrics(...)`` in the same
module, and ``with Timer(..., block=True)`` bodies.  Lambda thunks are
scanned directly; named thunks resolve to function defs in the same
module and the scan follows further same-module calls two levels deep —
enough to reach the streamed-epoch helpers the trainers actually
dispatch through, without whole-program call-graph analysis.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apnea_uq_tpu.lint import astwalk
from apnea_uq_tpu.lint.engine import Finding, LintContext, make_finding, register_rule

_FOLLOW_DEPTH = 2

# Numpy module spellings that force a device->host copy via asarray/array.
_NUMPY_MODULES = {"numpy"}
# Canonical (post-alias-resolution) names that sync regardless of how the
# import was spelled — `jr = jax`, `from numpy import asarray as aa`,
# `from jax import device_get as dg` all resolve here through
# canonical_call, covering the aliased-import escapes the
# module-attribute check above cannot see.
_CANONICAL_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "jax.device_get",
    "jax.block_until_ready",
}
_HOST_VALUE_HELPERS = {"host_values", "_host_values", "_host_predictions"}


def _numpy_aliases(aliases: Dict[str, str]) -> Set[str]:
    return {local for local, full in aliases.items() if full in _NUMPY_MODULES}


def _sync_reason(call: ast.Call, aliases: Dict[str, str],
                 np_names: Set[str]) -> Optional[str]:
    """Why this call is a host sync, or None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        if (func.attr in ("item", "tolist") and not call.args
                and not call.keywords):
            return f".{func.attr}() forces a device->host transfer"
        if func.attr == "block_until_ready":
            return ".block_until_ready() serializes the dispatch stream"
        if (isinstance(func.value, ast.Name) and func.value.id in np_names
                and func.attr in ("asarray", "array")):
            return (f"{func.value.id}.{func.attr}(...) copies the device "
                    f"array to host")
    name = astwalk.canonical_call(call, aliases)
    if name in _CANONICAL_SYNC_CALLS:
        if name.startswith("numpy."):
            return f"{name}(...) copies the device array to host"
        return f"{name}(...) blocks on device work"
    if name in _HOST_VALUE_HELPERS or (
            name is not None and name.split(".")[-1] in _HOST_VALUE_HELPERS):
        return "host_values(...) fetches device shards to host"
    if isinstance(func, ast.Name) and func.id in ("float", "int") \
            and len(call.args) == 1 and not call.keywords:
        arg = call.args[0]
        # float(x.shape[0]) / int(len(...)) are host-side already; only a
        # Name or a Call result plausibly holds a device array.
        if isinstance(arg, ast.Name):
            return (f"{func.id}({arg.id}) on a device array blocks until "
                    f"it is computed")
        if isinstance(arg, ast.Call):
            inner = astwalk.canonical_call(arg, aliases)
            if inner != "len" and not (inner or "").startswith("range"):
                return (f"{func.id}(...) on a call result blocks if it is "
                        f"a device array")
    return None


def _scan_region(sf, region: ast.AST, aliases, np_names,
                 module_fns: Dict[str, ast.AST], entered_at: int,
                 label: str, depth: int, visited: Set[int],
                 reported: Set[Tuple]) -> Iterator[Finding]:
    """Flag syncs in ``region`` and follow same-module callees."""
    callees: List[str] = []
    for node in ast.walk(region):
        if not isinstance(node, ast.Call):
            continue
        reason = _sync_reason(node, aliases, np_names)
        if reason is not None:
            mark = (sf.path, node.lineno)
            if mark not in reported:
                reported.add(mark)
                yield make_finding(
                    "host-sync-in-timed-region", sf.path, node.lineno,
                    f"{reason} inside the timed region entered at line "
                    f"{entered_at} ({label}) — it double-counts device "
                    f"work into the dispatch-side timing",
                )
        elif isinstance(node.func, ast.Name) and node.func.id in module_fns:
            callees.append((node.func.id, node.lineno))
    if depth <= 0:
        return
    for callee, use_line in callees:
        fn = _resolve_fn(module_fns, callee, use_line)
        if fn is None or id(fn) in visited:
            continue
        visited.add(id(fn))
        yield from _scan_region(
            sf, fn, aliases, np_names, module_fns, entered_at,
            f"{label} -> {callee}", depth - 1, visited, reported)


def _stepmetrics_receivers(tree: ast.Module, aliases) -> Set[str]:
    """Names assigned (anywhere in the module) from a StepMetrics(...)
    construction — the receivers whose .measure() defines a timed window."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        makes_metrics = any(
            isinstance(sub, ast.Call)
            and (astwalk.canonical_call(sub, aliases) or "").split(".")[-1]
            == "StepMetrics"
            for sub in ast.walk(node.value)
        )
        if not makes_metrics:
            continue
        for t in node.targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


def _local_functions(tree: ast.Module) -> Dict[str, List[ast.AST]]:
    """EVERY function def in the module by name (module-level and nested) —
    named thunks are usually closures right next to the measure call.
    Names can repeat across functions (every driver calls its closure
    ``thunk``), so each name keeps all defs, line-sorted."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    for defs in out.values():
        defs.sort(key=lambda d: d.lineno)
    return out


def _resolve_fn(module_fns: Dict[str, List[ast.AST]], name: str,
                use_line: int) -> Optional[ast.AST]:
    """The def a name most plausibly refers to at ``use_line``: the
    nearest preceding definition (Python closure semantics), else the
    first one (module-level helpers defined below their caller)."""
    defs = module_fns.get(name)
    if not defs:
        return None
    preceding = [d for d in defs if d.lineno <= use_line]
    return preceding[-1] if preceding else defs[0]


def _is_timing_timer(call: ast.Call, aliases) -> bool:
    """`Timer(..., block=True)` from utils.timing (threading.Timer never
    takes block=)."""
    name = astwalk.canonical_call(call, aliases)
    if name is None or name.split(".")[-1] != "Timer":
        return False
    if name.startswith("threading."):
        return False
    return any(kw.arg == "block" and isinstance(kw.value, ast.Constant)
               and kw.value.value is True for kw in call.keywords)


@register_rule(
    "host-sync-in-timed-region", "warning",
    "a host sync (.item()/.tolist(), float()/int() on arrays, "
    "np.asarray/np.array (aliased from-imports included), device_get, "
    "block_until_ready, host_values) inside a StepMetrics window or "
    "Timer(block=True) body corrupts the dispatch-vs-device timing the "
    "telemetry layer exists to measure",
)
def check(context: LintContext) -> Iterator[Finding]:
    for sf in context.files:
        aliases = astwalk.import_aliases(sf.tree)
        np_names = _numpy_aliases(aliases)
        module_fns = _local_functions(sf.tree)
        receivers = _stepmetrics_receivers(sf.tree, aliases)
        reported: Set[Tuple] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "measure":
                recv = node.func.value
                if not (isinstance(recv, ast.Name) and recv.id in receivers):
                    continue
                if len(node.args) < 2:
                    continue
                thunk = node.args[1]
                region: Optional[ast.AST] = None
                label = "StepMetrics.measure thunk"
                if isinstance(thunk, ast.Lambda):
                    region = thunk.body
                elif isinstance(thunk, ast.Name):
                    fn = _resolve_fn(module_fns, thunk.id, node.lineno)
                    if fn is not None:
                        region = fn
                        label = f"StepMetrics.measure thunk `{thunk.id}`"
                if region is None:
                    continue
                yield from _scan_region(
                    sf, region, aliases, np_names, module_fns,
                    node.lineno, label, _FOLLOW_DEPTH,
                    {id(region)}, reported)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ctx_expr = item.context_expr
                    if isinstance(ctx_expr, ast.Call) and _is_timing_timer(
                            ctx_expr, aliases):
                        for stmt in node.body:
                            yield from _scan_region(
                                sf, stmt, aliases, np_names, module_fns,
                                node.lineno, "Timer(block=True) body",
                                _FOLLOW_DEPTH, set(), reported)
