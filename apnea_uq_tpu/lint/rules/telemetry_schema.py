"""telemetry-event-schema: every event kind and field the code emits must
be declared in docs/OBSERVABILITY.md — and documented kinds must exist.

The telemetry layer's value is that ``events.jsonl`` is a *schema*, not a
printf stream: ``summarize``/``compare`` and the bench smoke test all key
on documented kinds and fields.  An undocumented field is a field those
consumers silently drop; an undocumented kind is a table the operator
cannot interpret.  The previous guard was a hand-rolled docs test; this
rule parses both sides — the doc's "Event kinds" bullet list and every
``<anything>.event("kind", field=...)`` call in scope — statically, with
no imports (so the ``telemetry.watch`` import-order workaround that
module loading once tripped over stays irrelevant here by construction).

What the field extractor resolves, per call: literal keyword arguments,
and ``**d`` splats where ``d`` is built in the same function from a dict
display, constant-key subscript assignments, and ``d.update({literal})``.
Dynamic extensions (``d.update(extra or {})``, f-string keys, splatting a
parameter) are untrackable statically and are skipped — the resolvable
keys are still checked.

The reverse (phantom) direction — a documented kind no code emits — runs
only when the scan scope contains the full emission universe (both
``telemetry/runlog.py`` and ``bench.py``); linting a single file must not
claim kinds emitted elsewhere are phantoms.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apnea_uq_tpu.lint.engine import Finding, LintContext, make_finding, register_rule

DOC_RELPATH = os.path.join("docs", "OBSERVABILITY.md")

# Envelope fields RunLog.event stamps on every record; `stage` is also a
# legal explicit kwarg (runlog.stage passes it) without per-kind mention.
_ENVELOPE_FIELDS = {"seq", "ts", "kind", "stage"}

_KIND_BULLET_RE = re.compile(r"^- \*\*(.+?)\*\*", re.M)
_BACKTICK_TOKEN_RE = re.compile(r"`([a-z][a-z0-9_]*)`")


def parse_documented_kinds(doc_text: str) -> Dict[str, Tuple[int, Set[str]]]:
    """{kind: (doc line, field tokens documented in its bullet)} from the
    "Event kinds" bullet list.  A bold header may name several kinds
    (``**`stage_start` / `stage_end`**``); they share the bullet body."""
    out: Dict[str, Tuple[int, Set[str]]] = {}
    lines = doc_text.splitlines()
    bullets: List[Tuple[int, str]] = []  # (start line idx, bullet text)
    current: Optional[List] = None
    for i, line in enumerate(lines):
        if _KIND_BULLET_RE.match(line):
            if current is not None:
                bullets.append((current[0], "\n".join(current[1])))
            current = [i, [line]]
        elif current is not None:
            if line.startswith(("  ", "\t")) or not line.strip():
                current[1].append(line)
            else:
                bullets.append((current[0], "\n".join(current[1])))
                current = None
    if current is not None:
        bullets.append((current[0], "\n".join(current[1])))
    for start, text in bullets:
        # A header may carry several bold segments ("**`probe`** /
        # **`probe_green`** / **`ritual_step`**") — every backticked
        # token inside ANY bold span of the bullet's first line is a
        # kind this bullet declares.
        first_line = text.lstrip("\n").splitlines()[0]
        kinds = [
            tok
            for bold in re.findall(r"\*\*(.+?)\*\*", first_line)
            for tok in _BACKTICK_TOKEN_RE.findall(bold)
        ]
        if not kinds:
            continue
        fields = set(_BACKTICK_TOKEN_RE.findall(text))
        for kind in kinds:
            # A kind may be described in several bullets (the event list
            # plus e.g. the HBM section's prose) — union the fields and
            # keep the first mention's line.
            if kind in out:
                line, existing = out[kind]
                out[kind] = (line, existing | fields)
            else:
                out[kind] = (start + 1, fields)
    return out


def _resolve_splat_keys(func: Optional[ast.AST], name: str) -> Set[str]:
    """Statically resolvable keys of ``**name`` inside ``func``: dict
    displays assigned to the name, constant-key subscript stores, and
    ``.update({literal})`` calls.  Dynamic extensions (parameter splats,
    computed keys, ``.update(expr)``) contribute nothing — the
    resolvable keys are still checked, the rest is invisible here."""
    keys: Set[str] = set()
    if func is None:
        return keys

    def take_dict(value: Optional[ast.AST]) -> None:
        if isinstance(value, ast.Dict):
            keys.update(k.value for k in value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str))

    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == name:
                    take_dict(node.value)
                elif (isinstance(target, ast.Subscript)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == name
                        and isinstance(target.slice, ast.Constant)
                        and isinstance(target.slice.value, str)):
                    keys.add(target.slice.value)
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name) and node.target.id == name:
            take_dict(node.value)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == name
                and len(node.args) == 1 and not node.keywords):
            take_dict(node.args[0])
    return keys


def _enclosing_function(tree: ast.Module, call: ast.Call) -> Optional[ast.AST]:
    best: Optional[ast.AST] = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (node.lineno <= call.lineno
                    and call.end_lineno <= (node.end_lineno or node.lineno)):
                if best is None or node.lineno >= best.lineno:
                    best = node
    return best


def iter_event_emissions(tree: ast.Module):
    """(call, kind, resolvable fields) for every ``X.event("kind", ...)``
    call with a constant kind."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "event"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        kind = node.args[0].value
        fields: Set[str] = set()
        for kw in node.keywords:
            if kw.arg is not None:
                fields.add(kw.arg)
            elif isinstance(kw.value, ast.Name):
                fields.update(_resolve_splat_keys(
                    _enclosing_function(tree, node), kw.value.id))
        yield node, kind, fields


@register_rule(
    "telemetry-event-schema", "error",
    "every RunLog event kind and resolvable field emitted in scope must "
    "be declared in docs/OBSERVABILITY.md's event-kind catalog (and "
    "documented kinds must be emitted somewhere)",
)
def check(context: LintContext) -> Iterator[Finding]:
    emitting = [
        (sf, list(iter_event_emissions(sf.tree))) for sf in context.files
    ]
    if not any(emissions for _sf, emissions in emitting):
        return
    # "Full emission universe" = the repo checkout's gate scope (the
    # package's runlog plus bench.py's mirror events).  Outside it — a
    # pip-installed package lints itself with no repo docs around, or a
    # user lints one emitting file of their own — the doc simply isn't
    # expected to exist, and demanding it would turn the 'runs anywhere'
    # CLI permanently red on clean installs.
    full_scope = (context.file_named("telemetry/runlog.py") is not None
                  and context.file_named("bench.py") is not None)
    doc_path = os.path.join(context.repo_root, DOC_RELPATH)
    if not os.path.exists(doc_path):
        if full_scope:
            sf = next(sf for sf, emissions in emitting if emissions)
            yield make_finding(
                "telemetry-event-schema", sf.path, 1,
                f"events are emitted in scope but {DOC_RELPATH} was not "
                f"found under the repo root ({context.repo_root}); the "
                f"event schema must be documented there",
            )
        return
    with open(doc_path, encoding="utf-8") as fh:
        documented = parse_documented_kinds(fh.read())
    emitted_kinds: Set[str] = set()
    for sf, emissions in emitting:
        for call, kind, fields in emissions:
            emitted_kinds.add(kind)
            if kind not in documented:
                yield make_finding(
                    "telemetry-event-schema", sf.path, call.lineno,
                    f"event kind `{kind}` is not declared in the "
                    f"{DOC_RELPATH} event catalog",
                )
                continue
            _doc_line, doc_fields = documented[kind]
            undocumented = sorted(
                fields - doc_fields - _ENVELOPE_FIELDS
            )
            if undocumented:
                yield make_finding(
                    "telemetry-event-schema", sf.path, call.lineno,
                    f"event `{kind}` emits field(s) {undocumented} not "
                    f"named in its {DOC_RELPATH} bullet",
                )
    # Phantom kinds: only meaningful when the whole emission universe is
    # in scope (the package's runlog plus bench.py's mirror events).
    if full_scope:
        doc_rel = os.path.relpath(doc_path, context.repo_root)
        for kind, (line, _fields) in sorted(documented.items()):
            if kind not in emitted_kinds:
                yield Finding(
                    rule="telemetry-event-schema", severity="error",
                    path=doc_rel, line=line,
                    message=(f"documented event kind `{kind}` is emitted "
                             f"nowhere in the scanned code — stale docs or "
                             f"a lost emission site"),
                )
