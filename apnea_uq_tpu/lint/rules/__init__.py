"""Rule registry population: importing this package registers every
shipped rule with :data:`apnea_uq_tpu.lint.engine.RULES`.

One module per rule family; see ``docs/LINT.md`` for the operator-facing
catalog (what each rule catches, why it matters on TPU, how to
suppress).  Rules are pure AST analyses — importing them must never pull
in jax/flax (a test enforces this by poisoning those modules).
"""

from apnea_uq_tpu.lint.rules import (  # noqa: F401  (import = register)
    bare_print,
    donation,
    host_sync,
    prng,
    retrace,
    telemetry_schema,
)
