"""jit-retrace-hazard: patterns that defeat jit's compilation cache.

A retrace storm never crashes — it shows up as a mystery slowdown (or as
the telemetry layer's ``retraces``/``backend_compiles`` counters ticking
per step, which is how ISSUE 2/3 observed these post-hoc).  Three
statically catchable shapes:

1. **jit-in-loop** — ``jax.jit(...)`` (or ``partial(jax.jit, ...)``)
   evaluated inside a ``for``/``while`` body: every iteration builds a
   fresh wrapper with an empty cache, so every iteration traces AND
   compiles.  Lambdas and locally-defined functions jitted in a loop are
   the canonical spelling of this; hoisting the jit out of the loop (or
   jitting a module-level function) fixes it.
2. **unhashable static at the call site** — an argument bound to a
   ``static_argnames``/``static_argnums`` parameter of an in-scope jitted
   function is a list/dict/set display.  jit statics key the compile
   cache by hash; this raises ``Unhashable static arguments`` at call
   time, on device, after minutes of setup.
3. **unhashable static default** — the jitted function declares a static
   parameter whose *default value* is a mutable display: the hazard of
   (2) baked into the signature.

The donation rule's pass-1 machinery is reused to map static names onto
signatures.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apnea_uq_tpu.lint import astwalk
from apnea_uq_tpu.lint.engine import Finding, LintContext, make_finding, register_rule
from apnea_uq_tpu.lint.rules.donation import (
    _jit_call_in,
    _param_names,
    literal_name_num_kwargs,
)

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp, ast.GeneratorExp)


def _static_kwargs(call: ast.Call) -> Tuple[List[str], List[int]]:
    return literal_name_num_kwargs(call, "static_argnames", "static_argnums")


def _collect_static_functions(context: LintContext) -> Dict[str, Dict]:
    """{bare name: {"static": set[str], "params": [...], "defaults":
    {param: default node}}} for jit-decorated defs in scope."""
    out: Dict[str, Dict] = {}
    for sf in context.files:
        aliases = astwalk.import_aliases(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                call = _jit_call_in(dec, aliases)
                if call is None:
                    continue
                names, nums = _static_kwargs(call)
                params = _param_names(node)
                static = set(names)
                static.update(params[i] for i in nums if i < len(params))
                if not static:
                    continue
                defaults: Dict[str, ast.AST] = {}
                pos_with_defaults = params[len(params)
                                           - len(node.args.defaults):]
                for p, d in zip(pos_with_defaults, node.args.defaults):
                    defaults[p] = d
                for a, d in zip(node.args.kwonlyargs, node.args.kw_defaults):
                    if d is not None:
                        defaults[a.arg] = d
                out[node.name] = {"static": static, "params": params,
                                  "defaults": defaults, "path": sf.path,
                                  "line": node.lineno}
    return out


@register_rule(
    "jit-retrace-hazard", "warning",
    "a pattern that defeats jit's compile cache: jit() constructed "
    "inside a loop, or a list/dict/set bound to a static argument "
    "(unhashable statics fail at call time)",
)
def check(context: LintContext) -> Iterator[Finding]:
    statics = _collect_static_functions(context)
    # (3) unhashable static defaults, once per definition.
    for name, info in statics.items():
        for param, default in info["defaults"].items():
            if param in info["static"] and isinstance(default, _UNHASHABLE):
                yield make_finding(
                    "jit-retrace-hazard", info["path"], default.lineno,
                    f"`{name}` declares static argument `{param}` with an "
                    f"unhashable (list/dict/set) default — jit statics key "
                    f"the compile cache by hash and raise on these",
                )
    for sf in context.files:
        aliases = astwalk.import_aliases(sf.tree)
        for _scope, body in astwalk.scopes(sf.tree):
            walk = astwalk.ScopeWalk(body)
            for site in walk.calls:
                # (1) jit wrapper constructed inside a loop.
                if site.loops and _jit_call_in(site.node, aliases) is not None:
                    # Decorated defs never appear here: decorators are
                    # recorded against the def statement, outside loops
                    # unless the def itself is loop-local — which is the
                    # hazard.
                    yield make_finding(
                        "jit-retrace-hazard", sf.path, site.node.lineno,
                        "jax.jit(...) evaluated inside a loop: every "
                        "iteration builds a fresh wrapper with an empty "
                        "compile cache and retraces — hoist the jitted "
                        "function out of the loop",
                    )
                    continue
                # (2) unhashable display bound to a static parameter.
                func = site.node.func
                if isinstance(func, ast.Name) and func.id in statics:
                    info = statics[func.id]
                    yield from _unhashable_static_args(
                        sf, site.node, func.id, info)


def _unhashable_static_args(sf, call: ast.Call, callee: str,
                            info: Dict) -> Iterator[Finding]:
    params = info["params"]
    bound: List[Tuple[str, ast.AST]] = []
    for pos, arg in enumerate(call.args):
        if pos < len(params):
            bound.append((params[pos], arg))
    for kw in call.keywords:
        if kw.arg is not None:
            bound.append((kw.arg, kw.value))
    for param, arg in bound:
        if param in info["static"] and isinstance(arg, _UNHASHABLE):
            yield make_finding(
                "jit-retrace-hazard", sf.path, arg.lineno,
                f"call to `{callee}` binds an unhashable "
                f"{type(arg).__name__.lower()} to static argument "
                f"`{param}` — jit raises `Non-hashable static arguments` "
                f"at dispatch; pass a tuple (or mark the arg non-static)",
            )
