"""donated-buffer-read: using an argument after passing it to a jitted
callee that donates it.

``donate_argnames``/``donate_argnums`` hand the argument's HBM to XLA;
after the call the Python name still points at a deleted buffer.  On TPU
a later read raises at best and aliases garbage at worst — and on CPU
(where donation is ignored) the same code passes every test, which is
exactly why this needs a static guard: the tier-1 suite runs off-TPU.

Pass 1 collects every function in scope jitted with donation — decorator
forms (``@partial(jax.jit, ..., donate_argnames=...)``) and rebinding
forms (``g = jax.jit(f, donate_argnums=...)``) — and maps donated
positions/names onto the wrapped function's signature.

Pass 2 walks every scope: after a *direct call by name* to a donating
function, the plain-name arguments bound to donated parameters are
tainted; a later load of a tainted name in a compatible branch, before
any rebind, is a finding.  A call inside a loop whose donated args are
never rebound in that loop is the same bug one iteration later — also
flagged.

Escapes that intentionally do NOT taint: attribute access on the jitted
function (``f.lower(...)`` — AOT lowering is abstract; ``f.__wrapped__``
is the undonated plain function) and passing the function itself as a
value (``record_jit_memory(log, "label", f, *args)`` lowers, never
executes).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apnea_uq_tpu.lint import astwalk
from apnea_uq_tpu.lint.engine import Finding, LintContext, make_finding, register_rule

_JIT_TAILS = ("jax.jit", "jax.pjit", "pjit.pjit", "jax.experimental.pjit.pjit")


def _is_jit_name(name: Optional[str]) -> bool:
    return name is not None and (name in _JIT_TAILS or name.endswith(".jit")
                                 or name == "jit")


def _constants_of(value: ast.AST, typ: type) -> List:
    """Constant literals of ``typ`` in a single constant or a
    tuple/list display (the spellings jit kwargs take in practice)."""
    if isinstance(value, (ast.Tuple, ast.List)):
        return [e.value for e in value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, typ)]
    if isinstance(value, ast.Constant) and isinstance(value.value, typ):
        return [value.value]
    return []


def literal_name_num_kwargs(call: ast.Call, names_kw: str,
                            nums_kw: str) -> Tuple[List[str], List[int]]:
    """(str literals under ``names_kw``, int literals under ``nums_kw``)
    on a jit(...) call — shared by the donation rule (donate_argnames/
    argnums) and the retrace rule (static_argnames/argnums)."""
    names: List[str] = []
    nums: List[int] = []
    for kw in call.keywords:
        if kw.arg == names_kw:
            names.extend(_constants_of(kw.value, str))
        elif kw.arg == nums_kw:
            nums.extend(_constants_of(kw.value, int))
    return names, nums


def _donation_kwargs(call: ast.Call) -> Tuple[List[str], List[int]]:
    return literal_name_num_kwargs(call, "donate_argnames", "donate_argnums")


def _param_names(fn: ast.AST) -> List[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _jit_call_in(expr: ast.AST, aliases) -> Optional[ast.Call]:
    """The jit(...)/partial(jit, ...) Call inside a decorator or an
    assignment value, else None."""
    if not isinstance(expr, ast.Call):
        return None
    name = astwalk.canonical_call(expr, aliases)
    if _is_jit_name(name):
        return expr
    if name in ("functools.partial", "partial") and expr.args:
        inner = astwalk.dotted_name(expr.args[0])
        if inner is not None:
            head, _, rest = inner.partition(".")
            resolved = aliases.get(head, head)
            full = f"{resolved}.{rest}" if rest else resolved
            if _is_jit_name(full):
                return expr
    return None


def collect_donating_functions(context: LintContext) -> Dict[str, Dict]:
    """{bare name: {"donated": set of param names, "params": [names],
    "path": file}} for every donating jitted function in scope."""
    out: Dict[str, Dict] = {}
    for sf in context.files:
        aliases = astwalk.import_aliases(sf.tree)
        defs: Dict[str, ast.AST] = {
            node.name: node for node in ast.walk(sf.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in defs.values():
            for dec in node.decorator_list:
                call = _jit_call_in(dec, aliases)
                if call is None:
                    continue
                names, nums = _donation_kwargs(call)
                params = _param_names(node)
                donated = set(names)
                donated.update(params[i] for i in nums if i < len(params))
                if donated:
                    out[node.name] = {"donated": donated, "params": params,
                                      "path": sf.path}
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            call = _jit_call_in(node.value, aliases)
            if call is None or not call.args:
                continue
            names, nums = _donation_kwargs(call)
            if not names and not nums:
                continue
            wrapped = astwalk.dotted_name(call.args[0])
            params = _param_names(defs[wrapped]) if wrapped in defs else []
            donated = set(names)
            donated.update(params[i] for i in nums if i < len(params))
            if donated:
                out[node.targets[0].id] = {"donated": donated,
                                           "params": params, "path": sf.path}
    return out


def _donated_arg_names(call: ast.Call, info: Dict) -> Set[str]:
    """Plain-Name arguments of this call bound to donated parameters."""
    donated: Set[str] = set()
    params = info["params"]
    for pos, arg in enumerate(call.args):
        if isinstance(arg, ast.Name) and pos < len(params) \
                and params[pos] in info["donated"]:
            donated.add(arg.id)
    for kw in call.keywords:
        if kw.arg in info["donated"] and isinstance(kw.value, ast.Name):
            donated.add(kw.value.id)
    return donated


@register_rule(
    "donated-buffer-read", "error",
    "an argument is read after being passed to a jit-compiled callee "
    "that donates it — the buffer no longer exists on TPU (CPU tests "
    "cannot catch this)",
)
def check(context: LintContext) -> Iterator[Finding]:
    donating = collect_donating_functions(context)
    if not donating:
        return
    for sf in context.files:
        for _scope, body in astwalk.scopes(sf.tree):
            walk = astwalk.ScopeWalk(body)
            reported = set()
            for site in walk.calls:
                func = site.node.func
                if not isinstance(func, ast.Name):
                    continue
                info = donating.get(func.id)
                if info is None:
                    continue
                if isinstance(site.stmt, (ast.Return, ast.Raise)):
                    # Control leaves the scope with the call; no later
                    # load (and no next loop iteration) can observe the
                    # donated buffer.
                    continue
                names = _donated_arg_names(site.node, info)
                for name in sorted(names):
                    yield from _check_taint(
                        sf, walk, site, func.id, name, reported)


def _check_taint(sf, walk: astwalk.ScopeWalk, site: astwalk.CallSite,
                 callee: str, name: str, reported: set) -> Iterator[Finding]:
    rebinds = [b.order for b in walk.bindings
               if b.name == name and b.order > site.order]
    first_rebind = min(rebinds) if rebinds else None
    for load in walk.loads:
        if load.name != name or load.stmt is site.stmt:
            continue
        if load.order <= site.order:
            continue
        if first_rebind is not None and load.order > first_rebind:
            break  # the name is fresh again (loads are order-sorted)
        if not astwalk.compatible(site.branch, load.branch):
            continue
        mark = (sf.path, load.node.lineno, name)
        if mark not in reported:
            reported.add(mark)
            yield make_finding(
                "donated-buffer-read", sf.path, load.node.lineno,
                f"`{name}` was donated to `{callee}` on line "
                f"{site.node.lineno} (donate_argnames/argnums); its buffer "
                f"no longer exists — use the callee's return value or "
                f"copy before the call",
            )
        break  # one finding per (call, name) is enough
    # Loop hazard: donation inside a loop that never rebinds the name.
    if site.loops:
        innermost = site.loops[-1]
        if not walk.loop_binds(innermost, (name,)):
            mark = (sf.path, site.node.lineno, name, "loop")
            if mark not in reported:
                reported.add(mark)
                yield make_finding(
                    "donated-buffer-read", sf.path, site.node.lineno,
                    f"`{name}` is donated to `{callee}` inside a loop that "
                    f"never rebinds it: the next iteration passes an "
                    f"already-deleted buffer",
                )
