"""prng-key-reuse: one key expression feeding ≥2 ``jax.random``
consumers without an intervening split/fold/reassignment.

Why it matters here specifically: the paper's epistemic-uncertainty
decomposition reads *disagreement* between MC-Dropout passes and between
Deep-Ensemble members.  A reused key silently correlates those streams —
identical dropout masks across passes, identical shuffles across
members — which deflates the disagreement and invalidates the MI/variance
numbers while every shape and loss still looks healthy.  Nothing crashes;
the uncertainty is just wrong.

What counts as consumption: any ``jax.random.*`` call taking the key as
its first argument.  Derivations (``split``/``fold_in``/``clone``) are
consumers too — JAX's contract is use-once even for them — but the
idiomatic derivation fan-out stays legal:

- ``fold_in(key, a)`` + ``fold_in(key, b)`` with *different* data args is
  the stream-derivation pattern (``utils/prng.py``) — allowed;
  the same data arg twice duplicates a stream — flagged.
- ``split(key)`` twice yields bit-identical children — flagged.
- a sampler (``uniform``/``normal``/``bernoulli``/``permutation``/...)
  plus ANY second consumer of the same key — flagged.
- a sampler consuming a key inside a loop that never rebinds any name in
  the key expression — the per-iteration-identical-noise hazard — flagged
  even with a single call site.

Scope: direct ``jax.random.*`` calls (through import aliases).  Keys
threaded through helper wrappers (e.g. ``prng.stream``) are derivations
by construction and are not tracked across the call.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from apnea_uq_tpu.lint import astwalk
from apnea_uq_tpu.lint.engine import Finding, LintContext, make_finding, register_rule

# jax.random attributes that do NOT consume a key first-arg.
_NON_CONSUMERS = {
    "key", "PRNGKey", "key_data", "wrap_key_data", "key_impl",
    "default_prng_impl", "rbg_key", "threefry2x32_key", "seed_with_impl",
}
_DERIVERS = {"split", "fold_in", "clone"}


def _consumer(call: ast.Call, aliases) -> Optional[str]:
    """The jax.random function name when this call consumes a key."""
    name = astwalk.canonical_call(call, aliases)
    if name is None or not name.startswith("jax.random."):
        return None
    fn = name.split(".", 2)[2]
    if "." in fn or fn in _NON_CONSUMERS:
        return None
    if not call.args:
        return None
    return fn


def _data_arg_src(call: ast.Call) -> Optional[str]:
    """Source of fold_in's second argument (the stream discriminator)."""
    if len(call.args) >= 2:
        return ast.unparse(call.args[1])
    return None


def _check_scope(sf, walk: astwalk.ScopeWalk, aliases) -> Iterator[Finding]:
    consumers: List[Tuple[astwalk.CallSite, str, str, Tuple[str, ...]]] = []
    for site in walk.calls:
        fn = _consumer(site.node, aliases)
        if fn is None:
            continue
        key_src = ast.unparse(site.node.args[0])
        consumers.append((site, fn, key_src, astwalk.names_in(site.node.args[0])))

    reported = set()
    for i, (a, fn_a, key_a, names_a) in enumerate(consumers):
        for b, fn_b, key_b, _names_b in consumers[i + 1:]:
            if key_a != key_b or not astwalk.compatible(a.branch, b.branch):
                continue
            if walk.bindings_between(names_a, a.order, b.order):
                continue  # key rebound between the two uses
            both_derive = fn_a in _DERIVERS and fn_b in _DERIVERS
            if both_derive:
                if fn_a != fn_b:
                    continue  # split+fold_in mix: distinct derivations
                if (fn_a == "fold_in"
                        and _data_arg_src(a.node) != _data_arg_src(b.node)):
                    continue  # fold_in fan-out with distinct stream ids
            mark = (sf.path, b.node.lineno, key_a)
            if mark in reported:
                continue
            reported.add(mark)
            yield make_finding(
                "prng-key-reuse", sf.path, b.node.lineno,
                f"key `{key_a}` already consumed by jax.random.{fn_a} on "
                f"line {a.node.lineno}; reusing it in jax.random.{fn_b} "
                f"correlates the two streams (split or fold_in first)",
            )
        # Single-site loop hazard: a sampler drawing from a key the loop
        # never rebinds produces identical noise every iteration.
        if fn_a not in _DERIVERS and a.loops:
            innermost = a.loops[-1]
            if not walk.loop_binds(innermost, names_a):
                mark = (sf.path, a.node.lineno, key_a, "loop")
                if mark not in reported:
                    reported.add(mark)
                    yield make_finding(
                        "prng-key-reuse", sf.path, a.node.lineno,
                        f"jax.random.{fn_a} consumes `{key_a}` inside a "
                        f"loop that never rebinds it: every iteration "
                        f"draws the SAME stream (fold_in the loop index "
                        f"first)",
                    )


@register_rule(
    "prng-key-reuse", "error",
    "the same PRNG key feeds two jax.random consumers without an "
    "intervening split/fold_in — correlated streams corrupt the "
    "MCD/DE uncertainty decomposition",
)
def check(context: LintContext) -> Iterator[Finding]:
    for sf in context.files:
        aliases = astwalk.import_aliases(sf.tree)
        for _scope, body in astwalk.scopes(sf.tree):
            yield from _check_scope(sf, astwalk.ScopeWalk(body), aliases)
