"""bare-print: library code must not call ``print`` directly.

Every user-facing line routes through ``telemetry.log`` so it can be
redirected, silenced, and mirrored into the active run's JSONL event
stream; a reintroduced ``print`` leaks output past all three (and, in
bench.py's case, would corrupt the one-JSON-line stdout machine
contract).  This migrates ``tests/test_no_bare_print.py``'s hand-rolled
scan onto the rule engine: the old one-file ALLOWLIST becomes an inline
``# apnea-lint: disable=bare-print -- <why>`` suppression at the actual
call site in ``telemetry/logging_shim.py``, so the exemption lives next
to the code it excuses and carries its justification with it.

Matches real ``print`` *calls* (``ast.Call`` on the bare name), so
comments, docstrings, and strings never trip it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from apnea_uq_tpu.lint.engine import Finding, LintContext, make_finding, register_rule


@register_rule(
    "bare-print", "error",
    "library code calls print() directly instead of telemetry.log — the "
    "line bypasses redirection, silencing, and the run-log mirror",
)
def check(context: LintContext) -> Iterator[Finding]:
    for sf in context.files:
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield make_finding(
                    "bare-print", sf.path, node.lineno,
                    "bare print() call — route output through "
                    "apnea_uq_tpu.telemetry.log (or suppress with a "
                    "justification if this IS the central sink)",
                )
