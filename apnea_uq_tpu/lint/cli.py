"""The ``apnea-uq lint`` subcommand.

``apnea-uq lint [paths ...] [--json] [--rule NAME ...]`` — exits 0 when
every finding is suppressed (with a justification), 1 otherwise, 2 on
usage errors.  With no paths it lints the installed package plus the
repo's ``bench.py`` when one sits next to it — the exact scope the
tier-1 gate (``tests/test_lint.py``) runs.

Kept jax-free end to end: the handler imports only the engine, the
reporters, and ``telemetry.log`` (the stdlib logging shim).
"""

from __future__ import annotations

import os
from typing import List

from apnea_uq_tpu.telemetry import log


def default_paths() -> List[str]:
    """The package directory, plus ``bench.py`` beside it when present."""
    import apnea_uq_tpu

    pkg_dir = os.path.dirname(os.path.abspath(apnea_uq_tpu.__file__))
    paths = [pkg_dir]
    bench = os.path.join(os.path.dirname(pkg_dir), "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    return paths


def cmd_lint(args) -> int:
    from apnea_uq_tpu.lint.engine import run_lint
    from apnea_uq_tpu.lint.report import emit_result, resolve_format

    paths = args.paths or default_paths()
    try:
        result = run_lint(paths, rules=args.rule or None)
    except (FileNotFoundError, ValueError, SyntaxError) as e:
        # Usage errors exit 2, distinct from exit 1 = real findings, so
        # CI gating on the exit code can't mistake a typo for a clean or
        # dirty tree.
        log(f"apnea-uq lint: {e}")
        raise SystemExit(2)
    emit_result(result, resolve_format(args))
    return 1 if result.unsuppressed else 0


def register(sub) -> None:
    """Attach the ``lint`` subcommand to the CLI's subparser registry."""
    p = sub.add_parser(
        "lint",
        help="AST lint for JAX/TPU correctness hazards (PRNG key reuse, "
             "donated-buffer reads, host syncs in timed regions, retrace "
             "hazards, telemetry schema drift, bare prints).")
    p.add_argument("paths", nargs="*", default=None,
                   help="Files/directories to lint; default: the "
                        "apnea_uq_tpu package plus bench.py beside it.")
    from apnea_uq_tpu.lint.report import add_format_args

    add_format_args(p)
    p.add_argument("--rule", action="append", default=[],
                   metavar="NAME",
                   help="Run only this rule (repeatable); default: all "
                        "registered rules — see docs/LINT.md.")
    p.set_defaults(fn=cmd_lint)
