"""Fused per-window UQ reduction as a Pallas TPU kernel.

The UQ hot op (SURVEY §3.3) reads a (K, M) stack of positive-class
probabilities (K = MC passes or ensemble members, M = windows, M >> K) and
produces five per-window vectors: mean, population variance, total
uncertainty H[E[p]], aleatoric proxy E[H[p]], and the epistemic mutual
information max(total - aleatoric, 0).  The reference computes these on
host NumPy with a Python loop over passes (uq_techniques.py:69-91); the
jnp engine in :mod:`apnea_uq_tpu.uq.metrics` is one jitted reduction; this
kernel goes one step further and fuses *all five* outputs into a single
pass over the stack in VMEM — each (K, TILE) column block is read from HBM
exactly once, and every output row is produced from registers.

Measured on a v5e chip (K=50, M=4.2M, chained-iteration timing): this
kernel sustains ~92 GB/s vs ~98 GB/s for the jitted jnp engine — XLA's
own fusion of the same reduction is already near-optimal, and the ~10%
gap tracks the 50->56 sublane padding of the (K, TILE) input block.  The
kernel is therefore shipped as a selectable alternate engine
(``uq_evaluation_dist(engine='pallas')``), not the default.

Layout: windows ride the 128-wide lane dimension (the natural vectorization
axis — metrics are independent per window), passes ride sublanes and are
reduced in-register.  Outputs are packed as rows of one (8, M) array so the
kernel has a single aligned (8, TILE) f32 output tile per grid step.

The kernel runs in interpret mode off-TPU, so the same code path is
testable on the CPU CI mesh (SURVEY §4).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_LN2 = 0.6931471805599453

# Output row indices in the packed (8, M) result.
ROW_MEAN = 0
ROW_VARIANCE = 1
ROW_TOTAL_ENTROPY = 2
ROW_ALEATORIC = 3
ROW_MUTUAL_INFO = 4
_N_ROWS = 8  # padded to the f32 (8, 128) sublane tile


def _xlogx(v):
    """x*log(x) with the 0*log(0)=0 convention (f32-safe near p=1)."""
    return jnp.where(v > 0.0, v * jnp.log(jnp.maximum(v, 1e-38)), 0.0)


def _uq_kernel(p_ref, out_ref, *, scale: float, eps: float):
    p = p_ref[...].astype(jnp.float32)                      # (K, TILE)
    k = p.shape[0]
    mean = jnp.mean(p, axis=0, keepdims=True)               # (1, TILE)
    # Population variance (np.var ddof=0 parity, uq_techniques.py:72).
    centered = p - mean
    var = jnp.mean(centered * centered, axis=0, keepdims=True)

    pc = jnp.clip(p, eps, 1.0 - eps)
    ent = -(_xlogx(pc) + _xlogx(1.0 - pc)) * scale          # (K, TILE)
    aleatoric = jnp.mean(ent, axis=0, keepdims=True)

    mc = jnp.clip(mean, eps, 1.0 - eps)
    total = -(_xlogx(mc) + _xlogx(1.0 - mc)) * scale
    mi = jnp.maximum(total - aleatoric, 0.0)                # uq_techniques.py:91

    pad = jnp.zeros((_N_ROWS - 5, total.shape[1]), jnp.float32)
    out_ref[...] = jnp.concatenate([mean, var, total, aleatoric, mi, pad], axis=0)
    del k


@partial(jax.jit, static_argnames=("base", "eps", "tile", "interpret"))
def _fused_call(predictions, base, eps, tile, interpret):
    k, m = predictions.shape
    m_padded = ((m + tile - 1) // tile) * tile
    # Pad windows with 0.5: entropy-safe, and sliced off before returning.
    p = jnp.pad(predictions.astype(jnp.float32), ((0, 0), (0, m_padded - m)),
                constant_values=0.5)
    scale = 1.0 if base == "nats" else 1.0 / _LN2
    kernel = partial(_uq_kernel, scale=scale, eps=eps)
    extra = {}
    if not interpret and pltpu is not None:
        # Window tiles are independent -> let Mosaic parallelize the grid.
        extra["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        )
    out = pl.pallas_call(
        kernel,
        grid=(m_padded // tile,),
        in_specs=[pl.BlockSpec((k, tile), lambda j: (0, j))],
        out_specs=pl.BlockSpec((_N_ROWS, tile), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((_N_ROWS, m_padded), jnp.float32),
        interpret=interpret,
        **extra,
    )(p)
    return out[:, :m]


def fused_uq_stats(
    predictions,
    *,
    base: str = "nats",
    eps: float = 1e-10,
    tile: int = 2048,
    interpret: bool | None = None,
) -> Dict[str, jax.Array]:
    """Per-window UQ vectors from a (K, M) stack in one fused HBM pass.

    Returns the five per-window keys of
    :func:`apnea_uq_tpu.uq.metrics.uq_evaluation_dist` (aggregates are
    cheap O(M) follow-ups and stay in jnp).  ``interpret=None`` auto-selects
    interpret mode off-TPU so tests run on the CPU mesh.
    """
    if base not in ("nats", "bits"):
        raise ValueError(f"base must be 'nats' or 'bits', got {base!r}")
    predictions = jnp.asarray(predictions)
    if predictions.ndim != 2:
        raise ValueError(f"expected (K, M) predictions, got {predictions.shape}")
    if tile % 128 != 0:
        raise ValueError(f"tile must be a multiple of 128 lanes, got {tile}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out = _fused_call(predictions, base, eps, int(tile), bool(interpret))
    return {
        "mean_pred": out[ROW_MEAN],
        "pred_variance": out[ROW_VARIANCE],
        "total_pred_entropy": out[ROW_TOTAL_ENTROPY],
        "expected_aleatoric_entropy": out[ROW_ALEATORIC],
        "mutual_info": out[ROW_MUTUAL_INFO],
    }
