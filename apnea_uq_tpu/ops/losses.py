"""Losses as fused logit-space ops.

The reference uses Keras ``binary_crossentropy`` on post-sigmoid
probabilities (cnn_baseline_train.py:101).  We keep the model in logit
space and use the numerically stable sigmoid-BCE, with an optional sample
mask so padded batches (static shapes for XLA) contribute zero loss.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def masked_bce_with_logits(logits, labels, mask=None):
    """Mean sigmoid binary cross-entropy over unmasked samples.

    Args:
      logits: (batch,) float logits.
      labels: (batch,) {0,1} labels (any float/int dtype).
      mask:   optional (batch,) {0,1}; 0 entries are excluded from the mean.

    Returns scalar float32 loss.
    """
    per_sample = optax.sigmoid_binary_cross_entropy(
        logits.astype(jnp.float32), labels.astype(jnp.float32)
    )
    if mask is None:
        return jnp.mean(per_sample)
    mask = mask.astype(jnp.float32)
    total = jnp.sum(per_sample * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count
