"""Measured tile geometry for the fused Pallas kernels: sweep, persist,
activate.

The MCD and DE kernels (ops/pallas_mcd.py, ops/pallas_de.py) take their
tile geometry — ``window_tile`` and the pass/member batching factor —
as keyword arguments with hand-picked defaults.  This module replaces
the hand-picking with measurement: :func:`run_autotune` times every
``window_tile x pass_group/member_group`` cell of a small grid against
the REAL dispatch bodies (the same jitted program families
uq/predict.py acquires, so off-TPU the cells exercise the XLA fallback
and the sweep degrades to a ~1.0-ratio plumbing check, exactly like the
bench ``mcd_kernel`` block's fallback rounds), picks the fastest cell
per program label, and returns a winners document.

The document persists beside the program store as the registry's
``autotune_config`` artifact (data/registry.py ``save_json`` — the
atomic_write_json writer), stamped with the SAME invalidation axes as a
stored program (backend fingerprint, jax/jaxlib versions, package
source hash — compilecache/store.py): a winner measured on one chip or
one code version is never offered to another.  :func:`activate` loads a
document into process-global state; :func:`tuned_kernel_kwargs` is the
read side, consulted once per predict/serve call to bake the tuned
geometry into the program's static signature.  Because
:func:`active_digest` is itself a ``store_key`` material field, a
geometry flip can never alias a program stored under the old geometry.

Import discipline: uq/predict.py imports this module at module level,
so everything here that touches predict, models, serving, or telemetry
is imported lazily inside :func:`run_autotune` — module level keeps
only stdlib + jax + the compilecache keying helpers.
"""

from __future__ import annotations

import hashlib
import json
import math
import time
from typing import Any, Dict, Optional, Tuple

import jax

from apnea_uq_tpu.compilecache import store as cc_store

# The geometry knobs a winner record may carry; anything else in a
# (possibly hand-edited) document is ignored rather than splatted into
# a kernel call that would reject it.
GEOMETRY_PARAMS = ("member_group", "pass_group", "window_tile")

# The kernels' built-in defaults (ops/pallas_mcd.py mcd_pallas_passes /
# ops/pallas_de.py de_pallas_*): the sweep always times this cell so
# ``best_vs_default`` is a measured ratio, never a guess.
DEFAULT_WINDOW_TILE = 16
DEFAULT_GROUP = 8

# ------------------------------------------------------- active state ----

_ACTIVE: Dict[str, Dict[str, int]] = {}
_ACTIVE_DIGEST: str = ""


def tuned_kernel_kwargs(label: str) -> Tuple[Tuple[str, int], ...]:
    """The tuned geometry for one program label as a sorted, hashable
    tuple of (kwarg, value) pairs — ``()`` when nothing is active for
    the label, so every call site can unconditionally thread the result
    through its jit static ``geometry`` argument and splat
    ``**dict(geometry)`` into the kernel entry."""
    return tuple(sorted(_ACTIVE.get(label, {}).items()))


def active_digest() -> str:
    """Content digest of the active geometry table ('' when empty) — a
    ``store_key`` material field (compilecache/store.py), so programs
    stored under one tuned geometry are invalidated by the next."""
    return _ACTIVE_DIGEST


def fingerprint() -> Dict[str, str]:
    """The staleness axes a winners document is stamped with — the same
    backend/jax/jaxlib/source material the program store keys on: a
    mismatch on ANY axis means the measurements no longer describe this
    process and the document is ignored."""
    import jaxlib

    return {
        "backend": cc_store.backend_fingerprint(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "source": cc_store._source_version(),
    }


def _digest(winners: Dict[str, Any]) -> str:
    material = json.dumps(winners, sort_keys=True, default=str)
    return hashlib.sha256(material.encode()).hexdigest()[:16]


def deactivate() -> None:
    """Drop any active tuned geometry (module-global): subsequent calls
    dispatch the kernels' built-in defaults again."""
    global _ACTIVE_DIGEST
    _ACTIVE.clear()
    _ACTIVE_DIGEST = ""


def activate(document: Optional[Dict[str, Any]]) -> int:
    """Load a winners document into the process-global geometry table.

    Returns the number of labels activated.  A missing/empty document or
    a :func:`fingerprint` mismatch (different chip, jax version, or
    package source than the document was measured on) deactivates and
    returns 0 — stale geometry silently reverts to defaults, mirroring
    the program store's staleness discipline.
    """
    global _ACTIVE_DIGEST
    deactivate()
    if not document:
        return 0
    if document.get("fingerprint") != fingerprint():
        return 0
    for label, record in (document.get("winners") or {}).items():
        geometry = {
            name: int(record[name])
            for name in GEOMETRY_PARAMS
            if name in record
        }
        if geometry:
            _ACTIVE[str(label)] = geometry
    if _ACTIVE:
        _ACTIVE_DIGEST = _digest(
            {label: _ACTIVE[label] for label in sorted(_ACTIVE)})
    return len(_ACTIVE)


def activate_from_registry(registry) -> int:
    """Activate the persisted ``autotune_config`` artifact from a data
    registry (the startup hook: cli/stages.py calls this wherever it
    builds the compile environment, so warm-cache, serve, and the eval
    stages all bake the same tuned geometry).  No artifact -> 0, with
    defaults active."""
    from apnea_uq_tpu.data import registry as registry_keys

    try:
        document = registry.load_json(registry_keys.AUTOTUNE_CONFIG)
    except Exception:  # noqa: BLE001 — absent/corrupt artifact: defaults win
        deactivate()
        return 0
    return activate(document)


# ------------------------------------------------------------- sweep ----

def _time_call(fn, args, *, warmup: int, reps: int) -> float:
    """Best-of-reps wall time of one cell's dispatch (bench.py
    ``_time`` discipline: warmup pays the compile, reps measure the
    steady state, block_until_ready fences the async dispatch)."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = math.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _grid(window_tiles, groups):
    """The sweep grid with the kernels' default cell always included."""
    cells = [(int(w), int(g)) for w in window_tiles for g in groups]
    if (DEFAULT_WINDOW_TILE, DEFAULT_GROUP) not in cells:
        cells.append((DEFAULT_WINDOW_TILE, DEFAULT_GROUP))
    return cells


def _geometry(param: str, window_tile: int, group: int):
    return tuple(sorted({"window_tile": window_tile, param: group}.items()))


def run_autotune(
    *,
    model_config=None,
    members: int = 3,
    n_passes: int = 4,
    windows: int = 64,
    chunk: int = 32,
    buckets: Tuple[int, ...] = (16,),
    window_tiles: Tuple[int, ...] = (8, 16),
    groups: Tuple[int, ...] = (4, 8),
    warmup: int = 1,
    reps: int = 2,
    seed: int = 7,
    run_log=None,
) -> Dict[str, Any]:
    """Sweep the fused-kernel tile grid and return a winners document.

    Each (label, window_tile, group) cell is timed in isolation — a
    raising cell records an error outcome and the sweep continues, the
    per-cell promotion of the bench block runner's degrade-don't-sink
    rule.  Targets cover the two DE predict program families
    (``de_predict_pallas_fused``, ``de_chunk_predict_pallas_fused``)
    plus the ``{mcd|de}_serve_b<bucket>_pallas_fused`` ladder for every
    requested bucket, timed through the SAME jitted program families
    uq/predict.py dispatches (geometry as their static argument), so
    off-TPU the sweep times the XLA fallback bodies under the pallas
    labels — cheap, ~1.0 ratios, real plumbing.

    Telemetry: one ``autotune_cell`` event per timed cell and one
    ``autotune_result`` event per label, carrying the
    ``best_vs_default`` ratio `telemetry compare`/`trend` arbitrate
    engine-default flips on.
    """
    import numpy as np

    from apnea_uq_tpu.config import ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.uq import predict as predict_mod

    if model_config is None:
        model_config = ModelConfig()
    model = AlarconCNN1D(model_config)
    variables = init_variables(model, jax.random.key(seed))
    stacked = predict_mod.stack_member_variables([
        init_variables(model, jax.random.key(seed + i))
        for i in range(members)
    ])
    rng = np.random.default_rng(seed)
    key = jax.random.key(seed + 1)
    base, eps = "nats", 1e-10

    def x_of(rows: int):
        import jax.numpy as jnp

        shape = (rows, model_config.time_steps, model_config.num_channels)
        return jnp.asarray(rng.normal(size=shape), jnp.float32)

    de_engine = predict_mod.resolve_de_engine("pallas", None)
    mcd_engine = predict_mod.resolve_mcd_engine("pallas", "clean", None)

    # (label, geometry param, shape, fn, args-builder) per target: the
    # EXACT jitted families serve_bucket_predict / ensemble_predict
    # dispatch, with the cell's geometry in the static signature.
    targets = []
    x_full, x_chunk = x_of(windows), x_of(chunk)
    batch = min(int(chunk), int(windows))
    label = predict_mod.de_program_label(
        model, streamed=False, engine="pallas", fused=True)
    targets.append((label, "member_group", tuple(x_full.shape),
                    predict_mod._ensemble_stats_jit,
                    lambda geom, x=x_full: (model, stacked, x, batch, base,
                                            eps, de_engine, geom)))
    label = predict_mod.de_program_label(
        model, streamed=True, engine="pallas", fused=True)
    targets.append((label, "member_group", tuple(x_chunk.shape),
                    predict_mod._ensemble_chunk_stats_jit,
                    lambda geom, x=x_chunk: (model, stacked, x, base, eps,
                                             de_engine, geom)))
    for bucket in buckets:
        x_b = x_of(int(bucket))
        label = predict_mod.serve_program_label(
            model, method="de", bucket=bucket, engine="pallas")
        targets.append((label, "member_group", tuple(x_b.shape),
                        predict_mod._ensemble_stats_jit,
                        lambda geom, x=x_b, b=int(bucket):
                        (model, stacked, x, b, base, eps, de_engine, geom)))
        label = predict_mod.serve_program_label(
            model, method="mcd", bucket=bucket, engine="pallas")
        targets.append((label, "pass_group", tuple(x_b.shape),
                        predict_mod._mcd_stats_jit,
                        lambda geom, x=x_b, b=int(bucket):
                        (model, variables, x, key, n_passes,
                         predict_mod._MCD_MODES["clean"], b, base, eps,
                         None, mcd_engine, geom)))

    backend = fingerprint()["backend"]
    cells = _grid(window_tiles, groups)
    winners: Dict[str, Any] = {}
    for label, param, shape, fn, make_args in targets:
        timed: Dict[Tuple[int, int], float] = {}
        for window_tile, group in cells:
            status, seconds = "ok", -1.0
            try:
                seconds = _time_call(
                    fn, make_args(_geometry(param, window_tile, group)),
                    warmup=warmup, reps=reps)
                timed[(window_tile, group)] = seconds
            except Exception:  # noqa: BLE001 — one cell must not sink the sweep
                status = "error"
            if run_log is not None:
                run_log.event("autotune_cell", label=label,
                              shape=list(shape), param=param,
                              window_tile=window_tile, group=group,
                              seconds=round(seconds, 5), status=status)
        if not timed:
            continue
        (best_tile, best_group), best_s = min(
            timed.items(), key=lambda item: item[1])
        default_s = timed.get((DEFAULT_WINDOW_TILE, DEFAULT_GROUP), best_s)
        record = {
            "shape": list(shape),
            "window_tile": best_tile,
            param: best_group,
            "best_s": round(best_s, 5),
            "default_s": round(default_s, 5),
            "best_vs_default": round(default_s / best_s, 3) if best_s else 1.0,
            "backend": backend,
        }
        winners[label] = record
        if run_log is not None:
            run_log.event("autotune_result", label=label,
                          shape=list(shape), param=param,
                          window_tile=best_tile, group=best_group,
                          best_s=record["best_s"],
                          default_s=record["default_s"],
                          best_vs_default=record["best_vs_default"],
                          backend=backend, cells=len(timed))
    return {
        "version": 1,
        "fingerprint": fingerprint(),
        "winners": winners,
    }
