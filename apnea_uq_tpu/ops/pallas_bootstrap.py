"""Fused Poisson-bootstrap resampling as a Pallas TPU kernel.

The bootstrap is the evaluation pipeline's real hot spot (SURVEY §3.3 hot
loop #2): the reference re-runs the full UQ metric suite per resample on
host NumPy (uq_techniques.py:137-165), and even the vectorized exact
engine (uq/bootstrap.py) pays for a (B, M) random **gather** of every
per-window metric vector — and gathers are what TPUs do worst.  Measured
on a v5e chip at the reference scale (B=100, M=293K windows,
chained-iteration timing): the exact gather engine costs **241 ms**, and
6.1 s at M=4.2M.

The reformulation: a multinomial resample enters every aggregate only
through its per-window **counts** ``c[b, i]``, and every aggregate is a
ratio of count-weighted sums — so bootstrap == ``C @ V`` where V packs
the per-window metric rows.  Generating exact multinomial counts needs a
histogram (sort or scatter — both slow on TPU; measured 347 ms scatter,
10.5 s sort), but the **Poisson bootstrap** [Hanley & MacGibbon 2006;
Chamandy et al. 2012, "Estimating uncertainty for massive data streams"]
replaces them with iid ``c[b, i] ~ Poisson(1)`` and normalizes each
resample by its realized size — the standard large-M approximation whose
resamples differ from multinomial ones by O(1/sqrt(M)).

This kernel fuses the whole thing into ONE pass over V: per window tile
it draws the (B, tile) count block from the TPU's hardware PRNG
(``pltpu.prng_random_bits``; the counts never touch HBM), maps bits to
Poisson counts with 10 integer threshold compares (inverse CDF truncated
at 9; P(c>9 | lambda=1) ~ 1.1e-7), and accumulates ``C @ V^T`` on the
MXU at full f32 precision.  Measured on the same v5e at B=100, M=293K
(post-precision-fix numbers): **2.95 ms** in a tight chained loop (vs
3.5 ms for the XLA Poisson formulation, whose (B, M) count matrix
round-trips HBM, and 241 ms for the exact gather engine); ``bench.py``'s
harness records 231 ms -> 8.8 ms (**26x**) for the end-to-end engine
swap at the same scale (BENCH_r*, context key ``bootstrap_b100_m293k``).
The Precision.HIGHEST matmul costs ~0.45 ms of that — the kernel is
PRNG/compare-bound, not MXU-bound, so the simpler both-operand HIGHEST
is kept over per-operand tuning.

Off-TPU (CPU tests, interpret mode has no PRNG primitives) the public
entry point falls back to the XLA Poisson formulation — same estimator,
different (threefry) count stream.  The exact multinomial engine stays
the default in :mod:`apnea_uq_tpu.uq.bootstrap` because its CI stream is
backend-stable; this engine is the measured TPU fast path
(``UQConfig.bootstrap_engine='poisson'``).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# Number of packed metric rows (f32 sublane tile multiple; callers pad).
N_ROWS = 16

# Poisson(1) inverse CDF truncated at 9, quantized to the 24-bit uniforms
# the kernel draws.  count = #{thresholds below the uniform draw}.
_CDF = [
    sum(math.exp(-1.0) / math.factorial(j) for j in range(k + 1))
    for k in range(10)
]
_ICDF = [int(t * (1 << 24)) for t in _CDF]


def _counts_from_bits(bits: jax.Array) -> jax.Array:
    """24-bit uniform draws -> Poisson(1) counts via the truncated
    inverse CDF (10 integer threshold compares).  Shared by the
    hardware-PRNG kernel and the injected-bits interpret twin, so the
    interpret-mode tests exercise the shipped count math."""
    counts = jnp.zeros(bits.shape, jnp.int32)
    for t in _ICDF:
        counts = counts + (bits > t).astype(jnp.int32)
    return counts


def _count_matmul(counts: jax.Array, v: jax.Array) -> jax.Array:
    """(B, tile) counts x (N_ROWS, tile) packed rows -> (B, N_ROWS).
    Full-f32 matmul precision is REQUIRED: the TPU MXU's default
    single-pass bf16 truncates v's mantissa, which both biases the sums
    (~0.25% observed on near-constant entropy rows) and collapses the
    tiny across-resample variance the CIs are made of.  HIGHEST selects
    the multi-pass bf16 decomposition that recovers f32 accuracy;
    counts are small integers (exact in any precision)."""
    return jax.lax.dot_general(
        counts.astype(jnp.float32), v,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


def _accumulate_tile(out_ref, acc, j) -> None:
    @pl.when(j == 0)
    def _init():
        out_ref[...] = acc

    @pl.when(j != 0)
    def _accum():
        out_ref[...] += acc


def _kernel(seed_ref, v_ref, out_ref, *, b_padded, tile):
    j = pl.program_id(0)
    # Deterministic per (key, tile) stream: the tile index is folded into
    # the second seed word (Mosaic supports at most two seed values), so
    # the same key + tile index always produce the same counts,
    # independent of grid size.
    pltpu.prng_seed(seed_ref[0], seed_ref[1] ^ (j * 0x61C88647))
    bits = pltpu.prng_random_bits((b_padded, tile)) & 0x00FFFFFF
    counts = _counts_from_bits(bits)
    acc = _count_matmul(counts, v_ref[...])  # (b_padded, N_ROWS)
    _accumulate_tile(out_ref, acc, j)


def _injected_bits_kernel(bits_ref, v_ref, out_ref):
    """Interpret-mode twin of :func:`_kernel`: the same count inverse-CDF
    and the same HIGHEST-precision count matmul, with the uniform draws
    read from an operand instead of the hardware PRNG (interpret mode has
    none) — so tier-1 exercises the kernel body on CPU, not just the XLA
    fallback (ISSUE 12 satellite)."""
    j = pl.program_id(0)
    counts = _counts_from_bits(bits_ref[...] & 0x00FFFFFF)
    _accumulate_tile(out_ref, _count_matmul(counts, v_ref[...]), j)


def poisson_sums_from_bits(v, bits, *, tile: int = 2048,
                           interpret: bool = True):
    """(B, N_ROWS) count-weighted sums from INJECTED 24-bit uniform draws
    ``bits`` ((B, M) int32), running the kernel body under
    ``pl.pallas_call(..., interpret=True)`` on any backend.  Test/parity
    surface only — the production entry point is
    :func:`poisson_bootstrap_sums`."""
    v = jnp.asarray(v, jnp.float32)
    if v.ndim != 2 or v.shape[0] != N_ROWS:
        raise ValueError(f"expected ({N_ROWS}, M) packed rows, got {v.shape}")
    bits = jnp.asarray(bits, jnp.int32)
    if bits.ndim != 2 or bits.shape[1] != v.shape[1]:
        raise ValueError(
            f"bits must be (B, {v.shape[1]}), got {bits.shape}")
    n_boot = bits.shape[0]
    b_padded = -(-n_boot // 8) * 8
    m = v.shape[1]
    m_padded = -(-m // tile) * tile
    if m_padded != m:
        v = jnp.pad(v, ((0, 0), (0, m_padded - m)))
        # Zero-padded draws sit below every CDF threshold -> count 0,
        # AND they multiply all-zero metric rows; either alone suffices
        # for exactness.
        bits = jnp.pad(bits, ((0, 0), (0, m_padded - m)))
    if b_padded != n_boot:
        bits = jnp.pad(bits, ((0, b_padded - n_boot), (0, 0)))
    out = pl.pallas_call(
        _injected_bits_kernel,
        grid=(m_padded // tile,),
        in_specs=[
            pl.BlockSpec((b_padded, tile), lambda j: (0, j)),
            pl.BlockSpec((N_ROWS, tile), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((b_padded, N_ROWS), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((b_padded, N_ROWS), jnp.float32),
        interpret=interpret,
    )(bits, v)
    return out[:n_boot]


@partial(jax.jit, static_argnames=("n_boot", "tile"))
def _pallas_call(v, seeds, n_boot, tile):
    b_padded = -(-n_boot // 8) * 8
    m = v.shape[1]
    m_padded = -(-m // tile) * tile
    # Zero-padding is EXACT here: padded windows draw counts like any
    # other, but multiply all-zero metric rows, contributing nothing.
    if m_padded != m:
        v = jnp.pad(v, ((0, 0), (0, m_padded - m)))
    out = pl.pallas_call(
        partial(_kernel, b_padded=b_padded, tile=tile),
        grid=(m_padded // tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((N_ROWS, tile), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((b_padded, N_ROWS), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((b_padded, N_ROWS), jnp.float32),
    )(seeds, v)
    return out[:n_boot]


@partial(jax.jit, static_argnames=("n_boot",))
def _xla_poisson_sums(v, key, n_boot):
    """Same estimator in plain XLA (CPU/GPU fallback): materializes the
    (B, M) count matrix, then one MXU matmul.  3.5 ms at B=100/M=293K on
    v5e — still ~70x over the exact gather engine."""
    cdf = jnp.asarray(_CDF, jnp.float32)
    u = jax.random.uniform(key, (n_boot, v.shape[1]))
    counts = jnp.sum(u[..., None] > cdf, axis=-1).astype(jnp.float32)
    # Same full-f32 precision requirement as the kernel's dot (see above).
    return jnp.matmul(counts, v.T, precision=jax.lax.Precision.HIGHEST)


def poisson_bootstrap_sums(v, key, n_boot: int, *, tile: int = 2048):
    """(B, N_ROWS) count-weighted sums of the packed per-window rows ``v``
    ((N_ROWS, M) f32, zero-padded rows allowed) over B Poisson resamples.

    Dispatches to the fused Pallas kernel on TPU, else the XLA
    formulation.  Both are deterministic given ``key`` on their backend;
    the two paths use different PRNG streams (hardware PRNG vs threefry),
    so cross-backend bit-parity is not provided — use the default exact
    engine where that matters.
    """
    v = jnp.asarray(v, jnp.float32)
    if v.ndim != 2 or v.shape[0] != N_ROWS:
        raise ValueError(f"expected ({N_ROWS}, M) packed rows, got {v.shape}")
    if tile % 128 != 0:
        raise ValueError(f"tile must be a multiple of 128 lanes, got {tile}")
    if jax.default_backend() == "tpu" and pltpu is not None:
        seeds = jnp.asarray(
            jax.random.key_data(key), jnp.uint32
        ).astype(jnp.int32)[:2]
        return _pallas_call(v, seeds, n_boot, tile)
    return _xla_poisson_sums(v, key, n_boot)
