"""On-device streaming (histogram) ROC-AUC and accuracy.

The reference tracks accuracy and ROC-AUC *during training* via Keras
compile metrics (cnn_baseline_train.py:100-102) — TF's AUC metric is a
threshold-binned streaming estimator (200 thresholds by default) updated
batch-by-batch in the fit loop.  The TPU-native equivalent here
accumulates per-class score histograms on device inside the jitted epoch
scan and closes them into an AUC at epoch end:

    update:  O(batch) scatter-add into (2, NUM_BINS) counts
    result:  midrank pairing over the bins —
             AUC = sum_b pos[b] * (neg_below[b] + neg[b]/2) / (P*N)

which is exactly the Mann-Whitney rank AUC of the bin-quantized scores
(ties within a bin get the 1/2 correction), so the estimate is exact up
to the 1/NUM_BINS score resolution — the same approximation class as the
Keras metric, with 512 bins instead of its 200 thresholds.

Everything is pure jnp: jit/vmap/scan/shard-safe, a fixed (2, NUM_BINS)
carry regardless of dataset size, no host sync until the epoch's scalars
are read.  Counts accumulate in int32 — float32 counters silently stop
incrementing past 2^24 rows per cell, well within a large epoch's reach
(concentrated bins saturate first).  The closing ratio is computed in
float32: its worst-case relative error is O(num_bins * eps) ~ 3e-5,
far below the 1/num_bins quantization already accepted.

Design note: callers gate the metric computation with a STATIC
``track_metrics`` flag rather than always computing and discarding —
under jit the flag removes the ops at trace time, so the default
(untracked) path pays exactly nothing; the measured train benchmarks
stay comparable across rounds.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

NUM_BINS = 512


def empty_histograms(num_bins: int = NUM_BINS) -> jax.Array:
    """(2, num_bins) int32 zeros; row 0 = negatives, row 1 = positives."""
    return jnp.zeros((2, num_bins), jnp.int32)


def histogram_update(
    hists: jax.Array, probs: jax.Array, labels: jax.Array, mask: jax.Array
) -> jax.Array:
    """Accumulate one masked batch of probabilities into the class
    histograms.  ``probs`` in [0, 1]; ``labels`` in {0, 1}; ``mask`` is a
    {0, 1} row INCLUSION mask (padded rows -> 0) — fractional sample
    weights are not supported (counts are integer; fractions would
    silently truncate to zero)."""
    num_bins = hists.shape[1]
    finite = jnp.isfinite(probs)
    # Sanitize before the int cast (NaN->int is backend-defined and warns
    # in eager mode); the finite mask below zeroes these rows' counts.
    bins = jnp.clip(
        (jnp.where(finite, probs, 0.0) * num_bins).astype(jnp.int32),
        0, num_bins - 1,
    )
    labels = labels.astype(jnp.float32)
    # Exclude non-finite probabilities (a diverged model) from the counts
    # rather than clipping NaN into a valid bin via backend-defined casts.
    mask = mask.astype(jnp.float32) * finite.astype(jnp.float32)
    neg = hists[0].at[bins].add((mask * (1.0 - labels)).astype(jnp.int32))
    pos = hists[1].at[bins].add((mask * labels).astype(jnp.int32))
    return jnp.stack([neg, pos])


def auc_from_histograms(hists: jax.Array) -> jax.Array:
    """Close the histograms into the rank AUC scalar.

    NaN when either class is empty (the host-side suite returns None
    there, evaluation/classification.py:50-51; NaN is its jit-safe
    equivalent).
    """
    neg = hists[0].astype(jnp.float32)
    pos = hists[1].astype(jnp.float32)
    n_neg = jnp.sum(neg)
    n_pos = jnp.sum(pos)
    neg_below = jnp.cumsum(neg) - neg  # exclusive prefix sum
    pairs = jnp.sum(pos * (neg_below + 0.5 * neg))
    denom = n_pos * n_neg
    return jnp.where(denom > 0, pairs / jnp.maximum(denom, 1.0), jnp.nan)


def accuracy_update(
    counts: jax.Array,
    probs: jax.Array,
    labels: jax.Array,
    mask: jax.Array,
    threshold: float = 0.5,
) -> jax.Array:
    """Accumulate (correct, total) over one masked batch; counts is (2,)
    int32 (batch-local sums are exact in f32, totals must not be).
    ``mask`` is a {0, 1} inclusion mask, not fractional weights.
    Non-finite probabilities are excluded, matching histogram_update."""
    mask = mask.astype(jnp.float32) * jnp.isfinite(probs).astype(jnp.float32)
    # Strictly greater: Keras BinaryAccuracy and the reference evaluator
    # (evaluate_classification.py:49) both send exactly-threshold to 0.
    pred = (probs > threshold).astype(jnp.float32)
    correct = jnp.sum(mask * (pred == labels.astype(jnp.float32)))
    return counts + jnp.stack([correct, jnp.sum(mask)]).astype(jnp.int32)


def accuracy_from_counts(counts: jax.Array) -> jax.Array:
    """correct/total; NaN when no rows were accumulated."""
    counts = counts.astype(jnp.float32)
    return jnp.where(counts[1] > 0, counts[0] / jnp.maximum(counts[1], 1.0), jnp.nan)


def empty_metric_state(num_bins: int = NUM_BINS) -> Tuple[jax.Array, jax.Array]:
    """(histograms, accuracy counts) — the epoch-scan metric carry."""
    return empty_histograms(num_bins), jnp.zeros((2,), jnp.int32)


def metric_update(
    metric_state: Tuple[jax.Array, jax.Array],
    probs: jax.Array,
    labels: jax.Array,
    mask: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    hists, counts = metric_state
    return (
        histogram_update(hists, probs, labels, mask),
        accuracy_update(counts, probs, labels, mask),
    )


def metric_results(
    metric_state: Tuple[jax.Array, jax.Array],
) -> Tuple[jax.Array, jax.Array]:
    """(accuracy, auc) scalars from the epoch's metric carry."""
    hists, counts = metric_state
    return accuracy_from_counts(counts), auc_from_histograms(hists)
