"""Fused Deep-Ensemble inference as a Pallas TPU kernel family.

Deep Ensembles are the paper's second UQ family: N independently trained
members score every window and the (N, M) probability matrix reduces to
the same four sufficient-statistic rows MCD uses (uq/metrics.py).  The
XLA path (uq/predict.py ``_ensemble_chunk_jit``) vmaps the member axis,
which re-streams the window chunk through HBM once per member and keeps
each member's weights live only for its own pass.  But the members
differ ONLY by weights — the input tile is loop-invariant N times over —
which is exactly the invariance the MCD kernel (ops/pallas_mcd.py)
exploits across MC passes.

This kernel is the member-axis twin of that design.  Per window tile it

- loads the tile and EVERY member's layer operands (conv kernels,
  biases, the frozen-BatchNorm statistics folded to one per-channel
  affine each) into VMEM **once**, then runs all members against the
  resident copies — the windows are read once per tile instead of once
  per member;
- processes the member axis in ``member_group``-sized batches (the
  ``pass_group`` trick from the MCD kernel, with members replacing MC
  passes — deterministic eval-mode forwards, so no PRNG is involved at
  all), batching each conv as member-batched shifted MXU matmuls with
  f32 accumulation;
- optionally applies the fused sufficient-stats reduction **in-kernel**
  (the exact :func:`~apnea_uq_tpu.uq.metrics.sufficient_stats` the XLA
  fused path runs), so a fused-stats program ships (4, tile) rows out of
  VMEM instead of the (N, tile) probability block.

VMEM budget at the default geometry (``window_tile=16``,
``member_group=8``): the widest layer (256 ch) holds
8x16x60x256 f32 ~= 7.9 MB in + ~6.9 MB out of live activations —
identical to the MCD kernel, since ``member_group`` bounds the live
batch exactly like ``pass_group`` does.  Resident weights scale with N
(~3.4 MB of folded operands per member at the reference architecture),
so the whole-ensemble-resident plan holds to N≈2-3 members at 16 MB;
beyond that the autotuner (ops/autotune.py) is the arbiter — it sweeps
``window_tile`` x ``member_group`` and the compiler's own spills show up
directly in the measured cell times.

Restrictions (uq/predict.py ``resolve_de_engine`` falls back to the XLA
body, exactly like the MCD kernel's fallback contract):

- single device (``mesh=None``): the kernel is a per-chip program.
- TPU backend with the pallas TPU package importable.

DE always runs members in eval mode (frozen running-statistics BN, no
dropout), so there is no parity-mode restriction: the fold is valid for
every DE program.  Off-TPU the kernel BODY still runs under tier-1:
:func:`de_forward_with_members` executes the identical tile body under
``pl.pallas_call(..., interpret=True)`` — DE needs no injected
randomness, so the interpret twin IS the shipped kernel — compared in
tests against the eval-mode Flax model and the XLA fused stats at the
PARITY.md tolerance tiers (f32 <=1e-6-grade, bf16 <=2e-2).
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# Default tile geometry: the VMEM budget math in the module docstring.
# Both are kwargs on the public entry points; `apnea-uq autotune` sweeps
# them and persists measured winners (ops/autotune.py).
DEFAULT_WINDOW_TILE = 16
DEFAULT_MEMBER_GROUP = 8


def pallas_de_available() -> bool:
    """Whether the fused kernel can actually run here (TPU backend with
    the pallas TPU package importable) — the same gate the MCD and
    bootstrap kernels' dispatch uses."""
    return pltpu is not None and jax.default_backend() == "tpu"


class MemberOperands(NamedTuple):
    """One conv block's kernel-resident operands for ALL members, the
    member axis leading.  BatchNorm enters as a per-(member, channel)
    affine: DE members run eval mode (running statistics), so
    (x - mean) * scale/sqrt(var + eps) + bias folds to
    x * bn_scale + bn_shift outside the kernel — per member, since every
    member carries its own statistics."""

    kernel: jax.Array    # (n_members, k, c_in, c_out) f32
    bias: jax.Array      # (n_members, 1, c_out) f32
    bn_scale: jax.Array  # (n_members, 1, c_out) f32
    bn_shift: jax.Array  # (n_members, 1, c_out) f32


def fold_member_params(
    model, stacked_variables
) -> Tuple[List[MemberOperands], jax.Array, jax.Array]:
    """Member-stacked Flax variable tree -> the kernel's flat operand
    list: per-block :class:`MemberOperands` plus the dense heads'
    ((n, c, 1) kernel, (n, 1, 1) bias).  The BN fold is elementwise, so
    it applies to the stacked leaves directly.  Biases and BN affines
    ship as (n, 1, c) rows — 1-D trailing operands tile poorly on TPU."""
    cfg = model.config
    params = stacked_variables["params"]
    stats = stacked_variables["batch_stats"]
    layers = []
    for i in range(len(cfg.features)):
        conv = params[f"conv_{i}"]
        bn = params[f"bn_{i}"]
        mean = stats[f"bn_{i}"]["mean"].astype(jnp.float32)
        var = stats[f"bn_{i}"]["var"].astype(jnp.float32)
        a = bn["scale"].astype(jnp.float32) * jax.lax.rsqrt(
            var + cfg.bn_epsilon
        )
        b = bn["bias"].astype(jnp.float32) - mean * a
        n = a.shape[0]
        layers.append(MemberOperands(
            kernel=conv["kernel"].astype(jnp.float32),
            bias=conv["bias"].reshape(n, 1, -1).astype(jnp.float32),
            bn_scale=a.reshape(n, 1, -1),
            bn_shift=b.reshape(n, 1, -1),
        ))
    head = params["head"]
    n = head["bias"].shape[0]
    return (layers, head["kernel"].astype(jnp.float32),
            head["bias"].reshape(n, 1, 1).astype(jnp.float32))


def _conv1d_same_members(x: jax.Array, kernel: jax.Array, dtype) -> jax.Array:
    """SAME-padded 1-D convolution for a member group, as k shifted
    member-batched MXU matmuls: operands cast to the compute dtype,
    accumulation pinned f32 (``preferred_element_type``) in every tier.
    x: (g, n, t, c_in), kernel: (g, k, c_in, c_out) -> (g, n, t, c_out)
    f32 — the member axis rides the dot_general batch dimension, the
    member-group analog of the MCD kernel's pass-group matmul."""
    g, n, t, c_in = x.shape
    k = kernel.shape[1]
    left = (k - 1) // 2
    xp = jnp.pad(x.astype(dtype),
                 ((0, 0), (0, 0), (left, k - 1 - left), (0, 0)))
    out = None
    for j in range(k):
        xs = jax.lax.slice_in_dim(xp, j, j + t, axis=2)
        contrib = jax.lax.dot_general(
            xs.reshape(g, n * t, c_in), kernel[:, j].astype(dtype),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        out = contrib if out is None else out + contrib
    return out.reshape(g, n, t, -1)


def _de_tile_body(x_tile, layers, head_w, head_b, n_members: int,
                  member_group: int, compute_dtype):
    """The shared kernel math: (tile_w, t, c) windows -> (n_members,
    tile_w) probabilities.  Members are processed in ``member_group``
    batches; each group's activations stay in (VMEM-resident) values
    across all conv blocks — only the (g, tile_w) probabilities leave.
    Both the TPU and interpret paths execute this exact body (DE is
    deterministic, so unlike MCD there is no PRNG seam between them)."""
    dtype = jnp.dtype(compute_dtype)
    tile_w, t_steps, _ = x_tile.shape
    rows = []
    for g0 in range(0, n_members, member_group):
        g = min(member_group, n_members - g0)
        a = jnp.broadcast_to(x_tile[None], (g,) + x_tile.shape)
        for layer in layers:
            a = _conv1d_same_members(a, layer.kernel[g0:g0 + g], dtype)
            a = a + layer.bias[g0:g0 + g][:, None]
            a = jnp.maximum(a, 0.0)
            a = (a * layer.bn_scale[g0:g0 + g][:, None]
                 + layer.bn_shift[g0:g0 + g][:, None])
        # GAP accumulates f32 like the Flax model (models/cnn1d.py).
        pooled = jnp.mean(a.astype(jnp.float32), axis=2)
        logits = jax.lax.dot_general(
            pooled.astype(dtype), head_w[g0:g0 + g].astype(dtype),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) + head_b[g0:g0 + g]
        rows.append(jax.nn.sigmoid(logits[..., 0].astype(jnp.float32)))
    return jnp.concatenate(rows, axis=0)


def _split_member_refs(param_refs, n_layers: int):
    layers = [
        MemberOperands(*(param_refs[4 * i + j][...] for j in range(4)))
        for i in range(n_layers)
    ]
    head_w = param_refs[4 * n_layers][...]
    head_b = param_refs[4 * n_layers + 1][...]
    return layers, head_w, head_b


def _member_kernel(x_ref, *refs, n_layers, n_members, member_group,
                   compute_dtype):
    """Probability kernel: one (n_members, tile_w) block per tile."""
    out_ref = refs[-1]
    layers, head_w, head_b = _split_member_refs(refs[:-1], n_layers)
    out_ref[...] = _de_tile_body(
        x_ref[...], layers, head_w, head_b, n_members, member_group,
        compute_dtype,
    )


def _stats_kernel(x_ref, *refs, n_layers, n_members, member_group,
                  compute_dtype, base, eps):
    """Fused-stats kernel: the member probabilities never leave VMEM —
    the tile reduces straight to the (4, tile_w) sufficient-statistic
    rows via the SAME ``sufficient_stats`` the XLA fused path runs, so
    the two engines agree by construction on the formula."""
    from apnea_uq_tpu.uq.metrics import sufficient_stats

    out_ref = refs[-1]
    layers, head_w, head_b = _split_member_refs(refs[:-1], n_layers)
    probs = _de_tile_body(
        x_ref[...], layers, head_w, head_b, n_members, member_group,
        compute_dtype,
    )
    out_ref[...] = sufficient_stats(probs, base=base, eps=eps)


def _pad_axis(a: jax.Array, multiple: int, axis: int) -> jax.Array:
    n = a.shape[axis]
    padded = -(-n // multiple) * multiple
    if padded == n:
        return a
    pads = [(0, 0)] * a.ndim
    pads[axis] = (0, padded - n)
    return jnp.pad(a, pads)


def _member_specs(layers, head_w, head_b):
    """Whole-array BlockSpecs for the resident operands: every tile maps
    to block (0, ..) — every member's weights are read once and reused
    for all window tiles."""
    specs = []
    operands = []
    for layer in layers:
        for arr in layer:
            operands.append(arr)
            specs.append(pl.BlockSpec(
                arr.shape, lambda j, nd=arr.ndim: (0,) * nd))
    for arr in (head_w, head_b):
        operands.append(arr)
        specs.append(pl.BlockSpec(
            arr.shape, lambda j, nd=arr.ndim: (0,) * nd))
    return operands, specs


def de_pallas_members(
    model,
    stacked_variables: dict,
    chunk: jax.Array,
    *,
    window_tile: int = DEFAULT_WINDOW_TILE,
    member_group: int = DEFAULT_MEMBER_GROUP,
    interpret: bool = False,
) -> jax.Array:
    """(n_members, bs) eval-mode DE probabilities of ONE window chunk
    through the fused kernel — the drop-in pallas twin of uq/predict.py's
    ``_ensemble_chunk_jit`` body (same output contract).  Traceable;
    call sites gate on :func:`pallas_de_available` (the compiled kernel
    assumes a TPU backend; ``interpret=True`` runs the same body
    anywhere).

    Zero-padded windows are exact here the same way the MCD kernel's
    padding is: eval-mode DE has no cross-window coupling (BN frozen,
    GAP per window), so padded windows produce padded probability
    columns that the caller slices off."""
    cfg = model.config
    layers, head_w, head_b = fold_member_params(model, stacked_variables)
    n_members = head_b.shape[0]
    m = chunk.shape[0]
    x = _pad_axis(jnp.asarray(chunk, jnp.float32), window_tile, axis=0)
    operands, specs = _member_specs(layers, head_w, head_b)
    out = pl.pallas_call(
        partial(
            _member_kernel, n_layers=len(layers), n_members=n_members,
            member_group=member_group, compute_dtype=cfg.compute_dtype,
        ),
        grid=(x.shape[0] // window_tile,),
        in_specs=[
            pl.BlockSpec((window_tile,) + x.shape[1:],
                         lambda j: (j, 0, 0)),
            *specs,
        ],
        out_specs=pl.BlockSpec((n_members, window_tile), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((n_members, x.shape[0]),
                                       jnp.float32),
        interpret=interpret,
    )(x, *operands)
    return out[:, :m]


def de_pallas_stats(
    model,
    stacked_variables: dict,
    chunk: jax.Array,
    *,
    base: str = "nats",
    eps: float = 1e-10,
    window_tile: int = DEFAULT_WINDOW_TILE,
    member_group: int = DEFAULT_MEMBER_GROUP,
    interpret: bool = False,
) -> jax.Array:
    """(4, bs) per-window sufficient statistics of ONE window chunk with
    the member reduction fused in-kernel: the (N, tile) probability
    block reduces to [mean, variance, H[E[p]], E[H[p]]] rows before
    leaving VMEM — the pallas twin of the XLA fused-stats body
    (``sufficient_stats`` over ``_ensemble_chunk_jit`` output)."""
    from apnea_uq_tpu.uq.metrics import N_STAT_ROWS

    cfg = model.config
    layers, head_w, head_b = fold_member_params(model, stacked_variables)
    n_members = head_b.shape[0]
    m = chunk.shape[0]
    x = _pad_axis(jnp.asarray(chunk, jnp.float32), window_tile, axis=0)
    operands, specs = _member_specs(layers, head_w, head_b)
    out = pl.pallas_call(
        partial(
            _stats_kernel, n_layers=len(layers), n_members=n_members,
            member_group=member_group, compute_dtype=cfg.compute_dtype,
            base=base, eps=float(eps),
        ),
        grid=(x.shape[0] // window_tile,),
        in_specs=[
            pl.BlockSpec((window_tile,) + x.shape[1:],
                         lambda j: (j, 0, 0)),
            *specs,
        ],
        out_specs=pl.BlockSpec((N_STAT_ROWS, window_tile),
                               lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((N_STAT_ROWS, x.shape[0]),
                                       jnp.float32),
        interpret=interpret,
    )(x, *operands)
    return out[:, :m]


def de_forward_with_members(
    model,
    stacked_variables: dict,
    chunk,
    *,
    window_tile: int = 8,
    member_group: int = 4,
    interpret: bool = True,
) -> jax.Array:
    """The kernel body under ``pl.pallas_call(..., interpret=True)`` —
    tier-1's CPU exercise of the kernel math (the DE analog of
    ``mcd_forward_with_masks``).  DE is deterministic, so no operand
    injection is needed: this runs the EXACT shipped body, only in
    interpret mode and at a small default geometry so ragged tiles and
    ragged member groups are exercised too.  Returns (n_members, M)
    probabilities."""
    return de_pallas_members(
        model, stacked_variables, jnp.asarray(chunk),
        window_tile=window_tile, member_group=member_group,
        interpret=interpret,
    )
