from apnea_uq_tpu.ops.entropy import binary_entropy
from apnea_uq_tpu.ops.losses import masked_bce_with_logits
from apnea_uq_tpu.ops.pallas_bootstrap import poisson_bootstrap_sums

__all__ = ["binary_entropy", "masked_bce_with_logits", "poisson_bootstrap_sums"]
