"""Closed-form binary entropy on device.

The reference computes entropy two different ways — scipy ``entropy`` over
stacked [1-p, p] columns in **nats** (uq_techniques.py:35-38) and a manual
log2 formula in **bits** (analyze_mcd_patient_level.py:109-115) — with two
different clipping epsilons (1e-10 vs 1e-9).  Here one jittable closed form
serves both, with the base and epsilon explicit.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import xlogy

_LN2 = 0.6931471805599453


def binary_entropy(p, *, base: str = "nats", eps: float = 1e-10, dtype=None):
    """Entropy of a Bernoulli(p) distribution, elementwise.

    ``base='nats'`` matches scipy.stats.entropy on [1-p, p]
    (uq_techniques.py:38); ``base='bits'`` matches the reference's manual
    log2 entropy (analyze_mcd_patient_level.py:114-115).

    Probabilities are clipped to [eps, 1-eps] before the log, mirroring the
    reference's ``safe_entropy`` clipping (uq_techniques.py:37).

    ``dtype`` promotes ``p`` before the clip/log: a sub-float32 input
    (bf16 probabilities from a ``compute_dtype='bfloat16'`` model) would
    otherwise flush 1-eps to 1.0 and evaluate the transcendental at ~3
    significant digits — the fused on-device reduction passes
    ``dtype=jnp.float32`` so its accumulation precision never depends on
    the model's compute dtype.
    """
    p = jnp.asarray(p)
    if dtype is not None:
        p = p.astype(dtype)
    p = jnp.clip(p, eps, 1.0 - eps)
    # xlogy gives 0*log(0) = 0, which matters in float32 where 1-eps can
    # round to exactly 1.0 for eps below the float32 ulp.
    q = 1.0 - p
    h = -(xlogy(p, p) + xlogy(q, q))
    if base == "nats":
        return h
    if base == "bits":
        return h / _LN2
    raise ValueError(f"base must be 'nats' or 'bits', got {base!r}")
