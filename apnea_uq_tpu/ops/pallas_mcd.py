"""Fused MC-Dropout inference as a Pallas TPU kernel family.

MC Dropout is the eval pipeline's dominant cost: T=50 stochastic forward
passes per window batch.  The XLA path (uq/predict.py ``_mcd_passes``)
vmaps the passes over dropout keys, which keeps the MXU fed but makes
every pass re-stream the weights and the window chunk through HBM, and
materializes every threefry dropout mask as a full activation-shaped
tensor (mask generation alone measured ~40% of MCD wall-clock on TPU;
utils/prng.py).  The passes differ ONLY by dropout mask — Gal &
Ghahramani's estimator is embarrassingly parallel across them — so the
weights and the input tile are loop-invariant T times over.

This kernel restructures the hot loop around that invariance.  Per
window tile it

- loads the tile and ALL layer operands (conv kernels, biases, the
  frozen-BatchNorm statistics folded to one per-channel affine) into
  VMEM **once**, then runs every pass against the resident copies —
  weights and windows are read once per tile instead of once per pass;
- draws the dropout masks **in-kernel** from the TPU's hardware PRNG
  (``pltpu.prng_random_bits``, the bootstrap kernel's count trick —
  ops/pallas_bootstrap.py): masks never materialize in HBM at all;
- keeps each pass's activations resident in VMEM across the
  conv->ReLU->BN->dropout blocks (no per-layer HBM round-trips), with
  passes processed in ``pass_group``-sized batches so the live
  activation block stays inside the ~16 MB VMEM budget: at the default
  geometry (``window_tile=16``, ``pass_group=8``) the widest layer
  (256 ch) holds 8x16x60x256 f32 ~= 7.9 MB in + ~6.9 MB out, next to
  ~3.4 MB of resident weights.

Mask-stream discipline: the per-(pass, chunk) ``fold_in`` key
discipline of the XLA path (PR-1) maps here to a per-(key, chunk, tile)
hardware-PRNG seed — ``fold_in(key, chunk_idx)``'s key data, with the
tile index folded into the second seed word exactly like the bootstrap
kernel — so masks are position-stable (same key + same chunk + same
tile -> same masks, independent of grid size).  Like the bootstrap
kernel, the hardware stream differs from threefry: the pallas engine is
distributionally equivalent to the XLA engine, not bit-equal — the
kernel *math* is pinned elementwise by the interpret-mode tests below.

Restrictions (uq/predict.py ``resolve_mcd_engine`` falls back to the
XLA body, exactly like the bootstrap kernel's off-TPU fallback):

- ``mode='clean'`` only: parity mode's BatchNorm batch statistics are
  whole-chunk reductions, incompatible with independent window tiles.
- single device (``mesh=None``): the kernel is a per-chip program.
- TPU backend with the pallas TPU package importable.

Off-TPU the kernel BODY still runs under tier-1: the injected-mask
entry (:func:`mcd_forward_with_masks`) executes the identical tile body
under ``pl.pallas_call(..., interpret=True)`` with caller-supplied keep
masks (interpret mode has no hardware PRNG), compared in tests against
an independent ``lax.conv_general_dilated`` reference at the PARITY.md
tolerance tiers (f32 <=1e-6-grade, bf16 <=2e-2).
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# Default tile geometry: the VMEM budget math in the module docstring.
# Both are kwargs on the public entry points — the bench `mcd_kernel`
# block is where alternative operating points get measured.
DEFAULT_WINDOW_TILE = 16
DEFAULT_PASS_GROUP = 8

# Dropout thresholds quantize rates to 24-bit uniforms, like the
# bootstrap kernel's Poisson inverse CDF.
_MASK_BITS = 24

# Odd golden-ratio constant decorrelating per-tile seed words (shared
# convention with ops/pallas_bootstrap.py).
_TILE_SEED_STRIDE = 0x61C88647


def pallas_mcd_available() -> bool:
    """Whether the fused kernel can actually run here (TPU backend with
    the pallas TPU package importable) — the same gate the bootstrap
    kernel's dispatch uses."""
    return pltpu is not None and jax.default_backend() == "tpu"


class LayerOperands(NamedTuple):
    """One conv block's kernel-resident operands.  BatchNorm enters as a
    single per-channel affine: clean-mode MCD freezes BN at the running
    statistics, so (x - mean) * scale/sqrt(var + eps) + bias folds to
    x * bn_scale + bn_shift outside the kernel."""

    kernel: jax.Array    # (k, c_in, c_out) f32
    bias: jax.Array      # (1, c_out) f32
    bn_scale: jax.Array  # (1, c_out) f32
    bn_shift: jax.Array  # (1, c_out) f32


def fold_layer_params(
    model, variables
) -> Tuple[List[LayerOperands], jax.Array, jax.Array]:
    """Flax variable tree -> the kernel's flat operand list:
    per-block :class:`LayerOperands` plus the dense head's
    ((c, 1) kernel, (1, 1) bias).  Biases and BN affines are shipped as
    (1, c) 2-D rows — 1-D operands tile poorly on TPU."""
    cfg = model.config
    params = variables["params"]
    stats = variables["batch_stats"]
    layers = []
    for i in range(len(cfg.features)):
        conv = params[f"conv_{i}"]
        bn = params[f"bn_{i}"]
        mean = stats[f"bn_{i}"]["mean"].astype(jnp.float32)
        var = stats[f"bn_{i}"]["var"].astype(jnp.float32)
        a = params[f"bn_{i}"]["scale"].astype(jnp.float32) * jax.lax.rsqrt(
            var + cfg.bn_epsilon
        )
        b = bn["bias"].astype(jnp.float32) - mean * a
        layers.append(LayerOperands(
            kernel=conv["kernel"].astype(jnp.float32),
            bias=conv["bias"].reshape(1, -1).astype(jnp.float32),
            bn_scale=a.reshape(1, -1),
            bn_shift=b.reshape(1, -1),
        ))
    head = params["head"]
    return (layers, head["kernel"].astype(jnp.float32),
            head["bias"].reshape(1, -1).astype(jnp.float32))


def _conv1d_same(x: jax.Array, kernel: jax.Array, dtype) -> jax.Array:
    """SAME-padded 1-D convolution as k shifted MXU matmuls: operands
    cast to the compute dtype, accumulation pinned f32
    (``preferred_element_type``) in every tier.  x: (n, t, c_in),
    kernel: (k, c_in, c_out) -> (n, t, c_out) f32."""
    n, t, c_in = x.shape
    k = kernel.shape[0]
    left = (k - 1) // 2
    xp = jnp.pad(x.astype(dtype), ((0, 0), (left, k - 1 - left), (0, 0)))
    out = None
    for j in range(k):
        xs = jax.lax.slice_in_dim(xp, j, j + t, axis=1)
        contrib = jax.lax.dot_general(
            xs.reshape(n * t, c_in), kernel[j].astype(dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        out = contrib if out is None else out + contrib
    return out.reshape(n, t, -1)


def _tile_body(x_tile, layers, head_w, head_b, rates, masks_for,
               n_passes_padded: int, pass_group: int, compute_dtype):
    """The shared kernel math: (tile_w, t, c) windows -> (T_padded,
    tile_w) probabilities.  ``masks_for(g0, g, li, shape)`` supplies the
    float 0/1 keep mask of one pass group's dropout layer — drawn from
    the hardware PRNG on the TPU path, loaded from an injected operand
    on the interpret path — so BOTH paths execute this exact body and
    the interpret tests exercise the shipped math, not a transcript of
    it.  Per pass group, activations stay in (VMEM-resident) values
    across all conv blocks; only the (g, tile_w) probabilities leave."""
    dtype = jnp.dtype(compute_dtype)
    tile_w, t_steps, _ = x_tile.shape
    rows = []
    for g0 in range(0, n_passes_padded, pass_group):
        g = min(pass_group, n_passes_padded - g0)
        a = jnp.broadcast_to(x_tile[None], (g,) + x_tile.shape)
        a = a.reshape(g * tile_w, t_steps, x_tile.shape[-1])
        for li, layer in enumerate(layers):
            a = _conv1d_same(a, layer.kernel, dtype)
            a = a + layer.bias[None]
            a = jnp.maximum(a, 0.0)
            a = a * layer.bn_scale[None] + layer.bn_shift[None]
            rate = rates[li]
            if rate > 0.0:
                keep = masks_for(g0, g, li, a.shape)
                a = a * (keep / (1.0 - rate))
        # GAP accumulates f32 like the Flax model (models/cnn1d.py).
        pooled = jnp.mean(a.astype(jnp.float32), axis=1)
        logits = jax.lax.dot_general(
            pooled.astype(dtype), head_w.astype(dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + head_b
        probs = jax.nn.sigmoid(logits[:, 0].astype(jnp.float32))
        rows.append(probs.reshape(g, tile_w))
    return jnp.concatenate(rows, axis=0)


def _split_layer_refs(param_refs, n_layers: int):
    layers = [
        LayerOperands(*(param_refs[4 * i + j][...] for j in range(4)))
        for i in range(n_layers)
    ]
    head_w = param_refs[4 * n_layers][...]
    head_b = param_refs[4 * n_layers + 1][...]
    return layers, head_w, head_b


def _prng_kernel(seed_ref, x_ref, *refs, n_layers, rates, thresholds,
                 n_passes_padded, pass_group, compute_dtype):
    """TPU kernel: per tile, seed the hardware PRNG from (key, chunk,
    tile) and draw every pass group's keep masks in-kernel — the masks
    live only as VMEM values, never as HBM tensors."""
    out_ref = refs[-1]
    layers, head_w, head_b = _split_layer_refs(refs[:-1], n_layers)
    j = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0], seed_ref[1] ^ (j * _TILE_SEED_STRIDE))

    def masks_for(g0, g, li, shape):
        n, t_steps, c = shape
        bits = pltpu.prng_random_bits((n * t_steps, c)) & 0x00FFFFFF
        # keep iff bits >= rate * 2^24  ->  P(keep) = 1 - rate, the
        # flax bernoulli(keep_prob) semantics on a 24-bit uniform.
        return (bits >= thresholds[li]).astype(jnp.float32).reshape(shape)

    out_ref[...] = _tile_body(
        x_ref[...], layers, head_w, head_b, rates, masks_for,
        n_passes_padded, pass_group, compute_dtype,
    )


def _injected_kernel(x_ref, *refs, n_layers, n_masked, rates,
                     n_passes_padded, pass_group, compute_dtype):
    """Interpret-mode twin: identical body, keep masks read from
    operands instead of the hardware PRNG (interpret mode has none) —
    the CPU tier-1 exercise of the kernel math (ISSUE 12 satellite)."""
    out_ref = refs[-1]
    mask_refs = refs[-1 - n_masked:-1]
    layers, head_w, head_b = _split_layer_refs(refs[:-1 - n_masked],
                                               n_layers)
    masked_order = [li for li, r in enumerate(rates) if r > 0.0]

    def masks_for(g0, g, li, shape):
        m = mask_refs[masked_order.index(li)][...]
        return m[g0:g0 + g].reshape(shape)

    out_ref[...] = _tile_body(
        x_ref[...], layers, head_w, head_b, rates, masks_for,
        n_passes_padded, pass_group, compute_dtype,
    )


def _pad_axis(a: jax.Array, multiple: int, axis: int) -> jax.Array:
    n = a.shape[axis]
    padded = -(-n // multiple) * multiple
    if padded == n:
        return a
    pads = [(0, 0)] * a.ndim
    pads[axis] = (0, padded - n)
    return jnp.pad(a, pads)


def _param_specs(layers, head_w, head_b):
    """Whole-array BlockSpecs for the resident operands: every tile maps
    to block (0, ..) — read once, reused for all T passes."""
    specs = []
    operands = []
    for layer in layers:
        for arr in layer:
            operands.append(arr)
            specs.append(pl.BlockSpec(
                arr.shape, lambda j, nd=arr.ndim: (0,) * nd))
    for arr in (head_w, head_b):
        operands.append(arr)
        specs.append(pl.BlockSpec(
            arr.shape, lambda j, nd=arr.ndim: (0,) * nd))
    return operands, specs


def mcd_pallas_passes(
    model,
    variables: dict,
    chunk: jax.Array,
    key: jax.Array,
    chunk_idx,
    n_passes: int,
    *,
    window_tile: int = DEFAULT_WINDOW_TILE,
    pass_group: int = DEFAULT_PASS_GROUP,
) -> jax.Array:
    """(T, bs) clean-mode MCD probabilities of ONE window chunk through
    the fused TPU kernel — the drop-in pallas twin of uq/predict.py's
    ``_mcd_passes`` body (same signature role, same output contract).
    Traceable; call sites gate on :func:`pallas_mcd_available` (the
    kernel itself assumes a TPU backend).

    Zero-padded windows are exact here the same way the bootstrap
    kernel's padding is: clean-mode MCD has no cross-window coupling
    (BN frozen, GAP per window), so padded windows produce padded
    probability columns that the caller slices off."""
    cfg = model.config
    rates = tuple(float(r) for r in cfg.dropout_rates)
    thresholds = tuple(int(r * (1 << _MASK_BITS)) for r in rates)
    layers, head_w, head_b = fold_layer_params(model, variables)
    m = chunk.shape[0]
    x = _pad_axis(jnp.asarray(chunk, jnp.float32), window_tile, axis=0)
    n_padded = -(-n_passes // pass_group) * pass_group
    # Per-(key, chunk) seed words; the tile index decorrelates in-kernel.
    seeds = jnp.asarray(
        jax.random.key_data(jax.random.fold_in(key, chunk_idx)), jnp.uint32
    ).astype(jnp.int32).reshape(-1)[:2]
    operands, specs = _param_specs(layers, head_w, head_b)
    out = pl.pallas_call(
        partial(
            _prng_kernel, n_layers=len(layers), rates=rates,
            thresholds=thresholds, n_passes_padded=n_padded,
            pass_group=pass_group, compute_dtype=cfg.compute_dtype,
        ),
        grid=(x.shape[0] // window_tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((window_tile,) + x.shape[1:],
                         lambda j: (j, 0, 0)),
            *specs,
        ],
        out_specs=pl.BlockSpec((n_padded, window_tile), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((n_padded, x.shape[0]),
                                       jnp.float32),
    )(seeds, x, *operands)
    return out[:n_passes, :m]


def mcd_forward_with_masks(
    model,
    variables: dict,
    chunk,
    masks: Sequence,
    *,
    window_tile: int = 8,
    pass_group: int = 4,
    interpret: bool = True,
) -> jax.Array:
    """The kernel body under ``pl.pallas_call(..., interpret=True)``
    with INJECTED keep masks — tier-1's CPU exercise of the kernel math.

    ``masks`` holds one float 0/1 array of shape ``(T, M, time,
    features_i)`` per dropout layer with a nonzero rate, in layer order.
    Returns (T, M) probabilities.  The interpret path runs the exact
    ``_tile_body`` the TPU kernel runs; only the mask source differs
    (interpret mode has no hardware PRNG)."""
    cfg = model.config
    rates = tuple(float(r) for r in cfg.dropout_rates)
    masked = [li for li, r in enumerate(rates) if r > 0.0]
    if not masked:
        raise ValueError(
            "model has no nonzero dropout rates — the injected-mask "
            "entry exists to exercise the mask math; use the eval-mode "
            "model directly for a deterministic forward"
        )
    if len(masks) != len(masked):
        raise ValueError(
            f"expected {len(masked)} mask arrays (one per nonzero-rate "
            f"dropout layer), got {len(masks)}"
        )
    layers, head_w, head_b = fold_layer_params(model, variables)
    m = chunk.shape[0]
    n_passes = masks[0].shape[0]
    x = _pad_axis(jnp.asarray(chunk, jnp.float32), window_tile, axis=0)
    n_padded = -(-n_passes // pass_group) * pass_group
    mask_arrays = []
    mask_specs = []
    for mask in masks:
        mk = _pad_axis(jnp.asarray(mask, jnp.float32), pass_group, axis=0)
        mk = _pad_axis(mk, window_tile, axis=1)
        mask_arrays.append(mk)
        mask_specs.append(pl.BlockSpec(
            (n_padded, window_tile) + mk.shape[2:],
            lambda j: (0, j, 0, 0)))
    operands, specs = _param_specs(layers, head_w, head_b)
    out = pl.pallas_call(
        partial(
            _injected_kernel, n_layers=len(layers), n_masked=len(masks),
            rates=rates, n_passes_padded=n_padded, pass_group=pass_group,
            compute_dtype=cfg.compute_dtype,
        ),
        grid=(x.shape[0] // window_tile,),
        in_specs=[
            pl.BlockSpec((window_tile,) + x.shape[1:],
                         lambda j: (j, 0, 0)),
            *specs,
            *mask_specs,
        ],
        out_specs=pl.BlockSpec((n_padded, window_tile), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((n_padded, x.shape[0]),
                                       jnp.float32),
        interpret=interpret,
    )(x, *operands, *mask_arrays)
    return out[:n_passes, :m]
