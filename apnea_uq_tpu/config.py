"""Unified configuration for every pipeline stage.

The reference scatters configuration across per-script module constants and
argparse blocks (e.g. cnn_baseline_train.py:16-32, prepare_numpy_datasets.py:45-57,
analyze_mcd_patient_level.py:15-30), and several analysis scripts are switched
MCD<->DE by hand-editing paths (aggregate_patient_uq_metrics.py:7).  Here one
dataclass tree covers all stages and serializes to/from JSON, so every run is
reproducible from a single config artifact.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

# Canonical seed of the reference pipeline (cnn_baseline_train.py:18,
# prepare_numpy_datasets.py:50, train_deep_ensemble_cnns.py:13).
DEFAULT_SEED = 2025

# SHHS2 window geometry (preprocess_shhs_raw.py:194, prepare_numpy_datasets.py:55).
TIME_STEPS = 60
NUM_CHANNELS = 4
CHANNELS = ("SaO2", "PR", "THOR RES", "ABDO RES")

# The blessed inference compute dtypes (PARITY.md "Tolerance tiers"):
# f32 is the parity tier (fused==full <=1e-6), bf16 the documented
# low-precision tier (<=2e-2 vs f32) — validated at config load so a
# typo fails immediately, not at first trace.
VALID_COMPUTE_DTYPES = ("float32", "bfloat16")

# MCD predictor engines (UQConfig.mcd_engine): 'xla' is the default
# vmap-over-keys path; 'pallas' the fused conv->BN->ReLU->dropout TPU
# kernel (ops/pallas_mcd.py), which falls back to 'xla' off-TPU.  The DE
# engines (UQConfig.de_engine) share the same vocabulary and fallback
# contract: 'pallas' is the fused member-batched kernel
# (ops/pallas_de.py).
VALID_MCD_ENGINES = ("xla", "pallas")
VALID_DE_ENGINES = VALID_MCD_ENGINES


@dataclass(frozen=True)
class ModelConfig:
    """Alarcón et al. 1D-CNN architecture (cnn_baseline_train.py:37-104).

    Six Conv1D->ReLU->BatchNorm->Dropout blocks, global average pooling over
    time, and a single-logit head.  ``compute_dtype='bfloat16'`` runs conv/
    dense math on the MXU in bf16 with float32 params and float32 batch-norm
    statistics; for strict numerical parity work use ``'float32'`` AND
    ``matmul_precision='highest'`` — on TPU the MXU truncates even float32
    matmul operands to bf16 by default (see the field comment below).
    """

    features: Sequence[int] = (128, 192, 224, 96, 256, 96)
    kernel_sizes: Sequence[int] = (7, 5, 3, 7, 9, 9)
    dropout_rates: Sequence[float] = (0.3, 0.3, 0.4, 0.2, 0.3, 0.5)
    time_steps: int = TIME_STEPS
    num_channels: int = NUM_CHANNELS
    bn_momentum: float = 0.99  # Keras BatchNormalization default
    bn_epsilon: float = 1e-3   # Keras BatchNormalization default
    compute_dtype: str = "float32"
    # Conv/dense MXU precision ('default' | 'high' | 'highest' | None).
    # The TPU MXU's default is single-pass bf16 even for float32 inputs,
    # so compute_dtype='float32' alone is NOT strict f32 there — set
    # matmul_precision='highest' for strict numerical-parity work.
    matmul_precision: str | None = None

    def __post_init__(self):
        # Reject at config load, not at first trace: a typo'd dtype would
        # otherwise surface minutes later as an opaque jnp.dtype error
        # inside the first jitted program.  The two members are the
        # blessed inference tiers (PARITY.md "Tolerance tiers"); anything
        # else (f16, f64, int8) is unblessed by the parity suite and the
        # audit's program-dtype-drift rule.
        if self.compute_dtype not in VALID_COMPUTE_DTYPES:
            raise ValueError(
                f"ModelConfig.compute_dtype must be one of "
                f"{VALID_COMPUTE_DTYPES}, got {self.compute_dtype!r}"
            )


@dataclass(frozen=True)
class TrainConfig:
    """Training loop settings (cnn_baseline_train.py:28-32,204-217)."""

    batch_size: int = 1024
    num_epochs: int = 30
    learning_rate: float = 1e-3
    validation_split: float = 0.1
    early_stopping_patience: int = 5
    restore_best_weights: bool = True
    seed: int = DEFAULT_SEED
    shuffle: bool = True
    # Stream batches from host memory through the prefetch feed instead of
    # holding the dataset in HBM (identical results; for datasets that
    # exceed the HBM budget).
    streaming: bool = False
    # Track per-epoch accuracy + streaming-histogram ROC-AUC on device
    # (the reference's Keras compile metrics, cnn_baseline_train.py:100-102);
    # adds history keys accuracy/auc/val_accuracy/val_auc.
    track_metrics: bool = False


@dataclass(frozen=True)
class EnsembleConfig:
    """Deep-Ensemble training (train_deep_ensemble_cnns.py:13-21,125-177)."""

    num_members: int = 5
    seed_base: int = DEFAULT_SEED  # member i uses seed_base + i
    num_epochs: int = 50
    batch_size: int = 1024
    learning_rate: float = 1e-3
    validation_split: float = 0.1
    early_stopping_patience: int = 5
    # Stream per-member batch stacks from host memory instead of holding
    # the dataset in HBM (identical results; for HBM-exceeding datasets).
    streaming: bool = False
    # Lockstep vmap packing pads num_members up to a multiple of the mesh
    # ensemble axis; the padded slots train real epochs either way.  False
    # (default) discards their weights — the historical behavior.  True
    # promotes them to REAL returned members: N=10 on an 8-wide axis
    # yields 16 members from the same jitted epoch work, bit-identical to
    # an explicit N=16 run with the same root key (padded slots already
    # receive globally-consistent per-member RNG streams).
    keep_padded_members: bool = False
    # Per-member per-epoch accuracy + streaming-histogram ROC-AUC on device
    # (the reference's ensemble trainer compiles the same Keras metrics as
    # the baseline); adds (epochs, N) history arrays accuracy/auc/
    # val_accuracy/val_auc.
    track_metrics: bool = False


@dataclass(frozen=True)
class UQConfig:
    """Uncertainty quantification (analyze_mcd_patient_level.py:21-23).

    ``mcd_mode`` selects the stochastic-pass semantics:

    - ``'parity'``: dropout on AND batch-norm in batch-statistics mode —
      the reference's ``model(x, training=True)`` regime
      (uq_techniques.py:22), behind its ~77% MCD accuracy.  BN batch
      statistics are computed per ``mcd_batch_size`` chunk; the reference
      used the whole test set as ONE batch, so exact reproduction of that
      detail needs the EFFECTIVE chunk — ``mcd_batch_size``, rounded up
      to the mesh data-axis multiple when a mesh is used — to be an
      exact multiple of the window count (a non-multiple chunk wrap-pads
      some windows more than others into the batch statistics; the
      drivers warn whenever that happens).  Off-mesh, set it equal to
      the window count.
    - ``'clean'``: dropout on, batch-norm frozen at running statistics —
      the methodologically standard MC Dropout.  Accuracy stays near the
      deterministic ~88%.
    """

    mc_passes: int = 50
    n_bootstrap: int = 100
    bootstrap_alpha: float = 0.05
    # 'exact' = multinomial gather (reference semantics, backend-stable CI
    # stream); 'poisson' = fused Pallas count-matmul kernel, ~95x faster
    # on TPU at reference scale, backend-specific stream
    # (ops/pallas_bootstrap.py).
    bootstrap_engine: str = "exact"
    # MCD predictor engine: 'xla' (default) is the vmap-over-keys path;
    # 'pallas' the fused conv->BN->ReLU->dropout TPU kernel
    # (ops/pallas_mcd.py) — masks drawn in-kernel from the hardware PRNG
    # (never materialized in HBM), weights + the window tile read once
    # per tile instead of once per pass.  Off-TPU (and in 'parity' mode
    # or on a mesh) the pallas engine falls back to the XLA body exactly
    # like the bootstrap kernel; like that kernel its mask stream is
    # backend-specific, so cross-engine bit-parity is not provided —
    # the kernel math itself is pinned by interpret-mode tests.
    mcd_engine: str = "xla"
    mcd_mode: str = "clean"
    # DE predictor engine: 'xla' (default) is the vmap-over-members path;
    # 'pallas' the fused member-batched TPU kernel (ops/pallas_de.py) —
    # every member's folded weights VMEM-resident per window tile, the
    # member axis processed in member_group batches, and (under
    # fused_reduction) the sufficient-stats reduction applied in-kernel.
    # Off-TPU (and on a mesh) the pallas engine falls back to the XLA
    # body under the same label — the shared resolve_engine rules
    # (uq/predict.py).  DE is deterministic, so unlike MCD the two
    # engines are pinned to agree elementwise by interpret-mode tests.
    de_engine: str = "xla"
    # Stream MCD / DE window chunks from host memory
    # (mc_dropout_predict_streaming / ensemble_predict_streaming) instead
    # of holding the test set in HBM; identical results to the in-HBM
    # paths.  Streaming composes with the mesh: each chunk's passes /
    # members shard over the 'ensemble' axis and its windows over 'data',
    # so HBM-exceeding sets stream through ALL chips.
    mcd_streaming: bool = False
    de_streaming: bool = False
    # Fused on-device uncertainty reduction (the default): the prediction
    # programs collapse each chunk's K resident passes/members to the
    # per-window sufficient statistics (mean, variance, H[E[p]], E[H[p]];
    # uq/metrics.py) so an eval ships (4, M) floats device->host instead
    # of the full (K, M) probability matrix — a ~K/4x D2H reduction plus
    # the dropped whole-set H2D re-upload, with per-window metrics equal
    # to the full-probs path to <=1e-6 (f32).  False restores the full
    # (K, M) stack (CLI: --full-probs) for parity work and the
    # raw-predictions artifact.
    fused_reduction: bool = True
    # Windows per device chunk.  MCD's T axis multiplies the activation
    # footprint (T x mcd_batch_size rows live at once), so its chunk is
    # smaller; 512 measured fastest at T=50 on a 16-GB v5e chip, where
    # 2048 already exceeds HBM.  Deterministic/ensemble inference keeps
    # only (members x) inference_batch_size rows live.
    inference_batch_size: int = 2048
    mcd_batch_size: int = 512
    entropy_eps: float = 1e-10  # uq_techniques.py:35
    decision_threshold: float = 0.5

    def __post_init__(self):
        # Same load-time rejection contract as ModelConfig.compute_dtype:
        # an unknown engine must fail when the config is built, not deep
        # inside the first eval's predictor dispatch.
        if self.mcd_engine not in VALID_MCD_ENGINES:
            raise ValueError(
                f"UQConfig.mcd_engine must be one of {VALID_MCD_ENGINES}, "
                f"got {self.mcd_engine!r}"
            )
        if self.de_engine not in VALID_DE_ENGINES:
            raise ValueError(
                f"UQConfig.de_engine must be one of {VALID_DE_ENGINES}, "
                f"got {self.de_engine!r}"
            )


@dataclass(frozen=True)
class IngestConfig:
    """Raw SHHS2 EDF+XML ingestion (preprocess_shhs_raw.py)."""

    channels: Sequence[str] = CHANNELS
    pr_alt_names: Sequence[str] = ("H.R.",)  # preprocess_shhs_raw.py:141
    target_rate_hz: float = 1.0
    window_size_s: int = TIME_STEPS
    overlap_s: int = 0
    min_event_overlap_s: float = 10.0
    apnea_event_concepts: Sequence[str] = (
        "Obstructive apnea|Obstructive Apnea",
        "Hypopnea|Hypopnea",
    )
    sao2_valid_range: tuple[float, float] = (80.0, 100.0)
    pr_valid_range: tuple[float, float] = (40.0, 200.0)
    max_nan_fraction: float = 0.1
    min_sleep_time_s: float = 300.0 * 60.0
    # Reference parity: stop collecting XML events at the first
    # 'Stages|Stages' event (preprocess_shhs_raw.py:176-177).
    stop_at_first_stage_event: bool = True


@dataclass(frozen=True)
class PrepareConfig:
    """Dataset finalization (prepare_numpy_datasets.py).

    ``nan_fill='train'`` computes imputation means from the training split
    only, fixing the reference's global-mean train->test leak
    (prepare_numpy_datasets.py:126-128); ``'global'`` reproduces the
    reference behavior for parity experiments.
    """

    test_size: float = 0.20
    seed: int = DEFAULT_SEED
    standardize_eps: float = 1e-8
    smote: bool = True
    smote_k_neighbors: int = 5
    rus: bool = True
    nan_fill: str = "train"


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout for ensemble/data parallel execution.

    ``ensemble_axis`` / ``data_axis`` are the two factor sizes of the
    (ensemble, data) mesh; 0 means auto.  With both auto the layout
    maximizes concurrent ensemble members (largest divisor of the device
    count <= the member count) and gives the remaining devices to the DP
    sub-axis.  Consumed by every CLI stage via
    :func:`apnea_uq_tpu.parallel.mesh.make_mesh_from_config`.
    """

    ensemble_axis: int = 0  # 0 -> auto: largest divisor <= num_members
    data_axis: int = 0      # 0 -> auto: device_count // ensemble_axis


@dataclass(frozen=True)
class CompileCacheConfig:
    """Compile-cost subsystem (apnea_uq_tpu/compilecache): pay for XLA
    compilation once per (program, shapes, topology, code version), not
    once per process.

    ``cache_dir`` points JAX's persistent compilation cache at a
    directory; "" resolves to ``APNEA_UQ_XLA_CACHE_DIR`` or
    ``<registry>/xla-cache``, and defers to an already-configured cache
    (``JAX_COMPILATION_CACHE_DIR``) when one is set.  The min-entry-size
    / min-compile-time knobs mirror JAX's ``jax_persistent_cache_*``
    thresholds; both default to 0 so every hot-path program is cached —
    raise them on shared caches where tiny entries are churn.
    ``program_store`` additionally AOT-serializes the *named* hot-path
    programs (``jax.export``) under ``store_dir`` ("" →
    ``APNEA_UQ_PROGRAM_STORE_DIR`` or ``<registry>/program-store``),
    keyed by (label, aval signature, jax/jaxlib version,
    backend+topology fingerprint, package source hash), so a warmed
    second process skips trace+lower too — ``apnea-uq warm-cache``
    precompiles the zoo.  ``enabled=False`` (or the
    ``APNEA_UQ_COMPILE_CACHE=0`` env kill switch) turns the whole
    subsystem off.
    """

    enabled: bool = True
    cache_dir: str = ""
    min_entry_size_bytes: int = 0
    min_compile_time_secs: float = 0.0
    program_store: bool = True
    store_dir: str = ""


@dataclass(frozen=True)
class ExperimentConfig:
    """Top-level bundle covering the whole pipeline."""

    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    ensemble: EnsembleConfig = field(default_factory=EnsembleConfig)
    uq: UQConfig = field(default_factory=UQConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    prepare: PrepareConfig = field(default_factory=PrepareConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    compilecache: CompileCacheConfig = field(
        default_factory=CompileCacheConfig)


def _to_jsonable(obj: Any) -> Any:
    """Dataclass/collection tree -> plain JSON values.  Shared by config
    serialization here and the artifact-registry manifest."""
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _to_jsonable(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return repr(obj)


# Fields that existed in previously-saved config JSONs but were removed;
# loading tolerates (and drops) them instead of failing the whole run.
_LEGACY_KEYS = {
    "MeshConfig": {"axis_names"},  # fixed ('ensemble', 'data') since r2
}


def _from_dict(cls: type, data: dict) -> Any:
    known = {f.name for f in dataclasses.fields(cls)}
    data = {
        k: v for k, v in data.items()
        if k not in _LEGACY_KEYS.get(cls.__name__, ())
    }
    unknown = set(data) - known
    if unknown:
        raise ValueError(
            f"unknown key(s) {sorted(unknown)} for {cls.__name__}; "
            f"valid keys: {sorted(known)}"
        )
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        # Nested-dataclass fields are dispatched via _NESTED (annotations
        # are strings under `from __future__ import annotations`, so the
        # field type itself cannot be inspected without re-resolution).
        if f.name in _NESTED:
            kwargs[f.name] = _from_dict(_NESTED[f.name], v)
        elif isinstance(v, list):
            kwargs[f.name] = tuple(v)
        else:
            kwargs[f.name] = v
    return cls(**kwargs)


def _check_nested_covers_experiment() -> None:
    """Every dataclass-typed ExperimentConfig field must be in _NESTED."""
    for f in dataclasses.fields(ExperimentConfig):
        assert f.name in _NESTED, f"_NESTED is missing ExperimentConfig.{f.name}"


_NESTED = {
    "model": ModelConfig,
    "train": TrainConfig,
    "ensemble": EnsembleConfig,
    "uq": UQConfig,
    "ingest": IngestConfig,
    "prepare": PrepareConfig,
    "mesh": MeshConfig,
    "compilecache": CompileCacheConfig,
}


_check_nested_covers_experiment()


def save_config(config: ExperimentConfig, path: str) -> None:
    with open(path, "w") as f:
        json.dump(_to_jsonable(config), f, indent=2)


def load_config(path: str) -> ExperimentConfig:
    with open(path) as f:
        return _from_dict(ExperimentConfig, json.load(f))
