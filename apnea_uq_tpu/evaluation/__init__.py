from apnea_uq_tpu.evaluation.classification import (
    average_precision,
    classification_report_dict,
    cohen_kappa,
    confusion_matrix_2x2,
    evaluate_classification,
    matthews_corrcoef,
    roc_auc,
)

__all__ = [
    "evaluate_classification",
    "roc_auc",
    "average_precision",
    "cohen_kappa",
    "matthews_corrcoef",
    "confusion_matrix_2x2",
    "classification_report_dict",
]
