"""Binary classification metric suite (first-party, no sklearn at runtime).

Capability parity with the reference evaluator
``evaluation/evaluate_classification.py:7-153``: accuracy, per-class
precision/recall/F1 report, ROC-AUC and PR-AUC with single-class guards
(:77-86), Cohen's kappa and Matthews correlation (:90-91), a confusion
matrix always padded to 2x2 (:94-114), and sensitivity/specificity
(:117-119).  Implementations are closed-form NumPy (rank-statistic ROC-AUC,
step-interpolated average precision) and are unit-tested against
scikit-learn in ``tests/test_eval_metrics.py``.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from apnea_uq_tpu.telemetry import log


def _as1d(a) -> np.ndarray:
    return np.asarray(a).reshape(-1)


def confusion_matrix_2x2(y_true, y_pred) -> np.ndarray:
    """[[TN, FP], [FN, TP]] — always 2x2 even if a class is absent."""
    y_true = _as1d(y_true).astype(np.int64)
    y_pred = _as1d(y_pred).astype(np.int64)
    cm = np.zeros((2, 2), dtype=np.int64)
    for t in (0, 1):
        for p in (0, 1):
            cm[t, p] = int(np.sum((y_true == t) & (y_pred == p)))
    return cm


def _average_ranks(scores: np.ndarray) -> np.ndarray:
    """Average ranks (1-based) with ties sharing their mean rank — the
    shared vectorized midrank helper (a Python loop over elements cost
    hundreds of ms per ROC-AUC call at the ~293K-window test-set scale)."""
    from apnea_uq_tpu.utils.ranking import rank_with_ties

    return rank_with_ties(scores)[0]


def roc_auc(y_true, scores) -> Optional[float]:
    """ROC-AUC via the Mann-Whitney rank statistic; None if single-class."""
    y_true = _as1d(y_true).astype(np.int64)
    scores = _as1d(scores).astype(np.float64)
    n_pos = int(np.sum(y_true == 1))
    n_neg = int(np.sum(y_true == 0))
    if n_pos == 0 or n_neg == 0:
        return None
    ranks = _average_ranks(scores)
    r_pos = float(np.sum(ranks[y_true == 1]))
    return (r_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)


def average_precision(y_true, scores) -> Optional[float]:
    """Average precision (sklearn-style step interpolation); None if no positives."""
    y_true = _as1d(y_true).astype(np.int64)
    scores = _as1d(scores).astype(np.float64)
    n_pos = int(np.sum(y_true == 1))
    if n_pos == 0:
        return None
    order = np.argsort(-scores, kind="mergesort")
    y_sorted = y_true[order]
    s_sorted = scores[order]
    tps = np.cumsum(y_sorted)
    fps = np.cumsum(1 - y_sorted)
    # evaluate at the last index of each distinct-score group
    distinct = np.where(np.diff(s_sorted))[0]
    idx = np.concatenate([distinct, [len(s_sorted) - 1]])
    precision = tps[idx] / (tps[idx] + fps[idx])
    recall = tps[idx] / n_pos
    recall_prev = np.concatenate([[0.0], recall[:-1]])
    return float(np.sum((recall - recall_prev) * precision))


def cohen_kappa(y_true, y_pred) -> float:
    cm = confusion_matrix_2x2(y_true, y_pred).astype(np.float64)
    n = cm.sum()
    if n == 0:
        return 0.0
    po = np.trace(cm) / n
    pe = float(np.sum(cm.sum(axis=0) * cm.sum(axis=1))) / (n * n)
    if pe == 1.0:
        return 0.0
    return float((po - pe) / (1.0 - pe))


def matthews_corrcoef(y_true, y_pred) -> float:
    cm = confusion_matrix_2x2(y_true, y_pred).astype(np.float64)
    tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
    denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    if denom == 0:
        return 0.0
    return float((tp * tn - fp * fn) / denom)


def classification_report_dict(y_true, y_pred) -> Dict[str, Dict[str, float]]:
    """Per-class precision/recall/F1/support plus macro and weighted averages."""
    y_true = _as1d(y_true).astype(np.int64)
    y_pred = _as1d(y_pred).astype(np.int64)
    report: Dict[str, Dict[str, float]] = {}
    supports, precisions, recalls, f1s = [], [], [], []
    for cls in (0, 1):
        tp = int(np.sum((y_true == cls) & (y_pred == cls)))
        fp = int(np.sum((y_true != cls) & (y_pred == cls)))
        fn = int(np.sum((y_true == cls) & (y_pred != cls)))
        support = int(np.sum(y_true == cls))
        prec = tp / (tp + fp) if (tp + fp) else 0.0
        rec = tp / (tp + fn) if (tp + fn) else 0.0
        f1 = 2 * prec * rec / (prec + rec) if (prec + rec) else 0.0
        report[str(cls)] = {
            "precision": prec, "recall": rec, "f1-score": f1, "support": support,
        }
        supports.append(support)
        precisions.append(prec)
        recalls.append(rec)
        f1s.append(f1)
    total = sum(supports) or 1
    report["macro avg"] = {
        "precision": float(np.mean(precisions)),
        "recall": float(np.mean(recalls)),
        "f1-score": float(np.mean(f1s)),
        "support": sum(supports),
    }
    w = np.asarray(supports, np.float64) / total
    report["weighted avg"] = {
        "precision": float(np.sum(w * precisions)),
        "recall": float(np.sum(w * recalls)),
        "f1-score": float(np.sum(w * f1s)),
        "support": sum(supports),
    }
    report["accuracy"] = float(np.mean(y_true == y_pred)) if len(y_true) else 0.0
    return report


def evaluate_classification(
    probs,
    y_true,
    *,
    threshold: float = 0.5,
    description: str = "",
    verbose: bool = False,
) -> Dict:
    """Full evaluation from positive-class probabilities.

    Mirrors the returned-dict surface of the reference evaluator
    (evaluate_classification.py:135-147): accuracy, ROC-AUC, PR-AUC (None
    when undefined), kappa, MCC, confusion matrix, sensitivity/specificity,
    and the per-class report.
    """
    probs = _as1d(probs).astype(np.float64)
    y_true = _as1d(y_true).astype(np.int64)
    # Strictly greater, matching the reference's tie-break exactly
    # (evaluate_classification.py:49, analyze_mcd_patient_level.py:117):
    # a probability of exactly `threshold` predicts class 0.
    y_pred = (probs > threshold).astype(np.int64)

    cm = confusion_matrix_2x2(y_true, y_pred)
    tn, fp, fn, tp = int(cm[0, 0]), int(cm[0, 1]), int(cm[1, 0]), int(cm[1, 1])
    sensitivity = tp / (tp + fn) if (tp + fn) else 0.0
    specificity = tn / (tn + fp) if (tn + fp) else 0.0

    results = {
        "description": description,
        "accuracy": float(np.mean(y_true == y_pred)) if len(y_true) else 0.0,
        "roc_auc": roc_auc(y_true, probs),
        "pr_auc": average_precision(y_true, probs),
        "cohen_kappa": cohen_kappa(y_true, y_pred),
        "mcc": matthews_corrcoef(y_true, y_pred),
        "confusion_matrix": cm,
        "sensitivity": sensitivity,
        "specificity": specificity,
        "report": classification_report_dict(y_true, y_pred),
        "threshold": threshold,
    }
    if verbose:
        log(f"=== {description or 'Classification evaluation'} ===")
        for k in ("accuracy", "roc_auc", "pr_auc", "cohen_kappa", "mcc",
                  "sensitivity", "specificity"):
            v = results[k]
            log(f"  {k}: {v:.4f}" if isinstance(v, float) else f"  {k}: {v}")
        log(f"  confusion_matrix [[TN FP][FN TP]]:\n{cm}")
    return results
