"""Scalar special functions for the in-tree statistical tests.

The two CDFs ``analysis/stats.py`` needs — standard normal (Mann-Whitney
asymptotic p) and Student t (Pearson p) — previously came from
``scipy.special``; they are implemented here so the framework's runtime
dependency claims hold (README "Environment").  Both are float64 scalar
functions (the tests produce scalar p-values), verified against
scipy.special across sign, tail, and degrees-of-freedom ranges in
tests/test_analysis.py.
"""

from __future__ import annotations

import math

_SQRT2 = math.sqrt(2.0)


def ndtr(x: float) -> float:
    """Standard normal CDF via the complementary error function."""
    return 0.5 * math.erfc(-float(x) / _SQRT2)


def _betacf(a: float, b: float, x: float) -> float:
    """Continued fraction for the incomplete beta (modified Lentz)."""
    tiny = 1e-300
    qab, qap, qam = a + b, a + 1.0, a - 1.0
    c = 1.0
    d = 1.0 - qab * x / qap
    if abs(d) < tiny:
        d = tiny
    d = 1.0 / d
    h = d
    for m in range(1, 300):
        m2 = 2 * m
        # even step
        aa = m * (b - m) * x / ((qam + m2) * (a + m2))
        d = 1.0 + aa * d
        if abs(d) < tiny:
            d = tiny
        c = 1.0 + aa / c
        if abs(c) < tiny:
            c = tiny
        d = 1.0 / d
        h *= d * c
        # odd step
        aa = -(a + m) * (qab + m) * x / ((a + m2) * (qap + m2))
        d = 1.0 + aa * d
        if abs(d) < tiny:
            d = tiny
        c = 1.0 + aa / c
        if abs(c) < tiny:
            c = tiny
        d = 1.0 / d
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < 1e-15:
            break
    return h


def betainc(a: float, b: float, x: float) -> float:
    """Regularized incomplete beta I_x(a, b), scalar float64."""
    if not (a > 0.0 and b > 0.0):
        raise ValueError(f"betainc requires a, b > 0, got {a}, {b}")
    if x <= 0.0:
        return 0.0
    if x >= 1.0:
        return 1.0
    ln_front = (
        math.lgamma(a + b)
        - math.lgamma(a)
        - math.lgamma(b)
        + a * math.log(x)
        + b * math.log1p(-x)
    )
    front = math.exp(ln_front)
    # Use the continued fraction on whichever side converges fast.
    if x < (a + 1.0) / (a + b + 2.0):
        return front * _betacf(a, b, x) / a
    return 1.0 - front * _betacf(b, a, 1.0 - x) / b


def stdtr(df: float, t: float) -> float:
    """Student t CDF with ``df`` degrees of freedom at ``t``."""
    df = float(df)
    t = float(t)
    if df <= 0.0:
        raise ValueError(f"stdtr requires df > 0, got {df}")
    if t == 0.0:
        return 0.5
    tail = 0.5 * betainc(0.5 * df, 0.5, df / (df + t * t))
    return tail if t < 0.0 else 1.0 - tail
