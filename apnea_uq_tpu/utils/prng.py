"""Deterministic PRNG handling.

The reference reseeds global TF/NumPy state per run / per ensemble member
(cnn_baseline_train.py:138-139, train_deep_ensemble_cnns.py:139-140).  JAX
keys are explicit; we derive every stream from one root key by folding in
well-known stream ids, so member i's initialization, shuffling, and dropout
streams are independent and reproducible regardless of execution order.
"""

from __future__ import annotations

import jax

# Partitionable threefry is a correctness requirement here, not a perf
# knob: the mesh predictors vmap dropout keys with spmd_axis_name and
# assert sharded == single-device results bit-for-bit, which only holds
# when random-bit generation is sharding-invariant.  Newer JAX defaults
# this on; older 0.4.x rigs default it off and produce mesh-dependent
# dropout masks, so pin it at import (before any key is made).
jax.config.update("jax_threefry_partitionable", True)

# Stream ids folded into derived keys.  Arbitrary but fixed constants.
STREAM_INIT = 0x1A17
STREAM_SHUFFLE = 0x5487
STREAM_DROPOUT = 0xD209
STREAM_BOOTSTRAP = 0xB007
STREAM_SMOTE = 0x5707E
STREAM_RUS = 0x4125


def seed_key(seed: int) -> jax.Array:
    """Root key for a run."""
    return jax.random.key(seed)


def stochastic_key(seed: int, impl: str = "auto") -> jax.Array:
    """Key for throughput-critical stochastic sampling (MCD dropout masks).

    ``impl='auto'`` selects the hardware-backed ``rbg`` generator on TPU —
    threefry mask generation costs ~40% of MC-Dropout wall-clock there
    (measured on v5e: 5.7K -> 9.6K windows/s at T=50) — and the default
    threefry elsewhere.  rbg is deterministic per key but its stream is
    not guaranteed stable across JAX versions/backends, which is why it is
    opt-in per call site rather than the global default: training-time
    reproducibility keeps threefry.
    """
    if impl == "auto":
        impl = "rbg" if jax.default_backend() == "tpu" else "threefry2x32"
    return jax.random.key(seed, impl=impl)


def bootstrap_key(seed: int) -> jax.Array:
    """Bootstrap-resample index key: always a threefry stream of ``seed``,
    never the hardware rbg, so reported confidence intervals stay stable
    across JAX versions/backends (index sampling is cheap; rbg's speed is
    only worth its weaker stream-stability guarantee for dropout masks).
    The impl is pinned explicitly so a global ``jax_default_prng_impl``
    override cannot silently void the guarantee."""
    return stream(jax.random.key(seed, impl="threefry2x32"), STREAM_BOOTSTRAP)


def member_key(root: jax.Array, member: int) -> jax.Array:
    """Per-ensemble-member key (reference: per-member seed 2025+i,
    train_deep_ensemble_cnns.py:126)."""
    return jax.random.fold_in(root, member)


def stream(root: jax.Array, stream_id: int) -> jax.Array:
    """Named sub-stream of a key."""
    return jax.random.fold_in(root, stream_id)
