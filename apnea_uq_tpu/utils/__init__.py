"""Shared utilities.  Lazy exports: ``utils.io`` (the crash-consistent
artifact writers) is imported by jax-free contexts — the data plane, the
lint/flow gates, telemetry — so importing this package must not drag in
the jax-loaded ``prng``/``timing`` modules as a side effect."""

__all__ = ["seed_key", "member_key", "Timer"]


def __getattr__(name):
    if name in ("seed_key", "member_key"):
        from apnea_uq_tpu.utils import prng

        return getattr(prng, name)
    if name == "Timer":
        from apnea_uq_tpu.utils.timing import Timer

        return Timer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
