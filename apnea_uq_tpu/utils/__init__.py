from apnea_uq_tpu.utils.prng import member_key, seed_key
from apnea_uq_tpu.utils.timing import Timer

__all__ = ["seed_key", "member_key", "Timer"]
