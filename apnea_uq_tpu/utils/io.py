"""Crash-consistent file writers — the one commit protocol for every
artifact the pipeline persists.

The interface between pipeline stages is files on disk (SURVEY §1), so
the repo's durability story is only as strong as its *weakest* writer: a
plain ``open(path, "w")`` under a registry root or run dir can expose a
torn file to a concurrent reader, and a ``tmp -> os.replace`` commit
that skips ``fsync`` can surface as an empty/old file after a power
loss (the rename may be journaled before the data blocks land).  The
out-of-core data plane (data/store.py) established the discipline —
**tmp, flush, fsync, atomic replace** — and ``apnea-uq flow`` (the
pipeline dataflow lint, apnea_uq_tpu/flow/) statically enforces that
every artifact-rooted write routes through here or hand-rolls the same
protocol.

Deliberately jax-free and dependency-free: these writers run in
telemetry/CLI contexts where no backend exists.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict


def read_json_tolerant(path: str, default: Any = None) -> Any:
    """Read a JSON snapshot, degrading to ``default`` on *any* torn or
    missing state: absent file, permission error, truncated tail,
    garbage bytes.

    The read-side half of the commit protocol above.  Writers here
    guarantee readers never observe a torn file — but only for crashes
    *between* syscalls on a POSIX filesystem.  A kill -9 mid-``rename``
    on a non-journaled store, an out-of-band copy, or a manually edited
    snapshot can still hand the resume path a half-written document, and
    resumable state (stream state, ingest progress, bench progress) must
    treat that as "no snapshot" — a fresh start — not crash-loop on
    ``json.JSONDecodeError`` forever.  ``apnea-uq conc``'s
    torn-read-protocol rule pins that every state/progress load routes
    through here.
    """
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return default


def atomic_write_json(path: str, data: Dict[str, Any], *,
                      sort_keys: bool = True,
                      trailing_newline: bool = False) -> None:
    """Write ``data`` as JSON at ``path`` via tmp -> fsync -> replace.

    ``sort_keys``/``trailing_newline`` exist for writers whose on-disk
    byte layout is itself a contract (the audit manifest keeps its
    insertion order and POSIX trailing newline); the durability protocol
    is identical either way.  The fsync *before* the replace is the
    crash-consistency half the bare rename idiom misses; the replace
    happens after close (replacing an open file fails on Windows).
    """
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=sort_keys)
        if trailing_newline:
            f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    """Write ``text`` at ``path`` via tmp -> fsync -> replace."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` at ``path`` via tmp -> fsync -> replace.  The tmp
    name is pid-suffixed: byte-blob writers (the AOT program store) can
    race across processes, and two writers must never interleave into
    one tmp file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
