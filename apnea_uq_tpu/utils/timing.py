"""Wall-clock timing and optional device profiling.

Replaces the reference's ad-hoc ``time.time()`` prints
(uq_techniques.py:21-23,28-31,339,347) with a reusable context manager
that can block on device work (``block_until_ready``) so timings measure
compute, not dispatch, and can optionally wrap a ``jax.profiler`` trace.

For per-step dispatch/device breakdowns, throughput, and recompile
counters, use :class:`apnea_uq_tpu.telemetry.StepMetrics` instead — this
module is the minimal standalone timer.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax


class Timer:
    """Context-manager timer: ``with Timer("mcd") as t: ...; t.elapsed_s``.

    By default the timer measures wall clock between ``__enter__`` and
    ``__exit__`` — which, under JAX's async dispatch, may be dispatch
    time only.  Pass ``block=True`` and hand the timed computation's
    result to :meth:`wrap` (or assign ``t.result``) and ``__exit__``
    blocks on it before reading the clock, so ``elapsed_s`` bounds the
    device work::

        with Timer("predict", block=True) as t:
            probs = t.wrap(predict(...))

    ``verbose=True`` reports through the central telemetry log (never a
    bare ``print``), so the line also lands in any active run log.
    """

    def __init__(self, name: str = "", verbose: bool = False,
                 block: bool = False):
        self.name = name
        self.verbose = verbose
        self.elapsed_s: float = 0.0
        self.result: Any = None
        self._block = block

    def wrap(self, tree: Any) -> Any:
        """Register ``tree`` as the result ``__exit__`` blocks on."""
        self.result = tree
        return tree

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # On an escaping exception the result (if any) may be garbage;
        # never block on it — report dispatch-side elapsed instead.
        if self._block and exc_type is None and self.result is not None:
            jax.block_until_ready(self.result)
        self.elapsed_s = time.perf_counter() - self._start
        if self.verbose:
            from apnea_uq_tpu.telemetry import log

            log(f"[{self.name}] {self.elapsed_s:.3f}s")


def block(tree: Any) -> Any:
    """Block until every array in a pytree is computed; returns the tree."""
    return jax.block_until_ready(tree)


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Wrap a block in a jax.profiler trace when ``log_dir`` is set."""
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
