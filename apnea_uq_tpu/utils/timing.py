"""Wall-clock timing and optional device profiling.

Replaces the reference's ad-hoc ``time.time()`` prints
(uq_techniques.py:21-23,28-31,339,347) with a reusable context manager that
blocks on device work (``block_until_ready``) so timings measure compute,
not dispatch, and can optionally wrap a ``jax.profiler`` trace.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax


class Timer:
    """Context-manager timer: ``with Timer("mcd") as t: ...; t.elapsed_s``."""

    def __init__(self, name: str = "", verbose: bool = False):
        self.name = name
        self.verbose = verbose
        self.elapsed_s: float = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed_s = time.perf_counter() - self._start
        if self.verbose:
            print(f"[{self.name}] {self.elapsed_s:.3f}s")


def block(tree: Any) -> Any:
    """Block until every array in a pytree is computed; returns the tree."""
    return jax.block_until_ready(tree)


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Wrap a block in a jax.profiler trace when ``log_dir`` is set."""
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
