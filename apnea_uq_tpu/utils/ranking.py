"""Shared midrank computation (ties share their mean rank).

One implementation feeds both rank statistics in the framework — the
Mann-Whitney U test (analysis/stats.py) and the rank-formulation ROC-AUC
(evaluation/classification.py) — so tie handling cannot silently diverge
between them.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def rank_with_ties(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Midranks (1-based) and the sizes of each tie group.

    Vectorized: boundary mask over the sorted values -> tie-group ids ->
    per-group midrank ``(start + 1 + end) / 2`` scattered back.
    """
    values = np.asarray(values)
    order = np.argsort(values, kind="mergesort")
    sorted_vals = values[order]
    boundary = np.concatenate(([True], sorted_vals[1:] != sorted_vals[:-1]))
    group_ids = np.cumsum(boundary) - 1
    counts = np.bincount(group_ids)
    ends = np.cumsum(counts)
    starts = ends - counts
    midranks_per_group = (starts + 1 + ends) / 2.0
    ranks = np.empty(values.size, np.float64)
    ranks[order] = midranks_per_group[group_ids]
    return ranks, counts.astype(np.float64)
