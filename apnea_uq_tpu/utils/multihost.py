"""Host fetches that survive multi-process (multi-host) meshes.

Arrays sharded over a mesh that spans processes are not fully addressable
from any single process; fetching them requires a lockstep allgather.
Every call site that pulls device results to host NumPy inside code that
may run under ``jax.distributed`` (the ensemble trainer's per-epoch
bookkeeping, the UQ drivers' prediction stacks) routes through here.
"""

from __future__ import annotations

import jax
import numpy as np


def is_primary() -> bool:
    """True on the process that owns shared-filesystem writes (process
    0), and everywhere in single-process runs.  The guard every
    mesh-parallel write site routes through (the run-log's process-0
    discipline, generalized — enforced by ``apnea-uq topo``'s
    ``unguarded-primary-io`` rule); never raises, so it is safe before
    (or without) a usable backend."""
    try:
        return jax.process_index() == 0
    except Exception:  # noqa: BLE001 - no backend => single process
        return True


def host_values(tree):
    """Device pytree -> host NumPy pytree, multi-process safe.

    Fully-addressable arrays (the single-process common case) convert
    directly; otherwise ONE ``process_allgather`` collective fetches the
    whole pytree — callers must invoke this in lockstep on every process
    (true for the epoch loops and drivers, which all processes execute
    identically).
    """
    if all(
        getattr(a, "is_fully_addressable", True) for a in jax.tree.leaves(tree)
    ):
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils

    return jax.tree.map(
        np.asarray, multihost_utils.process_allgather(tree, tiled=True)
    )
