"""The ONE blessed ``os.environ`` mutation seam.

Host-side analysis CLIs (``apnea-uq topo``, the ``apnea-uq check``
meta-gate) want an 8-device CPU rig so topology rules can interpret
sharding layouts without a real accelerator.  That takes two env pins
(``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count=8``)
applied *before* jax first imports — and for a while the pin was
copy-pasted into both CLIs, drifting apart one flag at a time.

``apnea-uq conc``'s env-mutation-in-library rule now pins this module
as the only place in the package allowed to write ``os.environ``
(:data:`apnea_uq_tpu.conc.rules.BLESSED_ENV_MODULES`); every other
mutation site is a finding.  Deliberately jax-free: importing jax here
would defeat the "before jax first imports" guard it implements.
"""

from __future__ import annotations

import os
import sys


def pin_host_analysis_rig(devices: int = 8) -> bool:
    """Pin this process to a ``devices``-way CPU rig, if jax has not
    loaded yet.

    Startup-seam contract: callers invoke this before anything that
    imports jax.  Once jax is in ``sys.modules`` the flags are inert
    (the platform is already chosen), so mutating the environment then
    would be pure shared-state hazard for zero effect — we no-op and
    return False instead.  ``JAX_PLATFORMS`` is a setdefault (an
    explicit operator choice wins); the device-count flag is appended
    only when absent so a caller-provided ``XLA_FLAGS`` survives.

    Returns True when the pins were applied (or already present and we
    re-affirmed them), False when jax was already imported.
    """
    if "jax" in sys.modules:
        return False
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={int(devices)}"
        ).strip()
    return True
