"""Hypothesis property sweep for telemetry/digest.py (ISSUE 18
satellite): across arbitrary in-range sample sets, merge groupings, and
merge orders, the digest keeps its three contracts — merged percentiles
within the documented ``REL_ERROR_BOUND`` of ``np.percentile`` over the
pooled raw samples, bit-exact count conservation under any merge order
(including empty digests in the mix), and a lossless payload round
trip.  Complements tests/test_digest.py's seeded cases with
generator-driven shrinking counterexamples."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from apnea_uq_tpu.telemetry.digest import (  # noqa: E402
    HI,
    LO,
    REL_ERROR_BOUND,
    LatencyDigest,
    merge_payloads,
)

# In-range latency samples: the documented bound is conditional on
# [LO, HI) (out-of-range samples clamp, by design), so the property
# sweep generates inside it.  Spanning 9+ decades keeps the generator
# honest about bin-ladder coverage.
_sample = st.floats(min_value=LO, max_value=HI * 0.99,
                    allow_nan=False, allow_infinity=False)
_samples = st.lists(_sample, min_size=1, max_size=200)
_sample_groups = st.lists(st.lists(_sample, min_size=0, max_size=80),
                          min_size=1, max_size=6)
_quantile = st.floats(min_value=0.0, max_value=100.0, allow_nan=False)


@settings(max_examples=200, deadline=None)
@given(samples=_samples, q=_quantile)
def test_percentile_within_documented_bound(samples, q):
    d = LatencyDigest("s")
    d.extend(samples)
    got = d.percentile(q)
    want = float(np.percentile(np.asarray(samples, np.float64), q))
    assert got == pytest.approx(want, rel=REL_ERROR_BOUND)


@settings(max_examples=100, deadline=None)
@given(groups=_sample_groups, q=_quantile, seed=st.integers(0, 2**16))
def test_merged_digest_matches_pooled_samples_any_order(groups, q, seed):
    digests = []
    for group in groups:
        d = LatencyDigest("s")
        d.extend(group)
        digests.append(d)
    order = np.random.default_rng(seed).permutation(len(digests))
    acc = LatencyDigest("s")
    for i in order:
        acc.merge(digests[i])
    pooled = np.concatenate(
        [np.asarray(g, np.float64) for g in groups]) if any(
            groups) else np.asarray([])
    # Exact conservation, regardless of merge order and empty members.
    assert acc.count == pooled.size
    if pooled.size == 0:
        assert acc.percentile(q) is None
        return
    want = float(np.percentile(pooled, q))
    assert acc.percentile(q) == pytest.approx(want, rel=REL_ERROR_BOUND)


@settings(max_examples=100, deadline=None)
@given(groups=_sample_groups)
def test_merge_is_order_invariant_bitwise(groups):
    digests = []
    for group in groups:
        d = LatencyDigest("s")
        d.extend(group)
        digests.append(d)

    def fold(order):
        acc = LatencyDigest("s")
        for i in order:
            acc.merge(digests[i])
        return acc

    forward = fold(range(len(digests)))
    backward = fold(reversed(range(len(digests))))
    assert forward.counts == backward.counts
    assert forward.underflow == backward.underflow
    assert forward.overflow == backward.overflow


@settings(max_examples=100, deadline=None)
@given(samples=_samples)
def test_payload_round_trip_preserves_everything(samples):
    d = LatencyDigest("ms")
    d.extend(samples)
    back = LatencyDigest.from_payload(d.to_payload())
    assert back.unit == d.unit
    assert back.counts == d.counts
    assert back.count == d.count
    # And transports through the merge helper unchanged.
    again = merge_payloads([d.to_payload()])
    assert again.counts == d.counts
