"""Grouped splitting and in-tree SMOTE / RUS rebalancing."""

import numpy as np
import pytest

from apnea_uq_tpu.data.sampling import (
    _minority_knn,
    grouped_train_test_split,
    random_undersample,
    smote_oversample,
    verify_no_group_overlap,
)


def make_grouped(rng, n_patients=20, per_patient=30):
    groups = np.repeat([f"p{i:03d}" for i in range(n_patients)], per_patient)
    return groups


class TestGroupedSplit:
    def test_no_patient_overlap(self, rng):
        groups = make_grouped(rng)
        tr, te = grouped_train_test_split(groups, test_size=0.2, seed=2025)
        verify_no_group_overlap(groups, tr, te)  # must not raise
        assert len(tr) + len(te) == len(groups)
        assert np.intersect1d(tr, te).size == 0

    def test_deterministic(self, rng):
        groups = make_grouped(rng)
        a = grouped_train_test_split(groups, seed=2025)
        b = grouped_train_test_split(groups, seed=2025)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])
        c = grouped_train_test_split(groups, seed=7)
        assert not np.array_equal(a[1], c[1])

    def test_test_fraction_of_groups(self, rng):
        groups = make_grouped(rng, n_patients=10)
        _, te = grouped_train_test_split(groups, test_size=0.2, seed=0)
        assert len(np.unique(groups[te])) == 2  # 20% of 10 patients

    def test_overlap_check_raises(self):
        groups = np.array(["a", "a", "b"])
        with pytest.raises(ValueError, match="both train and test"):
            verify_no_group_overlap(groups, np.array([0]), np.array([1, 2]))

    @pytest.mark.parametrize("seed", [0, 7, 2025])
    @pytest.mark.parametrize("test_size", [0.2, 0.3])
    def test_bit_identical_to_sklearn(self, rng, seed, test_size):
        """The in-tree split replicates sklearn's GroupShuffleSplit
        exactly (including seed 2025, the reference's split seed at
        prepare_numpy_datasets.py:140-142), so datasets prepared here
        contain exactly the patients the reference's pipeline selected."""
        sklearn = pytest.importorskip("sklearn.model_selection")

        # Unsorted, uneven group sizes — the shapes np.unique must handle.
        groups = rng.choice([f"p{i:03d}" for i in range(23)], size=400)
        tr, te = grouped_train_test_split(groups, test_size=test_size, seed=seed)
        splitter = sklearn.GroupShuffleSplit(
            n_splits=1, test_size=test_size, random_state=seed
        )
        tr_ref, te_ref = next(splitter.split(np.zeros(len(groups)), groups=groups))
        np.testing.assert_array_equal(tr, tr_ref)
        np.testing.assert_array_equal(te, te_ref)

    def test_every_group_lands_somewhere(self, rng):
        """Regression: floor((1-t)*n) sizing dropped a group entirely for
        (test_size, n_groups) pairs where float rounding lands just below
        an integer — train must be the exact complement of test."""
        sklearn = pytest.importorskip("sklearn.model_selection")
        for n_groups, test_size in [(5, 0.8), (90, 0.3), (170, 0.3), (10, 0.33)]:
            groups = np.repeat([f"g{i}" for i in range(n_groups)], 2)
            tr, te = grouped_train_test_split(groups, test_size=test_size, seed=0)
            assert len(tr) + len(te) == len(groups)
            splitter = sklearn.GroupShuffleSplit(
                n_splits=1, test_size=test_size, random_state=0
            )
            tr_ref, te_ref = next(
                splitter.split(np.zeros(len(groups)), groups=groups)
            )
            np.testing.assert_array_equal(tr, tr_ref)
            np.testing.assert_array_equal(te, te_ref)

    def test_bad_test_size_raises(self):
        with pytest.raises(ValueError, match="test_size"):
            grouped_train_test_split(np.array(["a", "b"]), test_size=1.0)

    def test_empty_train_raises(self):
        # sklearn raises here too; a silent empty train set would NaN
        # downstream standardization.
        with pytest.raises(ValueError, match="no training groups"):
            grouped_train_test_split(np.array(["a", "a"]), test_size=0.5)


class TestMinorityKnn:
    def test_matches_brute_force(self, rng):
        x = rng.normal(size=(50, 12)).astype(np.float32)
        got = _minority_knn(x, 5, chunk=16)
        d = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(d, np.inf)
        expect = np.argsort(d, axis=1)[:, :5]
        # Compare as sets per row (ties may order differently).
        for r in range(50):
            assert set(got[r].tolist()) == set(expect[r].tolist())

    def test_k_capped_at_n_minus_1(self, rng):
        x = rng.normal(size=(4, 3)).astype(np.float32)
        got = _minority_knn(x, 10)
        assert got.shape == (4, 3)


class TestSmote:
    def test_balances_classes(self, rng):
        x = rng.normal(size=(120, 240)).astype(np.float32)
        y = np.concatenate([np.zeros(100, np.int8), np.ones(20, np.int8)])
        xs, ys = smote_oversample(x, y, seed=2025)
        assert (ys == 0).sum() == (ys == 1).sum() == 100
        assert xs.shape == (200, 240)
        # Originals preserved as a prefix (imblearn order).
        np.testing.assert_array_equal(xs[:120], x)
        np.testing.assert_array_equal(ys[:120], y)

    def test_synthetic_on_segment_between_minority_points(self, rng):
        """Every synthetic sample lies on a segment between two minority
        samples (the SMOTE construction)."""
        x = rng.normal(size=(40, 3)).astype(np.float32)
        y = np.concatenate([np.zeros(30, np.int8), np.ones(10, np.int8)])
        xs, ys = smote_oversample(x, y, seed=0)
        minority = x[y == 1]
        for s in xs[40:]:
            # s = a + u (b - a): the residual from the closest pair model
            # must vanish for some (a, b) minority pair.
            ok = False
            for i in range(len(minority)):
                for j in range(len(minority)):
                    if i == j:
                        continue
                    a, b = minority[i], minority[j]
                    denom = ((b - a) ** 2).sum()
                    if denom == 0:
                        continue
                    u = float(((s - a) * (b - a)).sum() / denom)
                    if -1e-4 <= u <= 1 + 1e-4:
                        resid = np.abs(s - (a + u * (b - a))).max()
                        if resid < 1e-4:
                            ok = True
                            break
                if ok:
                    break
            assert ok, "synthetic sample not on any minority segment"

    def test_deterministic(self, rng):
        x = rng.normal(size=(60, 8)).astype(np.float32)
        y = (rng.uniform(size=60) > 0.75).astype(np.int8)
        a = smote_oversample(x, y, seed=3)
        b = smote_oversample(x, y, seed=3)
        np.testing.assert_array_equal(a[0], b[0])

    def test_already_balanced_is_identity(self, rng):
        x = rng.normal(size=(40, 5)).astype(np.float32)
        y = np.concatenate([np.zeros(20, np.int8), np.ones(20, np.int8)])
        xs, ys = smote_oversample(x, y)
        np.testing.assert_array_equal(xs, x)

    def test_single_class_raises(self, rng):
        x = rng.normal(size=(10, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="two classes"):
            smote_oversample(x, np.zeros(10, np.int8))

    def test_single_minority_sample_raises(self, rng):
        x = rng.normal(size=(10, 4)).astype(np.float32)
        y = np.zeros(10, np.int8)
        y[0] = 1
        with pytest.raises(ValueError, match="at least 2"):
            smote_oversample(x, y)


class TestRus:
    def test_balances_and_preserves_order(self, rng):
        x = rng.normal(size=(100, 7)).astype(np.float32)
        y = np.concatenate([np.zeros(80, np.int8), np.ones(20, np.int8)])
        ids = np.array([f"w{i}" for i in range(100)])
        xr, yr, (ids_r,) = random_undersample(x, y, seed=2025, extras=(ids,))
        assert (yr == 0).sum() == (yr == 1).sum() == 20
        assert xr.shape == (40, 7)
        # Kept rows appear in original relative order with aligned extras.
        kept_order = [int(s[1:]) for s in ids_r]
        assert kept_order == sorted(kept_order)
        np.testing.assert_array_equal(xr, x[kept_order])

    def test_deterministic(self, rng):
        x = rng.normal(size=(50, 2)).astype(np.float32)
        y = (rng.uniform(size=50) > 0.7).astype(np.int8)
        a = random_undersample(x, y, seed=1)
        b = random_undersample(x, y, seed=1)
        np.testing.assert_array_equal(a[0], b[0])
