"""The telemetry layer (ISSUE 2 tentpole): JSONL event schema round-trip,
stage bracketing and error capture, the active-run mirror of ``log()``,
StepMetrics dispatch/device separation + the jax.monitoring recompile
counter (fired by a forced retrace), Timer's block-until-ready contract,
and a golden render of ``telemetry summarize`` over a handwritten event
log (the summarizer reads events.jsonl alone, so the golden pins both the
schema and the table format)."""

import json
import logging
import os

import jax
import jax.numpy as jnp
import pytest

from apnea_uq_tpu import telemetry
from apnea_uq_tpu.telemetry.runlog import _ACTIVE, RunLog
from apnea_uq_tpu.telemetry.steps import StepMetrics, compile_counts, \
    install_compile_listener
from apnea_uq_tpu.utils.timing import Timer


@pytest.fixture(autouse=True)
def _no_leaked_active_run():
    """Every test must leave the process-global active-run stack empty —
    a leaked entry would silently mirror later tests' log() lines."""
    assert not _ACTIVE, f"active-run stack dirty on entry: {_ACTIVE}"
    yield
    leaked = list(_ACTIVE)
    _ACTIVE.clear()
    assert not leaked, f"test leaked active run logs: {leaked}"


def _fake_clock(start=1_700_000_000.0, step=1.0):
    state = {"t": start - step}

    def clock():
        state["t"] += step
        return state["t"]

    return clock


class TestRunLogSchema:
    def test_event_envelope_and_roundtrip(self, tmp_path):
        rl = RunLog(str(tmp_path), _clock=_fake_clock())
        rl.event("custom", alpha=1, beta=[1.5, 2.5])
        rl.event("custom", gamma="x")
        rl.close()

        events = telemetry.read_events(str(tmp_path))
        # close() appends run_finished, so 3 events, seq dense from 0.
        assert [e["seq"] for e in events] == [0, 1, 2]
        assert [e["kind"] for e in events] == [
            "custom", "custom", "run_finished"]
        assert events[0]["ts"] == 1_700_000_000.0
        assert events[0]["alpha"] == 1 and events[0]["beta"] == [1.5, 2.5]
        assert events[1]["gamma"] == "x"
        assert events[2]["status"] == "ok"

    def test_run_started_carries_topology_config_hash_argv(self, tmp_path):
        from apnea_uq_tpu.config import ExperimentConfig

        cfg = ExperimentConfig()
        rl = telemetry.start_run(str(tmp_path), stage="train", config=cfg,
                                 argv=["train", "--registry", "r"])
        rl.close()
        started = telemetry.read_events(str(tmp_path))[0]
        assert started["kind"] == "run_started"
        assert started["schema_version"] == telemetry.SCHEMA_VERSION
        assert started["stage"] == "train"
        assert started["argv"] == ["train", "--registry", "r"]
        assert started["config_hash"] == telemetry.config_hash(cfg)
        topo = started["topology"]
        assert topo["platform"] == "cpu"
        assert topo["device_count"] == jax.device_count()
        # start_run also snapshots the full config next to the events.
        with open(tmp_path / "config.json") as f:
            assert "train" in json.load(f)

    def test_config_hash_tracks_config_identity(self):
        import dataclasses

        from apnea_uq_tpu.config import ExperimentConfig

        a, b = ExperimentConfig(), ExperimentConfig()
        assert telemetry.config_hash(a) == telemetry.config_hash(b)
        c = dataclasses.replace(
            a, train=dataclasses.replace(a.train, num_epochs=99))
        assert telemetry.config_hash(a) != telemetry.config_hash(c)

    def test_stage_brackets_and_inherits(self, tmp_path):
        rl = RunLog(str(tmp_path))
        with rl.stage("fit", members=4):
            rl.event("epoch", loss=0.5)
        rl.close()
        start, epoch, end, _fin = telemetry.read_events(str(tmp_path))
        assert (start["kind"], start["stage"], start["members"]) == (
            "stage_start", "fit", 4)
        assert epoch["stage"] == "fit"  # inherited from the open stage
        assert end["kind"] == "stage_end" and end["status"] == "ok"
        assert end["wall_s"] >= 0

    def test_stage_records_escaping_exception(self, tmp_path):
        rl = RunLog(str(tmp_path))
        with pytest.raises(ValueError, match="boom"):
            with rl.stage("fit"):
                raise ValueError("boom")
        rl.close()
        kinds = [e["kind"] for e in telemetry.read_events(str(tmp_path))]
        assert kinds == ["stage_start", "error", "stage_end", "run_finished"]
        events = telemetry.read_events(str(tmp_path))
        assert events[1]["error"] == "ValueError: boom"
        assert events[2]["status"] == "error"

    def test_context_manager_exit_records_error_status(self, tmp_path):
        with pytest.raises(RuntimeError):
            with RunLog(str(tmp_path)) as rl:
                rl.event("work")
                raise RuntimeError("run died")
        events = telemetry.read_events(str(tmp_path))
        assert events[-1]["kind"] == "run_finished"
        assert events[-1]["status"] == "error"
        assert any(e["kind"] == "error" for e in events)

    def test_one_exception_yields_one_error_event(self, tmp_path):
        """A failure inside a stage unwinds through stage() AND the run's
        __exit__ — but one exception must count as one error, or
        `summarize` inflates the failure count operators triage from."""
        with pytest.raises(ValueError):
            with RunLog(str(tmp_path)) as rl:
                with rl.stage("fit"):
                    raise ValueError("single failure")
        events = telemetry.read_events(str(tmp_path))
        errors = [e for e in events if e["kind"] == "error"]
        assert len(errors) == 1, errors
        assert errors[0]["error"] == "ValueError: single failure"
        # A later, DIFFERENT exception is a new error event.
        rl2 = RunLog(str(tmp_path))
        with pytest.raises(ValueError):
            with rl2.stage("again"):
                raise ValueError("second failure")
        rl2.close()
        errors = [e for e in telemetry.read_events(str(tmp_path))
                  if e["kind"] == "error"]
        assert len(errors) == 2

    def test_torn_tail_line_is_tolerated(self, tmp_path):
        rl = RunLog(str(tmp_path))
        rl.event("whole", n=1)
        rl.close()
        path = tmp_path / telemetry.EVENTS_FILENAME
        with open(path, "a") as f:
            f.write('{"seq": 99, "kind": "torn')  # killed mid-write
        events = telemetry.read_events(str(tmp_path))
        assert [e["kind"] for e in events] == ["whole", "run_finished"]

    def test_read_events_empty_when_no_log(self, tmp_path):
        assert telemetry.read_events(str(tmp_path / "nowhere")) == []

    def test_disabled_runlog_is_inert_but_api_complete(self, tmp_path):
        rl = RunLog(str(tmp_path / "sub"), disabled=True)
        rl.run_started(stage="x")
        with rl.stage("s"):
            rl.event("e")
        rl.close()
        assert not os.path.exists(tmp_path / "sub")


class TestActiveRunMirror:
    def test_log_mirrors_into_active_run(self, tmp_path, capsys):
        rl = telemetry.start_run(str(tmp_path), stage="train")
        assert telemetry.current_run() is rl
        telemetry.log("hello from the library")
        rl.close()
        assert telemetry.current_run() is None
        assert "hello from the library" in capsys.readouterr().out
        logs = [e for e in telemetry.read_events(str(tmp_path))
                if e["kind"] == "log"]
        assert [e["message"] for e in logs] == ["hello from the library"]

    def test_log_without_active_run_only_prints(self, capsys):
        telemetry.log("plain line")
        assert capsys.readouterr().out == "plain line\n"

    def test_log_respects_stdlib_logging_level(self, capsys):
        logger = telemetry.get_logger()
        old = logger.level
        try:
            logger.setLevel(logging.WARNING)
            telemetry.log("silenced info line")
            telemetry.log("warned line", level=logging.WARNING)
        finally:
            logger.setLevel(old)
        out = capsys.readouterr().out
        assert "silenced info line" not in out
        assert "warned line" in out

    def test_narration_to_stderr_scopes_stream_and_keeps_mirror(
            self, tmp_path, capsys):
        """bench.py's one-JSON-line stdout contract: inside the scope,
        log() lines land on stderr (never stdout); outside, behavior is
        restored; the run-log mirror sees both either way."""
        from apnea_uq_tpu.telemetry.logging_shim import narration_to_stderr

        rl = telemetry.start_run(str(tmp_path))
        with narration_to_stderr():
            telemetry.log("narrated aside")
        telemetry.log("back on stdout")
        rl.close()
        captured = capsys.readouterr()
        assert "narrated aside" in captured.err
        assert "narrated aside" not in captured.out
        assert "back on stdout" in captured.out
        logs = [e["message"] for e in telemetry.read_events(str(tmp_path))
                if e["kind"] == "log"]
        assert logs == ["narrated aside", "back on stdout"]

    def test_nested_runs_innermost_wins(self, tmp_path):
        outer = telemetry.start_run(str(tmp_path / "outer"))
        inner = telemetry.start_run(str(tmp_path / "inner"))
        assert telemetry.current_run() is inner
        inner.close()
        assert telemetry.current_run() is outer
        outer.close()


class TestStepMetrics:
    def test_measure_returns_result_and_records(self, tmp_path):
        rl = RunLog(str(tmp_path))
        metrics = StepMetrics(rl)
        out = metrics.measure("mul", lambda: jnp.ones((8,)) * 3, n_items=8)
        rl.close()
        assert float(out[0]) == 3.0
        record = metrics.last
        assert 0 < record.dispatch_s <= record.device_s
        assert record.items_per_s > 0
        step = next(e for e in telemetry.read_events(str(tmp_path))
                    if e["kind"] == "step")
        assert step["label"] == "mul" and step["n_items"] == 8
        assert step["device_s"] >= step["dispatch_s"] > 0
        assert step["items_per_s"] > 0
        assert {"retraces", "backend_compiles"} <= set(step)

    def test_run_log_optional(self):
        metrics = StepMetrics(None)
        assert metrics.measure("host", lambda: 41 + 1) == 42
        assert metrics.totals()["steps"] == 1

    def test_recompile_counter_fires_on_forced_retrace(self):
        if not install_compile_listener():
            pytest.skip("this jax build lacks jax.monitoring listeners")

        @jax.jit
        def f(v):
            return v * 2

        metrics = StepMetrics(None)
        metrics.measure("cold", lambda: f(jnp.ones((3,))))
        # A new input SHAPE forces a retrace + XLA recompile of f; the
        # per-step counter delta is exactly what makes a silent retrace
        # storm (the vmap-over-members failure mode) visible.
        metrics.measure("retrace", lambda: f(jnp.ones((5,))))
        cold, retraced = metrics.records
        assert retraced.retraces >= 1, (cold, retraced)
        # Same shape again: cached program, no new trace or compile.
        metrics.measure("warm", lambda: f(jnp.ones((5,))))
        assert metrics.records[2].retraces == 0
        assert metrics.records[2].backend_compiles == 0

    def test_compile_counts_snapshot_is_cumulative(self):
        if not install_compile_listener():
            pytest.skip("this jax build lacks jax.monitoring listeners")

        @jax.jit
        def g(v):
            return v + 1

        before = compile_counts()
        g(jnp.ones((7,)))
        after = compile_counts()
        assert after["retraces"] >= before["retraces"] + 1


class TestTimerBlocking:
    def test_wrap_blocks_result_before_reading_clock(self):
        with Timer("t", block=True) as t:
            out = t.wrap(jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))))
        assert t.result is out
        assert t.elapsed_s > 0

    def test_block_false_never_blocks(self):
        with Timer("t") as t:
            t.result = object()  # not a jax type; blocking on it would raise
        assert t.elapsed_s > 0

    def test_escaping_exception_skips_blocking(self):
        with pytest.raises(KeyError):
            with Timer("t", block=True) as t:
                t.wrap(object())  # garbage result; must not be blocked on
                raise KeyError("died mid-computation")
        assert t.elapsed_s > 0

    def test_verbose_routes_through_telemetry_log(self, tmp_path, capsys):
        rl = telemetry.start_run(str(tmp_path))
        with Timer("timed_region", verbose=True):
            pass
        rl.close()
        assert "[timed_region]" in capsys.readouterr().out
        logs = [e for e in telemetry.read_events(str(tmp_path))
                if e["kind"] == "log"]
        assert any("[timed_region]" in e["message"] for e in logs)


# Handwritten event log for the golden render: fixed timestamps and
# pre-rounded floats so the expected text is byte-stable.  Mirrors one
# tiny train run (two epochs + an eval) the schema docs describe.
_GOLDEN_EVENTS = [
    {"seq": 0, "ts": 1700000000.0, "kind": "run_started",
     "schema_version": 1, "stage": "train",
     "config_hash": "abcdef0123456789" + "0" * 48,
     "topology": {"platform": "cpu", "device_count": 8}},
    {"seq": 1, "ts": 1700000000.1, "kind": "stage_start", "stage": "fit"},
    {"seq": 2, "ts": 1700000001.0, "kind": "step", "stage": "fit",
     "label": "train_epoch", "dispatch_s": 0.25, "device_s": 1.0,
     "retraces": 12, "backend_compiles": 1, "n_items": 512,
     "items_per_s": 512.0},
    {"seq": 3, "ts": 1700000001.1, "kind": "epoch", "stage": "fit",
     "epoch": 1, "loss": 0.68, "val_loss": 0.66},
    {"seq": 4, "ts": 1700000002.0, "kind": "step", "stage": "fit",
     "label": "train_epoch", "dispatch_s": 0.05, "device_s": 0.6,
     "retraces": 0, "backend_compiles": 0, "n_items": 512,
     "items_per_s": 853.333},
    {"seq": 5, "ts": 1700000002.1, "kind": "epoch", "stage": "fit",
     "epoch": 2, "loss": 0.52, "val_loss": 0.55},
    {"seq": 6, "ts": 1700000002.2, "kind": "stage_end", "stage": "fit",
     "wall_s": 2.1, "status": "ok"},
    {"seq": 7, "ts": 1700000002.3, "kind": "stage_start",
     "stage": "CNN_MCD_Unbalanced"},
    {"seq": 8, "ts": 1700000003.0, "kind": "eval_predict",
     "stage": "CNN_MCD_Unbalanced", "label": "CNN_MCD_Unbalanced",
     "method": "mcd", "n_passes": 50, "n_windows": 1024,
     "predict_s": 0.5, "dispatch_s": 0.1, "windows_per_s": 2048.0,
     "retraces": 4, "backend_compiles": 1},
    {"seq": 9, "ts": 1700000003.1, "kind": "stage_end",
     "stage": "CNN_MCD_Unbalanced", "wall_s": 0.9, "status": "ok"},
    {"seq": 10, "ts": 1700000003.2, "kind": "run_finished", "status": "ok"},
]

_GOLDEN_RENDER = """\
run: golden
started: 2023-11-14T22:13:20Z  stage: train  platform: cpu  devices: 8
config: abcdef012345  schema: v1  events: 11  status: ok

stage                  wall_s  steps   device_s  dispatch_s  retraces  compiles     items/s
fit                     2.100      2      1.600       0.300        12         1       640.0
CNN_MCD_Unbalanced      0.900      -          -           -         -         -           -

epochs: 2  loss 0.6800 -> 0.5200  val_loss 0.6600 -> 0.5500

evals:
  CNN_MCD_Unbalanced: 50x1024 windows in 0.500s (2048.0 windows/s)

errors: none"""


class TestSummarize:
    def _write(self, run_dir, events):
        os.makedirs(run_dir, exist_ok=True)
        with open(os.path.join(run_dir, telemetry.EVENTS_FILENAME), "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")

    def test_golden_render(self, tmp_path):
        run_dir = str(tmp_path / "golden")
        self._write(run_dir, _GOLDEN_EVENTS)
        assert telemetry.summarize_run(run_dir) == _GOLDEN_RENDER

    def test_missing_run_dir_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="events"):
            telemetry.summarize_run(str(tmp_path / "void"))

    def test_appended_multi_run_log_renders_latest_run_only(self, tmp_path):
        """bench.py reuses BENCH_RUN_DIR across invocations, appending
        whole runs back-to-back into one events.jsonl; summarize must
        render the latest run (not a merged double-count) and say how
        many earlier runs the log holds."""
        run_dir = str(tmp_path / "reused")
        stale = [dict(e) for e in _GOLDEN_EVENTS]
        stale[3] = {**stale[3], "loss": 9.99}  # a value only run 1 has
        self._write(run_dir, stale + _GOLDEN_EVENTS)
        text = telemetry.summarize_run(run_dir)
        assert "(latest of 2 runs appended to this log" in text
        # Stage rows and epoch counts come from the latest run alone.
        assert "epochs: 2  loss 0.6800 -> 0.5200" in text
        assert "9.99" not in text
        assert "fit                     2.100      2" in text

    def test_all_runs_renders_every_run_oldest_first(self, tmp_path,
                                                     capsys):
        """ISSUE 18 satellite: `summarize --all-runs` renders EVERY run
        of an appended log back to back (oldest first) instead of only
        the latest, and --json carries the machine-readable run count —
        so a replica restart is visible, not silently hidden."""
        from apnea_uq_tpu.cli.main import main

        run_dir = str(tmp_path / "reused")
        stale = [dict(e) for e in _GOLDEN_EVENTS]
        stale[3] = {**stale[3], "loss": 9.99}  # a value only run 1 has
        self._write(run_dir, stale + _GOLDEN_EVENTS)
        assert main(["telemetry", "summarize", run_dir,
                     "--all-runs"]) == 0
        text = capsys.readouterr().out
        assert "=== run 1 of 2 ===" in text
        assert "=== run 2 of 2 ===" in text
        assert "9.99" in text  # run 1's value is back on screen
        assert text.index("9.99") < text.index("=== run 2 of 2 ===")
        assert main(["telemetry", "summarize", run_dir, "--all-runs",
                     "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["run_count"] == 2
        assert len(doc["runs"]) == 2

    def test_errors_and_ensemble_fit_sections(self, tmp_path):
        run_dir = str(tmp_path / "err")
        self._write(run_dir, [
            {"seq": 0, "ts": 1700000000.0, "kind": "run_started",
             "schema_version": 1, "stage": "bench",
             "topology": {"platform": "cpu", "device_count": 1}},
            {"seq": 1, "ts": 1700000001.0, "kind": "ensemble_fit",
             "num_members": 16, "num_requested": 10, "promoted_members": 6,
             "lockstep_epochs": 40, "wasted_member_epochs": 64},
            {"seq": 2, "ts": 1700000002.0, "kind": "error",
             "where": "de_train", "error": "RuntimeError: OOM"},
        ])
        text = telemetry.summarize_run(run_dir)
        assert "16 members (requested 10, promoted 6)" in text
        assert "wasted member-epochs 64" in text
        assert "errors: 1" in text
        assert "[de_train] RuntimeError: OOM" in text

    def test_cli_subcommand_renders(self, tmp_path, capsys):
        from apnea_uq_tpu.cli.main import main

        run_dir = str(tmp_path / "golden")
        self._write(run_dir, _GOLDEN_EVENTS)
        assert main(["telemetry", "summarize", run_dir]) == 0
        assert _GOLDEN_RENDER in capsys.readouterr().out

    def test_cli_subcommand_rejects_non_run_dir(self, tmp_path):
        from apnea_uq_tpu.cli.main import main

        with pytest.raises(SystemExit, match="events"):
            main(["telemetry", "summarize", str(tmp_path)])
