"""Smoke tests for the plotting suite and correctness tests for the
sweep runner (variance monotone noise reduction, schema contract)."""

import numpy as np
import pandas as pd
import pytest

import jax

from apnea_uq_tpu.analysis import (
    aggregate_patients,
    window_level_analysis,
)
from apnea_uq_tpu.analysis import plots
from apnea_uq_tpu.analysis.sweep import de_member_sweep, mcd_pass_sweep
from apnea_uq_tpu.config import ModelConfig, UQConfig
from apnea_uq_tpu.models import AlarconCNN1D, init_variables


def _detailed(rng, n=300):
    true = rng.integers(0, 2, n)
    pred = np.where(rng.uniform(size=n) < 0.8, true, 1 - true)
    return pd.DataFrame({
        "Patient_ID": [f"P{i % 12}" for i in range(n)],
        "Window_Index": np.arange(n),
        "True_Label": true,
        "Predicted_Label": pred,
        "Predicted_Probability": rng.uniform(size=n),
        "Predictive_Variance": rng.uniform(0, 0.25, n),
        "Predictive_Entropy": rng.uniform(0, 1, n),
    })


class TestPlots:
    def test_c11_plots(self, rng, tmp_path):
        values = rng.uniform(size=6000)
        y = rng.integers(0, 2, 6000)
        p1 = plots.plot_uncertainty_metric(
            values, "Predictive_Variance", str(tmp_path / "m.png")
        )
        p2 = plots.plot_class_uncertainties(
            {"class 0": 0.1, "class 1": 0.2}, str(tmp_path / "c.png")
        )
        p3 = plots.plot_metric_distribution(
            values, y, "Predictive_Entropy", str(tmp_path / "d.png")
        )
        for p in (p1, p2, p3):
            assert (tmp_path / p.split("/")[-1]).stat().st_size > 0

    def test_c19_figures(self, rng, tmp_path):
        frames = {"MCD": _detailed(rng), "DE": _detailed(rng)}
        summaries = {k: aggregate_patients(v) for k, v in frames.items()}
        binned = {k: window_level_analysis(v).binned for k, v in frames.items()}
        paths = [
            plots.plot_patient_entropy_histograms(summaries, str(tmp_path / "h.png")),
            plots.plot_accuracy_vs_entropy(summaries, str(tmp_path / "s.png")),
            plots.plot_correct_incorrect_box(frames, str(tmp_path / "b.png")),
            plots.plot_binned_accuracy(binned, str(tmp_path / "a.png")),
        ]
        for p in paths:
            assert (tmp_path / p.split("/")[-1]).stat().st_size > 0

    def test_convergence_plot_schema(self, tmp_path):
        frame = pd.DataFrame({
            "N": [5, 10, 20],
            "Variance_Unbalanced": [0.03, 0.028, 0.027],
            "Variance_Balanced": [0.05, 0.047, 0.046],
        })
        plots.plot_convergence(frame, str(tmp_path / "conv.png"))
        with pytest.raises(ValueError, match="sweep frame"):
            plots.plot_convergence(pd.DataFrame({"K": [1]}), str(tmp_path / "x.png"))


class TestSweep:
    @pytest.fixture(scope="class")
    def setup(self):
        model = AlarconCNN1D(ModelConfig(
            features=(4, 6), kernel_sizes=(3, 3), dropout_rates=(0.3, 0.3)
        ))
        variables = init_variables(model, jax.random.key(0))
        rng = np.random.default_rng(1)
        sets = {
            "Unbalanced": rng.normal(size=(48, 60, 4)).astype(np.float32),
            "Balanced": rng.normal(size=(32, 60, 4)).astype(np.float32),
        }
        return model, variables, sets

    def test_mcd_sweep_schema_and_prefix_property(self, setup):
        model, variables, sets = setup
        cfg = UQConfig(inference_batch_size=32)
        frame = mcd_pass_sweep(
            model, variables, sets, pass_counts=(4, 8, 16), config=cfg,
            key=jax.random.key(3),
        )
        assert list(frame.columns) == ["N", "Variance_Unbalanced", "Variance_Balanced"]
        assert frame["N"].tolist() == [4, 8, 16]
        assert (frame[["Variance_Unbalanced", "Variance_Balanced"]] > 0).all().all()

    def test_mcd_sweep_count_exceeds_raises(self, setup):
        model, variables, sets = setup
        with pytest.raises(ValueError, match="exceeds"):
            # pass_counts max defines T; ask for a subset larger than max
            # via direct table path by giving unsorted counts where a count
            # exceeds the prediction depth is impossible here, so check the
            # DE pool-size guard instead in test_de below.
            de_member_sweep(
                model,
                [init_variables(model, jax.random.key(s)) for s in range(3)],
                sets,
                member_counts=(2, 5),
                config=UQConfig(inference_batch_size=32),
            )

    def test_de_sweep(self, setup):
        model, variables, sets = setup
        members = [init_variables(model, jax.random.key(s)) for s in range(6)]
        frame = de_member_sweep(
            model, members, sets, member_counts=(2, 4, 6),
            config=UQConfig(inference_batch_size=32),
        )
        assert frame["N"].tolist() == [2, 4, 6]
        # Deterministic members: prefix variance of K=6 equals direct calc.
        from apnea_uq_tpu.uq import ensemble_predict
        preds = np.asarray(ensemble_predict(
            model, members, sets["Unbalanced"], batch_size=32
        ))
        expect = float(preds.var(axis=0).mean())
        assert frame.loc[frame["N"] == 6, "Variance_Unbalanced"].iloc[0] == (
            pytest.approx(expect, rel=1e-6)
        )
