"""Classification metric suite vs scikit-learn (ground truth oracle).

sklearn is available in the dev image and used ONLY as a test oracle; the
framework's runtime implementations are first-party
(apnea_uq_tpu/evaluation/classification.py).
"""

import numpy as np
import pytest
import sklearn.metrics as skm

from apnea_uq_tpu.evaluation import (
    average_precision,
    classification_report_dict,
    cohen_kappa,
    confusion_matrix_2x2,
    evaluate_classification,
    matthews_corrcoef,
    roc_auc,
)


@pytest.fixture
def data(rng):
    probs = rng.uniform(size=1000)
    y = (rng.uniform(size=1000) < probs * 0.8 + 0.1).astype(int)
    return y, probs, (probs >= 0.5).astype(int)


def test_roc_auc_matches_sklearn(data):
    y, probs, _ = data
    assert roc_auc(y, probs) == pytest.approx(skm.roc_auc_score(y, probs), abs=1e-10)


def test_roc_auc_with_ties(rng):
    probs = rng.integers(0, 5, 500) / 4.0  # heavy ties
    y = rng.integers(0, 2, 500)
    assert roc_auc(y, probs) == pytest.approx(skm.roc_auc_score(y, probs), abs=1e-10)


def test_average_precision_matches_sklearn(data):
    y, probs, _ = data
    assert average_precision(y, probs) == pytest.approx(
        skm.average_precision_score(y, probs), abs=1e-10
    )


def test_average_precision_with_ties(rng):
    probs = rng.integers(0, 8, 600) / 7.0
    y = rng.integers(0, 2, 600)
    assert average_precision(y, probs) == pytest.approx(
        skm.average_precision_score(y, probs), abs=1e-10
    )


def test_kappa_mcc_match_sklearn(data):
    y, _, pred = data
    assert cohen_kappa(y, pred) == pytest.approx(skm.cohen_kappa_score(y, pred), abs=1e-10)
    assert matthews_corrcoef(y, pred) == pytest.approx(
        skm.matthews_corrcoef(y, pred), abs=1e-10
    )


def test_confusion_matrix(data):
    y, _, pred = data
    np.testing.assert_array_equal(
        confusion_matrix_2x2(y, pred), skm.confusion_matrix(y, pred, labels=[0, 1])
    )


def test_confusion_matrix_single_class_padded():
    """2x2 padding when a class is absent (evaluate_classification.py:94-114)."""
    cm = confusion_matrix_2x2([0, 0, 0], [0, 0, 1])
    assert cm.shape == (2, 2)
    assert cm[0, 0] == 2 and cm[0, 1] == 1 and cm[1, :].sum() == 0


def test_report_matches_sklearn(data):
    y, _, pred = data
    ours = classification_report_dict(y, pred)
    theirs = skm.classification_report(y, pred, output_dict=True, zero_division=0)
    for cls in ("0", "1", "macro avg", "weighted avg"):
        for k in ("precision", "recall", "f1-score", "support"):
            assert ours[cls][k] == pytest.approx(theirs[cls][k], abs=1e-10), (cls, k)
    assert ours["accuracy"] == pytest.approx(theirs["accuracy"], abs=1e-10)


def test_single_class_auc_guard():
    """ROC/PR AUC unavailable for single-class y (evaluate_classification.py:77-86)."""
    y = np.zeros(10, int)
    probs = np.linspace(0, 1, 10)
    assert roc_auc(y, probs) is None
    assert average_precision(y, probs) is None
    res = evaluate_classification(probs, y, description="single class")
    assert res["roc_auc"] is None and res["pr_auc"] is None
    assert 0 <= res["accuracy"] <= 1


def test_evaluate_classification_surface(data):
    y, probs, pred = data
    res = evaluate_classification(probs, y, description="test", verbose=False)
    assert res["accuracy"] == pytest.approx(skm.accuracy_score(y, pred), abs=1e-12)
    cm = res["confusion_matrix"]
    tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
    assert res["sensitivity"] == pytest.approx(tp / (tp + fn))
    assert res["specificity"] == pytest.approx(tn / (tn + fp))
