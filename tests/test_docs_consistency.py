"""Docs-vs-code consistency: every `apnea-uq <subcommand>` and every
`--flag` named in the user-facing docs must actually exist, and the
README's dependency claims must match the package's actual imports, so
the migration guide and README cannot silently rot as the code evolves."""

import ast
import re
from pathlib import Path

from apnea_uq_tpu.cli.main import build_parser

REPO = Path(__file__).resolve().parent.parent
DOCS = [REPO / "README.md", REPO / "docs" / "MIGRATION.md",
        REPO / "docs" / "OBSERVABILITY.md", REPO / "docs" / "LINT.md",
        REPO / "docs" / "PIPELINE.md",
        REPO / "docs" / "BENCH_TRAJECTORY.md",
        REPO / "docs" / "TOPOLOGY.md",
        REPO / "docs" / "SERVING.md"]

# README "Environment": packages claimed absent at runtime.  The claim
# rotted once (r2 verdict: sklearn/scipy imports on the prepare and
# analysis paths), so it is now enforced against the package's AST.
CLAIMED_ABSENT = ("tensorflow", "sklearn", "imblearn", "pyedflib", "scipy")


def _subparsers(parser):
    for action in parser._actions:
        if hasattr(action, "choices") and isinstance(action.choices, dict):
            return action.choices
    raise AssertionError("no subparsers found on the CLI parser")


def _code_text(doc: Path) -> str:
    """Only backticked spans and fenced code blocks — commands in the docs
    always live in code context, and prose mentioning `apnea-uq` as a word
    must not produce phantom subcommands."""
    text = doc.read_text().replace("\\\n", " ")  # join shell continuations
    fenced = re.findall(r"```[a-z]*\n(.*?)```", text, re.S)
    inline = re.findall(r"`([^`]*)`", text)
    return "\n".join(fenced + inline)


def test_documented_subcommands_exist():
    commands = set(_subparsers(build_parser()))
    documented = set()
    for doc in DOCS:
        documented.update(
            re.findall(r"apnea-uq ([a-z][a-z0-9-]*)", _code_text(doc))
        )
    missing = documented - commands
    assert not missing, f"docs reference unknown subcommands: {sorted(missing)}"
    # And the docs should cover the pipeline's core stages.
    for core in ("ingest", "prepare", "train", "train-ensemble", "eval-mcd",
                 "eval-de", "demo"):
        assert core in documented, f"core stage {core!r} undocumented"


def _imported_modules(path: Path) -> set:
    """Top-level module names imported anywhere in a source file (both
    module-level and function-local imports — a lazy import is still a
    runtime dependency)."""
    tree = ast.parse(path.read_text())
    mods = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            mods.update(alias.name.split(".")[0] for alias in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            mods.add(node.module.split(".")[0])
    return mods


def test_readme_dependency_claims_match_imports():
    """README claims these packages are not runtime dependencies; no file
    in the package may import them.  (`jax.scipy` is jax, not scipy —
    the AST walk sees only the top-level name, so it does not trip.)"""
    readme = (REPO / "README.md").read_text().lower()
    for name in CLAIMED_ABSENT:
        assert name.replace("sklearn", "scikit-learn") in readme or name in readme, (
            f"README no longer mentions {name!r}; update CLAIMED_ABSENT "
            f"to track the current dependency claims"
        )
    offenders = {}
    for path in sorted((REPO / "apnea_uq_tpu").rglob("*.py")):
        bad = _imported_modules(path) & set(CLAIMED_ABSENT)
        if bad:
            offenders[str(path.relative_to(REPO))] = sorted(bad)
    assert not offenders, (
        f"README claims no runtime dependency on {CLAIMED_ABSENT}, but the "
        f"package imports them: {offenders}"
    )


def test_documented_flags_exist_per_subcommand():
    """Within a documented command line, every --flag after
    `apnea-uq <sub>` must be a real option of that subcommand."""
    subs = _subparsers(build_parser())
    checked = 0
    for doc in DOCS:
        for m in re.finditer(
            r"apnea-uq ([a-z][a-z0-9-]*)((?:[ \t]+[^\s`|]+)*)",
            _code_text(doc),
        ):
            name, rest = m.group(1), m.group(2)
            if name not in subs:
                continue  # covered by the other test
            parser = subs[name]
            # Descend into nested command groups (`apnea-uq telemetry
            # compare --json` must be checked against the *compare*
            # subparser, not the bare `telemetry` group).
            tokens = rest.split()
            while tokens:
                nested = next(
                    (action.choices for action in parser._actions
                     if hasattr(action, "choices")
                     and isinstance(action.choices, dict)),
                    None,
                )
                if not nested or tokens[0] not in nested:
                    break
                parser = nested[tokens[0]]
                tokens = tokens[1:]
            known = {
                opt for action in parser._actions
                for opt in action.option_strings
            }
            for flag in re.findall(r"--[a-z][a-z0-9-]*", rest):
                assert flag in known, (
                    f"docs show `apnea-uq {name} ... {flag}` but that "
                    f"subcommand has no such flag (has {sorted(known)})"
                )
                checked += 1
    assert checked >= 10, "flag extraction matched suspiciously few flags"


def test_config_streaming_comments_track_mesh_support():
    """The UQConfig streaming comment rotted in r3: it said "the mesh is
    not used on these paths" in the same round the streamed predictors
    gained mesh composition.  Dataclass comments are user-facing docs
    too, so pin the claim to the code: the streamed predictors DO take a
    mesh, and no config comment may deny it."""
    import inspect

    from apnea_uq_tpu import config as config_mod
    from apnea_uq_tpu.uq import predict

    for fn in (predict.mc_dropout_predict_streaming,
               predict.ensemble_predict_streaming):
        assert "mesh" in inspect.signature(fn).parameters, (
            f"{fn.__name__} lost its mesh parameter; update the UQConfig "
            "streaming comment (and this test) to match"
        )
    src = inspect.getsource(config_mod)
    for stale in ("mesh is not used", "single-device (the mesh"):
        assert stale not in src, (
            f"config.py claims {stale!r} but the streamed predictors "
            "compose with the mesh"
        )
    # The comment block above mcd_streaming must acknowledge the mesh
    # composition positively, not just avoid denying it.
    uq_src = inspect.getsource(config_mod.UQConfig)
    comment = uq_src.split("mcd_streaming: bool")[0].rsplit("# Stream", 1)[-1]
    assert "mesh" in comment, (
        "the UQConfig streaming comment no longer mentions how streaming "
        "composes with the mesh"
    )


def test_parity_mode_docstrings_agree_on_chunk_stats():
    """r3 shipped contradictory docs: UQConfig called 'parity' mode
    "byte-for-byte the reference" while mc_dropout_predict documented
    that exact parity needs batch_size >= len(x) (BN statistics are
    per-chunk).  Both docstrings must state the whole-set-batch caveat,
    and neither may overclaim byte-for-byte."""
    from apnea_uq_tpu.config import UQConfig
    from apnea_uq_tpu.uq.predict import mc_dropout_predict

    for name, doc in (("UQConfig", UQConfig.__doc__),
                      ("mc_dropout_predict", mc_dropout_predict.__doc__)):
        assert "byte-for-byte" not in doc, f"{name} overclaims exact parity"
        # '>=' was itself an overclaim (a larger non-multiple chunk
        # wrap-pads windows unevenly into the BN batch statistics); the
        # docs must advise equality, not >=.
        assert ">= len(x)" not in doc and ">= the window count" not in doc, (
            f"{name} advises batch_size >= the set, but wrap-padding "
            "makes only exact multiples match whole-set BN statistics"
        )
        assert "multiple of the window count" in doc or "multiple of ``len(x)``" in doc, (
            f"{name} no longer documents that exact parity-mode BN "
            "statistics need the (effective) chunk to be an exact "
            "multiple of the window count"
        )
        # And both must acknowledge the mesh rounding that feeds the
        # effective chunk (the r4 review's silent-non-parity trap).
        assert "EFFECTIVE chunk" in doc, (
            f"{name} no longer mentions the mesh-rounded effective chunk"
        )


def test_design_doc_tracks_chunk_rounding():
    """DESIGN.md's streaming-x-mesh section rotted in r4 (it still said
    'MCD never rounds the chunk up' after both paths gained the shared
    rounding).  Pin the claim to the code: as long as the predictors
    share effective_batch_size, DESIGN.md must describe that and must
    not deny rounding."""
    import inspect

    from apnea_uq_tpu.uq import predict

    design = (REPO / "docs" / "DESIGN.md").read_text()
    if hasattr(predict, "effective_batch_size"):
        assert "never rounds the chunk" not in design, (
            "DESIGN.md denies chunk rounding, but the MCD paths and "
            "streamed DE round via effective_batch_size"
        )
        src = inspect.getsource(predict)
        assert src.count("effective_batch_size(batch_size, mesh)") >= 3, (
            "the shared rounding call sites moved; update this test and "
            "DESIGN.md together"
        )
        assert "effective_batch_size" in design, (
            "DESIGN.md no longer documents the shared chunk rounding"
        )
    else:
        # Renamed/removed helper: DESIGN.md must not keep citing it.
        assert "effective_batch_size" not in design, (
            "predict.effective_batch_size is gone but DESIGN.md still "
            "cites it; update the doc and this test together"
        )


def test_pipeline_doc_matches_live_extraction():
    """docs/PIPELINE.md is *generated* (`apnea-uq flow --update-docs`):
    the dataflow table must equal a fresh render from the live
    registry-access extraction, byte for byte, so the documented
    producer->consumer graph can never drift from the code."""
    from apnea_uq_tpu.flow import run_flow
    from apnea_uq_tpu.flow.pipedoc import GENERATED_MARKER, render_pipeline_doc

    _result, graph = run_flow(
        [str(REPO / "apnea_uq_tpu"), str(REPO / "bench.py")],
        manifest=None,
    )
    assert graph.full_scope, "extraction scope lost registry/stages anchors"
    rendered = render_pipeline_doc(graph)
    on_disk = (REPO / "docs" / "PIPELINE.md").read_text()
    assert GENERATED_MARKER in on_disk, (
        "docs/PIPELINE.md lost its generated-file marker"
    )
    assert on_disk == rendered, (
        "docs/PIPELINE.md is stale — regenerate with "
        "`apnea-uq flow --update-docs`"
    )


def test_bench_trajectory_doc_matches_live_render():
    """docs/BENCH_TRAJECTORY.md is *generated* (`apnea-uq telemetry
    trend --update-docs`): the round ledger must equal a fresh render
    from the archived BENCH_r*.json rounds, byte for byte, so the
    documented trajectory can never drift from the captures (the
    docs/PIPELINE.md discipline)."""
    from apnea_uq_tpu.telemetry import trend as trend_mod

    paths = trend_mod.archived_rounds(str(REPO))
    assert paths, "no archived BENCH_r*/MULTICHIP_r* rounds found"
    # The multichip dryrun twins must be part of the ledger (ISSUE 14
    # satellite: the mesh history is visible, not skipped).
    assert any("MULTICHIP" in p for p in paths), (
        "archived_rounds no longer sweeps MULTICHIP_r*.json"
    )
    rendered = trend_mod.render_trajectory_doc(
        trend_mod.build_trajectory(
            [trend_mod.load_round(p) for p in paths]))
    on_disk = (REPO / "docs" / "BENCH_TRAJECTORY.md").read_text()
    assert trend_mod.GENERATED_MARKER in on_disk, (
        "docs/BENCH_TRAJECTORY.md lost its generated-file marker"
    )
    assert on_disk == rendered, (
        "docs/BENCH_TRAJECTORY.md is stale — regenerate with "
        "`apnea-uq telemetry trend --update-docs`"
    )


def test_topology_doc_matches_manifest_render():
    """docs/TOPOLOGY.md is *generated* (`apnea-uq topo --update-docs`):
    it must equal a fresh render from the committed
    apnea_uq_tpu/topo/manifest.json, byte for byte, so the documented
    per-topology mesh facts can never drift from the golden rows."""
    from apnea_uq_tpu.topo.manifest import (
        GENERATED_MARKER,
        load_manifest,
        render_topology_doc,
    )

    rows = load_manifest()
    assert rows, "no committed topo manifest"
    rendered = render_topology_doc(rows)
    on_disk = (REPO / "docs" / "TOPOLOGY.md").read_text()
    assert GENERATED_MARKER in on_disk, (
        "docs/TOPOLOGY.md lost its generated-file marker"
    )
    assert on_disk == rendered, (
        "docs/TOPOLOGY.md is stale — regenerate with "
        "`apnea-uq topo --update-docs`"
    )


def test_bench_env_knobs_are_documented():
    """bench.py's module docstring is the operator's knob reference for
    the one hardware capture per round; an undocumented knob is
    undiscoverable mid-outage (r5 review caught BENCH_INIT_PROBE_SECS
    missing).  Enforce both directions against the source: every
    BENCH_* env var the script reads appears in the docstring, and the
    docstring names no phantom knobs."""
    source = (REPO / "bench.py").read_text()
    tree = ast.parse(source)
    read = set()
    for node in ast.walk(tree):
        # os.environ.get("BENCH_X", ...), os.getenv("BENCH_X"), and
        # os.environ["BENCH_X"]
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (node.func.attr in ("get", "getenv") and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and str(node.args[0].value).startswith("BENCH_")):
                read.add(node.args[0].value)
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)  # reads, not env writes
                and isinstance(node.slice, ast.Constant)
                and str(node.slice.value).startswith("BENCH_")):
            read.add(node.slice.value)
    assert read, "bench.py reads no BENCH_* knobs? scan is broken"

    docstring = ast.get_docstring(tree) or ""
    documented = set(re.findall(r"BENCH_[A-Z0-9_]+", docstring))
    # The docstring compresses families as BENCH_WINDOWS/PASSES/CHUNK —
    # expand slash-joined suffixes after a BENCH_ prefix (the list may
    # wrap across a line break after a slash).
    for m in re.finditer(r"BENCH_([A-Z0-9_]+(?:/\s*[A-Z0-9_]+)+)", docstring):
        for suffix in re.split(r"/\s*", m.group(1)):
            documented.add(f"BENCH_{suffix}")
    undocumented = read - documented
    assert not undocumented, (
        f"bench.py reads {sorted(undocumented)} but its module docstring "
        "(the operator knob reference) does not mention them"
    )
    phantom = documented - read
    assert not phantom, (
        f"bench.py's docstring documents {sorted(phantom)} but the script "
        "never reads them (knob rot)"
    )
