"""IR-level program audit (ISSUE 8): zoo capture, program rules against
injected violations, manifest round-trip, suppressions at the
zoo-registration site, the `apnea-uq audit` CLI contract, and the
program_audit telemetry read side (summarize + compare).

The acceptance test lowers the FULL zoo on CPU through the real CLI
(no dispatch) and must pass clean against the checked-in manifest; each
violation class — f64 leak, stray cross-member collective, dropped
donation, baked constant, host callback — is injected as a real lowered
synthetic program and must exit 1 with a pointable zoo.py location.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apnea_uq_tpu.audit.capture import CaptureStore, capture_program  # noqa: E402
from apnea_uq_tpu.audit.manifest import (  # noqa: E402
    DEFAULT_MANIFEST_PATH,
    load_manifest,
    manifest_row,
    save_manifest,
    zoo_label_lines,
)
from apnea_uq_tpu.audit.rules import (  # noqa: E402
    ENSEMBLE_AXIS,
    PROGRAM_RULES,
    AuditContext,
    run_program_rules,
)
from apnea_uq_tpu.compilecache.zoo import GROUP_LABELS  # noqa: E402
from apnea_uq_tpu.config import ExperimentConfig, ModelConfig, save_config  # noqa: E402
from apnea_uq_tpu.lint.engine import apply_suppressions, load_files  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ALL_LABELS = sorted({lb for labels in GROUP_LABELS.values() for lb in labels})


@pytest.fixture(scope="module")
def tiny_config_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("audit_cfg") / "config.json")
    save_config(ExperimentConfig(model=ModelConfig(
        features=(8, 12, 8), kernel_sizes=(5, 3, 3),
        dropout_rates=(0.3, 0.4, 0.5))), path)
    return path


# --------------------------------------------------- synthetic captures --

def _capture(label, fn, args, donate_args=(), group="eval-mcd"):
    return capture_program(label, fn, tuple(args), {}, group=group,
                           donate_args=donate_args)


def _context(captures, manifest=None, **kwargs):
    zoo_abs, label_lines = zoo_label_lines()
    rel = os.path.relpath(zoo_abs, REPO).replace(os.sep, "/")
    return AuditContext(programs=captures, manifest=manifest,
                        zoo_path=rel, label_lines=label_lines, **kwargs)


def _clean_capture(label="mcd_predict", group="eval-mcd"):
    return _capture(label, lambda x: jnp.tanh(x) * 2,
                    (jnp.zeros((8,), jnp.float32),), group=group)


def _f64_capture(label="mcd_predict"):
    from jax.experimental import enable_x64

    # Shaped-only f64 (no scalar reduction): the lowered module spells
    # it `tensor<8xf64>`, which a naive \bf64\b regex would MISS ('x'
    # and 'f' are word characters) — this fixture pins the suffix match.
    with enable_x64():
        return _capture(label,
                        lambda x: x.astype(jnp.float64) * 2.0,
                        (jnp.zeros((8,), jnp.float32),))


def _baked_constant_capture(label="predict_eval"):
    weights = jnp.asarray(
        np.random.default_rng(0).normal(size=(130, 200)).astype(np.float32))

    def fn(x):
        return x @ weights  # closes over 104 KB of weights -> jaxpr const

    return _capture(label, fn, (jnp.zeros((4, 130), jnp.float32),))


def _dropped_donation_capture(label="ensemble_epoch"):
    """Donation declared on an argument no output can alias (different
    shape): the compiled executable ends up with zero input-output
    aliases — the observable signature of an export-dropped donation."""
    def fn(state, x):
        return x * 2.0

    return _capture(label, fn, (jnp.zeros((16,), jnp.float32),
                                jnp.zeros((4,), jnp.float32)),
                    donate_args=(0,), group="train-ensemble")


def _export_round_trip_capture(label="ensemble_epoch"):
    """The literal PR-6 failure: a donating program serialized through
    jax.export comes back with donation GONE — the loaded twin declares
    nothing, and only the manifest row remembers it ever donated."""
    from jax import export as jax_export

    def fn(state):
        return state + 1.0

    spec = jax.ShapeDtypeStruct((8,), jnp.float32)
    exported = jax_export.export(jax.jit(fn, donate_argnums=(0,)))(spec)
    loaded = jax_export.deserialize(exported.serialize())
    return _capture(label, loaded.call, (jnp.zeros((8,), jnp.float32),),
                    donate_args=(), group="train-ensemble")


def _cross_member_collective_capture(label="de_predict"):
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(devs.size // 2, 2), (ENSEMBLE_AXIS, "data"))

    def body(x):
        return jax.lax.psum(x, ENSEMBLE_AXIS)

    def fn(x):
        return _shard_map(body, mesh=mesh, in_specs=P(ENSEMBLE_AXIS),
                          out_specs=P())(x)

    return _capture(label, fn,
                    (jnp.zeros((devs.size // 2 * 4,), jnp.float32),),
                    group="eval-de")


def _data_collective_capture(label="train_epoch"):
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(1, devs.size), (ENSEMBLE_AXIS, "data"))

    def body(x):
        return jax.lax.psum(x, "data")

    def fn(x):
        return _shard_map(body, mesh=mesh, in_specs=P("data"),
                          out_specs=P())(x)

    return _capture(label, fn, (jnp.zeros((devs.size * 2,), jnp.float32),),
                    group="train")


def _host_callback_capture(label="val_loss"):
    def fn(x):
        y = jax.pure_callback(
            lambda a: np.asarray(a) * 2.0,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    return _capture(label, fn, (jnp.zeros((8,), jnp.float32),),
                    group="train")


def _bf16_reduce_capture(label="mcd_predict_fused"):
    def fn(x):
        # A genuinely bf16-accumulated reduction (jnp.sum upcasts bf16
        # accumulators to f32 even under dtype=bfloat16 — that upcast is
        # exactly the promised behavior, so lax.reduce is the injection).
        xb = x.astype(jnp.bfloat16)
        return jax.lax.reduce(xb, jnp.bfloat16(0), jax.lax.add, (0,))

    return _capture(label, fn, (jnp.zeros((64,), jnp.float32),))


# -------------------------------------------------------- rule behavior --

def test_clean_capture_passes_all_rules():
    cap = _clean_capture()
    findings = run_program_rules(_context(
        {"mcd_predict": cap},
        manifest={"mcd_predict": manifest_row(cap)}))
    assert findings == []


def test_f64_leak_flagged_with_pointable_location():
    ctx = _context({"mcd_predict": _f64_capture()}, manifest={})
    findings = run_program_rules(ctx, rules=["program-dtype-drift"])
    assert len(findings) == 1
    f = findings[0]
    assert "f64" in f.message and f.message.startswith("mcd_predict:")
    # Pointable location: the label's registration line in zoo.py.
    assert f.path.endswith("compilecache/zoo.py")
    assert f.line == ctx.label_lines["mcd_predict"] > 1


def test_bf16_accumulation_and_tier_blessing():
    """The blessed low-precision tier (ISSUE 12 satellite): bf16 tensor
    types are legal ONLY under a `_bf16` label; bf16-ACCUMULATED reduces
    fail the `_fused` statistics programs in every tier."""
    cap = _bf16_reduce_capture()
    assert cap.bf16_accum_reduces >= 1 and cap.bf16_ops >= 1
    assert cap.tier == "f32"
    # An f32-tier fused label: both the unblessed bf16 types AND the
    # bf16 accumulation are violations.
    fused = run_program_rules(
        _context({"mcd_predict_fused": cap}, manifest={}),
        rules=["program-dtype-drift"])
    assert len(fused) == 2
    assert any("accumulate in bf16" in f.message for f in fused)
    assert any("f32-tier" in f.message for f in fused)
    # An f32-tier NON-fused label still needs the tier for its bf16
    # tensor types (one finding, no accumulation complaint).
    relabeled = dataclasses.replace(cap, label="mcd_predict")
    plain = run_program_rules(
        _context({"mcd_predict": relabeled}, manifest={}),
        rules=["program-dtype-drift"])
    assert len(plain) == 1 and "f32-tier" in plain[0].message
    # The blessed tier: `_bf16` labels may carry bf16 tensor types...
    blessed = dataclasses.replace(cap, label="mcd_predict_bf16")
    assert blessed.tier == "bf16"
    assert run_program_rules(
        _context({"mcd_predict_bf16": blessed}, manifest={}),
        rules=["program-dtype-drift"]) == []
    # ... but a fused `_bf16` program must STILL accumulate its
    # statistics in f32 (`_fused` sits mid-label in the suffix grammar).
    blessed_fused = dataclasses.replace(cap,
                                        label="mcd_predict_fused_bf16")
    findings = run_program_rules(
        _context({"mcd_predict_fused_bf16": blessed_fused}, manifest={}),
        rules=["program-dtype-drift"])
    assert len(findings) == 1
    assert "accumulate in bf16" in findings[0].message


def test_cross_member_collective_is_unconditional_violation():
    cap = _cross_member_collective_capture()
    assert any(ENSEMBLE_AXIS in key for key in cap.collectives)
    # Even a manifest that records the collective cannot bless it.
    blessing = {"de_predict": manifest_row(cap)}
    findings = run_program_rules(
        _context({"de_predict": cap}, manifest=blessing),
        rules=["program-collective-budget"])
    assert len(findings) == 1
    assert "cross-member" in findings[0].message


def test_collective_budget_diffs_against_manifest():
    cap = _data_collective_capture()
    assert cap.collectives == {"psum[data]": 1}
    # Matching row: clean.  Empty-budget row: drift.  Missing row: flagged.
    ok = run_program_rules(
        _context({"train_epoch": cap},
                 manifest={"train_epoch": manifest_row(cap)}),
        rules=["program-collective-budget"])
    assert ok == []
    drift = run_program_rules(
        _context({"train_epoch": cap},
                 manifest={"train_epoch": {"collectives": {}}}),
        rules=["program-collective-budget"])
    assert len(drift) == 1 and "drift" in drift[0].message
    missing = run_program_rules(
        _context({"train_epoch": cap}, manifest={}),
        rules=["program-collective-budget"])
    assert len(missing) == 1 and "no manifest row" in missing[0].message


def test_dropped_donation_flagged():
    cap = _dropped_donation_capture()
    assert cap.donated_args == 1 and cap.aliased_outputs == 0
    findings = run_program_rules(
        _context({"ensemble_epoch": cap}, manifest={}),
        rules=["program-donation-effectiveness"])
    assert len(findings) == 1
    assert "donation was dropped" in findings[0].message


def test_export_round_trip_loses_donation_and_manifest_catches_it():
    cap = _export_round_trip_capture()
    # jax.export dropped the declaration: the loaded twin donates nothing.
    assert cap.donated_args == 0
    manifest = {"ensemble_epoch": {"collectives": {}, "donates": True,
                                   "aliased": True}}
    findings = run_program_rules(
        _context({"ensemble_epoch": cap}, manifest=manifest),
        rules=["program-donation-effectiveness"])
    assert len(findings) == 1
    assert "manifest records this program as donating" in findings[0].message


def test_donation_survives_when_shapes_alias():
    def fn(state, x):
        return state + x

    cap = _capture("ensemble_epoch", fn,
                   (jnp.zeros((16,), jnp.float32),
                    jnp.zeros((16,), jnp.float32)),
                   donate_args=(0,), group="train-ensemble")
    assert cap.donated_args == 1 and cap.aliased_outputs >= 1
    findings = run_program_rules(
        _context({"ensemble_epoch": cap},
                 manifest={"ensemble_epoch": manifest_row(cap)}),
        rules=["program-donation-effectiveness"])
    assert findings == []


def test_baked_constant_flagged_and_threshold_respected():
    cap = _baked_constant_capture()
    assert cap.const_bytes >= 100_000
    findings = run_program_rules(
        _context({"predict_eval": cap}, manifest={}),
        rules=["program-constant-capture"])
    assert len(findings) == 1
    assert "baked into the program" in findings[0].message
    # A looser threshold lets the same capture pass.
    loose = run_program_rules(
        _context({"predict_eval": cap}, manifest={},
                 const_threshold=1 << 20),
        rules=["program-constant-capture"])
    assert loose == []


def test_host_callback_flagged():
    cap = _host_callback_capture()
    assert cap.host_callbacks
    findings = run_program_rules(
        _context({"val_loss": cap}, manifest={}),
        rules=["program-host-callback"])
    assert len(findings) == 1
    assert "host callback" in findings[0].message


def test_ensemble_axis_matches_mesh_constant():
    from apnea_uq_tpu.parallel import mesh as mesh_lib

    assert ENSEMBLE_AXIS == mesh_lib.AXIS_ENSEMBLE


def test_program_rules_registry():
    assert set(PROGRAM_RULES) == {
        "program-dtype-drift", "program-collective-budget",
        "program-donation-effectiveness", "program-constant-capture",
        "program-host-callback",
    }
    for rule in PROGRAM_RULES.values():
        assert rule.severity in ("error", "warning") and rule.summary
    with pytest.raises(ValueError, match="unknown program rule"):
        run_program_rules(_context({}, manifest={}), rules=["no-such"])


# ------------------------------------------- suppression at the zoo site --

_SUPPRESSED_ZOO = '''\
GROUP_LABELS = {
    "train": (
        # apnea-lint: disable=program-host-callback -- fixture: blessed
        "val_loss",
    ),
    "eval-mcd": (
        # apnea-lint: disable=program-dtype-drift
        "mcd_predict",
    ),
}
'''


def test_suppression_at_registration_site_requires_justification(tmp_path):
    zoo_file = tmp_path / "zoo.py"
    zoo_file.write_text(_SUPPRESSED_ZOO, encoding="utf-8")
    sf = load_files([str(zoo_file)], str(tmp_path))[0]
    context = AuditContext(
        programs={"val_loss": _host_callback_capture(),
                  "mcd_predict": _f64_capture()},
        manifest=None, zoo_path=sf.path,
        label_lines={"val_loss": 4, "mcd_predict": 8},
    )
    findings = [
        apply_suppressions(f, sf)
        for f in run_program_rules(
            context, rules=["program-host-callback",
                            "program-dtype-drift"])
    ]
    suppressed = [f for f in findings if f.suppressed]
    standing = [f for f in findings if not f.suppressed]
    # Justified comment suppresses the host-callback finding...
    assert len(suppressed) == 1
    assert suppressed[0].rule == "program-host-callback"
    assert suppressed[0].justification == "fixture: blessed"
    # ...the justification-less disable leaves the f64 finding standing.
    assert len(standing) == 1
    assert standing[0].rule == "program-dtype-drift"
    assert "lacks a justification" in standing[0].message


# --------------------------------------------------- manifest round-trip --

def test_manifest_save_merges_prior_rows_and_prunes_stale(tmp_path):
    path = str(tmp_path / "manifest.json")
    cap = _clean_capture()
    save_manifest(path, {"mcd_predict": cap})
    other = _data_collective_capture()
    merged = save_manifest(path, {"train_epoch": other},
                           prior=load_manifest(path))
    assert set(merged) == {"mcd_predict", "train_epoch"}
    reloaded = load_manifest(path)
    assert reloaded["mcd_predict"] == manifest_row(cap)
    assert reloaded["train_epoch"]["collectives"] == {"psum[data]": 1}
    # A prior row whose label left the zoo is PRUNED on update — the
    # drift pin's printed remediation (`--update-manifest`) must
    # actually remove stale rows, not preserve them forever.
    stale = dict(reloaded)
    stale["a_label_removed_from_the_zoo"] = {"group": "train",
                                             "collectives": {},
                                             "donates": False,
                                             "aliased": False}
    merged = save_manifest(path, {"train_epoch": other}, prior=stale)
    assert "a_label_removed_from_the_zoo" not in merged
    assert set(load_manifest(path)) == {"mcd_predict", "train_epoch"}


def test_cli_programs_default_tracks_warm_groups():
    """The CLI defaults of BOTH audit and warm-cache derive from
    zoo.WARM_GROUPS: a fifth group cannot be valid-but-silently-absent
    from the default scope."""
    from apnea_uq_tpu.cli.main import build_parser
    from apnea_uq_tpu.compilecache.zoo import WARM_GROUPS

    subs = next(a.choices for a in build_parser()._actions
                if hasattr(a, "choices") and isinstance(a.choices, dict))
    for name in ("audit", "warm-cache"):
        default = next(a.default for a in subs[name]._actions
                       if "--programs" in a.option_strings)
        assert default == ",".join(WARM_GROUPS), name


def test_checked_in_manifest_covers_every_zoo_label():
    manifest = load_manifest(DEFAULT_MANIFEST_PATH)
    assert manifest is not None
    assert set(manifest) == set(ALL_LABELS)
    for label, row in manifest.items():
        assert set(row) == {"group", "tier", "collectives", "donates",
                            "aliased"}
        # The tier column is label-derived and the manifest is its
        # reviewer-readable mirror: `_bf16` labels are the blessed
        # low-precision tier, everything else f32 (ISSUE 12 satellite).
        assert row["tier"] == ("bf16" if label.endswith("_bf16")
                               else "f32"), label
    # Both tiers actually exist in the checked-in zoo.
    tiers = {row["tier"] for row in manifest.values()}
    assert tiers == {"f32", "bf16"}
    # The repo-wide promises, as checked-in facts: no explicit
    # collectives anywhere in the zoo, and the lockstep ensemble epoch
    # both declares donation and keeps it through compilation.
    assert all(row["collectives"] == {} for row in manifest.values())
    assert manifest["ensemble_epoch"]["donates"]
    assert manifest["ensemble_epoch"]["aliased"]


# ------------------------------------------------------- the CLI contract --

def _patch_zoo(monkeypatch, captures):
    monkeypatch.setattr(
        "apnea_uq_tpu.audit.programs.capture_zoo",
        lambda config, groups: (captures, [], {}))


def test_cli_injected_violations_exit_1(monkeypatch, capsys,
                                        tiny_config_path):
    """Every injected violation class fails the real CLI with exit 1 and
    a zoo.py-anchored location (the acceptance criterion)."""
    from apnea_uq_tpu.cli.main import main

    zoo_abs, label_lines = zoo_label_lines()
    injections = {
        "f64 leak": ("mcd_predict", _f64_capture()),
        "stray collective": ("de_predict",
                             _cross_member_collective_capture()),
        "dropped donation": ("ensemble_epoch",
                             _dropped_donation_capture()),
        "baked constant": ("predict_eval", _baked_constant_capture()),
        "host callback": ("val_loss", _host_callback_capture()),
    }
    for name, (label, cap) in injections.items():
        _patch_zoo(monkeypatch, {label: cap})
        rc = main(["audit", "--config", tiny_config_path])
        out = capsys.readouterr().out
        assert rc == 1, f"{name} did not fail the audit:\n{out}"
        anchor = f"compilecache/zoo.py:{label_lines[label]}:"
        assert anchor in out, (
            f"{name} finding lacks the pointable location {anchor}:\n{out}"
        )


def test_cli_gha_format_for_injection(monkeypatch, capsys,
                                      tiny_config_path):
    from apnea_uq_tpu.cli.main import main

    _patch_zoo(monkeypatch, {"val_loss": _host_callback_capture()})
    rc = main(["audit", "--config", tiny_config_path, "--format", "gha"])
    assert rc == 1
    out = capsys.readouterr().out
    line = next(ln for ln in out.splitlines() if ln.startswith("::error"))
    assert "title=program-host-callback" in line
    assert "file=apnea_uq_tpu/compilecache/zoo.py" in line


def test_cli_update_manifest_round_trip(monkeypatch, capsys, tmp_path,
                                        tiny_config_path):
    """A legit budget change fails against the stale manifest, passes
    after --update-manifest, and the update persists; a cross-member
    collective stays fatal even through --update-manifest."""
    from apnea_uq_tpu.cli.main import main

    path = str(tmp_path / "manifest.json")
    cap = _data_collective_capture()          # psum[data] on train_epoch
    _patch_zoo(monkeypatch, {"train_epoch": cap})
    # No manifest yet: usage error, with guidance.
    with pytest.raises(SystemExit) as exc:
        main(["audit", "--config", tiny_config_path, "--manifest", path])
    assert exc.value.code == 2
    assert "--update-manifest" in capsys.readouterr().out
    # Record the budget, then audit clean against it.
    rc = main(["audit", "--config", tiny_config_path, "--manifest", path,
               "--update-manifest"])
    assert rc == 0
    capsys.readouterr()
    rc = main(["audit", "--config", tiny_config_path, "--manifest", path])
    assert rc == 0
    capsys.readouterr()
    assert load_manifest(path)["train_epoch"]["collectives"] == {
        "psum[data]": 1}
    # The tier column survives the --update-manifest round trip.
    assert load_manifest(path)["train_epoch"]["tier"] == "f32"
    # Drift: the program changes (loses its collective) -> exit 1.
    _patch_zoo(monkeypatch, {"train_epoch": _clean_capture(
        label="train_epoch", group="train")})
    rc = main(["audit", "--config", tiny_config_path, "--manifest", path])
    assert rc == 1
    assert "drift" in capsys.readouterr().out
    # Cross-member collectives cannot be blessed by updating — and the
    # failed update must NOT mutate the golden file (a committed
    # polluted manifest would fail CI on a later-corrected tree).
    before = load_manifest(path)
    _patch_zoo(monkeypatch,
               {"de_predict": _cross_member_collective_capture()})
    rc = main(["audit", "--config", tiny_config_path, "--manifest", path,
               "--update-manifest"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "cross-member" in out and "NOT updated" in out
    assert load_manifest(path) == before


def test_cli_usage_errors_exit_2(capsys, tiny_config_path):
    from apnea_uq_tpu.cli.main import main

    with pytest.raises(SystemExit) as exc:
        main(["audit", "--config", tiny_config_path,
              "--programs", "no-such-group"])
    assert exc.value.code == 2
    assert "unknown --programs" in capsys.readouterr().out


def test_capture_failure_exits_2(monkeypatch, capsys, tiny_config_path):
    from apnea_uq_tpu.cli.main import main

    monkeypatch.setattr(
        "apnea_uq_tpu.audit.programs.capture_zoo",
        lambda config, groups: ({}, [], {"mcd_predict": "boom"}))
    with pytest.raises(SystemExit) as exc:
        main(["audit", "--config", tiny_config_path])
    assert exc.value.code == 2
    assert "FAILED" in capsys.readouterr().out


# ------------------------------- the acceptance run: full zoo, real CLI --

@pytest.fixture(scope="module")
def full_zoo_audit(tiny_config_path, tmp_path_factory):
    """ONE full-zoo audit through the real CLI (all 12 labels lowered on
    the virtual-CPU mesh, nothing dispatched), shared by the acceptance
    assertions below.  stdout is captured via the telemetry log handler
    seam so a module fixture can hold it."""
    import contextlib
    import io

    from apnea_uq_tpu.cli.main import main

    run_dir = str(tmp_path_factory.mktemp("audit_run") / "run")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["audit", "--config", tiny_config_path, "--json",
                   "--run-dir", run_dir])
    return rc, buf.getvalue(), run_dir


def test_full_zoo_audit_passes_clean(full_zoo_audit):
    rc, out, _run_dir = full_zoo_audit
    assert rc == 0, f"audit over the full zoo is dirty:\n{out}"
    # --json stdout is pure JSON: narration (telemetry dir, skips) goes
    # to stderr, so `audit --json | jq .` parses without stripping.
    assert out.lstrip().startswith("{"), out[:200]
    doc = json.loads(out[out.index("{"):])
    assert doc["summary"]["unsuppressed"] == 0
    assert sorted(doc["programs"]) == ALL_LABELS
    for label, facts in doc["programs"].items():
        assert facts["flops"] is not None and facts["flops"] > 0
        assert facts["bytes_accessed"] and facts["bytes_accessed"] > 0
        assert facts["arithmetic_intensity"] > 0
        assert facts["collectives"] == 0
    assert doc["programs"]["ensemble_epoch"]["donated_args"] > 0
    assert doc["programs"]["ensemble_epoch"]["aliased_outputs"] > 0


def test_program_audit_events_and_summarize(full_zoo_audit):
    from apnea_uq_tpu.telemetry import summarize_data, summarize_run
    from apnea_uq_tpu.telemetry.runlog import read_events

    _rc, _out, run_dir = full_zoo_audit
    events = [e for e in read_events(run_dir)
              if e.get("kind") == "program_audit"]
    assert sorted(e["label"] for e in events) == ALL_LABELS
    rendered = summarize_run(run_dir)
    assert "program audit (lowered-IR cost)" in rendered
    assert "ensemble_epoch" in rendered
    data = summarize_data(run_dir)
    assert sorted(p["label"] for p in data["program_audits"]) == ALL_LABELS
    row = next(p for p in data["program_audits"]
               if p["label"] == "ensemble_epoch")
    assert row["donated_args"] > 0 and row["flops"] > 0


def test_compare_gates_audit_flops_lower_better(full_zoo_audit, tmp_path):
    """program_audit flops/bytes are comparable metrics with
    lower-is-better direction: an inflated candidate regresses, a
    cheaper one improves."""
    from apnea_uq_tpu.telemetry import compare as compare_mod

    _rc, _out, run_dir = full_zoo_audit
    worse = tmp_path / "worse_run"
    worse.mkdir()
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    for e in lines:
        if e.get("kind") == "program_audit":
            e["flops"] = e["flops"] * 1.5
            e["bytes_accessed"] = e["bytes_accessed"] * 1.2
    with open(worse / "events.jsonl", "w") as f:
        for e in lines:
            f.write(json.dumps(e) + "\n")
    comparison = compare_mod.compare_paths(run_dir, str(worse))
    regressed = {d.name for d in comparison.regressions}
    assert "audit.ensemble_epoch.flops" in regressed
    assert "audit.mcd_predict.bytes_accessed" in regressed
    # The reverse direction improves rather than regresses.
    back = compare_mod.compare_paths(str(worse), run_dir)
    assert not [d for d in back.regressions
                if d.name.startswith("audit.")]
    flop_delta = next(d for d in back.deltas
                      if d.name == "audit.mcd_predict.flops")
    assert flop_delta.improved and not flop_delta.higher_better


def test_zoo_capture_respects_group_filter(tiny_config_path):
    from apnea_uq_tpu.audit.programs import capture_zoo
    from apnea_uq_tpu.config import load_config

    config = load_config(tiny_config_path)
    captures, skipped, failures = capture_zoo(config, groups=("train",))
    assert not failures and not skipped
    assert sorted(captures) == sorted(GROUP_LABELS["train"])
    assert all(p.group == "train" for p in captures.values())
    with pytest.raises(ValueError, match="unknown audit group"):
        capture_zoo(config, groups=("nope",))


def test_streaming_config_skips_trainer_labels(tiny_config_path):
    from apnea_uq_tpu.audit.programs import capture_zoo
    from apnea_uq_tpu.config import load_config

    config = load_config(tiny_config_path)
    config = dataclasses.replace(
        config, train=dataclasses.replace(config.train, streaming=True))
    captures, skipped, failures = capture_zoo(config, groups=("train",))
    assert not failures and not captures
    assert sorted(label for label, _ in skipped) == sorted(
        GROUP_LABELS["train"])
