"""Fused Pallas DE kernel + autotune harness (ISSUE 16): interpret-mode
kernel-body tests (DE is deterministic, so the interpret twin IS the
shipped body — tier-1's CPU exercise of the kernel MATH, not just the
XLA fallback), engine resolution + fallback bit-identity on every DE
program family, the extended label grammar, `de_engine` config/CLI
plumbing, and the autotune measure→persist→activate lifecycle.

The compiled kernel itself needs a TPU; everything here runs on CPU.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apnea_uq_tpu.config import ModelConfig, UQConfig  # noqa: E402
from apnea_uq_tpu.models import AlarconCNN1D, init_variables  # noqa: E402
from apnea_uq_tpu.models.cnn1d import apply_model, predict_proba  # noqa: E402
from apnea_uq_tpu.ops import autotune, pallas_de  # noqa: E402
from apnea_uq_tpu.uq.metrics import sufficient_stats  # noqa: E402
from apnea_uq_tpu.uq.predict import (  # noqa: E402
    DE_PROGRAM_LABELS,
    SERVE_PROGRAM_LABELS,
    de_program_label,
    ensemble_predict,
    ensemble_predict_streaming,
    resolve_de_engine,
    serve_bucket_predict,
    serve_program_label,
    stack_member_variables,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The documented tolerance tiers (PARITY.md "Tolerance tiers").
F32_TOL = dict(rtol=0, atol=1e-6)
BF16_TOL = dict(rtol=0, atol=2e-2)


def _model(dtype="float32", features=(6, 8), kernels=(5, 3)):
    return AlarconCNN1D(ModelConfig(
        features=features, kernel_sizes=kernels,
        dropout_rates=(0.3, 0.4), compute_dtype=dtype,
    ))


def _members(model, n, seed=0):
    return stack_member_variables(
        [init_variables(model, jax.random.key(seed + i)) for i in range(n)])


def _eval_reference(model, stacked, x):
    """Per-member eval-mode probabilities through the real Flax forward
    (NOT the kernel's shifted-matmul decomposition)."""
    xj = jnp.asarray(x, jnp.float32)

    def one(variables):
        return predict_proba(apply_model(model, variables, xj,
                                         mode="eval")[0])

    return np.stack([
        np.asarray(one(jax.tree.map(lambda a: a[i], stacked)))
        for i in range(jax.tree.leaves(stacked)[0].shape[0])
    ])


@pytest.fixture(autouse=True)
def _defaults_active():
    """Every test starts and ends with NO tuned geometry active — the
    activation table is process-global state."""
    autotune.deactivate()
    yield
    autotune.deactivate()


class TestInterpretKernel:
    """The kernel BODY under pl.pallas_call(interpret=True) — identical
    `_de_tile_body` to the TPU path; DE needs no injected randomness,
    so this is the exact shipped kernel at CPU-runnable geometry."""

    def test_members_match_eval_mode_flax(self, rng):
        model = _model()
        stacked = _members(model, 3)
        x = rng.normal(size=(11, 60, 4)).astype(np.float32)  # ragged tile
        probs = np.asarray(pallas_de.de_forward_with_members(
            model, stacked, x))  # tile 8, group 4 -> ragged member group
        ref = _eval_reference(model, stacked, x)
        assert probs.shape == (3, 11)
        np.testing.assert_allclose(probs, ref, **F32_TOL)

    def test_ragged_tiles_and_member_groups(self, rng):
        """5 members at member_group=2 (ragged last group) across 13
        windows at window_tile=4 (ragged last tile)."""
        model = _model()
        stacked = _members(model, 5, seed=3)
        x = rng.normal(size=(13, 60, 4)).astype(np.float32)
        probs = np.asarray(pallas_de.de_forward_with_members(
            model, stacked, x, window_tile=4, member_group=2))
        ref = _eval_reference(model, stacked, x)
        assert probs.shape == (5, 13)
        np.testing.assert_allclose(probs, ref, **F32_TOL)

    def test_fused_stats_match_xla_fused(self, rng):
        """The in-kernel sufficient-stats reduction vs the XLA fused
        path's formula applied to the member probabilities — the two
        engines share `sufficient_stats`, so they agree by
        construction; this pins the plumbing."""
        model = _model()
        stacked = _members(model, 4, seed=5)
        x = rng.normal(size=(10, 60, 4)).astype(np.float32)
        stats = np.asarray(pallas_de.de_pallas_stats(
            model, stacked, jnp.asarray(x), window_tile=8, member_group=4,
            interpret=True))
        probs = _eval_reference(model, stacked, x)
        ref = np.asarray(sufficient_stats(jnp.asarray(probs)))
        assert stats.shape == (4, 10)
        np.testing.assert_allclose(stats, ref, **F32_TOL)
        # ... and against the production XLA fused program end to end.
        xla = np.asarray(ensemble_predict(
            model, stacked, x, batch_size=8, stats=("nats", 1e-10)))
        np.testing.assert_allclose(stats, xla, **F32_TOL)

    def test_bf16_tier_against_f32_reference(self, rng):
        """compute_dtype='bfloat16' through the kernel body stays within
        the documented bf16 tier (<=2e-2) of the f32 reference — the
        conv matmuls run bf16, GAP/stats accumulation stays f32."""
        model = _model("bfloat16")
        f32_model = _model()
        stacked = _members(f32_model, 3, seed=7)
        x = rng.normal(size=(9, 60, 4)).astype(np.float32)
        bf16 = np.asarray(pallas_de.de_forward_with_members(
            model, stacked, x))
        ref = _eval_reference(f32_model, stacked, x)
        np.testing.assert_allclose(bf16, ref, **BF16_TOL)


class TestEngineResolution:
    """resolve_de_engine: the pallas engine is requested per call but
    dispatches only where the kernel is valid; everywhere else the XLA
    body runs under the SAME (pallas-suffixed) label — the shared
    resolve_engine fallback contract."""

    def test_off_tpu_resolves_to_xla(self):
        assert jax.default_backend() != "tpu"  # the CPU test rig
        assert resolve_de_engine("pallas", None) == "xla"
        assert resolve_de_engine("xla", None) == "xla"

    def test_mesh_resolves_to_xla(self, monkeypatch):
        # Even with the kernel nominally available, a mesh must fall
        # back: the kernel is a per-chip program.
        monkeypatch.setattr(pallas_de, "pallas_de_available", lambda: True)
        from apnea_uq_tpu.parallel import make_mesh

        assert resolve_de_engine("pallas", None) == "pallas"
        assert resolve_de_engine(
            "pallas", make_mesh(num_members=4)) == "xla"

    def test_unknown_engine_rejected(self):
        with pytest.raises(ValueError, match="engine"):
            resolve_de_engine("bogus", None)

    def test_fallback_is_bit_identical_on_every_family(self, rng):
        """Off-TPU, engine='pallas' must produce EXACTLY the XLA path's
        results on all four DE program families — the fallback is the
        same body, so toggling the engine off-TPU never changes
        predictions (only the program label)."""
        model = _model()
        stacked = _members(model, 3)
        x = rng.normal(size=(21, 60, 4)).astype(np.float32)
        for stats in (None, ("nats", 1e-10)):
            ref = np.asarray(ensemble_predict(
                model, stacked, x, batch_size=8, stats=stats))
            pal = np.asarray(ensemble_predict(
                model, stacked, x, batch_size=8, stats=stats,
                engine="pallas"))
            np.testing.assert_array_equal(ref, pal)
            streamed = np.asarray(ensemble_predict_streaming(
                model, stacked, x, batch_size=8, stats=stats,
                engine="pallas"))
            np.testing.assert_array_equal(ref, streamed)

    def test_serve_bucket_fallback_is_bit_identical(self, rng):
        model = _model()
        stacked = _members(model, 3)
        x = rng.normal(size=(16, 60, 4)).astype(np.float32)
        ref = np.asarray(serve_bucket_predict(
            model, stacked, x, method="de", bucket=16))
        pal = np.asarray(serve_bucket_predict(
            model, stacked, x, method="de", bucket=16, engine="pallas"))
        np.testing.assert_array_equal(ref, pal)


class TestLabelsAndConfig:
    def test_label_grammar(self):
        f32 = _model()
        bf16 = _model("bfloat16")
        assert de_program_label(f32, streamed=False, engine="pallas",
                                fused=True) == "de_predict_pallas_fused"
        assert de_program_label(bf16, streamed=True, engine="pallas",
                                fused=False) == "de_chunk_predict_pallas_bf16"
        assert serve_program_label(f32, method="de", bucket=64,
                                   engine="pallas") == \
            "de_serve_b64_pallas_fused"
        assert serve_program_label(bf16, method="mcd", bucket=16,
                                   engine="pallas") == \
            "mcd_serve_b16_pallas_fused_bf16"
        assert serve_program_label(f32, method="de", bucket=256) == \
            "de_serve_b256_fused"

    def test_label_tables_cover_the_grammar(self):
        """16 DE labels (streamed x engine x fused x dtype) — the same
        grid as MCD since ISSUE 16 — and 24 serve labels (method x
        bucket x engine x dtype), no duplicates."""
        assert len(set(DE_PROGRAM_LABELS)) == 16
        assert len(set(SERVE_PROGRAM_LABELS)) == 24
        assert len([l for l in SERVE_PROGRAM_LABELS if "_pallas" in l]) == 12

    def test_de_engine_validated_at_config_load(self):
        with pytest.raises(ValueError, match="de_engine"):
            UQConfig(de_engine="mosaic")
        UQConfig(de_engine="pallas")

    def test_config_json_round_trips_de_engine(self, tmp_path):
        from apnea_uq_tpu.config import (ExperimentConfig, load_config,
                                         save_config)

        cfg = ExperimentConfig(uq=UQConfig(de_engine="pallas"))
        path = str(tmp_path / "config.json")
        save_config(cfg, path)
        assert load_config(path).uq.de_engine == "pallas"

    def test_eval_cli_flag_parses_and_overrides(self):
        from apnea_uq_tpu.cli.main import build_parser
        from apnea_uq_tpu.cli.stages import _apply_eval_overrides
        from apnea_uq_tpu.config import ExperimentConfig

        parser = build_parser()
        args = parser.parse_args(
            ["eval-de", "--registry", "r", "--de-engine", "pallas"])
        cfg = _apply_eval_overrides(args, ExperimentConfig())
        assert cfg.uq.de_engine == "pallas"
        # warm-cache and serve accept the same engine overrides, so the
        # warmed label set equals what an identically-flagged eval/serve
        # process dispatches.
        for cmd in ("warm-cache", "serve"):
            args = parser.parse_args(
                [cmd, "--registry", "r", "--de-engine", "pallas",
                 "--mcd-engine", "pallas"])
            cfg = _apply_eval_overrides(args, ExperimentConfig())
            assert cfg.uq.de_engine == "pallas"
            assert cfg.uq.mcd_engine == "pallas"

    def test_autotune_cli_registered_with_defaults(self):
        from apnea_uq_tpu.cli.main import build_parser

        args = build_parser().parse_args(
            ["autotune", "--registry", "r", "--window-tiles", "8,16",
             "--groups", "4,8"])
        assert args.window_tiles == "8,16"
        assert args.reps == 3

    def test_readme_recipe_flags_parse(self):
        """The README's autotune + de-engine recipe is flag-guarded:
        the flags it teaches must exist and parse."""
        readme = open(os.path.join(REPO, "README.md")).read()
        assert "--de-engine pallas" in readme
        assert "apnea-uq autotune" in readme
        from apnea_uq_tpu.cli.main import build_parser

        build_parser().parse_args(
            ["eval-de", "--registry", "r", "--compute-dtype", "bfloat16",
             "--de-engine", "pallas"])


class TestAutotune:
    """ops/autotune.py: the sweep measures isolated cells, the winners
    document activates only under a matching fingerprint, and the
    active geometry feeds both the jit static args and the program-store
    key."""

    def _doc(self, winners=None):
        return {
            "version": 1,
            "fingerprint": autotune.fingerprint(),
            "winners": winners if winners is not None else {
                "de_predict_pallas_fused": {
                    "window_tile": 32, "member_group": 4,
                    "best_s": 0.01, "default_s": 0.02,
                    "best_vs_default": 2.0,
                },
            },
        }

    def test_activate_and_tuned_kwargs_round_trip(self):
        assert autotune.tuned_kernel_kwargs("de_predict_pallas_fused") == ()
        assert autotune.active_digest() == ""
        assert autotune.activate(self._doc()) == 1
        assert autotune.tuned_kernel_kwargs("de_predict_pallas_fused") == (
            ("member_group", 4), ("window_tile", 32))
        assert autotune.active_digest() != ""
        # Labels without a winner keep defaults.
        assert autotune.tuned_kernel_kwargs("mcd_predict_pallas_fused") == ()
        autotune.deactivate()
        assert autotune.tuned_kernel_kwargs("de_predict_pallas_fused") == ()

    def test_stale_fingerprint_deactivates(self):
        doc = self._doc()
        doc["fingerprint"] = dict(doc["fingerprint"], source="deadbeef")
        assert autotune.activate(doc) == 0
        assert autotune.active_digest() == ""
        assert autotune.activate(None) == 0

    def test_non_geometry_keys_never_activate(self):
        """Only GEOMETRY_PARAMS feed the static jit signature — timing
        fields in the record must not leak into kernel kwargs."""
        assert autotune.activate(self._doc()) == 1
        kwargs = dict(autotune.tuned_kernel_kwargs("de_predict_pallas_fused"))
        assert set(kwargs) <= set(autotune.GEOMETRY_PARAMS)

    def test_registry_round_trip_and_staleness(self, tmp_path):
        from apnea_uq_tpu.data import registry as reg
        from apnea_uq_tpu.data.registry import ArtifactRegistry

        registry = ArtifactRegistry(str(tmp_path / "r"))
        # No artifact -> defaults, no error.
        assert autotune.activate_from_registry(registry) == 0
        registry.save_json(reg.AUTOTUNE_CONFIG, self._doc())
        assert autotune.activate_from_registry(registry) == 1
        assert autotune.tuned_kernel_kwargs("de_predict_pallas_fused") != ()
        # A stale persisted document (other source fingerprint) reverts
        # to defaults on activation — the store's staleness discipline.
        doc = self._doc()
        doc["fingerprint"]["jax"] = "0.0.0"
        registry.save_json(reg.AUTOTUNE_CONFIG, doc)
        assert autotune.activate_from_registry(registry) == 0
        assert autotune.tuned_kernel_kwargs("de_predict_pallas_fused") == ()

    def test_active_digest_keys_the_program_store(self):
        """Geometry is a static argument of the kernel programs, so the
        store key MUST fold the active winner digest — a program stored
        under one tile geometry must never be offered to a process that
        activated another."""
        from apnea_uq_tpu.compilecache.store import store_key

        base = store_key("de_predict_pallas_fused", "sig")
        assert autotune.activate(self._doc()) == 1
        tuned = store_key("de_predict_pallas_fused", "sig")
        assert tuned != base
        autotune.deactivate()
        assert store_key("de_predict_pallas_fused", "sig") == base

    def test_run_autotune_sweeps_and_reports(self):
        """A tiny CPU sweep end to end: every target label gets a
        winner record with the default cell always timed (so
        best_vs_default exists), cells are isolated, and the telemetry
        pair is emitted per cell / per label."""
        events = []

        class Log:
            def event(self, kind, **fields):
                events.append({"kind": kind, **fields})

        config = ModelConfig(features=(4, 6), kernel_sizes=(3, 3),
                             dropout_rates=(0.1, 0.2))
        doc = autotune.run_autotune(
            model_config=config, members=3, n_passes=2, windows=16,
            chunk=8, buckets=(16,), window_tiles=(8,), groups=(4,),
            warmup=1, reps=1, run_log=Log(),
        )
        assert doc["version"] == 1
        assert doc["fingerprint"] == autotune.fingerprint()
        winners = doc["winners"]
        assert set(winners) == {
            "de_predict_pallas_fused", "de_chunk_predict_pallas_fused",
            "de_serve_b16_pallas_fused", "mcd_serve_b16_pallas_fused",
        }
        for label, record in winners.items():
            assert record["best_s"] > 0 and record["default_s"] > 0
            assert record["best_vs_default"] > 0
            assert record["window_tile"] > 0
            param = "pass_group" if label.startswith("mcd") else \
                "member_group"
            assert record[param] > 0
        by_kind = {}
        for e in events:
            by_kind.setdefault(e["kind"], []).append(e)
        assert len(by_kind["autotune_result"]) == len(winners)
        # The grid was (8,)x(4,) plus the always-timed default cell.
        assert all(e["cells"] == 2 for e in by_kind["autotune_result"])
        assert all(c["status"] in ("ok", "error")
                   for c in by_kind["autotune_cell"])
        assert len(by_kind["autotune_cell"]) == 2 * len(winners)
        # The document activates on the machine that measured it.
        assert autotune.activate(doc) == len(winners)

    def test_default_geometry_constants_match_kernels(self):
        """The sweep's default cell is the kernels' shipped default —
        otherwise best_vs_default would compare against a geometry no
        un-tuned process runs."""
        assert autotune.DEFAULT_WINDOW_TILE == pallas_de.DEFAULT_WINDOW_TILE
        assert autotune.DEFAULT_GROUP == pallas_de.DEFAULT_MEMBER_GROUP
