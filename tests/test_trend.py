"""The cross-run perf-trajectory ledger (ISSUE 11 tentpole, piece 3):
``telemetry/trend.py`` + ``apnea-uq telemetry trend``.  The repo's own
archived BENCH_r01..r05 — two good rounds and three tunnel-outage error
captures — are the motivating fixtures: the ledger must ingest ALL of
them, render error rounds as gaps (never crash), reuse compare's
unit-direction inference for best/latest/delta, and regenerate the
byte-pinned docs/BENCH_TRAJECTORY.md deterministically."""

import json
import os

import pytest

from apnea_uq_tpu.cli.main import main
from apnea_uq_tpu.telemetry import trend as trend_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestArchivedRounds:
    def test_repo_rounds_ordered_numerically(self):
        paths = trend_mod.repo_rounds(REPO)
        labels = [trend_mod.round_label(p) for p in paths]
        assert labels[:5] == ["r01", "r02", "r03", "r04", "r05"]

    def test_error_rounds_are_gaps_not_crashes(self):
        """The acceptance shape: a trajectory covering r01-r05 with the
        three outage rounds as gaps."""
        paths = trend_mod.repo_rounds(REPO)[:5]
        rounds = [trend_mod.load_round(p) for p in paths]
        assert [r.status for r in rounds] == ["ok", "ok", "error",
                                              "error", "error"]
        assert rounds[2].metrics == {} and rounds[2].detail
        traj = trend_mod.build_trajectory(rounds)
        m = next(x for x in traj.metrics
                 if x.name == "mcd_t50_inference_throughput")
        assert m.values[:2] == [9563.7, 9447.2]
        assert m.values[2:] == [None, None, None]
        assert m.best == 9563.7 and m.best_round == "r01"
        assert m.latest == 9447.2 and m.latest_round == "r02"
        assert m.delta_pct == pytest.approx(-1.22, abs=0.01)
        assert not m.regressed  # -1.2% is inside the 5% band
        # The archived r02 context also rides along (the same
        # extraction compare gates with).
        assert any(x.name == "bootstrap.speedup" for x in traj.metrics)

    def test_threshold_flags_regression_vs_best(self):
        paths = trend_mod.repo_rounds(REPO)[:5]
        rounds = [trend_mod.load_round(p) for p in paths]
        traj = trend_mod.build_trajectory(rounds, threshold_pct=1.0)
        m = next(x for x in traj.metrics
                 if x.name == "mcd_t50_inference_throughput")
        assert m.regressed  # -1.2% vs best exceeds a 1% band
        assert m.name in [x.name for x in traj.regressions]

    def test_render_shows_round_statuses_and_gaps(self):
        paths = trend_mod.repo_rounds(REPO)[:5]
        traj = trend_mod.build_trajectory(
            [trend_mod.load_round(p) for p in paths])
        text = trend_mod.render_trajectory(traj)
        assert "r03[error]" in text and "r05[error]" in text
        assert "—" in text  # gaps, not zeros
        assert "mcd_t50_inference_throughput (^)" in text


class TestSyntheticRounds:
    def _capture(self, path, metric, value, unit, **extra):
        doc = {"metric": metric, "value": value, "unit": unit,
               "vs_baseline": 1.0}
        doc.update(extra)
        with open(path, "w") as f:
            json.dump(doc, f)
        return str(path)

    def test_direction_aware_best_for_seconds(self, tmp_path):
        a = self._capture(tmp_path / "BENCH_r01.json", "train_s", 10.0,
                          "seconds")
        b = self._capture(tmp_path / "BENCH_r02.json", "train_s", 4.0,
                          "seconds")
        c = self._capture(tmp_path / "BENCH_r03.json", "train_s", 6.0,
                          "seconds")
        rounds = [trend_mod.load_round(p) for p in (a, b, c)]
        traj = trend_mod.build_trajectory(rounds)
        m = next(x for x in traj.metrics if x.name == "train_s")
        assert not m.higher_better
        assert m.best == 4.0 and m.best_round == "r02"
        assert m.latest == 6.0
        assert m.delta_pct == pytest.approx(50.0)
        assert m.regressed  # +50% on a lower-is-better metric

    def test_backend_bound_series_split_by_mode(self, tmp_path):
        """A proxy round's operating-point-bound absolutes (smoke-shape
        D2H bytes, data-plane seconds) must NOT merge into the device
        series — else the tiny proxy values become 'best' and every
        later device round flags REGRESSED forever."""
        device = tmp_path / "BENCH_r01.json"
        with open(device, "w") as f:
            json.dump({"metric": "mcd_t50_inference_throughput",
                       "value": 9000.0, "unit": "windows/sec/chip",
                       "vs_baseline": 12.0,
                       "context": {"d2h_accounting":
                                   {"d2h_bytes_full": 6_553_600}}}, f)
        proxy = tmp_path / "BENCH_r02.json"
        with open(proxy, "w") as f:
            json.dump({"metric": "bench_cpu_proxy", "value": 3,
                       "unit": "blocks", "vs_baseline": 0, "schema": 2,
                       "proxy": True,
                       "context": {"d2h_accounting":
                                   {"d2h_bytes_full": 4096},
                                   "compile":
                                   {"cold_vs_warm_total": 4.0}}}, f)
        rounds = [trend_mod.load_round(str(p)) for p in (device, proxy)]
        traj = trend_mod.build_trajectory(rounds)
        by_name = {m.name: m for m in traj.metrics}
        # Two separate series, neither polluted by the other's shapes.
        assert by_name["d2h.bytes_full"].values == [6_553_600.0, None]
        assert not by_name["d2h.bytes_full"].regressed
        assert by_name["d2h.bytes_full [proxy]"].values == [None, 4096.0]
        # Relative metrics stay in one merged series.
        assert "compile.cold_vs_warm_total" in by_name
        assert "compile.cold_vs_warm_total [proxy]" not in by_name

    def test_proxy_round_is_labeled(self, tmp_path):
        path = tmp_path / "proxy.json"
        with open(path, "w") as f:
            json.dump({"metric": "bench_cpu_proxy", "value": 3,
                       "unit": "blocks", "vs_baseline": 0, "schema": 2,
                       "proxy": True,
                       "context": {"compile":
                                   {"cold_vs_warm_total": 4.0}}}, f)
        point = trend_mod.load_round(str(path))
        assert point.status == "proxy" and point.proxy
        assert "compile.cold_vs_warm_total" in point.metrics

    def test_run_dir_source_via_bench_metric_events(self, tmp_path):
        run_dir = tmp_path / "bench_run"
        os.makedirs(run_dir)
        events = [
            {"seq": 0, "ts": 1.0, "kind": "run_started",
             "schema_version": 1, "stage": "bench"},
            {"seq": 1, "ts": 2.0, "kind": "bench_metric",
             "role": "primary", "metric": "mcd_t50_inference_throughput",
             "value": 9000.0, "unit": "windows/sec/chip",
             "vs_baseline": 12.0},
            {"seq": 2, "ts": 3.0, "kind": "run_finished", "status": "ok"},
        ]
        with open(run_dir / "events.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        point = trend_mod.load_round(str(run_dir))
        assert point.status == "ok"
        assert point.label == "bench_run"
        assert point.metrics["mcd_t50_inference_throughput"].value == 9000.0

    def test_unreadable_source_is_an_error_round(self, tmp_path):
        missing = trend_mod.load_round(str(tmp_path / "nope.json"))
        assert missing.status == "error" and missing.metrics == {}
        garbage = tmp_path / "garbage.json"
        garbage.write_text("{truncated")
        assert trend_mod.load_round(str(garbage)).status == "error"


class TestTrendCLI:
    def test_text_and_json_over_archive_plus_extra(self, tmp_path,
                                                   capsys):
        extra = tmp_path / "candidate.json"
        with open(extra, "w") as f:
            json.dump({"metric": "mcd_t50_inference_throughput",
                       "value": 9800.0, "unit": "windows/sec/chip",
                       "vs_baseline": 13.0}, f)
        assert main(["telemetry", "trend", str(extra)]) == 0
        text = capsys.readouterr().out
        for label in ("r01[ok]", "r03[error]", "r05[error]",
                      "candidate[ok]"):
            assert label in text, text
        assert main(["telemetry", "trend", str(extra), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [r["label"] for r in doc["rounds"]][:5] == [
            "r01", "r02", "r03", "r04", "r05"]
        assert doc["rounds"][-1]["label"] == "candidate"
        m = next(x for x in doc["metrics"]
                 if x["name"] == "mcd_t50_inference_throughput")
        assert m["latest"] == 9800.0 and m["best"] == 9800.0

    def test_rounds_dir_override_and_empty_exit(self, tmp_path, capsys):
        with open(tmp_path / "BENCH_r01.json", "w") as f:
            json.dump({"metric": "m", "value": 1.0, "unit": "ratio"}, f)
        assert main(["telemetry", "trend",
                     "--rounds-dir", str(tmp_path)]) == 0
        assert "r01[ok]" in capsys.readouterr().out
        empty = tmp_path / "empty"
        empty.mkdir()
        with pytest.raises(SystemExit, match="no BENCH_r"):
            main(["telemetry", "trend", "--rounds-dir", str(empty)])

    def test_update_docs_rejects_extra_sources(self, tmp_path):
        """The doc is pinned against the archived rounds alone; extra
        sources must be rejected loudly, never silently dropped."""
        extra = tmp_path / "fresh.json"
        with open(extra, "w") as f:
            json.dump({"metric": "m", "value": 1.0, "unit": "ratio"}, f)
        with pytest.raises(SystemExit, match="archive the capture"):
            main(["telemetry", "trend", str(extra), "--update-docs",
                  "--docs", str(tmp_path / "TRAJ.md")])

    def test_update_docs_writes_pinned_render(self, tmp_path, capsys):
        out = tmp_path / "TRAJ.md"
        assert main(["telemetry", "trend", "--update-docs",
                     "--docs", str(out)]) == 0
        text = out.read_text()
        assert trend_mod.GENERATED_MARKER in text
        # Deterministic: a second render is byte-identical (the docs
        # pin's precondition) — over the full archive, multichip
        # rounds included.
        rounds = [trend_mod.load_round(p)
                  for p in trend_mod.archived_rounds()]
        again = trend_mod.render_trajectory_doc(
            trend_mod.build_trajectory(rounds))
        assert text == again


class TestMultichipRounds:
    """MULTICHIP_r*.json — the mesh-dryrun twins ride the ledger
    instead of being invisible (ISSUE 14 satellite)."""

    def test_archived_multichip_rounds_ingest(self):
        paths = trend_mod.multichip_rounds(REPO)
        assert [trend_mod.round_label(p) for p in paths][:5] == [
            "mch01", "mch02", "mch03", "mch04", "mch05"]
        point = trend_mod.load_round(paths[0])
        assert point.status == "ok"
        assert point.metrics["multichip.n_devices"].value == 8.0
        assert point.metrics["multichip.n_devices"].higher_better
        assert point.metrics["multichip.mesh_ensemble"].value == 4.0
        assert point.metrics["multichip.mesh_data"].value == 2.0

    def test_archived_rounds_interleaves_bench_then_multichip(self):
        labels = [trend_mod.round_label(p)
                  for p in trend_mod.archived_rounds(REPO)]
        assert labels[:5] == ["r01", "r02", "r03", "r04", "r05"]
        bench = [lbl for lbl in labels if not lbl.startswith("mch")]
        mch = [lbl for lbl in labels if lbl.startswith("mch")]
        # All bench rounds precede all multichip rounds, however many
        # bench rounds later sessions archive.
        assert labels == bench + mch
        assert mch[:5] == ["mch01", "mch02", "mch03", "mch04", "mch05"]

    def test_failed_and_skipped_dryruns_are_error_rounds(self, tmp_path):
        bad = tmp_path / "MULTICHIP_r01.json"
        bad.write_text(json.dumps({"n_devices": 0, "rc": 1, "ok": False,
                                   "skipped": False, "tail": "boom"}))
        point = trend_mod.load_round(str(bad))
        assert point.status == "error" and "rc=1" in point.detail
        skipped = tmp_path / "MULTICHIP_r02.json"
        skipped.write_text(json.dumps({"n_devices": 0, "rc": 0,
                                       "ok": False, "skipped": True,
                                       "tail": ""}))
        point = trend_mod.load_round(str(skipped))
        assert point.status == "error" and "skipped" in point.detail

    def test_multichip_series_in_cli_trajectory(self, capsys):
        assert main(["telemetry", "trend"]) == 0
        text = capsys.readouterr().out
        assert "mch01[ok]" in text and "mch05[ok]" in text
        assert "multichip.n_devices (^)" in text
