"""Vectorized bootstrap: statistical correctness and CI machinery
(replacing uq_techniques.py:116-206)."""

import numpy as np
import pytest

from apnea_uq_tpu.uq import (
    bootstrap_aggregates,
    bootstrap_metrics,
    compute_confidence_intervals,
)
from apnea_uq_tpu.uq.bootstrap import AGGREGATE_KEYS
from apnea_uq_tpu.uq.metrics import uq_evaluation_dist


def test_shapes_and_keys(rng):
    preds = rng.uniform(0.1, 0.9, size=(10, 200))
    y = rng.integers(0, 2, 200)
    agg = bootstrap_aggregates(preds, y, n_bootstrap=37, seed=0)
    assert set(agg.keys()) == set(AGGREGATE_KEYS)
    for v in agg.values():
        assert v.shape == (37,)


def test_bootstrap_mean_tracks_point_estimate(rng):
    """Bootstrap distribution of a mean must center on the sample mean."""
    preds = rng.uniform(0.1, 0.9, size=(20, 2000))
    y = rng.integers(0, 2, 2000)
    agg = bootstrap_aggregates(preds, y, n_bootstrap=400, seed=1)
    point = uq_evaluation_dist(preds, y)
    sample_mean = float(point["overall_mean_variance"])
    boot_mean = float(np.mean(np.asarray(agg["overall_mean_variance"])))
    boot_std = float(np.std(np.asarray(agg["overall_mean_variance"])))
    assert abs(boot_mean - sample_mean) < 4 * boot_std / np.sqrt(400) + 1e-6
    # spread must be of order sigma/sqrt(M)
    per_window_var = np.asarray(point["pred_variance"])
    expected_se = per_window_var.std() / np.sqrt(2000)
    assert 0.5 * expected_se < boot_std < 2.0 * expected_se


def test_confidence_intervals_ordering(rng):
    preds = rng.uniform(0.1, 0.9, size=(10, 500))
    y = rng.integers(0, 2, 500)
    agg = bootstrap_aggregates(preds, y, n_bootstrap=100, seed=2)
    cis = compute_confidence_intervals(agg, alpha=0.05)
    for k in AGGREGATE_KEYS:
        lo, mean, hi = cis[f"{k}_ci_lower"], cis[f"{k}_mean"], cis[f"{k}_ci_upper"]
        assert lo <= mean <= hi


def test_reference_shaped_api(rng):
    """bootstrap_metrics returns the reference's list-of-dicts shape
    (uq_techniques.py:116-172) and flows into compute_confidence_intervals."""
    preds = rng.uniform(0.1, 0.9, size=(5, 100))
    y = rng.integers(0, 2, 100)
    results = bootstrap_metrics(preds, y, n_bootstrap=12, random_state=3)
    assert isinstance(results, list) and len(results) == 12
    assert set(results[0].keys()) == set(AGGREGATE_KEYS)
    cis = compute_confidence_intervals(results)
    assert f"{AGGREGATE_KEYS[0]}_mean" in cis


def test_deterministic_given_seed(rng):
    preds = rng.uniform(0.1, 0.9, size=(5, 100))
    y = rng.integers(0, 2, 100)
    a = bootstrap_aggregates(preds, y, n_bootstrap=10, seed=7)
    b = bootstrap_aggregates(preds, y, n_bootstrap=10, seed=7)
    for k in AGGREGATE_KEYS:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_empty_results_ci():
    assert compute_confidence_intervals([]) == {}
    assert compute_confidence_intervals({}) == {}


class TestPoissonEngine:
    """The fused Poisson-bootstrap engine (ops/pallas_bootstrap.py): the
    XLA fallback path runs on the CPU CI; the Pallas kernel itself needs
    real hardware — run the gated test with
    ``APNEA_UQ_TEST_TPU=1 pytest tests/test_bootstrap.py -k on_tpu``
    on a TPU host (it skips on the default CPU-mesh suite)."""

    def test_deterministic_and_seed_sensitive(self, rng):
        preds = rng.uniform(0.1, 0.9, size=(8, 400))
        y = rng.integers(0, 2, 400)
        a = bootstrap_aggregates(preds, y, n_bootstrap=20, seed=7,
                                 engine="poisson")
        b = bootstrap_aggregates(preds, y, n_bootstrap=20, seed=7,
                                 engine="poisson")
        c = bootstrap_aggregates(preds, y, n_bootstrap=20, seed=8,
                                 engine="poisson")
        for k in AGGREGATE_KEYS:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))
        assert any(
            not np.array_equal(np.asarray(a[k]), np.asarray(c[k]))
            for k in AGGREGATE_KEYS
        )

    def test_statistically_matches_exact_engine(self, rng):
        """Poisson and multinomial bootstraps estimate the same thing: the
        mean of each aggregate's resampling distribution agrees within
        Monte-Carlo error, and CI widths are comparable."""
        m = 3000
        preds = rng.uniform(0.05, 0.95, size=(10, m))
        y = rng.integers(0, 2, m)
        B = 400
        exact = bootstrap_aggregates(preds, y, n_bootstrap=B, seed=1)
        pois = bootstrap_aggregates(preds, y, n_bootstrap=B, seed=1,
                                    engine="poisson")
        for k in AGGREGATE_KEYS:
            e = np.asarray(exact[k])
            p = np.asarray(pois[k])
            # Monte-Carlo error of the two distribution means, plus the
            # Poisson ratio-estimator bias O(mean/m) (each resample
            # normalizes by its realized size) — both shrink with m; at
            # the reference's M=293K windows the bias is ~1e-6 relative.
            tol = 5 * np.sqrt(e.var() / B + p.var() / B) + 3 * abs(e.mean()) / m + 1e-9
            assert abs(e.mean() - p.mean()) < tol, (k, e.mean(), p.mean())
            width_e = np.percentile(e, 97.5) - np.percentile(e, 2.5)
            width_p = np.percentile(p, 97.5) - np.percentile(p, 2.5)
            assert width_p < 2.5 * width_e + 1e-9
            assert width_e < 2.5 * width_p + 1e-9

    def test_single_class_guard(self, rng):
        preds = rng.uniform(0.1, 0.9, size=(5, 200))
        y = np.ones(200)  # class 0 absent
        agg = bootstrap_aggregates(preds, y, n_bootstrap=10, seed=2,
                                   engine="poisson")
        np.testing.assert_array_equal(
            np.asarray(agg["mean_variance_class_0"]), 0.0
        )
        assert np.all(np.asarray(agg["mean_variance_class_1"]) > 0)

    def test_bad_engine_rejected(self, rng):
        preds = rng.uniform(size=(3, 10))
        with pytest.raises(ValueError, match="engine"):
            bootstrap_aggregates(preds, np.zeros(10), engine="bogus")

    def test_pallas_kernel_on_tpu(self, rng):
        """TPU-only: the fused kernel agrees with its own expectation
        (count mean 1 -> sums ~ row sums), is deterministic, and zero
        padding beyond M contributes nothing."""
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("pallas kernel requires TPU")
        import jax.numpy as jnp

        from apnea_uq_tpu.ops.pallas_bootstrap import (
            N_ROWS, poisson_bootstrap_sums,
        )

        v = jnp.asarray(rng.uniform(size=(N_ROWS, 5000)), jnp.float32)
        key = jax.random.key(3)
        s1 = np.asarray(poisson_bootstrap_sums(v, key, 64))
        s2 = np.asarray(poisson_bootstrap_sums(v, key, 64))
        np.testing.assert_array_equal(s1, s2)
        assert s1.shape == (64, N_ROWS)
        row_sums = np.asarray(v.sum(axis=1))
        rel = np.abs(s1.mean(axis=0) / row_sums - 1)
        assert rel.max() < 0.05

    def test_low_variance_regime_on_tpu(self, rng):
        """TPU-only regression for the MXU precision bug: near-constant
        metric rows (a trained model's entropies vary by ~1e-4) must not
        be bf16-quantized by the kernel's matmul — the engines' aggregate
        means must agree to f32-level accuracy, not 0.25%."""
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("bf16 MXU truncation only manifests on TPU")
        preds = (0.5 + rng.normal(0, 0.002, size=(20, 20000))).astype(np.float32)
        y = rng.integers(0, 2, 20000)
        exact = bootstrap_aggregates(preds, y, n_bootstrap=50, seed=1)
        pois = bootstrap_aggregates(preds, y, n_bootstrap=50, seed=1,
                                    engine="poisson")
        for k in AGGREGATE_KEYS:
            e = np.asarray(exact[k])
            p = np.asarray(pois[k])
            assert abs(e.mean() - p.mean()) < 1e-5 + 1e-3 * abs(e.mean()), \
                (k, e.mean(), p.mean())
            # Quantization's other failure mode: the tiny across-resample
            # variance the CIs are made of collapsing to a constant.
            if e.std() > 0:
                assert p.std() > e.std() / 50, (k, e.std(), p.std())
