"""Vectorized bootstrap: statistical correctness and CI machinery
(replacing uq_techniques.py:116-206)."""

import numpy as np

from apnea_uq_tpu.uq import (
    bootstrap_aggregates,
    bootstrap_metrics,
    compute_confidence_intervals,
)
from apnea_uq_tpu.uq.bootstrap import AGGREGATE_KEYS
from apnea_uq_tpu.uq.metrics import uq_evaluation_dist


def test_shapes_and_keys(rng):
    preds = rng.uniform(0.1, 0.9, size=(10, 200))
    y = rng.integers(0, 2, 200)
    agg = bootstrap_aggregates(preds, y, n_bootstrap=37, seed=0)
    assert set(agg.keys()) == set(AGGREGATE_KEYS)
    for v in agg.values():
        assert v.shape == (37,)


def test_bootstrap_mean_tracks_point_estimate(rng):
    """Bootstrap distribution of a mean must center on the sample mean."""
    preds = rng.uniform(0.1, 0.9, size=(20, 2000))
    y = rng.integers(0, 2, 2000)
    agg = bootstrap_aggregates(preds, y, n_bootstrap=400, seed=1)
    point = uq_evaluation_dist(preds, y)
    sample_mean = float(point["overall_mean_variance"])
    boot_mean = float(np.mean(np.asarray(agg["overall_mean_variance"])))
    boot_std = float(np.std(np.asarray(agg["overall_mean_variance"])))
    assert abs(boot_mean - sample_mean) < 4 * boot_std / np.sqrt(400) + 1e-6
    # spread must be of order sigma/sqrt(M)
    per_window_var = np.asarray(point["pred_variance"])
    expected_se = per_window_var.std() / np.sqrt(2000)
    assert 0.5 * expected_se < boot_std < 2.0 * expected_se


def test_confidence_intervals_ordering(rng):
    preds = rng.uniform(0.1, 0.9, size=(10, 500))
    y = rng.integers(0, 2, 500)
    agg = bootstrap_aggregates(preds, y, n_bootstrap=100, seed=2)
    cis = compute_confidence_intervals(agg, alpha=0.05)
    for k in AGGREGATE_KEYS:
        lo, mean, hi = cis[f"{k}_ci_lower"], cis[f"{k}_mean"], cis[f"{k}_ci_upper"]
        assert lo <= mean <= hi


def test_reference_shaped_api(rng):
    """bootstrap_metrics returns the reference's list-of-dicts shape
    (uq_techniques.py:116-172) and flows into compute_confidence_intervals."""
    preds = rng.uniform(0.1, 0.9, size=(5, 100))
    y = rng.integers(0, 2, 100)
    results = bootstrap_metrics(preds, y, n_bootstrap=12, random_state=3)
    assert isinstance(results, list) and len(results) == 12
    assert set(results[0].keys()) == set(AGGREGATE_KEYS)
    cis = compute_confidence_intervals(results)
    assert f"{AGGREGATE_KEYS[0]}_mean" in cis


def test_deterministic_given_seed(rng):
    preds = rng.uniform(0.1, 0.9, size=(5, 100))
    y = rng.integers(0, 2, 100)
    a = bootstrap_aggregates(preds, y, n_bootstrap=10, seed=7)
    b = bootstrap_aggregates(preds, y, n_bootstrap=10, seed=7)
    for k in AGGREGATE_KEYS:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_empty_results_ci():
    assert compute_confidence_intervals([]) == {}
    assert compute_confidence_intervals({}) == {}
