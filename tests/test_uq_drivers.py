"""Driver pipeline tests: detailed-frame schema/semantics, evaluate_uq
aggregates vs direct computation, MCD/DE end-to-end on a tiny model, and
registry artifact round-trip."""

import os

import jax
import numpy as np
import pandas as pd
import pytest

from apnea_uq_tpu.analysis.columns import DETAILED_COLUMNS
from apnea_uq_tpu.config import ModelConfig, UQConfig
from apnea_uq_tpu.data.registry import ArtifactRegistry
from apnea_uq_tpu.models import AlarconCNN1D, init_variables
from apnea_uq_tpu.uq import (
    detailed_frame,
    evaluate_uq,
    run_de_analysis,
    run_mcd_analysis,
    save_run,
)
from apnea_uq_tpu.uq.predict import stack_member_variables


def _tiny():
    return AlarconCNN1D(ModelConfig(
        features=(4, 6), kernel_sizes=(3, 3), dropout_rates=(0.3, 0.3)
    ))


@pytest.fixture(scope="module")
def stack(    ):
    rng = np.random.default_rng(7)
    preds = rng.uniform(0.0, 1.0, size=(10, 200)).astype(np.float32)
    y = rng.integers(0, 2, 200)
    return preds, y


class TestDetailedFrame:
    def test_schema_and_values(self, stack):
        preds, y = stack
        pids = np.array([f"P{i % 5}" for i in range(200)])
        frame = detailed_frame(preds, y, pids)
        assert tuple(frame.columns) == DETAILED_COLUMNS
        np.testing.assert_allclose(
            frame["Predicted_Probability"], preds.mean(axis=0), rtol=1e-6
        )
        np.testing.assert_allclose(
            frame["Predictive_Variance"], preds.var(axis=0), rtol=1e-5
        )
        # Entropy is bits: mean prob 0.5 -> 1 bit.
        const = detailed_frame(np.full((3, 4), 0.5), np.zeros(4))
        np.testing.assert_allclose(const["Predictive_Entropy"], 1.0, atol=1e-5)
        # Threshold at 0.5 on the MEAN prob.
        np.testing.assert_array_equal(
            frame["Predicted_Label"], (preds.mean(axis=0) >= 0.5).astype(int)
        )

    def test_squeezes_trailing_axis_and_defaults_ids(self, stack):
        preds, y = stack
        frame = detailed_frame(preds[..., None], y)
        assert (frame["Patient_ID"] == "UNKNOWN").all()

    def test_length_mismatch_raises(self, stack):
        preds, y = stack
        with pytest.raises(ValueError, match="labels"):
            detailed_frame(preds, y[:-1])
        with pytest.raises(ValueError, match="patient_ids"):
            detailed_frame(preds, y, np.arange(5))


class TestEvaluateUQ:
    def test_aggregates_match_direct(self, stack):
        preds, y = stack
        ev = evaluate_uq(preds, y, UQConfig(n_bootstrap=50))
        assert ev.n_passes == 10 and ev.n_windows == 200
        assert ev.aggregates["overall_mean_variance"] == pytest.approx(
            float(preds.var(axis=0).mean()), rel=1e-5
        )
        # Decomposition identity: total ~ aleatoric + MI per window.
        pw = ev.per_window
        np.testing.assert_allclose(
            pw["total_pred_entropy"],
            pw["expected_aleatoric_entropy"] + pw["mutual_info"],
            atol=1e-5,
        )

    def test_accepts_trailing_singleton_axis(self, stack):
        preds, y = stack
        ev = evaluate_uq(preds[..., None], y, UQConfig(n_bootstrap=10))
        assert ev.n_passes == 10 and ev.n_windows == 200

    def test_ci_bounds_ordered_and_cover_point(self, stack):
        preds, y = stack
        ev = evaluate_uq(preds, y, UQConfig(n_bootstrap=200))
        ci = ev.confidence_intervals
        for name in ("overall_mean_variance", "mean_mutual_info"):
            lo, hi = ci[f"{name}_ci_lower"], ci[f"{name}_ci_upper"]
            assert lo <= hi
            assert lo - 0.05 <= ev.aggregates[name] <= hi + 0.05


class TestEndToEnd:
    @pytest.fixture(scope="class")
    def setup(self):
        model = _tiny()
        variables = init_variables(model, jax.random.key(0))
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 60, 4)).astype(np.float32)
        y = rng.integers(0, 2, 64)
        pids = np.array([f"P{i % 4}" for i in range(64)])
        return model, variables, x, y, pids

    def test_mcd_run_fused_default(self, setup):
        """The default driver config runs the fused reduction: no (K, M)
        stack on host, a (4, M) sufficient-statistics stack instead, and
        the full metric/CSV/classification pipeline downstream."""
        model, variables, x, y, pids = setup
        cfg = UQConfig(mc_passes=8, n_bootstrap=20, inference_batch_size=32,
                       mcd_batch_size=32)
        assert cfg.fused_reduction
        result = run_mcd_analysis(
            model, variables, x, y, patient_ids=pids, config=cfg,
            predict_key=jax.random.key(1),
        )
        assert result.fused and result.predictions is None
        assert result.stats.shape == (4, 64)
        # Stochastic passes actually differ (dropout active) -> nonzero
        # predictive variance somewhere.
        assert result.stats[1].max() > 0
        assert result.evaluation.n_passes == 8
        assert result.detailed is not None and len(result.detailed) == 64
        assert result.deterministic_classification is not None
        assert 0.0 <= result.classification["accuracy"] <= 1.0
        assert result.predict_seconds > 0

    def test_mcd_fused_matches_full_probs(self, setup):
        """Fused vs --full-probs on the same key: aggregates, per-window
        vectors, CIs, and the detailed frame agree to <=1e-6 (the ISSUE 6
        acceptance tolerance)."""
        import dataclasses

        model, variables, x, y, pids = setup
        fused_cfg = UQConfig(mc_passes=8, n_bootstrap=20,
                             inference_batch_size=32, mcd_batch_size=32)
        full_cfg = dataclasses.replace(fused_cfg, fused_reduction=False)
        a = run_mcd_analysis(model, variables, x, y, patient_ids=pids,
                             config=fused_cfg, predict_key=jax.random.key(1),
                             sanity_check=False)
        b = run_mcd_analysis(model, variables, x, y, patient_ids=pids,
                             config=full_cfg, predict_key=jax.random.key(1),
                             sanity_check=False)
        assert not b.fused and b.predictions.shape == (8, 64)
        assert b.stats is None
        for k in a.evaluation.aggregates:
            assert a.evaluation.aggregates[k] == pytest.approx(
                b.evaluation.aggregates[k], abs=1e-6), k
        for k in a.evaluation.per_window:
            np.testing.assert_allclose(
                a.evaluation.per_window[k], b.evaluation.per_window[k],
                rtol=0, atol=1e-6, err_msg=k)
        for k in a.evaluation.confidence_intervals:
            assert a.evaluation.confidence_intervals[k] == pytest.approx(
                b.evaluation.confidence_intervals[k], abs=1e-5), k
        pd.testing.assert_frame_equal(
            a.detailed, b.detailed, check_exact=False, rtol=1e-5,
            atol=1e-7)
        assert a.classification["accuracy"] == pytest.approx(
            b.classification["accuracy"])

    def test_fused_event_reports_d2h_reduction(self, setup, tmp_path):
        """eval_predict telemetry: fused=true and a d2h_bytes estimate
        exactly (4/K)x the full-probs run's (ISSUE 6 acceptance)."""
        import dataclasses

        from apnea_uq_tpu import telemetry
        from apnea_uq_tpu.telemetry.runlog import RunLog

        model, variables, x, y, pids = setup
        fused_cfg = UQConfig(mc_passes=8, n_bootstrap=5,
                             inference_batch_size=32, mcd_batch_size=32)
        rl = RunLog(str(tmp_path))
        run_mcd_analysis(model, variables, x, y, config=fused_cfg,
                         predict_key=jax.random.key(1), run_log=rl,
                         sanity_check=False, detailed=False)
        run_mcd_analysis(model, variables, x, y,
                         config=dataclasses.replace(fused_cfg,
                                                    fused_reduction=False),
                         predict_key=jax.random.key(1), run_log=rl,
                         sanity_check=False, detailed=False)
        rl.close()
        fused_ev, full_ev = [
            e for e in telemetry.read_events(str(tmp_path))
            if e["kind"] == "eval_predict"
        ]
        assert fused_ev["fused"] is True and full_ev["fused"] is False
        assert fused_ev["d2h_bytes"] == 4 * 64 * 4
        assert full_ev["d2h_bytes"] == 8 * 64 * 4
        assert fused_ev["d2h_bytes"] / full_ev["d2h_bytes"] == \
            pytest.approx(4 / 8)
        # The fused program was priced under its own memory label.
        labels = {e["label"]
                  for e in telemetry.read_events(str(tmp_path))
                  if e["kind"] == "memory_profile"}
        assert {"mcd_predict_fused", "mcd_predict"} <= labels

    def test_mcd_parity_mode_runs(self, setup):
        model, variables, x, y, pids = setup
        cfg = UQConfig(mc_passes=4, n_bootstrap=10, mcd_mode="parity",
                       inference_batch_size=64)
        result = run_mcd_analysis(
            model, variables, x, y, config=cfg, detailed=False,
            sanity_check=False,
        )
        assert result.detailed is None
        assert result.deterministic_classification is None

    def test_parity_mode_chunked_bn_warns(self, setup):
        """parity mode with mcd_batch_size < the window count computes
        per-chunk BN statistics (the reference's batch was the whole
        set), so the driver must warn rather than silently produce
        non-reference parity numbers; whole-set and clean-mode runs must
        stay silent."""
        import dataclasses
        import warnings

        model, variables, x, y, _ = setup
        chunked = UQConfig(mc_passes=2, n_bootstrap=5, mcd_mode="parity",
                           mcd_batch_size=32, inference_batch_size=64)
        for warned in (
            chunked,  # smaller chunk: per-chunk subsets
            # larger but NOT a multiple of the 64 windows: wrap-padding
            # repeats some windows more than others in the BN batch.
            dataclasses.replace(chunked, mcd_batch_size=96),
        ):
            with pytest.warns(UserWarning, match="wrap-padded"):
                run_mcd_analysis(model, variables, x, y, config=warned,
                                 detailed=False, sanity_check=False)
        for quiet in (
            dataclasses.replace(chunked, mcd_batch_size=len(x)),
            # exact multiple: every window appears equally in the chunk.
            dataclasses.replace(chunked, mcd_batch_size=2 * len(x)),
            dataclasses.replace(chunked, mcd_mode="clean"),
        ):
            with warnings.catch_warnings():
                warnings.simplefilter("error")
                run_mcd_analysis(model, variables, x, y, config=quiet,
                                 detailed=False, sanity_check=False)

    def test_empty_window_set_raises_value_error(self, setup):
        """An empty window set must fail with a clear ValueError up front —
        not a ZeroDivisionError from the parity chunk warning (advisor r4)
        or a silent (T, 0) result with NaN aggregates."""
        model, variables, _, _, _ = setup
        x0 = np.zeros((0, 60, 4), np.float32)
        y0 = np.zeros((0,), np.int64)
        cfg = UQConfig(mc_passes=2, n_bootstrap=5, mcd_mode="parity",
                       mcd_batch_size=32)
        with pytest.raises(ValueError, match="at least one window"):
            run_mcd_analysis(model, variables, x0, y0, config=cfg,
                             detailed=False, sanity_check=False)
        members = stack_member_variables(
            [init_variables(model, jax.random.key(s)) for s in range(2)]
        )
        with pytest.raises(ValueError, match="at least one window"):
            run_de_analysis(model, members, x0, y0, config=cfg,
                            detailed=False)

    def test_parity_warning_uses_mesh_effective_chunk(self, setup):
        """On a mesh the predictor rounds the chunk up to the data-axis
        multiple, so a nominally-exact mcd_batch_size can still wrap-pad:
        the warning must judge the EFFECTIVE chunk (review r4)."""
        from apnea_uq_tpu.parallel import make_mesh

        model, variables, x, y, _ = setup
        x60, y60 = x[:60], y[:60]
        cfg = UQConfig(mc_passes=2, n_bootstrap=5, mcd_mode="parity",
                       mcd_batch_size=60, inference_batch_size=64)
        # data axis 8: effective chunk ceil(60/8)*8 = 64 != k*60 -> warn
        # even though mcd_batch_size == len(x).
        mesh8 = make_mesh(num_members=1, ensemble_axis=1)
        assert mesh8.shape["data"] == 8
        with pytest.warns(UserWarning, match="effective chunk 64"):
            run_mcd_analysis(model, variables, x60, y60, config=cfg,
                             detailed=False, sanity_check=False, mesh=mesh8)
        # data axis 4: effective chunk stays 60 -> quiet.
        import warnings
        mesh4 = make_mesh(num_members=2, ensemble_axis=2)
        assert mesh4.shape["data"] == 4
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            run_mcd_analysis(model, variables, x60, y60, config=cfg,
                             detailed=False, sanity_check=False, mesh=mesh4)

    def test_de_run_and_registry(self, setup, tmp_path):
        """Full-probs DE run: the (N, M) stack and its raw_predictions
        artifact (the fused default's registry shape is covered by
        test_de_fused_registry_saves_stats)."""
        model, variables, x, y, pids = setup
        members = [init_variables(model, jax.random.key(s)) for s in range(3)]
        cfg = UQConfig(n_bootstrap=20, inference_batch_size=32,
                       fused_reduction=False)
        result = run_de_analysis(
            model, members, x, y, patient_ids=pids, config=cfg,
            label="DE_test",
        )
        assert result.predictions.shape == (3, 64)
        # Deterministic members: repeat run gives identical predictions.
        again = run_de_analysis(model, members, x, y, config=cfg, detailed=False)
        np.testing.assert_allclose(result.predictions, again.predictions, atol=1e-6)

        registry = ArtifactRegistry(str(tmp_path))
        paths = save_run(registry, result)
        assert set(paths) == {"raw_predictions", "detailed_windows", "metrics"}
        loaded = registry.load_arrays("raw_predictions:DE_test")
        np.testing.assert_allclose(loaded["predictions"], result.predictions)
        table = registry.load_table("detailed_windows:DE_test")
        assert tuple(table.columns) == DETAILED_COLUMNS
        pd.testing.assert_frame_equal(
            table, result.detailed, check_dtype=False, check_exact=False,
            rtol=1e-6,
        )
        # The scalar results survive the terminal: aggregates + CIs +
        # classification round-trip through the metrics JSON artifact.
        doc = registry.load_json("metrics:DE_test")
        assert doc["label"] == "DE_test"
        assert doc["n_passes"] == 3 and doc["n_windows"] == 64
        assert doc["aggregates"] == pytest.approx(result.evaluation.aggregates)
        assert doc["confidence_intervals"] == pytest.approx(
            result.evaluation.confidence_intervals
        )
        assert doc["classification"]["accuracy"] == pytest.approx(
            result.classification["accuracy"]
        )
        assert doc["classification"]["confusion_matrix"] == np.asarray(
            result.classification["confusion_matrix"]
        ).tolist()
        assert doc["fused"] is False

    def test_de_fused_registry_saves_stats(self, setup, tmp_path):
        """A fused DE run persists uq_stats:<label> (no raw_predictions —
        the (N, M) stack never existed on host) and a metrics doc whose
        numbers match a full-probs run's to <=1e-6."""
        model, variables, x, y, pids = setup
        members = [init_variables(model, jax.random.key(s)) for s in range(3)]
        cfg = UQConfig(n_bootstrap=20, inference_batch_size=32)
        result = run_de_analysis(
            model, members, x, y, patient_ids=pids, config=cfg,
            label="DE_fused",
        )
        assert result.fused and result.predictions is None
        registry = ArtifactRegistry(str(tmp_path))
        paths = save_run(registry, result)
        assert set(paths) == {"uq_stats", "detailed_windows", "metrics"}
        stats = registry.load_arrays("uq_stats:DE_fused")["stats"]
        assert stats.shape == (4, 64)
        np.testing.assert_allclose(stats, result.stats)
        doc = registry.load_json("metrics:DE_fused")
        assert doc["fused"] is True and doc["n_passes"] == 3
        import dataclasses
        full = run_de_analysis(
            model, members, x, y, patient_ids=pids,
            config=dataclasses.replace(cfg, fused_reduction=False),
            label="DE_fused",
        )
        for k in doc["aggregates"]:
            assert doc["aggregates"][k] == pytest.approx(
                full.evaluation.aggregates[k], abs=1e-6), k

    def test_mcd_streaming_config(self, setup):
        """UQConfig.mcd_streaming routes prediction through the host-
        streamed path with identical results."""
        model, variables, x, y, pids = setup
        # full-probs configs: this test pins the streamed == in-HBM RAW
        # prediction identity (the fused streamed/in-HBM equivalence is
        # test_uq_predict.py::TestFusedStats).
        base = UQConfig(mc_passes=6, n_bootstrap=10, mcd_batch_size=32,
                        fused_reduction=False)
        stream = UQConfig(mc_passes=6, n_bootstrap=10, mcd_batch_size=32,
                          mcd_streaming=True, fused_reduction=False)
        a = run_mcd_analysis(model, variables, x, y, config=base, seed=4,
                             detailed=False, sanity_check=False)
        b = run_mcd_analysis(model, variables, x, y, config=stream, seed=4,
                             detailed=False, sanity_check=False)
        np.testing.assert_array_equal(a.predictions, b.predictions)
        assert a.evaluation.confidence_intervals == b.evaluation.confidence_intervals
        # And the fused default streams identically too (stats route).
        fa = run_mcd_analysis(model, variables, x, y,
                              config=UQConfig(mc_passes=6, n_bootstrap=10,
                                              mcd_batch_size=32),
                              seed=4, detailed=False, sanity_check=False)
        fb = run_mcd_analysis(model, variables, x, y,
                              config=UQConfig(mc_passes=6, n_bootstrap=10,
                                              mcd_batch_size=32,
                                              mcd_streaming=True),
                              seed=4, detailed=False, sanity_check=False)
        np.testing.assert_array_equal(fa.stats, fb.stats)

    def test_mcd_streaming_with_mesh(self, setup):
        """Streaming + mesh compose in the driver (VERDICT r2 #5): the
        streamed chunks shard over (ensemble, data) and the run equals
        both the in-HBM mesh run and the single-device stream."""
        from apnea_uq_tpu.parallel import make_mesh

        model, variables, x, y, pids = setup
        mesh = make_mesh(num_members=4)  # (4, 2) on the 8-device rig
        base = UQConfig(mc_passes=6, n_bootstrap=10, mcd_batch_size=32,
                        fused_reduction=False)
        stream = UQConfig(mc_passes=6, n_bootstrap=10, mcd_batch_size=32,
                          mcd_streaming=True, fused_reduction=False)
        a = run_mcd_analysis(model, variables, x, y, config=base, seed=4,
                             detailed=False, sanity_check=False, mesh=mesh)
        b = run_mcd_analysis(model, variables, x, y, config=stream, seed=4,
                             detailed=False, sanity_check=False, mesh=mesh)
        np.testing.assert_allclose(a.predictions, b.predictions,
                                   rtol=1e-6, atol=1e-7)

    def test_de_streaming_config(self, setup):
        """UQConfig.de_streaming routes DE prediction through the host-
        streamed path with identical results."""
        model, variables, x, y, pids = setup
        members = [init_variables(model, jax.random.key(s)) for s in range(2)]
        base = UQConfig(n_bootstrap=10, inference_batch_size=32,
                        fused_reduction=False)
        stream = UQConfig(n_bootstrap=10, inference_batch_size=32,
                          de_streaming=True, fused_reduction=False)
        a = run_de_analysis(model, members, x, y, config=base, seed=4,
                            detailed=False)
        b = run_de_analysis(model, members, x, y, config=stream, seed=4,
                            detailed=False)
        np.testing.assert_allclose(a.predictions, b.predictions,
                                   rtol=1e-6, atol=1e-7)
        # CIs derive from the (float-tolerance-equal) predictions, so
        # compare with the same tolerance, not exact equality.
        ci_a, ci_b = a.evaluation.confidence_intervals, b.evaluation.confidence_intervals
        assert set(ci_a) == set(ci_b)
        for k in ci_a:
            assert ci_a[k] == pytest.approx(ci_b[k], rel=1e-5, abs=1e-7), k


class TestSyntheticDemo:
    """run_synthetic_demo: the reference's zero-data smoke demo
    (uq_techniques.py:395-446) as a first-class driver — a golden-range
    test per SURVEY §4 item 1."""

    def test_exercises_full_pipeline(self):
        from apnea_uq_tpu.uq import run_synthetic_demo

        res = run_synthetic_demo(n_models=5, n_windows=1000, seed=2025)
        ev = res.evaluation
        assert ev.n_passes == 5 and ev.n_windows == 1000
        # Golden ranges: the separable-latent construction must classify
        # well above chance and produce non-degenerate uncertainty.
        assert res.classification["accuracy"] > 0.75
        assert 0.0 < ev.aggregates["overall_mean_variance"] < 0.25
        assert ev.aggregates["mean_mutual_info"] >= 0.0
        assert (ev.aggregates["mean_total_pred_entropy"]
                >= ev.aggregates["mean_expected_aleatoric_entropy"])
        for name in ("overall_mean_variance", "mean_mutual_info"):
            lo = ev.confidence_intervals[f"{name}_ci_lower"]
            hi = ev.confidence_intervals[f"{name}_ci_upper"]
            assert lo <= hi
        # Detailed frame + synthetic patients feed the L6 analyses.
        assert res.detailed is not None and len(res.detailed) == 1000
        assert res.detailed["Patient_ID"].str.startswith("DEMO").all()
        assert res.detailed["Patient_ID"].nunique() > 1

    def test_deterministic_and_param_validation(self):
        from apnea_uq_tpu.uq import run_synthetic_demo

        a = run_synthetic_demo(n_windows=200, seed=7)
        b = run_synthetic_demo(n_windows=200, seed=7)
        np.testing.assert_array_equal(a.predictions, b.predictions)
        with pytest.raises(ValueError):
            run_synthetic_demo(positive_rate=1.5)


def test_demo_cli(tmp_path, capsys):
    from apnea_uq_tpu.cli.main import main

    plots = str(tmp_path / "figs")
    assert main(["demo", "--num-windows", "300", "--plots-dir", plots]) == 0
    out = capsys.readouterr().out
    assert "SYNTHETIC_DEMO" in out
    assert "overall_mean_variance" in out
    assert len(os.listdir(plots)) == 4
