"""Mergeable latency digest (ISSUE 18 tentpole): the fixed-bin
log-spaced histogram every ``serve_slo`` event serializes.

Pins the three properties the fleet rollup leans on:

- exact counts — add/extend/merge never lose or invent samples, under
  ANY merge order/grouping (bin-wise addition is associative and
  commutative);
- bounded percentile error — ``percentile(q)`` lands within the
  documented multiplicative bound (``REL_ERROR_BOUND``, half a bin
  ratio) of ``np.percentile`` over the pooled raw samples, merged or
  not, for every in-range sample set;
- lossless transport — the sparse JSON payload round-trips bit-exactly
  and refuses malformed/foreign payloads loudly.

All host-side NumPy — no jax anywhere near this module.
"""

import numpy as np
import pytest

from apnea_uq_tpu.telemetry.digest import (
    BINS_PER_DECADE,
    HI,
    LO,
    NUM_BINS,
    RATIO,
    REL_ERROR_BOUND,
    LatencyDigest,
    bin_index,
    bin_value,
    merge_payloads,
)


# ------------------------------------------------------------- binning --


class TestBinning:
    def test_bins_are_monotone_and_cover_the_range(self):
        # Every in-range value lands in a valid bin, and bin index is
        # monotone in the value.
        values = np.geomspace(LO, HI * 0.999, 5000)
        idx = [bin_index(float(v)) for v in values]
        assert all(0 <= i < NUM_BINS for i in idx)
        assert idx == sorted(idx)
        assert idx[0] == 0 and idx[-1] == NUM_BINS - 1

    def test_bin_value_is_inside_its_own_bin(self):
        for i in (0, 1, 63, 64, 320, NUM_BINS - 1):
            rep = bin_value(i)
            assert bin_index(rep) == i
            lo_edge = LO * RATIO**i
            assert lo_edge <= rep < lo_edge * RATIO

    def test_out_of_range_and_non_finite_values(self):
        # Underflow: zero, negatives, NaN (unmeasurable) all clamp low.
        for v in (0.0, -1.0, LO / 2, float("nan"), float("-inf")):
            assert bin_index(v) == -1
        # Overflow clamps high.
        for v in (HI, HI * 10, float("inf")):
            assert bin_index(v) == NUM_BINS
        assert bin_value(-1) == LO
        assert bin_value(NUM_BINS) == HI

    def test_bin_geometry_constants(self):
        assert NUM_BINS == BINS_PER_DECADE * 10
        assert RATIO == pytest.approx(10.0 ** (1.0 / BINS_PER_DECADE))
        # The documented bound IS half a bin in log space.
        assert REL_ERROR_BOUND == pytest.approx(np.sqrt(RATIO) - 1.0)


# ------------------------------------------------- counts and merging --


def _seeded_samples(seed, n=500):
    rng = np.random.default_rng(seed)
    kind = seed % 4
    if kind == 0:
        return rng.lognormal(mean=-3.0, sigma=1.2, size=n)
    if kind == 1:
        return rng.uniform(1e-4, 2.0, size=n)
    if kind == 2:
        return rng.exponential(scale=0.05, size=n)
    return np.full(n, float(rng.uniform(1e-3, 1.0)))  # degenerate


class TestCounts:
    def test_add_extend_count_exactly(self):
        d = LatencyDigest("s")
        assert d.count == 0
        d.add(0.5)
        d.extend([0.1, 0.2, 0.3])
        d.extend(np.asarray([1e-9, 1e9]))  # under/overflow still count
        assert d.count == 6

    def test_merge_orders_conserve_exact_counts(self):
        # The satellite contract: ANY merge grouping/order yields the
        # same total count and the same bin table.
        parts = [_seeded_samples(s) for s in range(6)]
        digests = []
        for part in parts:
            d = LatencyDigest("s")
            d.extend(part)
            digests.append(d)
        total = sum(len(p) for p in parts)

        def fold(order):
            acc = LatencyDigest("s")
            for i in order:
                acc.merge(digests[i])
            return acc

        base = fold(range(6))
        assert base.count == total
        rng = np.random.default_rng(7)
        for _ in range(5):
            order = rng.permutation(6)
            other = fold(order)
            assert other.count == total
            assert other.counts == base.counts
            assert other.underflow == base.underflow
            assert other.overflow == base.overflow

    def test_merge_with_empty_is_identity(self):
        d = LatencyDigest("s")
        d.extend(_seeded_samples(1))
        before = (dict(d.counts), d.underflow, d.overflow)
        d.merge(LatencyDigest("s"))
        assert (dict(d.counts), d.underflow, d.overflow) == before
        empty = LatencyDigest("s")
        empty.merge(d)
        assert empty.count == d.count
        assert empty.percentile(50) == d.percentile(50)

    def test_unit_mismatch_refused(self):
        d_s, d_ms = LatencyDigest("s"), LatencyDigest("ms")
        with pytest.raises(ValueError, match="unit"):
            d_s.merge(d_ms)


# -------------------------------------------------- percentile bound --


class TestPercentileBound:
    @pytest.mark.parametrize("seed", range(8))
    def test_single_digest_within_documented_bound(self, seed):
        samples = _seeded_samples(seed)
        d = LatencyDigest("s")
        d.extend(samples)
        for q in (0, 1, 10, 25, 50, 75, 90, 95, 99, 99.9, 100):
            got = d.percentile(q)
            want = float(np.percentile(samples, q))
            assert got == pytest.approx(want, rel=REL_ERROR_BOUND), (
                f"q={q}: digest {got} vs numpy {want}")

    @pytest.mark.parametrize("n_parts", (2, 3, 7))
    def test_merged_percentiles_match_pooled_raw_samples(self, n_parts):
        # The fleet contract: merging per-replica digests reproduces
        # np.percentile over the POOLED raw samples within the bound —
        # as if one process had seen all the traffic.
        parts = [_seeded_samples(10 + i, n=300 + 50 * i)
                 for i in range(n_parts)]
        acc = LatencyDigest("s")
        for part in parts:
            d = LatencyDigest("s")
            d.extend(part)
            acc.merge(d)
        pooled = np.concatenate(parts)
        assert acc.count == pooled.size
        for q in (50, 90, 95, 99):
            got = acc.percentile(q)
            want = float(np.percentile(pooled, q))
            assert got == pytest.approx(want, rel=REL_ERROR_BOUND)

    def test_empty_digest_percentile_is_none(self):
        d = LatencyDigest("s")
        assert d.percentile(50) is None
        assert d.percentiles((50, 99)) == [None, None]

    def test_percentile_argument_validation(self):
        d = LatencyDigest("s")
        d.add(0.1)
        for bad in (-0.1, 100.1):
            with pytest.raises(ValueError, match="percentile"):
                d.percentile(bad)

    def test_single_sample_every_percentile_is_its_bin(self):
        d = LatencyDigest("s")
        d.add(0.25)
        rep = d.percentile(50)
        assert rep == d.percentile(0) == d.percentile(100)
        assert rep == pytest.approx(0.25, rel=REL_ERROR_BOUND)


# ------------------------------------------------------------ payload --


class TestPayload:
    def test_round_trip_is_exact(self):
        d = LatencyDigest("ms")
        d.extend(_seeded_samples(3) * 1e3)
        d.add(0.0)    # underflow
        d.add(1e12)   # overflow
        back = LatencyDigest.from_payload(d.to_payload())
        assert back.unit == "ms"
        assert back.counts == d.counts
        assert back.underflow == d.underflow == 1
        assert back.overflow == d.overflow == 1
        assert back.percentile(95) == d.percentile(95)

    def test_payload_is_sparse(self):
        d = LatencyDigest("s")
        d.add(0.5)
        payload = d.to_payload()
        assert len(payload["bins"]) == 1
        assert "underflow" not in payload and "overflow" not in payload
        assert payload["n"] == 1

    def test_foreign_and_malformed_payloads_refused(self):
        with pytest.raises(ValueError, match="version"):
            LatencyDigest.from_payload({"v": 99, "unit": "s", "bins": {}})
        with pytest.raises(ValueError):
            LatencyDigest.from_payload(
                {"v": 1, "unit": "s", "bins": {str(NUM_BINS + 5): 1}})
        with pytest.raises(ValueError):
            LatencyDigest.from_payload(
                {"v": 1, "unit": "s", "bins": {"3": -2}})

    def test_merge_payloads_helper(self):
        parts = [_seeded_samples(s) for s in (20, 21)]
        payloads = []
        for part in parts:
            d = LatencyDigest("s")
            d.extend(part)
            payloads.append(d.to_payload())
        merged = merge_payloads(payloads)
        assert merged.unit == "s"
        assert merged.count == sum(len(p) for p in parts)
        with pytest.raises(ValueError, match="unit"):
            merge_payloads(payloads, unit="ms")
        assert merge_payloads([], unit="s").count == 0
