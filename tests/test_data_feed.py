"""Host->device feed: batching and prefetch semantics."""

import numpy as np

from apnea_uq_tpu.data.feed import batch_iterator, prefetch_to_device


def test_batch_iterator_covers_all_rows(rng):
    x = rng.normal(size=(25, 4)).astype(np.float32)
    y = np.arange(25)
    batches = list(batch_iterator({"x": x, "y": y}, batch_size=8))
    assert [len(b["y"]) for b in batches] == [8, 8, 8, 1]
    np.testing.assert_array_equal(np.concatenate([b["y"] for b in batches]), y)


def test_drop_remainder(rng):
    x = rng.normal(size=(25, 4)).astype(np.float32)
    batches = list(batch_iterator({"x": x}, batch_size=8, drop_remainder=True))
    assert [len(b["x"]) for b in batches] == [8, 8, 8]


def test_shuffle_deterministic_and_complete(rng):
    y = np.arange(100)
    a = list(batch_iterator({"y": y}, 16, shuffle=True, seed=5))
    b = list(batch_iterator({"y": y}, 16, shuffle=True, seed=5))
    c = list(batch_iterator({"y": y}, 16, shuffle=True, seed=6))
    flat_a = np.concatenate([m["y"] for m in a])
    flat_b = np.concatenate([m["y"] for m in b])
    flat_c = np.concatenate([m["y"] for m in c])
    np.testing.assert_array_equal(flat_a, flat_b)
    assert not np.array_equal(flat_a, flat_c)
    np.testing.assert_array_equal(np.sort(flat_a), y)  # a permutation


def test_prefetch_preserves_stream(rng):
    x = rng.normal(size=(40, 3)).astype(np.float32)
    batches = list(batch_iterator({"x": x}, 8))
    out = list(prefetch_to_device(batches, size=2))
    assert len(out) == len(batches)
    for got, want in zip(out, batches):
        np.testing.assert_array_equal(np.asarray(got["x"]), want["x"])


def test_prefetch_empty_stream():
    assert list(prefetch_to_device([], size=2)) == []


def test_prefetch_lazy_consumption(rng):
    """The producer is only pulled `size` batches ahead of the consumer."""
    pulled = []

    def producer():
        for i in range(6):
            pulled.append(i)
            yield {"i": np.array([i])}

    stream = prefetch_to_device(producer(), size=2)
    assert pulled == []           # nothing pulled before iteration starts
    first = next(stream)
    assert int(np.asarray(first["i"])[0]) == 0
    assert len(pulled) <= 4       # 1 yielded + up to `size` in flight + 1 refill
