"""Host->device feed: prefetch semantics."""

import numpy as np
import pytest

from apnea_uq_tpu.data.feed import prefetch_to_device


def _batches(x, batch_size):
    for start in range(0, x.shape[0], batch_size):
        yield {"x": x[start:start + batch_size]}


def test_prefetch_preserves_stream(rng):
    x = rng.normal(size=(40, 3)).astype(np.float32)
    batches = list(_batches(x, 8))
    out = list(prefetch_to_device(batches, size=2))
    assert len(out) == len(batches)
    for got, want in zip(out, batches):
        np.testing.assert_array_equal(np.asarray(got["x"]), want["x"])


def test_prefetch_empty_stream():
    assert list(prefetch_to_device([], size=2)) == []


def test_prefetch_size_validation():
    with pytest.raises(ValueError):
        list(prefetch_to_device([{"x": np.ones(2)}], size=0))


def test_prefetch_lazy_consumption(rng):
    """The producer is only pulled `size` batches ahead of the consumer."""
    pulled = []

    def producer():
        for i in range(6):
            pulled.append(i)
            yield {"i": np.array([i])}

    stream = prefetch_to_device(producer(), size=2)
    assert pulled == []           # nothing pulled before iteration starts
    first = next(stream)
    assert int(np.asarray(first["i"])[0]) == 0
    assert len(pulled) <= 4       # 1 yielded + up to `size` in flight + 1 refill
