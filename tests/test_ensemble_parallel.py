"""Ensemble-axis training over the 8-device virtual mesh.

Covers the reference capability of train_deep_ensemble_cnns.py (sequential
member loop) re-designed as concurrent mesh-parallel training, including
per-member early stopping semantics (SURVEY §7 hard parts).
"""

import dataclasses

import jax
import numpy as np
import pytest

from apnea_uq_tpu.config import EnsembleConfig, ModelConfig
from apnea_uq_tpu.models import AlarconCNN1D
from apnea_uq_tpu.parallel import fit_ensemble, make_mesh
from apnea_uq_tpu.uq import ensemble_predict, uq_evaluation_dist


def _tiny():
    return AlarconCNN1D(ModelConfig(
        features=(8, 8), kernel_sizes=(5, 3), dropout_rates=(0.1, 0.1)
    ))


def _data(rng, n=512):
    y = rng.integers(0, 2, n)
    x = rng.normal(size=(n, 60, 4)).astype(np.float32)
    x[:, :, 0] += (y * 2.0 - 1.0)[:, None] * 1.5
    return x, y.astype(np.float32)


def test_mesh_shapes():
    m = make_mesh(num_members=8)
    assert m.shape["ensemble"] * m.shape["data"] == len(jax.devices())
    assert m.shape["ensemble"] == 8
    m2 = make_mesh(num_members=2)
    assert m2.shape["ensemble"] == 2 and m2.shape["data"] == 4
    m3 = make_mesh(num_members=3)  # 3 does not divide 8 -> largest divisor <= 3
    assert m3.shape["ensemble"] == 2
    with pytest.raises(ValueError):
        make_mesh(ensemble_axis=5)


def test_ensemble_trains_and_members_differ(rng):
    model = _tiny()
    x, y = _data(rng)
    cfg = EnsembleConfig(num_members=4, num_epochs=6, batch_size=128,
                         validation_split=0.125, early_stopping_patience=3)
    res = fit_ensemble(model, x, y, cfg, mesh=make_mesh(4))
    assert res.history["loss"].shape[1] == 4
    # every member's loss decreased
    assert np.all(res.history["loss"][-1] < res.history["loss"][0])
    # members are genuinely different models (different init streams)
    p0 = res.member_variables(0)["params"]
    p1 = res.member_variables(1)["params"]
    leaves0, leaves1 = jax.tree.leaves(p0), jax.tree.leaves(p1)
    assert any(not np.allclose(a, b) for a, b in zip(leaves0, leaves1))
    # ensemble prediction end-to-end, through the vmapped member axis
    probs = np.asarray(ensemble_predict(model, res.stacked_variables(), x[:64]))
    assert probs.shape == (4, 64)
    m = uq_evaluation_dist(probs, y[:64])
    assert float(np.min(np.asarray(m["mutual_info"]))) >= 0


@pytest.mark.slow  # wrap-pad transparency at predict level runs by default
def test_member_count_not_multiple_of_mesh(rng):
    """5 members on an 8-way ensemble axis: padding must be transparent."""
    model = _tiny()
    x, y = _data(rng, n=256)
    cfg = EnsembleConfig(num_members=5, num_epochs=2, batch_size=64,
                         validation_split=0.25)
    res = fit_ensemble(model, x, y, cfg, mesh=make_mesh(8))
    assert res.num_members == 5
    assert res.history["loss"].shape[1] == 5
    probs = np.asarray(ensemble_predict(model, res.stacked_variables(), x[:16]))
    assert probs.shape == (5, 16)


def test_padded_member_cost_is_logged(rng):
    """Lockstep vmap packing pads N up to the ensemble-axis multiple and
    trains throwaway slots (SURVEY §2.3's 8+2 case); fit_ensemble must
    name that cost up front instead of silently charging it — and stay
    quiet when nothing is padded."""
    model = _tiny()
    x, y = _data(rng, n=128)
    cfg = EnsembleConfig(num_members=3, num_epochs=1, batch_size=64,
                         validation_split=0.25)
    lines = []
    fit_ensemble(model, x, y, cfg, mesh=make_mesh(8), log_fn=lines.append)
    pad_lines = [l for l in lines if "discarded slot" in l]
    # 3 members on the auto (ensemble=8 -> padded to 8)... the mesh
    # factorization decides; assert the message matches the actual pad.
    assert len(pad_lines) == 1, lines
    assert "3 members" in pad_lines[0]

    cfg4 = EnsembleConfig(num_members=4, num_epochs=1, batch_size=64,
                          validation_split=0.25)
    lines4 = []
    fit_ensemble(model, x, y, cfg4, mesh=make_mesh(4), log_fn=lines4.append)
    assert not any("discarded slot" in l for l in lines4), lines4


class TestKeepPaddedMembers:
    """EnsembleConfig.keep_padded_members: the padded lockstep slots —
    pure discarded waste by default — come back as REAL members, so the
    same jitted epoch work yields more ensemble capacity (the r5 verdict's
    'the waste could be a feature')."""

    def _fit(self, rng, cfg, n=256):
        model = _tiny()
        x, y = _data(rng, n=n)
        return fit_ensemble(model, x, y, cfg, mesh=make_mesh(8))

    def test_promoted_bitmatch_explicit_larger_run(self, rng):
        """N=10 promoted on an 8-wide ensemble axis == an explicit N=16
        run with the same root key, member for member, bit for bit — and
        from the SAME number of jitted epoch dispatches as the default
        N=10 path (the promotion is free: every path executes identical
        lockstep epoch programs)."""
        cfg10 = EnsembleConfig(num_members=10, num_epochs=2, batch_size=64,
                               validation_split=0.25)
        cfg10k = dataclasses.replace(cfg10, keep_padded_members=True)
        cfg16 = dataclasses.replace(cfg10, num_members=16)
        x, y = _data(np.random.default_rng(2025), n=256)
        model = _tiny()
        mesh = make_mesh(8)
        r10 = fit_ensemble(model, x, y, cfg10, mesh=mesh)
        r10k = fit_ensemble(model, x, y, cfg10k, mesh=mesh)
        r16 = fit_ensemble(model, x, y, cfg16, mesh=mesh)

        # Promotion accounting.
        assert r10k.num_members == 16
        assert r10k.num_requested == 10
        assert r10k.promoted_members == 6
        assert r10k.member_ids.tolist() == list(range(16))
        assert r10k.history["loss"].shape[1] == 16
        assert r10k.epochs_run.shape == (16,)

        # Zero extra device compute: the trainer's epoch bookkeeping shows
        # the promoted run dispatched exactly as many jitted lockstep
        # epochs as the default (discarding) run.
        assert r10k.lockstep_epochs == r10.lockstep_epochs
        assert r10.promoted_members == 0 and r10.num_members == 10

        # Promoted members ARE the members an explicit N=16 run trains:
        # identical weights (bit-for-bit), histories, and bookkeeping.
        for a, b in zip(jax.tree.leaves(r10k.state.params),
                        jax.tree.leaves(r16.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(r10k.state.batch_stats),
                        jax.tree.leaves(r16.state.batch_stats)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(r10k.history["loss"],
                                      r16.history["loss"])
        np.testing.assert_array_equal(r10k.history["val_loss"],
                                      r16.history["val_loss"])
        np.testing.assert_array_equal(r10k.best_epoch, r16.best_epoch)
        np.testing.assert_array_equal(r10k.epochs_run, r16.epochs_run)

        # Default-config output is unchanged vs today: the promoted run's
        # first 10 members are exactly the default run's 10.
        for a, b in zip(jax.tree.leaves(r10k.state.params),
                        jax.tree.leaves(r10.state.params)):
            np.testing.assert_array_equal(np.asarray(a)[:10], np.asarray(b))
        np.testing.assert_array_equal(r10k.history["loss"][:, :10],
                                      r10.history["loss"])

        # The promoted result feeds DE inference whole (N_eff passes).
        probs = np.asarray(ensemble_predict(_tiny(), r10k, x[:16]))
        assert probs.shape == (16, 16)

    def test_promotion_log_and_no_pad_noop(self, rng):
        """The startup log names the promotion; when nothing pads (N a
        multiple of the axis) the flag changes nothing at all."""
        model = _tiny()
        x, y = _data(rng, n=128)
        cfg = EnsembleConfig(num_members=3, num_epochs=1, batch_size=64,
                             validation_split=0.25, keep_padded_members=True)
        lines = []
        res = fit_ensemble(model, x, y, cfg, mesh=make_mesh(8),
                           log_fn=lines.append)
        assert res.num_members == 8 and res.promoted_members == 5
        promo = [l for l in lines if "promoted slot" in l]
        assert len(promo) == 1 and "3 members" in promo[0], lines
        assert not any("discarded slot" in l for l in lines)

        cfg8 = dataclasses.replace(cfg, num_members=8)
        lines8 = []
        res8 = fit_ensemble(model, x, y, cfg8, mesh=make_mesh(8),
                            log_fn=lines8.append)
        assert res8.num_members == 8 and res8.promoted_members == 0
        assert not any("slot" in l for l in lines8), lines8

    def test_promotion_with_early_stopping_stays_bitmatched(self, rng):
        """With early stopping ACTIVE the promoted run is still
        bit-identical to the explicit larger run — which also means the
        lockstep waits on all returned members, so it may dispatch MORE
        epochs than the discarding run (epochs that train a real member,
        not padding; the docs' 'free per epoch' qualification)."""
        x, y = _data(np.random.default_rng(11), n=256)
        model = _tiny()
        mesh = make_mesh(8)
        cfg3 = EnsembleConfig(num_members=3, num_epochs=8, batch_size=64,
                              validation_split=0.25,
                              early_stopping_patience=2)
        cfg3k = dataclasses.replace(cfg3, keep_padded_members=True)
        cfg8 = dataclasses.replace(cfg3, num_members=8)
        r3 = fit_ensemble(model, x, y, cfg3, mesh=mesh)
        r3k = fit_ensemble(model, x, y, cfg3k, mesh=mesh)
        r8 = fit_ensemble(model, x, y, cfg8, mesh=mesh)

        # Bit-identity with the explicit N=8 run survives early stopping.
        assert r3k.lockstep_epochs == r8.lockstep_epochs
        for a, b in zip(jax.tree.leaves(r3k.state.params),
                        jax.tree.leaves(r8.state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(r3k.best_epoch, r8.best_epoch)
        np.testing.assert_array_equal(r3k.epochs_run, r8.epochs_run)

        # The promoted lockstep runs until ALL 8 members stop — never
        # fewer dispatches than the 3-member run, possibly more.
        assert r3k.lockstep_epochs >= r3.lockstep_epochs
        # Waste accounting stays consistent on both results.
        for r in (r3, r3k):
            assert r.wasted_member_epochs() == (
                r.num_members * r.lockstep_epochs - int(np.sum(r.epochs_run))
            )
            assert r.wasted_member_epochs() >= 0

    def test_promoted_members_checkpoint_under_global_seeds(self, rng,
                                                            tmp_path):
        """save_ensemble_result keys every returned member — promoted
        slots included — by seed_base + global index, so a later run that
        legitimately asks for the larger N resumes instead of retraining."""
        from apnea_uq_tpu.training import (
            EnsembleCheckpointStore, result_member_seeds,
            save_ensemble_result,
        )

        model = _tiny()
        x, y = _data(rng, n=128)
        cfg = EnsembleConfig(num_members=3, num_epochs=1, batch_size=64,
                             validation_split=0.25, seed_base=2025,
                             keep_padded_members=True)
        res = fit_ensemble(model, x, y, cfg, mesh=make_mesh(8))
        assert result_member_seeds(res, cfg.seed_base) == [
            2025 + i for i in range(8)
        ]
        store = EnsembleCheckpointStore(str(tmp_path / "ens"))
        save_ensemble_result(store, res, seed_base=cfg.seed_base)
        assert store.existing_seeds() == [2025 + i for i in range(8)]


def test_per_member_early_stopping_bookkeeping(rng):
    model = _tiny()
    x, y = _data(rng, n=384)
    # 12 epochs (not 20): the lax.scan always runs the full num_epochs
    # with masking, so the cap is pure wall-clock; members stop around
    # epoch 6-9 on this data and the e_i < E assertion branch still fires.
    cfg = EnsembleConfig(num_members=4, num_epochs=12, batch_size=64,
                         validation_split=0.25, early_stopping_patience=2)
    res = fit_ensemble(model, x, y, cfg, mesh=make_mesh(4))
    val = res.history["val_loss"]  # (E, N)
    for i in range(4):
        e_i = int(res.epochs_run[i])
        # member's recorded best epoch is the argmin of ITS val losses over
        # the epochs it actually trained
        assert res.best_epoch[i] == int(np.argmin(val[:e_i, i]))
        # stopped members stop exactly patience epochs after their best,
        # unless the global epoch cap ended training first
        if e_i < val.shape[0]:
            assert e_i - 1 - res.best_epoch[i] == cfg.early_stopping_patience


class TestDataParallelism:
    """The `data` mesh axis must do real work: batches shard over it, and
    the gradient all-reduce over its device groups must exist in the
    compiled program — not merely a mesh shape reported in metadata."""

    def test_dataset_placement_and_shard_shapes(self):
        from apnea_uq_tpu.parallel.mesh import data_sharding

        mesh = make_mesh(2)  # (ensemble=2, data=4)
        x = jax.device_put(np.zeros((64, 60, 4), np.float32), data_sharding(mesh))
        shards = x.addressable_shards
        assert len(shards) == 8
        # 4-way split of the window axis, replicated over the ensemble axis.
        assert all(s.data.shape == (16, 60, 4) for s in shards)
        assert len({s.device for s in shards}) == 8

    def test_gradient_allreduce_in_compiled_epoch(self, rng):
        """The compiled ensemble-epoch program on a (2,4) mesh contains an
        all-reduce over the 4-device data-axis groups; the same program on
        a pure-ensemble (8,1) mesh contains none."""
        from apnea_uq_tpu.parallel.ensemble import (
            count_data_allreduces, ensemble_epoch_compiled_text,
        )

        model = _tiny()
        x, y = _data(rng, n=256)
        cfg = EnsembleConfig(num_members=2, num_epochs=1, batch_size=64,
                             validation_split=0.25)
        dp_mesh = make_mesh(2)  # (2, 4): groups of 4 = the data axis
        dp_text = ensemble_epoch_compiled_text(model, x, y, cfg, mesh=dp_mesh)
        assert count_data_allreduces(dp_text, dp_mesh) > 0, \
            "DP mesh must insert a gradient all-reduce"

        cfg8 = EnsembleConfig(num_members=8, num_epochs=1, batch_size=64,
                              validation_split=0.25)
        pure_mesh = make_mesh(8)
        pure_text = ensemble_epoch_compiled_text(model, x, y, cfg8, mesh=pure_mesh)
        assert count_data_allreduces(pure_text, pure_mesh) == 0, \
            "pure ensemble mesh (data=1) must need no collective"
        assert " all-reduce(" not in pure_text and " all-reduce-start(" not in pure_text

    @pytest.mark.slow  # DP-equality runs by default via the baseline
    # trainer (test_training.py::test_fit_with_mesh_is_data_parallel_and_
    # equivalent); the HLO all-reduce assertion above stays default too.
    def test_dp_matches_single_device_run(self, rng):
        """(2,4) mesh trains the SAME models as a single-device run: DP
        slices the compute, not the semantics (same batches, same order)."""
        model = _tiny()
        x, y = _data(rng, n=256)
        cfg = EnsembleConfig(num_members=2, num_epochs=3, batch_size=64,
                             validation_split=0.25)
        res_dp = fit_ensemble(model, x, y, cfg, mesh=make_mesh(2))
        single = make_mesh(num_members=2, devices=jax.devices()[:1])
        assert dict(single.shape) == {"ensemble": 1, "data": 1}
        res_one = fit_ensemble(model, x, y, cfg, mesh=single)
        np.testing.assert_allclose(
            res_dp.history["loss"], res_one.history["loss"], rtol=2e-4, atol=2e-5
        )
        np.testing.assert_allclose(
            res_dp.history["val_loss"], res_one.history["val_loss"],
            rtol=2e-4, atol=2e-5,
        )
        for a, b in zip(
            jax.tree.leaves(res_dp.state.params),
            jax.tree.leaves(res_one.state.params),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-4)


def test_make_mesh_from_config():
    from apnea_uq_tpu.config import MeshConfig
    from apnea_uq_tpu.parallel.mesh import make_mesh_from_config

    m = make_mesh_from_config(MeshConfig(), num_members=2)
    assert dict(m.shape) == {"ensemble": 2, "data": 4}
    m2 = make_mesh_from_config(MeshConfig(data_axis=2), num_members=8)
    assert dict(m2.shape) == {"ensemble": 4, "data": 2}
    m3 = make_mesh_from_config(MeshConfig(ensemble_axis=8), num_members=2)
    assert dict(m3.shape) == {"ensemble": 8, "data": 1}
    with pytest.raises(ValueError):
        make_mesh_from_config(MeshConfig(data_axis=3))
    with pytest.raises(ValueError):
        make_mesh_from_config(MeshConfig(ensemble_axis=2, data_axis=2))


@pytest.mark.slow  # the baseline trainer's streamed==in-HBM parity
# (test_training.py::test_fit_streaming_identical_to_in_hbm) runs by default
def test_fit_ensemble_streaming_identical(rng):
    """Streamed ensemble training (host batch stacks -> prefetch -> vmapped
    step) reproduces the in-HBM scan path: same permutations, RNG streams,
    losses, early-stop bookkeeping, and final members."""
    model = _tiny()
    x, y = _data(rng, n=320)
    cfg = EnsembleConfig(num_members=2, num_epochs=3, batch_size=64,
                         validation_split=0.2, early_stopping_patience=2)
    mesh = make_mesh(2)  # (2, 4): member + data axes both exercised
    r_mem = fit_ensemble(model, x, y, cfg, mesh=mesh)
    r_str = fit_ensemble(model, x, y, cfg, mesh=mesh, streaming=True)
    np.testing.assert_allclose(r_str.history["loss"], r_mem.history["loss"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(r_str.history["val_loss"],
                               r_mem.history["val_loss"], rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(r_str.best_epoch, r_mem.best_epoch)
    np.testing.assert_array_equal(r_str.epochs_run, r_mem.epochs_run)
    for a, b in zip(jax.tree.leaves(r_str.state.params),
                    jax.tree.leaves(r_mem.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
