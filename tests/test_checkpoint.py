"""Checkpoint tests: state round-trip, per-member resume semantics,
ensemble save/unstack."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apnea_uq_tpu.config import ModelConfig
from apnea_uq_tpu.models import AlarconCNN1D
from apnea_uq_tpu.parallel.ensemble import init_ensemble_state
from apnea_uq_tpu.training import (
    EnsembleCheckpointStore,
    create_train_state,
    member_state,
    restore_state,
    save_ensemble,
    save_state,
)


def _tiny():
    return AlarconCNN1D(ModelConfig(
        features=(4, 6), kernel_sizes=(3, 3), dropout_rates=(0.1, 0.1)
    ))


def _tree_allclose(a, b):
    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for la, lb in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb))


def test_state_round_trip(tmp_path):
    model = _tiny()
    state = create_train_state(model, jax.random.key(3))
    path = save_state(str(tmp_path / "ckpt"), state)
    template = create_train_state(model, jax.random.key(99))  # different values
    restored = restore_state(path, template)
    _tree_allclose(state.params, restored.params)
    _tree_allclose(state.batch_stats, restored.batch_stats)
    _tree_allclose(state.opt_state, restored.opt_state)
    assert int(restored.step) == int(state.step)


def test_member_store_resume_semantics(tmp_path):
    model = _tiny()
    store = EnsembleCheckpointStore(str(tmp_path / "ens"))
    assert store.existing_seeds() == []
    assert not store.member_exists(2025)

    s0 = create_train_state(model, jax.random.key(0))
    s1 = create_train_state(model, jax.random.key(1))
    store.save_member(2025, s0)
    store.save_member(2026, s1)
    assert store.existing_seeds() == [2025, 2026]
    assert store.member_exists(2025) and not store.member_exists(2030)

    template = create_train_state(model, jax.random.key(42))
    r0 = store.restore_member(2025, template)
    _tree_allclose(s0.params, r0.params)
    # restore_members preserves order
    r = store.restore_members([2026, 2025], template)
    _tree_allclose(s1.params, r[0].params)
    _tree_allclose(s0.params, r[1].params)


def test_save_ensemble_unstacks_members(tmp_path):
    model = _tiny()
    stacked = init_ensemble_state(model, 3, jax.random.key(7))
    store = EnsembleCheckpointStore(str(tmp_path / "ens"))
    seeds = [2025, 2026, 2027]
    save_ensemble(store, stacked, seeds)
    assert store.existing_seeds() == seeds

    template = create_train_state(model, jax.random.key(0))
    for i, seed in enumerate(seeds):
        restored = store.restore_member(seed, template)
        _tree_allclose(member_state(stacked, i).params, restored.params)

    # Members have distinct inits (per-member RNG folding).
    l0 = jax.tree.leaves(member_state(stacked, 0).params)
    l1 = jax.tree.leaves(member_state(stacked, 1).params)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b)) for a, b in zip(l0, l1)
    )


def test_save_ensemble_skip_existing(tmp_path):
    model = _tiny()
    store = EnsembleCheckpointStore(str(tmp_path / "ens"))
    stacked_a = init_ensemble_state(model, 2, jax.random.key(1))
    stacked_b = init_ensemble_state(model, 2, jax.random.key(2))
    save_ensemble(store, stacked_a, [10, 11])
    # With skip_existing, a second save must NOT overwrite member 10.
    save_ensemble(store, stacked_b, [10, 12], skip_existing=True)
    template = create_train_state(model, jax.random.key(0))
    r10 = store.restore_member(10, template)
    _tree_allclose(member_state(stacked_a, 0).params, r10.params)
    assert store.existing_seeds() == [10, 11, 12]
