"""Trainer tests: loss goes down, early stopping + best-weight restore,
prediction chunking invariance."""

import jax
import numpy as np

from apnea_uq_tpu.config import ModelConfig, TrainConfig
from apnea_uq_tpu.models import AlarconCNN1D
from apnea_uq_tpu.training import create_train_state, fit, predict_proba_batched


def _separable_data(rng, n=512):
    """Windows whose channel-0 mean drift determines the label — learnable fast."""
    y = rng.integers(0, 2, n)
    x = rng.normal(size=(n, 60, 4)).astype(np.float32)
    x[:, :, 0] += (y * 2.0 - 1.0)[:, None] * 1.5
    return x, y.astype(np.float32)


def _tiny():
    return AlarconCNN1D(ModelConfig(
        features=(8, 8), kernel_sizes=(5, 3), dropout_rates=(0.1, 0.1)
    ))


def test_loss_decreases(rng):
    model = _tiny()
    x, y = _separable_data(rng)
    state = create_train_state(model, jax.random.key(0))
    cfg = TrainConfig(batch_size=64, num_epochs=5, validation_split=0.0, seed=1)
    result = fit(model, state, x, y, cfg)
    assert result.history["loss"][-1] < result.history["loss"][0]


def test_learns_separable_problem(rng):
    model = _tiny()
    x, y = _separable_data(rng, n=1024)
    state = create_train_state(model, jax.random.key(0))
    cfg = TrainConfig(batch_size=128, num_epochs=12, validation_split=0.1, seed=1)
    result = fit(model, state, x, y, cfg)
    probs = np.asarray(
        predict_proba_batched(model, result.state.variables(), x, batch_size=256)
    )
    acc = float(np.mean((probs >= 0.5) == (y >= 0.5)))
    assert acc > 0.8, acc


def test_early_stopping_restores_best(rng):
    model = _tiny()
    x, y = _separable_data(rng, n=256)
    state = create_train_state(model, jax.random.key(0))
    cfg = TrainConfig(
        batch_size=64, num_epochs=30, validation_split=0.2,
        early_stopping_patience=2, seed=1,
    )
    result = fit(model, state, x, y, cfg)
    val = result.history["val_loss"]
    assert result.best_epoch == int(np.argmin(val))
    if result.stopped_early:
        assert len(val) < cfg.num_epochs
        # patience semantics: best epoch is `patience` before the last epoch run
        assert len(val) - 1 - result.best_epoch == cfg.early_stopping_patience


def test_partial_batch_masking(rng):
    """N not divisible by batch size must train without shape errors and
    padded rows must not contribute (loss is finite, same epochs run)."""
    model = _tiny()
    x, y = _separable_data(rng, n=130)  # 130 % 64 != 0
    state = create_train_state(model, jax.random.key(0))
    cfg = TrainConfig(batch_size=64, num_epochs=2, validation_split=0.0, seed=1)
    result = fit(model, state, x, y, cfg)
    assert np.isfinite(result.history["loss"]).all()


def test_predict_chunking_invariance(rng):
    model = _tiny()
    x, _ = _separable_data(rng, n=100)
    state = create_train_state(model, jax.random.key(0))
    p1 = np.asarray(predict_proba_batched(model, state.variables(), x, batch_size=7))
    p2 = np.asarray(predict_proba_batched(model, state.variables(), x, batch_size=100))
    np.testing.assert_allclose(p1, p2, rtol=2e-5, atol=1e-6)


def test_reproducible_given_seed(rng):
    model = _tiny()
    x, y = _separable_data(rng, n=128)
    cfg = TrainConfig(batch_size=64, num_epochs=2, validation_split=0.0, seed=42)
    r1 = fit(model, create_train_state(model, jax.random.key(5)), x, y, cfg)
    r2 = fit(model, create_train_state(model, jax.random.key(5)), x, y, cfg)
    np.testing.assert_allclose(r1.history["loss"], r2.history["loss"], rtol=1e-6)


def test_fit_with_mesh_is_data_parallel_and_equivalent(rng):
    """Baseline fit over a data-only mesh: the compiled epoch contains the
    gradient all-reduce over all 8 devices, and losses match the
    single-device run (same batches, same order, sliced compute)."""
    from apnea_uq_tpu.parallel import make_mesh
    from apnea_uq_tpu.parallel.mesh import data_sharding
    from apnea_uq_tpu.training.state import make_optimizer
    from apnea_uq_tpu.training.trainer import _epoch_jit

    model = _tiny()
    x, y = _separable_data(rng, n=256)
    cfg = TrainConfig(batch_size=64, num_epochs=3, validation_split=0.25,
                      seed=3)
    mesh = make_mesh(num_members=1)  # (ensemble=1, data=8)
    assert dict(mesh.shape) == {"ensemble": 1, "data": 8}

    r_mesh = fit(model, create_train_state(model, jax.random.key(5)), x, y,
                 cfg, mesh=mesh)
    r_one = fit(model, create_train_state(model, jax.random.key(5)), x, y, cfg)
    np.testing.assert_allclose(r_mesh.history["loss"], r_one.history["loss"],
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        r_mesh.history["val_loss"], r_one.history["val_loss"],
        rtol=2e-4, atol=2e-5,
    )

    from apnea_uq_tpu.parallel.ensemble import count_data_allreduces

    state = create_train_state(model, jax.random.key(5))
    tx = make_optimizer(cfg.learning_rate)
    args = (model, tx, state, x[:192].astype(np.float32),
            y[:192].astype(np.float32), jax.random.key(1), 64, True)
    dp_text = _epoch_jit.lower(*args, data_sharding(mesh)).compile().as_text()
    assert count_data_allreduces(dp_text, mesh) > 0
    plain_text = _epoch_jit.lower(*args, None).compile().as_text()
    assert " all-reduce(" not in plain_text and " all-reduce-start(" not in plain_text


def test_fit_streaming_identical_to_in_hbm(rng):
    """Streaming fit (host batches -> prefetch_to_device -> step) must
    reproduce the in-HBM scan path exactly: same permutation, batches,
    masks, dropout streams, and loss accumulation order."""
    model = _tiny()
    x, y = _separable_data(rng, n=200)  # 200 % 64 != 0: wrap-pad exercised
    cfg = TrainConfig(batch_size=64, num_epochs=3, validation_split=0.2, seed=9)
    r_mem = fit(model, create_train_state(model, jax.random.key(2)), x, y, cfg)
    r_str = fit(model, create_train_state(model, jax.random.key(2)), x, y, cfg,
                streaming=True)
    np.testing.assert_allclose(r_str.history["loss"], r_mem.history["loss"],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(r_str.history["val_loss"],
                               r_mem.history["val_loss"], rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree.leaves(r_str.state.params),
                    jax.tree.leaves(r_mem.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_fit_streaming_with_mesh(rng):
    """Streaming + DP mesh: batches are placed pre-sharded over 'data' and
    results still match the plain single-device run."""
    from apnea_uq_tpu.parallel import make_mesh

    model = _tiny()
    x, y = _separable_data(rng, n=192)
    cfg = TrainConfig(batch_size=64, num_epochs=2, validation_split=0.25, seed=4)
    mesh = make_mesh(num_members=1)  # (1, 8)
    r_mesh = fit(model, create_train_state(model, jax.random.key(7)), x, y,
                 cfg, mesh=mesh, streaming=True)
    r_one = fit(model, create_train_state(model, jax.random.key(7)), x, y, cfg)
    np.testing.assert_allclose(r_mesh.history["loss"], r_one.history["loss"],
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(r_mesh.history["val_loss"],
                               r_one.history["val_loss"], rtol=2e-4, atol=2e-5)
