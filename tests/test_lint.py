"""`apnea-uq lint` — engine, rules, suppressions, CLI, and the tier-1
zero-findings gate (ISSUE 4).

Layout: per-rule positive/negative fixture pairs under
``tests/lint_fixtures/`` (positives pin the exact finding count so a
rule that silently stops firing is caught, negatives pin the
idiomatic-code false-positive rate at zero), the suppression
round-trip (justified = suppressed, missing justification = the finding
stands), a ``--json`` golden, the telemetry-schema rule against a
synthetic repo, the jax-poisoned import test, and — the gate — zero
unsuppressed findings over ``apnea_uq_tpu/`` + ``bench.py`` via the real
CLI entry point, in-process, which is how tier-1 runs the linter.
"""

import json
import os
import sys

import pytest

from apnea_uq_tpu.lint.engine import RULES, run_lint
from apnea_uq_tpu.lint.report import result_data

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
PKG = os.path.join(REPO, "apnea_uq_tpu")
BENCH = os.path.join(REPO, "bench.py")


def _lint_fixture(name, rule):
    return run_lint([os.path.join(FIXTURES, name)], rules=[rule],
                    repo_root=FIXTURES)


# ------------------------------------------------------------ rule pairs --

# (rule, positive fixture, exact finding count, negative fixture)
RULE_FIXTURES = [
    ("prng-key-reuse", "prng_pos.py", 5, "prng_neg.py"),
    ("donated-buffer-read", "donation_pos.py", 3, "donation_neg.py"),
    ("host-sync-in-timed-region", "host_sync_pos.py", 7, "host_sync_neg.py"),
    ("jit-retrace-hazard", "retrace_pos.py", 4, "retrace_neg.py"),
    ("bare-print", "bare_print_pos.py", 1, "bare_print_neg.py"),
]


@pytest.mark.parametrize("rule,pos,count,neg", RULE_FIXTURES,
                         ids=[r[0] for r in RULE_FIXTURES])
def test_rule_fixture_pair(rule, pos, count, neg):
    found = _lint_fixture(pos, rule).unsuppressed
    assert len(found) == count, (
        f"{rule} found {len(found)} on {pos}, expected {count}: "
        f"{[f.render() for f in found]}"
    )
    assert all(f.rule == rule for f in found)
    clean = _lint_fixture(neg, rule).unsuppressed
    assert not clean, (
        f"{rule} false-positives on idiomatic code {neg}: "
        f"{[f.render() for f in clean]}"
    )


def test_registry_ships_exactly_the_documented_rules():
    run_lint([os.path.join(FIXTURES, "bare_print_neg.py")])  # force import
    assert set(RULES) == {
        "prng-key-reuse", "donated-buffer-read",
        "host-sync-in-timed-region", "jit-retrace-hazard",
        "telemetry-event-schema", "bare-print",
    }
    for rule in RULES.values():
        assert rule.severity in ("error", "warning")
        assert rule.summary


# -------------------------------------------------- telemetry schema rule --

_SCHEMA_DOC = """# Observability

## Event schema

Event kinds and their payloads:

- **`alpha`** — first kind: `x`, `y`.
- **`beta`** / **`gamma`** — a shared bullet declaring `z`.
- **`never_emitted`** — a kind no code emits: `q`.
"""

_SCHEMA_CODE = """\
def emit(log):
    log.event("alpha", x=1, y=2)
    log.event("alpha", x=1, oops=3)
    log.event("delta", x=1)
    fields = {"z": 1}
    fields["w"] = 2
    log.event("beta", **fields)
"""


def _schema_repo(tmp_path):
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(_SCHEMA_DOC)
    (tmp_path / "telemetry").mkdir()
    (tmp_path / "telemetry" / "runlog.py").write_text(_SCHEMA_CODE)
    (tmp_path / "bench.py").write_text('def b(log):\n    log.event("gamma", z=1)\n')
    return tmp_path


def test_schema_rule_positive_and_fields(tmp_path):
    repo = _schema_repo(tmp_path)
    result = run_lint(
        [str(repo / "telemetry" / "runlog.py"), str(repo / "bench.py")],
        rules=["telemetry-event-schema"], repo_root=str(repo),
    )
    by_line = {(f.path.replace(os.sep, "/"), f.line): f.message
               for f in result.unsuppressed}
    # Undocumented field on a documented kind.
    assert "['oops']" in by_line[("telemetry/runlog.py", 3)]
    # Undocumented kind.
    assert "`delta`" in by_line[("telemetry/runlog.py", 4)]
    # **splat resolved through dict display + constant subscript store.
    assert "['w']" in by_line[("telemetry/runlog.py", 7)]
    # Phantom direction (runlog.py + bench.py both in scope): the
    # documented-but-never-emitted kind is flagged AT the doc.
    phantom = [f for f in result.unsuppressed
               if f.path.replace(os.sep, "/") == "docs/OBSERVABILITY.md"]
    assert len(phantom) == 1 and "`never_emitted`" in phantom[0].message
    assert len(result.unsuppressed) == 4


def test_schema_rule_negative_and_partial_scope(tmp_path):
    repo = _schema_repo(tmp_path)
    clean = tmp_path / "clean.py"
    clean.write_text('def e(log):\n    log.event("alpha", x=1)\n')
    result = run_lint([str(clean)], rules=["telemetry-event-schema"],
                      repo_root=str(repo))
    # Clean emission: no findings — and in particular NO phantom claims,
    # because a single-file scope does not contain the emission universe.
    assert not result.unsuppressed


def test_schema_rule_requires_the_doc_only_in_full_scope(tmp_path):
    """A repo-checkout scope (runlog.py + bench.py present) with the doc
    deleted is an error; a lone emitting file (e.g. a pip-installed
    package linting itself with no repo around) simply skips the rule —
    the 'runs anywhere' CLI must not go red on clean installs."""
    (tmp_path / "telemetry").mkdir()
    (tmp_path / "telemetry" / "runlog.py").write_text(_SCHEMA_CODE)
    (tmp_path / "bench.py").write_text('def b(log):\n    log.event("g", z=1)\n')
    full = run_lint(
        [str(tmp_path / "telemetry" / "runlog.py"), str(tmp_path / "bench.py")],
        rules=["telemetry-event-schema"], repo_root=str(tmp_path))
    assert len(full.unsuppressed) == 1
    assert "OBSERVABILITY.md" in full.unsuppressed[0].message

    lone = tmp_path / "emitter.py"
    lone.write_text('def e(log):\n    log.event("alpha", x=1)\n')
    result = run_lint([str(lone)], rules=["telemetry-event-schema"],
                      repo_root=str(tmp_path / "nowhere"))
    assert not result.unsuppressed


# ------------------------------------------------------------ suppression --

def test_suppression_round_trip_justified():
    result = _lint_fixture("suppression_ok.py", "bare-print")
    assert not result.unsuppressed
    suppressed = [f for f in result.findings if f.suppressed]
    assert len(suppressed) == 2  # trailing AND standalone placements
    for f in suppressed:
        assert f.justification and "fixture" in f.justification


def test_suppression_without_justification_is_a_finding():
    result = _lint_fixture("suppression_missing.py", "bare-print")
    assert len(result.unsuppressed) == 1
    assert "lacks a justification" in result.unsuppressed[0].message


def test_json_golden():
    result = _lint_fixture("suppression_missing.py", "bare-print")
    assert result_data(result) == {
        "findings": [
            {
                "rule": "bare-print",
                "severity": "error",
                "path": "suppression_missing.py",
                "line": 6,
                "message": (
                    "bare print() call — route output through "
                    "apnea_uq_tpu.telemetry.log (or suppress with a "
                    "justification if this IS the central sink)  "
                    "[suppression comment lacks a justification: use "
                    "`# apnea-lint: disable=bare-print -- <why>`]"
                ),
                "suppressed": False,
                "justification": None,
            },
        ],
        "summary": {
            "files_scanned": 1,
            "rules_run": ["bare-print"],
            "findings": 1,
            "suppressed": 0,
            "unsuppressed": 1,
            # Per-rule counts cover every rule that RAN (zero counts
            # included) so CI diffs of --json output are deterministic.
            "by_rule": {
                "bare-print": {
                    "findings": 1, "suppressed": 0, "unsuppressed": 1,
                },
            },
        },
    }


def test_json_by_rule_covers_all_rules_run_with_zero_counts():
    result = _lint_fixture("bare_print_neg.py", "bare-print")
    data = result_data(result)
    assert data["summary"]["by_rule"] == {
        "bare-print": {"findings": 0, "suppressed": 0, "unsuppressed": 0},
    }


def test_gha_reporter_format_and_suppression_filter():
    """--format gha: one ::error/::warning workflow-command line per
    UNSUPPRESSED finding (suppressed ones are resolved exemptions),
    empty output on a clean tree."""
    from apnea_uq_tpu.lint.report import render_gha

    result = _lint_fixture("bare_print_pos.py", "bare-print")
    lines = render_gha(result).splitlines()
    assert len(lines) == 1
    assert lines[0].startswith("::error file=bare_print_pos.py,line=")
    assert ",title=bare-print::" in lines[0]
    # Messages with newlines/percent must be %-escaped, commas in
    # property values too (GitHub's workflow-command grammar).
    import dataclasses as dc

    from apnea_uq_tpu.lint.engine import Finding, LintResult

    weird = LintResult(
        findings=[Finding(rule="bare-print", severity="error",
                          path="a,b.py", line=3,
                          message="50% broken\nsecond line")],
        files_scanned=1, rules_run=("bare-print",),
    )
    out = render_gha(weird)
    assert "file=a%2Cb.py" in out
    assert "50%25 broken%0Asecond line" in out
    # Suppressed findings produce no annotation at all.
    sup = LintResult(
        findings=[dc.replace(weird.findings[0], suppressed=True,
                             justification="fixture")],
        files_scanned=1, rules_run=("bare-print",),
    )
    assert render_gha(sup) == ""


def test_cli_format_gha(capsys):
    from apnea_uq_tpu.cli.main import main

    rc = main(["lint", os.path.join(FIXTURES, "bare_print_pos.py"),
               "--rule", "bare-print", "--format", "gha"])
    assert rc == 1
    out = capsys.readouterr().out
    assert out.startswith("::error file=")
    # A clean run emits NO annotation lines (GitHub renders every
    # stdout line that looks like a command; silence = green).
    rc = main(["lint", os.path.join(FIXTURES, "bare_print_neg.py"),
               "--rule", "bare-print", "--format", "gha"])
    assert rc == 0
    assert "::" not in capsys.readouterr().out


# ------------------------------------------------------- the tier-1 gate --

def test_package_gate_zero_unsuppressed_findings():
    """`apnea-uq lint apnea_uq_tpu bench.py` must be clean — this is the
    tier-1 wiring: any new hazard (or undocumented telemetry field)
    anywhere in the package fails the suite, not just a bench run."""
    result = run_lint([PKG, BENCH], repo_root=REPO)
    assert not result.unsuppressed, "\n".join(
        f.render() for f in result.unsuppressed
    )
    # Pin the suppression audit trail: every exemption in the tree is
    # intentional and justified; a NEW suppression must be reviewed here.
    suppressed = sorted(
        (f.path.replace(os.sep, "/"), f.rule)
        for f in result.findings if f.suppressed
    )
    assert suppressed == [
        ("apnea_uq_tpu/compilecache/probe.py", "bare-print"),
        # x2: the pre-epoch permutation landing, and the streamed val
        # loop's O(batch) host gather off a possibly store-backed slice.
        ("apnea_uq_tpu/parallel/ensemble.py", "host-sync-in-timed-region"),
        ("apnea_uq_tpu/parallel/ensemble.py", "host-sync-in-timed-region"),
        ("apnea_uq_tpu/telemetry/logging_shim.py", "bare-print"),
        ("apnea_uq_tpu/training/trainer.py", "host-sync-in-timed-region"),
        ("apnea_uq_tpu/training/trainer.py", "host-sync-in-timed-region"),
        ("bench.py", "bare-print"),
        ("bench.py", "bare-print"),
    ]
    # The rglob covers new files implicitly — which also means a MOVED
    # module silently leaves the lint's scope (the hazard the old
    # test_no_bare_print scope pin guarded).  Pin the modules whose
    # coverage matters most: the subprocess-heavy telemetry layer (where
    # status prints creep back in) and the donation/PRNG hot paths.
    scanned = {p.replace(os.sep, "/") for p in result.scanned_paths}
    for rel in ("apnea_uq_tpu/telemetry/memory.py",
                "apnea_uq_tpu/telemetry/profiler.py",
                "apnea_uq_tpu/telemetry/compare.py",
                "apnea_uq_tpu/telemetry/watch.py",
                # The perf-trajectory ledger (ISSUE 11): jax-free read
                # side, but its doc render must stay in the bare-print /
                # schema scan scope like the rest of the telemetry layer.
                "apnea_uq_tpu/telemetry/trend.py",
                # The model-quality stream (ISSUE 13): the quality
                # event emitter/gate and the drift fingerprint engine —
                # both emit documented telemetry kinds, so they must
                # stay inside the schema rule's scan scope.
                "apnea_uq_tpu/telemetry/quality.py",
                "apnea_uq_tpu/analysis/fingerprint.py",
                "apnea_uq_tpu/analysis/calibration.py",
                "apnea_uq_tpu/telemetry/logging_shim.py",
                "apnea_uq_tpu/parallel/ensemble.py",
                "apnea_uq_tpu/uq/predict.py",
                "apnea_uq_tpu/compilecache/store.py",
                "apnea_uq_tpu/compilecache/zoo.py",
                "apnea_uq_tpu/compilecache/probe.py",
                "apnea_uq_tpu/audit/capture.py",
                "apnea_uq_tpu/audit/programs.py",
                "apnea_uq_tpu/audit/rules.py",
                "apnea_uq_tpu/audit/cli.py",
                # The topology gate (ISSUE 14): the spec-driven mesh
                # seam and the fourth rule family — the topo CLI emits
                # the documented topo_program telemetry kind, so it
                # must stay inside the schema rule's scan scope.
                "apnea_uq_tpu/parallel/topology.py",
                "apnea_uq_tpu/parallel/mesh.py",
                "apnea_uq_tpu/topo/capture.py",
                "apnea_uq_tpu/topo/rules.py",
                "apnea_uq_tpu/topo/manifest.py",
                "apnea_uq_tpu/topo/cli.py",
                "apnea_uq_tpu/utils/multihost.py",
                # The online serving tier (ISSUE 15): the engine and the
                # SLO tracker emit the documented serve_batch /
                # serve_request / serve_slo kinds, and the stream scorer
                # is a long-lived writer — all five modules must stay
                # inside the bare-print / schema scan scope.
                "apnea_uq_tpu/serving/coalescer.py",
                "apnea_uq_tpu/serving/engine.py",
                "apnea_uq_tpu/serving/slo.py",
                "apnea_uq_tpu/serving/stream.py",
                "apnea_uq_tpu/serving/loadgen.py",
                # The online drift monitor (ISSUE 17): emits the
                # documented serve_drift kind with literal kwargs — the
                # schema rule must keep scanning it.
                "apnea_uq_tpu/serving/drift.py",
                # The Pallas DE kernel + autotune harness (ISSUE 16):
                # the kernel bodies and the winner-persisting sweep —
                # autotune emits the documented autotune_cell /
                # autotune_result kinds, so both must stay inside the
                # bare-print / schema scan scope.
                "apnea_uq_tpu/ops/pallas_de.py",
                "apnea_uq_tpu/ops/autotune.py",
                # The out-of-core data plane (ISSUE 9): store shard I/O
                # and the telemetry-emitting ingest/registry paths.
                "apnea_uq_tpu/data/store.py",
                "apnea_uq_tpu/data/ingest.py",
                "apnea_uq_tpu/data/registry.py",
                # The flow gate (ISSUE 10): the dataflow analyzer and the
                # shared crash-consistent writers it enforces.
                "apnea_uq_tpu/flow/extract.py",
                "apnea_uq_tpu/flow/rules.py",
                "apnea_uq_tpu/flow/manifest.py",
                "apnea_uq_tpu/flow/pipedoc.py",
                "apnea_uq_tpu/flow/cli.py",
                "apnea_uq_tpu/utils/io.py",
                # The conc gate (ISSUE 19): the fifth rule family, its
                # perturbation harness, and the blessed env seam it
                # pins — all jax-free, all inside the lint scope so a
                # stray print/undocumented event in the auditor itself
                # fails the suite.
                "apnea_uq_tpu/conc/rules.py",
                "apnea_uq_tpu/conc/perturb.py",
                "apnea_uq_tpu/conc/cli.py",
                "apnea_uq_tpu/utils/env.py",
                # Fleet tracing (ISSUE 20): the span mint/sample/merge
                # module — its serve_trace/trace_report emissions must
                # stay under the event-schema rule's eye.
                "apnea_uq_tpu/telemetry/spans.py",
                "bench.py"):
        assert rel in scanned, f"{rel} moved out of the lint gate's scope"


def test_cli_entry_point_gate_and_exit_codes(capsys):
    from apnea_uq_tpu.cli.main import main

    assert main(["lint", PKG, BENCH]) == 0
    capsys.readouterr()
    assert main(["lint", os.path.join(FIXTURES, "bare_print_pos.py")]) == 1
    out = capsys.readouterr().out
    assert "[bare-print]" in out and "1 finding(s)" in out


def test_cli_json_and_rule_filter(capsys):
    from apnea_uq_tpu.cli.main import main

    rc = main(["lint", os.path.join(FIXTURES, "prng_pos.py"),
               "--rule", "prng-key-reuse", "--json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["summary"]["rules_run"] == ["prng-key-reuse"]
    assert doc["summary"]["unsuppressed"] == 5


def test_cli_usage_errors_exit_2(capsys):
    """Exit 2 (usage) stays distinct from exit 1 (findings) so CI gating
    on the exit code can't mistake a typo for a clean or dirty tree."""
    from apnea_uq_tpu.cli.main import main

    with pytest.raises(SystemExit) as exc:
        main(["lint", os.path.join(FIXTURES, "prng_neg.py"),
              "--rule", "no-such-rule"])
    assert exc.value.code == 2
    assert "unknown rule" in capsys.readouterr().out
    with pytest.raises(SystemExit) as exc:
        main(["lint", os.path.join(FIXTURES, "does_not_exist.py")])
    assert exc.value.code == 2


def test_lint_runs_with_jax_and_flax_poisoned(capsys):
    """The acceptance bar: the linter imports no jax/flax at lint time.
    Poison both in sys.modules (None = ImportError on any import) after
    evicting every cached lint module, then run the FULL package gate
    through the CLI entry point."""
    evicted = {}
    for name in list(sys.modules):
        if name == "apnea_uq_tpu.lint" or name.startswith("apnea_uq_tpu.lint."):
            evicted[name] = sys.modules.pop(name)
    saved = {}
    for mod in ("jax", "flax"):
        for name in list(sys.modules):
            if name == mod or name.startswith(mod + "."):
                saved[name] = sys.modules.pop(name)
        sys.modules[mod] = None
    try:
        from apnea_uq_tpu.cli.main import main

        assert main(["lint", PKG, BENCH]) == 0
    finally:
        for mod in ("jax", "flax"):
            sys.modules.pop(mod, None)
        sys.modules.update(saved)
        sys.modules.update(evicted)
    out = capsys.readouterr().out
    assert "0 finding(s)" in out
