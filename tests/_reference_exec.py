"""Shared machinery for exec-the-reference parity tests.

Exec'ing the mounted reference grants it in-process code execution, so
each file is pinned to the sha256 of the snapshot that was reviewed
(2025-05-23 checkout); a drifted file is skipped, never executed.
Re-review and re-pin when the mounted snapshot legitimately updates.

Used by ``test_reference_exec_parity.py`` (metric cores, analysis
scripts, cohort scripts, plot interop) and
``test_reference_driver_shells.py`` (the six trainer/driver shells,
stub-exec'd with a fake Keras).
"""

from __future__ import annotations

import importlib.util
import os
import sys
import types

import pytest

REF_ROOT = "/root/reference"
REF_PATH = f"{REF_ROOT}/uncertainty_quantification/uq_techniques.py"
REF_EVAL_PATH = f"{REF_ROOT}/evaluation/evaluate_classification.py"

_REVIEWED_SHA256 = {
    REF_PATH:
        "1b7b8f98b9cfc3b765b2f0d9c46a6db1d2ecaf4b5ccd055a7eb6c79e8978f723",
    REF_EVAL_PATH:
        "9b0f21f04ab54437d36414feea3754052902e28379035b193bc0038d5663db14",
    f"{REF_ROOT}/data_prepocessing/preprocess_shhs_raw.py":
        "e7dc5a2cde88c1c05fa6597cb07accb4b9cfb52b966494a0e072d54de0163ee8",
    f"{REF_ROOT}/data_prepocessing/prepare_numpy_datasets.py":
        "8e985cd220ab08d822f42c601883a95d8363575d174b99f173489390412f0282",
    f"{REF_ROOT}/uncertainty_quantification/aggregate_patient_uq_metrics.py":
        "ba2c79c55fabde48557e53f28d916b2aa2927525af200b13a1862edd84cf7f56",
    f"{REF_ROOT}/uncertainty_quantification/analyze_window_level_uncertainty.py":
        "cf9941ab587c62aa6328113fa00e5d5f5d4be5135d5f31e584395daca728da88",
    f"{REF_ROOT}/uq_analysis/patient_accuracy_entropy_correlation.py":
        "f769a431bb75b4fc35c359e4876dd2778c0217a7cdbd7ab8f5033eb537da42f7",
    f"{REF_ROOT}/uq_analysis/window_uncertainty_vs_correctness_mannwhitney.py":
        "2e0f21fb9b409549be4700edaf0070aeea8ea12a287b62137adbb38df3692022",
    f"{REF_ROOT}/datasets/SHHS_cohort_analysis.py":
        "e979f7000ee246560cce3b7d46736198900e97530d4fb5ab3b5bc648d70d328d",
    f"{REF_ROOT}/datasets/SHHS_signal_quality.py":
        "7800cd52aece6569d544c0747b2f4822e9e45054b557d90e95a5176e8fc9399a",
    f"{REF_ROOT}/uq_analysis/final_plot_uq_overview_figures.py":
        "92c7d9a97f19157ae3ecc485ba5ef548eb8c75b1d31bef2f4cd2f25600eac2e8",
    f"{REF_ROOT}/uq_analysis/hyperparameter_plot_mcd_or_de_pass_convergence.py":
        "413018ef1c861bcfa96d7d0427f6d0884abb0b750e3de27e235f224e796a5116",
    # The six trainer/driver shells (C4, C5, C13-C16).  The shells were
    # surveyed line-by-line (SURVEY §2.1/§3) but the reference checkout
    # has not been mounted in any build environment since their exec
    # tests were authored (PR 2 re-checked: /root/reference absent, no
    # network), so their checksums are still UNPINNED: the exec helper
    # refuses to run them until a reviewer re-reads the mounted files
    # and fills these in — the tests skip with an explicit "no reviewed
    # checksum pinned" reason, never exec'ing unreviewed content.
    # Closing the loop is one command once a mount exists:
    #     python tests/_reference_exec.py --print-pins
    # re-read each listed file, then paste the printed entries here.
    f"{REF_ROOT}/models/cnn_baseline_train.py": None,
    f"{REF_ROOT}/models/train_deep_ensemble_cnns.py": None,
    f"{REF_ROOT}/uncertainty_quantification/analyze_mcd_patient_level.py": None,
    f"{REF_ROOT}/uncertainty_quantification/analyze_de_patient_level.py": None,
    f"{REF_ROOT}/uncertainty_quantification/evaluate_mcd_global.py": None,
    f"{REF_ROOT}/uncertainty_quantification/evaluate_de_global.py": None,
}


def reference_mounted() -> bool:
    return os.path.exists(REF_PATH)


def stub_tensorflow():
    """A minimal module tree satisfying the reference's tf imports
    (`import tensorflow as tf`, `from tensorflow.keras.models import
    Model`) — for modules whose functions under test never touch tf.
    The driver shells, which DO call Keras, use the richer recording
    fake in test_reference_driver_shells.py instead."""
    tf = types.ModuleType("tensorflow")
    keras = types.ModuleType("tensorflow.keras")
    keras_models = types.ModuleType("tensorflow.keras.models")

    class Model:  # annotation placeholder only
        pass

    keras.Model = Model
    keras.models = keras_models
    keras_models.Model = Model
    tf.keras = keras
    return {
        "tensorflow": tf,
        "tensorflow.keras": keras,
        "tensorflow.keras.models": keras_models,
    }


def checksum_ok(path: str) -> None:
    """Skip (without executing) unless ``path`` hashes to its reviewed
    checksum — untrusted drift in the mount cannot run in-process."""
    import hashlib

    if not os.path.exists(path):
        pytest.skip(f"reference module not mounted: {path}")
    pinned = _REVIEWED_SHA256.get(path)
    if pinned is None:
        pytest.skip(f"no reviewed checksum pinned for {path}; refusing exec")
    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    if digest != pinned:
        pytest.skip(
            f"mounted reference {path} does not match its reviewed "
            f"checksum ({digest[:12]}... != {pinned[:12]}...); refusing "
            "to exec unreviewed content — re-review and re-pin"
        )


def outstanding_pins() -> list:
    """Reference files whose reviewed checksum is still unpinned (their
    exec tests skip until a reviewer closes the loop)."""
    return sorted(p for p, pin in _REVIEWED_SHA256.items() if pin is None)


def compute_pins(paths) -> dict:
    """sha256 of each path as currently mounted (None when absent).
    Maintainer input for re-pinning — REVIEW the file contents before
    pasting a printed hash into ``_REVIEWED_SHA256``; the hash pins what
    you reviewed, it is not the review."""
    import hashlib

    pins = {}
    for path in paths:
        if os.path.exists(path):
            with open(path, "rb") as f:
                pins[path] = hashlib.sha256(f.read()).hexdigest()
        else:
            pins[path] = None
    return pins


def format_pins(pins: dict) -> str:
    """Ready-to-paste ``_REVIEWED_SHA256`` entries (f-string form for
    paths under REF_ROOT, matching the table above)."""
    lines = []
    for path, digest in sorted(pins.items()):
        key = (f'f"{{REF_ROOT}}{path[len(REF_ROOT):]}"'
               if path.startswith(REF_ROOT + "/") else repr(path))
        value = "None,  # not mounted" if digest is None else f'"{digest}",'
        lines.append(f"    {key}:\n        {value}")
    return "\n".join(lines)


def exec_reference_module(name: str, path: str, stubs: dict,
                          run_name: str | None = None):
    """Exec a reference source file as a module with the given stub
    modules temporarily installed in sys.modules (restored afterwards,
    also if the import raises) — shared by every exec-parity fixture.
    The file must pass :func:`checksum_ok` first.  ``run_name`` overrides
    the module's ``__name__`` (pass ``"__main__"`` to drive an
    argparse-gated script's main block)."""
    checksum_ok(path)
    saved = {n: sys.modules.get(n) for n in stubs}
    sys.modules.update(stubs)
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        module = importlib.util.module_from_spec(spec)
        if run_name is not None:
            module.__name__ = run_name
        spec.loader.exec_module(module)
    finally:
        for n, mod in saved.items():
            if mod is None:
                sys.modules.pop(n, None)
            else:
                sys.modules[n] = mod
    return module


if __name__ == "__main__":
    # Maintainer mode: `python tests/_reference_exec.py --print-pins`
    # hashes every still-unpinned reference file on the current mount and
    # prints paste-ready _REVIEWED_SHA256 entries.  Review each file
    # BEFORE pasting — the pin certifies the content you read.
    import sys as _sys

    if "--print-pins" in _sys.argv[1:]:
        todo = outstanding_pins()
        if not todo:
            print("# every reference file already has a pinned checksum")
        else:
            pins = compute_pins(todo)
            missing = [p for p, d in pins.items() if d is None]
            print("# sha256 of the CURRENT mount — re-read each file, then")
            print("# replace the matching None entries in _REVIEWED_SHA256:")
            print(format_pins(pins))
            if missing:
                print(f"# {len(missing)} file(s) not mounted; mount the "
                      "reviewed reference checkout and re-run")
    else:
        print(__doc__)
        print("usage: python tests/_reference_exec.py --print-pins")
