"""Multi-host topology readiness (ISSUE 14): the TopologySpec-driven
mesh refactor (bit-parity pinned on single-host layouts), the simulated
topology sweep, the source + program topo rules against fixtures and
injected violations, the `apnea-uq topo` CLI contract, the committed
manifest's coverage, and the `apnea-uq check` meta-gate.

The acceptance runs: every injected violation class — unguarded write,
single-host enumeration, cross-host collective payload over budget,
per-device HBM overflow at 2x8 — exits 1 through the real CLI anchored
at a pointable source line, and the clean tree exits 0 with every
suppression justified.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from apnea_uq_tpu.audit.manifest import zoo_label_lines  # noqa: E402
from apnea_uq_tpu.compilecache.zoo import GROUP_LABELS  # noqa: E402
from apnea_uq_tpu.config import (  # noqa: E402
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    save_config,
)
from apnea_uq_tpu.lint.engine import (  # noqa: E402
    LintContext,
    apply_suppressions,
    load_files,
)
from apnea_uq_tpu.parallel import topology as topo_mod  # noqa: E402
from apnea_uq_tpu.parallel.mesh import (  # noqa: E402
    AXIS_DATA,
    AXIS_ENSEMBLE,
    make_mesh,
    make_mesh_from_config,
)
from apnea_uq_tpu.topo.capture import (  # noqa: E402
    MESH_FAMILY_LABELS,
    TopoProgramFacts,
    distill_facts,
)
from apnea_uq_tpu.topo.manifest import (  # noqa: E402
    DEFAULT_MANIFEST_PATH,
    load_manifest,
    manifest_row,
    merge_rows,
    render_topology_doc,
)
from apnea_uq_tpu.topo.rules import (  # noqa: E402
    RULE_SUBJECTS,
    TOPO_RULES,
    TopoContext,
    run_topo_rules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures", "topo")
ALL_ZOO_LABELS = {lb for labels in GROUP_LABELS.values() for lb in labels}
TOPOLOGIES = ("1x8", "2x4", "4x2")


@pytest.fixture(scope="module")
def tiny_config_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("topo_cfg") / "config.json")
    save_config(ExperimentConfig(model=ModelConfig(
        features=(8, 12, 8), kernel_sizes=(5, 3, 3),
        dropout_rates=(0.3, 0.4, 0.5))), path)
    return path


# --------------------------------------------- topology-driven meshes --

class TestTopologySpec:
    def test_single_host_mesh_is_bit_parity_with_flat_reshape(self):
        """The acceptance pin: on single-host layouts the new
        TopologySpec construction is identical to the historical
        np.asarray(jax.devices()).reshape(e, d)."""
        devs = jax.devices()
        d = len(devs)
        for n in (1, 2, 3, 4, 5, 8, 10):
            e = 1
            for cand in range(1, d + 1):
                if d % cand == 0 and cand <= max(n, 1):
                    e = cand
            legacy = np.asarray(devs).reshape(e, d // e)
            mesh = make_mesh(num_members=n)
            assert mesh.axis_names == (AXIS_ENSEMBLE, AXIS_DATA)
            assert (np.asarray(mesh.devices) == legacy).all(), n
        # Explicit pins reshape identically too.
        mesh = make_mesh(ensemble_axis=2)
        assert (np.asarray(mesh.devices)
                == np.asarray(devs).reshape(2, d // 2)).all()

    def test_mesh_from_config_pins_and_errors(self):
        assert make_mesh_from_config(
            MeshConfig(data_axis=4), num_members=8).devices.shape == (2, 4)
        assert make_mesh_from_config(
            MeshConfig(ensemble_axis=4, data_axis=2),
            num_members=1).devices.shape == (4, 2)
        with pytest.raises(ValueError, match="does not divide"):
            make_mesh(ensemble_axis=3)
        with pytest.raises(ValueError, match="does not match"):
            make_mesh_from_config(MeshConfig(ensemble_axis=4, data_axis=4))

    def test_detect_topology_single_host(self):
        spec, devs = topo_mod.detect_topology()
        assert spec.hosts == 1
        assert spec.devices_per_host == len(jax.devices())
        assert devs == list(jax.devices())

    def test_solver_prefers_within_host_data_axis(self):
        spec = topo_mod.TopologySpec(2, 4)
        # members=4: both (2, 4) and (4, 2) satisfy the bound; only
        # data<=4-within-host layouts are preferred, largest e wins.
        assert topo_mod.solve_layout(spec, 4) == (4, 2)
        # members=8 on 2x4: e=8 gives d=1 (within host) — preferred.
        assert topo_mod.solve_layout(spec, 8) == (8, 1)
        # Pure data-parallel falls back to the cross-host layout
        # rather than refusing it (the analysis charges the traffic).
        assert topo_mod.solve_layout(spec, 1) == (1, 8)

    def test_axis_spans_hosts_layout_math(self):
        spec = topo_mod.TopologySpec(2, 4)
        assert not topo_mod.axis_spans_hosts(spec, 4, 2, AXIS_DATA)
        assert topo_mod.axis_spans_hosts(spec, 4, 2, AXIS_ENSEMBLE)
        assert topo_mod.axis_spans_hosts(spec, 1, 8, AXIS_DATA)
        single = topo_mod.TopologySpec(1, 8)
        assert not topo_mod.axis_spans_hosts(single, 1, 8, AXIS_DATA)
        assert not topo_mod.axis_spans_hosts(single, 4, 2, AXIS_ENSEMBLE)

    def test_simulated_topologies_of_the_canonical_rig(self):
        assert [s.name for s in topo_mod.simulated_topologies(8)] == \
            list(TOPOLOGIES)

    def test_simulated_mesh_uses_host_major_runs(self):
        spec = topo_mod.TopologySpec(2, 4)
        mesh = make_mesh(num_members=4, topology=spec)
        grid = np.asarray(mesh.devices)
        assert grid.shape == (4, 2)
        flat = list(jax.devices())
        # Data rows are contiguous host-major runs: row i is
        # devices[2i:2i+2], so every data group sits inside one
        # simulated host of four.
        for i in range(4):
            assert list(grid[i]) == flat[2 * i:2 * i + 2]

    def test_spec_validation(self):
        with pytest.raises(ValueError, match=">=1 host"):
            topo_mod.TopologySpec(0, 8)
        with pytest.raises(ValueError, match="needs 16 devices"):
            topo_mod.host_major_devices(topo_mod.TopologySpec(2, 8),
                                        jax.devices())


# ------------------------------------------------- source rule fixtures --

SOURCE_RULE_FIXTURES = [
    ("single-host-device-enumeration", "device_enum_pos.py", 3,
     "device_enum_neg.py"),
    ("unguarded-primary-io", "primary_io_pos.py", 3,
     "primary_io_neg.py"),
    ("lockstep-collective-discipline", "lockstep_pos.py", 3,
     "lockstep_neg.py"),
]


def _source_findings(name, rule):
    files = load_files([os.path.join(FIXTURES, name)], FIXTURES)
    ctx = TopoContext(lint=LintContext(files=files, repo_root=FIXTURES))
    return [apply_suppressions(f, files[0])
            for f in run_topo_rules(ctx, rules=[rule])]


@pytest.mark.parametrize("rule,pos,count,neg", SOURCE_RULE_FIXTURES,
                         ids=[r[0] for r in SOURCE_RULE_FIXTURES])
def test_source_rule_fixture_pair(rule, pos, count, neg):
    found = [f for f in _source_findings(pos, rule) if not f.suppressed]
    assert len(found) == count, [f.render() for f in found]
    assert all(f.rule == rule for f in found)
    clean = [f for f in _source_findings(neg, rule) if not f.suppressed]
    assert not clean, [f.render() for f in clean]


def test_registry_ships_exactly_the_documented_rules():
    assert set(TOPO_RULES) == {
        "single-host-device-enumeration", "unguarded-primary-io",
        "lockstep-collective-discipline", "topo-collective-manifest",
        "topo-cross-host-payload", "topo-hbm-budget",
    }
    assert {n for n, s in RULE_SUBJECTS.items() if s == "source"} == {
        "single-host-device-enumeration", "unguarded-primary-io",
        "lockstep-collective-discipline",
    }
    for rule in TOPO_RULES.values():
        assert rule.severity in ("error", "warning") and rule.summary
    with pytest.raises(ValueError, match="unknown topo rule"):
        run_topo_rules(TopoContext(), rules=["no-such"])


# ---------------------------------------------- program rule injections --

def _facts(label="ensemble_epoch", topology="2x4", e=4, d=2,
           collectives=None, payloads=None, cross=None, cross_bytes=0,
           blowup=1, per_device=1 << 20,
           hbm=topo_mod.DEFAULT_HBM_BYTES,
           dcn=topo_mod.DEFAULT_CROSS_HOST_BUDGET_BYTES):
    return TopoProgramFacts(
        label=label, topology=topology, mesh_ensemble=e, mesh_data=d,
        collectives=dict(collectives or {}),
        collective_payloads=dict(payloads or {}),
        cross_host=list(cross or []), cross_host_bytes=cross_bytes,
        replication_blowup=blowup, per_device_bytes=per_device,
        hbm_budget_bytes=hbm, cross_host_budget_bytes=dcn,
    )


def _program_context(facts_list, manifest=None):
    zoo_abs, label_lines = zoo_label_lines()
    rel = os.path.relpath(zoo_abs, REPO).replace(os.sep, "/")
    return TopoContext(
        programs={(f.topology, f.label): f for f in facts_list},
        manifest=manifest, zoo_path=rel, label_lines=label_lines,
    )


def test_clean_facts_pass_all_program_rules():
    f = _facts()
    manifest = {"ensemble_epoch": {"2x4": manifest_row(f)}}
    assert run_topo_rules(_program_context([f], manifest)) == []


def test_missing_and_drifted_manifest_rows_flagged():
    f = _facts()
    missing = run_topo_rules(
        _program_context([f], manifest={}),
        rules=["topo-collective-manifest"])
    assert len(missing) == 1 and "no manifest row" in missing[0].message
    drift_row = manifest_row(_facts(e=2, d=4))
    drift = run_topo_rules(
        _program_context([f], {"ensemble_epoch": {"2x4": drift_row}}),
        rules=["topo-collective-manifest"])
    assert len(drift) == 1 and "drift" in drift[0].message
    # The finding anchors at the zoo-registration line.
    _zoo, lines = zoo_label_lines()
    assert drift[0].line == lines["ensemble_epoch"] > 1
    assert drift[0].path.endswith("compilecache/zoo.py")


def test_gather_over_hosts_is_unconditional_violation():
    f = _facts(collectives={"all_gather[ensemble]": 1},
               payloads={"all_gather[ensemble]": 4096},
               cross=["all_gather[ensemble]"],
               cross_bytes=4096 * 4, blowup=4)
    # Even a manifest blessing the collective set cannot bless the
    # cross-host gather.
    manifest = {"ensemble_epoch": {"2x4": manifest_row(f)}}
    findings = run_topo_rules(_program_context([f], manifest),
                              rules=["topo-cross-host-payload"])
    assert len(findings) == 1
    assert "scales with the process count" in findings[0].message


def test_cross_host_payload_over_budget_flagged():
    f = _facts(collectives={"psum[data]": 1},
               payloads={"psum[data]": 256 << 20},
               cross=["psum[data]"], cross_bytes=256 << 20)
    findings = run_topo_rules(_program_context([f], manifest={}),
                              rules=["topo-cross-host-payload"])
    assert len(findings) == 1
    assert "exceed the spec's DCN budget" in findings[0].message
    # Under budget: clean.
    small = _facts(collectives={"psum[data]": 1},
                   payloads={"psum[data]": 1024},
                   cross=["psum[data]"], cross_bytes=1024)
    assert run_topo_rules(_program_context([small], manifest={}),
                          rules=["topo-cross-host-payload"]) == []


def test_hbm_overflow_flagged():
    f = _facts(topology="2x8", e=4, d=4,
               per_device=int(20e9), hbm=int(16e9))
    findings = run_topo_rules(_program_context([f], manifest={}),
                              rules=["topo-hbm-budget"])
    assert len(findings) == 1
    assert "exceeds the spec's HBM budget" in findings[0].message
    assert "2x8" in findings[0].message


def test_distill_facts_classifies_and_models_payloads():
    """distill_facts turns a captured ProgramAudit into per-topology
    facts: reduce-style cross-host traffic charges payload once,
    gather-style scales with the axis size, intra-host traffic charges
    nothing."""
    class FakeAudit:
        label = "ensemble_epoch"
        collectives = {"psum[data]": 2, "all_gather[ensemble]": 1}
        collective_payloads = {"psum[data]": 1000,
                               "all_gather[ensemble]": 64}
        memory_fields = {"peak_bytes": 123}

    spec = topo_mod.TopologySpec(2, 4)
    f = distill_facts(FakeAudit(), spec, 4, 2)
    # data is within-host on (4, 2) over 2x4 -> psum charges nothing;
    # the ensemble gather spans hosts and scales by e=4.
    assert f.cross_host == ["all_gather[ensemble]"]
    assert f.cross_host_bytes == 64 * 4
    assert f.replication_blowup == 4
    assert f.per_device_bytes == 123
    # On a single host nothing crosses.
    g = distill_facts(FakeAudit(), topo_mod.TopologySpec(1, 8), 4, 2)
    assert g.cross_host == [] and g.cross_host_bytes == 0


def test_manifest_merge_preserves_and_prunes(tmp_path):
    f1 = _facts(label="ensemble_epoch", topology="1x8")
    f2 = _facts(label="train_epoch", topology="1x8", e=1, d=8)
    rows = merge_rows({("1x8", f.label): f for f in (f1, f2)})
    assert set(rows) == {"ensemble_epoch", "train_epoch"}
    # Updating one cell preserves the other label's rows; a label that
    # left the mesh family is pruned.
    stale = dict(rows)
    stale["a_label_gone_from_the_family"] = {"1x8": {"mesh": {}}}
    merged = merge_rows({("2x4", f1.label): f1}, prior=stale)
    assert set(merged) == {"ensemble_epoch", "train_epoch"}
    assert set(merged["ensemble_epoch"]) == {"1x8", "2x4"}


# ------------------------------------------------ the committed manifest --

def test_checked_in_manifest_covers_every_mesh_family_cell():
    """The zoo/manifest drift pin: every mesh-family label (all of them
    real zoo labels) has a committed row for every canonical topology,
    and the single-host rows carry no cross-host traffic."""
    manifest = load_manifest(DEFAULT_MANIFEST_PATH)
    assert manifest is not None
    assert set(manifest) == set(MESH_FAMILY_LABELS)
    assert set(MESH_FAMILY_LABELS) <= ALL_ZOO_LABELS
    for label, topos in manifest.items():
        assert set(topos) == set(TOPOLOGIES), label
        for topology, row in topos.items():
            assert set(row) == {"mesh", "collectives", "cross_host"}
            e, d = row["mesh"]["ensemble"], row["mesh"]["data"]
            assert e * d == 8, (label, topology)
            # The repo-wide invariant as a checked-in fact: no explicit
            # collectives anywhere in the mesh families today, hence
            # nothing cross-host — the gate exists for the refactor
            # that changes that.
            assert row["collectives"] == {}, (label, topology)
            assert row["cross_host"] == [], (label, topology)


def test_topology_doc_renders_from_manifest():
    rendered = render_topology_doc(load_manifest(DEFAULT_MANIFEST_PATH))
    assert "| program | 1x8 | 2x4 | 4x2 |" in rendered
    for label in MESH_FAMILY_LABELS:
        assert f"`{label}`" in rendered


# ------------------------------------------------------- the CLI contract --

def _patch_sweep(monkeypatch, facts_list, skipped=(), failures=None):
    monkeypatch.setattr(
        "apnea_uq_tpu.topo.capture.sweep_topologies",
        lambda config, specs=None: (
            {(f.topology, f.label): f for f in facts_list},
            list(skipped), dict(failures or {})))


CLEAN_FIXTURE = os.path.join(FIXTURES, "lockstep_neg.py")


def test_cli_injected_violations_exit_1(monkeypatch, capsys, tmp_path,
                                        tiny_config_path):
    """The acceptance criterion: each injected violation class fails
    the real CLI with exit 1, anchored at a pointable source line."""
    from apnea_uq_tpu.cli.main import main

    _zoo, label_lines = zoo_label_lines()
    manifest_path = str(tmp_path / "manifest.json")

    # Program-side classes anchor at the zoo-registration site.
    injections = {
        "cross-host payload over budget": _facts(
            label="train_epoch", collectives={"psum[data]": 1},
            payloads={"psum[data]": 256 << 20}, cross=["psum[data]"],
            cross_bytes=256 << 20),
        "per-device HBM overflow at 2x8": _facts(
            label="ensemble_epoch", topology="2x8", e=4, d=4,
            per_device=int(20e9), hbm=int(16e9)),
        "gather scaling with process count": _facts(
            label="de_predict_fused",
            collectives={"all_gather[ensemble]": 1},
            payloads={"all_gather[ensemble]": 4096},
            cross=["all_gather[ensemble]"], cross_bytes=16384, blowup=4),
    }
    for name, facts in injections.items():
        _patch_sweep(monkeypatch, [facts])
        # Bless the manifest rows first so only the budget rules fire.
        rows = merge_rows({(facts.topology, facts.label): facts})
        from apnea_uq_tpu.topo.manifest import write_manifest

        write_manifest(manifest_path, rows)
        rc = main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
                   "--manifest", manifest_path])
        out = capsys.readouterr().out
        assert rc == 1, f"{name} did not fail the topo gate:\n{out}"
        anchor = f"compilecache/zoo.py:{label_lines[facts.label]}:"
        assert anchor in out, (name, out)

    # Source-side classes anchor at the offending call site.
    for fixture, rule, line in (
            ("primary_io_pos.py", "unguarded-primary-io", 11),
            ("device_enum_pos.py", "single-host-device-enumeration", 7)):
        _patch_sweep(monkeypatch, [_facts()])
        rows = merge_rows({("2x4", "ensemble_epoch"): _facts()})
        write_manifest(manifest_path, rows)
        rc = main(["topo", os.path.join(FIXTURES, fixture),
                   "--config", tiny_config_path,
                   "--manifest", manifest_path])
        out = capsys.readouterr().out
        assert rc == 1
        assert f"{fixture}:{line}: [{rule}]" in out, out


def test_cli_gha_format_and_usage_errors(monkeypatch, capsys, tmp_path,
                                         tiny_config_path):
    from apnea_uq_tpu.cli.main import main

    f = _facts(label="ensemble_epoch", topology="2x8",
               per_device=int(20e9), hbm=int(16e9))
    _patch_sweep(monkeypatch, [f])
    manifest_path = str(tmp_path / "manifest.json")
    from apnea_uq_tpu.topo.manifest import write_manifest

    write_manifest(manifest_path, merge_rows({("2x8", f.label): f}))
    rc = main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
               "--manifest", manifest_path, "--format", "gha"])
    assert rc == 1
    out = capsys.readouterr().out
    line = next(ln for ln in out.splitlines()
                if ln.startswith("::error"))
    assert "title=topo-hbm-budget" in line
    assert "file=apnea_uq_tpu/compilecache/zoo.py" in line

    with pytest.raises(SystemExit) as exc:
        main(["topo", "--rule", "no-such-rule",
              "--config", tiny_config_path])
    assert exc.value.code == 2
    assert "unknown topo rule" in capsys.readouterr().out

    # No manifest yet: usage error with guidance.
    _patch_sweep(monkeypatch, [_facts()])
    with pytest.raises(SystemExit) as exc:
        main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
              "--manifest", str(tmp_path / "nope.json")])
    assert exc.value.code == 2
    assert "--update-manifest" in capsys.readouterr().out

    # A capture failure is exit 2, never a silent pass.
    _patch_sweep(monkeypatch, [], failures={"2x4/ensemble_epoch": "boom"})
    with pytest.raises(SystemExit) as exc:
        main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
              "--manifest", str(tmp_path / "manifest.json")])
    assert exc.value.code == 2
    assert "FAILED" in capsys.readouterr().out


def test_cli_source_only_rule_selection_skips_the_sweep(monkeypatch,
                                                        capsys,
                                                        tiny_config_path):
    """--rule with only source rules must not trigger the jax-loading
    sweep (the lint-anywhere property of the source side)."""
    from apnea_uq_tpu.cli.main import main

    def boom(config, specs=None):
        raise AssertionError("sweep ran for a source-only selection")

    monkeypatch.setattr("apnea_uq_tpu.topo.capture.sweep_topologies",
                        boom)
    rc = main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
               "--rule", "lockstep-collective-discipline"])
    assert rc == 0
    capsys.readouterr()


def test_cli_update_manifest_round_trip(monkeypatch, capsys, tmp_path,
                                        tiny_config_path):
    from apnea_uq_tpu.cli.main import main

    manifest_path = str(tmp_path / "manifest.json")
    f = _facts(label="ensemble_epoch", topology="2x4")
    _patch_sweep(monkeypatch, [f])
    rc = main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
               "--manifest", manifest_path, "--update-manifest"])
    assert rc == 0
    capsys.readouterr()
    saved = load_manifest(manifest_path)
    assert saved["ensemble_epoch"]["2x4"] == manifest_row(f)
    # Clean re-run against the recorded manifest.
    rc = main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
               "--manifest", manifest_path])
    assert rc == 0
    capsys.readouterr()
    # Drift (layout change) -> exit 1; failed update never mutates.
    g = _facts(label="ensemble_epoch", topology="2x4", e=2, d=4,
               per_device=int(20e9), hbm=int(16e9))
    _patch_sweep(monkeypatch, [g])
    before = load_manifest(manifest_path)
    rc = main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
               "--manifest", manifest_path, "--update-manifest"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "NOT updated" in out
    assert load_manifest(manifest_path) == before


def test_cli_update_docs_renders_manifest(monkeypatch, capsys, tmp_path,
                                          tiny_config_path):
    from apnea_uq_tpu.cli.main import main

    manifest_path = str(tmp_path / "manifest.json")
    docs_path = str(tmp_path / "TOPOLOGY.md")
    f = _facts(label="ensemble_epoch", topology="2x4")
    _patch_sweep(monkeypatch, [f])
    rc = main(["topo", CLEAN_FIXTURE, "--config", tiny_config_path,
               "--manifest", manifest_path, "--update-manifest",
               "--update-docs", "--docs", docs_path])
    assert rc == 0
    capsys.readouterr()
    text = open(docs_path).read()
    assert "`ensemble_epoch`" in text
    assert text == render_topology_doc(load_manifest(manifest_path))


# ------------------------------------- the acceptance run: real sweep --

@pytest.fixture(scope="module")
def real_topo_run(tiny_config_path, tmp_path_factory):
    """ONE real sweep through the real CLI (source scan over the
    package + three topologies lowered on the virtual-CPU rig, nothing
    dispatched), shared by the acceptance assertions below."""
    import contextlib
    import io

    from apnea_uq_tpu.cli.main import main

    run_dir = str(tmp_path_factory.mktemp("topo_run") / "run")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["topo", "--config", tiny_config_path, "--json",
                   "--run-dir", run_dir])
    return rc, buf.getvalue(), run_dir


def test_clean_tree_gate_and_suppression_audit_trail(real_topo_run):
    """The tier-1 gate: zero unsuppressed findings over the package +
    bench.py + the full simulated sweep, with every suppression
    justified and pinned — a NEW suppression must be reviewed here."""
    rc, out, _run_dir = real_topo_run
    assert rc == 0, f"topo gate is dirty:\n{out}"
    doc = json.loads(out[out.index("{"):])
    assert doc["summary"]["unsuppressed"] == 0
    suppressed = sorted(
        (f["path"].replace(os.sep, "/"), f["rule"])
        for f in doc["findings"] if f["suppressed"]
    )
    assert suppressed == [
        ("apnea_uq_tpu/audit/capture.py",
         "single-host-device-enumeration"),
        ("apnea_uq_tpu/compilecache/store.py",
         "single-host-device-enumeration"),
        ("apnea_uq_tpu/parallel/mesh.py",
         "single-host-device-enumeration"),
        ("apnea_uq_tpu/parallel/topology.py",
         "single-host-device-enumeration"),
        ("apnea_uq_tpu/telemetry/runlog.py",
         "single-host-device-enumeration"),
        ("apnea_uq_tpu/topo/capture.py",
         "single-host-device-enumeration"),
        ("bench.py", "single-host-device-enumeration"),
        ("bench.py", "single-host-device-enumeration"),
    ]
    # All three topologies captured for every mesh-family label.
    cells = set(doc["programs"])
    assert cells == {f"{label}@{topo}" for label in MESH_FAMILY_LABELS
                     for topo in TOPOLOGIES}
    for cell, facts in doc["programs"].items():
        assert facts["cross_host_bytes"] == 0, cell
        assert facts["per_device_bytes"] is not None
        assert facts["per_device_bytes"] < facts["hbm_budget_bytes"]


def test_topo_program_events_and_compare(real_topo_run, tmp_path):
    """topo --run-dir persists one topo_program event per cell, and
    telemetry compare gates the cross-host/per-device bytes
    lower-is-better."""
    from apnea_uq_tpu.telemetry import compare as compare_mod
    from apnea_uq_tpu.telemetry.runlog import read_events

    _rc, _out, run_dir = real_topo_run
    events = [e for e in read_events(run_dir)
              if e.get("kind") == "topo_program"]
    assert sorted((e["topology"], e["label"]) for e in events) == sorted(
        (topo, label) for label in MESH_FAMILY_LABELS
        for topo in TOPOLOGIES)
    worse = tmp_path / "worse_run"
    worse.mkdir()
    lines = [json.loads(line) for line in
             open(os.path.join(run_dir, "events.jsonl")) if line.strip()]
    for e in lines:
        if e.get("kind") == "topo_program":
            e["cross_host_bytes"] = e["cross_host_bytes"] + 10_000_000
            e["per_device_bytes"] = int(e["per_device_bytes"] * 2)
    with open(worse / "events.jsonl", "w") as f:
        for e in lines:
            f.write(json.dumps(e) + "\n")
    comparison = compare_mod.compare_paths(run_dir, str(worse))
    regressed = {d.name for d in comparison.regressions}
    assert "topo.ensemble_epoch.2x4.cross_host_bytes" in regressed
    assert "topo.train_epoch.1x8.per_device_bytes" in regressed


# ------------------------------------------------- the check meta-gate --

def test_check_merges_exit_codes(monkeypatch, capsys, tiny_config_path):
    """check = lint + flow + audit + topo + conc with one exit code: 0
    all clean, 1 any findings, 2 any usage error (and a usage error
    never hides another gate's findings)."""
    from apnea_uq_tpu.cli.main import main

    calls = []

    def fake(name, rc, *, raises=False):
        def run(*a, **k):
            calls.append(name)
            if raises:
                raise SystemExit(rc)
            return rc
        return run

    # Patch the sys.modules objects (importlib.import_module), not the
    # "pkg.mod.attr" string path: cmd_check's lazy from-imports read
    # sys.modules, and an earlier module-eviction test (test_lint's
    # jax-poison run) can leave the package ATTRIBUTE pointing at a
    # different module object than the sys.modules entry.
    import importlib

    def patch(codes, raises=()):
        calls.clear()
        for name, modpath, attr in (
                ("lint", "apnea_uq_tpu.lint.cli", "cmd_lint"),
                ("flow", "apnea_uq_tpu.flow.cli", "cmd_flow"),
                ("audit", "apnea_uq_tpu.audit.cli", "cmd_audit"),
                ("topo", "apnea_uq_tpu.topo.cli", "cmd_topo"),
                ("conc", "apnea_uq_tpu.conc.cli", "cmd_conc")):
            monkeypatch.setattr(
                importlib.import_module(modpath), attr,
                fake(name, codes[name], raises=name in raises))

    all_clean = {"lint": 0, "flow": 0, "audit": 0, "topo": 0, "conc": 0}
    patch(all_clean)
    assert main(["check", "--config", tiny_config_path]) == 0
    assert calls == ["lint", "flow", "audit", "topo", "conc"]
    out = capsys.readouterr().out
    assert "== apnea-uq lint ==" in out and "clean" in out

    patch({**all_clean, "topo": 1})
    assert main(["check", "--config", tiny_config_path]) == 1
    assert "FINDINGS" in capsys.readouterr().out

    # A usage error in audit still runs topo + conc, and 2 wins overall.
    patch({**all_clean, "audit": 2, "topo": 1}, raises=("audit",))
    assert main(["check", "--config", tiny_config_path]) == 2
    assert calls == ["lint", "flow", "audit", "topo", "conc"]
    assert "USAGE ERROR" in capsys.readouterr().out
