"""Fused Pallas MCD kernel (ISSUE 12): interpret-mode kernel-body tests
with injected masks (the CPU tier-1 exercise of the kernel MATH, not
just the XLA fallback), engine resolution + fallback parity on every
MCD program family, label/config validation, and the bootstrap kernel's
injected-bits interpret twin.

The hardware-PRNG path itself needs a TPU:
``APNEA_UQ_TEST_TPU=1 pytest tests/test_pallas_mcd.py -k on_tpu``.
"""

import inspect

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from apnea_uq_tpu.config import ModelConfig, UQConfig  # noqa: E402
from apnea_uq_tpu.models import AlarconCNN1D, init_variables  # noqa: E402
from apnea_uq_tpu.models.cnn1d import apply_model, predict_proba  # noqa: E402
from apnea_uq_tpu.ops import pallas_mcd  # noqa: E402
from apnea_uq_tpu.uq import mc_dropout_predict  # noqa: E402
from apnea_uq_tpu.uq.predict import (  # noqa: E402
    DE_PROGRAM_LABELS,
    MCD_PROGRAM_LABELS,
    de_program_label,
    mc_dropout_predict_streaming,
    mcd_program_label,
    resolve_mcd_engine,
)

# The documented tolerance tiers (PARITY.md "Tolerance tiers").
F32_TOL = dict(rtol=0, atol=1e-6)
BF16_TOL = dict(rtol=0, atol=2e-2)


def _model(dtype="float32", features=(6, 8), kernels=(5, 3),
           rates=(0.3, 0.4)):
    return AlarconCNN1D(ModelConfig(
        features=features, kernel_sizes=kernels, dropout_rates=rates,
        compute_dtype=dtype,
    ))


def _reference_forward(model, variables, x, masks):
    """Independent forward: ``lax.conv_general_dilated`` convolutions
    (NOT the kernel's shifted-matmul decomposition) + explicit BN/
    dropout math, so agreement genuinely checks the kernel body."""
    cfg = model.config
    params = variables["params"]
    stats = variables["batch_stats"]
    n_passes = masks[0].shape[0] if masks else 1
    h = jnp.broadcast_to(jnp.asarray(x, jnp.float32)[None],
                         (n_passes,) + x.shape)
    mask_i = 0
    for i, rate in enumerate(cfg.dropout_rates):
        flat = h.reshape((-1,) + tuple(h.shape[2:]))
        out = jax.lax.conv_general_dilated(
            flat, params[f"conv_{i}"]["kernel"], (1,), "SAME",
            dimension_numbers=("NWC", "WIO", "NWC"),
        ) + params[f"conv_{i}"]["bias"]
        out = jnp.maximum(out, 0.0)
        out = (
            (out - stats[f"bn_{i}"]["mean"])
            * jax.lax.rsqrt(stats[f"bn_{i}"]["var"] + cfg.bn_epsilon)
            * params[f"bn_{i}"]["scale"] + params[f"bn_{i}"]["bias"]
        )
        if rate > 0.0:
            m = jnp.asarray(masks[mask_i], jnp.float32)
            mask_i += 1
            out = out * m.reshape(out.shape) / (1.0 - rate)
        h = out.reshape((n_passes, -1) + tuple(out.shape[1:]))
    pooled = h.mean(axis=2)
    logits = pooled @ params["head"]["kernel"] + params["head"]["bias"]
    return jax.nn.sigmoid(logits[..., 0])


class TestInterpretKernel:
    """The kernel BODY under pl.pallas_call(interpret=True) with
    injected masks — identical `_tile_body` to the TPU path; only the
    mask source differs (interpret mode has no hardware PRNG)."""

    def test_keep_valued_masks_reduce_to_eval_mode(self, rng):
        """Masks of constant value (1 - rate) cancel the dropout
        scaling exactly, so the kernel must reproduce the deterministic
        eval-mode model — end-to-end validation of the conv/BN/GAP/head
        math against the real Flax forward."""
        model = _model()
        variables = init_variables(model, jax.random.key(0))
        x = rng.normal(size=(11, 60, 4)).astype(np.float32)  # pads to 16
        masks = [np.full((3, 11, 60, f), 1.0 - r, np.float32)
                 for f, r in zip((6, 8), (0.3, 0.4))]
        probs = np.asarray(pallas_mcd.mcd_forward_with_masks(
            model, variables, x, masks))
        ref = np.asarray(predict_proba(apply_model(
            model, variables, jnp.asarray(x), mode="eval")[0]))
        assert probs.shape == (3, 11)
        np.testing.assert_allclose(probs, np.broadcast_to(ref, (3, 11)),
                                   **F32_TOL)

    def test_random_masks_match_independent_conv_reference(self, rng):
        """Random 0/1 masks against the lax.conv reference: pins the
        shifted-matmul convolution AND the mask application/scaling at
        the f32 tier, across wrap-padded window tiles and pass groups."""
        model = _model()
        variables = init_variables(model, jax.random.key(1))
        x = rng.normal(size=(13, 60, 4)).astype(np.float32)
        masks = [(rng.uniform(size=(5, 13, 60, f)) > r).astype(np.float32)
                 for f, r in zip((6, 8), (0.3, 0.4))]
        probs = np.asarray(pallas_mcd.mcd_forward_with_masks(
            model, variables, x, masks, window_tile=4, pass_group=2))
        ref = np.asarray(_reference_forward(model, variables, x, masks))
        assert probs.shape == (5, 13)
        np.testing.assert_allclose(probs, ref, **F32_TOL)

    def test_bf16_tier_against_f32_reference(self, rng):
        """compute_dtype='bfloat16' through the kernel body stays within
        the documented bf16 tier (<=2e-2) of the f32 reference — the
        conv matmuls run bf16, accumulation and stats stay f32."""
        model = _model("bfloat16")
        f32_model = _model()
        variables = init_variables(f32_model, jax.random.key(2))
        x = rng.normal(size=(9, 60, 4)).astype(np.float32)
        masks = [(rng.uniform(size=(2, 9, 60, f)) > r).astype(np.float32)
                 for f, r in zip((6, 8), (0.3, 0.4))]
        bf16 = np.asarray(pallas_mcd.mcd_forward_with_masks(
            model, variables, x, masks))
        ref = np.asarray(_reference_forward(f32_model, variables, x, masks))
        np.testing.assert_allclose(bf16, ref, **BF16_TOL)

    def test_mask_count_validated(self, rng):
        model = _model()
        variables = init_variables(model, jax.random.key(0))
        x = rng.normal(size=(4, 60, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="mask arrays"):
            pallas_mcd.mcd_forward_with_masks(
                model, variables, x,
                [np.ones((2, 4, 60, 6), np.float32)])  # needs 2, got 1
        # A dropout-free model is a clear error, not an IndexError.
        no_dropout = _model(rates=(0.0, 0.0))
        with pytest.raises(ValueError, match="no nonzero dropout"):
            pallas_mcd.mcd_forward_with_masks(no_dropout, variables, x, [])


class TestEngineResolution:
    """resolve_mcd_engine: the pallas engine is requested per call but
    dispatches only where the kernel is valid; everywhere else the XLA
    body runs under the SAME (pallas-suffixed) label — the bootstrap
    kernel's fallback contract."""

    def test_off_tpu_resolves_to_xla(self):
        assert jax.default_backend() != "tpu"  # the CPU test rig
        assert resolve_mcd_engine("pallas", "clean", None) == "xla"
        assert resolve_mcd_engine("xla", "clean", None) == "xla"

    def test_parity_mode_and_mesh_resolve_to_xla(self, monkeypatch):
        # Even with the kernel nominally available, parity mode and a
        # mesh must fall back: batch statistics are whole-chunk, and
        # the kernel is a per-chip program.
        monkeypatch.setattr(pallas_mcd, "pallas_mcd_available",
                            lambda: True)
        from apnea_uq_tpu.parallel import make_mesh

        assert resolve_mcd_engine("pallas", "clean", None) == "pallas"
        assert resolve_mcd_engine("pallas", "parity", None) == "xla"
        assert resolve_mcd_engine(
            "pallas", "clean", make_mesh(num_members=4)) == "xla"

    def test_unknown_engine_rejected(self):
        with pytest.raises(ValueError, match="engine"):
            resolve_mcd_engine("bogus", "clean", None)

    def test_fallback_is_bit_identical_on_every_family(self, rng):
        """Off-TPU, engine='pallas' must produce EXACTLY the XLA path's
        results on all four MCD program families — the fallback is the
        same body, so toggling the engine off-TPU never changes
        predictions (only the program label)."""
        model = _model()
        variables = init_variables(model, jax.random.key(0))
        x = rng.normal(size=(21, 60, 4)).astype(np.float32)
        key = jax.random.key(7)
        stat_spec = ("nats", 1e-10)
        for stats in (None, stat_spec):
            ref = np.asarray(mc_dropout_predict(
                model, variables, x, n_passes=4, batch_size=8, key=key,
                stats=stats))
            pal = np.asarray(mc_dropout_predict(
                model, variables, x, n_passes=4, batch_size=8, key=key,
                stats=stats, engine="pallas"))
            np.testing.assert_array_equal(ref, pal)
            streamed = np.asarray(mc_dropout_predict_streaming(
                model, variables, x, n_passes=4, batch_size=8, key=key,
                stats=stats, engine="pallas"))
            np.testing.assert_array_equal(ref, streamed)


class TestLabelsAndConfig:
    def test_label_grammar(self):
        f32 = _model()
        bf16 = _model("bfloat16")
        assert mcd_program_label(f32, streamed=False, engine="xla",
                                 fused=False) == "mcd_predict"
        assert mcd_program_label(bf16, streamed=True, engine="pallas",
                                 fused=True) == \
            "mcd_chunk_predict_pallas_fused_bf16"
        assert de_program_label(bf16, streamed=False, engine="xla",
                                fused=True) == "de_predict_fused_bf16"
        assert de_program_label(f32, streamed=True, engine="xla",
                                fused=False) == "de_chunk_predict"

    def test_label_tables_cover_the_grammar(self):
        """16 MCD labels and 16 DE labels (streamed x engine x fused x
        dtype — the DE grid gained its engine axis in ISSUE 16), no
        duplicates — and every combination the builders can emit is in
        its table (the builders assert membership on every call)."""
        assert len(set(MCD_PROGRAM_LABELS)) == 16
        assert len(set(DE_PROGRAM_LABELS)) == 16
        for streamed in (False, True):
            for engine in ("xla", "pallas"):
                for fused in (False, True):
                    for model in (_model(), _model("bfloat16")):
                        mcd_program_label(model, streamed=streamed,
                                          engine=engine, fused=fused)
                        de_program_label(model, streamed=streamed,
                                         engine=engine, fused=fused)

    def test_compute_dtype_validated_at_config_load(self):
        with pytest.raises(ValueError, match="compute_dtype"):
            ModelConfig(compute_dtype="float16")
        with pytest.raises(ValueError, match="compute_dtype"):
            ModelConfig(compute_dtype="int8")
        ModelConfig(compute_dtype="bfloat16")  # the blessed tier

    def test_mcd_engine_validated_at_config_load(self):
        with pytest.raises(ValueError, match="mcd_engine"):
            UQConfig(mcd_engine="mosaic")
        UQConfig(mcd_engine="pallas")

    def test_config_json_round_trips_engine_and_dtype(self, tmp_path):
        from apnea_uq_tpu.config import (ExperimentConfig, load_config,
                                         save_config)

        cfg = ExperimentConfig(
            model=ModelConfig(compute_dtype="bfloat16"),
            uq=UQConfig(mcd_engine="pallas"),
        )
        path = str(tmp_path / "config.json")
        save_config(cfg, path)
        loaded = load_config(path)
        assert loaded.model.compute_dtype == "bfloat16"
        assert loaded.uq.mcd_engine == "pallas"
        # A hand-edited bad value fails AT LOAD, inside the dataclass.
        text = open(path).read().replace('"bfloat16"', '"float16"')
        open(path, "w").write(text)
        with pytest.raises(ValueError, match="compute_dtype"):
            load_config(path)

    def test_eval_cli_flags_parse_and_override(self):
        from apnea_uq_tpu.cli.main import build_parser
        from apnea_uq_tpu.cli.stages import _apply_eval_overrides
        from apnea_uq_tpu.config import ExperimentConfig

        parser = build_parser()
        args = parser.parse_args(
            ["eval-mcd", "--registry", "r", "--compute-dtype", "bfloat16",
             "--mcd-engine", "pallas"])
        cfg = _apply_eval_overrides(args, ExperimentConfig())
        assert cfg.model.compute_dtype == "bfloat16"
        assert cfg.uq.mcd_engine == "pallas"
        args = parser.parse_args(
            ["eval-de", "--registry", "r", "--compute-dtype", "bfloat16"])
        cfg = _apply_eval_overrides(args, ExperimentConfig())
        assert cfg.model.compute_dtype == "bfloat16"
        # No flags -> the config passes through untouched.
        args = parser.parse_args(["eval-mcd", "--registry", "r"])
        base = ExperimentConfig()
        assert _apply_eval_overrides(args, base) is base

    def test_overrides_fold_in_before_the_run_log_opens(self):
        """The run-dir config snapshot must record the dtype/engine the
        eval actually ran: the override application has to precede the
        `_run(...)` bracket in both eval commands (source-order pin)."""
        from apnea_uq_tpu.cli import stages

        for cmd in (stages.cmd_eval_mcd, stages.cmd_eval_de):
            src = inspect.getsource(cmd)
            assert src.index("_apply_eval_overrides") < src.index(
                "_run(args"), cmd.__name__


class TestBootstrapInterpretKernel:
    """The Poisson-bootstrap kernel body on CPU via injected bits
    (ops/pallas_bootstrap.poisson_sums_from_bits): the same inverse-CDF
    count math and HIGHEST-precision count matmul the TPU kernel runs."""

    def test_injected_bits_match_numpy_reference(self, rng):
        from apnea_uq_tpu.ops.pallas_bootstrap import (
            _CDF, N_ROWS, poisson_sums_from_bits,
        )

        v = rng.uniform(size=(N_ROWS, 3000)).astype(np.float32)
        bits = rng.integers(0, 1 << 24, size=(10, 3000)).astype(np.int32)
        out = np.asarray(poisson_sums_from_bits(v, bits, tile=1024))
        icdf = [int(t * (1 << 24)) for t in _CDF]
        counts = np.zeros_like(bits)
        for t in icdf:
            counts += (bits > t).astype(np.int32)
        ref = counts.astype(np.float64) @ v.T.astype(np.float64)
        assert out.shape == (10, N_ROWS)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_count_distribution_is_poisson_one(self, rng):
        """Uniform bits through the shipped inverse CDF produce
        Poisson(1)-distributed counts (mean and variance ~1) — the
        statistical contract the estimator rests on."""
        from apnea_uq_tpu.ops.pallas_bootstrap import _counts_from_bits

        bits = jnp.asarray(
            rng.integers(0, 1 << 24, size=(64, 4096)), jnp.int32)
        counts = np.asarray(_counts_from_bits(bits))
        assert abs(counts.mean() - 1.0) < 0.02
        assert abs(counts.var() - 1.0) < 0.05

    def test_zero_padding_is_exact(self, rng):
        from apnea_uq_tpu.ops.pallas_bootstrap import (
            N_ROWS, poisson_sums_from_bits,
        )

        v = rng.uniform(size=(N_ROWS, 100)).astype(np.float32)
        bits = rng.integers(0, 1 << 24, size=(5, 100)).astype(np.int32)
        # tile > M forces padding; sums must equal the unpadded math.
        padded = np.asarray(poisson_sums_from_bits(v, bits, tile=256))
        exact = np.asarray(poisson_sums_from_bits(v, bits, tile=128))
        np.testing.assert_allclose(padded, exact, rtol=1e-6)


class TestPallasKernelOnTPU:
    def test_mcd_pallas_passes_on_tpu(self, rng):
        """TPU-only: the hardware-PRNG kernel is deterministic per
        (key, chunk), pass-stochastic, and its per-window mean prob
        agrees with the XLA engine within Monte-Carlo error."""
        if jax.default_backend() != "tpu":
            pytest.skip("pallas MCD kernel requires TPU")
        model = _model()
        variables = init_variables(model, jax.random.key(0))
        x = jnp.asarray(rng.normal(size=(40, 60, 4)), jnp.float32)
        key = jax.random.key(3)
        a = np.asarray(pallas_mcd.mcd_pallas_passes(
            model, variables, x, key, jnp.int32(0), 64))
        b = np.asarray(pallas_mcd.mcd_pallas_passes(
            model, variables, x, key, jnp.int32(0), 64))
        np.testing.assert_array_equal(a, b)
        assert a.shape == (64, 40)
        assert np.all((a >= 0) & (a <= 1))
        assert np.std(a, axis=0).max() > 0  # stochastic across passes
        xla = np.asarray(mc_dropout_predict(
            model, variables, x, n_passes=64, batch_size=40, key=key))
        se = np.sqrt(a.var(axis=0) / 64 + xla.var(axis=0) / 64) + 1e-4
        assert np.all(np.abs(a.mean(axis=0) - xla.mean(axis=0)) < 5 * se)
