"""Profusion XML annotation parsing (preprocess_shhs_raw.py:169-190 parity)."""

import numpy as np

from apnea_uq_tpu.data.annotations import parse_xml_annotations

XML = """<?xml version="1.0"?>
<PSGAnnotation>
  <ScoredEvents>
    <ScoredEvent>
      <EventType>Recording Start Time</EventType>
      <EventConcept>Recording Start Time</EventConcept>
      <Start>0.0</Start>
      <Duration>25200.0</Duration>
    </ScoredEvent>
    <ScoredEvent>
      <EventType>Respiratory|Respiratory</EventType>
      <EventConcept>Obstructive apnea|Obstructive Apnea</EventConcept>
      <Start>100.0</Start>
      <Duration>20.0</Duration>
    </ScoredEvent>
    <ScoredEvent>
      <EventType>Respiratory|Respiratory</EventType>
      <EventConcept>Hypopnea|Hypopnea</EventConcept>
      <Start>300.5</Start>
      <Duration>15.0</Duration>
    </ScoredEvent>
    <ScoredEvent>
      <EventType>Stages|Stages</EventType>
      <EventConcept>Wake|0</EventConcept>
      <Start>0.0</Start>
      <Duration>30.0</Duration>
    </ScoredEvent>
    <ScoredEvent>
      <EventType>Respiratory|Respiratory</EventType>
      <EventConcept>Hypopnea|Hypopnea</EventConcept>
      <Start>900.0</Start>
      <Duration>12.0</Duration>
    </ScoredEvent>
  </ScoredEvents>
</PSGAnnotation>
"""


def write_xml(tmp_path):
    path = tmp_path / "shhs2-200001-nsrr.xml"
    path.write_text(XML)
    return str(path)


def test_stop_at_first_stage_event(tmp_path):
    events = parse_xml_annotations(write_xml(tmp_path))
    # Parsing stops at the Stages|Stages event: the trailing hypopnea is
    # not collected (preprocess_shhs_raw.py:176-177).
    assert len(events) == 3
    assert events.recording_duration_s == 25200.0
    np.testing.assert_allclose(events.start_s, [0.0, 100.0, 300.5])
    np.testing.assert_allclose(events.duration_s, [25200.0, 20.0, 15.0])


def test_scan_all_events(tmp_path):
    events = parse_xml_annotations(
        write_xml(tmp_path), stop_at_first_stage_event=False
    )
    assert len(events) == 5


def test_select_concepts(tmp_path):
    events = parse_xml_annotations(write_xml(tmp_path))
    apnea = events.select_concepts(
        ["Obstructive apnea|Obstructive Apnea", "Hypopnea|Hypopnea"]
    )
    assert len(apnea) == 2
    np.testing.assert_allclose(apnea.start_s, [100.0, 300.5])


def test_missing_recording_start(tmp_path):
    path = tmp_path / "x.xml"
    path.write_text(
        "<A><ScoredEvents><ScoredEvent>"
        "<EventType>Respiratory|Respiratory</EventType>"
        "<EventConcept>Hypopnea|Hypopnea</EventConcept>"
        "<Start>1</Start><Duration>11</Duration>"
        "</ScoredEvent></ScoredEvents></A>"
    )
    events = parse_xml_annotations(str(path))
    assert events.recording_duration_s == 0.0  # preprocess_shhs_raw.py:91
