"""Model-quality observability end to end (ISSUE 13 tentpole):
the eval stages emit ``quality_metrics`` + ``drift_fingerprint`` events
through the REAL CLI, `apnea-uq quality check` gates a drifted cohort
(vs the frozen ``quality_baseline``) and a miscalibrated run (vs a
healthy baseline run) nonzero, self-comparison is clean, and
``telemetry compare`` gates ``quality.<label>.ece`` across run dirs —
including across the CPU-proxy boundary, where quality metrics are
backend-independent and refuse nothing."""

import json
import os

import numpy as np
import pytest

from apnea_uq_tpu import telemetry
from apnea_uq_tpu.cli.main import main
from apnea_uq_tpu.config import (
    EnsembleConfig,
    ExperimentConfig,
    ModelConfig,
    PrepareConfig,
    TrainConfig,
    UQConfig,
    _to_jsonable,
)
from apnea_uq_tpu.data import WindowSet
from apnea_uq_tpu.data import registry as reg
from apnea_uq_tpu.data.registry import ArtifactRegistry
from apnea_uq_tpu.telemetry import compare as compare_mod
from apnea_uq_tpu.telemetry import quality as quality_mod


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    """Registry with a frozen quality baseline, an (untrained)
    checkpoint, and two REAL `apnea-uq eval-mcd` runs: a healthy one on
    the prepared cohort and a drifted one after the test windows were
    shifted under the frozen baseline.  Training is skipped — the
    quality plumbing only needs a restorable checkpoint, and a fresh
    init is two orders of magnitude cheaper than a fit."""
    import jax

    from apnea_uq_tpu.models import AlarconCNN1D
    from apnea_uq_tpu.training import create_train_state, save_state

    root = tmp_path_factory.mktemp("quality")
    registry_dir = str(root / "registry")
    rng = np.random.default_rng(0)
    n, n_patients = 360, 12
    pids = np.array([f"P{i % n_patients:03d}" for i in range(n)])
    y = rng.integers(0, 2, n).astype(np.int8)
    x = rng.normal(size=(n, 60, 4)).astype(np.float32)
    x[:, :, 0] += (y.astype(np.float32) * 2 - 1)[:, None] * 1.2
    windows = WindowSet(
        x=x, y=y, patient_ids=pids,
        start_time_s=np.arange(n, dtype=np.int32) * 60,
        channels=("SaO2", "PR", "THOR RES", "ABDO RES"),
    )
    ArtifactRegistry(registry_dir).save_arrays(reg.WINDOWS,
                                               windows.to_arrays())
    config = ExperimentConfig(
        model=ModelConfig(features=(3,), kernel_sizes=(3,),
                          dropout_rates=(0.2,)),
        train=TrainConfig(batch_size=64, num_epochs=1,
                          validation_split=0.1, seed=1),
        ensemble=EnsembleConfig(num_members=2, num_epochs=1,
                                batch_size=64, seed_base=2025),
        uq=UQConfig(mc_passes=3, n_bootstrap=8,
                    inference_batch_size=128),
        prepare=PrepareConfig(smote=False),
    )
    config_path = str(root / "config.json")
    with open(config_path, "w") as f:
        json.dump(_to_jsonable(config), f)

    assert main(["prepare", "--registry", registry_dir,
                 "--config", config_path]) == 0
    registry = ArtifactRegistry(registry_dir)
    assert registry.exists(reg.QUALITY_BASELINE)

    model = AlarconCNN1D(config.model)
    state = create_train_state(model, jax.random.key(0),
                               learning_rate=config.train.learning_rate)
    save_state(os.path.join(registry_dir, "checkpoint", "baseline"),
               state)

    healthy = str(root / "healthy_run")
    assert main(["eval-mcd", "--registry", registry_dir,
                 "--config", config_path, "--run-dir", healthy]) == 0

    # Inject a per-channel cohort shift: overwrite the test windows with
    # a scaled+offset copy while the quality_baseline stays frozen — the
    # deployed-drift scenario the fingerprint exists to catch.
    test = registry.load_arrays(reg.TEST_STD_UNBALANCED)
    registry.save_arrays(
        reg.TEST_STD_UNBALANCED,
        {"x": test["x"] * 2.0 + 1.0, "y": test["y"],
         "patient_ids": test["patient_ids"]},
    )
    drifted = str(root / "drifted_run")
    assert main(["eval-mcd", "--registry", registry_dir,
                 "--config", config_path, "--run-dir", drifted,
                 "--no-detailed"]) == 0
    return {"root": root, "registry": registry_dir,
            "config": config_path, "healthy": healthy,
            "drifted": drifted}


def _fabricated_run_dir(path, events):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, telemetry.EVENTS_FILENAME), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return str(path)


def _quality_run(path, *, ece, proxy=False, windows_per_s=5000.0,
                 label="CNN_MCD_Unbalanced"):
    """A fabricated run dir with one quality_metrics event (+ a
    backend-bound eval throughput, + optional proxy provenance)."""
    events = [{"seq": 0, "ts": 1.0, "kind": "run_started",
               "schema_version": 1, "stage": "eval-mcd"}]
    if proxy:
        events.append({"seq": 1, "ts": 1.5, "kind": "bench_mode",
                       "proxy": True, "platform": "cpu"})
    events += [
        {"seq": 2, "ts": 2.0, "kind": "eval_predict", "label": label,
         "windows_per_s": windows_per_s, "fused": True,
         "d2h_bytes": 4 * 90 * 4},
        {"seq": 3, "ts": 2.5, "kind": "quality_metrics", "label": label,
         "n_windows": 90, "n_passes": 3, "fused": True, "num_bins": 15,
         "ece": ece, "mce": min(1.0, ece * 2), "brier": 0.2 + ece / 4},
        {"seq": 4, "ts": 3.0, "kind": "run_finished", "status": "ok"},
    ]
    return _fabricated_run_dir(path, events)


class TestEndToEnd:
    def test_eval_emits_quality_and_drift_events(self, env):
        events = telemetry.read_events(env["healthy"])
        qm = [e for e in events if e["kind"] == "quality_metrics"]
        drifts = [e for e in events if e["kind"] == "drift_fingerprint"]
        assert {e["label"] for e in qm} == {"CNN_MCD_Unbalanced",
                                            "CNN_MCD_Balanced_RUS"}
        for e in qm:
            assert 0.0 <= e["ece"] <= 1.0
            assert 0.0 <= e["brier"] <= 1.0
            assert e["fused"] is True
            unc = e["uncertainty"]
            for key in quality_mod.UNCERTAINTY_KEYS:
                assert unc[key]["p05"] <= unc[key]["p95"]
                assert sum(unc[key]["histogram"]["counts"]) \
                    == e["n_windows"]
        # The detailed Unbalanced run carries the patient rollup.
        unb = next(e for e in qm if e["label"] == "CNN_MCD_Unbalanced")
        assert unb["patients"]["n_patients"] > 1
        assert 0.0 <= unb["patients"]["accuracy_min"] \
            <= unb["patients"]["accuracy_mean"] <= 1.0
        # Drift self-score vs the just-frozen PER-SET baselines: clean
        # for BOTH sets — the RUS set scores against the RUS baseline,
        # so its deliberate class re-balance reads as exactly zero
        # drift, never a false gate failure.
        assert {e["label"] for e in drifts} == {"Unbalanced",
                                                "Balanced_RUS"}
        for e in drifts:
            assert e["max_psi"] == 0.0, e["label"]
            assert e["max_ks"] == 0.0, e["label"]
        unb_drift = next(e for e in drifts if e["label"] == "Unbalanced")
        assert len(unb_drift["channels"]) == 4

    def test_self_check_and_self_baseline_exit_zero(self, env):
        assert main(["quality", "check", env["healthy"]]) == 0
        assert main(["quality", "check", env["healthy"],
                     "--baseline", env["healthy"]]) == 0

    def test_drifted_cohort_gates_exit_1(self, env, capsys):
        events = telemetry.read_events(env["drifted"])
        drift = next(e for e in events
                     if e["kind"] == "drift_fingerprint"
                     and e["label"] == "Unbalanced")
        assert drift["max_psi"] > 0.2
        # Only the shifted set drifts: the untouched RUS set stays at
        # its own baseline (the per-set freeze keeps it quiet).
        rus = next(e for e in events
                   if e["kind"] == "drift_fingerprint"
                   and e["label"] == "Balanced_RUS")
        assert rus["max_psi"] == 0.0
        assert main(["quality", "check", env["drifted"]]) == 1
        out = capsys.readouterr().out
        assert "quality-drift" in out and "max_psi" in out

    def test_disjoint_baseline_labels_still_gate_drift(self, env,
                                                       tmp_path):
        """A baseline sharing no quality_metrics label must NOT discard
        the candidate's drift checks: the drifted run still exits 1 on
        drift (not 2), matching compare's missing-on-one-side rule."""
        other = _quality_run(tmp_path / "other_label", ece=0.1,
                             label="CNN_DE_Unbalanced")
        assert main(["quality", "check", env["drifted"],
                     "--baseline", other]) == 1

    def test_quality_emission_failure_never_kills_the_eval(self, env,
                                                           monkeypatch,
                                                           tmp_path,
                                                           capsys):
        """The quality event is derived AFTER the expensive predict; a
        bug in its computation (e.g. a NaN that survived imputation
        detonating in the binning) must degrade to a logged skip, never
        abort the eval."""
        from apnea_uq_tpu.telemetry import quality as quality_mod

        def boom(run_log, result, **kw):
            raise ValueError("synthetic quality emission failure")

        monkeypatch.setattr(quality_mod, "emit_quality_metrics", boom)
        run_dir = str(tmp_path / "guarded_run")
        assert main(["eval-mcd", "--registry", env["registry"],
                     "--config", env["config"], "--run-dir", run_dir,
                     "--no-detailed"]) == 0
        out = capsys.readouterr().out
        assert "quality_metrics emission skipped" in out
        events = telemetry.read_events(run_dir)
        assert not any(e["kind"] == "quality_metrics" for e in events)
        # The eval itself completed and recorded its results.
        assert any(e["kind"] == "eval_predict" for e in events)
        assert events[-1]["status"] == "ok"

    def test_malformed_baseline_never_kills_the_eval(self, env,
                                                     tmp_path, capsys):
        """A truncated/hand-edited quality_baseline document must be
        logged and skipped at eval time — not crash before predict."""
        from apnea_uq_tpu.data.registry import ArtifactRegistry

        registry = ArtifactRegistry(env["registry"])
        good = registry.load_json(reg.QUALITY_BASELINE)
        try:
            registry.save_json(reg.QUALITY_BASELINE,
                               {"version": 1,
                                "sets": {reg.TEST_STD_UNBALANCED:
                                         {"broken": True}}})
            run_dir = str(tmp_path / "malformed_baseline_run")
            assert main(["eval-mcd", "--registry", env["registry"],
                         "--config", env["config"],
                         "--run-dir", run_dir, "--no-detailed"]) == 0
            out = capsys.readouterr().out
            assert "drift fingerprint skipped" in out
            events = telemetry.read_events(run_dir)
            assert not any(e["kind"] == "drift_fingerprint"
                           for e in events)
            assert any(e["kind"] == "quality_metrics" for e in events)
        finally:
            registry.save_json(reg.QUALITY_BASELINE, good)

    def test_miscalibrated_run_vs_healthy_baseline_exits_1(
            self, env, tmp_path, capsys):
        """Acceptance (a): a synthetically miscalibrated candidate run
        gated against the healthy baseline run through the real CLI."""
        healthy_qm = [e for e in telemetry.read_events(env["healthy"])
                      if e["kind"] == "quality_metrics"
                      and e["label"] == "CNN_MCD_Unbalanced"]
        bad = _quality_run(tmp_path / "bad",
                           ece=healthy_qm[0]["ece"] * 4 + 0.2)
        assert main(["quality", "check", bad,
                     "--baseline", env["healthy"]]) == 1
        out = capsys.readouterr().out
        assert "quality-calibration-regression" in out
        # Without --baseline the drift-free fabricated run has ZERO
        # gateable checks — exit 2 (usage), never a clean pass over
        # zero checks (compare's no-comparable-metrics contract).
        with pytest.raises(SystemExit) as exc:
            main(["quality", "check", bad])
        assert exc.value.code == 2

    def test_gate_event_appended_to_checked_run(self, env):
        before = len([e for e in telemetry.read_events(env["drifted"])
                      if e["kind"] == "quality_gate"])
        assert main(["quality", "check", env["drifted"]]) == 1
        events = telemetry.read_events(env["drifted"])
        gates = [e for e in events if e["kind"] == "quality_gate"]
        assert len(gates) == before + 1
        assert gates[-1]["passed"] is False
        assert gates[-1]["failures"]
        # Appended without a new run_started: the latest-run boundary
        # keeps the verdict attached to the run it judged.
        latest, _ = telemetry.runlog.latest_run(events)
        assert any(e["kind"] == "quality_gate" for e in latest)

    def test_check_json_and_gha_formats(self, env, capsys):
        assert main(["quality", "check", env["drifted"], "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        gate = doc["quality_gate"]
        assert gate["passed"] is False
        assert any(c["metric"] == "max_psi" and not c["passed"]
                   for c in gate["checks"])
        assert main(["quality", "check", env["drifted"],
                     "--format", "gha"]) == 1
        out = capsys.readouterr().out
        assert "::error" in out and "quality-drift" in out

    def test_no_quality_telemetry_is_exit_2(self, tmp_path, capsys):
        empty = _fabricated_run_dir(tmp_path / "no_quality", [
            {"seq": 0, "ts": 1.0, "kind": "run_started",
             "schema_version": 1, "stage": "train"},
            {"seq": 1, "ts": 2.0, "kind": "run_finished", "status": "ok"},
        ])
        with pytest.raises(SystemExit) as exc:
            main(["quality", "check", empty])
        assert exc.value.code == 2
        assert "no quality_metrics" in capsys.readouterr().out
        # A missing run dir is a plain usage failure too.
        with pytest.raises(SystemExit):
            main(["quality", "check", str(tmp_path / "missing")])

    def test_disjoint_baseline_labels_exit_2(self, env, tmp_path,
                                             capsys):
        other = _quality_run(tmp_path / "other", ece=0.1,
                             label="CNN_DE_Unbalanced")
        with pytest.raises(SystemExit) as exc:
            main(["quality", "check", other,
                  "--baseline", env["healthy"]])
        assert exc.value.code == 2
        assert "shares no quality_metrics run label" in \
            capsys.readouterr().out

    def test_summarize_renders_quality_sections(self, env, capsys):
        assert main(["telemetry", "summarize", env["drifted"]]) == 0
        out = capsys.readouterr().out
        assert "quality (calibration + uncertainty):" in out
        assert "drift (vs frozen quality_baseline):" in out
        assert "quality gate: FAILED" in out
        assert main(["telemetry", "summarize", env["drifted"],
                     "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["quality_metrics"][0]["ece"] is not None
        assert doc["drift_fingerprints"][0]["max_psi"] is not None
        assert doc["quality_gates"][-1]["passed"] is False


def _serve_drift_run(path, drift_events):
    """A fabricated serve run dir: run boundary + the given
    ``serve_drift`` event payloads (seq/ts/kind filled in)."""
    events = [{"seq": 0, "ts": 1.0, "kind": "run_started",
               "schema_version": 1, "stage": "serve"}]
    for i, payload in enumerate(drift_events):
        events.append({"seq": i + 1, "ts": 2.0 + i,
                       "kind": "serve_drift", **payload})
    events.append({"seq": len(events), "ts": 99.0,
                   "kind": "run_finished", "status": "ok"})
    return _fabricated_run_dir(path, events)


def _drift_event(*, tenant="default", max_psi, max_ks=0.0,
                 verdict="ok", final=True, **overrides):
    doc = {"tenant": tenant, "verdict": verdict, "windows": 256,
           "max_psi": max_psi, "max_ks": max_ks,
           "max_mean_shift": max_psi, "worst_channel": "ch1",
           "warn_psi": 0.1, "drift_psi": 0.2, "warn_ks": 0.1,
           "drift_ks": 0.2, "final": final}
    doc.update(overrides)
    return doc


class TestServeRunGating:
    """ISSUE 17 read side: `apnea-uq quality check` accepts a SERVE run
    directory — the online per-tenant ``serve_drift`` verdicts gate in
    place of batch-eval fingerprints, jax-free, same exit-code
    contract."""

    def test_drifted_serve_session_exits_1(self, tmp_path, capsys):
        run = _serve_drift_run(tmp_path / "drifted", [
            _drift_event(max_psi=0.85, max_ks=0.4, verdict="drift"),
        ])
        assert main(["quality", "check", run]) == 1
        out = capsys.readouterr().out
        assert "quality-serve-drift" in out
        assert "tenant default" in out
        # The drifted verdict landed in the run's own audit trail.
        gates = [e for e in telemetry.read_events(run)
                 if e["kind"] == "quality_gate"]
        assert gates[-1]["passed"] is False

    def test_clean_serve_session_exits_0(self, tmp_path):
        run = _serve_drift_run(tmp_path / "clean", [
            _drift_event(max_psi=0.02, max_ks=0.01, verdict="ok"),
        ])
        assert main(["quality", "check", run]) == 0

    def test_last_event_per_tenant_wins(self, tmp_path):
        """The gate reads each tenant's LAST event (append order): an
        early drifted re-score followed by a clean final flush is a
        recovered session, not a failure — and vice versa."""
        recovered = _serve_drift_run(tmp_path / "recovered", [
            _drift_event(max_psi=0.9, verdict="drift", final=False),
            _drift_event(max_psi=0.03, verdict="ok"),
        ])
        assert main(["quality", "check", recovered]) == 0
        worsened = _serve_drift_run(tmp_path / "worsened", [
            _drift_event(max_psi=0.03, verdict="ok", final=False),
            _drift_event(max_psi=0.9, verdict="drift"),
        ])
        assert main(["quality", "check", worsened]) == 1

    def test_event_thresholds_beat_cli_fallbacks(self, tmp_path):
        """Each event self-describes the thresholds it was scored with
        (per-tenant overrides included): the gate uses THOSE, so it can
        never disagree with the emitted verdict.  The CLI thresholds
        apply only to pre-threshold-field logs."""
        # A tight tenant: drift_psi 0.05 fails a PSI the CLI default
        # (0.2) would wave through.
        tight = _serve_drift_run(tmp_path / "tight", [
            _drift_event(max_psi=0.15, verdict="drift", drift_psi=0.05),
        ])
        assert main(["quality", "check", tight]) == 1
        # A loose tenant: drift_psi 0.5 passes a PSI the CLI default
        # would fail.
        loose = _serve_drift_run(tmp_path / "loose", [
            _drift_event(max_psi=0.3, verdict="ok", drift_psi=0.5),
        ])
        assert main(["quality", "check", loose]) == 0
        # No threshold fields on the event: the CLI flag is the bar.
        legacy = _serve_drift_run(tmp_path / "legacy", [
            {"tenant": "default", "verdict": "ok", "windows": 64,
             "max_psi": 0.15, "max_ks": 0.05, "final": True},
        ])
        assert main(["quality", "check", legacy]) == 0
        assert main(["quality", "check", legacy,
                     "--psi-threshold", "0.1"]) == 1

    def test_gate_boundary_matches_monitor_verdict(self, tmp_path):
        """value == drift threshold IS drift (the monitor's >= rule):
        the gate must fail it too, not pass on a strict <."""
        run = _serve_drift_run(tmp_path / "boundary", [
            _drift_event(max_psi=0.2, verdict="drift"),
        ])
        assert main(["quality", "check", run]) == 1

    def test_multi_tenant_worst_tenant_gates(self, tmp_path, capsys):
        run = _serve_drift_run(tmp_path / "tenants", [
            _drift_event(tenant="icu-3", max_psi=0.02, verdict="ok"),
            _drift_event(tenant="ward-b", max_psi=0.7, max_ks=0.5,
                         verdict="drift"),
        ])
        assert main(["quality", "check", run, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        checks = doc["quality_gate"]["checks"]
        by_label = {}
        for c in checks:
            by_label.setdefault(c["label"], []).append(c["passed"])
        assert all(by_label["tenant icu-3"])
        assert not all(by_label["tenant ward-b"])


class TestCompareQuality:
    def test_compare_gates_quality_ece_between_run_dirs(self, env,
                                                        tmp_path):
        """Acceptance: `telemetry compare` gates quality.<label>.ece
        across two run dirs — the real healthy run vs a fabricated
        worse one — lower-is-better with no direction flag."""
        healthy_qm = next(e for e in telemetry.read_events(env["healthy"])
                          if e["kind"] == "quality_metrics"
                          and e["label"] == "CNN_MCD_Unbalanced")
        worse = _quality_run(tmp_path / "worse",
                             ece=healthy_qm["ece"] * 2 + 0.1)
        comparison = compare_mod.compare_paths(env["healthy"], worse)
        regressed = {d.name for d in comparison.regressions}
        assert "quality.CNN_MCD_Unbalanced.ece" in regressed
        delta = next(d for d in comparison.deltas
                     if d.name == "quality.CNN_MCD_Unbalanced.ece")
        assert not delta.higher_better
        assert main(["telemetry", "compare", env["healthy"], worse]) == 1
        # Self-comparison stays clean.
        assert main(["telemetry", "compare", env["healthy"],
                     env["healthy"]]) == 0

    def test_quality_metrics_cross_proxy_boundary_unrefused(self,
                                                            tmp_path):
        """Acceptance: quality metrics are backend-independent — across
        the CPU-proxy boundary the backend-bound throughput is dropped
        but quality.<label>.* refuses NOTHING and still gates."""
        device = _quality_run(tmp_path / "device", ece=0.05)
        proxy_same = _quality_run(tmp_path / "proxy", ece=0.05,
                                  proxy=True, windows_per_s=3.0)
        comparison = compare_mod.compare_paths(device, proxy_same)
        assert comparison.candidate_proxy
        names = {d.name for d in comparison.deltas}
        assert {"quality.CNN_MCD_Unbalanced.ece",
                "quality.CNN_MCD_Unbalanced.mce",
                "quality.CNN_MCD_Unbalanced.brier"} <= names
        assert not any(n.startswith("quality.")
                       for n in comparison.skipped_backend_bound)
        # The backend-bound throughput IS refused...
        assert ("eval.CNN_MCD_Unbalanced.windows_per_s"
                in comparison.skipped_backend_bound)
        assert not comparison.regressions
        # ...and a miscalibrated proxy round still gates.
        proxy_worse = _quality_run(tmp_path / "proxy_worse", ece=0.4,
                                   proxy=True, windows_per_s=3.0)
        regressed = {d.name for d in compare_mod.compare_paths(
            device, proxy_worse).regressions}
        assert "quality.CNN_MCD_Unbalanced.ece" in regressed

    def test_drift_scores_gate_lower_is_better(self, env, tmp_path):
        comparison = compare_mod.compare_paths(env["healthy"],
                                               env["drifted"])
        regressed = {d.name for d in comparison.regressions}
        assert "drift.Unbalanced.max_psi" in regressed

    def test_trend_rounds_dir_sweeps_registry_runs(self, env, tmp_path,
                                                   capsys):
        """ISSUE 13 satellite: --rounds-dir pointed at a registry-like
        root sweeps <root>/runs/* run dirs, so quality history needs no
        hand-listed sources."""
        root = tmp_path / "ledger_root"
        runs = root / "runs"
        runs.mkdir(parents=True)
        _quality_run(runs / "eval-a", ece=0.05)
        _quality_run(runs / "eval-b", ece=0.06)
        assert main(["telemetry", "trend", "--rounds-dir", str(root),
                     "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        labels = [r["label"] for r in doc["rounds"]]
        assert labels == ["eval-a", "eval-b"]
        series = {m["name"]: m for m in doc["metrics"]}
        ece = series["quality.CNN_MCD_Unbalanced.ece"]
        assert ece["values"] == [0.05, 0.06]
        assert ece["higher_better"] is False

    def test_trend_runs_sweep_orders_chronologically(self, tmp_path,
                                                     capsys):
        """Run dirs sweep in run-START order, not name order: a shared
        series' 'latest' must be the newest run even when an earlier
        stage name sorts after it alphabetically."""
        root = tmp_path / "chrono_root"
        runs = root / "runs"
        runs.mkdir(parents=True)

        def run_at(name, ts, ece):
            _fabricated_run_dir(runs / name, [
                {"seq": 0, "ts": ts, "kind": "run_started",
                 "schema_version": 1, "stage": "eval"},
                {"seq": 1, "ts": ts + 1, "kind": "quality_metrics",
                 "label": "CNN_MCD_Unbalanced", "ece": ece},
                {"seq": 2, "ts": ts + 2, "kind": "run_finished",
                 "status": "ok"},
            ])

        # Alphabetical order (a-newest, z-oldest) contradicts time
        # order; the ledger must follow time.
        run_at("z-oldest", 100.0, 0.05)
        run_at("a-newest", 900.0, 0.30)
        assert main(["telemetry", "trend", "--rounds-dir", str(root),
                     "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [r["label"] for r in doc["rounds"]] == ["z-oldest",
                                                       "a-newest"]
        ece = next(m for m in doc["metrics"]
                   if m["name"] == "quality.CNN_MCD_Unbalanced.ece")
        assert ece["latest"] == 0.30 and ece["latest_round"] == "a-newest"
        assert ece["regressed"] is True  # latest worsened vs best=0.05

        # An APPENDED multi-run log (reused run dir) sorts by its
        # LATEST run's start — the run whose metrics it contributes —
        # not its oldest.
        reused = runs / "b-reused"
        run_at("b-reused", 50.0, 0.05)
        with open(os.path.join(reused, telemetry.EVENTS_FILENAME),
                  "a") as f:
            for e in ({"seq": 0, "ts": 2000.0, "kind": "run_started",
                       "schema_version": 1, "stage": "eval"},
                      {"seq": 1, "ts": 2001.0, "kind": "quality_metrics",
                       "label": "CNN_MCD_Unbalanced", "ece": 0.4},
                      {"seq": 2, "ts": 2002.0, "kind": "run_finished",
                       "status": "ok"}):
                f.write(json.dumps(e) + "\n")
        assert main(["telemetry", "trend", "--rounds-dir", str(root),
                     "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [r["label"] for r in doc["rounds"]] == [
            "z-oldest", "a-newest", "b-reused"]
        ece = next(m for m in doc["metrics"]
                   if m["name"] == "quality.CNN_MCD_Unbalanced.ece")
        assert ece["latest"] == 0.4 and ece["latest_round"] == "b-reused"

        # A --sources path the sweep also finds contributes ONE round.
        assert main(["telemetry", "trend", "--rounds-dir", str(root),
                     "--json", str(runs / "a-newest")]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [r["label"] for r in doc["rounds"]].count("a-newest") == 1

    def test_unwritable_run_dir_still_renders_verdict(self, env,
                                                      monkeypatch,
                                                      capsys):
        """The gate-event append is best-effort: a read-only run dir
        (CI artifact mount) must not cost the user the verdict."""
        from apnea_uq_tpu.telemetry import quality as quality_mod

        def denied(gate):
            raise PermissionError("read-only artifact mount")

        monkeypatch.setattr(quality_mod, "record_gate_event", denied)
        assert main(["quality", "check", env["drifted"]]) == 1
        out = capsys.readouterr().out
        assert "verdict not recorded" in out
        assert "quality-drift" in out  # the findings still rendered


def test_refreeze_logs_drift_vs_prior_baseline(tmp_path, capsys):
    """Re-running prepare re-freezes the baseline by design — but a
    drifted cohort must not be absorbed SILENTLY: the overwrite first
    scores the new sets against the prior baseline and logs the PSI."""
    from apnea_uq_tpu.data.prepare import PreparedDatasets, save_prepared

    rng = np.random.default_rng(5)

    def prepared(shift=0.0):
        x_test = (rng.normal(size=(60, 30, 2)) + shift).astype(np.float32)
        return PreparedDatasets(
            x_train=np.zeros((8, 30, 2), np.float32),
            y_train=np.zeros(8, np.int8),
            x_test=x_test,
            y_test=np.zeros(60, np.int8),
            patient_ids_test=np.array([f"P{i % 4}" for i in range(60)]),
            x_test_rus=None, y_test_rus=None,
        )

    registry = ArtifactRegistry(str(tmp_path / "reg"))
    save_prepared(prepared(), registry)
    capsys.readouterr()
    save_prepared(prepared(shift=5.0), registry)
    out = capsys.readouterr().out
    assert "quality_baseline re-freeze" in out
    assert "max_psi" in out
    # And the artifact now describes the new cohort.
    doc = registry.load_json(reg.QUALITY_BASELINE)
    assert set(doc["sets"]) == {reg.TEST_STD_UNBALANCED}


class TestQualityCheckJaxFree:
    def test_quality_check_runs_with_jax_poisoned(self, tmp_path,
                                                  capsys):
        """The read side must work on machines with no usable backend:
        poison jax/flax in sys.modules and run the real CLI check."""
        import subprocess
        import sys

        run_dir = _quality_run(tmp_path / "run", ece=0.05)
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['flax'] = None\n"
            "from apnea_uq_tpu.cli.main import main\n"
            f"rc = main(['quality', 'check', {run_dir!r}, "
            f"'--baseline', {run_dir!r}])\n"
            "raise SystemExit(rc)\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
