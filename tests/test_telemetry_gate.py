"""The regression gate + hardware-watch autopilot (ISSUE 3 tentpole,
pieces 3-4): ``telemetry compare`` turning a synthetic injected
regression into a nonzero exit (bench JSON and run-dir sources,
direction inference, per-metric thresholds), and ``telemetry watch``
running the evidence ritual on a mocked green probe — probe trail,
ritual_step events, saved stdout/stderr, exit-code contract."""

import glob
import json
import os
import time
import types

import pytest

from apnea_uq_tpu import telemetry
from apnea_uq_tpu.cli.main import main
from apnea_uq_tpu.telemetry import compare as compare_mod
from apnea_uq_tpu.telemetry import watch as watch_mod
from apnea_uq_tpu.telemetry.runlog import _ACTIVE


@pytest.fixture(autouse=True)
def _no_leaked_active_run():
    assert not _ACTIVE, f"active-run stack dirty on entry: {_ACTIVE}"
    yield
    leaked = list(_ACTIVE)
    _ACTIVE.clear()
    assert not leaked, f"test leaked active run logs: {leaked}"


def _bench_json(path, value, *, de_ratio=None):
    """A minimal BENCH_r*.json capture in the driver schema."""
    doc = {"metric": "mcd_t50_inference_throughput", "value": value,
           "unit": "windows/sec/chip", "vs_baseline": 1.0}
    if de_ratio is not None:
        doc["secondary"] = {"metric": "de_concurrent_speedup",
                            "value": de_ratio, "unit": "ratio"}
    with open(path, "w") as f:
        json.dump(doc, f)
    return str(path)


def _run_dir(path, *, peak_bytes, windows_per_s, runs=1):
    """A telemetry run dir whose events carry one HBM peak and one bench
    throughput; ``runs>1`` appends stale runs with garbage values first
    (the comparator must read the latest run only)."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, telemetry.EVENTS_FILENAME), "w") as f:
        for i in range(runs):
            latest = i == runs - 1
            events = [
                {"seq": 0, "ts": 1.0, "kind": "run_started",
                 "schema_version": 1, "stage": "bench",
                 "topology": {"platform": "tpu", "device_count": 8}},
                {"seq": 1, "ts": 2.0, "kind": "memory_profile",
                 "label": "ensemble_epoch",
                 "peak_bytes": peak_bytes if latest else 1},
                {"seq": 2, "ts": 3.0, "kind": "bench_throughput",
                 "metric": "mcd_t50_inference_throughput",
                 "windows_per_s": windows_per_s if latest else 10**9},
                {"seq": 3, "ts": 4.0, "kind": "run_finished",
                 "status": "ok"},
            ]
            for e in events:
                f.write(json.dumps(e) + "\n")
    return str(path)


class TestCompare:
    def test_injected_regression_gates_nonzero(self, tmp_path, capsys):
        """The ISSUE 3 acceptance path: a synthetic -10% throughput drop
        must flip the CLI exit code to 1."""
        base = _bench_json(tmp_path / "r05.json", 1000.0)
        cand = _bench_json(tmp_path / "r06.json", 900.0)
        comparison = compare_mod.compare_paths(base, cand)
        (delta,) = comparison.regressions
        assert delta.name == "mcd_t50_inference_throughput"
        assert delta.delta_pct == pytest.approx(-10.0)
        assert main(["telemetry", "compare", base, cand]) == 1
        out = capsys.readouterr().out
        assert "REGRESSED" in out and "regressions: 1" in out

    def test_improvement_and_within_threshold_exit_zero(self, tmp_path,
                                                        capsys):
        base = _bench_json(tmp_path / "b.json", 1000.0)
        # +30%: far past the threshold, but in the GOOD direction — a
        # faster candidate must never "regress" by being different.
        faster = _bench_json(tmp_path / "f.json", 1300.0)
        assert main(["telemetry", "compare", base, faster]) == 0
        assert "improved" in capsys.readouterr().out
        # -4%: worsening, but inside the default 5% threshold.
        close = _bench_json(tmp_path / "c.json", 960.0)
        assert main(["telemetry", "compare", base, close]) == 0
        assert "ok" in capsys.readouterr().out

    def test_run_dir_sources_and_bytes_direction(self, tmp_path):
        """Run-dir metrics gate too, with unit-inferred direction: an
        HBM peak GROWING is the regression (lower-is-better), and the
        latest run of an appended log is the one compared."""
        base = _run_dir(tmp_path / "base", peak_bytes=8 * 2**30,
                        windows_per_s=5000.0)
        cand = _run_dir(tmp_path / "cand", peak_bytes=10 * 2**30,
                        windows_per_s=5000.0, runs=3)
        comparison = compare_mod.compare_paths(base, cand)
        (delta,) = comparison.regressions
        assert delta.name == "memory.ensemble_epoch.peak_bytes"
        assert not delta.higher_better
        assert delta.delta_pct == pytest.approx(25.0)
        # A SHRINKING peak is an improvement, not a regression.
        slim = _run_dir(tmp_path / "slim", peak_bytes=6 * 2**30,
                        windows_per_s=5000.0)
        assert compare_mod.compare_paths(base, slim).regressions == []

    def test_per_metric_threshold_override(self, tmp_path):
        base = _bench_json(tmp_path / "b.json", 1000.0, de_ratio=4.0)
        cand = _bench_json(tmp_path / "c.json", 990.0, de_ratio=3.0)
        # DE speedup fell 25%: regression at the default 5%...
        assert main(["telemetry", "compare", base, cand]) == 1
        # ...but an explicit 30% band for that one metric absorbs it.
        assert main(["telemetry", "compare", base, cand,
                     "--metric-threshold", "de_concurrent_speedup=30"]) == 0
        # And a global loose threshold with a TIGHT per-metric override
        # still trips on the overridden metric alone.
        assert main(["telemetry", "compare", base, cand,
                     "--threshold-pct", "50",
                     "--metric-threshold", "de_concurrent_speedup=10"]) == 1

    def test_bad_threshold_spec_and_missing_inputs_exit_cleanly(
            self, tmp_path):
        base = _bench_json(tmp_path / "b.json", 1.0)
        with pytest.raises(SystemExit, match="NAME=PCT"):
            main(["telemetry", "compare", base, base,
                  "--metric-threshold", "oops"])
        with pytest.raises(SystemExit, match="not a number"):
            main(["telemetry", "compare", base, base,
                  "--metric-threshold", "x=fast"])
        with pytest.raises(SystemExit):
            main(["telemetry", "compare", base,
                  str(tmp_path / "missing.json")])
        empty = tmp_path / "not_a_run"
        empty.mkdir()
        with pytest.raises(SystemExit, match="events"):
            main(["telemetry", "compare", base, str(empty)])

    def test_disjoint_metric_sets_exit_2(self, tmp_path, capsys):
        """No metric in common = nothing gateable: the same exit-2
        usage-error contract as a bench_error capture (ISSUE 11: exit 2
        is reserved for 'no block comparable', exit 1 for real
        regressions)."""
        base = _bench_json(tmp_path / "b.json", 1000.0)
        cand = _run_dir(tmp_path / "cand", peak_bytes=1, windows_per_s=0)
        with pytest.raises(SystemExit) as exc:
            main(["telemetry", "compare", base, str(cand)])
        assert exc.value.code == 2
        assert "no common metrics" in capsys.readouterr().out

    def test_proxy_boundary_drops_backend_bound_metrics(self, tmp_path):
        """ISSUE 11: a CPU-proxy capture gates its relative metrics
        against a device round, but absolute throughput is refused
        across the proxy boundary — dropped and listed, never compared.
        """
        def v2(path, *, proxy, cold_vs_warm, throughput=1000.0):
            if proxy:
                head = {"metric": "bench_cpu_proxy", "value": 3,
                        "unit": "blocks", "vs_baseline": 0}
            else:
                head = {"metric": "mcd_t50_inference_throughput",
                        "value": throughput, "unit": "windows/sec/chip",
                        "vs_baseline": 10.0}
            head.update({
                "schema": 2, "proxy": proxy,
                "backend": {"platform": "cpu" if proxy else "tpu"},
                "blocks": {"compile": {"status": "ok", "seconds": 1.0}},
                "context": {"compile":
                            {"cold_vs_warm_total": cold_vs_warm}},
            })
            with open(path, "w") as f:
                json.dump(head, f)
            return str(path)

        device = v2(tmp_path / "device.json", proxy=False,
                    cold_vs_warm=4.0)
        proxy_same = v2(tmp_path / "proxy.json", proxy=True,
                        cold_vs_warm=4.0)
        comparison = compare_mod.compare_paths(device, proxy_same)
        assert comparison.candidate_proxy and not comparison.baseline_proxy
        # The device headline was dropped, not compared...
        assert ("mcd_t50_inference_throughput"
                in comparison.skipped_backend_bound)
        names = {d.name for d in comparison.deltas}
        assert "mcd_t50_inference_throughput" not in names
        # ...while the relative compile metric still gates.
        assert "compile.cold_vs_warm_total" in names
        assert main(["telemetry", "compare", device, proxy_same]) == 0
        proxy_worse = v2(tmp_path / "proxy_worse.json", proxy=True,
                         cold_vs_warm=2.0)
        assert main(["telemetry", "compare", device, proxy_worse]) == 1
        # Two device rounds compare the throughput normally.
        device_worse = v2(tmp_path / "device_worse.json", proxy=False,
                          cold_vs_warm=4.0, throughput=500.0)
        comparison = compare_mod.compare_paths(device, device_worse)
        assert comparison.skipped_backend_bound == []
        (reg,) = comparison.regressions
        assert reg.name == "mcd_t50_inference_throughput"

    def test_mcd_kernel_ratios_gate_across_proxy_boundary(self, tmp_path):
        """ISSUE 12: the `mcd_kernel` block's XLA-vs-Pallas and
        f32-vs-bf16 speedups are backend-INDEPENDENT relative metrics —
        they survive the proxy-boundary drop and gate like
        bootstrap.speedup, with higher-is-better direction."""
        def v2(path, *, proxy, xla_vs_pallas, f32_vs_bf16):
            doc = {
                "metric": ("bench_cpu_proxy" if proxy
                           else "mcd_t50_inference_throughput"),
                "value": 3 if proxy else 1000.0,
                "unit": "blocks" if proxy else "windows/sec/chip",
                "vs_baseline": 0 if proxy else 10.0,
                "schema": 2, "proxy": proxy,
                "backend": {"platform": "cpu" if proxy else "tpu"},
                "blocks": {"mcd_kernel": {"status": "ok", "seconds": 1.0}},
                "context": {"mcd_kernel": {
                    "xla_vs_pallas": xla_vs_pallas,
                    "f32_vs_bf16": f32_vs_bf16,
                    "pallas_engine": "xla" if proxy else "pallas",
                }},
            }
            with open(path, "w") as f:
                json.dump(doc, f)
            return str(path)

        device = v2(tmp_path / "device.json", proxy=False,
                    xla_vs_pallas=3.0, f32_vs_bf16=1.8)
        same = v2(tmp_path / "proxy_same.json", proxy=True,
                  xla_vs_pallas=3.0, f32_vs_bf16=1.8)
        comparison = compare_mod.compare_paths(device, same)
        names = {d.name for d in comparison.deltas}
        # The ratios crossed the proxy boundary instead of being
        # dropped as backend-bound...
        assert {"mcd_kernel.xla_vs_pallas",
                "mcd_kernel.f32_vs_bf16"} <= names
        assert not any(n.startswith("mcd_kernel")
                       for n in comparison.skipped_backend_bound)
        assert not comparison.regressions
        # ...and a shrunk speedup regresses (higher-is-better ratio).
        worse = v2(tmp_path / "worse.json", proxy=True,
                   xla_vs_pallas=1.0, f32_vs_bf16=1.8)
        regressed = {d.name for d in
                     compare_mod.compare_paths(device, worse).regressions}
        assert "mcd_kernel.xla_vs_pallas" in regressed

    def test_run_dir_proxy_mode_drops_shape_bound_metrics(self,
                                                          tmp_path):
        """A proxy bench run stamps bench_mode proxy:true into its own
        run dir; comparing it against a device run dir must drop the
        row-count-dependent data.* absolutes (smoke shapes vs device
        shapes) while relative metrics still gate."""
        def run_dir(path, *, proxy, load_s, hit):
            os.makedirs(path, exist_ok=True)
            events = [
                {"seq": 0, "ts": 1.0, "kind": "run_started",
                 "schema_version": 1, "stage": "bench"},
                {"seq": 1, "ts": 1.5, "kind": "bench_mode",
                 "proxy": proxy, "platform": "cpu" if proxy else "tpu"},
                {"seq": 2, "ts": 2.0, "kind": "data_load",
                 "key": "prepared", "load_s": load_s},
                {"seq": 3, "ts": 2.5, "kind": "compile_event",
                 "label": "mcd_predict_fused", "source": "store",
                 "hit": hit, "lower_s": 0.0, "compile_s": 0.0},
                {"seq": 4, "ts": 3.0, "kind": "run_finished",
                 "status": "ok"},
            ]
            with open(os.path.join(path, telemetry.EVENTS_FILENAME),
                      "w") as f:
                for e in events:
                    f.write(json.dumps(e) + "\n")
            return str(path)

        device = run_dir(tmp_path / "device", proxy=False, load_s=1.9,
                         hit=True)
        proxy = run_dir(tmp_path / "proxy", proxy=True, load_s=0.002,
                        hit=True)
        comparison = compare_mod.compare_paths(device, proxy)
        assert comparison.candidate_proxy
        assert "data.prepared.load_s" in comparison.skipped_backend_bound
        names = {d.name for d in comparison.deltas}
        assert "data.prepared.load_s" not in names
        assert "compile.hit_ratio" in names
        assert comparison.regressions == []
        # Two device run dirs still compare the data-plane cost.
        device2 = run_dir(tmp_path / "device2", proxy=False, load_s=4.0,
                          hit=True)
        comparison = compare_mod.compare_paths(device, device2)
        (reg,) = comparison.regressions
        assert reg.name == "data.prepared.load_s"

    def test_v2_error_payload_with_surviving_blocks_still_gates(
            self, tmp_path):
        """A watchdog-killed v2 capture folds its surviving progress
        into the bench_error payload; the survived primary must gate
        like any other capture (a hang after N good blocks reports N
        blocks — ISSUE 11 satellite 1)."""
        err = {"metric": "bench_error", "value": 0, "unit": "error",
               "vs_baseline": 0, "error": "watchdog fired", "schema": 2,
               "blocks": {"mcd": {"status": "ok", "seconds": 9.0}},
               "primary": {"metric": "mcd_t50_inference_throughput",
                           "value": 900.0, "unit": "windows/sec/chip"}}
        path = tmp_path / "killed.json"
        with open(path, "w") as f:
            json.dump(err, f)
        base = _bench_json(tmp_path / "base.json", 1000.0)
        comparison = compare_mod.compare_paths(base, str(path))
        (reg,) = comparison.regressions
        assert reg.name == "mcd_t50_inference_throughput"
        assert reg.delta_pct == pytest.approx(-10.0)

    def test_progress_file_wrapper_gates_the_primary_too(self, tmp_path):
        """A BENCH_PROGRESS_FILE capture wraps the driver blocks as
        {"primary": ..., "secondary": ...}; the comparator must unwrap
        it — extracting only the secondary would silently pass a
        regressed primary metric."""
        base = _bench_json(tmp_path / "printed.json", 1000.0, de_ratio=4.0)
        progress = tmp_path / "progress.json"
        with open(progress, "w") as f:
            json.dump({
                "primary": {"metric": "mcd_t50_inference_throughput",
                            "value": 500.0, "unit": "windows/sec/chip"},
                "secondary": {"metric": "de_concurrent_speedup",
                              "value": 4.0, "unit": "ratio"},
            }, f)
        comparison = compare_mod.compare_paths(base, str(progress))
        names = {d.name for d in comparison.deltas}
        assert {"mcd_t50_inference_throughput",
                "de_concurrent_speedup"} <= names
        (reg,) = comparison.regressions
        assert reg.name == "mcd_t50_inference_throughput"
        assert main(["telemetry", "compare", base, str(progress)]) == 1

    def test_bench_error_capture_is_exit_2_usage_error(self, tmp_path,
                                                       capsys):
        """ISSUE 6 satellite: a BENCH_*.json whose payload is a
        bench_error record must exit 2 with a clear "no comparable
        metrics in source" message — never extract bench_error=0 as a
        metric and report a clean exit-0 pass over it."""
        err_doc = {"metric": "bench_error", "value": 0, "unit": "error",
                   "vs_baseline": 0, "error": "TPU backend unavailable"}
        bare = tmp_path / "err.json"
        with open(bare, "w") as f:
            json.dump(err_doc, f)
        # The archived watch/driver capture shape wraps the parsed
        # stdout line under "parsed" (the repo's BENCH_r05.json).
        wrapped = tmp_path / "r05.json"
        with open(wrapped, "w") as f:
            json.dump({"n": 5, "cmd": "python bench.py", "rc": 2,
                       "tail": "...", "parsed": err_doc}, f)
        good = _bench_json(tmp_path / "good.json", 1000.0)
        for src in (str(bare), str(wrapped)):
            for argv in ([src, good], [good, src], [src, src]):
                with pytest.raises(SystemExit) as exc:
                    main(["telemetry", "compare", *argv])
                assert exc.value.code == 2, argv
            assert "no comparable metrics in source" in \
                capsys.readouterr().out
        # A parse-dead capture (parsed: null, the r03/r04 shape) is the
        # same usage error.
        dead = tmp_path / "r03.json"
        with open(dead, "w") as f:
            json.dump({"n": 3, "cmd": "python bench.py", "rc": 1,
                       "tail": "", "parsed": None}, f)
        with pytest.raises(SystemExit) as exc:
            main(["telemetry", "compare", str(dead), good])
        assert exc.value.code == 2

    def test_metric_free_run_dir_is_exit_2_usage_error(self, tmp_path,
                                                       capsys):
        """A run directory with events but nothing gateable (e.g. a
        train-only run) follows the same exit-2 contract as a
        bench_error capture — not an exit-1 'regression' from the
        no-common-metrics check."""
        run_dir = tmp_path / "train_only"
        os.makedirs(run_dir)
        with open(os.path.join(run_dir, telemetry.EVENTS_FILENAME),
                  "w") as f:
            for e in ({"seq": 0, "ts": 1.0, "kind": "run_started",
                       "schema_version": 1, "stage": "train"},
                      {"seq": 1, "ts": 2.0, "kind": "epoch", "epoch": 1,
                       "loss": 0.5},
                      {"seq": 2, "ts": 3.0, "kind": "run_finished",
                       "status": "ok"}):
                f.write(json.dumps(e) + "\n")
        good = _bench_json(tmp_path / "good.json", 1000.0)
        with pytest.raises(SystemExit) as exc:
            main(["telemetry", "compare", str(run_dir), good])
        assert exc.value.code == 2
        assert "no comparable metrics in source" in capsys.readouterr().out

    def test_parsed_wrapper_real_capture_gates_normally(self, tmp_path):
        """A real metric line under the watch-capture "parsed" wrapper
        (the repo's BENCH_r01/r02 shape) unwraps and gates like the bare
        driver line."""
        base = _bench_json(tmp_path / "printed.json", 1000.0)
        wrapped = tmp_path / "r01.json"
        with open(wrapped, "w") as f:
            json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                       "tail": "...",
                       "parsed": {"metric": "mcd_t50_inference_throughput",
                                  "value": 900.0,
                                  "unit": "windows/sec/chip",
                                  "vs_baseline": 1.0}}, f)
        comparison = compare_mod.compare_paths(base, str(wrapped))
        (reg,) = comparison.regressions
        assert reg.name == "mcd_t50_inference_throughput"
        assert reg.delta_pct == pytest.approx(-10.0)
        assert main(["telemetry", "compare", base, str(wrapped)]) == 1

    def test_archived_bench_r05_exits_2(self, capsys):
        """The repo's own BENCH_r05.json (a tunnel-outage bench_error
        capture) is the motivating fixture — gate it for real."""
        r05 = os.path.join(os.path.dirname(__file__), "..", "BENCH_r05.json")
        if not os.path.exists(r05):
            pytest.skip("archived BENCH_r05.json not present")
        with pytest.raises(SystemExit) as exc:
            main(["telemetry", "compare", r05, r05])
        assert exc.value.code == 2
        assert "bench_error" in capsys.readouterr().out

    def test_eval_d2h_bytes_gates_lower_is_better(self, tmp_path):
        """eval_predict d2h_bytes (the fused-reduction win) gates as a
        bytes metric: a candidate re-inflating the transfer regresses."""
        def run_with_d2h(path, d2h):
            os.makedirs(path, exist_ok=True)
            events = [
                {"seq": 0, "ts": 1.0, "kind": "run_started",
                 "schema_version": 1, "stage": "eval-mcd"},
                {"seq": 1, "ts": 2.0, "kind": "eval_predict",
                 "label": "CNN_MCD_Unbalanced", "windows_per_s": 5000.0,
                 "fused": d2h < 10**6, "d2h_bytes": d2h},
                {"seq": 2, "ts": 3.0, "kind": "run_finished",
                 "status": "ok"},
            ]
            with open(os.path.join(path, telemetry.EVENTS_FILENAME),
                      "w") as f:
                for e in events:
                    f.write(json.dumps(e) + "\n")
            return str(path)

        fused = run_with_d2h(tmp_path / "fused", 4 * 1024 * 4)
        full = run_with_d2h(tmp_path / "full", 50 * 1024 * 4)
        comparison = compare_mod.compare_paths(fused, full)
        (delta,) = comparison.regressions
        assert delta.name == "eval.CNN_MCD_Unbalanced.d2h_bytes"
        assert not delta.higher_better
        # The reverse direction (full -> fused) is an improvement.
        assert compare_mod.compare_paths(full, fused).regressions == []

    def test_one_sided_metrics_listed_never_regress(self, tmp_path):
        base = _bench_json(tmp_path / "b.json", 1000.0, de_ratio=4.0)
        cand = _bench_json(tmp_path / "c.json", 1000.0)  # no secondary
        comparison = compare_mod.compare_paths(base, cand)
        assert "de_concurrent_speedup" in comparison.only_in_baseline
        assert comparison.regressions == []

    def test_json_output_shape(self, tmp_path, capsys):
        base = _bench_json(tmp_path / "b.json", 1000.0)
        cand = _bench_json(tmp_path / "c.json", 800.0)
        assert main(["telemetry", "compare", base, cand, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressed"] is True
        # Two metrics: the throughput and its unchanged .vs_baseline.
        delta = next(d for d in doc["deltas"]
                     if d["name"] == "mcd_t50_inference_throughput")
        assert delta["regressed"] is True
        assert delta["delta_pct"] == pytest.approx(-20.0)

    def test_metric_direction_override_gates_unknown_units(self, tmp_path):
        """An unknown-unit lower-is-better metric (a future latency or
        loss scalar) defaults to higher-is-better and could never
        regress; --metric-direction NAME=lower closes that hole."""
        def score_json(path, value):
            with open(path, "w") as f:
                json.dump({"metric": "val_loss", "value": value,
                           "unit": "score"}, f)  # unknown unit
            return str(path)

        base = score_json(tmp_path / "b.json", 100.0)
        worse = score_json(tmp_path / "c.json", 150.0)
        # Default inference: higher-is-better, +50% looks like progress.
        assert main(["telemetry", "compare", base, worse]) == 0
        assert main(["telemetry", "compare", base, worse,
                     "--metric-direction", "val_loss=lower"]) == 1
        # And the override works in the permissive direction too.
        assert main(["telemetry", "compare", worse, base,
                     "--metric-direction", "val_loss=lower"]) == 0
        with pytest.raises(SystemExit, match="higher|lower"):
            main(["telemetry", "compare", base, worse,
                  "--metric-direction", "val_loss=down"])

    def test_zero_baseline_json_has_no_infinity_token(self, tmp_path,
                                                      capsys):
        """json.dumps(float('inf')) emits a bare `Infinity` no strict
        parser accepts; the undefined-percent case must serialize as
        null."""
        base = _bench_json(tmp_path / "b.json", 0.0)
        cand = _bench_json(tmp_path / "c.json", 5.0)
        assert main(["telemetry", "compare", base, cand, "--json"]) == 0
        out = capsys.readouterr().out
        assert "Infinity" not in out
        doc = json.loads(out)  # strict parse must succeed
        delta = next(d for d in doc["deltas"]
                     if d["name"] == "mcd_t50_inference_throughput")
        assert delta["delta_pct"] is None and not delta["regressed"]

    def test_zero_baseline_compares_by_sign(self):
        metrics = {"m": compare_mod.Metric("m", 0.0, "seconds", False)}
        worse = {"m": compare_mod.Metric("m", 3.0, "seconds", False)}
        (delta,) = compare_mod.compare_metrics(metrics, worse)
        assert delta.regressed and delta.delta_pct == float("inf")
        same = {"m": compare_mod.Metric("m", 0.0, "seconds", False)}
        (delta,) = compare_mod.compare_metrics(metrics, same)
        assert not delta.regressed

    def test_unit_direction_inference(self):
        assert compare_mod.unit_direction("windows/sec/chip")
        assert compare_mod.unit_direction("ratio")
        assert not compare_mod.unit_direction("seconds")
        assert not compare_mod.unit_direction("bytes")
        assert compare_mod.unit_direction(None)  # unknown: higher wins

    def test_name_direction_inference(self):
        """ISSUE 13 satellite: ece/mce/brier/psi/ks/drift as a metric
        NAME token gate lower-is-better with no --metric-direction."""
        for name in ("quality.CNN_MCD_Unbalanced.ece", "val_ece",
                     "quality.X.mce", "cohort_brier",
                     "drift.Unbalanced.max_psi", "drift.RUS.max_ks",
                     "input_drift_score"):
            assert compare_mod.name_direction(name) is False, name
        # Existing metric names carry none of the tokens — the unit
        # inference stays authoritative for them.
        for name in ("mcd_t50_inference_throughput", "bootstrap.speedup",
                     "compile.total_s", "data.prepared.load_s",
                     "audit.mcd_predict_fused.flops",
                     "eval.CNN_MCD_Unbalanced.d2h_bytes"):
            assert compare_mod.name_direction(name) is None, name
        # And substrings never false-trigger: the token must stand
        # alone ("checksum" contains neither `ks` nor `psi` as tokens).
        assert compare_mod.name_direction("checksum_verify_s") is None
        assert compare_mod.name_direction("epsilon_sweep") is None
        # metric_direction: the name inference WINS over the unit.
        assert compare_mod.metric_direction("val_ece",
                                            "windows/sec") is False
        assert compare_mod.metric_direction("throughput",
                                            "windows/sec") is True

    def test_quality_named_metric_gates_without_direction_flag(
            self, tmp_path):
        """Golden for the name-based direction: a driver-schema capture
        whose metric is named val_ece (unknown unit) regresses when it
        RISES, with no --metric-direction flag — the hole the
        unknown-unit default left for calibration scores."""
        def ece_json(path, value):
            with open(path, "w") as f:
                json.dump({"metric": "val_ece", "value": value,
                           "unit": "score"}, f)
            return str(path)

        base = ece_json(tmp_path / "b.json", 0.05)
        worse = ece_json(tmp_path / "c.json", 0.09)
        assert main(["telemetry", "compare", base, worse]) == 1
        assert main(["telemetry", "compare", worse, base]) == 0
        # An explicit override still wins over the name inference.
        assert main(["telemetry", "compare", base, worse,
                     "--metric-direction", "val_ece=higher"]) == 0


def _green_probe(timeout_s):
    return True, "ok"


def _fake_runner(records, rc_by_name=None, hang=(), stdout_by_name=None):
    """A subprocess.run stand-in that records each ritual invocation;
    steps named in ``hang`` raise TimeoutExpired like a tunnel-flap
    hang hitting the step's timeout; ``stdout_by_name`` overrides a
    step's stdout (e.g. a bench result payload)."""
    import subprocess

    rc_by_name = rc_by_name or {}
    stdout_by_name = stdout_by_name or {}

    def runner(argv, cwd=None, env=None, capture_output=None, text=None,
               timeout=None):
        if "pytest" in argv:
            name = "tpu_tests"
        elif "trend" in argv:
            name = "trend"
        else:
            name = "bench"
        records.append({"name": name, "argv": argv, "cwd": cwd,
                        "env": env, "timeout": timeout})
        if name in hang:
            raise subprocess.TimeoutExpired(argv, timeout,
                                            output=f"{name} partial\n")
        return types.SimpleNamespace(
            returncode=rc_by_name.get(name, 0),
            stdout=stdout_by_name.get(name, f"{name} stdout\n"),
            stderr="")

    return runner


class TestWatch:
    def test_green_probe_runs_evidence_ritual(self, tmp_path):
        """The ISSUE 3 acceptance path: a mocked green probe must
        execute the ritual into a fresh run dir, with the probe trail
        and per-step exit codes as telemetry."""
        records = []
        rc = watch_mod.watch(str(tmp_path), probe=_green_probe,
                             runner=_fake_runner(records), budget_s=60.0)
        assert rc == 0
        (run_dir,) = glob.glob(str(tmp_path / "runs" / "watch-*"))
        events = telemetry.read_events(run_dir)
        kinds = [e["kind"] for e in events]
        assert kinds.count("probe") == 1
        assert "probe_green" in kinds
        steps = [e for e in events if e["kind"] == "ritual_step"]
        assert [s["name"] for s in steps] == ["bench", "tpu_tests",
                                              "trend"]
        assert all(s["returncode"] == 0 for s in steps)
        assert all(s["passed"] is True for s in steps)
        assert events[-1] == {**events[-1], "kind": "run_finished",
                              "status": "ok"}
        # The bench step lands its capture INSIDE the watch run dir, the
        # TPU-gated tests get their env switch, and the closing trend
        # snapshot ingests the bench run dir as its extra source.
        bench, tests, trend = records
        assert bench["env"]["BENCH_RUN_DIR"].startswith(run_dir)
        assert bench["env"]["BENCH_PROGRESS_FILE"].startswith(run_dir)
        assert bench["cwd"] == watch_mod._REPO_ROOT
        assert tests["env"]["APNEA_UQ_TEST_TPU"] == "1"
        assert "-k" in tests["argv"] and "on_tpu" in tests["argv"]
        assert trend["argv"][-1] == os.path.join(run_dir, "bench")
        assert "telemetry" in trend["argv"] and "trend" in trend["argv"]
        # Each step's stdout is preserved next to its event.
        for step in steps:
            path = os.path.join(run_dir, step["stdout_path"])
            with open(path) as f:
                assert f"{step['name']} stdout" in f.read()

    def test_failing_step_does_not_stop_ritual(self, tmp_path):
        # A red bench (no parseable payload, rc 1) must not stop the
        # later steps.
        records = []
        rc = watch_mod.watch(
            str(tmp_path), probe=_green_probe,
            runner=_fake_runner(records, {"bench": 1}), budget_s=60.0)
        assert rc == 1
        assert [r["name"] for r in records] == ["bench", "tpu_tests",
                                                "trend"]
        (run_dir,) = glob.glob(str(tmp_path / "runs" / "watch-*"))
        events = telemetry.read_events(run_dir)
        rcs = [e["returncode"] for e in events
               if e["kind"] == "ritual_step"]
        assert rcs == [1, 0, 0]
        assert events[-1]["status"] == "error"

    def test_bench_step_gates_on_per_block_statuses(self, tmp_path):
        """ISSUE 11 tentpole piece 4: a bench that exited nonzero but
        printed a v2 payload with surviving ok blocks is a PASSED step
        (partial results are evidence), with the block counts on its
        ritual_step event."""
        payload = json.dumps({
            "metric": "bench_partial", "value": 2, "unit": "blocks",
            "vs_baseline": 0, "schema": 2, "proxy": True,
            "blocks": {"compile": {"status": "ok", "seconds": 1.0},
                       "data_plane": {"status": "ok", "seconds": 0.1},
                       "mcd": {"status": "error", "error_tail": "boom"}},
        })
        records = []
        rc = watch_mod.watch(
            str(tmp_path), probe=_green_probe,
            runner=_fake_runner(records, {"bench": 3},
                                stdout_by_name={"bench": payload + "\n"}),
            skip_tests=True, budget_s=60.0)
        assert rc == 0  # bench passed on blocks, trend passed on rc
        (run_dir,) = glob.glob(str(tmp_path / "runs" / "watch-*"))
        events = telemetry.read_events(run_dir)
        bench_step = next(e for e in events if e["kind"] == "ritual_step"
                          and e["name"] == "bench")
        assert bench_step["returncode"] == 3
        assert bench_step["passed"] is True
        assert bench_step["blocks_ok"] == 2
        assert bench_step["blocks_error"] == 1
        assert bench_step["proxy"] is True
        assert events[-1]["status"] == "ok"
        # An all-dead payload does NOT pass the step.
        dead = json.dumps({"metric": "bench_error", "value": 0,
                           "unit": "error", "vs_baseline": 0,
                           "schema": 2, "blocks": {}})
        records = []
        rc = watch_mod.watch(
            str(tmp_path), probe=_green_probe,
            runner=_fake_runner(records, {"bench": 2},
                                stdout_by_name={"bench": dead + "\n"}),
            skip_tests=True, budget_s=60.0)
        assert rc == 1

    def test_hung_step_times_out_instead_of_hanging_watch(self, tmp_path):
        """A tunnel flap AFTER the green probe hangs jax.devices() inside
        the tpu_tests subprocess; the step timeout turns that into a
        failed step (partial output preserved), never a hung watch."""
        records = []
        rc = watch_mod.watch(
            str(tmp_path), probe=_green_probe,
            runner=_fake_runner(records, hang=("tpu_tests",)),
            budget_s=60.0)
        assert rc == 1
        assert records[0]["timeout"] == 7200.0  # bench's step budget
        assert records[1]["timeout"] == 3600.0
        assert records[2]["timeout"] == 600.0   # trend snapshot
        (run_dir,) = glob.glob(str(tmp_path / "runs" / "watch-*"))
        events = telemetry.read_events(run_dir)
        hung = next(e for e in events if e["kind"] == "ritual_step"
                    and e["name"] == "tpu_tests")
        assert hung["timed_out"] is True and hung["returncode"] == -1
        with open(os.path.join(run_dir, hung["stdout_path"])) as f:
            assert "tpu_tests partial" in f.read()

    def test_missing_ritual_files_fail_fast_before_the_wait(self, tmp_path):
        # A site-packages install (no bench.py next to the package) must
        # fail in seconds, not after a 24h probe wait.
        def no_probe(timeout_s):  # pragma: no cover - must not run
            raise AssertionError("probe must not run when preflight fails")

        rc = watch_mod.watch(str(tmp_path / "out"), probe=no_probe,
                             repo_root=str(tmp_path / "not_a_checkout"),
                             budget_s=60.0)
        assert rc == 2
        assert not glob.glob(str(tmp_path / "out" / "runs" / "*"))

    def test_skip_tests_runs_bench_and_trend(self, tmp_path):
        records = []
        assert watch_mod.watch(str(tmp_path), probe=_green_probe,
                               runner=_fake_runner(records),
                               skip_tests=True, budget_s=60.0) == 0
        assert [r["name"] for r in records] == ["bench", "trend"]

    def test_expired_budget_exits_2_without_a_run_dir(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setattr(time, "sleep", lambda s: None)

        def never_green(timeout_s):
            return False, "UNAVAILABLE: flapping tunnel"

        def no_ritual(*a, **k):  # pragma: no cover - must not run
            raise AssertionError("ritual must not run without green")

        rc = watch_mod.watch(str(tmp_path), probe=never_green,
                             runner=no_ritual, budget_s=0.2)
        assert rc == 2
        # Exit 2 mirrors bench's init-retry exhaustion, and no empty
        # evidence dir is left behind to look like a capture.
        assert not glob.glob(str(tmp_path / "runs" / "*"))

    def test_wait_for_green_backoff_schedule(self, monkeypatch):
        # The schedule bench.py's init retry pinned: 20s, then x1.6.
        sleeps = []
        monkeypatch.setattr(time, "sleep", sleeps.append)
        outcomes = iter([(False, "red"), (False, "red"), (True, "ok")])
        attempts_seen = []
        green, attempts, last = watch_mod.wait_for_green(
            600.0, probe=lambda t: next(outcomes),
            on_attempt=lambda n, g, d: attempts_seen.append((n, g)))
        assert green and attempts == 3 and last == "ok"
        assert sleeps == [20.0, 32.0]
        assert attempts_seen == [(1, False), (2, False), (3, True)]

    def test_probe_backend_green_on_cpu(self):
        # The real probe: jax.devices() in a budgeted subprocess — on
        # the CPU suite backend it must come back green.
        green, detail = watch_mod.probe_backend(probe_timeout_s=120.0)
        assert green and detail == "ok"

    def test_cli_watch_wires_probe_and_ritual(self, tmp_path, monkeypatch,
                                              capsys):
        records = []
        monkeypatch.setattr(watch_mod, "probe_backend", _green_probe)
        monkeypatch.setattr(watch_mod, "subprocess",
                            types.SimpleNamespace(
                                run=_fake_runner(records)))
        assert main(["telemetry", "watch", "--out", str(tmp_path),
                     "--budget-secs", "60", "--skip-tests"]) == 0
        assert [r["name"] for r in records] == ["bench", "trend"]
        out = capsys.readouterr().out
        assert "backend GREEN" in out
        assert "bench finished rc=0" in out

    def test_telemetry_watch_name_is_always_the_submodule(self):
        """`telemetry.watch` must resolve to the watch MODULE on every
        access path (attribute and from-import), never flip to the
        watch() function depending on import order; the lazy function
        exports from it keep working."""
        import types as types_mod

        from apnea_uq_tpu import telemetry

        assert isinstance(telemetry.watch, types_mod.ModuleType)
        assert telemetry.watch is watch_mod
        assert telemetry.wait_for_green is watch_mod.wait_for_green
        assert telemetry.probe_backend is watch_mod.probe_backend
        assert "watch" not in telemetry.__all__

    def test_evidence_ritual_steps_are_parameterized(self, tmp_path):
        steps = watch_mod.evidence_ritual_steps(str(tmp_path))
        assert [s.name for s in steps] == ["bench", "tpu_tests", "trend"]
        bench = steps[0]
        assert bench.argv[1].endswith("bench.py")
        assert bench.env["BENCH_RUN_DIR"] == str(tmp_path / "bench")
        assert bench.payload_json is True
        trend = steps[-1]
        assert trend.argv[-1] == str(tmp_path / "bench")
        no_tests = watch_mod.evidence_ritual_steps(str(tmp_path),
                                                   skip_tests=True)
        assert [s.name for s in no_tests] == ["bench", "trend"]

    def test_bench_payload_summary_shapes(self):
        v2 = json.dumps({"metric": "m", "proxy": True,
                         "blocks": {"a": {"status": "ok"},
                                    "b": {"status": "error"}}})
        assert watch_mod.bench_payload_summary(f"noise\n{v2}\n") == {
            "payload_metric": "m", "proxy": True,
            "blocks_ok": 1, "blocks_error": 1}
        # v1 line: parseable, zero blocks.
        v1 = json.dumps({"metric": "m", "value": 1.0})
        assert watch_mod.bench_payload_summary(v1)["blocks_ok"] == 0
        # No JSON at all: None (exit code stays the verdict).
        assert watch_mod.bench_payload_summary("bench stdout\n") is None
