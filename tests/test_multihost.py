"""Multi-host (multi-process) ensemble training over a GLOBAL device mesh.

The reference has no distributed backend at all (SURVEY §2.3: no NCCL/
MPI/Horovod anywhere); the framework's comm story is JAX collectives over
whatever fabric connects the mesh — ICI within a TPU slice, DCN across
hosts, and Gloo on this CPU test rig.  This test launches TWO processes
with 4 virtual devices each, assembles the 8-device global platform via
``jax.distributed``, trains the ensemble over a global (2, 4) mesh
spanning both processes, and asserts both processes see identical
histories that match the single-process run on the same 8 devices —
the multi-host path is the same program, just laid over two hosts.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


class TestHostValues:
    """utils/multihost.py::host_values — both sides of the
    addressability fork, without needing a second process."""

    def test_fully_addressable_fast_path_converts_in_place(self):
        import jax.numpy as jnp

        from apnea_uq_tpu.utils import multihost

        tree = {"a": jnp.arange(4.0), "b": (jnp.ones((2, 3)), 5)}
        out = multihost.host_values(tree)
        assert isinstance(out["a"], np.ndarray)
        np.testing.assert_array_equal(out["a"], np.arange(4.0))
        np.testing.assert_array_equal(out["b"][0], np.ones((2, 3)))
        # Plain host values ride along untouched (np.asarray of 5).
        assert out["b"][1] == 5

    def test_non_addressable_tree_routes_through_process_allgather(
            self, monkeypatch):
        """A single leaf that is not fully addressable must push the
        WHOLE tree through ONE tiled process_allgather (lockstep
        contract), converted to NumPy on the way out."""
        from jax.experimental import multihost_utils

        from apnea_uq_tpu.utils import multihost

        class ShardedLeaf:
            is_fully_addressable = False

        calls = []

        def fake_allgather(tree, tiled=False):
            calls.append((tree, tiled))
            return {"sharded": np.arange(3.0), "local": np.ones(2)}

        monkeypatch.setattr(multihost_utils, "process_allgather",
                            fake_allgather)
        tree = {"sharded": ShardedLeaf(), "local": np.ones(2)}
        out = multihost.host_values(tree)
        assert len(calls) == 1
        assert calls[0][0] is tree and calls[0][1] is True
        np.testing.assert_array_equal(out["sharded"], np.arange(3.0))
        assert isinstance(out["sharded"], np.ndarray)

    def test_leaves_without_the_attribute_count_as_addressable(self):
        from apnea_uq_tpu.utils import multihost

        out = multihost.host_values({"x": [1.0, 2.0]})
        np.testing.assert_array_equal(out["x"], np.asarray([1.0, 2.0]))

    def test_is_primary_single_process(self):
        from apnea_uq_tpu.utils.multihost import is_primary

        assert is_primary() is True


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow  # two full interpreter+backend boots; minutes of wall
@pytest.mark.skipif(
    os.environ.get("APNEA_UQ_SKIP_MULTIHOST") == "1",
    reason="multi-process test disabled",
)
def test_two_process_training_matches_single_process():
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # A failed/timed-out worker must not orphan its peer: the survivor
        # would sit blocked in a collective barrier holding the port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    assert outs[0]["mesh"] == {"ensemble": 2, "data": 4}
    # Both processes observed the same global training run AND the same
    # mesh-sharded evaluation (predictions allgathered across processes).
    np.testing.assert_allclose(outs[0]["loss"], outs[1]["loss"], rtol=1e-6)
    np.testing.assert_allclose(outs[0]["val_loss"], outs[1]["val_loss"],
                               rtol=1e-6)
    np.testing.assert_allclose(outs[0]["de_pred_sum"], outs[1]["de_pred_sum"],
                               rtol=1e-6)
    assert outs[0]["de_accuracy"] == outs[1]["de_accuracy"]
    np.testing.assert_allclose(outs[0]["mcd_pred_sum"],
                               outs[1]["mcd_pred_sum"], rtol=1e-6)
    assert outs[0]["mcd_det_accuracy"] == outs[1]["mcd_det_accuracy"]
    # Host-streamed MCD and DE over the process-spanning mesh: both
    # processes assembled identical streamed predictions (worker also
    # asserts streamed == in-HBM in-process).
    np.testing.assert_allclose(outs[0]["mcd_streamed_sum"],
                               outs[1]["mcd_streamed_sum"], rtol=1e-6)
    np.testing.assert_allclose(outs[0]["de_streamed_sum"],
                               outs[1]["de_streamed_sum"], rtol=1e-6)

    # And the 2-host global mesh trains the SAME models as one process
    # with all 8 devices (same data, same mesh shape, same RNG streams).
    from apnea_uq_tpu.config import EnsembleConfig, ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D
    from apnea_uq_tpu.parallel import fit_ensemble, make_mesh

    model = AlarconCNN1D(ModelConfig(
        features=(8, 8), kernel_sizes=(5, 3), dropout_rates=(0.1, 0.1)
    ))
    rng = np.random.default_rng(2025)
    y = rng.integers(0, 2, 256)
    x = rng.normal(size=(256, 60, 4)).astype(np.float32)
    x[:, :, 0] += (y * 2.0 - 1.0)[:, None] * 1.5
    res = fit_ensemble(
        model, x, y.astype(np.float32),
        EnsembleConfig(num_members=2, num_epochs=2, batch_size=64,
                       validation_split=0.25),
        mesh=make_mesh(num_members=2),
    )
    np.testing.assert_allclose(res.history["loss"], outs[0]["loss"],
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(res.history["val_loss"], outs[0]["val_loss"],
                               rtol=2e-4, atol=2e-5)
