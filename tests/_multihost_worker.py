"""Worker process for the multi-host ensemble-training test.

Run as: python _multihost_worker.py <process_id> <num_processes> <port>

Each process owns 4 virtual CPU devices; jax.distributed assembles them
into one global platform (collectives ride Gloo — the CPU stand-in for
the ICI/DCN fabric a TPU pod uses), and fit_ensemble trains over the
global (ensemble, data) mesh exactly as it would single-process.  Prints
one JSON line with the training history for the parent test to compare.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    process_id, num_processes, port = (int(a) for a in sys.argv[1:4])
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=num_processes,
        process_id=process_id,
    )

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from apnea_uq_tpu.config import EnsembleConfig, ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D
    from apnea_uq_tpu.parallel import fit_ensemble, make_mesh

    model = AlarconCNN1D(ModelConfig(
        features=(8, 8), kernel_sizes=(5, 3), dropout_rates=(0.1, 0.1)
    ))
    # Same data on every process (the replicated-dataset DP design).
    rng = np.random.default_rng(2025)
    y = rng.integers(0, 2, 256)
    x = rng.normal(size=(256, 60, 4)).astype(np.float32)
    x[:, :, 0] += (y * 2.0 - 1.0)[:, None] * 1.5
    y = y.astype(np.float32)

    mesh = make_mesh(num_members=2)  # global (2, 4) spanning both processes
    assert len(jax.devices()) == 4 * num_processes
    assert len(jax.local_devices()) == 4
    cfg = EnsembleConfig(num_members=2, num_epochs=2, batch_size=64,
                         validation_split=0.25)
    res = fit_ensemble(model, x, y, cfg, mesh=mesh)

    # Mesh-sharded DE + MCD inference and the full eval drivers across
    # processes: predictions (and the MCD deterministic sanity probe) come
    # back through the multihost-safe allgather.
    from apnea_uq_tpu.config import UQConfig
    from apnea_uq_tpu.uq import run_de_analysis, run_mcd_analysis

    de = run_de_analysis(
        model, res.stacked_variables(), x[:64], y[:64],
        config=UQConfig(n_bootstrap=10, inference_batch_size=32),
        mesh=mesh, detailed=False,
    )
    assert de.predictions.shape == (2, 64)
    mcd = run_mcd_analysis(
        model, res.member_variables(0), x[:64], y[:64],
        config=UQConfig(mc_passes=4, n_bootstrap=10, mcd_batch_size=32,
                        inference_batch_size=32),
        mesh=mesh, detailed=False, sanity_check=True, seed=3,
    )
    assert mcd.predictions.shape == (4, 64)
    assert mcd.deterministic_classification is not None

    # Host-streamed MCD over the PROCESS-SPANNING mesh — the scenario the
    # streamed chunk-placement/rounding exists for: no process addresses
    # every device, so chunks MUST device_put shard-wise and results come
    # back through the multihost-safe fetch.  batch_size=22 does not
    # divide the 4-wide data axis and rounds to 24; the streamed run must
    # equal the in-HBM mesh run at the same nominal batch size.
    from apnea_uq_tpu.uq import mc_dropout_predict, mc_dropout_predict_streaming
    from apnea_uq_tpu.utils import prng
    from apnea_uq_tpu.utils.multihost import host_values

    skey = prng.stochastic_key(7)
    streamed = mc_dropout_predict_streaming(
        model, res.member_variables(0), x[:64], n_passes=3, batch_size=22,
        key=skey, mesh=mesh,
    )
    hbm = host_values(mc_dropout_predict(
        model, res.member_variables(0), x[:64], n_passes=3, batch_size=22,
        key=skey, mesh=mesh,
    ))
    assert streamed.shape == (3, 64)
    np.testing.assert_allclose(streamed, hbm, rtol=1e-6, atol=1e-7)
    # ... and the streamed DE path over the same process-spanning mesh.
    from apnea_uq_tpu.uq import ensemble_predict, ensemble_predict_streaming

    de_streamed = ensemble_predict_streaming(
        model, res.stacked_variables(), x[:64], batch_size=22, mesh=mesh,
    )
    de_hbm = host_values(ensemble_predict(
        model, res.stacked_variables(), x[:64], batch_size=22, mesh=mesh,
    ))
    assert de_streamed.shape == (2, 64)
    np.testing.assert_allclose(de_streamed, de_hbm, rtol=1e-6, atol=1e-7)

    print(json.dumps({
        "process_id": process_id,
        "mesh": dict(mesh.shape),
        "loss": np.asarray(res.history["loss"]).tolist(),
        "val_loss": np.asarray(res.history["val_loss"]).tolist(),
        "best_epoch": np.asarray(res.best_epoch).tolist(),
        "de_pred_sum": float(de.predictions.sum()),
        "de_accuracy": de.classification["accuracy"],
        "mcd_pred_sum": float(mcd.predictions.sum()),
        "mcd_det_accuracy": mcd.deterministic_classification["accuracy"],
        "mcd_streamed_sum": float(streamed.sum()),
        "de_streamed_sum": float(de_streamed.sum()),
    }))


if __name__ == "__main__":
    main()
