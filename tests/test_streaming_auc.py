"""On-device streaming (histogram) AUC + accuracy (ops/streaming_auc.py)
and their integration into fit(track_metrics=True) — the TPU-native
equivalent of the reference's Keras compile metrics
(cnn_baseline_train.py:100-102)."""

import jax
import numpy as np
import pytest

from apnea_uq_tpu.config import ModelConfig, TrainConfig
from apnea_uq_tpu.evaluation.classification import roc_auc
from apnea_uq_tpu.models import AlarconCNN1D
from apnea_uq_tpu.ops.streaming_auc import (
    accuracy_from_counts,
    auc_from_histograms,
    empty_metric_state,
    metric_results,
    metric_update,
)
from apnea_uq_tpu.training import create_train_state, fit


@pytest.fixture
def rng():
    return np.random.default_rng(0)


TINY = ModelConfig(features=(8, 12), kernel_sizes=(5, 3),
                   dropout_rates=(0.1, 0.1))


def _stream(probs, labels, mask=None, batches=4):
    """Feed (probs, labels) through the metric carry in several batches."""
    state = empty_metric_state()
    if mask is None:
        mask = np.ones_like(probs, np.float32)
    for p, l, m in zip(np.array_split(probs, batches),
                       np.array_split(labels, batches),
                       np.array_split(mask, batches)):
        state = metric_update(state, p, l, m)
    return metric_results(state)


class TestStreamingOps:
    def test_matches_exact_auc(self, rng):
        probs = rng.uniform(0, 1, 4000).astype(np.float32)
        labels = (rng.uniform(size=4000) < 0.35).astype(np.float32)
        acc, auc = _stream(probs, labels)
        exact = roc_auc(labels, probs)
        # 512-bin quantization: error bounded well below 1e-2 here.
        assert float(auc) == pytest.approx(exact, abs=5e-3)
        assert float(acc) == pytest.approx(
            np.mean((probs >= 0.5) == labels), abs=1e-6
        )

    def test_batching_invariance(self, rng):
        probs = rng.uniform(0, 1, 1000).astype(np.float32)
        labels = (rng.uniform(size=1000) < 0.5).astype(np.float32)
        a = _stream(probs, labels, batches=1)
        b = _stream(probs, labels, batches=7)
        assert float(a[1]) == pytest.approx(float(b[1]), abs=1e-6)
        assert float(a[0]) == pytest.approx(float(b[0]), abs=1e-6)

    def test_perfect_and_inverted_separation(self):
        probs = np.concatenate([np.full(50, 0.9), np.full(50, 0.1)]).astype(np.float32)
        labels = np.concatenate([np.ones(50), np.zeros(50)]).astype(np.float32)
        _, auc = _stream(probs, labels, batches=2)
        assert float(auc) == pytest.approx(1.0)
        _, auc_inv = _stream(probs, 1.0 - labels, batches=2)
        assert float(auc_inv) == pytest.approx(0.0)

    def test_single_class_nan(self):
        probs = np.asarray([0.2, 0.8], np.float32)
        _, auc = _stream(probs, np.ones(2, np.float32), batches=1)
        assert np.isnan(float(auc))

    def test_mask_excludes_rows(self, rng):
        probs = rng.uniform(0, 1, 200).astype(np.float32)
        labels = (rng.uniform(size=200) < 0.5).astype(np.float32)
        mask = np.zeros(200, np.float32)
        mask[:120] = 1.0
        masked = _stream(probs, labels, mask=mask, batches=3)
        trimmed = _stream(probs[:120], labels[:120], batches=3)
        assert float(masked[1]) == pytest.approx(float(trimmed[1]), abs=1e-6)
        assert float(masked[0]) == pytest.approx(float(trimmed[0]), abs=1e-6)

    def test_nonfinite_probs_excluded(self, rng):
        # A diverged model's NaN/inf scores must not be binned as if they
        # were real probabilities — they drop out of both AUC and accuracy.
        probs = rng.uniform(0, 1, 200).astype(np.float32)
        labels = (rng.uniform(size=200) < 0.5).astype(np.float32)
        dirty = probs.copy()
        dirty[::5] = np.nan
        dirty[1::7] = np.inf
        bad = np.isnan(dirty) | np.isinf(dirty)
        polluted = _stream(dirty, labels, batches=3)
        clean = _stream(probs[~bad], labels[~bad], batches=3)
        assert float(polluted[1]) == pytest.approx(float(clean[1]), abs=1e-6)
        assert float(polluted[0]) == pytest.approx(float(clean[0]), abs=1e-6)

    def test_ties_in_one_bin_give_half(self):
        # All scores identical -> every pos/neg pair ties -> AUC 0.5.
        probs = np.full(100, 0.42, np.float32)
        labels = np.concatenate([np.ones(40), np.zeros(60)]).astype(np.float32)
        _, auc = _stream(probs, labels, batches=2)
        assert float(auc) == pytest.approx(0.5)

    def test_empty_state_results_nan(self):
        acc, auc = metric_results(empty_metric_state())
        assert np.isnan(float(acc)) and np.isnan(float(auc))
        assert np.isnan(float(accuracy_from_counts(np.zeros(2))))
        assert np.isnan(float(auc_from_histograms(np.zeros((2, 8)))))


def _fit_data(rng, n=256):
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    x = rng.normal(size=(n, 60, 4)).astype(np.float32)
    x[:, :, 0] += (2 * y[:, None] - 1) * 1.5  # separable via channel 0
    return x, y


class TestFitIntegration:
    def test_history_keys_and_values(self, rng):
        # Same scale as test_training.test_learns_separable_problem — a
        # shorter run can sit in an inverted early transient where AUC
        # legitimately reads ~0.
        x, y = _fit_data(rng, n=1024)
        model = AlarconCNN1D(TINY)
        state = create_train_state(model, jax.random.key(0))
        cfg = TrainConfig(num_epochs=12, batch_size=128,
                          validation_split=0.1,
                          early_stopping_patience=20, track_metrics=True)
        res = fit(model, state, x, y, cfg)
        for k in ("accuracy", "auc", "val_accuracy", "val_auc"):
            assert len(res.history[k]) == len(res.history["loss"])
        # Separable data: final-epoch val AUC must beat chance clearly.
        assert res.history["val_auc"][-1] > 0.8
        assert res.history["accuracy"][-1] > 0.7

    def test_tracking_does_not_change_training(self, rng):
        x, y = _fit_data(rng)
        model = AlarconCNN1D(TINY)
        state = create_train_state(model, jax.random.key(0))
        cfg_off = TrainConfig(num_epochs=2, batch_size=64,
                              validation_split=0.25,
                              early_stopping_patience=10)
        cfg_on = TrainConfig(num_epochs=2, batch_size=64,
                             validation_split=0.25,
                             early_stopping_patience=10, track_metrics=True)
        a = fit(model, state, x, y, cfg_off)
        b = fit(model, state, x, y, cfg_on)
        np.testing.assert_allclose(a.history["loss"], b.history["loss"],
                                   rtol=1e-6)
        for la, lb in zip(jax.tree.leaves(a.state.params),
                          jax.tree.leaves(b.state.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_streaming_path_matches_in_hbm(self, rng):
        x, y = _fit_data(rng)
        model = AlarconCNN1D(TINY)
        state = create_train_state(model, jax.random.key(0))
        cfg = TrainConfig(num_epochs=2, batch_size=64, validation_split=0.25,
                          early_stopping_patience=10, track_metrics=True)
        a = fit(model, state, x, y, cfg)
        b = fit(model, state, x, y, cfg, streaming=True)
        for k in ("accuracy", "auc", "val_accuracy", "val_auc"):
            np.testing.assert_allclose(a.history[k], b.history[k],
                                       rtol=1e-5, atol=1e-6)


class TestEnsembleIntegration:
    @pytest.mark.slow  # 4 ensemble-epoch compiles; the baseline trainer's
    # metric integration + streamed parity runs by default (TestFitIntegration)
    def test_history_shapes_and_streaming_parity(self, rng):
        from apnea_uq_tpu.config import EnsembleConfig
        from apnea_uq_tpu.parallel import fit_ensemble

        x, y = _fit_data(rng, n=128)
        model = AlarconCNN1D(TINY)
        # 2 epochs on purpose: epoch-2 parity catches a streaming metric
        # carry that fails to reset between epochs.
        cfg = EnsembleConfig(num_members=2, num_epochs=2, batch_size=64,
                             validation_split=0.25,
                             early_stopping_patience=10, track_metrics=True)
        res = fit_ensemble(model, x, y, cfg)
        for k in ("accuracy", "auc", "val_accuracy", "val_auc"):
            assert res.history[k].shape == res.history["loss"].shape
            assert np.isfinite(res.history[k]).all()
            assert (res.history[k] >= 0).all() and (res.history[k] <= 1).all()
        # Streamed path must report identical metrics (same members, same
        # batches, same streams).
        stream = fit_ensemble(model, x, y, cfg, streaming=True)
        for k in ("accuracy", "auc", "val_accuracy", "val_auc"):
            np.testing.assert_allclose(res.history[k], stream.history[k],
                                       rtol=1e-5, atol=1e-6)

    def test_off_by_default_history_unchanged(self, rng):
        from apnea_uq_tpu.config import EnsembleConfig
        from apnea_uq_tpu.parallel import fit_ensemble

        x, y = _fit_data(rng, n=128)
        model = AlarconCNN1D(TINY)
        cfg = EnsembleConfig(num_members=2, num_epochs=1, batch_size=64,
                             validation_split=0.25,
                             early_stopping_patience=10)
        res = fit_ensemble(model, x, y, cfg)
        assert set(res.history) == {"loss", "val_loss"}
