"""Fleet telemetry (ISSUE 18): replica-aware SLO aggregation.

Covers the jax-free read side — per-replica stats (torn tails, appended
multi-run logs, pre-digest replica rebuilds), the merged rollup's exact
counters and digest-bound percentiles, outlier flagging at a
configurable spread threshold, the per-tenant worst-verdict drift
rollup, rollup persistence (registry artifact + appended
``fleet_rollup`` event), `telemetry compare` gating of ``fleet.*``
metrics, the `apnea-uq telemetry fleet` CLI exit codes/formats, the
capacity sweep's knee detection, and the ISSUE 18 acceptance bar: three
REAL serve replica subprocesses sharing one warm program store, merged
within the documented digest bound of the pooled raw request latencies,
with an injected-slow replica flagged through the imbalance ratio and
two rollups gated against each other on ``fleet.p99_ms``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from apnea_uq_tpu.telemetry.digest import REL_ERROR_BOUND, LatencyDigest
from apnea_uq_tpu.telemetry.fleet import (
    DEFAULT_SPREAD_THRESHOLD,
    FleetRollup,
    NoFleetTelemetry,
    build_rollup,
    fleet_result,
    record_rollup,
    render_fleet,
    replica_stats,
    rollup_data,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ fixtures --


def _slo_event(seq, *, replica_id, lats, buckets=None, final=True,
               interval_s=4.0, windows=None, extra=None):
    digest = LatencyDigest("s")
    digest.extend(lats)
    event = {
        "seq": seq, "ts": 2.0 + seq, "kind": "serve_slo",
        "replica_id": replica_id,
        "requests": len(lats), "windows": windows or len(lats),
        "batches": max(1, len(lats) // 4),
        "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 3),
        "p95_ms": round(float(np.percentile(lats, 95)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 3),
        "windows_per_s": round((windows or len(lats)) / interval_s, 3),
        "queue_wait_mean_s": 0.002, "pad_waste": 0.25,
        "interval_s": interval_s, "final": final,
        "digest": digest.to_payload(),
        "buckets": buckets or {},
    }
    if extra:
        event.update(extra)
    return event


def _bucket_row(batches, windows, pad_rows, device_ms):
    digest = LatencyDigest("ms")
    digest.extend(device_ms)
    return {
        "batches": batches, "windows": windows, "pad_rows": pad_rows,
        "pad_waste": 0.0, "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
        "digest": digest.to_payload(),
    }


def _write_events(run_dir, events, torn_tail=False):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "events.jsonl"), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        if torn_tail:
            f.write('{"seq": 999, "kind": "serve_slo", "requ')


def _replica_dir(tmp_path, name, lats, **kw):
    d = str(tmp_path / name)
    _write_events(d, [_slo_event(0, replica_id=name, lats=lats, **kw)])
    return d


# ------------------------------------------------------- replica stats --


class TestReplicaStats:
    def test_missing_dir_and_no_serve_slo_raise(self, tmp_path):
        with pytest.raises(NoFleetTelemetry, match="no events"):
            replica_stats(str(tmp_path / "nope"))
        d = str(tmp_path / "train_run")
        _write_events(d, [{"seq": 0, "kind": "epoch", "loss": 0.5}])
        with pytest.raises(NoFleetTelemetry, match="serve_slo"):
            replica_stats(d)

    def test_last_snapshot_wins_and_torn_tail_tolerated(self, tmp_path):
        d = str(tmp_path / "r0")
        stale = _slo_event(0, replica_id="r0", lats=[0.1] * 4, final=False)
        fresh = _slo_event(1, replica_id="r0", lats=[0.1] * 8)
        _write_events(d, [stale, fresh], torn_tail=True)
        rep = replica_stats(d)
        assert rep.requests == 8  # the cumulative LAST snapshot
        assert rep.replica_id == "r0"
        assert rep.digest_source == "serve_slo"
        assert rep.digest.count == 8

    def test_appended_multi_run_log_uses_latest_run(self, tmp_path):
        d = str(tmp_path / "r0")
        events = (
            [{"seq": 0, "kind": "run_started", "stage": "serve"},
             _slo_event(1, replica_id="old", lats=[9.0] * 4)]
            + [{"seq": 2, "kind": "run_started", "stage": "serve"},
               _slo_event(3, replica_id="new", lats=[0.05] * 6)]
        )
        _write_events(d, events)
        rep = replica_stats(d)
        assert rep.replica_id == "new"
        assert rep.requests == 6
        assert rep.earlier_runs == 1

    def test_pre_digest_log_rebuilds_from_serve_request(self, tmp_path):
        # Old replicas (pre-ISSUE-18) carry no digest payload: the
        # stats rebuild one from the per-request events, same values.
        d = str(tmp_path / "r0")
        lats = [0.01, 0.02, 0.04, 0.08]
        slo = _slo_event(0, replica_id="r0", lats=lats)
        del slo["digest"]
        reqs = [{"seq": i + 1, "kind": "serve_request",
                 "request_id": f"q{i}", "latency_s": v}
                for i, v in enumerate(lats)]
        _write_events(d, reqs + [slo])
        rep = replica_stats(d)
        assert rep.digest_source == "serve_request"
        assert rep.digest.count == 4
        assert rep.digest.percentile(50) == pytest.approx(
            float(np.percentile(lats, 50)), rel=REL_ERROR_BOUND)


# ------------------------------------------------------------- rollup --


class TestBuildRollup:
    def test_counters_sum_exactly_and_throughput_adds(self, tmp_path):
        rng = np.random.default_rng(0)
        dirs = [_replica_dir(tmp_path, f"r{i}",
                             rng.lognormal(-3.5, 0.4, 50))
                for i in range(3)]
        rollup = build_rollup(dirs)
        assert rollup.requests == 150
        assert rollup.windows == 150
        assert rollup.digest.count == 150
        assert rollup.windows_per_s == pytest.approx(3 * 12.5)
        assert rollup.requests_per_s == pytest.approx(3 * 12.5)

    def test_percentiles_within_bound_of_pooled_samples(self, tmp_path):
        rng = np.random.default_rng(1)
        parts = [rng.lognormal(-3.5, 0.6, 200) * s
                 for s in (1.0, 1.3, 2.0)]
        dirs = [_replica_dir(tmp_path, f"r{i}", part)
                for i, part in enumerate(parts)]
        rollup = build_rollup(dirs)
        pooled = np.concatenate(parts)
        for q, got in ((50, rollup.p50_ms), (95, rollup.p95_ms),
                       (99, rollup.p99_ms)):
            want = float(np.percentile(pooled, q)) * 1e3
            assert got == pytest.approx(
                want, rel=REL_ERROR_BOUND + 1e-4), f"p{q}"

    def test_outlier_flagged_at_configurable_spread(self, tmp_path):
        dirs = [
            _replica_dir(tmp_path, "fast0", [0.010] * 20),
            _replica_dir(tmp_path, "fast1", [0.012] * 20),
            _replica_dir(tmp_path, "slow", [0.200] * 20),
        ]
        rollup = build_rollup(dirs)  # default threshold 2.0
        assert rollup.outliers == ["slow"]
        assert rollup.imbalance_ratio >= 2.0
        flagged = {r.replica_id: r.outlier for r in rollup.replicas}
        assert flagged == {"fast0": False, "fast1": False, "slow": True}
        # A huge threshold un-flags it; the ratio itself is unchanged.
        relaxed = build_rollup(dirs, spread_threshold=50.0)
        assert relaxed.outliers == []
        assert relaxed.imbalance_ratio == rollup.imbalance_ratio
        findings = fleet_result(rollup).findings
        assert [f.rule for f in findings] == ["fleet-outlier-replica"]
        assert findings[0].path == dirs[2]

    def test_single_replica_never_outliers(self, tmp_path):
        rollup = build_rollup([_replica_dir(tmp_path, "r0", [0.1] * 8)])
        assert rollup.imbalance_ratio == pytest.approx(1.0)
        assert rollup.outliers == []

    def test_spread_threshold_and_empty_validation(self, tmp_path):
        with pytest.raises(NoFleetTelemetry):
            build_rollup([])
        d = _replica_dir(tmp_path, "r0", [0.1] * 4)
        with pytest.raises(ValueError, match="spread threshold"):
            build_rollup([d], spread_threshold=1.0)
        assert DEFAULT_SPREAD_THRESHOLD == 2.0

    def test_bucket_tables_merge_exactly(self, tmp_path):
        d0 = str(tmp_path / "r0")
        d1 = str(tmp_path / "r1")
        _write_events(d0, [_slo_event(
            0, replica_id="r0", lats=[0.01] * 8,
            buckets={"16": _bucket_row(4, 50, 14, [5.0] * 4)})])
        _write_events(d1, [_slo_event(
            0, replica_id="r1", lats=[0.01] * 8,
            buckets={"16": _bucket_row(2, 30, 2, [50.0] * 2),
                     "64": _bucket_row(1, 60, 4, [80.0])})])
        rollup = build_rollup([d0, d1])
        assert list(rollup.buckets) == ["16", "64"]
        b16 = rollup.buckets["16"]
        assert b16["batches"] == 6 and b16["windows"] == 80
        # pad_waste recomputed from merged counters: 16/(6*16).
        assert b16["pad_waste"] == pytest.approx(16 / 96, abs=1e-4)
        # merged device-time digest spans both replicas' regimes
        assert b16["p99_ms"] == pytest.approx(50.0, rel=REL_ERROR_BOUND)

    def test_drift_rollup_worst_verdict_wins(self, tmp_path):
        def with_drift(name, verdict, psi):
            d = str(tmp_path / name)
            _write_events(d, [
                _slo_event(0, replica_id=name, lats=[0.01] * 4),
                {"seq": 1, "kind": "serve_drift", "tenant": "P1",
                 "verdict": verdict, "windows": 100, "max_psi": psi,
                 "max_ks": 0.01},
                {"seq": 2, "kind": "serve_drift", "tenant": "P2",
                 "verdict": "ok", "windows": 50, "max_psi": 0.01,
                 "max_ks": 0.005},
            ])
            return d

        dirs = [with_drift("r0", "ok", 0.02),
                with_drift("r1", "drift", 0.9)]
        rollup = build_rollup(dirs)
        assert rollup.drift["P1"]["verdict"] == "drift"
        assert rollup.drift["P1"]["max_psi"] == pytest.approx(0.9)
        assert rollup.drift["P1"]["replicas"] == {"r0": "ok",
                                                  "r1": "drift"}
        assert rollup.drift["P2"]["verdict"] == "ok"
        findings = fleet_result(rollup).findings
        assert "fleet-drift" in {f.rule for f in findings}
        text = render_fleet(rollup)
        assert "[P1] drift" in text and "r1=drift" in text

    def test_trace_ledger_rides_rollup_and_flags_lost_exemplars(
            self, tmp_path):
        """ISSUE 20: the serve_slo `trace` ledger surfaces per replica
        — a replica whose over_budget_traced trails its over_budget
        lost exemplar waterfalls and is flagged in the rollup table."""
        healthy = _replica_dir(
            tmp_path, "r0", [0.01] * 8,
            extra={"trace": {"completed": 8, "traced": 2, "slow_ms": 100,
                             "over_budget": 2, "over_budget_traced": 2}})
        lossy = _replica_dir(
            tmp_path, "r1", [0.01] * 8,
            extra={"trace": {"completed": 8, "traced": 1, "slow_ms": 100,
                             "over_budget": 3, "over_budget_traced": 1}})
        rollup = build_rollup([healthy, lossy])
        by_id = {r.replica_id: r for r in rollup.replicas}
        assert by_id["r0"].trace["over_budget"] == 2
        assert by_id["r1"].trace["over_budget_traced"] == 1
        text = render_fleet(rollup)
        flagged = [ln for ln in text.splitlines()
                   if "MISSING-EXEMPLARS" in ln]
        assert len(flagged) == 1 and "r1" in flagged[0]
        # The ledger rides the JSON document too.
        data = rollup_data(rollup)
        reps = {r["replica_id"]: r for r in data["replicas"]}
        assert reps["r1"]["trace"]["over_budget"] == 3


# ------------------------------------------- persistence and compare --


class TestRecordAndCompare:
    def _rollup_dir(self, tmp_path, tag, scale):
        rng = np.random.default_rng(42)
        dirs = [_replica_dir(tmp_path, f"{tag}-r{i}",
                             rng.lognormal(-3.5, 0.5, 120) * scale)
                for i in range(2)]
        out = str(tmp_path / f"{tag}-rollup")
        record_rollup(build_rollup(dirs), out)
        return out

    def test_record_rollup_artifact_and_event(self, tmp_path):
        out = self._rollup_dir(tmp_path, "a", 1.0)
        doc = json.load(open(os.path.join(out, "fleet_rollup.json")))
        assert len(doc["replicas"]) == 2
        assert doc["digest"]["n"] == 240
        events = [json.loads(line) for line in
                  open(os.path.join(out, "events.jsonl"))]
        kinds = [e["kind"] for e in events]
        # Audit-trail contract: appended events, no new run_started.
        assert "run_started" not in kinds
        rollup_events = [e for e in events if e["kind"] == "fleet_rollup"]
        assert len(rollup_events) == 1
        assert rollup_events[0]["replicas"] == 2
        assert rollup_events[0]["requests"] == 240
        manifest = json.load(open(os.path.join(out, "manifest.json")))
        assert "fleet_rollup" in json.dumps(manifest)

    def test_compare_gates_fleet_p99_across_two_rollups(self, tmp_path):
        from apnea_uq_tpu.telemetry.compare import (
            compare_paths,
            load_source,
        )

        fast = self._rollup_dir(tmp_path, "fast", 1.0)
        slow = self._rollup_dir(tmp_path, "slow", 2.0)
        metrics, facts = load_source(fast)
        assert facts["kind"] == "run_dir"
        assert "fleet.p99_ms" in metrics
        assert metrics["fleet.p99_ms"].backend_bound
        # imbalance_ratio: "ratio" would unit-infer higher-better; the
        # extraction must pin lower-better explicitly.
        assert metrics["fleet.imbalance_ratio"].higher_better is False
        assert metrics["fleet.pad_waste"].backend_bound is False
        comp = compare_paths(fast, slow)
        worse = {d.name for d in comp.deltas if d.regressed}
        assert "fleet.p99_ms" in worse
        # And the other direction improves.
        back = compare_paths(slow, fast)
        better = {d.name for d in back.deltas if d.improved}
        assert "fleet.p99_ms" in better

    def test_trend_ingests_rollup_dir_as_extra_source(self, tmp_path):
        from apnea_uq_tpu.telemetry import trend

        out = self._rollup_dir(tmp_path, "t", 1.0)
        point = trend.load_round(out)
        assert point.status == "ok"
        traj = trend.build_trajectory([point])
        names = {m.name for m in traj.metrics}
        assert "fleet.p99_ms" in names
        assert "fleet.windows_per_s" in names


# ---------------------------------------------------------------- CLI --


class TestFleetCLI:
    def _main(self, argv, capsys):
        from apnea_uq_tpu.cli.main import main

        code = main(argv)
        return code, capsys.readouterr().out

    def test_text_json_and_exit_codes(self, tmp_path, capsys):
        rng = np.random.default_rng(5)
        dirs = [_replica_dir(tmp_path, f"r{i}",
                             rng.lognormal(-3.5, 0.4, 40))
                for i in range(2)]
        out_dir = str(tmp_path / "rollup")
        code, out = self._main(
            ["telemetry", "fleet", *dirs, "--out", out_dir], capsys)
        assert code == 0
        assert "fleet: 2 replica(s)" in out
        assert os.path.exists(os.path.join(out_dir, "fleet_rollup.json"))
        code, out = self._main(
            ["telemetry", "fleet", *dirs, "--json"], capsys)
        assert code == 0
        doc = json.loads(out)
        assert len(doc["fleet_rollup"]["replicas"]) == 2
        assert doc["summary"]["findings"] == 0

    def test_outlier_exits_one_and_gha_format(self, tmp_path, capsys):
        dirs = [_replica_dir(tmp_path, "fast", [0.01] * 20),
                _replica_dir(tmp_path, "fast2", [0.011] * 20),
                _replica_dir(tmp_path, "slow", [0.5] * 20)]
        code, out = self._main(
            ["telemetry", "fleet", *dirs, "--format", "gha"], capsys)
        assert code == 1
        assert "::error" in out and "fleet-outlier-replica" in out
        # Relaxing the spread threshold clears the finding.
        code, _ = self._main(
            ["telemetry", "fleet", *dirs, "--spread-threshold", "60"],
            capsys)
        assert code == 0

    def test_non_telemetry_dir_exits_two(self, tmp_path, capsys):
        empty = str(tmp_path / "not_a_run")
        os.makedirs(empty)
        with pytest.raises(SystemExit) as exc:
            self._main(["telemetry", "fleet", empty], capsys)
        assert exc.value.code == 2


# -------------------------------------------------- capacity knee math --


class TestCapacityKnee:
    def _knee(self):
        sys.path.insert(0, REPO)
        try:
            from bench import capacity_knee
        finally:
            sys.path.remove(REPO)
        return capacity_knee

    def test_ratio_knee_is_first_saturated_cell(self):
        capacity_knee = self._knee()
        cells = [
            {"offered_rps": 4.0, "achieved_ratio": 1.01, "p99_ms": 50.0},
            {"offered_rps": 8.0, "achieved_ratio": 0.97, "p99_ms": 90.0},
            {"offered_rps": 16.0, "achieved_ratio": 0.80, "p99_ms": 400.0},
            {"offered_rps": 32.0, "achieved_ratio": 0.40, "p99_ms": 900.0},
        ]
        knee, reason = capacity_knee(cells)
        assert knee == 16.0
        assert "0.8" in reason and "0.95" in reason

    def test_budget_knee_and_no_knee(self):
        capacity_knee = self._knee()
        cells = [
            {"offered_rps": 4.0, "achieved_ratio": 1.0, "p99_ms": 50.0},
            {"offered_rps": 8.0, "achieved_ratio": 0.99, "p99_ms": 300.0},
        ]
        assert capacity_knee(cells) == (None, None)
        knee, reason = capacity_knee(cells, p99_budget_ms=200.0)
        assert knee == 8.0 and "budget" in reason
        assert capacity_knee([], p99_budget_ms=100.0) == (None, None)

    def test_capacity_metrics_refused_across_proxy_boundary(self, tmp_path):
        # The proxy contract: knee rate and peak throughput are
        # backend-bound absolutes — a proxy round must not gate them
        # against a device round; the base achieved ratio still gates.
        from apnea_uq_tpu.telemetry.compare import compare_paths

        def doc(proxy, knee, ratio):
            return {
                "metric": "bench_cpu_proxy" if proxy else "x_throughput",
                "value": 2 if proxy else 100.0,
                "unit": "blocks" if proxy else "windows/sec",
                "vs_baseline": 0, "schema": 2, "proxy": proxy,
                "backend": {"platform": "cpu" if proxy else "tpu",
                            "requested": "cpu-proxy" if proxy else "tpu"},
                "blocks": {"capacity": {"status": "ok", "seconds": 9.0}},
                "context": {"capacity": {
                    "cells": [{"offered_rps": 4.0, "achieved_rps": 4.0,
                               "achieved_ratio": ratio,
                               "windows_per_s": 12.0, "p99_ms": 80.0,
                               "imbalance_ratio": 1.0}],
                    "knee_offered_rps": knee,
                    "peak_windows_per_s": 12.0}},
            }

        device = tmp_path / "BENCH_device.json"
        proxy = tmp_path / "BENCH_proxy.json"
        device.write_text(json.dumps(doc(False, 32.0, 1.0)))
        proxy.write_text(json.dumps(doc(True, 4.0, 0.99)))
        comp = compare_paths(str(device), str(proxy))
        names = {d.name for d in comp.deltas}
        assert "capacity.knee_offered_rps" not in names
        assert "capacity.peak_windows_per_s" not in names
        assert "capacity.base_achieved_ratio" in names


# --------------------------------- acceptance: real replica processes --


def _subprocess_env(tmp_path):
    """Clean replica-subprocess environment: CPU backend, ONE shared
    program store + XLA cache for the whole fleet (the multi-replica
    warm contract under test)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_COMPILATION_CACHE_DIR",
                        "APNEA_UQ_XLA_CACHE_DIR",
                        "APNEA_UQ_PROGRAM_STORE_DIR",
                        "APNEA_UQ_REPLICA_ID",
                        "XLA_FLAGS")
           and not k.startswith("BENCH_")}
    env["JAX_PLATFORMS"] = "cpu"
    env["APNEA_UQ_PROGRAM_STORE_DIR"] = str(tmp_path / "program-store")
    env["APNEA_UQ_XLA_CACHE_DIR"] = str(tmp_path / "xla-cache")
    return env


def test_fleet_acceptance_three_replicas(tmp_path):
    """ISSUE 18 acceptance: three REAL serve replica subprocesses
    (python -m apnea_uq_tpu.serving.replica) sharing one warm program
    store, one of them degraded with an injected per-batch sleep.  The
    merged rollup's percentiles land within the documented digest bound
    of np.percentile over the POOLED raw serve_request latencies, the
    slow replica is flagged through the imbalance ratio, and two
    rollups (fast-pair baseline vs full-fleet candidate) gate
    fleet.p99_ms through `telemetry compare`."""
    from apnea_uq_tpu import telemetry
    from apnea_uq_tpu.cli.main import main as cli_main
    from apnea_uq_tpu.telemetry.compare import compare_paths

    env = _subprocess_env(tmp_path)
    run_dirs = [str(tmp_path / f"rep{i}") for i in range(3)]

    def replica_cmd(i, run_dir):
        cmd = [sys.executable, "-m", "apnea_uq_tpu.serving.replica",
               "--run-dir", run_dir, "--requests", "10",
               "--passes", "2", "--arrival", "poisson",
               "--rate", "20", "--seed", str(i),
               # ISSUE 20: 1-in-5 baseline stream + tail-based
               # exemplars — every request over 250ms keeps its
               # waterfall, so the degraded replica can't hide.
               "--trace-every", "5", "--trace-slow-ms", "250"]
        if i == 2:
            cmd += ["--slow-ms", "500"]  # the degraded replica
        return cmd

    # Warm-up pays the compiles into the SHARED store; the fleet's
    # request paths then acquire store hits.
    warm = subprocess.run(
        [sys.executable, "-m", "apnea_uq_tpu.serving.replica",
         "--run-dir", str(tmp_path / "warmup"), "--requests", "2",
         "--passes", "2"],
        cwd=REPO, env=dict(env, APNEA_UQ_REPLICA_ID="warmup"),
        capture_output=True, text=True, timeout=600)
    assert warm.returncode == 0, warm.stdout[-3000:]

    procs = [subprocess.Popen(
        replica_cmd(i, d), cwd=REPO,
        env=dict(env, APNEA_UQ_REPLICA_ID=f"replica-{i}"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i, d in enumerate(run_dirs)]
    for proc in procs:
        out, _ = proc.communicate(timeout=600)
        assert proc.returncode == 0, out[-3000:]

    # Every replica stamped its identity on the serving events.
    for i, d in enumerate(run_dirs):
        slos = [e for e in telemetry.read_events(d)
                if e["kind"] == "serve_slo"]
        assert slos and slos[-1]["replica_id"] == f"replica-{i}"
        assert slos[-1]["digest"]["n"] == 10

    rollup = build_rollup(run_dirs)
    assert rollup.requests == 30

    # The digest-bound contract against POOLED RAW latencies.
    pooled = [e["latency_s"]
              for d in run_dirs
              for e in telemetry.read_events(d)
              if e["kind"] == "serve_request"]
    assert len(pooled) == 30
    for q, got in ((50, rollup.p50_ms), (95, rollup.p95_ms),
                   (99, rollup.p99_ms)):
        want = float(np.percentile(pooled, q)) * 1e3
        assert got == pytest.approx(want, rel=REL_ERROR_BOUND + 1e-3), (
            f"p{q}: digest {got} vs pooled numpy {want}")

    # The injected 500ms-per-batch replica is the imbalance outlier.
    assert rollup.outliers == ["replica-2"]
    assert rollup.imbalance_ratio >= DEFAULT_SPREAD_THRESHOLD
    assert any(r.outlier for r in rollup.replicas
               if r.replica_id == "replica-2")

    # Two persisted rollups gate through compare: the fast pair as
    # baseline, the full fleet (carrying the slow replica) regresses
    # fleet.p99_ms.
    fast_dir = str(tmp_path / "rollup-fast")
    full_dir = str(tmp_path / "rollup-full")
    record_rollup(build_rollup(run_dirs[:2]), fast_dir)
    record_rollup(rollup, full_dir)
    comp = compare_paths(fast_dir, full_dir)
    regressed = {d.name for d in comp.deltas if d.regressed}
    assert "fleet.p99_ms" in regressed

    # And the CLI agrees end to end: exit 1, the outlier named.
    code = cli_main(["telemetry", "fleet", *run_dirs])
    assert code == 1

    # --- ISSUE 20 acceptance: the cross-replica trace merge attributes
    # the fleet tail to the degraded replica's SERVICE phase, span ids
    # never collide across the three concurrent processes, and every
    # over-budget request kept its exemplar waterfall (coverage 1.0).
    # The rate-20 fleet above deliberately saturates the degraded
    # replica, so its tail latency is queue wait — correct attribution
    # there is "queue".  Service-phase attribution needs an offered
    # load the slow replica can absorb: uniform arrivals at 1 req/s
    # put a 1s gap between requests, which the 500ms injected sleep
    # fits inside, so the tail spans are service-dominated by
    # construction (and deterministically so — no Poisson bursts).
    from apnea_uq_tpu.telemetry import spans as spans_mod

    trace_dirs = [str(tmp_path / f"trace-rep{i}") for i in range(3)]

    def gentle_cmd(i, run_dir):
        cmd = [sys.executable, "-m", "apnea_uq_tpu.serving.replica",
               "--run-dir", run_dir, "--requests", "10",
               "--passes", "2", "--arrival", "uniform",
               "--rate", "1", "--seed", str(i),
               "--trace-every", "5", "--trace-slow-ms", "250"]
        if i == 2:
            cmd += ["--slow-ms", "500"]  # the degraded replica
        return cmd

    procs = [subprocess.Popen(
        gentle_cmd(i, d), cwd=REPO,
        env=dict(env, APNEA_UQ_REPLICA_ID=f"replica-{i}"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i, d in enumerate(trace_dirs)]
    for proc in procs:
        out, _ = proc.communicate(timeout=600)
        assert proc.returncode == 0, out[-3000:]

    report = spans_mod.build_trace(trace_dirs)
    assert not report.collisions
    span_ids = [s["span_id"] for s in report.spans]
    assert len(set(span_ids)) == len(span_ids)
    # Every span id is replica-prefixed and every replica contributed
    # at least one span (the first completed request always emits).
    assert {sid.split("/", 1)[0] for sid in span_ids} == {
        "replica-0", "replica-1", "replica-2"}
    assert report.tail_replica == "replica-2"
    assert report.tail_phase == "service"
    assert report.over_budget >= 10  # every degraded request
    assert report.exemplar_coverage == 1.0

    # The report dir persists and gates: queue/service/pad shares and
    # exemplar coverage ride compare as backend-unbound ratios.
    report_dir = str(tmp_path / "trace-report")
    spans_mod.record_trace(report, report_dir)
    events = list(telemetry.read_events(report_dir))
    assert events[-1]["kind"] == "trace_report"
    assert events[-1]["exemplar_coverage"] == 1.0
    comp = compare_paths(report_dir, report_dir)
    assert {d.name for d in comp.deltas} >= {
        "trace.service_share_p99", "trace.exemplar_coverage"}

    # The CLI agrees: the one-replica-dominated tail is a finding
    # (exit 1), and a sourceless dir is a usage error (exit 2).
    assert cli_main(["telemetry", "trace", *trace_dirs]) == 1
    empty = tmp_path / "no-traces"
    empty.mkdir()
    with pytest.raises(SystemExit) as exc:
        cli_main(["telemetry", "trace", str(empty)])
    assert exc.value.code == 2
