"""End-to-end CLI pipeline test: every stage subcommand runs against one
shared registry, in dependency order, on synthetic data with a tiny model.

This is the integration test the reference never had — its stages were
hand-run scripts whose file-name contracts drifted apart (SURVEY §1); here
the whole chain prepare -> train -> train-ensemble -> eval-mcd/eval-de ->
aggregate/analyze/correlate/sweep/figures runs in-process.
"""

import glob
import json
import os

import numpy as np
import pandas as pd
import pytest

from apnea_uq_tpu.cli.main import main
from apnea_uq_tpu.config import (
    EnsembleConfig,
    ExperimentConfig,
    ModelConfig,
    PrepareConfig,
    TrainConfig,
    UQConfig,
    _to_jsonable,
)
from apnea_uq_tpu.data import WindowSet
from apnea_uq_tpu.data import registry as reg
from apnea_uq_tpu.data.registry import ArtifactRegistry


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    """Registry pre-seeded with synthetic windows + a tiny config file."""
    root = tmp_path_factory.mktemp("cli")
    registry_dir = str(root / "registry")
    rng = np.random.default_rng(0)

    n, n_patients = 480, 16
    pids = np.array([f"P{i % n_patients:03d}" for i in range(n)])
    y = rng.integers(0, 2, n).astype(np.int8)
    x = rng.normal(size=(n, 60, 4)).astype(np.float32)
    x[:, :, 0] += (y.astype(np.float32) * 2 - 1)[:, None] * 1.2
    windows = WindowSet(
        x=x, y=y, patient_ids=pids,
        start_time_s=np.arange(n, dtype=np.int32) * 60,
        channels=("SaO2", "PR", "THOR RES", "ABDO RES"),
    )
    ArtifactRegistry(registry_dir).save_arrays(reg.WINDOWS, windows.to_arrays())

    config = ExperimentConfig(
        model=ModelConfig(features=(4, 6), kernel_sizes=(3, 3),
                          dropout_rates=(0.2, 0.3)),
        train=TrainConfig(batch_size=64, num_epochs=2, validation_split=0.1,
                          seed=1),
        ensemble=EnsembleConfig(num_members=2, num_epochs=2, batch_size=64,
                                seed_base=2025),
        uq=UQConfig(mc_passes=4, n_bootstrap=10, inference_batch_size=128),
        prepare=PrepareConfig(smote=False),
    )
    config_path = str(root / "config.json")
    with open(config_path, "w") as f:
        json.dump(_to_jsonable(config), f)
    return {"root": root, "registry": registry_dir, "config": config_path}


def run(*argv) -> int:
    return main(list(argv))


@pytest.mark.parametrize("order", [0])
def test_full_pipeline(env, order, capsys):
    registry_dir, config = env["registry"], env["config"]
    registry = ArtifactRegistry(registry_dir)

    # -- prepare ----------------------------------------------------------
    assert run("prepare", "--registry", registry_dir, "--config", config) == 0
    assert registry.exists(reg.TRAIN_STD_SMOTE)
    assert registry.exists(reg.TEST_STD_UNBALANCED)
    assert registry.exists(reg.TEST_STD_RUS)

    # -- train baseline ---------------------------------------------------
    train_run_dir = str(env["root"] / "train_run")
    assert run("train", "--registry", registry_dir, "--config", config,
               "--run-dir", train_run_dir, "--profile") == 0
    out = capsys.readouterr().out
    assert "saved baseline checkpoint" in out
    assert "baseline on Unbalanced" in out

    # --profile left a bounded trace artifact under the run dir and
    # announced it (ISSUE 3 acceptance); the fit priced its compiled
    # programs as memory_profile events and the stage brackets took
    # device-memory snapshots.
    from apnea_uq_tpu import telemetry
    train_events = telemetry.read_events(train_run_dir)
    prof = next(e for e in train_events if e["kind"] == "profile_captured")
    assert prof["steps_profiled"] >= 1
    trace_dir = os.path.join(train_run_dir, prof["trace_dir"])
    assert glob.glob(os.path.join(trace_dir, "plugins", "profile", "*", "*")), \
        f"no trace artifact under {trace_dir}"
    mem_labels = {e["label"] for e in train_events
                  if e["kind"] == "memory_profile"}
    assert {"train_epoch", "val_loss"} <= mem_labels
    snap_labels = {e["label"] for e in train_events
                   if e["kind"] == "memory_snapshot"}
    assert {"fit.start", "fit.end"} <= snap_labels

    # -- train ensemble + idempotent resume -------------------------------
    assert run("train-ensemble", "--registry", registry_dir,
               "--config", config) == 0
    assert "saved 2 members" in capsys.readouterr().out
    assert run("train-ensemble", "--registry", registry_dir,
               "--config", config) == 0
    assert "nothing to do" in capsys.readouterr().out

    # -- eval-mcd / eval-de -----------------------------------------------
    mcd_plots = str(env["root"] / "mcd_plots")
    profile_dir = str(env["root"] / "trace")
    # --profile and --profile-dir both start a jax.profiler session;
    # nesting them must be refused up front, not mid-evaluation.
    with pytest.raises(SystemExit, match="mutually exclusive"):
        run("eval-mcd", "--registry", registry_dir, "--config", config,
            "--profile", "--profile-dir", profile_dir)
    assert run("eval-mcd", "--registry", registry_dir, "--config", config,
               "--plots-dir", mcd_plots, "--profile-dir", profile_dir) == 0
    # --profile-dir wraps the evaluation in a jax.profiler trace
    # (SURVEY §5.1 tracing hook).
    assert glob.glob(os.path.join(profile_dir, "**", "*"), recursive=True)
    out = capsys.readouterr().out
    assert "CNN_MCD_Unbalanced" in out and "overall_mean_variance" in out
    # The deterministic sanity probe runs once, on the first (Unbalanced)
    # set — reference behavior (analyze_mcd_patient_level.py:203-211).
    assert out.count("deterministic accuracy") == 1
    assert registry.exists(f"{reg.DETAILED_WINDOWS}:CNN_MCD_Unbalanced")
    # The fused default never materializes the (K, M) stack: the eval
    # persists the (4, M) sufficient statistics, not raw predictions.
    assert registry.exists(f"{reg.UQ_STATS}:CNN_MCD_Balanced_RUS")
    assert not registry.exists(f"{reg.RAW_PREDICTIONS}:CNN_MCD_Balanced_RUS")
    stats = registry.load_arrays(f"{reg.UQ_STATS}:CNN_MCD_Balanced_RUS")
    assert stats["stats"].shape[0] == 4
    # The printed scalar results are persisted too (metrics JSON artifact).
    metrics_doc = registry.load_json(f"{reg.METRICS}:CNN_MCD_Unbalanced")
    assert set(metrics_doc) >= {"aggregates", "confidence_intervals",
                                "classification"}
    assert metrics_doc["fused"] is True
    assert "overall_mean_variance" in metrics_doc["aggregates"]
    assert "overall_mean_variance_ci_lower" in metrics_doc["confidence_intervals"]
    assert 0.0 <= metrics_doc["classification"]["accuracy"] <= 1.0
    # 4 evaluation plots (3 metric distributions + class bar) per test set
    # (reference emits these inside evaluate_uq_methods, uq_techniques.py:369-387)
    mcd_pngs = sorted(os.listdir(mcd_plots))
    assert len(mcd_pngs) == 8 and all(p.endswith(".png") for p in mcd_pngs)
    assert any("CNN_MCD_Unbalanced_mutual_info" in p for p in mcd_pngs)

    de_plots = str(env["root"] / "de_plots")
    de_run_dir = str(env["root"] / "de_run")
    # --full-probs: the escape hatch restores the (N, M) round-trip and
    # the raw_predictions artifact (the fused default is exercised by
    # eval-mcd above and test_eval_fused_vs_full_probs_parity).
    assert run("eval-de", "--registry", registry_dir, "--config", config,
               "--num-members", "2", "--plots-dir", de_plots,
               "--run-dir", de_run_dir, "--profile", "--full-probs") == 0
    capsys.readouterr()
    # The eval --profile brackets ONLY the timed predict (the driver
    # enters the session after the HBM pre-pass) — one bracket capture
    # per test set, each with a real trace artifact.
    de_events = telemetry.read_events(de_run_dir)
    de_profs = [e for e in de_events if e["kind"] == "profile_captured"]
    assert {p["label"] for p in de_profs} == {"de-Unbalanced",
                                             "de-Balanced_RUS"}
    for p in de_profs:
        assert p["mode"] == "bracket" and p["steps_profiled"] is None
        assert glob.glob(os.path.join(de_run_dir, p["trace_dir"],
                                      "plugins", "profile", "*", "*"))
    for e in de_events:
        if e["kind"] == "eval_predict":
            assert e["fused"] is False
            assert e["d2h_bytes"] == 2 * e["n_windows"] * 4
    assert registry.exists(f"{reg.DETAILED_WINDOWS}:CNN_DE_Unbalanced")
    assert registry.exists(f"{reg.METRICS}:CNN_DE_Unbalanced")
    assert registry.load_json(f"{reg.METRICS}:CNN_DE_Unbalanced")["fused"] \
        is False
    preds = registry.load_arrays(f"{reg.RAW_PREDICTIONS}:CNN_DE_Unbalanced")
    assert preds["predictions"].shape[0] == 2
    assert len(os.listdir(de_plots)) == 8

    # -- global (no-CSV) evaluation variants (C15/C16) ---------------------
    # --no-detailed reproduces evaluate_{mcd,de}_global.py: aggregates +
    # CIs only, no per-window detailed CSV.  Overwrite-safety: run into a
    # fresh registry so the detailed artifacts above survive.
    global_registry = str(env["root"] / "registry_global")
    import shutil
    shutil.copytree(registry_dir, global_registry)
    greg = ArtifactRegistry(global_registry)
    detailed_csv = os.path.join(
        global_registry, greg.describe(f"{reg.DETAILED_WINDOWS}:CNN_DE_Unbalanced")["file"]
    )
    before = os.path.getmtime(detailed_csv)
    assert run("eval-de", "--registry", global_registry, "--config", config,
               "--num-members", "2", "--no-detailed") == 0
    capsys.readouterr()
    doc = greg.load_json(f"{reg.METRICS}:CNN_DE_Unbalanced")
    assert "overall_mean_variance" in doc["aggregates"]
    # The global variant did not rewrite the per-window CSV.
    assert os.path.getmtime(detailed_csv) == before

    # -- metrics read-back -------------------------------------------------
    assert run("metrics", "--registry", registry_dir, "--config", config,
               "--label", "CNN_MCD_Unbalanced") == 0
    out = capsys.readouterr().out
    assert "stochastic-mean accuracy" in out and "overall_mean_variance" in out
    assert run("metrics", "--registry", registry_dir, "--config", config,
               "--label", "CNN_DE_Unbalanced", "--json") == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["label"] == "CNN_DE_Unbalanced"
    with pytest.raises(SystemExit, match="no metrics stored"):
        run("metrics", "--registry", registry_dir, "--config", config,
            "--label", "NOPE")
    capsys.readouterr()

    # -- aggregate / analyze / correlate ----------------------------------
    assert run("aggregate-patients", "--registry", registry_dir,
               "--config", config, "--label", "CNN_MCD_Unbalanced") == 0
    assert "Top 5 patients" in capsys.readouterr().out
    summary = registry.load_table(f"{reg.PATIENT_SUMMARY}:CNN_MCD_Unbalanced")
    detailed = registry.load_table(f"{reg.DETAILED_WINDOWS}:CNN_MCD_Unbalanced")
    assert summary["num_windows"].sum() == len(detailed)

    retention_png = str(env["root"] / "retention.png")
    reliability_png = str(env["root"] / "reliability.png")
    assert run("analyze-windows", "--registry", registry_dir,
               "--config", config, "--label", "CNN_MCD_Unbalanced",
               "--retention", "--retention-plot", retention_png,
               "--calibration-plot", reliability_png) == 0
    out = capsys.readouterr().out
    assert "Binned accuracy" in out
    assert "Selective prediction" in out
    assert "Expected calibration error" in out
    assert os.path.getsize(retention_png) > 0
    assert os.path.getsize(reliability_png) > 0

    assert run("correlate", "--registry", registry_dir, "--config", config,
               "--labels", "CNN_MCD_Unbalanced") == 0
    out = capsys.readouterr().out
    assert "patient accuracy vs mean entropy" in out
    assert "entropy(incorrect) > entropy(correct)" in out

    # -- sweep -------------------------------------------------------------
    plot_path = str(env["root"] / "mcd_conv.png")
    assert run("sweep", "--registry", registry_dir, "--config", config,
               "--method", "mcd", "--counts", "2", "4",
               "--plot", plot_path) == 0
    capsys.readouterr()
    assert os.path.getsize(plot_path) > 0
    sweep_frame = registry.load_table("sweep:mcd")
    assert sweep_frame["N"].tolist() == [2, 4]

    assert run("sweep", "--registry", registry_dir, "--config", config,
               "--method", "de", "--counts", "1", "2") == 0
    capsys.readouterr()

    # -- figures ------------------------------------------------------------
    fig_dir = str(env["root"] / "figs")
    assert run("figures", "--registry", registry_dir, "--config", config,
               "--labels", "CNN_MCD_Unbalanced", "CNN_DE_Unbalanced",
               "--out-dir", fig_dir) == 0
    capsys.readouterr()
    figs = sorted(os.listdir(fig_dir))
    assert len(figs) == 5 and "retention_curves.png" in figs


def test_eval_fused_vs_full_probs_parity(env, tmp_path, capsys):
    """The README smoke recipe's CI twin (ISSUE 6 satellite): evaluate
    the same checkpoints once fused (the default) and once --full-probs,
    and assert the two persisted metric documents match to <=1e-6 —
    only the provenance fields (fused, predict_seconds) may differ.
    Self-contained: prepares/trains its own registry copy, so it does
    not depend on test_full_pipeline having run."""
    import shutil

    config = env["config"]
    base = str(tmp_path / "base")
    shutil.copytree(env["registry"], base)
    assert run("prepare", "--registry", base, "--config", config) == 0
    assert run("train-ensemble", "--registry", base, "--config", config) == 0
    full = str(tmp_path / "full")
    shutil.copytree(base, full)
    fused_run = str(tmp_path / "fused_run")
    full_run = str(tmp_path / "full_run")
    assert run("eval-de", "--registry", base, "--config", config,
               "--num-members", "2", "--no-detailed",
               "--run-dir", fused_run) == 0
    assert run("eval-de", "--registry", full, "--config", config,
               "--num-members", "2", "--no-detailed", "--full-probs",
               "--run-dir", full_run) == 0
    out = capsys.readouterr().out
    assert "(fused reduction)" in out

    breg, freg = ArtifactRegistry(base), ArtifactRegistry(full)
    a = breg.load_json(f"{reg.METRICS}:CNN_DE_Unbalanced")
    b = freg.load_json(f"{reg.METRICS}:CNN_DE_Unbalanced")
    assert a["fused"] is True and b["fused"] is False
    assert a["n_passes"] == b["n_passes"] == 2
    assert a["n_windows"] == b["n_windows"]
    assert a["aggregates"] == pytest.approx(b["aggregates"], abs=1e-6)
    assert a["confidence_intervals"] == pytest.approx(
        b["confidence_intervals"], abs=1e-5)
    assert a["classification"]["accuracy"] == pytest.approx(
        b["classification"]["accuracy"])
    # Artifact shapes: fused -> uq_stats, full -> raw_predictions (the
    # that-and-ONLY-that claim is pinned at the driver level by
    # test_uq_drivers save_run tests; the env registry copy may carry
    # stale artifacts from the pipeline test).
    assert breg.exists(f"{reg.UQ_STATS}:CNN_DE_Unbalanced")
    assert freg.exists(f"{reg.RAW_PREDICTIONS}:CNN_DE_Unbalanced")
    assert breg.load_arrays(
        f"{reg.UQ_STATS}:CNN_DE_Unbalanced")["stats"].shape[0] == 4

    # Telemetry: the fused run's d2h estimate is (4/K)x the full run's,
    # and the summarizer renders both sides' eval lines with the new
    # fused/d2h annotations.
    from apnea_uq_tpu import telemetry
    fused_evs = [e for e in telemetry.read_events(fused_run)
                 if e["kind"] == "eval_predict"]
    full_evs = [e for e in telemetry.read_events(full_run)
                if e["kind"] == "eval_predict"]
    assert fused_evs and len(fused_evs) == len(full_evs)
    for fe, pe in zip(fused_evs, full_evs):
        assert fe["fused"] is True and pe["fused"] is False
        assert fe["d2h_bytes"] * pe["n_passes"] == \
            pe["d2h_bytes"] * 4  # exactly (4/K)x
    assert "[fused, d2h" in telemetry.summarize_run(fused_run)
    assert "[full-probs, d2h" in telemetry.summarize_run(full_run)


def test_sweep_from_csv(tmp_path, capsys):
    """--from-csv plots a hand-collected table (the reference's C20
    workflow: hyperparameter_plot_mcd_or_de_pass_convergence.py only ever
    plotted a CSV) without touching a registry or checkpoints."""
    csv_path = str(tmp_path / "conv.csv")
    pd.DataFrame({
        "N": [10, 25, 50],
        "Variance_Unbalanced": [0.04, 0.03, 0.028],
        "Variance_Balanced": [0.05, 0.042, 0.04],
    }).to_csv(csv_path, index=False)
    plot_path = str(tmp_path / "conv.png")
    assert run("sweep", "--from-csv", csv_path, "--plot", plot_path) == 0
    capsys.readouterr()
    assert os.path.getsize(plot_path) > 0

    with pytest.raises(SystemExit):
        run("sweep", "--from-csv", csv_path)  # --plot is required
    with pytest.raises(SystemExit):
        run("sweep", "--method", "mcd")      # incomplete re-run arguments


def test_cohort_stage(env, tmp_path, capsys):
    rng = np.random.default_rng(1)
    n = 100
    pd.DataFrame({
        "ahi_a0h3a": rng.exponential(10, n),
        "age_s2": rng.normal(60, 8, n),
        "gender": rng.choice([1, 2], n),
        "quoxim": rng.choice([4, 5], n),
    }).to_csv(tmp_path / "meta.csv", index=False)
    assert run("cohort", "--metadata-csv", str(tmp_path / "meta.csv"),
               "--signal-quality") == 0
    out = capsys.readouterr().out
    assert "AHI severity distribution" in out and "Oximeter" in out


def test_ingest_stage(env, tmp_path, capsys):
    from apnea_uq_tpu.data.edf import EdfSignal, write_edf

    rng = np.random.default_rng(2)
    edf_dir = tmp_path / "edf"
    xml_dir = tmp_path / "xml"
    edf_dir.mkdir()
    xml_dir.mkdir()
    n_seconds = 360
    for patient in ("200001", "200002"):
        signals = [
            EdfSignal("SaO2", 1.0,
                      (95 + rng.normal(0, 1, n_seconds)).astype(np.float32)),
            EdfSignal("PR", 1.0,
                      (70 + rng.normal(0, 5, n_seconds)).astype(np.float32)),
            EdfSignal("THOR RES", 10.0,
                      rng.normal(0, 0.5, 10 * n_seconds).astype(np.float32)),
            EdfSignal("ABDO RES", 10.0,
                      rng.normal(0, 0.5, 10 * n_seconds).astype(np.float32)),
        ]
        write_edf(str(edf_dir / f"shhs2-{patient}.edf"), signals)
        (xml_dir / f"shhs2-{patient}-nsrr.xml").write_text(
            """<?xml version="1.0"?>
<PSGAnnotation><ScoredEvents>
<ScoredEvent><EventType>Recording Start Time</EventType>
<EventConcept>Recording Start Time</EventConcept>
<Start>0</Start><Duration>25200</Duration></ScoredEvent>
<ScoredEvent><EventType>Respiratory|Respiratory</EventType>
<EventConcept>Obstructive apnea|Obstructive Apnea</EventConcept>
<Start>70</Start><Duration>25</Duration></ScoredEvent>
</ScoredEvents></PSGAnnotation>
"""
        )
    registry_dir = str(tmp_path / "ingest_registry")
    assert run("ingest", "--edf-dir", str(edf_dir), "--xml-dir", str(xml_dir),
               "--registry", registry_dir) == 0
    out = capsys.readouterr().out
    assert "processed 2 recordings" in out
    arrays = ArtifactRegistry(registry_dir).load_arrays(reg.WINDOWS)
    assert arrays["x"].shape[1:] == (60, 4)
    assert arrays["x"].shape[0] == 12  # 2 recordings x 6 windows


def test_init_config(tmp_path, capsys):
    out_path = str(tmp_path / "cfg.json")
    assert run("init-config", "--out", out_path) == 0
    with open(out_path) as f:
        data = json.load(f)
    assert set(data) >= {"model", "train", "ensemble", "uq"}


def test_ingest_to_figures_single_registry(tmp_path, capsys):
    """The whole pipeline in ONE continuous run from raw signals: synthetic
    EDF+XML -> ingest -> prepare -> train -> train-ensemble -> eval-mcd ->
    eval-de -> aggregate -> analyze -> correlate -> figures, every stage
    consuming the registry the previous stage wrote.  This crosses the
    L1->L2 seam (SURVEY §1: `SHHS2_ID_all_60.csv` ->
    prepare_numpy_datasets.py:61) inside a single registry — the seam the
    reference's drifted filename contracts broke — where
    test_full_pipeline starts from a pre-seeded windows artifact."""
    from apnea_uq_tpu.data.edf import EdfSignal, write_edf

    rng = np.random.default_rng(5)
    edf_dir = tmp_path / "edf"
    xml_dir = tmp_path / "xml"
    edf_dir.mkdir()
    xml_dir.mkdir()
    n_seconds = 1800  # 30 windows per recording
    for i in range(6):
        patient = f"20010{i}"
        # An apnea run in the first half of each recording gives every
        # patient positive AND negative windows, so any patient split
        # leaves both classes on both sides (RUS/metrics need that).
        signals = [
            EdfSignal("SaO2", 1.0,
                      (95 + rng.normal(0, 1, n_seconds)).astype(np.float32)),
            EdfSignal("PR", 1.0,
                      (70 + rng.normal(0, 5, n_seconds)).astype(np.float32)),
            EdfSignal("THOR RES", 10.0,
                      rng.normal(0, 0.5, 10 * n_seconds).astype(np.float32)),
            EdfSignal("ABDO RES", 10.0,
                      rng.normal(0, 0.5, 10 * n_seconds).astype(np.float32)),
        ]
        write_edf(str(edf_dir / f"shhs2-{patient}.edf"), signals)
        (xml_dir / f"shhs2-{patient}-nsrr.xml").write_text(
            """<?xml version="1.0"?>
<PSGAnnotation><ScoredEvents>
<ScoredEvent><EventType>Recording Start Time</EventType>
<EventConcept>Recording Start Time</EventConcept>
<Start>0</Start><Duration>25200</Duration></ScoredEvent>
<ScoredEvent><EventType>Respiratory|Respiratory</EventType>
<EventConcept>Obstructive apnea|Obstructive Apnea</EventConcept>
<Start>70</Start><Duration>50</Duration></ScoredEvent>
<ScoredEvent><EventType>Respiratory|Respiratory</EventType>
<EventConcept>Hypopnea|Hypopnea</EventConcept>
<Start>400</Start><Duration>40</Duration></ScoredEvent>
</ScoredEvents></PSGAnnotation>
"""
        )

    registry_dir = str(tmp_path / "registry")
    config = ExperimentConfig(
        model=ModelConfig(features=(4, 6), kernel_sizes=(3, 3),
                          dropout_rates=(0.2, 0.3)),
        train=TrainConfig(batch_size=32, num_epochs=1, validation_split=0.1,
                          seed=1),
        ensemble=EnsembleConfig(num_members=2, num_epochs=1, batch_size=32,
                                seed_base=2025),
        uq=UQConfig(mc_passes=4, n_bootstrap=10, inference_batch_size=64,
                    mcd_batch_size=64),
        prepare=PrepareConfig(smote=False),
    )
    config_path = str(tmp_path / "config.json")
    with open(config_path, "w") as f:
        json.dump(_to_jsonable(config), f)

    # L1: raw EDF/XML -> windows artifact.
    assert run("ingest", "--edf-dir", str(edf_dir), "--xml-dir", str(xml_dir),
               "--registry", registry_dir) == 0
    assert "processed 6 recordings" in capsys.readouterr().out
    registry = ArtifactRegistry(registry_dir)
    arrays = registry.load_arrays(reg.WINDOWS)
    assert arrays["x"].shape == (180, 60, 4)
    assert 0 < arrays["y"].sum() < 180  # both classes ingested

    # L2 consumes L1's output in place — the seam under test.
    assert run("prepare", "--registry", registry_dir, "--config",
               config_path) == 0
    capsys.readouterr()
    assert registry.exists(reg.TEST_STD_UNBALANCED)

    # L3 -> L5 -> L6 -> L7 on the same registry.
    assert run("train", "--registry", registry_dir, "--config",
               config_path) == 0
    assert run("train-ensemble", "--registry", registry_dir, "--config",
               config_path) == 0
    assert run("eval-mcd", "--registry", registry_dir, "--config",
               config_path) == 0
    assert run("eval-de", "--registry", registry_dir, "--config",
               config_path, "--num-members", "2") == 0
    assert run("aggregate-patients", "--registry", registry_dir, "--config",
               config_path, "--label", "CNN_MCD_Unbalanced") == 0
    assert run("analyze-windows", "--registry", registry_dir, "--config",
               config_path, "--label", "CNN_MCD_Unbalanced") == 0
    assert run("correlate", "--registry", registry_dir, "--config",
               config_path, "--labels", "CNN_MCD_Unbalanced") == 0
    capsys.readouterr()
    fig_dir = str(tmp_path / "figs")
    assert run("figures", "--registry", registry_dir, "--config", config_path,
               "--labels", "CNN_MCD_Unbalanced", "CNN_DE_Unbalanced",
               "--out-dir", fig_dir) == 0
    capsys.readouterr()
    assert len(os.listdir(fig_dir)) == 5
    # Patient-level artifacts trace back to the ingested recordings
    # (numeric-string IDs come back as ints from the CSV round-trip).
    summary = registry.load_table(f"{reg.PATIENT_SUMMARY}:CNN_MCD_Unbalanced")
    assert set(summary["Patient_ID"].astype(str)).issubset(
        {f"20010{i}" for i in range(6)}
    )
