"""bench.py execution coverage (r4 verdict item 2).

Two rounds of bench rework shipped without ever executing — the TPU
tunnel was down and the script had no off-TPU path — so a bench-script
bug could silently waste the next hardware capture.  These tests make
that impossible:

* the CPU smoke test runs the REAL ``python bench.py`` end-to-end at
  tiny shapes (``BENCH_PLATFORM=cpu`` + size knobs) and asserts the one
  JSON line carries the full schema — primary metric, DE secondary, and
  the streamed-overhead + bootstrap context blocks with no degraded
  ``error`` fields;
* the ``_resolve_backend`` unit tests cover the init retry loop added
  for the *fast-fail* outage mode (r4's capture died in seconds on
  ``UNAVAILABLE``): transient failures retry with backoff, an exhausted
  budget degrades to the CPU-proxy capture (BENCH_CPU_PROXY=0 restores
  the exit-2 abort, now folding surviving progress into the error
  payload), and explicit platform overrides skip the probe entirely;
* the block-isolation tests force blocks to raise and assert the
  result-v2 payload stays parseable with per-block statuses, and that
  ``telemetry compare`` gates the surviving blocks (exit 2 only when
  NO block is comparable).
"""

import glob
import importlib.util
import json
import os
import subprocess
import sys
import time
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

SMOKE_ENV = {
    # Retarget the backend from inside bench.py (sitecustomize pins
    # JAX_PLATFORMS=axon at boot, so the subprocess is the production
    # smoke path, not a test shortcut).
    "BENCH_PLATFORM": "cpu",
    "BENCH_DTYPE": "float32",  # CPU emulates bf16 convs too slowly
    "BENCH_WINDOWS": "256",
    "BENCH_PASSES": "4",
    "BENCH_CHUNK": "64",
    # XLA:CPU backward convolutions run far off peak, so the DE-train
    # block dominates the smoke wall-clock — keep its shapes minimal.
    "BENCH_MEMBERS": "2",
    "BENCH_TRAIN_WINDOWS": "64",
    "BENCH_EPOCHS": "1",
    "BENCH_BATCH": "32",
    "BENCH_DE_REPS": "1",
    "BENCH_DE_CHUNK": "64",
    "BENCH_BOOT_WINDOWS": "2048",
    "BENCH_WATCHDOG_SECS": "900",
    # Exercise the bounded trace capture (ISSUE 3): one extra
    # steady-state MCD pass AFTER the timed reps, profiled into the run
    # dir — cheap at smoke shapes, and proves the profiler path off-TPU.
    "BENCH_PROFILE": "1",
    # Capacity sweep (ISSUE 18): 3 tiny offered-rate cells, 2 replica
    # subprocesses each, few requests — enough for a real fleet-merged
    # saturation curve without dominating the smoke wall-clock.
    "BENCH_CAPACITY_RATES": "6,12,24",
    "BENCH_CAPACITY_REPLICAS": "2",
    "BENCH_CAPACITY_REQUESTS": "6",
}


def test_readme_smoke_recipe_pins_every_smoke_knob():
    """The README's off-TPU recipe claims test parity with this module
    ('runs exactly this end-to-end in CI'), so every knob SMOKE_ENV pins
    must appear in the README command verbatim (r5 advisor finding: the
    recipe was missing DE_REPS/DE_CHUNK/WATCHDOG and ran a ~3x longer DE
    phase than the test it cited)."""
    readme = open(os.path.join(REPO, "README.md")).read()
    for k, v in SMOKE_ENV.items():
        assert f"{k}={v}" in readme, (
            f"README off-TPU smoke recipe is missing {k}={v}; keep it in "
            f"sync with tests/test_bench_smoke.py SMOKE_ENV"
        )
    # The recipe's pre-flight includes the static hazard gate (ISSUE 4):
    # `apnea-uq lint` must stay in the README smoke section, since it is
    # the one check that runs in seconds and catches the bug classes
    # (donation reads, key reuse) a CPU smoke run can NEVER observe.
    assert "apnea-uq lint" in readme, (
        "README smoke recipe lost the `apnea-uq lint` gate; the static "
        "hazard lint is part of the pre-capture ritual"
    )
    # And the flow gate (ISSUE 10): the artifact-contract + write-
    # discipline check is the other seconds-fast, jax-free pre-flight
    # that catches bug classes no CPU smoke run can observe.
    assert "apnea-uq flow" in readme, (
        "README smoke recipe lost the `apnea-uq flow` gate; the "
        "pipeline dataflow check is part of the pre-capture ritual"
    )
    # The CPU-proxy recipe (ISSUE 11): the off-TPU capture mode that
    # keeps the perf trajectory alive through tunnel outages, plus the
    # trajectory ledger that reads it back.
    assert "BENCH_CPU_PROXY=1 python bench.py" in readme, (
        "README lost the CPU-proxy smoke recipe "
        "(`BENCH_CPU_PROXY=1 python bench.py`)"
    )
    assert "apnea-uq telemetry trend" in readme, (
        "README lost the `apnea-uq telemetry trend` trajectory-ledger "
        "recipe"
    )
    # The model-quality gate (ISSUE 13): calibration-regression +
    # input-drift checking is part of the same jax-free pre-flight
    # family as lint/flow — the recipe must keep teaching it.
    assert "apnea-uq quality check" in readme, (
        "README smoke recipe lost the `apnea-uq quality check` gate; "
        "the model-quality check is part of the post-eval ritual"
    )
    # Fleet tracing (ISSUE 20): the tail-attribution assembler and the
    # flag that arms tail-based exemplar capture are part of the same
    # serving-observability recipe family as fleet/drift.
    assert "apnea-uq telemetry trace" in readme, (
        "README lost the `apnea-uq telemetry trace` fleet-tracing "
        "recipe"
    )
    assert "--trace-slow-ms" in readme, (
        "README lost the `--trace-slow-ms` tail-exemplar flag; the "
        "serving recipe must keep teaching tail-based sampling"
    )


def _smoke_env(progress_file: str, run_dir: str) -> dict:
    # Strip ambient BENCH_* knobs too: an exported BENCH_SKIP_DE/
    # BENCH_METRIC in a developer shell must not reshape the asserted
    # schema (SMOKE_ENV is the complete knob set for this run).
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
           and not k.startswith("BENCH_")}
    env.update(SMOKE_ENV)
    env["BENCH_PROGRESS_FILE"] = progress_file
    # Keep the telemetry run dir (default ./bench_run) out of the repo cwd.
    env["BENCH_RUN_DIR"] = run_dir
    # Share the suite's persistent compile cache so repeat runs are warm.
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(__file__), ".jax_cache"))
    return env


@pytest.mark.slow  # fresh interpreter + full-model CPU convs (~3-5 min)
def test_bench_cpu_smoke_end_to_end(tmp_path):
    progress = str(tmp_path / "progress.json")
    run_dir = str(tmp_path / "bench_run")
    proc = subprocess.run(
        [sys.executable, BENCH], cwd=REPO, env=_smoke_env(progress, run_dir),
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, f"bench.py failed:\n{proc.stderr[-3000:]}"
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    result = json.loads(lines[0])

    # Driver schema on the primary metric.
    assert result["metric"] == "mcd_t50_inference_throughput"
    assert result["unit"] == "windows/sec/chip"
    assert result["value"] > 0
    assert result["vs_baseline"] > 0
    assert result["effective"]["windows"] == 256

    # DE secondary in the same schema (metric name tracks BENCH_MEMBERS).
    sec = result["secondary"]
    assert sec["metric"] == "de2_train_wallclock"
    assert sec["unit"] == "seconds"
    assert sec["value"] > 0
    assert sec["vs_baseline"] > 0
    assert len(sec["effective"]["per_rep_ratios"]) == 1
    # Zero-waste accounting context: slots trained == members returned
    # (single-device mesh: nothing pads, nothing promoted), plus the
    # quantified lockstep early-stop waste at reference patience=5.
    de_ctx = sec["context"]
    assert de_ctx["effective_members"] == 2
    assert de_ctx["promoted_members"] == 0
    assert de_ctx["cost_per_member"] == pytest.approx(
        sec["value"] / de_ctx["effective_members"], rel=0.01)
    waste = de_ctx["early_stop_waste"]
    assert "error" not in waste, waste
    assert waste["patience"] == 5
    assert waste["member_epochs_computed"] == (
        waste["member_epochs_active"] + waste["wasted_member_epochs"])
    assert waste["wasted_member_epochs"] >= 0

    # Context blocks executed for real — no degraded error fields.
    ctx = result["context"]
    boot = ctx["bootstrap_b100_m293k"]
    assert "error" not in boot, boot
    assert boot["exact_ms"] > 0 and boot["poisson_ms"] > 0
    streamed = ctx["streamed_overhead"]
    assert "error" not in streamed, streamed
    for key in ("mcd_streamed_vs_inhbm", "de10_streamed_vs_inhbm"):
        assert streamed[key] > 0, (key, streamed)
    fused = ctx["fused_reduction"]
    assert "error" not in fused, fused
    assert fused["fused_s"] > 0 and fused["fused_vs_full"] > 0
    # d2h accounting: full = passes x windows x 4 bytes, fused = 4 rows
    # x windows x 4 bytes (at the smoke's BENCH_PASSES=4 they coincide).
    assert fused["d2h_bytes_full"] == 4 * 256 * 4
    assert fused["d2h_bytes_fused"] == 4 * 256 * 4
    # Compile-cost block (ISSUE 7): two real probe subprocesses against
    # one fresh cache/store pair — the cold run compiles fresh, the warm
    # run loads the stored program with ZERO fresh XLA compiles.
    compile_ctx = ctx["compile"]
    assert "error" not in compile_ctx, compile_ctx
    assert compile_ctx["cold"]["source"] == "jit"
    assert compile_ctx["cold"]["total_s"] > 0
    assert compile_ctx["warm"]["source"] == "store"
    assert compile_ctx["warm"]["persistent_cache_misses"] == 0
    assert compile_ctx["warm"]["total_s"] > 0
    # Data-plane block (ISSUE 9): cold stage-start load of the same
    # window set via monolithic .npz vs sharded memmap store, plus a
    # full streamed pass — all host-side, so the smoke run exercises it
    # for real.
    data_ctx = ctx["data_plane"]
    assert "error" not in data_ctx, data_ctx
    assert data_ctx["rows"] == 256
    assert data_ctx["npz_load_s"] > 0 and data_ctx["store_stream_s"] > 0
    assert data_ctx["store_rows_per_s"] > 0
    assert data_ctx["store_vs_npz_first_batch"] > 0
    # IR-audit block (ISSUE 8): the `apnea-uq audit` subprocess lowered
    # the inference zoo on CPU and found it clean against the checked-in
    # manifest, with per-program cost facts attached to the capture.
    audit_ctx = ctx["program_audit"]
    assert "error" not in audit_ctx, audit_ctx
    assert audit_ctx["clean"] is True and audit_ctx["unsuppressed"] == 0
    for label in ("mcd_predict_fused", "mcd_predict_pallas_fused",
                  "mcd_predict_fused_bf16", "de_predict_fused",
                  "predict_eval", "predict_eval_bf16"):
        assert audit_ctx["programs"][label]["flops"] > 0, (label, audit_ctx)
    # MCD-kernel block (ISSUE 12): XLA-vs-Pallas at the smoke operating
    # point.  Off-TPU the pallas engine resolves to the XLA fallback, so
    # the smoke run pins the fallback contract (ratio ~1) and records
    # which body ran; the bf16 half is skipped at BENCH_DTYPE=float32.
    kernel_ctx = ctx["mcd_kernel"]
    assert "error" not in kernel_ctx, kernel_ctx
    assert kernel_ctx["xla_f32_s"] > 0 and kernel_ctx["pallas_f32_s"] > 0
    assert kernel_ctx["xla_vs_pallas"] > 0
    assert kernel_ctx["pallas_engine"] == "xla"
    assert "f32_vs_bf16" not in kernel_ctx
    # DE-kernel block (ISSUE 16): the ensemble twin of the MCD kernel
    # probe — same fallback contract off-TPU, same bf16 gating.
    de_kernel_ctx = ctx["de_kernel"]
    assert "error" not in de_kernel_ctx, de_kernel_ctx
    assert de_kernel_ctx["xla_f32_s"] > 0
    assert de_kernel_ctx["pallas_f32_s"] > 0
    assert de_kernel_ctx["xla_vs_pallas"] > 0
    assert de_kernel_ctx["pallas_engine"] == "xla"
    assert "f32_vs_bf16" not in de_kernel_ctx
    # Autotune block (ISSUE 16): a tiny in-process sweep ran for real —
    # winners picked per label, nothing persisted by the bench.
    at_ctx = ctx["autotune"]
    assert "error" not in at_ctx, at_ctx
    assert at_ctx["labels"] >= 1
    assert at_ctx["best_vs_default"] > 0
    for w in at_ctx["winners"].values():
        assert w["window_tile"] > 0 and w["group"] > 0
    # D2H-accounting block (ISSUE 11): the arithmetic transfer contract
    # at the run's shapes, present even when no device ran.
    d2h_ctx = ctx["d2h_accounting"]
    assert d2h_ctx["d2h_bytes_full"] == 4 * 256 * 4
    assert d2h_ctx["d2h_bytes_fused"] == 4 * 256 * 4
    # Quality block (ISSUE 13): fixed-seed synthetic calibration + drift
    # tooling proof — a calibrated predictor scores near-zero ECE, the
    # self-drift is exactly zero, and the injected shift is detected.
    qual_ctx = ctx["quality"]
    assert "error" not in qual_ctx, qual_ctx
    assert 0.0 <= qual_ctx["ece"] < 0.05
    assert 0.0 < qual_ctx["brier"] < 0.3
    assert qual_ctx["self_max_psi"] == 0.0
    assert qual_ctx["shifted_max_psi"] > 0.2
    # Serve block (ISSUE 15): the load-generated serving loop ran for
    # real — warm bucket programs, coalesced dispatches, and a final
    # SLO summary with the gateable percentiles/throughput/pad-waste.
    serve_ctx = ctx["serve"]
    assert "error" not in serve_ctx, serve_ctx
    assert serve_ctx["requests"] == 64
    assert serve_ctx["windows"] >= serve_ctx["requests"]
    assert serve_ctx["batches"] >= 1
    assert serve_ctx["p50_ms"] > 0 and serve_ctx["p99_ms"] >= serve_ctx["p50_ms"]
    assert serve_ctx["windows_per_s"] > 0
    assert 0.0 <= serve_ctx["pad_waste"] < 1.0
    # Online drift (ISSUE 17): the bench's loadgen cohort shifts halfway
    # through (BENCH_SERVE_DRIFT_AFTER default), and the monitor's final
    # verdict — scored against the seeded standard-normal baseline —
    # flips to "drift" online, proving detection end to end.
    assert serve_ctx["drift_verdicts"] == {"default": "drift"}, serve_ctx
    # Per-bucket SLO breakdown (ISSUE 17 satellite): the summary keys
    # every dispatched bucket size with its own percentiles + pad share.
    assert serve_ctx["buckets"], serve_ctx
    for per in serve_ctx["buckets"].values():
        assert per["batches"] >= 1 and per["p50_ms"] is not None
    # Capacity block (ISSUE 18): K replica subprocesses per offered-rate
    # cell sharing one warm program store, each cell fleet-merged — the
    # saturation curve is real measurements, knee or no knee.
    cap_ctx = ctx["capacity"]
    assert "error" not in cap_ctx, cap_ctx
    assert cap_ctx["replicas"] == 2
    assert cap_ctx["arrival"] == "poisson"
    assert [c["offered_rps"] for c in cap_ctx["cells"]] == [6.0, 12.0,
                                                            24.0]
    for cell in cap_ctx["cells"]:
        assert cell["achieved_rps"] > 0, cap_ctx
        assert cell["achieved_ratio"] > 0, cap_ctx
        assert cell["p99_ms"] > 0 and cell["windows_per_s"] > 0
        assert cell["imbalance_ratio"] >= 1.0
    assert cap_ctx["peak_windows_per_s"] > 0
    if cap_ctx["knee_offered_rps"] is not None:
        assert cap_ctx["knee_offered_rps"] in [6.0, 12.0, 24.0]
        assert cap_ctx["knee_reason"]

    # Result-v2 envelope (ISSUE 11): schema-versioned payload with
    # backend facts and a per-block status map, every block ok on the
    # full smoke run.
    assert result["schema"] == 2
    assert result["proxy"] is False
    assert result["backend"]["platform"] == "cpu"
    assert result["backend"]["requested"] == "cpu"
    blocks = result["blocks"]
    assert {n for n, b in blocks.items() if b["status"] == "ok"} == {
        "mcd", "bootstrap", "streamed", "fused", "mcd_kernel", "de_kernel",
        "autotune", "de_train",
        "earlystop_waste", "compile", "program_audit", "data_plane",
        "d2h_accounting", "quality", "serve", "capacity"}, blocks
    assert all(b["seconds"] >= 0 for b in blocks.values()), blocks

    # The printed line was assembled from the on-disk progress capture:
    # the two artifacts are the same result by construction (the v2
    # envelope keys live beside primary/secondary in the progress file).
    with open(progress) as f:
        saved = json.load(f)
    assert saved["secondary"] == sec
    primary_only = {k: v for k, v in result.items()
                    if k not in ("secondary", "schema", "proxy",
                                 "backend", "blocks")}
    assert saved["primary"] == primary_only
    assert saved["blocks"] == blocks
    assert saved["schema"] == 2 and saved["proxy"] is False

    # The run's telemetry event log (BENCH_RUN_DIR) captured the whole
    # bench: stages bracketed, per-epoch ensemble step metrics with
    # device-vs-dispatch time and recompile counters, and the canonical
    # ensemble_fit accounting record the DE context block was SOURCED
    # from (bench._last_ensemble_fit_event) — not recomputed inline.
    from apnea_uq_tpu import telemetry

    events = telemetry.read_events(run_dir)
    kinds = {e["kind"] for e in events}
    assert {"run_started", "stage_start", "stage_end", "step",
            "ensemble_epoch", "ensemble_fit", "bench_throughput",
            "bench_metric", "bench_block", "run_finished",
            # The serving telemetry triple (ISSUE 15): the serve block
            # streams its batch/request/SLO events into the same run log.
            "serve_batch", "serve_request", "serve_slo",
            # The online-drift verdicts (ISSUE 17): the shifted loadgen
            # cohort lands gateable serve_drift events beside them.
            "serve_drift",
            # The autotune sweep (ISSUE 16): per-cell timings and the
            # per-label winner verdicts land in the same run log.
            "autotune_cell", "autotune_result",
            # The capacity sweep (ISSUE 18): one fleet-merged event per
            # offered-rate cell.
            "capacity_cell"} <= kinds, \
        sorted(kinds)
    # Every block's outcome is mirrored into the run log as it happens.
    block_events = {e["name"]: e["status"] for e in events
                    if e["kind"] == "bench_block"}
    assert block_events == {n: "ok" for n in result["blocks"]}, \
        block_events
    assert events[-1] == {**events[-1], "kind": "run_finished",
                          "status": "ok"}
    stages = {e["stage"] for e in events if e["kind"] == "stage_start"}
    assert {"mcd_framework", "mcd_reference_pattern", "de_train",
            "de_earlystop_waste"} <= stages, sorted(stages)
    steps = [e for e in events if e["kind"] == "step"]
    assert all(e["device_s"] >= e["dispatch_s"] > 0 for e in steps)
    assert all("retraces" in e and "backend_compiles" in e for e in steps)
    # The printed DE context and the event log agree because the former
    # is derived from the latter.
    fit_events = [e for e in events if e["kind"] == "ensemble_fit"]
    assert fit_events[-1]["num_members"] == de_ctx["effective_members"]
    assert (fit_events[-1]["wasted_member_epochs"]
            == waste["wasted_member_epochs"])
    metric_events = {e["role"]: e for e in events
                     if e["kind"] == "bench_metric"}
    assert metric_events["primary"]["metric"] == result["metric"]
    assert metric_events["primary"]["value"] == result["value"]
    assert metric_events["secondary"]["metric"] == sec["metric"]

    # ISSUE 3 capture layer, end to end on the real bench: the stage
    # brackets snapshotted device memory, fit_ensemble priced its
    # lockstep epoch program (memory_profile), and BENCH_PROFILE left a
    # bounded trace artifact announced via profile_captured.
    assert {"memory_snapshot", "memory_profile",
            "profile_captured", "data_load"} <= kinds, sorted(kinds)
    mem_labels = {e["label"] for e in events
                  if e["kind"] == "memory_profile"}
    assert "ensemble_epoch" in mem_labels
    (prof,) = [e for e in events if e["kind"] == "profile_captured"]
    assert prof["label"] == "mcd_framework"
    trace_glob = os.path.join(run_dir, prof["trace_dir"],
                              "plugins", "profile", "*", "*")
    assert glob.glob(trace_glob), f"no trace artifact at {trace_glob}"

    # And the read side renders it without touching jax.
    text = telemetry.summarize_run(run_dir)
    assert "de_train" in text and "errors: none" in text
    assert "hbm (compiled memory analysis):" in text
    assert "ensemble_epoch" in text
    assert "profiler traces:" in text
    assert "bench blocks:" in text and "  mcd: ok" in text

    # The regression gate closes the loop on the same artifacts: the
    # capture against itself is clean (exit 0), and an injected -50%
    # throughput gates nonzero — BENCH_r06 vs r05 will be this command.
    from apnea_uq_tpu.cli.main import main as cli_main

    baseline = str(tmp_path / "baseline.json")
    with open(baseline, "w") as f:
        f.write(lines[0])
    worse = dict(result)
    worse["value"] = result["value"] / 2
    regressed = str(tmp_path / "regressed.json")
    with open(regressed, "w") as f:
        json.dump(worse, f)
    assert cli_main(["telemetry", "compare", baseline, baseline]) == 0
    assert cli_main(["telemetry", "compare", baseline, regressed]) == 1


@pytest.mark.slow  # two compile-probe subprocesses + the audit lowering
def test_bench_cpu_proxy_end_to_end(tmp_path, capsys):
    """The ISSUE 11 acceptance path: with the TPU backend absent (the
    exact r03-r05 condition, here entered explicitly via
    BENCH_CPU_PROXY=1 — the auto-selection on probe exhaustion is
    unit-tested in TestResolveBackend), `python bench.py` exits 0 with
    a schema-v2 payload whose backend-independent blocks are all ok,
    `telemetry compare` gates the relative metrics against a prior
    round, and `telemetry trend` renders r01-r05 plus the new round."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
           and not k.startswith("BENCH_")}
    env["BENCH_CPU_PROXY"] = "1"
    env["BENCH_PROGRESS_FILE"] = str(tmp_path / "progress.json")
    env["BENCH_RUN_DIR"] = str(tmp_path / "bench_run")
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(__file__), ".jax_cache"))
    proc = subprocess.run(
        [sys.executable, BENCH], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, f"proxy bench failed:\n{proc.stderr[-3000:]}"
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    result = json.loads(lines[0])

    # Schema-v2 proxy payload, still in the driver schema.
    assert result["schema"] == 2 and result["proxy"] is True
    assert result["metric"] == "bench_cpu_proxy"
    assert result["unit"] == "blocks" and result["value"] >= 3
    assert result["backend"]["platform"] == "cpu"
    assert result["backend"]["requested"] == "cpu-proxy"
    statuses = {n: b["status"] for n, b in result["blocks"].items()}
    # >= 3 ok blocks including compile, data-plane, audit (the
    # acceptance floor), plus the arithmetic D2H contract.
    for name in ("compile", "data_plane", "program_audit",
                 "d2h_accounting", "quality", "serve"):
        assert statuses[name] == "ok", statuses
    # Device blocks are unavailable, not errors.
    for name in ("mcd", "bootstrap", "streamed", "fused", "de_train"):
        assert statuses[name] == "unavailable", statuses
    compile_ctx = result["context"]["compile"]
    assert compile_ctx["warm"]["persistent_cache_misses"] == 0
    assert result["context"]["data_plane"]["rows"] == 256  # proxy shapes
    # The serve block is backend-aware, not backend-gated: the proxy
    # round still measures the coalescer (its pad_waste gates across
    # the proxy boundary; the CPU latencies are marked backend-bound).
    assert result["context"]["serve"]["requests"] == 64
    assert 0.0 <= result["context"]["serve"]["pad_waste"] < 1.0

    # compare: clean against itself, gating a worsened relative metric,
    # and refusing absolute throughput across the proxy boundary.
    from apnea_uq_tpu.cli.main import main as cli_main

    payload = tmp_path / "proxy_round.json"
    payload.write_text(lines[0])
    worse_doc = json.loads(lines[0])
    worse_doc["context"]["compile"]["cold_vs_warm_total"] /= 2
    worse = tmp_path / "proxy_worse.json"
    worse.write_text(json.dumps(worse_doc))
    assert cli_main(["telemetry", "compare", str(payload),
                     str(payload)]) == 0
    assert cli_main(["telemetry", "compare", str(payload),
                     str(worse)]) == 1
    capsys.readouterr()
    r02 = os.path.join(REPO, "BENCH_r02.json")
    if os.path.exists(r02):
        # The archived device round shares no backend-independent
        # metrics with a proxy round -> exit 2 (refused), never a bogus
        # cross-backend throughput comparison.
        with pytest.raises(SystemExit) as exc:
            cli_main(["telemetry", "compare", r02, str(payload)])
        assert exc.value.code == 2
        out = capsys.readouterr().out
        assert "backend-bound" in out or "no common metrics" in out

    # trend: the trajectory covers r01-r05 plus the new round.
    assert cli_main(["telemetry", "trend", str(payload)]) == 0
    text = capsys.readouterr().out
    for label in ("r01[ok]", "r02[ok]", "r03[error]", "r04[error]",
                  "r05[error]", "proxy_round[proxy]"):
        assert label in text, text


@pytest.mark.slow  # real bench subprocess up to the primary metric
def test_bench_kill_after_primary_keeps_primary_on_disk(tmp_path):
    """The r5 failure mode, made survivable: kill -9 the bench the moment
    the primary metric is measured (mid-run, context blocks and the DE
    secondary still pending) and the primary must already be on disk in
    full driver schema."""
    import signal

    progress = str(tmp_path / "progress.json")
    run_dir = str(tmp_path / "bench_run")
    proc = subprocess.Popen(
        [sys.executable, BENCH], cwd=REPO, env=_smoke_env(progress, run_dir),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 900
        saved = {}
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                out, err = proc.communicate()
                pytest.fail(
                    f"bench exited rc={proc.returncode} before the kill "
                    f"window:\n{err[-2000:]}"
                )
            try:
                with open(progress) as f:
                    saved = json.load(f)
            except (OSError, ValueError):
                saved = {}
            if "primary" in saved:
                proc.send_signal(signal.SIGKILL)
                break
            time.sleep(0.2)
        else:
            pytest.fail("primary metric never appeared in the progress file")
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()

    # The already-captured primary survives the kill, in full schema.
    with open(progress) as f:
        survived = json.load(f)
    primary = survived["primary"]
    assert primary["metric"] == "mcd_t50_inference_throughput"
    assert primary["unit"] == "windows/sec/chip"
    assert primary["value"] > 0
    assert primary["vs_baseline"] > 0
    assert primary["context"]["model_flops_per_window"] > 0

    # The telemetry event log shares the crash-survivability contract:
    # flushed per event, everything up to the kill is on disk (possibly
    # with a tolerated torn tail), starting with run_started.
    from apnea_uq_tpu import telemetry

    events = telemetry.read_events(run_dir)
    assert events and events[0]["kind"] == "run_started"
    assert not any(e["kind"] == "run_finished" for e in events)


class TestProgressFile:
    """The incremental-checkpoint machinery itself (fast, no subprocess):
    atomic read-modify-write per block, reset-per-run, disable knob."""

    def test_record_preserves_earlier_blocks(self, bench_mod, monkeypatch,
                                             tmp_path):
        path = str(tmp_path / "p.json")
        monkeypatch.setenv("BENCH_PROGRESS_FILE", path)
        bench_mod._progress_reset()
        assert bench_mod._progress_read() == {}
        out = bench_mod._progress_record("primary", {"value": 1})
        assert out == {"value": 1}
        bench_mod._progress_record("secondary", {"value": 2})
        assert bench_mod._progress_read() == {
            "primary": {"value": 1}, "secondary": {"value": 2}}
        # Re-recording a key overwrites just that key (the incremental
        # context updates bench_mcd performs mid-run).
        bench_mod._progress_record("primary", {"value": 3})
        assert bench_mod._progress_read()["primary"] == {"value": 3}
        assert bench_mod._progress_read()["secondary"] == {"value": 2}

    def test_reset_starts_fresh(self, bench_mod, monkeypatch, tmp_path):
        path = str(tmp_path / "p.json")
        monkeypatch.setenv("BENCH_PROGRESS_FILE", path)
        bench_mod._progress_record("primary", {"value": 1})
        bench_mod._progress_reset()
        assert bench_mod._progress_read() == {}

    def test_corrupt_file_reads_empty(self, bench_mod, monkeypatch,
                                      tmp_path):
        path = tmp_path / "p.json"
        path.write_text("{truncated")
        monkeypatch.setenv("BENCH_PROGRESS_FILE", str(path))
        assert bench_mod._progress_read() == {}

    def test_empty_path_disables(self, bench_mod, monkeypatch, tmp_path):
        monkeypatch.chdir(tmp_path)
        monkeypatch.setenv("BENCH_PROGRESS_FILE", "")
        bench_mod._progress_reset()
        out = bench_mod._progress_record("primary", {"value": 1})
        assert out == {"value": 1}  # still returns the value for chaining
        assert list(tmp_path.iterdir()) == []  # nothing written anywhere


@pytest.fixture(scope="module")
def bench_mod():
    # exec_module runs bench.py's top level IN THIS PROCESS; an ambient
    # BENCH_PLATFORM (or BENCH_CPU_PROXY, which triggers the same
    # config update) would make it jax.config.update the suite's global
    # platform mid-run, so shield both for the import (module-scope
    # fixture, so no monkeypatch — restore by hand).
    saved = {k: os.environ.pop(k, None)
             for k in ("BENCH_PLATFORM", "BENCH_CPU_PROXY")}
    try:
        spec = importlib.util.spec_from_file_location(
            "_bench_under_test", BENCH)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
    yield mod
    mod._set_proxy(False)  # never leak proxy state into other tests


def _proc(rc: int, stderr: str = "") -> types.SimpleNamespace:
    return types.SimpleNamespace(returncode=rc, stderr=stderr, stdout="")


class TestResolveBackend:
    """The init retry + CPU-proxy fallback (ISSUE 11 tentpole, piece 2):
    transient failures retry with backoff, exhaustion now degrades to
    the CPU-proxy capture instead of aborting (the exact r03-r05 loss),
    BENCH_CPU_PROXY=0 restores the exit-2 abort WITH surviving progress
    folded into the error payload, and the budget/probe-count knobs are
    env-configurable."""

    @pytest.fixture(autouse=True)
    def _clean_env(self, monkeypatch):
        for k in ("BENCH_PLATFORM", "BENCH_CPU_PROXY",
                  "BENCH_BACKEND_BUDGET_S", "BENCH_BACKEND_PROBES"):
            monkeypatch.delenv(k, raising=False)
        # Keep the abort path's probe run log out of the repo cwd.
        monkeypatch.setenv("BENCH_RUN_DIR", "")

    def test_transient_unavailable_retries_then_succeeds(
        self, bench_mod, monkeypatch
    ):
        calls, sleeps = [], []
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "600")

        def fake_run(cmd, **kw):
            calls.append(cmd)
            if len(calls) < 3:
                return _proc(1, "jaxlib.xla_extension.XlaRuntimeError: "
                                "UNAVAILABLE: TPU backend setup error")
            return _proc(0)

        monkeypatch.setattr(subprocess, "run", fake_run)
        monkeypatch.setattr(time, "sleep", sleeps.append)
        proxy, records = bench_mod._resolve_backend()
        assert proxy is False
        assert len(calls) == 3
        assert sleeps == [20.0, 32.0]  # backoff between failed probes
        # The probe trail is returned for replay into the run log.
        assert [r["green"] for r in records] == [False, False, True]
        assert records[0]["attempt"] == 1

    def test_exhausted_budget_degrades_to_cpu_proxy(
        self, bench_mod, monkeypatch, capsys
    ):
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "1")
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: _proc(1, "UNAVAILABLE: flapping tunnel"),
        )
        monkeypatch.setattr(time, "sleep", lambda s: None)
        config_updates = []
        monkeypatch.setattr(bench_mod.jax.config, "update",
                            lambda k, v: config_updates.append((k, v)))
        proxy, records = bench_mod._resolve_backend()
        assert proxy is True
        assert records and not any(r["green"] for r in records)
        assert "UNAVAILABLE: flapping tunnel" in records[-1]["detail"]
        # The auto-proxy retargeted jax, and nothing printed to stdout
        # (no bench_error line: the capture continues).
        assert ("jax_platforms", "cpu") in config_updates
        assert capsys.readouterr().out == ""

    def test_cpu_proxy_zero_forbids_fallback_and_folds_progress(
        self, bench_mod, monkeypatch, capsys, tmp_path
    ):
        """The old abort contract, opted back into — now preserving the
        checkpoints that survived in BENCH_PROGRESS_FILE inside the
        error payload (ISSUE 11 satellite 1).  The abort fires BEFORE
        the per-run progress reset, so the surviving content is a
        previous run's: it rides under prior_progress, never as this
        run's blocks/primary (which compare/watch would gate as fresh
        evidence)."""
        monkeypatch.setenv("BENCH_CPU_PROXY", "0")
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "1")
        monkeypatch.setenv("BENCH_RUN_DIR", str(tmp_path / "rl"))
        progress = tmp_path / "progress.json"
        progress.write_text(json.dumps({
            "blocks": {"compile": {"status": "ok", "seconds": 3.0}},
            "primary": {"metric": "mcd_t50_inference_throughput",
                        "value": 9000.0, "unit": "windows/sec/chip"},
        }))
        monkeypatch.setenv("BENCH_PROGRESS_FILE", str(progress))
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: _proc(1, "UNAVAILABLE: flapping tunnel"),
        )
        monkeypatch.setattr(time, "sleep", lambda s: None)
        with pytest.raises(SystemExit) as exc:
            bench_mod._resolve_backend()
        assert exc.value.code == 2
        err = json.loads(capsys.readouterr().out.strip())
        assert err["metric"] == "bench_error"
        assert err["unit"] == "error"
        assert "UNAVAILABLE: flapping tunnel" in err["error"]
        assert err["schema"] == 2
        # The surviving checkpoints ride along under prior_progress —
        # preserved, but never as THIS run's blocks (nothing ran yet).
        assert "blocks" not in err and "primary" not in err
        prior = err["prior_progress"]
        assert prior["blocks"]["compile"]["status"] == "ok"
        assert prior["primary"]["value"] == 9000.0
        # And the probe trail landed in the run log, without a topology
        # probe that could hang on the dead backend.
        from apnea_uq_tpu import telemetry

        events = telemetry.read_events(str(tmp_path / "rl"))
        assert [e["kind"] for e in events][:2] == ["run_started", "probe"]
        assert events[-1] == {**events[-1], "kind": "run_finished",
                              "status": "error"}

    def test_explicit_cpu_proxy_skips_probe(self, bench_mod, monkeypatch):
        def boom(cmd, **kw):  # pragma: no cover - must not run
            raise AssertionError("probe must not run under BENCH_CPU_PROXY")

        monkeypatch.setenv("BENCH_CPU_PROXY", "1")
        monkeypatch.setattr(subprocess, "run", boom)
        assert bench_mod._resolve_backend() == (True, [])

    def test_hang_mode_reported_in_probe_trail(self, bench_mod,
                                               monkeypatch):
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "1")
        monkeypatch.setattr(time, "sleep", lambda s: None)
        monkeypatch.setattr(bench_mod.jax.config, "update",
                            lambda k, v: None)

        def hang(cmd, **kw):
            raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 120))

        monkeypatch.setattr(subprocess, "run", hang)
        proxy, records = bench_mod._resolve_backend()
        assert proxy is True
        assert "hung" in records[-1]["detail"]

    def test_platform_override_skips_probe(self, bench_mod, monkeypatch):
        def boom(cmd, **kw):  # pragma: no cover - must not run
            raise AssertionError("probe must not run under BENCH_PLATFORM")

        monkeypatch.setenv("BENCH_PLATFORM", "cpu")
        monkeypatch.setattr(subprocess, "run", boom)
        assert bench_mod._resolve_backend() == (False, [])

    def test_zero_budget_disables(self, bench_mod, monkeypatch):
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "0")
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: (_ for _ in ()).throw(AssertionError),
        )
        assert bench_mod._resolve_backend() == (False, [])

    def test_backend_budget_env_wins_over_init_wait(self, bench_mod,
                                                    monkeypatch):
        # BENCH_BACKEND_BUDGET_S=0 disables even with a nonzero
        # BENCH_INIT_WAIT_SECS: the new knob is the one consulted first.
        monkeypatch.setenv("BENCH_BACKEND_BUDGET_S", "0")
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "600")
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: (_ for _ in ()).throw(AssertionError),
        )
        assert bench_mod._resolve_backend() == (False, [])

    def test_backend_probes_caps_attempt_count(self, bench_mod,
                                               monkeypatch):
        monkeypatch.setenv("BENCH_BACKEND_BUDGET_S", "600")
        monkeypatch.setenv("BENCH_BACKEND_PROBES", "2")
        calls = []
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: (calls.append(cmd)
                               or _proc(1, "UNAVAILABLE")),
        )
        monkeypatch.setattr(time, "sleep", lambda s: None)
        monkeypatch.setattr(bench_mod.jax.config, "update",
                            lambda k, v: None)
        proxy, records = bench_mod._resolve_backend()
        assert proxy is True
        assert len(calls) == 2 and len(records) == 2


def _stub_blocks(bench_mod, monkeypatch, *, fail=(), values=None):
    """Stub every heavy bench block with tiny dict payloads; block names
    in ``fail`` raise instead.  Returns the value map for assertions."""
    values = values or {}

    def v(name, default):
        return values.get(name, default)

    def make(name, result, state=None):
        def fn(*a, **k):
            if name in fail:
                raise RuntimeError(f"{name} boom")
            return (result, state) if state is not None else result
        return fn

    monkeypatch.setattr(bench_mod, "bench_mcd", make(
        "mcd",
        v("mcd", {"metric": "mcd_t50_inference_throughput", "value": 100.0,
                  "unit": "windows/sec/chip", "vs_baseline": 10.0}),
        {"model": None, "variables": None, "x": None,
         "n_passes": 4, "chunk": 64}))
    monkeypatch.setattr(bench_mod, "bench_de_train", make(
        "de_train",
        v("de_train", {"metric": "de2_train_wallclock", "value": 2.0,
                       "unit": "seconds", "vs_baseline": 3.0}),
        {"model": None, "x": None, "y": None, "batch": 32}))
    monkeypatch.setattr(bench_mod, "bench_bootstrap", make(
        "bootstrap", v("bootstrap", {"speedup": 20.0})))
    monkeypatch.setattr(bench_mod, "bench_streamed", make(
        "streamed", v("streamed", {"mcd_streamed_vs_inhbm": 1.1,
                                   "de10_streamed_vs_inhbm": 1.2})))
    monkeypatch.setattr(bench_mod, "bench_fused", make(
        "fused", v("fused", {"fused_vs_full": 0.8,
                             "d2h_bytes_full": 4096,
                             "d2h_bytes_fused": 4096})))
    monkeypatch.setattr(bench_mod, "bench_mcd_kernel", make(
        "mcd_kernel", v("mcd_kernel", {"xla_vs_pallas": 1.0,
                                       "f32_vs_bf16": 1.5,
                                       "pallas_engine": "xla"})))
    monkeypatch.setattr(bench_mod, "bench_de_kernel", make(
        "de_kernel", v("de_kernel", {"xla_vs_pallas": 1.0,
                                     "f32_vs_bf16": 1.4,
                                     "pallas_engine": "xla"})))
    monkeypatch.setattr(bench_mod, "bench_autotune", make(
        "autotune", v("autotune", {"labels": 4,
                                   "best_label": "de_serve_b16_pallas_fused",
                                   "best_vs_default": 1.0})))
    monkeypatch.setattr(bench_mod, "bench_de_earlystop_waste", make(
        "earlystop_waste", v("earlystop_waste", {"patience": 5})))
    monkeypatch.setattr(bench_mod, "bench_compile_startup", make(
        "compile", v("compile", {"cold_vs_warm_total": 4.0})))
    monkeypatch.setattr(bench_mod, "bench_program_audit", make(
        "program_audit", v("program_audit", {
            "clean": True, "unsuppressed": 0,
            "programs": {"mcd_predict_fused": {"flops": 1000,
                                               "arithmetic_intensity": 2}},
        })))
    monkeypatch.setattr(bench_mod, "bench_data_plane", make(
        "data_plane", v("data_plane", {"npz_load_s": 0.5,
                                       "store_rows_per_s": 1000.0})))
    monkeypatch.setattr(bench_mod, "bench_d2h_accounting", make(
        "d2h_accounting", v("d2h_accounting", {"d2h_bytes_full": 4096,
                                               "d2h_bytes_fused": 4096})))
    monkeypatch.setattr(bench_mod, "bench_quality", make(
        "quality", v("quality", {"ece": 0.01, "brier": 0.16,
                                 "self_max_psi": 0.0,
                                 "shifted_max_psi": 2.0})))
    monkeypatch.setattr(bench_mod, "bench_serve", make(
        "serve", v("serve", {"requests": 64, "windows": 160,
                             "batches": 1, "p50_ms": 5.0, "p95_ms": 9.0,
                             "p99_ms": 10.0, "windows_per_s": 2000.0,
                             "queue_wait_mean_s": 0.001,
                             "pad_waste": 0.375})))
    monkeypatch.setattr(bench_mod, "bench_capacity", make(
        "capacity", v("capacity", {
            "replicas": 2, "arrival": "poisson",
            "cells": [{"offered_rps": 4.0, "achieved_ratio": 1.0,
                       "p99_ms": 50.0, "windows_per_s": 10.0}],
            "knee_offered_rps": None, "knee_reason": None,
            "peak_windows_per_s": 10.0})))


class TestMainDispatch:
    """main()'s block orchestration, metric routing, and watchdog
    lifecycle, with the heavy bench blocks stubbed out — the branches
    the CPU smoke run does not execute (BENCH_METRIC=de_train,
    BENCH_SKIP_DE, and the per-block failure paths)."""

    @pytest.fixture(autouse=True)
    def stub(self, bench_mod, monkeypatch, tmp_path):
        monkeypatch.setenv("BENCH_PLATFORM", "cpu")  # skip the init probe
        # main() checkpoints each block to the progress file and opens a
        # telemetry run dir; keep both writes out of the repo cwd.
        monkeypatch.setenv("BENCH_PROGRESS_FILE",
                           str(tmp_path / "progress.json"))
        monkeypatch.setenv("BENCH_RUN_DIR", str(tmp_path / "bench_run"))
        # Every test starts from a clean knob state — ambient exported
        # BENCH_METRIC/BENCH_SKIP_* must not reroute the branch under
        # test (the same sanitization the subprocess smoke test does).
        for k in ("BENCH_METRIC", "BENCH_SKIP_DE", "BENCH_SKIP_STREAMED",
                  "BENCH_SKIP_FUSED", "BENCH_SKIP_MCD_KERNEL",
                  "BENCH_SKIP_DE_KERNEL", "BENCH_SKIP_AUTOTUNE",
                  "BENCH_SKIP_COMPILE",
                  "BENCH_SKIP_AUDIT", "BENCH_SKIP_DATA",
                  "BENCH_SKIP_QUALITY", "BENCH_SKIP_SERVE",
                  "BENCH_SKIP_CAPACITY", "BENCH_CAPACITY_RATES",
                  "BENCH_CAPACITY_REPLICAS", "BENCH_CAPACITY_REQUESTS",
                  "BENCH_CAPACITY_P99_BUDGET_MS",
                  "BENCH_CPU_PROXY", "BENCH_WASTE_EPOCHS"):
            monkeypatch.delenv(k, raising=False)
        _stub_blocks(bench_mod, monkeypatch)
        self.bench_mod = bench_mod
        self.tmp_path = tmp_path

    def _run(self, capsys):
        self.bench_mod.main()
        return json.loads(capsys.readouterr().out.strip())

    def test_default_is_mcd_plus_de_secondary(self, capsys):
        out = self._run(capsys)
        assert out["metric"] == "mcd_t50_inference_throughput"
        assert out["secondary"]["metric"] == "de2_train_wallclock"
        assert out["schema"] == 2 and out["proxy"] is False
        ok = {n for n, b in out["blocks"].items() if b["status"] == "ok"}
        assert ok == {"mcd", "bootstrap", "streamed", "fused", "mcd_kernel",
                      "de_kernel", "autotune", "de_train",
                      "earlystop_waste", "compile",
                      "program_audit", "data_plane", "d2h_accounting",
                      "quality", "serve", "capacity"}
        assert out["context"]["bootstrap_b100_m293k"] == {"speedup": 20.0}
        assert out["context"]["serve"]["pad_waste"] == 0.375
        assert out["context"]["de_kernel"]["xla_vs_pallas"] == 1.0
        assert out["context"]["autotune"]["best_vs_default"] == 1.0
        assert (out["secondary"]["context"]["early_stop_waste"]
                == {"patience": 5})

    def test_skip_serve_records_clean_skip(self, monkeypatch, capsys):
        """ISSUE 15 satellite: BENCH_SKIP_SERVE=1 skips the serve block
        cleanly — a skipped status with its reason in the v2 envelope,
        no serve context value, and no serving telemetry emitted."""
        monkeypatch.setenv("BENCH_SKIP_SERVE", "1")
        out = self._run(capsys)
        assert out["blocks"]["serve"] == {"status": "skipped",
                                          "reason": "BENCH_SKIP_SERVE"}
        assert out["context"]["serve"] is None
        from apnea_uq_tpu import telemetry

        events = telemetry.read_events(str(self.tmp_path / "bench_run"))
        assert not any(e["kind"].startswith("serve_") for e in events)

    def test_skip_capacity_records_clean_skip(self, monkeypatch, capsys):
        """ISSUE 18: BENCH_SKIP_CAPACITY=1 skips the capacity sweep
        cleanly — skipped status with its reason, no capacity context,
        every other block untouched."""
        monkeypatch.setenv("BENCH_SKIP_CAPACITY", "1")
        out = self._run(capsys)
        assert out["blocks"]["capacity"] == {
            "status": "skipped", "reason": "BENCH_SKIP_CAPACITY"}
        assert out["context"]["capacity"] is None
        assert out["blocks"]["serve"]["status"] == "ok"

    def test_skip_de_kernel_records_clean_skip(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_SKIP_DE_KERNEL", "1")
        out = self._run(capsys)
        assert out["blocks"]["de_kernel"] == {
            "status": "skipped", "reason": "BENCH_SKIP_DE_KERNEL"}
        assert out["context"]["de_kernel"] is None
        assert out["blocks"]["autotune"]["status"] == "ok"

    def test_skip_autotune_records_clean_skip(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_SKIP_AUTOTUNE", "1")
        out = self._run(capsys)
        assert out["blocks"]["autotune"] == {
            "status": "skipped", "reason": "BENCH_SKIP_AUTOTUNE"}
        assert out["context"]["autotune"] is None
        assert out["blocks"]["de_kernel"]["status"] == "ok"

    def test_skip_de_drops_secondary(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_SKIP_DE", "1")
        out = self._run(capsys)
        assert out["metric"] == "mcd_t50_inference_throughput"
        assert "secondary" not in out
        assert out["blocks"]["de_train"] == {"status": "skipped",
                                             "reason": "BENCH_SKIP_DE"}
        assert out["blocks"]["earlystop_waste"]["status"] == "skipped"

    def test_de_train_metric_runs_alone(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_METRIC", "de_train")
        out = self._run(capsys)
        assert out["metric"] == "de2_train_wallclock"
        assert "secondary" not in out
        assert out["blocks"]["de_train"]["status"] == "ok"
        assert out["blocks"]["mcd"] == {"status": "skipped",
                                        "reason": "BENCH_METRIC=de_train"}
        assert out["context"]["early_stop_waste"] == {"patience": 5}

    def test_watchdog_cancelled_after_results(self, monkeypatch, capsys):
        cancelled = []

        class Timer:
            def cancel(self):
                cancelled.append(True)

        monkeypatch.setattr(
            self.bench_mod, "_start_watchdog", lambda: Timer())
        self._run(capsys)
        assert cancelled == [True]


class TestBlockIsolation:
    """ISSUE 11 satellite 3: force blocks to raise and assert the
    payload stays parseable, the other blocks keep their real values,
    and `telemetry compare` gates the ok blocks — exiting 2 only when
    NO block is comparable."""

    @pytest.fixture(autouse=True)
    def _env(self, bench_mod, monkeypatch, tmp_path):
        monkeypatch.setenv("BENCH_PLATFORM", "cpu")
        monkeypatch.setenv("BENCH_PROGRESS_FILE",
                           str(tmp_path / "progress.json"))
        monkeypatch.setenv("BENCH_RUN_DIR", str(tmp_path / "bench_run"))
        for k in ("BENCH_METRIC", "BENCH_SKIP_DE", "BENCH_SKIP_STREAMED",
                  "BENCH_SKIP_FUSED", "BENCH_SKIP_MCD_KERNEL",
                  "BENCH_SKIP_DE_KERNEL", "BENCH_SKIP_AUTOTUNE",
                  "BENCH_SKIP_COMPILE",
                  "BENCH_SKIP_AUDIT", "BENCH_SKIP_DATA",
                  "BENCH_SKIP_QUALITY", "BENCH_SKIP_SERVE",
                  "BENCH_SKIP_CAPACITY", "BENCH_CAPACITY_RATES",
                  "BENCH_CAPACITY_REPLICAS", "BENCH_CAPACITY_REQUESTS",
                  "BENCH_CAPACITY_P99_BUDGET_MS",
                  "BENCH_CPU_PROXY", "BENCH_WASTE_EPOCHS"):
            monkeypatch.delenv(k, raising=False)
        self.bench_mod = bench_mod
        self.tmp_path = tmp_path

    def _run_to_file(self, capsys, name) -> str:
        self.bench_mod.main()
        line = capsys.readouterr().out.strip()
        path = self.tmp_path / name
        path.write_text(line)
        return str(path)

    def test_one_raising_block_degrades_to_its_status(
        self, monkeypatch, capsys
    ):
        _stub_blocks(self.bench_mod, monkeypatch, fail=("bootstrap",))
        self.bench_mod.main()  # exits 0: other blocks measured
        out = json.loads(capsys.readouterr().out.strip())
        # (a) the payload is parseable, in full driver schema.
        assert out["metric"] == "mcd_t50_inference_throughput"
        assert out["value"] == 100.0
        # (b) the failed block carries its status + error tail; every
        # other block reports ok with its real values.
        boot = out["blocks"]["bootstrap"]
        assert boot["status"] == "error"
        assert "bootstrap boom" in boot["error_tail"]
        assert boot["seconds"] >= 0
        others = {n: b["status"] for n, b in out["blocks"].items()
                  if n != "bootstrap"}
        assert set(others.values()) == {"ok"}, others
        assert out["context"]["bootstrap_b100_m293k"] == {
            "error": "RuntimeError: bootstrap boom"}
        assert out["context"]["data_plane"]["npz_load_s"] == 0.5
        # The run log mirrors the per-block outcome.
        from apnea_uq_tpu import telemetry

        events = telemetry.read_events(str(self.tmp_path / "bench_run"))
        block_events = {e["name"]: e["status"] for e in events
                        if e["kind"] == "bench_block"}
        assert block_events["bootstrap"] == "error"
        assert block_events["compile"] == "ok"
        # A run with a failed block still closes ok (blocks measured).
        assert events[-1]["status"] == "ok"

    def test_context_values_checkpoint_incrementally(
        self, monkeypatch, capsys, tmp_path
    ):
        """The pre-v2 per-block re-record contract survives the block
        runner: each context block's VALUE is on disk the moment it is
        measured, so a watchdog fire after N good context blocks folds
        N measured values — not just N ok statuses — into the error
        payload."""
        _stub_blocks(self.bench_mod, monkeypatch)
        progress = self.tmp_path / "progress.json"
        seen = {}

        def spy(*a, **k):
            # d2h_accounting is the LAST block: every earlier context
            # value must already be checkpointed when it runs.
            with open(progress) as f:
                saved = json.load(f)
            seen["ctx"] = dict(saved["primary"]["context"])
            return {"d2h_bytes_full": 1, "d2h_bytes_fused": 1}

        monkeypatch.setattr(self.bench_mod, "bench_d2h_accounting", spy)
        self.bench_mod.main()
        capsys.readouterr()
        assert seen["ctx"]["bootstrap_b100_m293k"] == {"speedup": 20.0}
        assert seen["ctx"]["compile"] == {"cold_vs_warm_total": 4.0}
        assert seen["ctx"]["data_plane"]["npz_load_s"] == 0.5

    def test_compare_gates_ok_blocks_of_partial_payload(
        self, monkeypatch, capsys
    ):
        from apnea_uq_tpu.cli.main import main as cli_main

        _stub_blocks(self.bench_mod, monkeypatch, fail=("bootstrap",))
        base = self._run_to_file(capsys, "base.json")
        # Same values -> clean pass over the ok blocks' metrics.
        assert cli_main(["telemetry", "compare", base, base]) == 0
        capsys.readouterr()
        # Worsen one OK block's metric -> exit 1 (the gate still works
        # over a partial payload).
        _stub_blocks(self.bench_mod, monkeypatch, fail=("bootstrap",),
                     values={"streamed": {"mcd_streamed_vs_inhbm": 2.5,
                                          "de10_streamed_vs_inhbm": 1.2}})
        worse = self._run_to_file(capsys, "worse.json")
        assert cli_main(["telemetry", "compare", base, worse]) == 1
        capsys.readouterr()

    def test_compare_exits_2_only_when_no_block_comparable(
        self, monkeypatch, capsys
    ):
        from apnea_uq_tpu.cli.main import main as cli_main

        all_blocks = ("mcd", "de_train", "bootstrap", "streamed", "fused",
                      "mcd_kernel", "de_kernel", "autotune",
                      "earlystop_waste", "compile",
                      "program_audit", "data_plane", "d2h_accounting",
                      "quality", "serve", "capacity")
        _stub_blocks(self.bench_mod, monkeypatch)
        good = self._run_to_file(capsys, "good.json")
        _stub_blocks(self.bench_mod, monkeypatch, fail=all_blocks)
        with pytest.raises(SystemExit) as exc:
            self.bench_mod.main()  # nothing measured -> exit 2
        assert exc.value.code == 2
        line = capsys.readouterr().out.strip()
        dead = json.loads(line)  # still parseable
        assert dead["metric"] == "bench_partial"
        assert dead["value"] == 0 and dead["unit"] == "blocks"
        dead_path = self.tmp_path / "dead.json"
        dead_path.write_text(line)
        with pytest.raises(SystemExit) as exc:
            cli_main(["telemetry", "compare", str(dead_path), good])
        assert exc.value.code == 2
        assert "no comparable metrics" in capsys.readouterr().out

