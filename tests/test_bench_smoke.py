"""bench.py execution coverage (r4 verdict item 2).

Two rounds of bench rework shipped without ever executing — the TPU
tunnel was down and the script had no off-TPU path — so a bench-script
bug could silently waste the next hardware capture.  These tests make
that impossible:

* the CPU smoke test runs the REAL ``python bench.py`` end-to-end at
  tiny shapes (``BENCH_PLATFORM=cpu`` + size knobs) and asserts the one
  JSON line carries the full schema — primary metric, DE secondary, and
  the streamed-overhead + bootstrap context blocks with no degraded
  ``error`` fields;
* the ``_wait_for_backend`` unit tests cover the init retry loop added
  for the *fast-fail* outage mode (r4's capture died in seconds on
  ``UNAVAILABLE``): transient failures retry with backoff, an exhausted
  budget emits the standard ``bench_error`` JSON line and exits 2, and
  explicit platform overrides skip the probe entirely.
"""

import glob
import importlib.util
import json
import os
import subprocess
import sys
import time
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

SMOKE_ENV = {
    # Retarget the backend from inside bench.py (sitecustomize pins
    # JAX_PLATFORMS=axon at boot, so the subprocess is the production
    # smoke path, not a test shortcut).
    "BENCH_PLATFORM": "cpu",
    "BENCH_DTYPE": "float32",  # CPU emulates bf16 convs too slowly
    "BENCH_WINDOWS": "256",
    "BENCH_PASSES": "4",
    "BENCH_CHUNK": "64",
    # XLA:CPU backward convolutions run far off peak, so the DE-train
    # block dominates the smoke wall-clock — keep its shapes minimal.
    "BENCH_MEMBERS": "2",
    "BENCH_TRAIN_WINDOWS": "64",
    "BENCH_EPOCHS": "1",
    "BENCH_BATCH": "32",
    "BENCH_DE_REPS": "1",
    "BENCH_DE_CHUNK": "64",
    "BENCH_BOOT_WINDOWS": "2048",
    "BENCH_WATCHDOG_SECS": "900",
    # Exercise the bounded trace capture (ISSUE 3): one extra
    # steady-state MCD pass AFTER the timed reps, profiled into the run
    # dir — cheap at smoke shapes, and proves the profiler path off-TPU.
    "BENCH_PROFILE": "1",
}


def test_readme_smoke_recipe_pins_every_smoke_knob():
    """The README's off-TPU recipe claims test parity with this module
    ('runs exactly this end-to-end in CI'), so every knob SMOKE_ENV pins
    must appear in the README command verbatim (r5 advisor finding: the
    recipe was missing DE_REPS/DE_CHUNK/WATCHDOG and ran a ~3x longer DE
    phase than the test it cited)."""
    readme = open(os.path.join(REPO, "README.md")).read()
    for k, v in SMOKE_ENV.items():
        assert f"{k}={v}" in readme, (
            f"README off-TPU smoke recipe is missing {k}={v}; keep it in "
            f"sync with tests/test_bench_smoke.py SMOKE_ENV"
        )
    # The recipe's pre-flight includes the static hazard gate (ISSUE 4):
    # `apnea-uq lint` must stay in the README smoke section, since it is
    # the one check that runs in seconds and catches the bug classes
    # (donation reads, key reuse) a CPU smoke run can NEVER observe.
    assert "apnea-uq lint" in readme, (
        "README smoke recipe lost the `apnea-uq lint` gate; the static "
        "hazard lint is part of the pre-capture ritual"
    )
    # And the flow gate (ISSUE 10): the artifact-contract + write-
    # discipline check is the other seconds-fast, jax-free pre-flight
    # that catches bug classes no CPU smoke run can observe.
    assert "apnea-uq flow" in readme, (
        "README smoke recipe lost the `apnea-uq flow` gate; the "
        "pipeline dataflow check is part of the pre-capture ritual"
    )


def _smoke_env(progress_file: str, run_dir: str) -> dict:
    # Strip ambient BENCH_* knobs too: an exported BENCH_SKIP_DE/
    # BENCH_METRIC in a developer shell must not reshape the asserted
    # schema (SMOKE_ENV is the complete knob set for this run).
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
           and not k.startswith("BENCH_")}
    env.update(SMOKE_ENV)
    env["BENCH_PROGRESS_FILE"] = progress_file
    # Keep the telemetry run dir (default ./bench_run) out of the repo cwd.
    env["BENCH_RUN_DIR"] = run_dir
    # Share the suite's persistent compile cache so repeat runs are warm.
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(__file__), ".jax_cache"))
    return env


@pytest.mark.slow  # fresh interpreter + full-model CPU convs (~3-5 min)
def test_bench_cpu_smoke_end_to_end(tmp_path):
    progress = str(tmp_path / "progress.json")
    run_dir = str(tmp_path / "bench_run")
    proc = subprocess.run(
        [sys.executable, BENCH], cwd=REPO, env=_smoke_env(progress, run_dir),
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, f"bench.py failed:\n{proc.stderr[-3000:]}"
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    result = json.loads(lines[0])

    # Driver schema on the primary metric.
    assert result["metric"] == "mcd_t50_inference_throughput"
    assert result["unit"] == "windows/sec/chip"
    assert result["value"] > 0
    assert result["vs_baseline"] > 0
    assert result["effective"]["windows"] == 256

    # DE secondary in the same schema (metric name tracks BENCH_MEMBERS).
    sec = result["secondary"]
    assert sec["metric"] == "de2_train_wallclock"
    assert sec["unit"] == "seconds"
    assert sec["value"] > 0
    assert sec["vs_baseline"] > 0
    assert len(sec["effective"]["per_rep_ratios"]) == 1
    # Zero-waste accounting context: slots trained == members returned
    # (single-device mesh: nothing pads, nothing promoted), plus the
    # quantified lockstep early-stop waste at reference patience=5.
    de_ctx = sec["context"]
    assert de_ctx["effective_members"] == 2
    assert de_ctx["promoted_members"] == 0
    assert de_ctx["cost_per_member"] == pytest.approx(
        sec["value"] / de_ctx["effective_members"], rel=0.01)
    waste = de_ctx["early_stop_waste"]
    assert "error" not in waste, waste
    assert waste["patience"] == 5
    assert waste["member_epochs_computed"] == (
        waste["member_epochs_active"] + waste["wasted_member_epochs"])
    assert waste["wasted_member_epochs"] >= 0

    # Context blocks executed for real — no degraded error fields.
    ctx = result["context"]
    boot = ctx["bootstrap_b100_m293k"]
    assert "error" not in boot, boot
    assert boot["exact_ms"] > 0 and boot["poisson_ms"] > 0
    streamed = ctx["streamed_overhead"]
    assert "error" not in streamed, streamed
    for key in ("mcd_streamed_vs_inhbm", "de10_streamed_vs_inhbm"):
        assert streamed[key] > 0, (key, streamed)
    fused = ctx["fused_reduction"]
    assert "error" not in fused, fused
    assert fused["fused_s"] > 0 and fused["fused_vs_full"] > 0
    # d2h accounting: full = passes x windows x 4 bytes, fused = 4 rows
    # x windows x 4 bytes (at the smoke's BENCH_PASSES=4 they coincide).
    assert fused["d2h_bytes_full"] == 4 * 256 * 4
    assert fused["d2h_bytes_fused"] == 4 * 256 * 4
    # Compile-cost block (ISSUE 7): two real probe subprocesses against
    # one fresh cache/store pair — the cold run compiles fresh, the warm
    # run loads the stored program with ZERO fresh XLA compiles.
    compile_ctx = ctx["compile"]
    assert "error" not in compile_ctx, compile_ctx
    assert compile_ctx["cold"]["source"] == "jit"
    assert compile_ctx["cold"]["total_s"] > 0
    assert compile_ctx["warm"]["source"] == "store"
    assert compile_ctx["warm"]["persistent_cache_misses"] == 0
    assert compile_ctx["warm"]["total_s"] > 0
    # Data-plane block (ISSUE 9): cold stage-start load of the same
    # window set via monolithic .npz vs sharded memmap store, plus a
    # full streamed pass — all host-side, so the smoke run exercises it
    # for real.
    data_ctx = ctx["data_plane"]
    assert "error" not in data_ctx, data_ctx
    assert data_ctx["rows"] == 256
    assert data_ctx["npz_load_s"] > 0 and data_ctx["store_stream_s"] > 0
    assert data_ctx["store_rows_per_s"] > 0
    assert data_ctx["store_vs_npz_first_batch"] > 0
    # IR-audit block (ISSUE 8): the `apnea-uq audit` subprocess lowered
    # the inference zoo on CPU and found it clean against the checked-in
    # manifest, with per-program cost facts attached to the capture.
    audit_ctx = ctx["program_audit"]
    assert "error" not in audit_ctx, audit_ctx
    assert audit_ctx["clean"] is True and audit_ctx["unsuppressed"] == 0
    for label in ("mcd_predict_fused", "de_predict_fused", "predict_eval"):
        assert audit_ctx["programs"][label]["flops"] > 0, (label, audit_ctx)

    # The printed line was assembled from the on-disk progress capture:
    # the two artifacts are the same result by construction.
    with open(progress) as f:
        saved = json.load(f)
    assert saved["secondary"] == sec
    primary_only = {k: v for k, v in result.items() if k != "secondary"}
    assert saved["primary"] == primary_only

    # The run's telemetry event log (BENCH_RUN_DIR) captured the whole
    # bench: stages bracketed, per-epoch ensemble step metrics with
    # device-vs-dispatch time and recompile counters, and the canonical
    # ensemble_fit accounting record the DE context block was SOURCED
    # from (bench._last_ensemble_fit_event) — not recomputed inline.
    from apnea_uq_tpu import telemetry

    events = telemetry.read_events(run_dir)
    kinds = {e["kind"] for e in events}
    assert {"run_started", "stage_start", "stage_end", "step",
            "ensemble_epoch", "ensemble_fit", "bench_throughput",
            "bench_metric", "run_finished"} <= kinds, sorted(kinds)
    assert events[-1] == {**events[-1], "kind": "run_finished",
                          "status": "ok"}
    stages = {e["stage"] for e in events if e["kind"] == "stage_start"}
    assert {"mcd_framework", "mcd_reference_pattern", "de_train",
            "de_earlystop_waste"} <= stages, sorted(stages)
    steps = [e for e in events if e["kind"] == "step"]
    assert all(e["device_s"] >= e["dispatch_s"] > 0 for e in steps)
    assert all("retraces" in e and "backend_compiles" in e for e in steps)
    # The printed DE context and the event log agree because the former
    # is derived from the latter.
    fit_events = [e for e in events if e["kind"] == "ensemble_fit"]
    assert fit_events[-1]["num_members"] == de_ctx["effective_members"]
    assert (fit_events[-1]["wasted_member_epochs"]
            == waste["wasted_member_epochs"])
    metric_events = {e["role"]: e for e in events
                     if e["kind"] == "bench_metric"}
    assert metric_events["primary"]["metric"] == result["metric"]
    assert metric_events["primary"]["value"] == result["value"]
    assert metric_events["secondary"]["metric"] == sec["metric"]

    # ISSUE 3 capture layer, end to end on the real bench: the stage
    # brackets snapshotted device memory, fit_ensemble priced its
    # lockstep epoch program (memory_profile), and BENCH_PROFILE left a
    # bounded trace artifact announced via profile_captured.
    assert {"memory_snapshot", "memory_profile",
            "profile_captured", "data_load"} <= kinds, sorted(kinds)
    mem_labels = {e["label"] for e in events
                  if e["kind"] == "memory_profile"}
    assert "ensemble_epoch" in mem_labels
    (prof,) = [e for e in events if e["kind"] == "profile_captured"]
    assert prof["label"] == "mcd_framework"
    trace_glob = os.path.join(run_dir, prof["trace_dir"],
                              "plugins", "profile", "*", "*")
    assert glob.glob(trace_glob), f"no trace artifact at {trace_glob}"

    # And the read side renders it without touching jax.
    text = telemetry.summarize_run(run_dir)
    assert "de_train" in text and "errors: none" in text
    assert "hbm (compiled memory analysis):" in text
    assert "ensemble_epoch" in text
    assert "profiler traces:" in text

    # The regression gate closes the loop on the same artifacts: the
    # capture against itself is clean (exit 0), and an injected -50%
    # throughput gates nonzero — BENCH_r06 vs r05 will be this command.
    from apnea_uq_tpu.cli.main import main as cli_main

    baseline = str(tmp_path / "baseline.json")
    with open(baseline, "w") as f:
        f.write(lines[0])
    worse = dict(result)
    worse["value"] = result["value"] / 2
    regressed = str(tmp_path / "regressed.json")
    with open(regressed, "w") as f:
        json.dump(worse, f)
    assert cli_main(["telemetry", "compare", baseline, baseline]) == 0
    assert cli_main(["telemetry", "compare", baseline, regressed]) == 1


@pytest.mark.slow  # real bench subprocess up to the primary metric
def test_bench_kill_after_primary_keeps_primary_on_disk(tmp_path):
    """The r5 failure mode, made survivable: kill -9 the bench the moment
    the primary metric is measured (mid-run, context blocks and the DE
    secondary still pending) and the primary must already be on disk in
    full driver schema."""
    import signal

    progress = str(tmp_path / "progress.json")
    run_dir = str(tmp_path / "bench_run")
    proc = subprocess.Popen(
        [sys.executable, BENCH], cwd=REPO, env=_smoke_env(progress, run_dir),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 900
        saved = {}
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                out, err = proc.communicate()
                pytest.fail(
                    f"bench exited rc={proc.returncode} before the kill "
                    f"window:\n{err[-2000:]}"
                )
            try:
                with open(progress) as f:
                    saved = json.load(f)
            except (OSError, ValueError):
                saved = {}
            if "primary" in saved:
                proc.send_signal(signal.SIGKILL)
                break
            time.sleep(0.2)
        else:
            pytest.fail("primary metric never appeared in the progress file")
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()

    # The already-captured primary survives the kill, in full schema.
    with open(progress) as f:
        survived = json.load(f)
    primary = survived["primary"]
    assert primary["metric"] == "mcd_t50_inference_throughput"
    assert primary["unit"] == "windows/sec/chip"
    assert primary["value"] > 0
    assert primary["vs_baseline"] > 0
    assert primary["context"]["model_flops_per_window"] > 0

    # The telemetry event log shares the crash-survivability contract:
    # flushed per event, everything up to the kill is on disk (possibly
    # with a tolerated torn tail), starting with run_started.
    from apnea_uq_tpu import telemetry

    events = telemetry.read_events(run_dir)
    assert events and events[0]["kind"] == "run_started"
    assert not any(e["kind"] == "run_finished" for e in events)


class TestProgressFile:
    """The incremental-checkpoint machinery itself (fast, no subprocess):
    atomic read-modify-write per block, reset-per-run, disable knob."""

    def test_record_preserves_earlier_blocks(self, bench_mod, monkeypatch,
                                             tmp_path):
        path = str(tmp_path / "p.json")
        monkeypatch.setenv("BENCH_PROGRESS_FILE", path)
        bench_mod._progress_reset()
        assert bench_mod._progress_read() == {}
        out = bench_mod._progress_record("primary", {"value": 1})
        assert out == {"value": 1}
        bench_mod._progress_record("secondary", {"value": 2})
        assert bench_mod._progress_read() == {
            "primary": {"value": 1}, "secondary": {"value": 2}}
        # Re-recording a key overwrites just that key (the incremental
        # context updates bench_mcd performs mid-run).
        bench_mod._progress_record("primary", {"value": 3})
        assert bench_mod._progress_read()["primary"] == {"value": 3}
        assert bench_mod._progress_read()["secondary"] == {"value": 2}

    def test_reset_starts_fresh(self, bench_mod, monkeypatch, tmp_path):
        path = str(tmp_path / "p.json")
        monkeypatch.setenv("BENCH_PROGRESS_FILE", path)
        bench_mod._progress_record("primary", {"value": 1})
        bench_mod._progress_reset()
        assert bench_mod._progress_read() == {}

    def test_corrupt_file_reads_empty(self, bench_mod, monkeypatch,
                                      tmp_path):
        path = tmp_path / "p.json"
        path.write_text("{truncated")
        monkeypatch.setenv("BENCH_PROGRESS_FILE", str(path))
        assert bench_mod._progress_read() == {}

    def test_empty_path_disables(self, bench_mod, monkeypatch, tmp_path):
        monkeypatch.chdir(tmp_path)
        monkeypatch.setenv("BENCH_PROGRESS_FILE", "")
        bench_mod._progress_reset()
        out = bench_mod._progress_record("primary", {"value": 1})
        assert out == {"value": 1}  # still returns the value for chaining
        assert list(tmp_path.iterdir()) == []  # nothing written anywhere


@pytest.fixture(scope="module")
def bench_mod():
    # exec_module runs bench.py's top level IN THIS PROCESS; an ambient
    # BENCH_PLATFORM would make it jax.config.update the suite's global
    # platform mid-run, so shield it for the import (module-scope fixture,
    # so no monkeypatch — restore by hand).
    saved = os.environ.pop("BENCH_PLATFORM", None)
    try:
        spec = importlib.util.spec_from_file_location(
            "_bench_under_test", BENCH)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        if saved is not None:
            os.environ["BENCH_PLATFORM"] = saved
    return mod


def _proc(rc: int, stderr: str = "") -> types.SimpleNamespace:
    return types.SimpleNamespace(returncode=rc, stderr=stderr, stdout="")


class TestWaitForBackend:
    def test_transient_unavailable_retries_then_succeeds(
        self, bench_mod, monkeypatch
    ):
        calls, sleeps = [], []
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "600")

        def fake_run(cmd, **kw):
            calls.append(cmd)
            if len(calls) < 3:
                return _proc(1, "jaxlib.xla_extension.XlaRuntimeError: "
                                "UNAVAILABLE: TPU backend setup error")
            return _proc(0)

        monkeypatch.setattr(subprocess, "run", fake_run)
        monkeypatch.setattr(time, "sleep", sleeps.append)
        bench_mod._wait_for_backend()  # returns without raising
        assert len(calls) == 3
        assert sleeps == [20.0, 32.0]  # backoff between failed probes

    def test_exhausted_budget_emits_error_json_and_exits(
        self, bench_mod, monkeypatch, capsys
    ):
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "1")
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: _proc(1, "UNAVAILABLE: flapping tunnel"),
        )
        # With sleep a no-op the loop spins probes until the 1s budget's
        # monotonic deadline passes, then gives up with the error line.
        monkeypatch.setattr(time, "sleep", lambda s: None)
        with pytest.raises(SystemExit) as exc:
            bench_mod._wait_for_backend()
        assert exc.value.code == 2
        err = json.loads(capsys.readouterr().out.strip())
        assert err["metric"] == "bench_error"
        assert err["unit"] == "error"
        assert "UNAVAILABLE: flapping tunnel" in err["error"]

    def test_hang_mode_reported(self, bench_mod, monkeypatch, capsys):
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "1")
        monkeypatch.setattr(time, "sleep", lambda s: None)

        def hang(cmd, **kw):
            raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 120))

        monkeypatch.setattr(subprocess, "run", hang)
        with pytest.raises(SystemExit):
            bench_mod._wait_for_backend()
        err = json.loads(capsys.readouterr().out.strip())
        assert "hung" in err["error"]

    def test_platform_override_skips_probe(self, bench_mod, monkeypatch):
        def boom(cmd, **kw):  # pragma: no cover - must not run
            raise AssertionError("probe must not run under BENCH_PLATFORM")

        monkeypatch.setenv("BENCH_PLATFORM", "cpu")
        monkeypatch.setattr(subprocess, "run", boom)
        bench_mod._wait_for_backend()

    def test_zero_budget_disables(self, bench_mod, monkeypatch):
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "0")
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: (_ for _ in ()).throw(AssertionError),
        )
        bench_mod._wait_for_backend()


class TestMainDispatch:
    """main()'s metric routing and watchdog lifecycle, with the heavy
    bench functions stubbed out — the only bench.py lines the CPU smoke
    does not execute are the BENCH_METRIC=de_train and BENCH_SKIP_DE
    branches."""

    @pytest.fixture(autouse=True)
    def stub(self, bench_mod, monkeypatch, tmp_path):
        monkeypatch.setenv("BENCH_PLATFORM", "cpu")  # skip the init probe
        # main() checkpoints each block to the progress file and opens a
        # telemetry run dir; keep both writes out of the repo cwd.
        monkeypatch.setenv("BENCH_PROGRESS_FILE",
                           str(tmp_path / "progress.json"))
        monkeypatch.setenv("BENCH_RUN_DIR", str(tmp_path / "bench_run"))
        # Every test starts from a clean knob state — ambient exported
        # BENCH_METRIC/BENCH_SKIP_DE must not reroute the branch under
        # test (the same sanitization the subprocess smoke test does).
        monkeypatch.delenv("BENCH_METRIC", raising=False)
        monkeypatch.delenv("BENCH_SKIP_DE", raising=False)
        monkeypatch.setattr(bench_mod, "bench_mcd", lambda: {"metric": "mcd"})
        monkeypatch.setattr(
            bench_mod, "bench_de_train",
            lambda progress_key="secondary": {"metric": "de"})
        self.bench_mod = bench_mod

    def _run(self, capsys):
        self.bench_mod.main()
        return json.loads(capsys.readouterr().out.strip())

    def test_default_is_mcd_plus_de_secondary(self, capsys):
        out = self._run(capsys)
        assert out["metric"] == "mcd"
        assert out["secondary"]["metric"] == "de"

    def test_skip_de_drops_secondary(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_SKIP_DE", "1")
        out = self._run(capsys)
        assert out["metric"] == "mcd"
        assert "secondary" not in out

    def test_de_train_metric_runs_alone(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_METRIC", "de_train")
        out = self._run(capsys)
        assert out == {"metric": "de"}

    def test_watchdog_cancelled_after_results(self, monkeypatch, capsys):
        cancelled = []

        class Timer:
            def cancel(self):
                cancelled.append(True)

        monkeypatch.setattr(
            self.bench_mod, "_start_watchdog", lambda: Timer())
        self._run(capsys)
        assert cancelled == [True]

