"""bench.py execution coverage (r4 verdict item 2).

Two rounds of bench rework shipped without ever executing — the TPU
tunnel was down and the script had no off-TPU path — so a bench-script
bug could silently waste the next hardware capture.  These tests make
that impossible:

* the CPU smoke test runs the REAL ``python bench.py`` end-to-end at
  tiny shapes (``BENCH_PLATFORM=cpu`` + size knobs) and asserts the one
  JSON line carries the full schema — primary metric, DE secondary, and
  the streamed-overhead + bootstrap context blocks with no degraded
  ``error`` fields;
* the ``_wait_for_backend`` unit tests cover the init retry loop added
  for the *fast-fail* outage mode (r4's capture died in seconds on
  ``UNAVAILABLE``): transient failures retry with backoff, an exhausted
  budget emits the standard ``bench_error`` JSON line and exits 2, and
  explicit platform overrides skip the probe entirely.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

SMOKE_ENV = {
    # Retarget the backend from inside bench.py (sitecustomize pins
    # JAX_PLATFORMS=axon at boot, so the subprocess is the production
    # smoke path, not a test shortcut).
    "BENCH_PLATFORM": "cpu",
    "BENCH_DTYPE": "float32",  # CPU emulates bf16 convs too slowly
    "BENCH_WINDOWS": "256",
    "BENCH_PASSES": "4",
    "BENCH_CHUNK": "64",
    # XLA:CPU backward convolutions run far off peak, so the DE-train
    # block dominates the smoke wall-clock — keep its shapes minimal.
    "BENCH_MEMBERS": "2",
    "BENCH_TRAIN_WINDOWS": "64",
    "BENCH_EPOCHS": "1",
    "BENCH_BATCH": "32",
    "BENCH_DE_REPS": "1",
    "BENCH_DE_CHUNK": "64",
    "BENCH_BOOT_WINDOWS": "2048",
    "BENCH_WATCHDOG_SECS": "900",
}


@pytest.mark.slow  # fresh interpreter + full-model CPU convs (~3-5 min)
def test_bench_cpu_smoke_end_to_end():
    # Strip ambient BENCH_* knobs too: an exported BENCH_SKIP_DE/
    # BENCH_METRIC in a developer shell must not reshape the asserted
    # schema (SMOKE_ENV is the complete knob set for this run).
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
           and not k.startswith("BENCH_")}
    env.update(SMOKE_ENV)
    # Share the suite's persistent compile cache so repeat runs are warm.
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(__file__), ".jax_cache"))
    proc = subprocess.run(
        [sys.executable, BENCH], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, f"bench.py failed:\n{proc.stderr[-3000:]}"
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    result = json.loads(lines[0])

    # Driver schema on the primary metric.
    assert result["metric"] == "mcd_t50_inference_throughput"
    assert result["unit"] == "windows/sec/chip"
    assert result["value"] > 0
    assert result["vs_baseline"] > 0
    assert result["effective"]["windows"] == 256

    # DE secondary in the same schema (metric name tracks BENCH_MEMBERS).
    sec = result["secondary"]
    assert sec["metric"] == "de2_train_wallclock"
    assert sec["unit"] == "seconds"
    assert sec["value"] > 0
    assert sec["vs_baseline"] > 0
    assert len(sec["effective"]["per_rep_ratios"]) == 1

    # Context blocks executed for real — no degraded error fields.
    ctx = result["context"]
    boot = ctx["bootstrap_b100_m293k"]
    assert "error" not in boot, boot
    assert boot["exact_ms"] > 0 and boot["poisson_ms"] > 0
    streamed = ctx["streamed_overhead"]
    assert "error" not in streamed, streamed
    for key in ("mcd_streamed_vs_inhbm", "de10_streamed_vs_inhbm"):
        assert streamed[key] > 0, (key, streamed)


@pytest.fixture(scope="module")
def bench_mod():
    # exec_module runs bench.py's top level IN THIS PROCESS; an ambient
    # BENCH_PLATFORM would make it jax.config.update the suite's global
    # platform mid-run, so shield it for the import (module-scope fixture,
    # so no monkeypatch — restore by hand).
    saved = os.environ.pop("BENCH_PLATFORM", None)
    try:
        spec = importlib.util.spec_from_file_location(
            "_bench_under_test", BENCH)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        if saved is not None:
            os.environ["BENCH_PLATFORM"] = saved
    return mod


def _proc(rc: int, stderr: str = "") -> types.SimpleNamespace:
    return types.SimpleNamespace(returncode=rc, stderr=stderr, stdout="")


class TestWaitForBackend:
    def test_transient_unavailable_retries_then_succeeds(
        self, bench_mod, monkeypatch
    ):
        calls, sleeps = [], []
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "600")

        def fake_run(cmd, **kw):
            calls.append(cmd)
            if len(calls) < 3:
                return _proc(1, "jaxlib.xla_extension.XlaRuntimeError: "
                                "UNAVAILABLE: TPU backend setup error")
            return _proc(0)

        monkeypatch.setattr(subprocess, "run", fake_run)
        monkeypatch.setattr(time, "sleep", sleeps.append)
        bench_mod._wait_for_backend()  # returns without raising
        assert len(calls) == 3
        assert sleeps == [20.0, 32.0]  # backoff between failed probes

    def test_exhausted_budget_emits_error_json_and_exits(
        self, bench_mod, monkeypatch, capsys
    ):
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "1")
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: _proc(1, "UNAVAILABLE: flapping tunnel"),
        )
        # With sleep a no-op the loop spins probes until the 1s budget's
        # monotonic deadline passes, then gives up with the error line.
        monkeypatch.setattr(time, "sleep", lambda s: None)
        with pytest.raises(SystemExit) as exc:
            bench_mod._wait_for_backend()
        assert exc.value.code == 2
        err = json.loads(capsys.readouterr().out.strip())
        assert err["metric"] == "bench_error"
        assert err["unit"] == "error"
        assert "UNAVAILABLE: flapping tunnel" in err["error"]

    def test_hang_mode_reported(self, bench_mod, monkeypatch, capsys):
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "1")
        monkeypatch.setattr(time, "sleep", lambda s: None)

        def hang(cmd, **kw):
            raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 120))

        monkeypatch.setattr(subprocess, "run", hang)
        with pytest.raises(SystemExit):
            bench_mod._wait_for_backend()
        err = json.loads(capsys.readouterr().out.strip())
        assert "hung" in err["error"]

    def test_platform_override_skips_probe(self, bench_mod, monkeypatch):
        def boom(cmd, **kw):  # pragma: no cover - must not run
            raise AssertionError("probe must not run under BENCH_PLATFORM")

        monkeypatch.setenv("BENCH_PLATFORM", "cpu")
        monkeypatch.setattr(subprocess, "run", boom)
        bench_mod._wait_for_backend()

    def test_zero_budget_disables(self, bench_mod, monkeypatch):
        monkeypatch.delenv("BENCH_PLATFORM", raising=False)
        monkeypatch.setenv("BENCH_INIT_WAIT_SECS", "0")
        monkeypatch.setattr(
            subprocess, "run",
            lambda cmd, **kw: (_ for _ in ()).throw(AssertionError),
        )
        bench_mod._wait_for_backend()


class TestMainDispatch:
    """main()'s metric routing and watchdog lifecycle, with the heavy
    bench functions stubbed out — the only bench.py lines the CPU smoke
    does not execute are the BENCH_METRIC=de_train and BENCH_SKIP_DE
    branches."""

    @pytest.fixture(autouse=True)
    def stub(self, bench_mod, monkeypatch):
        monkeypatch.setenv("BENCH_PLATFORM", "cpu")  # skip the init probe
        # Every test starts from a clean knob state — ambient exported
        # BENCH_METRIC/BENCH_SKIP_DE must not reroute the branch under
        # test (the same sanitization the subprocess smoke test does).
        monkeypatch.delenv("BENCH_METRIC", raising=False)
        monkeypatch.delenv("BENCH_SKIP_DE", raising=False)
        monkeypatch.setattr(bench_mod, "bench_mcd", lambda: {"metric": "mcd"})
        monkeypatch.setattr(
            bench_mod, "bench_de_train", lambda: {"metric": "de"})
        self.bench_mod = bench_mod

    def _run(self, capsys):
        self.bench_mod.main()
        return json.loads(capsys.readouterr().out.strip())

    def test_default_is_mcd_plus_de_secondary(self, capsys):
        out = self._run(capsys)
        assert out["metric"] == "mcd"
        assert out["secondary"]["metric"] == "de"

    def test_skip_de_drops_secondary(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_SKIP_DE", "1")
        out = self._run(capsys)
        assert out["metric"] == "mcd"
        assert "secondary" not in out

    def test_de_train_metric_runs_alone(self, monkeypatch, capsys):
        monkeypatch.setenv("BENCH_METRIC", "de_train")
        out = self._run(capsys)
        assert out == {"metric": "de"}

    def test_watchdog_cancelled_after_results(self, monkeypatch, capsys):
        cancelled = []

        class Timer:
            def cancel(self):
                cancelled.append(True)

        monkeypatch.setattr(
            self.bench_mod, "_start_watchdog", lambda: Timer())
        self._run(capsys)
        assert cancelled == [True]

