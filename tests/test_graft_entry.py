"""Driver-contract tests for __graft_entry__.py.

The driver compile-checks ``entry()`` single-chip and runs
``dryrun_multichip(N)`` with N *virtual CPU devices of its choosing* —
not necessarily the 8 this suite's conftest pins.  The in-process test
covers entry() on the session platform; the subprocess tests boot fresh
interpreters with other device counts (16: a larger pod-shaped mesh;
5: a prime count that forces the data-axis-1 / fit_ensemble branch), so
a driver invocation at those sizes cannot be the first time that code
path ever runs.
"""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_is_jittable_and_finite():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as ge
    finally:
        sys.path.remove(REPO)
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (256,)
    assert np.isfinite(np.asarray(out)).all()


def _run_dryrun(n_devices: int, timeout: int = 600) -> str:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}"
    )
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as ge; "
         f"ge.dryrun_multichip({n_devices})"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, f"dryrun({n_devices}) failed:\n{proc.stderr[-3000:]}"
    return proc.stdout


@pytest.mark.slow  # fresh interpreter + backend boot + compiles
@pytest.mark.parametrize("n_devices,expect", [
    # 16 devices: (8, 2) mesh — both axes active, grad all-reduce present.
    (16, "grad psum on 'data'"),
    # 5 devices: prime count -> (5, 1) mesh, no data axis, the
    # fit_ensemble (non-AOT) branch.
    (5, "none (data axis = 1)"),
])
def test_dryrun_multichip_other_device_counts(n_devices, expect):
    out = _run_dryrun(n_devices)
    assert "dryrun_multichip OK" in out, out
    assert expect in out, out
