"""Exec-the-reference numeric parity.

The strongest parity evidence available: load the reference's metric core
(/root/reference/uncertainty_quantification/uq_techniques.py — pure
NumPy/SciPy once its unused ``tensorflow`` import is stubbed) and compare
it value-for-value against the framework's engines on random (K, M)
stacks.  This pins parity against the living reference code rather than
re-typed formulas:

- ``uq_evaluation_dist`` (uq_techniques.py:40-112) vs uq/metrics.py
- ``bootstrap_metrics``  (uq_techniques.py:116-172) vs the gather engine,
  driven with the reference's own ``np.random.choice`` index stream so
  per-resample values match exactly
- ``compute_confidence_intervals`` (uq_techniques.py:175-206) vs
  uq/bootstrap.py on identical bootstrap inputs
"""

import os
import types

import numpy as np
import pytest

import apnea_uq_tpu.uq.bootstrap as bootstrap_mod
from apnea_uq_tpu.uq.bootstrap import (
    AGGREGATE_KEYS,
    compute_confidence_intervals,
    gather_aggregates,
)
from apnea_uq_tpu.uq.metrics import uq_evaluation_dist

# Shared exec machinery (checksum pinning, stub installation) lives in
# tests/_reference_exec.py, shared with test_reference_driver_shells.py.
from _reference_exec import (
    REF_EVAL_PATH,
    REF_PATH,
    exec_reference_module as _exec_reference_module,
    reference_mounted,
    stub_tensorflow as _stub_tensorflow,
)

pytestmark = pytest.mark.skipif(
    not reference_mounted(), reason="reference checkout not mounted"
)


@pytest.fixture(scope="module")
def ref():
    """The reference uq_techniques module, exec'd with tf stubbed."""
    os.environ.setdefault("MPLBACKEND", "Agg")
    return _exec_reference_module(
        "ref_uq_techniques", REF_PATH, _stub_tensorflow()
    )


def _stack(rng, k=7, m=500, kind="uniform"):
    if kind == "uniform":
        p = rng.uniform(0.0, 1.0, size=(k, m))
    elif kind == "edgy":  # mass near the clip boundaries
        p = np.clip(rng.beta(0.05, 0.05, size=(k, m)), 0.0, 1.0)
    elif kind == "saturated":  # EXACT 0.0/1.0 entries exercise the eps clip
        p = rng.uniform(0.0, 1.0, size=(k, m))
        p[rng.uniform(size=(k, m)) < 0.3] = 0.0
        p[rng.uniform(size=(k, m)) < 0.3] = 1.0
    elif kind == "constant":
        p = np.full((k, m), 0.37)
    else:
        raise ValueError(kind)
    y = (rng.uniform(size=m) < 0.4).astype(np.int64)
    return p.astype(np.float32), y


VECTOR_KEYS = (
    "mean_pred",
    "pred_variance",
    "total_pred_entropy",
    "expected_aleatoric_entropy",
    "mutual_info",
)
SCALAR_KEYS = (
    "overall_mean_variance",
    "mean_variance_class_0",
    "mean_variance_class_1",
)


class TestUqEvaluationDist:
    @pytest.mark.parametrize("kind", ["uniform", "edgy", "saturated", "constant"])
    def test_matches_reference(self, ref, rng, kind):
        preds, y = _stack(rng, kind=kind)
        theirs = ref.uq_evaluation_dist(preds.astype(np.float64), y)
        ours = uq_evaluation_dist(preds, y)
        for key in VECTOR_KEYS:
            np.testing.assert_allclose(
                np.asarray(ours[key]), theirs[key], rtol=2e-5, atol=2e-6,
                err_msg=key,
            )
        for key in SCALAR_KEYS:
            assert float(ours[key]) == pytest.approx(
                float(theirs[key]), rel=2e-5, abs=2e-6
            ), key

    def test_single_pass_and_trailing_axis(self, ref, rng):
        # (K, M, 1) stacks and 1-D single-pass inputs take the same
        # degenerate path in both implementations (uq_techniques.py:61-66).
        preds, y = _stack(rng, k=1, m=64)
        theirs = ref.uq_evaluation_dist(preds.astype(np.float64), y)
        ours = uq_evaluation_dist(preds[..., None], y)
        for key in VECTOR_KEYS:
            np.testing.assert_allclose(
                np.asarray(ours[key]), theirs[key], rtol=2e-5, atol=2e-6,
                err_msg=key,
            )
        np.testing.assert_allclose(np.asarray(ours["pred_variance"]), 0.0)

    def test_empty_class_guard(self, ref, rng):
        preds, _ = _stack(rng, m=64)
        y = np.ones(64, np.int64)  # class 0 absent
        theirs = ref.uq_evaluation_dist(preds.astype(np.float64), y)
        ours = uq_evaluation_dist(preds, y)
        assert float(theirs["mean_variance_class_0"]) == 0.0
        assert float(ours["mean_variance_class_0"]) == 0.0


class TestBootstrapParity:
    def test_gather_engine_matches_reference_loop(self, ref, rng):
        """Drive the gather engine with the reference's exact index stream:
        per-resample aggregates must match the reference's
        recompute-everything loop value-for-value, which proves the
        gather formulation is the same math, not just the same
        distribution."""
        preds, y = _stack(rng, k=5, m=300)
        n_bootstrap, seed = 20, 123

        theirs = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=n_bootstrap, random_state=seed
        )
        assert len(theirs) == n_bootstrap

        # Regenerate the identical index matrix the reference drew
        # (uq_techniques.py:130-142: np.random.seed then B draws of
        # np.random.choice(M, M, replace=True)).
        np.random.seed(seed)
        m = preds.shape[1]
        idx = np.stack([np.random.choice(m, m, replace=True) for _ in range(n_bootstrap)])

        metrics = uq_evaluation_dist(preds, y)
        ours = gather_aggregates(
            metrics["pred_variance"],
            metrics["total_pred_entropy"],
            metrics["expected_aleatoric_entropy"],
            metrics["mutual_info"],
            np.asarray(y),
            idx,
        )
        for b in range(n_bootstrap):
            for key in AGGREGATE_KEYS:
                assert float(np.asarray(ours[key])[b]) == pytest.approx(
                    float(theirs[b][key]), rel=3e-5, abs=3e-6
                ), f"resample {b}, {key}"

    def test_compute_confidence_intervals_matches(self, ref, rng):
        preds, y = _stack(rng, k=5, m=300)
        results = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=30, random_state=7
        )
        theirs = ref.compute_confidence_intervals(results, alpha=0.05)
        ours = compute_confidence_intervals(results, alpha=0.05)
        assert set(ours) == set(theirs)
        for key in theirs:
            assert ours[key] == pytest.approx(theirs[key], rel=1e-12), key

    def test_ci_alpha_sweep_matches(self, ref, rng):
        preds, y = _stack(rng, k=4, m=200)
        results = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=25, random_state=11
        )
        for alpha in (0.01, 0.1, 0.32):
            theirs = ref.compute_confidence_intervals(results, alpha=alpha)
            ours = compute_confidence_intervals(results, alpha=alpha)
            for key in theirs:
                assert ours[key] == pytest.approx(theirs[key], rel=1e-12), (alpha, key)

class TestClassificationEvaluatorParity:
    """C6: exec the reference's sklearn-based evaluator
    (evaluate_classification.py:7-153) and compare the framework's
    in-tree suite value-for-value on the same probabilities."""

    @pytest.fixture(scope="class")
    def ref_eval(self):
        pytest.importorskip("sklearn")
        return _exec_reference_module(
            "ref_evaluate_classification", REF_EVAL_PATH, {}
        )

    def test_matches_reference_evaluator(self, ref_eval, rng, capsys):
        from apnea_uq_tpu.evaluation.classification import evaluate_classification

        n = 400
        probs = rng.uniform(0.0, 1.0, n)
        # Exactly-0.5 rows included deliberately: both sides threshold
        # strictly (> 0.5 -> positive), so ties predict class 0 on both.
        probs[:8] = 0.5
        y = (rng.uniform(size=len(probs)) < 0.35).astype(np.int64)

        class StubModel:
            def predict(self, x):
                return probs.reshape(-1, 1)

        theirs = ref_eval.evaluate_classification_model(
            StubModel(), np.zeros((len(probs), 1)), y
        )
        capsys.readouterr()  # swallow the reference's prints
        assert theirs is not None
        ours = evaluate_classification(probs, y)

        assert ours["accuracy"] == pytest.approx(theirs["accuracy"], abs=1e-12)
        assert ours["roc_auc"] == pytest.approx(theirs["roc_auc"], rel=1e-10)
        assert ours["cohen_kappa"] == pytest.approx(theirs["cohen_kappa"], rel=1e-10)
        assert ours["mcc"] == pytest.approx(theirs["mcc"], rel=1e-10)
        assert ours["sensitivity"] == pytest.approx(
            theirs["overall_sensitivity"], rel=1e-12)
        assert ours["specificity"] == pytest.approx(
            theirs["overall_specificity"], rel=1e-12)
        np.testing.assert_array_equal(
            np.asarray(ours["confusion_matrix"]), theirs["confusion_matrix"]
        )
        # PR-AUC definitions differ by design: the reference trapezoid-
        # integrates the PR curve (auc(recall, precision)), the framework
        # uses sklearn-style step-interpolated average precision.  They
        # agree closely but not exactly.
        assert ours["pr_auc"] == pytest.approx(theirs["auc_pr"], rel=0.02)
        # Per-class report values are the same sklearn definitions (the
        # reference's returned dict uses bare "0"/"1" keys — target_names
        # only shapes its printed report).
        for cls in ("0", "1"):
            for k in ("precision", "recall", "f1-score", "support"):
                assert ours["report"][cls][k] == pytest.approx(
                    theirs["classification_report_dict"][cls][k], rel=1e-12
                ), (cls, k)

    def test_single_class_guard_matches(self, ref_eval, rng, capsys):
        from apnea_uq_tpu.evaluation.classification import evaluate_classification

        probs = rng.uniform(0.0, 1.0, 50)

        class StubModel:
            def predict(self, x):
                return probs

        theirs = ref_eval.evaluate_classification_model(
            StubModel(), np.zeros((50, 1)), np.ones(50, np.int64)
        )
        capsys.readouterr()
        ours = evaluate_classification(probs, np.ones(50, np.int64))
        # Both report the undefined AUCs as None and keep going.
        assert theirs["roc_auc"] is None and ours["roc_auc"] is None
        assert ours["accuracy"] == pytest.approx(theirs["accuracy"], abs=1e-12)


REF_PREP_PATH = "/root/reference/data_prepocessing/preprocess_shhs_raw.py"


class TestPreprocessingParity:
    """C1: exec the reference's preprocessing module (pyedflib stubbed —
    only the EDF reader touches it) and pin the two correctness-critical
    internals against the framework's ingestion: window labeling + the
    flattened CSV layout (segment_and_label_edf_data,
    preprocess_shhs_raw.py:194-263) and artifact interpolation
    (remove_artifacts, :100-124).  The sleep-time check
    (calculate_sleep_time, :75-98) is NOT compared: it indexes the parsed
    events with capitalized keys its own parser never produces
    ("EventConcept" vs "event_concept"), so it raises KeyError on any
    non-empty event list — a reference defect, not a behavior to match."""

    @pytest.fixture(scope="class")
    def ref_prep(self):
        pytest.importorskip("scipy")
        stub = types.ModuleType("pyedflib")

        class EdfReader:  # import-time placeholder only
            pass

        stub.EdfReader = EdfReader
        return _exec_reference_module(
            "ref_preprocess_shhs_raw", REF_PREP_PATH, {"pyedflib": stub}
        )

    def test_segment_and_label_matches(self, ref_prep, rng, tmp_path):
        import pandas as pd

        from apnea_uq_tpu.config import IngestConfig
        from apnea_uq_tpu.data import WindowSet
        from apnea_uq_tpu.data.annotations import RespiratoryEvents
        from apnea_uq_tpu.data.ingest import label_windows, windows_to_reference_csv

        channels = ["SaO2", "PR", "THOR RES", "ABDO RES"]
        n_seconds = 60 * 7 + 13  # ragged tail: the short final segment drops
        edf_df = pd.DataFrame(
            {ch: rng.normal(size=n_seconds) for ch in channels}
        )[channels]
        # Overlap geometry: >=10 s inside one window, split across two
        # windows (neither side reaches 10), exactly 10 s, 9 s, and a
        # non-selected concept.
        triples = [
            ("Obstructive apnea|Obstructive Apnea", 70.0, 25.0),
            ("Hypopnea|Hypopnea", 115.0, 12.0),
            ("Central apnea|Central Apnea", 200.0, 40.0),
            ("Hypopnea|Hypopnea", 245.0, 10.0),
            ("Obstructive apnea|Obstructive Apnea", 355.0, 9.0),
        ]
        xml_df = pd.DataFrame([
            {"event_type": "Respiratory|Respiratory", "event_concept": c,
             "start": s, "duration": d}
            for c, s, d in triples
        ])
        theirs = ref_prep.segment_and_label_edf_data(edf_df, xml_df, "200123")

        n_windows = n_seconds // 60
        assert len(theirs) == n_windows
        cfg = IngestConfig()
        events = RespiratoryEvents(
            event_type=np.asarray(["Respiratory|Respiratory"] * len(triples),
                                  dtype=object),
            event_concept=np.asarray([t[0] for t in triples], dtype=object),
            start_s=np.asarray([t[1] for t in triples], float),
            duration_s=np.asarray([t[2] for t in triples], float),
            recording_duration_s=float(n_seconds),
        )
        labels = label_windows(
            n_windows, cfg.window_size_s, events,
            concepts=cfg.apnea_event_concepts,
            min_overlap_s=cfg.min_event_overlap_s,
        )
        np.testing.assert_array_equal(
            labels, theirs["Apnea/Hypopnea"].to_numpy()
        )
        # Fixed geometry: window 1 gets the 25 s obstructive overlap, the
        # 12 s hypopnea splits 5/7 across windows 1-2 (neither adds a new
        # label), the central apnea is non-selected, window 4 gets the
        # exactly-10 s hypopnea, the 9 s event stays below threshold.
        assert labels.tolist() == [0, 1, 0, 0, 1, 0, 0]

        # Flattened-CSV layout: identical feature columns/ordering/values
        # and metadata columns.
        ws = WindowSet(
            x=edf_df.to_numpy()[: n_windows * 60]
                .reshape(n_windows, 60, 4).astype(np.float32),
            y=labels,
            patient_ids=np.full(n_windows, "200123"),
            start_time_s=(np.arange(n_windows) * 60).astype(np.int32),
            channels=tuple(channels),
        )
        path = str(tmp_path / "ours.csv")
        windows_to_reference_csv(ws, path)
        ours = pd.read_csv(path, dtype={"Patient_ID": str})
        assert list(ours.columns) == list(theirs.columns)
        feature_cols = list(theirs.columns[:-4])
        np.testing.assert_allclose(
            ours[feature_cols].to_numpy(),
            theirs[feature_cols].to_numpy().astype(np.float64),
            rtol=1e-6, atol=1e-7,
        )
        for col in ("Start_Time", "End_Time", "Apnea/Hypopnea"):
            np.testing.assert_array_equal(
                ours[col].to_numpy(), theirs[col].to_numpy()
            )
        assert (ours["Patient_ID"] == theirs["Patient_ID"].astype(str)).all()

    def test_remove_artifacts_matches(self, ref_prep, rng):
        from apnea_uq_tpu.data.ingest import interpolate_out_of_range

        n = 400
        sao2 = 92.0 + rng.normal(0.0, 3.0, n)
        pr = 75.0 + rng.normal(0.0, 20.0, n)
        # Inject out-of-range runs including both edges (np.interp
        # extrapolates flat there) and exact boundary values (valid in
        # both implementations: the masks are strict < lo | > hi).
        sao2[:3] = 60.0
        sao2[100:110] = 101.5
        sao2[200] = 80.0   # boundary: stays
        sao2[-2:] = 120.0
        pr[50:60] = 30.0
        pr[300] = 200.0    # boundary: stays
        thor = rng.normal(size=n)  # untouched channel

        theirs = ref_prep.remove_artifacts(
            {"SaO2": sao2.copy(), "PR": pr.copy(), "THOR RES": thor.copy()}
        )
        np.testing.assert_allclose(
            interpolate_out_of_range(sao2, 80.0, 100.0), theirs["SaO2"],
            rtol=1e-6, atol=1e-5,
        )
        np.testing.assert_allclose(
            interpolate_out_of_range(pr, 40.0, 200.0), theirs["PR"],
            rtol=1e-6, atol=1e-5,
        )
        np.testing.assert_array_equal(theirs["THOR RES"], thor)


REF_PREPARE_PATH = (
    "/root/reference/data_prepocessing/prepare_numpy_datasets.py"
)


class TestPrepareParity:
    """C2: exec the reference's dataset-finalization module (imblearn
    stubbed — its SMOTE/RUS classes are only touched inside the main
    driver, not the functions under test) and pin the reshape + per-window
    standardization math (prepare_numpy_datasets.py:66-95)."""

    @pytest.fixture(scope="class")
    def ref_prepare(self):
        pytest.importorskip("sklearn")
        over = types.ModuleType("imblearn.over_sampling")
        under = types.ModuleType("imblearn.under_sampling")
        imblearn = types.ModuleType("imblearn")

        class SMOTE:  # import-time placeholders only
            pass

        class RandomUnderSampler:
            pass

        over.SMOTE = SMOTE
        under.RandomUnderSampler = RandomUnderSampler
        imblearn.over_sampling = over
        imblearn.under_sampling = under
        return _exec_reference_module(
            "ref_prepare_numpy_datasets", REF_PREPARE_PATH,
            {"imblearn": imblearn, "imblearn.over_sampling": over,
             "imblearn.under_sampling": under},
        )

    def test_standardize_per_window_matches(self, ref_prepare, rng, capsys):
        from apnea_uq_tpu.data.prepare import standardize_per_window

        x = rng.normal(2.0, 3.0, size=(50, 60, 4))
        x[7, :, 2] = 5.0  # constant channel-in-window: eps guard path
        theirs = ref_prepare.standardize_per_window(x.copy())
        capsys.readouterr()
        np.testing.assert_allclose(
            standardize_per_window(x.astype(np.float32)), theirs,
            rtol=1e-5, atol=1e-6,
        )

    def test_reshape_matches_csv_interop(self, ref_prepare, rng, capsys,
                                         tmp_path):
        """The reference reshapes the flattened CSV features with a plain
        C-order reshape (steps, features); windows_from_reference_csv must
        land every value in the same (window, t, ch) cell."""
        import pandas as pd

        from apnea_uq_tpu.data import WindowSet
        from apnea_uq_tpu.data.ingest import (
            windows_from_reference_csv, windows_to_reference_csv,
        )

        channels = ("SaO2", "PR", "THOR RES", "ABDO RES")
        n = 12
        x = rng.normal(size=(n, 60, 4)).astype(np.float32)
        ws = WindowSet(
            x=x, y=rng.integers(0, 2, n).astype(np.int8),
            patient_ids=np.asarray([f"P{i}" for i in range(n)]),
            start_time_s=(np.arange(n) * 60).astype(np.int32),
            channels=channels,
        )
        path = str(tmp_path / "flat.csv")
        windows_to_reference_csv(ws, path)
        frame = pd.read_csv(path)
        flat = frame[ref_prepare.FEATURE_COLS].to_numpy()
        theirs = ref_prepare.reshape_flat_to_3d(flat, 60, 4)
        capsys.readouterr()
        back = windows_from_reference_csv(path)
        np.testing.assert_allclose(theirs, x, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(back.x, theirs, rtol=1e-6, atol=1e-7)
        with pytest.raises(ValueError):
            ref_prepare.reshape_flat_to_3d(flat[:, :-1], 60, 4)


class TestBootstrapOwnStream:
    def test_own_stream_agrees_statistically(self, ref, rng):
        """Our jax-PRNG bootstrap and the reference's np-PRNG bootstrap
        estimate the same sampling distribution: B=400 means must agree
        within a few standard errors."""
        preds, y = _stack(rng, k=5, m=400)
        theirs = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=400, random_state=3
        )
        theirs_ci = ref.compute_confidence_intervals(theirs)
        ours_ci = compute_confidence_intervals(
            bootstrap_mod.bootstrap_aggregates(preds, y, n_bootstrap=400, seed=3)
        )
        for key in AGGREGATE_KEYS:
            ref_vals = np.asarray([r[key] for r in theirs])
            se = ref_vals.std() / np.sqrt(len(ref_vals))
            assert abs(ours_ci[f"{key}_mean"] - theirs_ci[f"{key}_mean"]) < max(
                4 * se, 1e-7
            ), key


class TestAnalysisScriptsExecParity:
    """C17/C18/C21/C22: the four analysis scripts are module-level
    programs that read a CSV from a hard-coded relative path at import.
    Synthesizing that CSV in a tmp cwd makes them exec'able after all
    (r3 PARITY.md assumed they were not), extending the strongest parity
    tier to patient aggregation, window binning, and both statistical
    tests: after exec, the scripts' module globals hold their computed
    frames/statistics, compared value-for-value against the framework."""

    REF_AGG = ("/root/reference/uncertainty_quantification/"
               "aggregate_patient_uq_metrics.py")
    REF_WINDOW = ("/root/reference/uncertainty_quantification/"
                  "analyze_window_level_uncertainty.py")
    REF_CORR = ("/root/reference/uq_analysis/"
                "patient_accuracy_entropy_correlation.py")
    REF_MWU = ("/root/reference/uq_analysis/"
               "window_uncertainty_vs_correctness_mannwhitney.py")

    @pytest.fixture()
    def detailed(self, rng):
        """A detailed per-window frame in the reference CSV schema, with
        both correct and incorrect windows, continuous uncertainty
        values, and single-window patients (the std-zeroing edge)."""
        import pandas as pd

        n = 240
        # object dtype: a fixed-width <U3 array would silently truncate
        # the SOLO ids and void the single-window std-zeroing assertions.
        pids = np.array([f"P{i % 12:02d}" for i in range(n)], dtype=object)
        pids[:2] = ["SOLO_A", "SOLO_B"]  # single-window patients
        y = (rng.uniform(size=n) < 0.3).astype(np.int64)
        flip = rng.uniform(size=n) < 0.2
        pred = np.where(flip, 1 - y, y)
        probs = np.clip(rng.beta(2, 2, n), 1e-6, 1 - 1e-6)
        return pd.DataFrame({
            "Patient_ID": pids,
            "Window_Index": np.arange(n),
            "True_Label": y,
            "Predicted_Label": pred,
            "Predicted_Probability": probs,
            "Predictive_Variance": rng.uniform(0.0, 0.25, n),
            "Predictive_Entropy": rng.uniform(0.0, 1.0, n),
        })

    def test_patient_aggregation_matches(self, detailed, tmp_path,
                                         monkeypatch, capsys):
        from apnea_uq_tpu.analysis.patient import (
            SUMMARY_METRIC_COLUMNS, aggregate_patients,
        )

        monkeypatch.chdir(tmp_path)
        detailed.to_csv(tmp_path / "detail_patient_MCD.csv", index=False)
        ref = _exec_reference_module("ref_aggregate", self.REF_AGG, {})
        capsys.readouterr()
        theirs = ref.patient_summary.sort_values("Patient_ID").reset_index(
            drop=True)
        ours = aggregate_patients(detailed).sort_values(
            "Patient_ID").reset_index(drop=True)
        assert list(theirs["Patient_ID"]) == list(ours["Patient_ID"])
        for col in SUMMARY_METRIC_COLUMNS:
            np.testing.assert_allclose(
                ours[col].to_numpy(np.float64),
                theirs[col].to_numpy(np.float64),
                rtol=1e-12, atol=1e-12, err_msg=col,
            )
        # Both zero the std for single-window patients (:45-46).
        solo = theirs[theirs["Patient_ID"].str.startswith("SOLO")]
        assert (solo["std_variance"] == 0).all()
        assert (solo["std_entropy"] == 0).all()

    def test_window_binning_matches(self, detailed, tmp_path, monkeypatch,
                                    capsys):
        from apnea_uq_tpu.analysis.windows import window_level_analysis

        monkeypatch.chdir(tmp_path)
        detailed.to_csv(tmp_path / "detail_patient_DE.csv", index=False)
        ref = _exec_reference_module("ref_window_level", self.REF_WINDOW, {})
        capsys.readouterr()
        ours = window_level_analysis(detailed)
        theirs = ref.binned_results.reset_index()
        assert ours.num_windows == len(ref.uq_results_df)
        assert ours.overall_accuracy == pytest.approx(
            float(ref.uq_results_df["Correct"].mean()), abs=1e-15)
        assert [str(b) for b in theirs[theirs.columns[0]]] == [
            str(b) for b in ours.binned["Predictive_Entropy_Bin"]]
        np.testing.assert_array_equal(
            ours.binned["window_count"], theirs["window_count"])
        np.testing.assert_allclose(
            ours.binned["accuracy"].to_numpy(np.float64),
            theirs["accuracy"].to_numpy(np.float64), rtol=1e-12)
        np.testing.assert_allclose(
            ours.binned["error_rate"].to_numpy(np.float64),
            theirs["error_rate"].to_numpy(np.float64), rtol=1e-12)

    def test_pearson_correlation_matches(self, detailed, tmp_path,
                                         monkeypatch, capsys):
        pytest.importorskip("scipy")
        from apnea_uq_tpu.analysis.patient import aggregate_patients
        from apnea_uq_tpu.analysis.stats import pearson_corr

        monkeypatch.chdir(tmp_path)
        summary = aggregate_patients(detailed)
        summary.to_csv(tmp_path / "patient_summary.csv", index=False)
        # __main__-gated module: exec has no side effects; call its
        # function (the script's whole computation, :15-46) directly.
        ref = _exec_reference_module("ref_patient_corr", self.REF_CORR, {})
        r_ref, p_ref = ref.calculate_and_print_correlation(
            str(tmp_path / "patient_summary.csv"), "MCD",
            "mean_entropy", "patient_accuracy",
        )
        capsys.readouterr()
        assert r_ref is not None
        r, p = pearson_corr(summary["mean_entropy"],
                            summary["patient_accuracy"])
        assert r == pytest.approx(r_ref, rel=1e-12)
        assert p == pytest.approx(p_ref, rel=1e-9)  # in-tree t CDF

    def test_mann_whitney_matches(self, detailed, tmp_path, monkeypatch,
                                  capsys):
        pytest.importorskip("scipy")
        from apnea_uq_tpu.analysis.stats import mann_whitney_u

        monkeypatch.chdir(tmp_path)
        detailed.to_csv(tmp_path / "detail_patient_DE.csv", index=False)
        ref = _exec_reference_module("ref_mannwhitney", self.REF_MWU, {})
        capsys.readouterr()
        # The script's whole body is one try/except that would swallow a
        # missing-file error; the globals only exist on the happy path.
        assert hasattr(ref, "stat") and hasattr(ref, "p_value"), (
            "reference script did not reach the test computation")
        correct = detailed["True_Label"] == detailed["Predicted_Label"]
        u, p = mann_whitney_u(
            detailed.loc[~correct, "Predictive_Entropy"],
            detailed.loc[correct, "Predictive_Entropy"],
            alternative="greater",
        )
        assert u == pytest.approx(float(ref.stat), rel=1e-12)
        assert p == pytest.approx(float(ref.p_value), rel=1e-9)


class TestCohortScriptsExecParity:
    """C23/C24: the two datasets/ scripts are function-based (argparse
    __main__-gated), so exec is side-effect free and their analysis
    functions can be driven directly on a synthetic NSRR metadata CSV.
    They print rather than return, so parity is pinned on the printed
    numbers (formatted identically from the framework's structured
    output).  Bonus finding preserved here: the reference's AHI severity
    table is UNREACHABLE — its np.select call passes value-subsets of
    mismatched lengths as the condition list and raises 'shape mismatch',
    swallowed by the script's blanket except — so the framework's
    severity distribution implements the labeled intent
    (SHHS_cohort_analysis.py:139-152), which the reference code itself
    never manages to print."""

    REF_COHORT = "/root/reference/datasets/SHHS_cohort_analysis.py"
    REF_QUALITY = "/root/reference/datasets/SHHS_signal_quality.py"

    @pytest.fixture()
    def metadata(self, rng, tmp_path):
        import pandas as pd

        n = 300
        df = pd.DataFrame({
            "ahi_a0h3a": np.where(rng.uniform(size=n) < 0.12, np.nan,
                                  rng.exponential(12.0, n)),
            "age_s2": np.where(rng.uniform(size=n) < 0.05, np.nan,
                               rng.normal(63.0, 10.0, n).round(1)),
            "gender": rng.choice([1.0, 2.0], n),
            "race": rng.choice([1.0, 2.0, 3.0], n, p=[0.7, 0.2, 0.1]),
            "quoxim": rng.choice([1.0, 2.0, 3.0, 4.0, 5.0, np.nan], n),
            "quhr": rng.choice([3.0, 4.0, 5.0], n),
            "quchest": rng.choice([2.0, 4.0, 5.0], n),
            "quabdo": rng.choice([4.0, 5.0], n),
        })
        path = tmp_path / "shhs2-dataset.csv"
        df.to_csv(path, index=False)
        return df, str(path)

    def test_cohort_demographics_match(self, metadata, capsys):
        import re

        from apnea_uq_tpu.analysis.cohort import analyze_cohort

        df, path = metadata
        ref = _exec_reference_module("ref_cohort_analysis", self.REF_COHORT, {})
        ref.analyze_cohort(path)
        out = capsys.readouterr().out
        ours = analyze_cohort(df)

        assert f"N = {ours['n_cohort']}" in out
        age, ahi = ours["age"], ours["ahi"]
        assert (f"Mean Age: {age['mean']:.1f} ± {age['std']:.1f} years"
                in out)
        assert f"Median Age: {age['median']:.1f} years" in out
        assert (f"Age Range: {age['min']:.1f} - {age['max']:.1f} years"
                in out)
        assert (f"Mean AHI: {ahi['mean']:.1f} ± {ahi['std']:.1f} events/hour"
                in out)
        assert f"Median AHI: {ahi['median']:.1f} events/hour" in out
        for label, key in (("Male (1.0)", "Male"), ("Female (2.0)", "Female")):
            cat = ours["gender"]["categories"][key]
            m = re.search(rf"{re.escape(label)}:\s+(\d+)\s+\(([\d.]+)%\)", out)
            assert m, label
            assert int(m.group(1)) == cat["count"]
            assert float(m.group(2)) == pytest.approx(cat["percent"], abs=0.05)
        for label, key in (("White (1.0)", "White"),
                           ("Black or African American (2.0)",
                            "Black or African American"),
                           ("Other (3.0)", "Other")):
            cat = ours["race"]["categories"][key]
            m = re.search(rf"{re.escape(label)}:\s+(\d+)\s+\(([\d.]+)%\)", out)
            assert m, label
            assert int(m.group(1)) == cat["count"]

        # The reference defect, pinned: its severity table never prints
        # (np.select over mismatched-length value subsets raises, caught
        # by the blanket except) — while the framework's distribution
        # totals the full cohort under the same labeled thresholds.
        assert "AHI Severity Distribution in Cohort:" not in out
        assert "shape mismatch" in out
        sev = ours["ahi_severity"]
        assert int(sev["count"].sum()) == ours["n_cohort"]
        assert list(sev["category"]) == [
            "Normal (AHI < 5.0)", "Mild OSA (AHI 5.0-14.9)",
            "Moderate OSA (AHI 15.0-29.9)", "Severe OSA (AHI >= 30.0)",
        ]

    def test_signal_quality_matches(self, metadata, capsys):
        import re

        import pandas as pd

        from apnea_uq_tpu.analysis.cohort import (
            QUALITY_VARS, analyze_signal_quality,
        )

        df, path = metadata
        ref = _exec_reference_module("ref_signal_quality", self.REF_QUALITY, {})
        ref.analyze_signal_quality(path)
        out = capsys.readouterr().out
        ours = analyze_signal_quality(df)

        assert f"N = {ours['n_cohort']}" in out
        for var in QUALITY_VARS:
            info = ours["channels"][var]
            # Per-variable section: mean score + every category count.
            sec = out.split(f"({var})")[1].split("--- Statistics")[0]
            values = pd.to_numeric(
                df.loc[pd.to_numeric(df["ahi_a0h3a"], errors="coerce")
                       .notna(), var], errors="coerce").dropna()
            assert f"N (non-missing values): {info['n']}" in sec
            assert f"Mean score: {values.mean():.2f}" in sec
            for label, cat in info["categories"].items():
                m = re.search(
                    rf"Category \d+ \({re.escape(label)}\): {cat['count']}\b",
                    sec)
                assert m, (var, label, cat, sec[:500])


class TestPlotScriptsConsumeFrameworkArtifacts:
    """C19/C20 interop: plots cannot be value-compared, but the reference
    plot scripts CAN be fed the framework's own artifacts — proving the
    detailed-frame, patient-summary, and sweep-table schemas this
    framework writes are consumable by the reference's thesis-figure
    code unchanged (the artifact-contract guarantee PARITY.md claims)."""

    REF_FIGURES = "/root/reference/uq_analysis/final_plot_uq_overview_figures.py"
    REF_CONV = ("/root/reference/uq_analysis/"
                "hyperparameter_plot_mcd_or_de_pass_convergence.py")

    def test_thesis_figures_script_runs_on_framework_csvs(
            self, rng, tmp_path, monkeypatch, capsys):
        pytest.importorskip("scipy")
        pytest.importorskip("seaborn")
        import matplotlib
        matplotlib.use("Agg")

        from apnea_uq_tpu.analysis.patient import aggregate_patients
        from apnea_uq_tpu.uq.drivers import detailed_frame

        monkeypatch.chdir(tmp_path)
        # Framework artifacts for both methods: detailed per-window frame
        # (from a synthetic prediction stack) and its patient aggregation.
        for tag in ("MCD", "DE"):
            k = 6 if tag == "MCD" else 4
            m = 180
            preds = np.clip(
                rng.beta(2.0, 2.0, (k, m))
                + rng.normal(0, 0.05, (k, m)), 1e-6, 1 - 1e-6)
            y = (rng.uniform(size=m) < 0.3).astype(np.int64)
            pids = np.array([f"p{i % 15:02d}" for i in range(m)],
                            dtype=object)
            frame = detailed_frame(preds, y, pids)
            frame.to_csv(tmp_path / f"detail_patient_{tag}.csv", index=False)
            summary_dir = tmp_path / f"patient_level_uq_analysis_{tag}"
            summary_dir.mkdir()
            aggregate_patients(frame).to_csv(
                summary_dir / f"patient_summary_metrics_{tag}.csv",
                index=False)

        _exec_reference_module("ref_thesis_figures", self.REF_FIGURES, {})
        out = capsys.readouterr().out
        pngs = sorted(p.name for p in (tmp_path / "final_thesis_plots").glob("*.png"))
        assert pngs == [
            "binned_accuracy_plot_final_annotated.png",
            "patient_accuracy_vs_entropy_final.png",
            "patient_entropy_histograms_final.png",
            "window_correctness_boxplots_final.png",
        ], (pngs, out[-2000:])
        for p in (tmp_path / "final_thesis_plots").glob("*.png"):
            assert p.stat().st_size > 0

    def test_convergence_plot_consumes_framework_sweep_table(
            self, rng, tmp_path, monkeypatch, capsys):
        import jax
        import matplotlib
        matplotlib.use("Agg")

        from apnea_uq_tpu.analysis.sweep import mcd_pass_sweep
        from apnea_uq_tpu.config import ModelConfig, UQConfig
        from apnea_uq_tpu.models import AlarconCNN1D, init_variables

        monkeypatch.chdir(tmp_path)
        model = AlarconCNN1D(ModelConfig(
            features=(6, 6), kernel_sizes=(3, 3), dropout_rates=(0.3, 0.3)))
        variables = init_variables(model, jax.random.key(0))
        sets = {
            "Unbalanced": rng.normal(size=(40, 60, 4)).astype(np.float32),
            "Balanced": rng.normal(size=(32, 60, 4)).astype(np.float32),
        }
        table = mcd_pass_sweep(
            model, variables, sets, pass_counts=(2, 4, 8),
            config=UQConfig(mcd_batch_size=40), key=jax.random.key(1))
        assert list(table.columns) == ["N", "Variance_Unbalanced",
                                       "Variance_Balanced"]
        table.to_csv(tmp_path / "conv.csv", index=False)

        ref = _exec_reference_module("ref_convergence_plot", self.REF_CONV, {})
        ref.plot_variance_convergence(
            str(tmp_path / "conv.csv"),
            output_plot_filename=str(tmp_path / "conv.png"),
            method="mcd",
        )
        capsys.readouterr()
        assert (tmp_path / "conv.png").stat().st_size > 0
