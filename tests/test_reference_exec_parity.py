"""Exec-the-reference numeric parity.

The strongest parity evidence available: load the reference's metric core
(/root/reference/uncertainty_quantification/uq_techniques.py — pure
NumPy/SciPy once its unused ``tensorflow`` import is stubbed) and compare
it value-for-value against the framework's engines on random (K, M)
stacks.  This pins parity against the living reference code rather than
re-typed formulas:

- ``uq_evaluation_dist`` (uq_techniques.py:40-112) vs uq/metrics.py
- ``bootstrap_metrics``  (uq_techniques.py:116-172) vs the gather engine,
  driven with the reference's own ``np.random.choice`` index stream so
  per-resample values match exactly
- ``compute_confidence_intervals`` (uq_techniques.py:175-206) vs
  uq/bootstrap.py on identical bootstrap inputs
"""

import importlib.util
import os
import sys
import types

import numpy as np
import pytest

import apnea_uq_tpu.uq.bootstrap as bootstrap_mod
from apnea_uq_tpu.uq.bootstrap import (
    AGGREGATE_KEYS,
    compute_confidence_intervals,
    gather_aggregates,
)
from apnea_uq_tpu.uq.metrics import uq_evaluation_dist

REF_PATH = "/root/reference/uncertainty_quantification/uq_techniques.py"
REF_EVAL_PATH = "/root/reference/evaluation/evaluate_classification.py"

pytestmark = pytest.mark.skipif(
    not os.path.exists(REF_PATH), reason="reference checkout not mounted"
)


def _stub_tensorflow():
    """A minimal module tree satisfying the reference's tf imports
    (`import tensorflow as tf`, `from tensorflow.keras.models import
    Model`) — the metric functions under test never touch tf."""
    tf = types.ModuleType("tensorflow")
    keras = types.ModuleType("tensorflow.keras")
    keras_models = types.ModuleType("tensorflow.keras.models")

    class Model:  # annotation placeholder only
        pass

    keras.Model = Model
    keras.models = keras_models
    keras_models.Model = Model
    tf.keras = keras
    return {
        "tensorflow": tf,
        "tensorflow.keras": keras,
        "tensorflow.keras.models": keras_models,
    }


@pytest.fixture(scope="module")
def ref():
    """The reference uq_techniques module, exec'd with tf stubbed."""
    os.environ.setdefault("MPLBACKEND", "Agg")
    stubs = _stub_tensorflow()
    saved = {name: sys.modules.get(name) for name in stubs}
    sys.modules.update(stubs)
    try:
        spec = importlib.util.spec_from_file_location("ref_uq_techniques", REF_PATH)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
    finally:
        for name, mod in saved.items():
            if mod is None:
                sys.modules.pop(name, None)
            else:
                sys.modules[name] = mod
    return module


def _stack(rng, k=7, m=500, kind="uniform"):
    if kind == "uniform":
        p = rng.uniform(0.0, 1.0, size=(k, m))
    elif kind == "edgy":  # mass near the clip boundaries
        p = np.clip(rng.beta(0.05, 0.05, size=(k, m)), 0.0, 1.0)
    elif kind == "saturated":  # EXACT 0.0/1.0 entries exercise the eps clip
        p = rng.uniform(0.0, 1.0, size=(k, m))
        p[rng.uniform(size=(k, m)) < 0.3] = 0.0
        p[rng.uniform(size=(k, m)) < 0.3] = 1.0
    elif kind == "constant":
        p = np.full((k, m), 0.37)
    else:
        raise ValueError(kind)
    y = (rng.uniform(size=m) < 0.4).astype(np.int64)
    return p.astype(np.float32), y


VECTOR_KEYS = (
    "mean_pred",
    "pred_variance",
    "total_pred_entropy",
    "expected_aleatoric_entropy",
    "mutual_info",
)
SCALAR_KEYS = (
    "overall_mean_variance",
    "mean_variance_class_0",
    "mean_variance_class_1",
)


class TestUqEvaluationDist:
    @pytest.mark.parametrize("kind", ["uniform", "edgy", "saturated", "constant"])
    def test_matches_reference(self, ref, rng, kind):
        preds, y = _stack(rng, kind=kind)
        theirs = ref.uq_evaluation_dist(preds.astype(np.float64), y)
        ours = uq_evaluation_dist(preds, y)
        for key in VECTOR_KEYS:
            np.testing.assert_allclose(
                np.asarray(ours[key]), theirs[key], rtol=2e-5, atol=2e-6,
                err_msg=key,
            )
        for key in SCALAR_KEYS:
            assert float(ours[key]) == pytest.approx(
                float(theirs[key]), rel=2e-5, abs=2e-6
            ), key

    def test_single_pass_and_trailing_axis(self, ref, rng):
        # (K, M, 1) stacks and 1-D single-pass inputs take the same
        # degenerate path in both implementations (uq_techniques.py:61-66).
        preds, y = _stack(rng, k=1, m=64)
        theirs = ref.uq_evaluation_dist(preds.astype(np.float64), y)
        ours = uq_evaluation_dist(preds[..., None], y)
        for key in VECTOR_KEYS:
            np.testing.assert_allclose(
                np.asarray(ours[key]), theirs[key], rtol=2e-5, atol=2e-6,
                err_msg=key,
            )
        np.testing.assert_allclose(np.asarray(ours["pred_variance"]), 0.0)

    def test_empty_class_guard(self, ref, rng):
        preds, _ = _stack(rng, m=64)
        y = np.ones(64, np.int64)  # class 0 absent
        theirs = ref.uq_evaluation_dist(preds.astype(np.float64), y)
        ours = uq_evaluation_dist(preds, y)
        assert float(theirs["mean_variance_class_0"]) == 0.0
        assert float(ours["mean_variance_class_0"]) == 0.0


class TestBootstrapParity:
    def test_gather_engine_matches_reference_loop(self, ref, rng):
        """Drive the gather engine with the reference's exact index stream:
        per-resample aggregates must match the reference's
        recompute-everything loop value-for-value, which proves the
        gather formulation is the same math, not just the same
        distribution."""
        preds, y = _stack(rng, k=5, m=300)
        n_bootstrap, seed = 20, 123

        theirs = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=n_bootstrap, random_state=seed
        )
        assert len(theirs) == n_bootstrap

        # Regenerate the identical index matrix the reference drew
        # (uq_techniques.py:130-142: np.random.seed then B draws of
        # np.random.choice(M, M, replace=True)).
        np.random.seed(seed)
        m = preds.shape[1]
        idx = np.stack([np.random.choice(m, m, replace=True) for _ in range(n_bootstrap)])

        metrics = uq_evaluation_dist(preds, y)
        ours = gather_aggregates(
            metrics["pred_variance"],
            metrics["total_pred_entropy"],
            metrics["expected_aleatoric_entropy"],
            metrics["mutual_info"],
            np.asarray(y),
            idx,
        )
        for b in range(n_bootstrap):
            for key in AGGREGATE_KEYS:
                assert float(np.asarray(ours[key])[b]) == pytest.approx(
                    float(theirs[b][key]), rel=3e-5, abs=3e-6
                ), f"resample {b}, {key}"

    def test_compute_confidence_intervals_matches(self, ref, rng):
        preds, y = _stack(rng, k=5, m=300)
        results = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=30, random_state=7
        )
        theirs = ref.compute_confidence_intervals(results, alpha=0.05)
        ours = compute_confidence_intervals(results, alpha=0.05)
        assert set(ours) == set(theirs)
        for key in theirs:
            assert ours[key] == pytest.approx(theirs[key], rel=1e-12), key

    def test_ci_alpha_sweep_matches(self, ref, rng):
        preds, y = _stack(rng, k=4, m=200)
        results = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=25, random_state=11
        )
        for alpha in (0.01, 0.1, 0.32):
            theirs = ref.compute_confidence_intervals(results, alpha=alpha)
            ours = compute_confidence_intervals(results, alpha=alpha)
            for key in theirs:
                assert ours[key] == pytest.approx(theirs[key], rel=1e-12), (alpha, key)

class TestClassificationEvaluatorParity:
    """C6: exec the reference's sklearn-based evaluator
    (evaluate_classification.py:7-153) and compare the framework's
    in-tree suite value-for-value on the same probabilities."""

    @pytest.fixture(scope="class")
    def ref_eval(self):
        pytest.importorskip("sklearn")
        if not os.path.exists(REF_EVAL_PATH):
            pytest.skip("reference evaluation module not mounted")
        spec = importlib.util.spec_from_file_location(
            "ref_evaluate_classification", REF_EVAL_PATH
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module

    def test_matches_reference_evaluator(self, ref_eval, rng, capsys):
        from apnea_uq_tpu.evaluation.classification import evaluate_classification

        n = 400
        probs = rng.uniform(0.0, 1.0, n)
        probs = probs[np.abs(probs - 0.5) > 1e-6]  # reference thresholds
        # with strict > 0.5, the framework with >= — identical off 0.5.
        y = (rng.uniform(size=len(probs)) < 0.35).astype(np.int64)

        class StubModel:
            def predict(self, x):
                return probs.reshape(-1, 1)

        theirs = ref_eval.evaluate_classification_model(
            StubModel(), np.zeros((len(probs), 1)), y
        )
        capsys.readouterr()  # swallow the reference's prints
        assert theirs is not None
        ours = evaluate_classification(probs, y)

        assert ours["accuracy"] == pytest.approx(theirs["accuracy"], abs=1e-12)
        assert ours["roc_auc"] == pytest.approx(theirs["roc_auc"], rel=1e-10)
        assert ours["cohen_kappa"] == pytest.approx(theirs["cohen_kappa"], rel=1e-10)
        assert ours["mcc"] == pytest.approx(theirs["mcc"], rel=1e-10)
        assert ours["sensitivity"] == pytest.approx(
            theirs["overall_sensitivity"], rel=1e-12)
        assert ours["specificity"] == pytest.approx(
            theirs["overall_specificity"], rel=1e-12)
        np.testing.assert_array_equal(
            np.asarray(ours["confusion_matrix"]), theirs["confusion_matrix"]
        )
        # PR-AUC definitions differ by design: the reference trapezoid-
        # integrates the PR curve (auc(recall, precision)), the framework
        # uses sklearn-style step-interpolated average precision.  They
        # agree closely but not exactly.
        assert ours["pr_auc"] == pytest.approx(theirs["auc_pr"], rel=0.02)
        # Per-class report values are the same sklearn definitions (the
        # reference's returned dict uses bare "0"/"1" keys — target_names
        # only shapes its printed report).
        for cls in ("0", "1"):
            for k in ("precision", "recall", "f1-score", "support"):
                assert ours["report"][cls][k] == pytest.approx(
                    theirs["classification_report_dict"][cls][k], rel=1e-12
                ), (cls, k)

    def test_single_class_guard_matches(self, ref_eval, rng, capsys):
        from apnea_uq_tpu.evaluation.classification import evaluate_classification

        probs = rng.uniform(0.0, 1.0, 50)

        class StubModel:
            def predict(self, x):
                return probs

        theirs = ref_eval.evaluate_classification_model(
            StubModel(), np.zeros((50, 1)), np.ones(50, np.int64)
        )
        capsys.readouterr()
        ours = evaluate_classification(probs, np.ones(50, np.int64))
        # Both report the undefined AUCs as None and keep going.
        assert theirs["roc_auc"] is None and ours["roc_auc"] is None
        assert ours["accuracy"] == pytest.approx(theirs["accuracy"], abs=1e-12)


class TestBootstrapOwnStream:
    def test_own_stream_agrees_statistically(self, ref, rng):
        """Our jax-PRNG bootstrap and the reference's np-PRNG bootstrap
        estimate the same sampling distribution: B=400 means must agree
        within a few standard errors."""
        preds, y = _stack(rng, k=5, m=400)
        theirs = ref.bootstrap_metrics(
            preds.astype(np.float64), y, n_bootstrap=400, random_state=3
        )
        theirs_ci = ref.compute_confidence_intervals(theirs)
        ours_ci = compute_confidence_intervals(
            bootstrap_mod.bootstrap_aggregates(preds, y, n_bootstrap=400, seed=3)
        )
        for key in AGGREGATE_KEYS:
            ref_vals = np.asarray([r[key] for r in theirs])
            se = ref_vals.std() / np.sqrt(len(ref_vals))
            assert abs(ours_ci[f"{key}_mean"] - theirs_ci[f"{key}_mean"]) < max(
                4 * se, 1e-7
            ), key
