"""Per-channel data fingerprints + drift scoring
(analysis/fingerprint.py): streaming invariance (ndarray vs sharded
store at odd block sizes), shape-fact rates (NaN/flatline/saturation),
PSI/KS detection of injected shifts, and the edge-compatibility
contract behind score_against_baseline."""

import json

import numpy as np
import pytest

from apnea_uq_tpu.analysis import fingerprint as fp
from apnea_uq_tpu.data import store as store_mod


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _windows(rng, n=400, steps=30, channels=3):
    return rng.normal(size=(n, steps, channels)).astype(np.float32)


def test_fingerprint_schema_and_moments(rng):
    x = _windows(rng)
    doc = fp.compute_fingerprint(x)
    assert doc["version"] == fp.FINGERPRINT_VERSION
    assert doc["rows"] == 400 and doc["window_steps"] == 30
    assert [c["name"] for c in doc["channels"]] == ["ch0", "ch1", "ch2"]
    for c, col in zip(doc["channels"], range(3)):
        vals = x[:, :, col].astype(np.float64)
        assert c["mean"] == pytest.approx(vals.mean(), abs=1e-6)
        assert c["std"] == pytest.approx(vals.std(), abs=1e-6)
        assert c["min"] == pytest.approx(vals.min())
        assert c["max"] == pytest.approx(vals.max())
        assert sum(c["counts"]) == vals.size
        # Histogram-derived quantiles land within a bin width of exact.
        bin_w = c["edges"][1] - c["edges"][0]
        assert c["quantiles"]["p50"] == pytest.approx(
            np.percentile(vals, 50), abs=bin_w)
        assert c["quantiles"]["p05"] <= c["quantiles"]["p95"]


def test_streaming_matches_in_core_bit_for_bit(rng, tmp_path):
    """The in-core and out-of-core prepare paths must freeze IDENTICAL
    baselines: fingerprint(ndarray) == fingerprint(sharded store) at an
    awkward block size, byte-for-byte as JSON."""
    x = _windows(rng, n=333)
    store = store_mod.write_store(str(tmp_path / "st"), {"x": x},
                                  rows_per_shard=57)
    a = fp.compute_fingerprint(x)
    b = fp.compute_fingerprint(store.read("x"), block_rows=41)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_nan_flatline_saturation_rates(rng):
    x = _windows(rng, n=100, steps=20, channels=2)
    x[0, :, 0] = 2.5                 # flat window on ch0
    x[1, 5:, 0] = np.nan             # NaNs on ch0
    # Railed window on ch1: >50% of samples pinned at the extremes.
    x[2, :, 1] = np.concatenate([np.full(12, 4.0), np.full(4, -4.0),
                                 rng.normal(size=4)]).astype(np.float32)
    doc = fp.compute_fingerprint(x)
    ch0, ch1 = doc["channels"]
    assert ch0["flatline_rate"] == pytest.approx(1 / 100)
    assert ch0["nan_rate"] == pytest.approx(15 / (100 * 20))
    assert ch1["saturation_rate"] == pytest.approx(1 / 100)
    # A flat window is flat, not saturated.
    assert ch0["saturation_rate"] == 0.0


def test_self_drift_is_zero_and_shift_detected(rng):
    x = _windows(rng)
    base = fp.compute_fingerprint(x)
    self_report = fp.score_against_baseline(x, base)
    assert self_report["max_psi"] == 0.0
    assert self_report["max_ks"] == 0.0
    assert self_report["max_mean_shift"] == 0.0
    # Shift ONE channel; the report must localize it.
    shifted = x.copy()
    shifted[:, :, 1] = shifted[:, :, 1] * 1.8 + 1.0
    report = fp.score_against_baseline(shifted, base)
    assert report["worst_channel"] == "ch1"
    assert report["max_psi"] > 0.2
    assert report["max_ks"] > 0.2
    assert report["max_mean_shift"] > 0.5
    by_name = {c["name"]: c for c in report["channels"]}
    assert by_name["ch0"]["psi"] < 0.05  # untouched channels stay quiet
    assert by_name["ch2"]["psi"] < 0.05
    # New NaNs show up as a rate delta even when the histogram barely
    # moves (NaNs never land in bins).
    holey = x.copy()
    holey[:50, :, 0] = np.nan
    nan_report = fp.score_against_baseline(holey, base)
    assert next(c for c in nan_report["channels"]
                if c["name"] == "ch0")["nan_rate_delta"] > 0.1


def test_out_of_range_values_clamp_into_boundary_bins(rng):
    x = _windows(rng, n=200)
    base = fp.compute_fingerprint(x)
    # A cohort far outside the baseline range must still score (clamped
    # into the edge bins = maximal drift), never crash.
    report = fp.score_against_baseline(x * 100.0, base)
    assert report["max_psi"] > 1.0


def test_incompatible_fingerprints_raise(rng):
    x = _windows(rng, channels=3)
    base = fp.compute_fingerprint(x)
    with pytest.raises(ValueError, match="channel count"):
        fp.drift_report(base, fp.compute_fingerprint(x[:, :, :2]))
    # Same channel count, different edges: not comparable either.
    other = fp.compute_fingerprint(x * 3.0)
    with pytest.raises(ValueError, match="edges"):
        fp.drift_report(base, other)


def test_validation_errors(rng):
    with pytest.raises(ValueError, match="empty"):
        fp.compute_fingerprint(np.empty((0, 10, 2), np.float32))
    with pytest.raises(ValueError, match="shape"):
        fp.compute_fingerprint(np.zeros((5, 10), np.float32))
    with pytest.raises(ValueError, match="num_bins"):
        fp.compute_fingerprint(_windows(rng), num_bins=1)
    with pytest.raises(ValueError, match="channel names"):
        fp.compute_fingerprint(_windows(rng), channel_names=["a"])


def test_psi_and_ks_primitives():
    even = [25, 25, 25, 25]
    assert fp.population_stability_index(even, even) == 0.0
    assert fp.ks_statistic(even, even) == 0.0
    skewed = [97, 1, 1, 1]
    assert fp.population_stability_index(even, skewed) > 0.2
    assert fp.ks_statistic(even, skewed) == pytest.approx(0.72)
    # PSI tolerates empty bins on either side (clipped, not inf/nan).
    assert np.isfinite(fp.population_stability_index([0, 100], [100, 0]))


def test_fingerprint_is_json_round_trippable(rng):
    doc = fp.compute_fingerprint(_windows(rng, n=50))
    again = json.loads(json.dumps(doc))
    assert fp.drift_report(doc, again)["max_psi"] == 0.0

# ---------------------------------------------------------------------------
# RollingFingerprint (ISSUE 17): the online accumulator the serving-path
# drift monitor folds every scored window into.


def test_rolling_matches_batch_scoring_without_decay(rng):
    """With decay off, folding the whole cohort window-by-window must
    score exactly like the batch path (same frozen edges, same counts)."""
    x = _windows(rng, n=300)
    base = fp.compute_fingerprint(x)
    rolling = fp.RollingFingerprint(base)
    for w in x:
        rolling.update(w)                      # one (T, C) window at a time
    batch_report = fp.score_against_baseline(x, base)
    rolling_report = rolling.score(base)
    assert rolling.seen == 300
    assert rolling_report["max_psi"] == pytest.approx(
        batch_report["max_psi"], abs=1e-9)
    assert rolling_report["max_ks"] == pytest.approx(
        batch_report["max_ks"], abs=1e-9)
    # Self-traffic scores quiet; a shifted cohort must not.
    assert rolling_report["max_psi"] < 0.05
    shifted = fp.RollingFingerprint(base)
    shifted.update(x * 2.0 + 1.5)
    report = shifted.score(base)
    assert report["max_psi"] > 0.2 and report["max_ks"] > 0.2


def test_rolling_batch_fold_decays_prior_state_exactly(rng):
    """An n-window batch fold fades the PRIOR state by exactly decay**n
    and adds the new windows at full weight (recency inside one fold is
    not modeled — folds are tiny next to any real half-life)."""
    x = _windows(rng, n=64)
    base = fp.compute_fingerprint(x)
    r = fp.RollingFingerprint(base, half_life=16.0)
    r.update(x[:32])
    prior = r.counts.copy()
    fresh = fp.RollingFingerprint(base, half_life=16.0)
    fresh.update(x[32:])                       # raw histogram, no prior
    r.update(x[32:])
    np.testing.assert_allclose(
        r.counts, prior * 0.5 ** (32 / 16.0) + fresh.counts, rtol=1e-12)
    assert r.seen == 64
    assert r.window_w == pytest.approx(
        fresh.window_w + 32 * 0.5 ** (32 / 16.0))


def test_rolling_decay_ages_out_an_incident(rng):
    """A drifted burst must fade once clean traffic resumes: the score
    right after the burst is high, and far lower after 8 half-lives of
    clean windows (recency bias), while the cumulative no-decay variant
    stays polluted."""
    x = _windows(rng, n=1200)
    base = fp.compute_fingerprint(x)
    decayed = fp.RollingFingerprint(base, half_life=50.0)
    cumulative = fp.RollingFingerprint(base)
    burst = x[:200] * 2.0 + 1.5
    for r in (decayed, cumulative):
        r.update(burst)
    during = decayed.score(base)["max_psi"]
    for r in (decayed, cumulative):
        r.update(x[200:600])                   # 400 clean = 8 half-lives
    after = decayed.score(base)["max_psi"]
    assert during > 0.2
    assert after < during / 3
    assert cumulative.score(base)["max_psi"] > after


def test_rolling_state_round_trips_through_json(rng):
    """to_json/from_json must reproduce the exact scoring state (the
    stream scorer persists it inside stream_state.json): same report
    before and after, and updates keep agreeing afterwards."""
    x = _windows(rng, n=120)
    base = fp.compute_fingerprint(x)
    rolling = fp.RollingFingerprint(base, half_life=64.0)
    rolling.update(x[:80])
    doc = json.loads(json.dumps(rolling.to_json()))   # via real JSON
    restored = fp.RollingFingerprint.from_json(doc)
    assert restored.seen == rolling.seen
    assert json.dumps(restored.score(base), sort_keys=True) == \
        json.dumps(rolling.score(base), sort_keys=True)
    rolling.update(x[80:])
    restored.update(x[80:])
    assert json.dumps(restored.score(base), sort_keys=True) == \
        json.dumps(rolling.score(base), sort_keys=True)
    with pytest.raises(ValueError, match="version"):
        fp.RollingFingerprint.from_json({**doc, "version": 999})


def test_rolling_validation_and_shape_rates(rng):
    x = _windows(rng, n=40, steps=20, channels=2)
    base = fp.compute_fingerprint(x)
    with pytest.raises(ValueError, match="half_life"):
        fp.RollingFingerprint(base, half_life=0.0)
    rolling = fp.RollingFingerprint(base)
    with pytest.raises(ValueError, match="no windows"):
        rolling.fingerprint()
    with pytest.raises(ValueError, match="shape"):
        rolling.update(np.zeros((5, 20, 3), np.float32))
    # NaN / flatline windows land in the same rate fields the batch
    # fingerprint computes.
    dirty = x.copy()
    dirty[0, :, 0] = 3.25                       # flat window on ch0
    dirty[1, 10:, 0] = np.nan
    rolling.update(dirty)
    doc = rolling.fingerprint()
    ch0 = doc["channels"][0]
    assert ch0["flatline_rate"] == pytest.approx(1 / 40)
    assert ch0["nan_rate"] == pytest.approx(10 / (40 * 20))
    assert doc["rows"] == 40
