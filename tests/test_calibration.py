"""Calibration analysis (analysis/calibration.py): reliability bins,
ECE/MCE, Brier score — probability-calibration tooling the reference
lacks, on the detailed-frame contract."""

import numpy as np
import pandas as pd
import pytest

from apnea_uq_tpu.analysis import (
    COL_PROB,
    COL_TRUE_LABEL,
    calibration_summary,
    reliability_bins,
)


def _frame(probs, y):
    return pd.DataFrame({COL_PROB: probs, COL_TRUE_LABEL: y})


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def test_perfectly_calibrated_has_near_zero_ece(rng):
    # Labels drawn FROM the predicted probabilities -> calibrated by
    # construction; ECE is sampling noise only.
    n = 200_000
    probs = rng.uniform(0, 1, n)
    y = (rng.uniform(size=n) < probs).astype(np.float64)
    s = calibration_summary(_frame(probs, y))
    assert s.ece < 0.01
    assert s.mce < 0.03
    # Brier of a calibrated continuous-prob predictor: E[p(1-p)] = 1/6.
    assert s.brier == pytest.approx(1.0 / 6.0, abs=0.01)


def test_miscalibrated_overconfident_detected(rng):
    n = 50_000
    true_p = rng.uniform(0.2, 0.8, n)
    y = (rng.uniform(size=n) < true_p).astype(np.float64)
    overconfident = np.clip(true_p + np.where(true_p > 0.5, 0.19, -0.19), 0, 1)
    s = calibration_summary(_frame(overconfident, y))
    assert s.ece > 0.1


def test_brier_matches_formula(rng):
    probs = rng.uniform(0, 1, 100)
    y = rng.integers(0, 2, 100).astype(np.float64)
    s = calibration_summary(_frame(probs, y))
    assert s.brier == pytest.approx(float(np.mean((probs - y) ** 2)))


def test_bins_complete_and_counts_sum(rng):
    probs = rng.uniform(0, 1, 1000)
    y = rng.integers(0, 2, 1000)
    bins = reliability_bins(_frame(probs, y), num_bins=15)
    assert len(bins) == 15
    assert bins["count"].sum() == 1000
    occupied = bins["count"] > 0
    assert np.isfinite(bins.loc[occupied, "mean_confidence"]).all()
    assert bins.loc[~occupied, "mean_confidence"].isna().all()


def test_p_equal_one_joins_last_bin():
    bins = reliability_bins(_frame([1.0, 0.999, 0.0], [1, 1, 0]), num_bins=10)
    assert bins["count"].iloc[-1] == 2
    assert bins["count"].iloc[0] == 1


def test_validation_errors():
    with pytest.raises(ValueError, match="no windows"):
        calibration_summary(_frame([], []))
    with pytest.raises(ValueError, match="missing column"):
        calibration_summary(pd.DataFrame({COL_PROB: [0.5]}))
    with pytest.raises(ValueError, match="lie in"):
        calibration_summary(_frame([1.5], [1]))
    with pytest.raises(ValueError, match="num_bins"):
        reliability_bins(_frame([0.5], [1]), num_bins=0)


def test_report_and_plot(tmp_path, rng):
    from apnea_uq_tpu.analysis.plots import plot_reliability_diagram

    probs = rng.uniform(0, 1, 500)
    y = (rng.uniform(size=500) < probs).astype(np.float64)
    s = calibration_summary(_frame(probs, y))
    assert "Expected calibration error" in s.report()
    out = str(tmp_path / "rel.png")
    assert plot_reliability_diagram({"DEMO": s.bins}, out) == out
    import os

    assert os.path.getsize(out) > 0
